"""In-batch same-key sequencing (SURVEY.md §7.4 hard part #1).

Redis serializes decisions; a batched device call does not. A batch holding k
requests for one key must behave like k sequential Lua calls: greedy
conditional consume in batch order (denied requests consume nothing —
the documented contract, ``interface.go:104-105``).

The greedy recurrence ``c_i = c_{i-1} + n_i * [c_{i-1} + n_i <= avail]`` is
not associative, so it cannot be a plain prefix sum. This module computes it
with a bounded fixpoint iteration plus a safety intersection:

1. Stable-sort requests by slot id; segment = run of equal slots.
2. Start from "everyone consumes" and iterate
   ``allowed <- (segment-exclusive-cumsum(n * allowed) + n <= avail)``.
   Each iteration alternates between under- and over-admitting relative to
   the greedy solution and converges monotonically toward it.
3. Safety intersection: one final pass keeps only requests that fit under the
   final mask's own consumption, **intersected with** that mask. Because the
   result is a subset of the mask used to compute consumption, every kept
   request satisfies its quota check a fortiori — the op can under-admit in
   adversarial mixed-n cases but can never over-admit.

Exactness guarantees (tested in tests/test_segment.py):
* uniform n within a segment (incl. the ubiquitous all-n=1 case): exact greedy
  after iteration 1;
* any segment whose greedy solution is reached within ``iters`` fixpoint
  steps: exact.

TPU implementation notes (this shapes everything here):
* no gathers anywhere — permutations are applied by carrying payloads
  through multi-operand stable ``lax.sort`` (gather/scatter cost ~7 ns/elem
  serialized on TPU; sorts and f32 scans are ~ns/elem vectorized);
* the per-segment head value is propagated with a masked cummax instead of
  an index gather: the global exclusive cumsum ``c`` of non-negative
  consumption is non-decreasing, so the max of head-masked ``c`` over the
  prefix IS the segment head's value;
* int32 cumsums go through ops.scans.exact_cumsum_i32 (MXU-blocked limbs);
  f32 uses the fast builtin. Quantities are int64 "micro-units"
  (1 request == 1_000_000 units) in the dense backend and plain f32 request
  counts in the sketch backend; both share this kernel.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ratelimiter_tpu.ops.scans import cumsum_fast, exact_cumsum_i32

MICRO = 1_000_000

#: f32 integers are exact below this; the fast f32 cumsum path is only used
#: while the batch's total consumption stays under it (see admit).
_F32_EXACT = 1 << 24


def _head_prop(c: jnp.ndarray, seg_head: jnp.ndarray) -> jnp.ndarray:
    """Value of ``c`` at each element's segment head. Requires c
    non-decreasing and >= 0 with seg_head[0] True (always true for a
    cumsum of non-negative consumption)."""
    masked = jnp.where(seg_head, c, jnp.zeros_like(c))
    return jax.lax.cummax(masked)


def _segment_exclusive_cumsum(x: jnp.ndarray, seg_head: jnp.ndarray) -> jnp.ndarray:
    """Exclusive cumsum of non-negative x restarting at each segment head."""
    c = cumsum_fast(x) - x  # global exclusive cumsum, non-decreasing
    return c - _head_prop(c, seg_head)


def _segment_exclusive_cumsum_exact_f32(x: jnp.ndarray,
                                        seg_head: jnp.ndarray) -> jnp.ndarray:
    """Exact segment-exclusive cumsum for *integer-valued* f32 x.

    The f32 builtin cumsum loses integer exactness once a partial sum
    crosses 2^24; this path runs the scan on int32 (MXU limb cumsum +
    int32 head propagation — both exact while true prefix sums fit int32)
    and only casts the *segment-relative* value back to f32. The final
    cast is exact below 2^24; above it, the value already exceeds any
    admissible quota (limits are validated < 2^24), so the f32 rounding
    (relative error 2^-24) can never flip a ``cons + n <= avail``
    comparison. Decision-exact for total batch consumption < 2^31.
    """
    xi = x.astype(jnp.int32)
    c = exact_cumsum_i32(xi) - xi
    seg = c - jax.lax.cummax(jnp.where(seg_head, c, jnp.zeros_like(c)))
    return seg.astype(x.dtype)


def admit(
    sid: jnp.ndarray,        # int32[B] slot/segment id per request
    n_units: jnp.ndarray,    # [B] requested amount (>=0; 0 = padding)
    avail_units: jnp.ndarray,  # [B] per-request available quota (equal within a slot)
    iters: int,
) -> tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Greedy-in-batch-order admission.

    Returns (in original request order):
        allowed:    bool[B]
        seen_units: [B] — free quota as seen by request i (after consumption
                    by allowed same-slot requests earlier in the batch,
                    before its own). ``seen - n*allowed`` is the
                    post-decision remaining; ``n - seen`` is the deficit for
                    retry-after math.
        consumed_units: [B] — n_units where allowed else 0 (original order;
                    callers fold this into state by sid).
    """
    B = sid.shape[0]
    iota = jax.lax.iota(jnp.int32, B)
    # One stable multi-operand sort replaces argsort + payload gathers.
    s, nn, av, orig = jax.lax.sort((sid, n_units, avail_units, iota),
                                   num_keys=1, is_stable=True)

    seg_head = jnp.concatenate(
        [jnp.ones((1,), dtype=bool), s[1:] != s[:-1]])

    zero = jnp.zeros((), nn.dtype)

    def _solve(excl_cumsum):
        allowed = jnp.ones(s.shape, dtype=bool)
        for _ in range(iters):
            cons = excl_cumsum(jnp.where(allowed, nn, zero), seg_head)
            allowed = cons + nn <= av
        # Safety intersection: subset of the last mask, checked against that
        # mask's own consumption -> never over-admits (module docstring).
        cons = excl_cumsum(jnp.where(allowed, nn, zero), seg_head)
        allowed = allowed & (cons + nn <= av)
        # Consumption under the final mask, for consistent per-request views.
        cons = excl_cumsum(jnp.where(allowed, nn, zero), seg_head)
        seen = av - cons
        return allowed, seen

    if jnp.issubdtype(nn.dtype, jnp.floating):
        # f32 exactness guard (2^24 precondition): the fast f32 cumsum is
        # only exact while every partial sum of consumption is an exactly
        # representable integer, i.e. total batch consumption < 2^24. The
        # total is data-dependent, so the guard is a runtime cond, not a
        # trace-time assert: mega-batches whose cumulative cost crosses
        # 2^24 take the int32 limb-exact path instead of silently
        # mis-admitting. Floating n_units must be integer-valued request
        # counts (the sketch path's contract).
        total = jnp.sum(nn.astype(jnp.int64))
        allowed, seen = jax.lax.cond(
            total < _F32_EXACT,
            lambda: _solve(_segment_exclusive_cumsum),
            lambda: _solve(_segment_exclusive_cumsum_exact_f32),
        )
    else:
        allowed, seen = _solve(_segment_exclusive_cumsum)

    # Restore original order with a second sort keyed by the carried index.
    _, allowed_i, seen_o = jax.lax.sort(
        (orig, allowed.astype(jnp.int32), seen), num_keys=1, is_stable=True)
    allowed_o = allowed_i.astype(bool)
    consumed_o = jnp.where(allowed_o, n_units, zero)
    return allowed_o, seen_o, consumed_o


def segment_consumption(sid: jnp.ndarray, n_units: jnp.ndarray) -> jnp.ndarray:
    """Segment-exclusive cumsum of (already-masked) consumption, returned
    in ORIGINAL request order: cons[i] = sum of n_units[j] for j < i in
    the same slot. The cascade path (ops/hier_kernels.py) uses this to
    recompute each scope's per-request consumption view under the FINAL
    all-or-nothing mask — a request denied at a later scope must not
    appear consumed in the quantities (seen/remaining, CU targets) the
    earlier scopes report or write. Same sort/cumsum machinery and f32
    exactness guard as :func:`admit`."""
    B = sid.shape[0]
    iota = jax.lax.iota(jnp.int32, B)
    s, nn, orig = jax.lax.sort((sid, n_units, iota), num_keys=1,
                               is_stable=True)
    seg_head = jnp.concatenate(
        [jnp.ones((1,), dtype=bool), s[1:] != s[:-1]])
    if jnp.issubdtype(nn.dtype, jnp.floating):
        total = jnp.sum(nn.astype(jnp.int64))
        cons = jax.lax.cond(
            total < _F32_EXACT,
            lambda: _segment_exclusive_cumsum(nn, seg_head),
            lambda: _segment_exclusive_cumsum_exact_f32(nn, seg_head),
        )
    else:
        cons = _segment_exclusive_cumsum(nn, seg_head)
    _, cons_o = jax.lax.sort((orig, cons), num_keys=1, is_stable=True)
    return cons_o
