"""In-batch same-key sequencing (SURVEY.md §7.4 hard part #1).

Redis serializes decisions; a batched device call does not. A batch holding k
requests for one key must behave like k sequential Lua calls: greedy
conditional consume in batch order (denied requests consume nothing —
the documented contract, ``interface.go:104-105``).

The greedy recurrence ``c_i = c_{i-1} + n_i * [c_{i-1} + n_i <= avail]`` is
not associative, so it cannot be a plain prefix sum. This module computes it
with a bounded fixpoint iteration plus a safety intersection:

1. Stable-sort requests by slot id; segment = run of equal slots.
2. Start from "everyone consumes" and iterate
   ``allowed <- (segment-exclusive-cumsum(n * allowed) + n <= avail)``.
   Each iteration alternates between under- and over-admitting relative to
   the greedy solution and converges monotonically toward it.
3. Safety intersection: one final pass keeps only requests that fit under the
   final mask's own consumption, **intersected with** that mask. Because the
   result is a subset of the mask used to compute consumption, every kept
   request satisfies its quota check a fortiori — the op can under-admit in
   adversarial mixed-n cases but can never over-admit.

Exactness guarantees (tested in tests/test_segment.py):
* uniform n within a segment (incl. the ubiquitous all-n=1 case): exact greedy
  after iteration 1;
* any segment whose greedy solution is reached within ``iters`` fixpoint
  steps: exact.

All quota quantities are int64 "micro-units" (1 request == 1_000_000 units)
so token-bucket fractional levels and window counts share one kernel.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

MICRO = 1_000_000


def _segment_exclusive_cumsum(x: jnp.ndarray, seg_head: jnp.ndarray) -> jnp.ndarray:
    """Exclusive cumsum of x restarting at each True in seg_head.

    x is sorted by segment; seg_head[i] marks the first element of a segment
    (seg_head[0] must be True).
    """
    c = jnp.cumsum(x) - x  # global exclusive cumsum
    idx = jnp.arange(x.shape[0])
    head_idx = jax.lax.cummax(jnp.where(seg_head, idx, 0))
    return c - c[head_idx]


def admit(
    sid: jnp.ndarray,        # int32[B] slot/segment id per request
    n_units: jnp.ndarray,    # int64[B] requested amount in micro-units (>=0; 0 = padding)
    avail_units: jnp.ndarray,  # int64[B] per-request available quota (equal within a slot)
    iters: int,
) -> tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Greedy-in-batch-order admission.

    Returns (in original request order):
        allowed:    bool[B]
        seen_units: int64[B] — free quota as seen by request i (after
                    consumption by allowed same-slot requests earlier in the
                    batch, before its own). ``seen - n*allowed`` is the
                    post-decision remaining; ``n - seen`` is the deficit for
                    retry-after math.
        consumed_units: int64[B] — n_units where allowed else 0 (original
                    order; callers scatter-add this into state by sid).
    """
    order = jnp.argsort(sid, stable=True)
    s = sid[order]
    nn = n_units[order]
    av = avail_units[order]

    seg_head = jnp.concatenate(
        [jnp.ones((1,), dtype=bool), s[1:] != s[:-1]])

    allowed = jnp.ones(s.shape, dtype=bool)
    for _ in range(iters):
        cons = _segment_exclusive_cumsum(jnp.where(allowed, nn, 0), seg_head)
        allowed = cons + nn <= av
    # Safety intersection: subset of the last mask, checked against that
    # mask's own consumption -> never over-admits (module docstring).
    cons = _segment_exclusive_cumsum(jnp.where(allowed, nn, 0), seg_head)
    allowed = allowed & (cons + nn <= av)
    # Consumption under the final mask, for consistent per-request views.
    cons = _segment_exclusive_cumsum(jnp.where(allowed, nn, 0), seg_head)
    seen = av - cons

    inv = jnp.zeros_like(order).at[order].set(jnp.arange(order.shape[0]))
    allowed_o = allowed[inv]
    seen_o = seen[inv]
    consumed_o = jnp.where(allowed_o, n_units, 0)
    return allowed_o, seen_o, consumed_o
