"""Count-min-sketch sliding-window kernels — the TPU_SKETCH hot path.

This is the framework's reason to exist (BASELINE.json north star): replace
"one Redis round-trip per key per decision" with "one fused device call per
*batch* against a fixed-size sketch". Key cardinality no longer costs memory
(reference: ~200 B/user in Redis, ``docs/ARCHITECTURE.md:458-469``; here:
depth x width x ring counters TOTAL, shared by all keys) — the cost moves to
a bounded, measured overestimate that can only cause false *denies*, never
over-admission (SURVEY.md §7.4 hard part #3).

Design (SURVEY.md §2.2 sliding-window row, BASELINE config 4):

* The window is covered by ``SW`` sub-windows of ``sub_us`` each. The
  *current* sub-window's counts live in their own ``cur int32[d, w]`` slab;
  completed sub-windows are flushed into a ring ``slabs int32[SW, d, w]``.
  The oldest ring slab is the *boundary* sub-window, weighted by its
  remaining overlap fraction — the same ``prev * (1 - progress)`` shape as
  the exact sliding window (``slidingwindow.go:190-197``), at sub-window
  resolution.
* A running ``totals int32[d, w]`` equals ``cur`` plus all fully-in-window
  ring slabs. Per-step writes touch only ``cur`` and ``totals`` (two
  (d, w) scatter-adds — small, donation-aliased); the full ring is read or
  written ONLY inside a lax.cond that fires once per sub-window rollover
  (the "decay/rotate kernel" of BASELINE config 4), where totals is
  recomputed from the ring masks — a self-healing sweep, not a hot-path
  cost. No Redis TTLs, no full-state traffic per call (hard part #2).
* Row indices use Kirsch-Mitzenmacher double hashing
  ``col_r = (h1 + r * h2) mod w`` so the device only does 32-bit math; the
  host supplies two 32-bit hash halves per key (uint64 emulation avoided on
  the TPU hot path).
* Estimate = min over rows of ``totals + frac * boundary_slab`` (classic CMS
  min-read), clamped >= 0. Admission reuses ops.segment.admit in f32 units,
  segmenting by h1 (a 32-bit segment-id collision merges two keys' in-batch
  sequencing for that batch only — conservative and vanishingly rare).
* Writes are conditional on admission (denial consumes nothing — the
  documented contract the reference's windows violate, SURVEY.md §2.4.2):
  one scatter-add into the current slab and one into totals.

Time is an explicit int64-microsecond scalar operand; everything about
"which sub-window is current / expired" is integer period arithmetic, so
virtual-time tests are exact (SURVEY.md §4.3).
"""

from __future__ import annotations

from functools import partial
from typing import Callable, Dict

import jax
import jax.numpy as jnp

from ratelimiter_tpu.core.clock import to_micros
from ratelimiter_tpu.core.config import Config
from ratelimiter_tpu.core.errors import InvalidConfigError
from ratelimiter_tpu.ops import ensure_x64, policy_kernels
from ratelimiter_tpu.ops.segment import admit
from ratelimiter_tpu.ops.sortmerge import row_gather, row_histogram, row_histogram_max

State = Dict[str, jnp.ndarray]

#: slab_period init: far enough in the past that every slab reads as expired.
_NEVER = -(1 << 40)


def sketch_geometry(cfg: Config) -> tuple[int, int, int, int, int]:
    """Returns (window_us, sub_us, SW, S, limit); S == SW is the ring size.

    Fixed-window mode uses a single sub-window (the whole window) and no
    boundary weighting. Sliding mode uses the largest divisor of window_us
    that is <= the requested sketch.sub_windows, so any window duration gets
    an exact integer sub-window size (no fractional-period drift)."""
    from ratelimiter_tpu.core.types import Algorithm

    if cfg.algorithm is Algorithm.TOKEN_BUCKET:
        # Token-bucket semantics live in ops/bucket_kernels.py (decaying
        # debt meter, no sub-window ring); building windowed kernels for a
        # TOKEN_BUCKET config would silently change semantics.
        raise InvalidConfigError(
            "token bucket uses bucket_kernels, not the windowed sketch "
            "(construct via create_limiter or SketchTokenBucketLimiter)")
    if cfg.limit >= (1 << 24):
        # The sketch admission path compares f32 quantities; limits at or
        # above 2^24 would make boundary comparisons inexact (ops/segment
        # _segment_exclusive_cumsum_exact_f32's cast argument). Use the
        # dense backend for limits that large.
        raise InvalidConfigError(
            f"sketch backend requires limit < 2**24, got {cfg.limit}")
    W = to_micros(cfg.window)
    if cfg.algorithm is Algorithm.FIXED_WINDOW:
        SW = 1
    else:
        SW = next(k for k in range(min(cfg.sketch.sub_windows, W), 0, -1)
                  if W % k == 0)
    return W, W // SW, SW, SW, cfg.limit


def init_state(cfg: Config) -> State:
    ensure_x64()
    _, _, _, S, _ = sketch_geometry(cfg)
    d, w = cfg.sketch.depth, cfg.sketch.width
    state = {
        "cur": jnp.zeros((d, w), jnp.int32),
        "slabs": jnp.zeros((S, d, w), jnp.int32),
        "totals": jnp.zeros((d, w), jnp.int32),
        "slab_period": jnp.full((S,), _NEVER, jnp.int64),
        "last_period": jnp.asarray(_NEVER, jnp.int64),
    }
    T = cfg.hierarchy.tenants
    if T:
        # Hierarchical cascade (ADR-020): per-tenant + global in-window
        # counters riding the SAME sub-window ring clock as the CMS —
        # one extra (S, T+1) slab, flushed/recomputed by the same
        # rollover sweep. Index T is the global scope.
        state.update({
            "tn_cur": jnp.zeros((T + 1,), jnp.int32),
            "tn_slabs": jnp.zeros((S, T + 1), jnp.int32),
            "tn_totals": jnp.zeros((T + 1,), jnp.int32),
        })
    K = cfg.sketch.hh_slots
    if K:
        # Heavy-hitter side table: direct-mapped (slot = h1 mod K) private
        # ring cells for promoted keys, sharing the sketch's period clock.
        # A key with h1 == 0 can never own a slot (0 marks free) — a
        # 2^-32 event whose only effect is staying on the sketch path.
        state.update({
            "hh_owner": jnp.zeros((K,), jnp.uint32),
            # The owner's SECOND hash half, captured at claim time: the
            # DCN exporter needs the full (h1, h2) pair to fold a
            # promoted key's private counts back into CMS-column form on
            # the wire (parallel/dcn.export_completed).
            "hh_owner2": jnp.zeros((K,), jnp.uint32),
            "hh_cur": jnp.zeros((K,), jnp.int32),
            "hh_slabs": jnp.zeros((S, K), jnp.int32),
            "hh_totals": jnp.zeros((K,), jnp.int32),
            "hh_last": jnp.full((K,), _NEVER, jnp.int64),
        })
    return state


def _rollover(state: State, p, *, SW: int, S: int) -> State:
    """Advance state to period p (p > last_period). Flushes ``cur`` into the
    ring at slot ``last_period % S``, recomputes ``totals`` as the masked sum
    of ring slabs still fully inside the window (self-healing — any
    transient negatives from reset subtraction wash out), and zeroes
    ``cur``.

    This is deliberately NOT part of the per-request step kernel: a
    lax.cond over the ring would force XLA to materialize copies of the
    full (S, d, w) state every step (measured ~1.4 ms/step at 60x4x64K).
    The period is pure integer arithmetic on the host-supplied timestamp,
    so the *host* decides when to dispatch this kernel (~once per
    sub-window), exactly like it decides when to dispatch steps. See
    SketchLimiter._sync_period.
    """
    p_old = state["last_period"]
    slabs, periods = state["slabs"], state["slab_period"]
    slot = (p_old % S).astype(jnp.int32)
    slabs = slabs.at[slot].set(state["cur"])
    periods = periods.at[slot].set(p_old)
    # Fully-in-window flushed periods: [p-SW+1, p-1]. (The boundary period
    # p-SW is read weighted at estimate time; period p is `cur`.)
    in_window = (periods >= p - SW + 1) & (periods <= p - 1)
    totals = jnp.tensordot(in_window.astype(jnp.int32), slabs, axes=1)
    out = {"cur": jnp.zeros_like(state["cur"]), "slabs": slabs,
           "totals": totals, "slab_period": periods,
           "last_period": jnp.asarray(p, jnp.int64)}
    if "tn_cur" in state:
        # Tenant/global counters share the ring clock (ADR-020): same
        # flush + masked-sum recompute as the CMS and hh slabs.
        tn_slabs = state["tn_slabs"].at[slot].set(state["tn_cur"])
        out.update({
            "tn_cur": jnp.zeros_like(state["tn_cur"]),
            "tn_slabs": tn_slabs,
            "tn_totals": jnp.tensordot(in_window.astype(jnp.int32),
                                       tn_slabs, axes=1),
        })
    if "hh_owner" in state:
        # The side table rides the same period clock: flush, recompute,
        # and reclaim slots idle a full window (their in-window counts are
        # provably zero — every write at period q lives in slab q, and
        # idleness means no q > p - SW).
        hh_slabs = state["hh_slabs"].at[slot].set(state["hh_cur"])
        hh_totals = jnp.tensordot(in_window.astype(jnp.int32), hh_slabs,
                                  axes=1)
        idle = state["hh_last"] <= p - SW
        out.update({
            "hh_owner": jnp.where(idle, jnp.uint32(0), state["hh_owner"]),
            "hh_owner2": jnp.where(idle, jnp.uint32(0),
                                   state["hh_owner2"]),
            "hh_cur": jnp.zeros_like(state["hh_cur"]),
            "hh_slabs": hh_slabs,
            "hh_totals": hh_totals,
            "hh_last": state["hh_last"],
        })
    return out


def _columns(h1, h2, d: int, w: int):
    """Kirsch-Mitzenmacher double-hashed CMS columns, (B, d) int32 column
    indices into each of the d rows."""
    r = jnp.arange(d, dtype=jnp.uint32)
    cols = (h1[:, None] + r[None, :] * h2[:, None]) & jnp.uint32(w - 1)
    return cols.astype(jnp.int32)


def _boundary_weight(state: State, p, now_us, *, sub_us: int, SW: int,
                     S: int, weighted: bool, pre=None):
    """(frac, boundary) for the sliding-window boundary sub-window: the
    rollover-boundary check (is the slab at slot p % S the period p-SW
    slab?) and its remaining-overlap weight. ``pre`` short-circuits with
    scan-hoisted values (see _sketch_scan); fixed-window mode returns
    (0.0, None). Shared by the jnp and Pallas estimate paths so both see
    the exact same scalar math."""
    if not weighted:
        return jnp.float32(0.0), None
    if pre is not None:
        # Scan path: (frac, boundary) precomputed OUTSIDE the loop
        # body. Scalars derived from the loop carry defeat XLA's
        # invariant hoisting, making the dynamic ring slice + dense
        # combine re-run per iteration (measured 2 us -> 500+ us per
        # step); the chunk precondition (one sub-window per chunk)
        # makes the hoist exact. See _sketch_scan.
        return pre
    # Ring size S == SW, so the boundary period p-SW lives at
    # slot p % S (the very slot the next rollover overwrites).
    b_idx = (p % S).astype(jnp.int32)
    boundary_valid = state["slab_period"][b_idx] == p - SW
    elapsed_in = (now_us - p * sub_us).astype(jnp.float32)
    frac = jnp.where(
        boundary_valid,
        jnp.clip(1.0 - elapsed_in / jnp.float32(sub_us), 0.0, 1.0),
        0.0)
    boundary = jax.lax.dynamic_index_in_dim(state["slabs"], b_idx,
                                            keepdims=False)
    return frac, boundary


def _estimate(state: State, cols, p, now_us, *, sub_us: int, SW: int, S: int,
              weighted: bool = True, pre=None):
    """Min-over-rows window estimate at the given (B, d) columns, via
    sort-merge reads (ops/sortmerge.py — no gathers on the hot path).
    ``weighted`` adds the boundary sub-window scaled by its remaining
    overlap fraction (sliding semantics); fixed-window mode reads totals
    alone.

    Returns (est, frac, boundary): the (B,) min-estimate plus the scalar
    boundary weight and the dense (d, w) boundary slab (None when not
    weighted) so the conservative-update write path can reuse them."""
    from ratelimiter_tpu.ops.sortmerge import _use_sortmerge

    d = cols.shape[1]
    B = cols.shape[0]
    w = state["totals"].shape[1]
    if weighted:
        frac, boundary = _boundary_weight(state, p, now_us, sub_us=sub_us,
                                          SW=SW, S=S, weighted=True, pre=pre)
        if not _use_sortmerge(B, w):
            # Direct-indexing regime: pre-combine the two tables DENSELY
            # (frac is a scalar) and gather once per row. Numerically
            # identical to gathering both and combining per element, but
            # measured ~100x faster on the tunnel TPU at the serving
            # shape (B=4096, w=65536: 550 us -> ~5 us per step) — XLA
            # lowers the fused two-gather combine pathologically.
            combined = (state["totals"].astype(jnp.float32)
                        + frac * boundary.astype(jnp.float32))
            est = None
            for r in range(d):
                e_r = combined[r][cols[:, r]]
                est = e_r if est is None else jnp.minimum(est, e_r)
        else:
            # Sort-merge regime (B >= w/2): delta encoding needs integer
            # rows for exactness, so gather both and combine after.
            est = None
            for r in range(d):
                t_r, b_r = row_gather((state["totals"][r], boundary[r]),
                                      cols[:, r])
                e_r = t_r.astype(jnp.float32) + frac * b_r.astype(jnp.float32)
                est = e_r if est is None else jnp.minimum(est, e_r)
    else:
        frac, boundary = jnp.float32(0.0), None
        est = None
        for r in range(d):
            (t_r,) = row_gather((state["totals"][r],), cols[:, r])
            e_r = t_r.astype(jnp.float32)
            est = e_r if est is None else jnp.minimum(est, e_r)
    return jnp.maximum(est, 0.0), frac, boundary  # (B,), scalar, (d, w)|None


def _hh_boundary_slab(state: State, p, *, SW: int, S: int):
    """The side table's boundary sub-window column vector (K,). Validity is
    carried by ``frac`` (0 when the boundary period is absent), exactly as
    for the CMS boundary slab."""
    b_idx = (p % S).astype(jnp.int32)
    return jax.lax.dynamic_index_in_dim(state["hh_slabs"], b_idx,
                                        keepdims=False)


def _sketch_step(state: State, h1, h2, n, now_us, policy=None, hier=None, *,
                 limit: int, sub_us: int, SW: int, S: int, d: int, w: int,
                 iters: int, weighted: bool, conservative: bool,
                 hh: int = 0, hh_thresh: float = 0.0, tenants: int = 0,
                 axis_name: str | None = None, pre=None, pre_hh=None,
                 use_pallas: bool = False):
    # Precondition (host-enforced via _sync_period): state.last_period is
    # the period of now_us. Clamp defends against clock skew backwards —
    # the reference has the same NTP caveat (``docs/ALGORITHMS.md:162``).
    now_us = jnp.maximum(now_us, state["last_period"] * sub_us)
    p = state["last_period"]

    # Fused-kernel path (ADR-011): columns derive INSIDE the Pallas
    # kernels, so the (B, d) column matrix never materializes. Collective
    # merges and the hh side table stay on the reference path (the psum'd
    # histogram and private-cell reads are not fused).
    use_pallas = use_pallas and axis_name is None and not hh
    if use_pallas:
        from ratelimiter_tpu.ops import pallas_sketch

        cols = None
        frac, boundary = _boundary_weight(state, p, now_us, sub_us=sub_us,
                                          SW=SW, S=S, weighted=weighted,
                                          pre=pre)
        bop = (boundary if boundary is not None
               else jnp.zeros_like(state["totals"]))
        est = jnp.maximum(
            pallas_sketch.window_estimate(state["totals"], bop, frac,
                                          h1, h2), 0.0)
    else:
        cols = _columns(h1, h2, d, w)                        # (B, d)
        est, frac, boundary = _estimate(state, cols, p, now_us,
                                        sub_us=sub_us, SW=SW, S=S,
                                        weighted=weighted, pre=pre)

    if hh:
        # Heavy-hitter side table (ROADMAP v0.2): a promoted key's NEW
        # traffic is counted exactly in its private ring cell while its
        # pre-promotion history stays in the sketch and expires on the
        # normal window schedule — the estimate is the SUM of the two.
        # Nothing is copied at promotion (a copied estimate would freeze
        # the key's most-inflated moment — promotion fires exactly when
        # est crosses the threshold — into a window-long sentence), and
        # nothing is counted twice (each request lives either in the
        # sketch or in the private cell, never both). Direct-mapped:
        # slot = h1 mod K, identity = h1 (a 32-bit identity collision
        # merges two keys into one exact cell — same direction as a CMS
        # collision: over-count, false denies only).
        sid_hh = jax.lax.bitcast_convert_type(
            h1 & jnp.uint32(hh - 1), jnp.int32)
        owner = state["hh_owner"][sid_hh]                    # (B,)
        mine = owner == h1
        est_hh = state["hh_totals"][sid_hh].astype(jnp.float32)
        if weighted:
            hh_b = pre_hh if pre_hh is not None else _hh_boundary_slab(
                state, p, SW=SW, S=S)
            est_hh = est_hh + frac * hh_b[sid_hh].astype(jnp.float32)
        est = est + jnp.where(mine, jnp.maximum(est_hh, 0.0), 0.0)
    else:
        mine = None

    if policy is not None:
        # Per-key limit overrides (policy engine): the search key is the
        # device-side packing of the (h1, h2) halves the columns already
        # ride on, so the lookup costs log2(capacity) tiny gathers and no
        # extra operand. Limits are validated < 2^24 at override-set time
        # (the same f32-exactness gate as the base limit).
        q = policy_kernels.pack_halves(h1, h2)
        pidx, pfound = policy_kernels.lookup_i64(policy["key"], q)
        lim_f = jnp.where(pfound, policy["limit"][pidx],
                          jnp.int64(limit)).astype(jnp.float32)
    else:
        lim_f = jnp.float32(limit)
    avail = jnp.maximum(lim_f - est, 0.0)
    n_f = n.astype(jnp.float32)
    sid = jax.lax.bitcast_convert_type(h1, jnp.int32)
    allowed, seen, _ = admit(sid, n_f, avail, iters)

    tn_hist = None
    if tenants and hier is not None:
        # Hierarchical cascade (ADR-020): key-scope survivors run the
        # tenant + global stages against the tn counter slab, still in
        # THIS dispatch. The tenant boundary sub-window rides the same
        # frac scalar as the CMS boundary (frac is 0 when the boundary
        # period is absent); its fractional part ceils — conservative,
        # toward denying — so tenant/global admission stays exact int64.
        from ratelimiter_tpu.ops import hier_kernels
        from ratelimiter_tpu.ops.segment import segment_consumption

        tid = hier_kernels.derive_tids(hier, h1, h2, tenants)
        est_tn = state["tn_totals"].astype(jnp.int64)
        if weighted:
            tn_b = jax.lax.dynamic_index_in_dim(
                state["tn_slabs"], (p % S).astype(jnp.int32),
                keepdims=False)
            est_tn = est_tn + jnp.ceil(
                frac * jnp.maximum(tn_b, 0).astype(jnp.float32)
            ).astype(jnp.int64)
        avail_sc = hier_kernels.scope_avail(hier["limit"],
                                            jnp.maximum(est_tn, 0))
        allowed_casc, tn_hist = hier_kernels.cascade_admit(
            allowed, tid, n, avail_sc, hier["weight"], tenants, iters)
        # All-or-nothing: recompute the key scope's consumption view
        # under the FINAL mask so writes (CU targets / adds), hh
        # promotion targets, and the reported remaining all reflect
        # only what was actually admitted. Cond'd on the cascade having
        # flipped any verdict: under no tenant/global contention (the
        # common case) the masks are equal and the stage-1 view already
        # IS the final view — the extra sort pass is skipped.
        seen = jax.lax.cond(
            jnp.any(allowed_casc != allowed),
            lambda: avail - segment_consumption(
                sid, jnp.where(allowed_casc, n_f, jnp.float32(0.0))),
            lambda: seen)
        allowed = allowed_casc
        if axis_name is not None:
            tn_hist = jax.lax.psum(tn_hist, axis_name)
    not_mine = True if mine is None else ~mine

    if conservative and axis_name is None:
        # Conservative update (SURVEY.md hard part #3): raise each touched
        # cell only as high as the largest single-key post-batch target that
        # maps to it, never the sum of colliding keys. Target for a key's
        # last allowed request is est + total in-batch consumption; the
        # per-column segment-max picks exactly that. Denied requests write
        # nothing (matching "denial consumes nothing").
        #
        # CU requires a globally-sequenced view of the batch, so it applies
        # on single-chip and mesh-gather paths only. Under the delta merge
        # (axis_name set) the else-branch's psum-of-increments runs instead:
        # a pmax of per-chip CU targets would UNDERCOUNT cross-chip traffic
        # (true counts add across chips) and a psum of per-chip CU deltas
        # can undercount rows whose dense read exceeds the min-estimate —
        # both break the never-over-admit direction. Vanilla sums never do.
        target = jnp.where(allowed & not_mine, est + (avail - seen) + n_f, 0.0)
        if use_pallas:
            from ratelimiter_tpu.ops import pallas_sketch

            totals, cur = pallas_sketch.cu_update(
                state["totals"], state["cur"], bop, frac, h1, h2, target)
        else:
            deltas = []
            for r in range(d):
                m_r = row_histogram_max(cols[:, r], target, w)
                read_r = state["totals"][r].astype(jnp.float32)
                if boundary is not None:
                    read_r = read_r + frac * boundary[r].astype(jnp.float32)
                deltas.append(jnp.ceil(jnp.maximum(m_r - read_r, 0.0)))
            hists = jnp.stack(deltas).astype(jnp.int32)
            totals = state["totals"] + hists
            cur = state["cur"] + hists
    else:
        add = jnp.where(allowed & not_mine, n, 0).astype(jnp.int32)  # (B,)
        if use_pallas:
            from ratelimiter_tpu.ops import pallas_sketch

            totals, cur = pallas_sketch.add_update(
                state["totals"], state["cur"], h1, h2, add)
        else:
            hists = jnp.stack([row_histogram(cols[:, r], add, w)
                               for r in range(d)])
            if axis_name is not None:
                # Multi-chip delta merge: every chip adds the summed
                # histogram, keeping the replicated-state invariant (ICI
                # psum — the analog of all app servers sharing one Redis,
                # SURVEY.md §2.6).
                hists = jax.lax.psum(hists, axis_name)
            totals = state["totals"] + hists
            cur = state["cur"] + hists
    # cur and totals share the same histogram so the "current sub-window
    # also counts in totals" invariant holds by construction.

    new_state = {"cur": cur, "slabs": state["slabs"], "totals": totals,
                 "slab_period": state["slab_period"],
                 "last_period": state["last_period"]}

    if "tn_cur" in state:
        if tn_hist is not None:
            th = tn_hist.astype(jnp.int32)
            new_state.update({"tn_cur": state["tn_cur"] + th,
                              "tn_slabs": state["tn_slabs"],
                              "tn_totals": state["tn_totals"] + th})
        else:
            # Hierarchy-shaped state on a path that did not receive the
            # table operand (reset-adjacent internal calls, the scan
            # bench path): counters carry through untouched.
            new_state.update({k: state[k] for k in
                              ("tn_cur", "tn_slabs", "tn_totals")})

    if hh:
        # Owned-key consumption goes to the private cells (exact counts).
        n_add = jnp.where(allowed & mine, n, 0).astype(jnp.int32)
        hh_hist = row_histogram(sid_hh, n_add, hh)
        # Promotion: unowned keys whose post-batch target crosses the
        # threshold claim their (free) slot — ownership only, no mass
        # (see the estimate comment above). Winner selection packs
        # (target, h1) into one int64 scatter-max so the slot goes to the
        # HOTTEST candidate deterministically (incl. across chips).
        target_pr = jnp.where(allowed, est + (avail - seen) + n_f, est)
        free = owner == jnp.uint32(0)
        cand = not_mine & free & (target_pr >= jnp.float32(hh_thresh))
        mass_i = jnp.ceil(jnp.clip(target_pr, 0.0, float(1 << 30))
                          ).astype(jnp.int64)
        packed = jnp.where(cand,
                           (mass_i << 32) | h1.astype(jnp.int64),
                           jnp.int64(0))
        touched = row_histogram(sid_hh, (mine | cand).astype(jnp.int32),
                                hh) > 0
        claims = jnp.zeros((hh,), jnp.int64).at[sid_hh].max(packed)
        if axis_name is not None:
            hh_hist = jax.lax.psum(hh_hist, axis_name)
            # Packed max is order-consistent across chips: the global max
            # target (ties broken by h1) wins everywhere.
            claims = jax.lax.pmax(claims, axis_name)
            touched = jax.lax.pmax(touched, axis_name)
        # Winner's h2, recovered by a second scatter keyed on the winning
        # packed value (equal packed => equal h1 => same key => same h2,
        # so ties cannot mix pairs). Needed so DCN export can rebuild the
        # owner's CMS columns (export_completed).
        winner = cand & (packed == claims[sid_hh])
        h2w = jnp.zeros((hh,), jnp.uint32).at[sid_hh].max(
            jnp.where(winner, h2, jnp.uint32(0)))
        if axis_name is not None:
            h2w = jax.lax.pmax(h2w, axis_name)
        claim_owner = (claims & jnp.int64(0xFFFFFFFF)).astype(jnp.uint32)
        newly = (state["hh_owner"] == jnp.uint32(0)) & (
            claim_owner != jnp.uint32(0))
        new_state.update({
            "hh_owner": jnp.where(newly, claim_owner, state["hh_owner"]),
            "hh_owner2": jnp.where(newly, h2w, state["hh_owner2"]),
            "hh_cur": state["hh_cur"] + hh_hist,
            "hh_slabs": state["hh_slabs"],
            "hh_totals": state["hh_totals"] + hh_hist,
            "hh_last": jnp.where(touched, p, state["hh_last"]),
        })

    remaining = jnp.maximum(
        jnp.floor(seen - jnp.where(allowed, n_f, 0.0)), 0.0).astype(jnp.int32)
    return new_state, (allowed, remaining, est)


def _sketch_reset(state: State, h1, h2, now_us, *,
                  sub_us: int, SW: int, S: int, d: int, w: int,
                  weighted: bool, hh: int = 0):
    """Per-key reset: subtract the key's current min-estimate from all its
    cells in both ``cur`` and ``totals`` (equal amounts; cells may go
    transiently negative, reads clamp at 0 and the next rollover's totals
    recompute self-heals). Colliding keys gain allowance — errors toward
    allowing, never toward false denial. Promoted keys subtract from their
    private side-table cells instead."""
    now_us = jnp.maximum(now_us, state["last_period"] * sub_us)
    p = state["last_period"]
    cols = _columns(h1, h2, d, w)
    est, frac, _ = _estimate(state, cols, p, now_us, sub_us=sub_us, SW=SW,
                             S=S, weighted=weighted)
    if hh:
        # A promoted key's estimate is CMS remnant + private count
        # (_sketch_step): reset subtracts each part from its own table.
        sid_hh = jax.lax.bitcast_convert_type(
            h1 & jnp.uint32(hh - 1), jnp.int32)
        mine = state["hh_owner"][sid_hh] == h1
        est_hh = state["hh_totals"][sid_hh].astype(jnp.float32)
        if weighted:
            hh_b = _hh_boundary_slab(state, p, SW=SW, S=S)
            est_hh = est_hh + frac * hh_b[sid_hh].astype(jnp.float32)
        sub_hh = jnp.where(mine, jnp.floor(jnp.maximum(est_hh, 0.0)),
                           0.0).astype(jnp.int32)
        hh_hist = row_histogram(sid_hh, sub_hh, hh)
        sub = jnp.floor(est).astype(jnp.int32)
    else:
        hh_hist = None
        sub = jnp.floor(est).astype(jnp.int32)
    hists = jnp.stack([row_histogram(cols[:, r], sub, w) for r in range(d)])
    out = {"cur": state["cur"] - hists, "slabs": state["slabs"],
           "totals": state["totals"] - hists,
           "slab_period": state["slab_period"],
           "last_period": state["last_period"]}
    if "tn_cur" in state:
        # Reset forgives a KEY's usage only: tenant/global counters track
        # actually-admitted aggregate traffic and deliberately stand
        # (ADR-020 — subtracting one key's estimate from its tenant would
        # let a reset-hammering key drain its whole tenant's accounting).
        out.update({k: state[k] for k in
                    ("tn_cur", "tn_slabs", "tn_totals")})
    if hh:
        out.update({
            "hh_owner": state["hh_owner"],
            "hh_owner2": state["hh_owner2"],
            "hh_cur": state["hh_cur"] - hh_hist,
            "hh_slabs": state["hh_slabs"],
            "hh_totals": state["hh_totals"] - hh_hist,
            "hh_last": state["hh_last"],
        })
    return out


@jax.jit
def finish_window(allowed, remaining, now_us, window_us):
    """Device-side result assembly for windowed sketches (sliding and
    fixed): retry-after is time to window reset (``fixedwindow.go:107-112``)
    computed ON DEVICE, so the pipelined serving path's resolve phase does
    one bulk device→host fetch per batch instead of per-request NumPy
    float math after the blocking readback (ADR-010). Returns
    ``(allowed bool[B], remaining int64[B], retry f64[B], reset f64[B])``."""
    cur_ws = (now_us // window_us) * window_us
    reset = (cur_ws + window_us).astype(jnp.float64) / 1e6
    retry = jnp.where(allowed, jnp.float64(0.0),
                      (cur_ws + window_us - now_us).astype(jnp.float64) / 1e6)
    return (allowed, remaining.astype(jnp.int64), retry,
            jnp.broadcast_to(reset, allowed.shape))


def _pack_bits(mask):
    """(B,) bool -> (B/8,) uint8 little-endian bit packing, on device. Keeps
    per-decision results 1 bit wide so bulk readback is bandwidth-cheap."""
    b = mask.reshape(-1, 8).astype(jnp.uint8)
    weights = (jnp.uint8(1) << jnp.arange(8, dtype=jnp.uint8))[None, :]
    return (b * weights).sum(axis=1).astype(jnp.uint8)


def _sketch_scan(state: State, h1s, h2s, ns, now0_us, dt_us, *, step_kw):
    """Run T sequential sketch steps entirely on device (lax.scan), one
    dispatch total. Timestamps advance dt_us per step. Returns packed allow
    bitmasks (T, B/8) and the per-step deny counts — the shape the
    micro-batching server and the throughput bench both consume
    (SURVEY.md §7.4 hard part #4: amortize host/device boundary costs).

    Precondition (host-enforced, same as the single step): the whole chunk
    [now0, now0 + T*dt] lies within the current sub-window period — chunks
    span tens of ms, sub-windows are ~1 s; callers split chunks at period
    boundaries and dispatch the rollover kernel between them.

    That precondition also makes the boundary slab and its validity
    loop-invariant, so they are computed HERE, outside the scan body,
    with only the per-step boundary weight riding the xs. This matters
    enormously: scalars derived from the loop carry defeat XLA's
    invariant hoisting and force the 64 MB dynamic ring slice + dense
    combine to re-run every iteration (measured ~500 us/step at the
    config-3 serving shape; hoisted: single-digit us)."""
    T = h1s.shape[0]
    weighted = step_kw.get("weighted", True)
    sub_us = step_kw["sub_us"]
    S, SW = step_kw["S"], step_kw["SW"]
    hh = step_kw.get("hh", 0)

    if weighted:
        p = state["last_period"]
        b_idx = (p % S).astype(jnp.int32)
        boundary_valid = state["slab_period"][b_idx] == p - SW
        boundary = jax.lax.dynamic_index_in_dim(state["slabs"], b_idx,
                                                keepdims=False)
        ts = now0_us + jnp.arange(T, dtype=jnp.int64) * dt_us
        ts = jnp.maximum(ts, p * sub_us)  # same skew clamp as the step
        elapsed = (ts - p * sub_us).astype(jnp.float32)
        fracs = jnp.where(boundary_valid,
                          jnp.clip(1.0 - elapsed / jnp.float32(sub_us),
                                   0.0, 1.0),
                          0.0)
        # Same hoist for the side table's boundary column (loop-invariant
        # under the one-sub-window-per-chunk precondition).
        hh_b = (_hh_boundary_slab(state, p, SW=SW, S=S) if hh else None)
    else:
        boundary = None
        fracs = jnp.zeros((T,), jnp.float32)
        hh_b = None

    def body(st, xs):
        h1, h2, n, i, frac_t = xs
        pre = (frac_t, boundary) if weighted else None
        st, (allowed, _rem, _est) = _sketch_step(
            st, h1, h2, n, now0_us + i * dt_us, pre=pre, pre_hh=hh_b,
            **step_kw)
        return st, (_pack_bits(allowed), jnp.sum(~allowed).astype(jnp.int32))

    idx = jnp.arange(T, dtype=jnp.int64)
    state, (packed, denies) = jax.lax.scan(
        body, state, (h1s, h2s, ns, idx, fracs))
    return state, packed, denies


_STEP_CACHE: Dict[tuple, Callable] = {}


def _hh_params(cfg: Config) -> tuple[int, float]:
    """(hh_slots, promotion threshold in requests) for cfg; (0, 0) when the
    side table is disabled."""
    K = cfg.sketch.hh_slots
    if not K:
        return 0, 0.0
    return K, max(1.0, float(cfg.limit) * cfg.sketch.hh_promote_fraction)


def build_steps(cfg: Config) -> tuple[Callable, Callable, Callable]:
    """Returns (step, reset, rollover) jitted callables; memoized per static
    config. The host calls ``rollover(state, p)`` whenever the sub-window
    period of the dispatch timestamp differs from the state's period (see
    _rollover for why this is host-driven). ``step`` accepts an optional
    trailing ``policy`` operand (the device-resident override table)."""
    from ratelimiter_tpu.core.types import Algorithm

    ensure_x64()

    W, sub_us, SW, S, limit = sketch_geometry(cfg)
    d, w = cfg.sketch.depth, cfg.sketch.width
    weighted = cfg.algorithm is not Algorithm.FIXED_WINDOW
    cu = cfg.sketch.conservative_update
    hh, hh_thresh = _hh_params(cfg)
    tenants = cfg.hierarchy.tenants
    use_pallas = _resolve_pallas(cfg)
    key = (limit, W, SW, d, w, cfg.max_batch_admission_iters, weighted, cu,
           hh, hh_thresh, tenants, use_pallas)
    cached = _STEP_CACHE.get(key)
    if cached is not None:
        return cached
    step = jax.jit(
        partial(_sketch_step, limit=limit, sub_us=sub_us, SW=SW, S=S, d=d, w=w,
                iters=cfg.max_batch_admission_iters, weighted=weighted,
                conservative=cu, hh=hh, hh_thresh=hh_thresh, tenants=tenants,
                use_pallas=use_pallas),
        donate_argnums=(0,))
    reset = jax.jit(
        partial(_sketch_reset, sub_us=sub_us, SW=SW, S=S, d=d, w=w,
                weighted=weighted, hh=hh),
        donate_argnums=(0,))
    rollover = jax.jit(
        partial(_rollover, SW=SW, S=S), donate_argnums=(0,))
    _STEP_CACHE[key] = (step, reset, rollover)
    return step, reset, rollover


def _resolve_pallas(cfg: Config, *, bucket: bool = False) -> bool:
    """Static kernel selection for this config (ADR-011)."""
    from ratelimiter_tpu.ops import pallas_sketch

    return pallas_sketch.resolve_kernels(cfg, bucket=bucket) == "pallas"


# ------------------------------------------------- hashed-operand steps
#
# The serving hot path stages ONE uint64 buffer per batch and the step
# derives (h1, h2) ON DEVICE (ops/hashing.split_hash_dev) — the host
# never runs per-key hash math after ingest (ADR-011). ``premix=True``
# additionally applies the splitmix64 finalizer in-step: the raw-u64-id
# wire lane (T_ALLOW_HASHED) ships tenant ids untouched and the device
# does ALL the mixing.

_HASHED_CACHE: Dict[tuple, Callable] = {}


def _sketch_step_h64(state: State, h64, n, now_us, policy=None, hier=None, *,
                     seed: int, premix: bool, **step_kw):
    from ratelimiter_tpu.ops.hashing import split_hash_dev, splitmix64_dev

    h = h64
    if premix:
        h = splitmix64_dev(h)
    h1, h2 = split_hash_dev(h, seed)
    return _sketch_step(state, h1, h2, n, now_us, policy, hier, **step_kw)


def build_hashed_step(cfg: Config, *, premix: bool = False) -> Callable:
    """Jitted ``step(state, h64, n, now_us, policy)`` taking finalized
    64-bit hashes (premix=False — string-key and pre-hashed traffic) or
    raw u64 ids (premix=True — the hashed wire lane); memoized per static
    config. Decision-identical to build_steps' (h1, h2) step by the
    split_hash host/device bit-equality (tests/test_hashing_device.py)."""
    ensure_x64()

    W, sub_us, SW, S, limit = sketch_geometry(cfg)
    d, w = cfg.sketch.depth, cfg.sketch.width
    from ratelimiter_tpu.core.types import Algorithm

    weighted = cfg.algorithm is not Algorithm.FIXED_WINDOW
    cu = cfg.sketch.conservative_update
    hh, hh_thresh = _hh_params(cfg)
    tenants = cfg.hierarchy.tenants
    use_pallas = _resolve_pallas(cfg)
    seed = cfg.sketch.seed
    key = (limit, W, SW, d, w, cfg.max_batch_admission_iters, weighted, cu,
           hh, hh_thresh, tenants, use_pallas, seed, premix)
    cached = _HASHED_CACHE.get(key)
    if cached is not None:
        return cached
    step = jax.jit(
        partial(_sketch_step_h64, seed=seed, premix=premix,
                limit=limit, sub_us=sub_us, SW=SW, S=S, d=d, w=w,
                iters=cfg.max_batch_admission_iters, weighted=weighted,
                conservative=cu, hh=hh, hh_thresh=hh_thresh, tenants=tenants,
                use_pallas=use_pallas),
        donate_argnums=(0,))
    _HASHED_CACHE[key] = step
    return step


@jax.jit
def pack_wire(allowed, remaining, retry, reset):
    """Device-side response packing for the hashed wire lane (ADR-011):
    the allow mask bit-packs to B/8 bytes and remaining/retry/reset ride
    ONE (3B,) int64 array (floats bitcast), so resolve fetches two
    compact buffers and the responder's frame build is three slice
    memcpys — no per-request host math, no per-request Python objects."""
    bits = _pack_bits(allowed)
    words = jnp.concatenate([
        remaining.astype(jnp.int64),
        jax.lax.bitcast_convert_type(retry.astype(jnp.float64), jnp.int64),
        jax.lax.bitcast_convert_type(reset.astype(jnp.float64), jnp.int64),
    ])
    return bits, words


def _migrate_window(state: State, now_us, *, sub_o: int, SWo: int, So: int,
                    sub_n: int, SWn: int, Sn: int, hh: int):
    """Re-bucket ring state onto a new sub-window geometry (dynamic
    window updates). Every old sub-window's mass is attributed to the
    LAST new period its time span overlaps, so nothing expires earlier
    than it would have under either window — migration can only err
    toward denying, never over-admission. Mass mapped past the new
    window's tail (an old window longer than the new one) drops into the
    boundary-or-older region and ages out exactly like native history.
    """
    p_last = state["last_period"]
    p_now = now_us // sub_n
    sp = state["slab_period"]                              # (So,)
    valid = (sp >= p_last - SWo) & (sp <= p_last - 1)
    q = ((sp + 1) * sub_o - 1) // sub_n                    # last overlapped
    to_cur = valid & (q >= p_now)
    in_ring = valid & (q < p_now) & (q >= p_now - SWn)
    slot = (q % Sn).astype(jnp.int32)

    def rebucket(slabs, cur):
        contrib = slabs * in_ring.reshape((-1,) + (1,) * (slabs.ndim - 1))
        new_slabs = jnp.zeros((Sn,) + slabs.shape[1:],
                              slabs.dtype).at[slot].add(contrib)
        # dtype pinned: jnp.sum would promote int32 to the default int,
        # permanently doubling the hot arrays' width and tripping the
        # next rollover's int64->int32 scatter.
        new_cur = cur + jnp.sum(
            slabs * to_cur.reshape((-1,) + (1,) * (slabs.ndim - 1)),
            axis=0, dtype=cur.dtype)
        return new_slabs, new_cur

    new_slabs, new_cur = rebucket(state["slabs"], state["cur"])
    periods_n = jnp.full((Sn,), _NEVER, jnp.int64).at[slot].max(
        jnp.where(in_ring, q, _NEVER))
    in_window = ((periods_n >= p_now - SWn + 1)
                 & (periods_n <= p_now - 1)).astype(jnp.int32)
    totals_n = (jnp.tensordot(in_window, new_slabs, axes=1)
                .astype(new_cur.dtype) + new_cur)
    out = {"cur": new_cur, "slabs": new_slabs, "totals": totals_n,
           "slab_period": periods_n,
           "last_period": jnp.asarray(p_now, jnp.int64)}
    if "tn_cur" in state:
        # Tenant/global counters re-bucket with the same conservative
        # last-overlapped-period rule as the CMS ring (rebucket() is
        # shape-generic over the trailing axes).
        tn_slabs, tn_cur = rebucket(state["tn_slabs"], state["tn_cur"])
        out.update({
            "tn_cur": tn_cur,
            "tn_slabs": tn_slabs,
            "tn_totals": (jnp.tensordot(in_window, tn_slabs, axes=1)
                          .astype(tn_cur.dtype) + tn_cur),
        })
    if hh:
        hh_slabs, hh_cur = rebucket(state["hh_slabs"], state["hh_cur"])
        hh_totals = (jnp.tensordot(in_window, hh_slabs, axes=1)
                     .astype(hh_cur.dtype) + hh_cur)
        q_hh = ((state["hh_last"] + 1) * sub_o - 1) // sub_n
        out.update({
            "hh_owner": state["hh_owner"],
            "hh_owner2": state["hh_owner2"],
            "hh_cur": hh_cur,
            "hh_slabs": hh_slabs,
            "hh_totals": hh_totals,
            "hh_last": jnp.where(state["hh_last"] == _NEVER,
                                 jnp.int64(_NEVER), q_hh),
        })
    return out


def build_migrate(old_cfg: Config, new_cfg: Config) -> Callable:
    """Jitted ``migrate(state, now_us) -> state`` moving ring state from
    old_cfg's window geometry to new_cfg's. Limit/depth/width/hh must
    match (only the window changes)."""
    ensure_x64()
    _, sub_o, SWo, So, _ = sketch_geometry(old_cfg)
    _, sub_n, SWn, Sn, _ = sketch_geometry(new_cfg)
    if (old_cfg.sketch.depth, old_cfg.sketch.width) != (
            new_cfg.sketch.depth, new_cfg.sketch.width):
        raise InvalidConfigError("window migration cannot change geometry")
    hh, _ = _hh_params(old_cfg)
    # No donation: the ring shapes change (So != Sn in general), so the
    # old buffers cannot be reused anyway and donating only warns.
    return jax.jit(
        partial(_migrate_window, sub_o=sub_o, SWo=SWo, So=So, sub_n=sub_n,
                SWn=SWn, Sn=Sn, hh=hh))


_SCAN_CACHE: Dict[tuple, Callable] = {}


def build_scan(cfg: Config) -> Callable:
    """Jitted multi-step runner: ``scan(state, h1s, h2s, ns, now0_us, dt_us)
    -> (state, packed_masks, deny_counts)`` where the leading axis of
    h1s/h2s/ns is time. One device dispatch for T batches."""
    from ratelimiter_tpu.core.types import Algorithm

    ensure_x64()

    W, sub_us, SW, S, limit = sketch_geometry(cfg)
    d, w = cfg.sketch.depth, cfg.sketch.width
    weighted = cfg.algorithm is not Algorithm.FIXED_WINDOW
    cu = cfg.sketch.conservative_update
    hh, hh_thresh = _hh_params(cfg)
    use_pallas = _resolve_pallas(cfg)
    key = (limit, W, SW, d, w, cfg.max_batch_admission_iters, weighted, cu,
           hh, hh_thresh, use_pallas)
    cached = _SCAN_CACHE.get(key)
    if cached is not None:
        return cached
    step_kw = dict(limit=limit, sub_us=sub_us, SW=SW, S=S, d=d, w=w,
                   iters=cfg.max_batch_admission_iters, weighted=weighted,
                   conservative=cu, hh=hh, hh_thresh=hh_thresh,
                   use_pallas=use_pallas)
    scan = jax.jit(partial(_sketch_scan, step_kw=step_kw), donate_argnums=(0,))
    _SCAN_CACHE[key] = scan
    return scan
