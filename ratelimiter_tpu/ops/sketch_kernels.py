"""Count-min-sketch sliding-window kernels — the TPU_SKETCH hot path.

This is the framework's reason to exist (BASELINE.json north star): replace
"one Redis round-trip per key per decision" with "one fused device call per
*batch* against a fixed-size sketch". Key cardinality no longer costs memory
(reference: ~200 B/user in Redis, ``docs/ARCHITECTURE.md:458-469``; here:
depth x width x ring counters TOTAL, shared by all keys) — the cost moves to
a bounded, measured overestimate that can only cause false *denies*, never
over-admission (SURVEY.md §7.4 hard part #3).

Design (SURVEY.md §2.2 sliding-window row, BASELINE config 4):

* The window is covered by ``SW`` sub-windows of ``sub_us`` each; a ring of
  ``S = SW + 1`` slabs ``int32[S, d, w]`` holds per-sub-window CMS counts.
  The +1 slab is the *boundary* sub-window, weighted by its remaining
  overlap fraction — the same ``prev * (1 - progress)`` shape as the exact
  sliding window (``slidingwindow.go:190-197``), at sub-window resolution.
* A running ``totals int32[d, w]`` equals the sum of all fully-in-window
  slabs, maintained incrementally: slabs are subtracted when they age out
  (a lax.cond that fires ~once per sub-window, not per dispatch — the
  "decay/rotate kernel" of BASELINE config 4) and added to by each batch's
  scatter. No Redis TTLs, no full-state sweep per call (hard part #2).
* Row indices use Kirsch-Mitzenmacher double hashing
  ``col_r = (h1 + r * h2) mod w`` so the device only does 32-bit math; the
  host supplies two 32-bit hash halves per key (uint64 emulation avoided on
  the TPU hot path).
* Estimate = min over rows of ``totals + frac * boundary_slab`` (classic CMS
  min-read), clamped >= 0. Admission reuses ops.segment.admit in f32 units,
  segmenting by h1 (a 32-bit segment-id collision merges two keys' in-batch
  sequencing for that batch only — conservative and vanishingly rare).
* Writes are conditional on admission (denial consumes nothing — the
  documented contract the reference's windows violate, SURVEY.md §2.4.2):
  one scatter-add into the current slab and one into totals.

Time is an explicit int64-microsecond scalar operand; everything about
"which sub-window is current / expired" is integer period arithmetic, so
virtual-time tests are exact (SURVEY.md §4.3).
"""

from __future__ import annotations

import math
from functools import partial
from typing import Callable, Dict, Tuple

import jax

jax.config.update("jax_enable_x64", True)

import jax.numpy as jnp

from ratelimiter_tpu.core.clock import MICROS, to_micros
from ratelimiter_tpu.core.config import Config
from ratelimiter_tpu.core.errors import InvalidConfigError
from ratelimiter_tpu.ops.segment import admit

State = Dict[str, jnp.ndarray]

#: slab_period init: far enough in the past that every slab reads as expired.
_NEVER = -(1 << 40)


def sketch_geometry(cfg: Config) -> tuple[int, int, int, int, int]:
    """Returns (window_us, sub_us, SW, S, limit).

    Fixed-window mode uses a single sub-window (the whole window) and no
    boundary weighting. Sliding mode uses the largest divisor of window_us
    that is <= the requested sketch.sub_windows, so any window duration gets
    an exact integer sub-window size (no fractional-period drift)."""
    from ratelimiter_tpu.core.types import Algorithm

    W = to_micros(cfg.window)
    if cfg.algorithm is Algorithm.FIXED_WINDOW:
        SW = 1
    else:
        SW = next(k for k in range(min(cfg.sketch.sub_windows, W), 0, -1)
                  if W % k == 0)
    return W, W // SW, SW, SW + 1, cfg.limit


def init_state(cfg: Config) -> State:
    _, _, _, S, _ = sketch_geometry(cfg)
    d, w = cfg.sketch.depth, cfg.sketch.width
    return {
        "slabs": jnp.zeros((S, d, w), jnp.int32),
        "totals": jnp.zeros((d, w), jnp.int32),
        "slab_period": jnp.full((S,), _NEVER, jnp.int64),
        "last_period": jnp.asarray(_NEVER, jnp.int64),
    }


def _advance(state: State, p, *, SW: int, S: int) -> State:
    """Advance ring time to period p: subtract slabs that aged out of the
    window from totals (rare; guarded by cond) and recycle the current slab
    if it still holds a previous ring lap."""
    slab_period = state["slab_period"]
    slabs = state["slabs"]
    totals = state["totals"]
    p_old = state["last_period"]

    # Slabs leaving the full-window set (p_old-SW, p_old] -> (p-SW, p].
    was_full = slab_period > p_old - SW
    now_full = slab_period > p - SW
    leaving = was_full & ~now_full

    def sub_leaving(t):
        return t - jnp.tensordot(leaving.astype(jnp.int32), slabs, axes=1)

    totals = jax.lax.cond(jnp.any(leaving), sub_leaving, lambda t: t, totals)

    # Recycle the current slab. Ring invariant: its stored period is
    # congruent to idx mod S and <= p - S, hence already out of the window,
    # so zeroing it never needs a totals correction.
    idx = (p % S).astype(jnp.int32)
    stale = slab_period[idx] != p
    slabs = jax.lax.cond(
        stale, lambda s: s.at[idx].set(jnp.zeros_like(s[0])), lambda s: s, slabs)
    slab_period = slab_period.at[idx].set(p)

    return {"slabs": slabs, "totals": totals, "slab_period": slab_period,
            "last_period": jnp.asarray(p, jnp.int64)}


def _columns(h1, h2, d: int, w: int):
    """Kirsch-Mitzenmacher double-hashed CMS columns, (B, d) int32 flat
    indices into a (d, w) array flattened to (d*w,)."""
    r = jnp.arange(d, dtype=jnp.uint32)
    cols = (h1[:, None] + r[None, :] * h2[:, None]) & jnp.uint32(w - 1)
    return (r[None, :].astype(jnp.int32) * w + cols.astype(jnp.int32))


def _estimate(state: State, flat_cols, p, now_us, *, sub_us: int, SW: int, S: int,
              weighted: bool = True):
    """Min-over-rows window estimate at the given flat columns. ``weighted``
    adds the boundary sub-window scaled by its overlap fraction (sliding
    semantics); fixed-window mode reads totals alone."""
    totals_f = state["totals"].reshape(-1)[flat_cols].astype(jnp.float32)
    if weighted:
        b_idx = ((p - SW) % S).astype(jnp.int32)
        boundary_valid = state["slab_period"][b_idx] == p - SW
        elapsed_in = (now_us - p * sub_us).astype(jnp.float32)
        frac = jnp.where(boundary_valid, 1.0 - elapsed_in / jnp.float32(sub_us), 0.0)
        boundary_f = state["slabs"][b_idx].reshape(-1)[flat_cols].astype(jnp.float32)
        est_rows = totals_f + frac * boundary_f
    else:
        est_rows = totals_f
    return jnp.maximum(jnp.min(est_rows, axis=1), 0.0)  # (B,)


def _sketch_step(state: State, h1, h2, n, now_us, *,
                 limit: int, sub_us: int, SW: int, S: int, d: int, w: int,
                 iters: int, weighted: bool):
    p = now_us // sub_us
    state = _advance(state, p, SW=SW, S=S)

    flat_cols = _columns(h1, h2, d, w)                       # (B, d)
    est = _estimate(state, flat_cols, p, now_us, sub_us=sub_us, SW=SW, S=S,
                    weighted=weighted)

    avail = jnp.maximum(jnp.float32(limit) - est, 0.0)
    n_f = n.astype(jnp.float32)
    sid = jax.lax.bitcast_convert_type(h1, jnp.int32)
    allowed, seen, _ = admit(sid, n_f, avail, iters)

    add = jnp.where(allowed, n, 0).astype(jnp.int32)         # (B,)
    add_bd = jnp.broadcast_to(add[:, None], flat_cols.shape).reshape(-1)
    flat = flat_cols.reshape(-1)
    totals = state["totals"].reshape(-1).at[flat].add(add_bd).reshape(d, w)
    idx = (p % S).astype(jnp.int32)
    cur = state["slabs"][idx].reshape(-1).at[flat].add(add_bd).reshape(d, w)
    slabs = state["slabs"].at[idx].set(cur)

    new_state = {"slabs": slabs, "totals": totals,
                 "slab_period": state["slab_period"],
                 "last_period": state["last_period"]}
    remaining = jnp.maximum(
        jnp.floor(seen - jnp.where(allowed, n_f, 0.0)), 0.0).astype(jnp.int32)
    return new_state, (allowed, remaining, est)


def _sketch_reset(state: State, h1, h2, now_us, *,
                  sub_us: int, SW: int, S: int, d: int, w: int, weighted: bool):
    """Per-key reset: subtract the key's current min-estimate from all its
    cells in both the current slab and totals (equal amounts, preserving the
    totals == sum-of-full-slabs invariant; cells may go transiently negative
    in the slab, reads clamp at 0). Colliding keys gain allowance — errors
    toward allowing, never toward false denial."""
    p = now_us // sub_us
    state = _advance(state, p, SW=SW, S=S)
    flat_cols = _columns(h1, h2, d, w)
    est = _estimate(state, flat_cols, p, now_us, sub_us=sub_us, SW=SW, S=S,
                    weighted=weighted)
    sub = jnp.broadcast_to(
        jnp.floor(est)[:, None].astype(jnp.int32), flat_cols.shape).reshape(-1)
    flat = flat_cols.reshape(-1)
    totals = state["totals"].reshape(-1).at[flat].add(-sub).reshape(d, w)
    idx = (p % S).astype(jnp.int32)
    cur = state["slabs"][idx].reshape(-1).at[flat].add(-sub).reshape(d, w)
    slabs = state["slabs"].at[idx].set(cur)
    return {"slabs": slabs, "totals": totals,
            "slab_period": state["slab_period"],
            "last_period": state["last_period"]}


_STEP_CACHE: Dict[tuple, Callable] = {}


def build_steps(cfg: Config) -> tuple[Callable, Callable]:
    """Returns (step, reset) jitted callables; memoized per static config."""
    from ratelimiter_tpu.core.types import Algorithm

    W, sub_us, SW, S, limit = sketch_geometry(cfg)
    d, w = cfg.sketch.depth, cfg.sketch.width
    weighted = cfg.algorithm is not Algorithm.FIXED_WINDOW
    key = (limit, W, SW, d, w, cfg.max_batch_admission_iters, weighted)
    cached = _STEP_CACHE.get(key)
    if cached is not None:
        return cached
    step = jax.jit(
        partial(_sketch_step, limit=limit, sub_us=sub_us, SW=SW, S=S, d=d, w=w,
                iters=cfg.max_batch_admission_iters, weighted=weighted),
        donate_argnums=(0,))
    reset = jax.jit(
        partial(_sketch_reset, sub_us=sub_us, SW=SW, S=S, d=d, w=w,
                weighted=weighted),
        donate_argnums=(0,))
    _STEP_CACHE[key] = (step, reset)
    return step, reset
