"""Host-side key hashing for the sketch backend.

The reference sends raw string keys over RESP and lets Redis hash them
internally; here keys are reduced to 64 bits at ingest (the serving tier's
job — SURVEY.md §7.4 hard part #4: "keys pre-hashed to u64 on host") and the
device only ever sees two 32-bit halves for Kirsch-Mitzenmacher double
hashing (ops/sketch_kernels._columns).

Two paths:
* strings  -> ratelimiter_tpu.native bulk hasher (word-at-a-time
  multiply-rotate, C++ kernel with a bit-identical vectorized NumPy twin):
  stable across processes/restarts, so checkpointed sketches stay
  addressable. Benched >= 10M keys/s including packing (tests/test_hashing
  has the cross-checks; benchmarks/ the numbers).
* uint64 ids -> splitmix64 finalizer, fully vectorized in NumPy — the fast
  path used by benchmarks and id-keyed tenants.
"""

from __future__ import annotations

from typing import Sequence

import numpy as np

from ratelimiter_tpu.native import bulk_hash_u64


def hash_strings_u64(keys: Sequence[str]) -> np.ndarray:
    """Stable 64-bit hashes of string keys (native bulk hasher)."""
    return bulk_hash_u64(keys)


def hash_prefixed_u64(keys: Sequence[str], prefix: str = "") -> np.ndarray:
    """THE key→hash rule: namespace prefix (exactly as it namespaces
    Redis keys in the reference, ``config.go:81-87``) then the bulk
    hash. One definition shared by the sketch backends
    (SketchLimiter._hash) and the audit tap's string lane
    (observability/audit.py) — if the formatting rule ever changes,
    both move together, or string-lane audit hashes would silently
    diverge from serving hashes."""
    if prefix:
        keys = [f"{prefix}:{k}" for k in keys]
    return bulk_hash_u64(keys)


def splitmix64(x: np.ndarray) -> np.ndarray:
    """Vectorized splitmix64 finalizer: uniform 64-bit mixing of integer ids."""
    x = np.asarray(x, dtype=np.uint64).copy()
    with np.errstate(over="ignore"):
        x += np.uint64(0x9E3779B97F4A7C15)
        x = (x ^ (x >> np.uint64(30))) * np.uint64(0xBF58476D1CE4E5B9)
        x = (x ^ (x >> np.uint64(27))) * np.uint64(0x94D049BB133111EB)
        x = x ^ (x >> np.uint64(31))
    return x


def key_token(key: str) -> str:
    """Irreversible ``key#<16hex>`` token for logs and the control-plane
    event journal (the OPERATIONS §6 PII boundary). ONE definition —
    LoggingDecorator redaction and every journal emit site render keys
    through this, so redacted log lines and journal ``key_hash`` fields
    stay joinable. Hash-of-hash: ``hash_strings_u64`` feeds decisions
    and wire routing, so its raw value is quasi-public; the extra
    splitmix keeps tokens uncorrelatable with routing hashes."""
    return f"key#{int(splitmix64(hash_strings_u64([key]))[0]):016x}"


def split_hash(h64: np.ndarray, seed: int = 0) -> tuple[np.ndarray, np.ndarray]:
    """(h1, h2) uint32 halves for double hashing; h2 forced odd so strides
    cycle the full power-of-two width. A seed remixes per-limiter so two
    sketches never share collision patterns."""
    h = h64
    if seed:
        h = splitmix64(h ^ np.uint64(seed & 0xFFFFFFFFFFFFFFFF))
    h1 = (h & np.uint64(0xFFFFFFFF)).astype(np.uint32)
    h2 = ((h >> np.uint64(32)).astype(np.uint32)) | np.uint32(1)
    return h1, h2


# ------------------------------------------------------- device twins
#
# Bit-identical jnp forms of splitmix64 / split_hash, traced INSIDE the
# jitted decision step (ops/sketch_kernels.build_hashed_step), so the
# serving hot path stages one raw uint64 buffer per batch and the device
# does all per-key mixing — the host never touches per-key hash math
# (ADR-011). uint64 wrap-around semantics match NumPy exactly (jax x64
# is enabled by every entry point via ops.ensure_x64); the host/device
# agreement is fuzz-pinned by tests/test_hashing_device.py.

def splitmix64_dev(x):
    """jnp twin of splitmix64 (same constants, same wrap-around)."""
    import jax.numpy as jnp

    x = x.astype(jnp.uint64) + jnp.uint64(0x9E3779B97F4A7C15)
    x = (x ^ (x >> jnp.uint64(30))) * jnp.uint64(0xBF58476D1CE4E5B9)
    x = (x ^ (x >> jnp.uint64(27))) * jnp.uint64(0x94D049BB133111EB)
    return x ^ (x >> jnp.uint64(31))


def split_hash_dev(h64, seed: int = 0):
    """jnp twin of split_hash; ``seed`` is trace-time static (it is baked
    into the compiled step alongside the sketch geometry)."""
    import jax.numpy as jnp

    h = h64.astype(jnp.uint64)
    if seed:
        h = splitmix64_dev(h ^ jnp.uint64(seed & 0xFFFFFFFFFFFFFFFF))
    h1 = (h & jnp.uint64(0xFFFFFFFF)).astype(jnp.uint32)
    h2 = (h >> jnp.uint64(32)).astype(jnp.uint32) | jnp.uint32(1)
    return h1, h2


# ----------------------------------------------------- splitmix64 inverse
#
# splitmix64 is a bijection on u64 (an odd-constant add, then three
# invertible xorshift-multiply rounds), so a FINALIZED hash can be taken
# back to the raw id that produced it. The fleet tier (ADR-017) uses this
# to forward already-finalized hashes over the plain T_ALLOW_HASHED wire
# lane — the receiver re-finalizes the recovered raw ids and lands on
# bit-identical hashes, so cross-host forwarding needs no new decision
# frame type. Fuzz-pinned round-trip in tests/test_fleet.py.

#: Modular inverses of the two splitmix64 multipliers mod 2^64.
_INV_C1 = np.uint64(pow(0xBF58476D1CE4E5B9, -1, 1 << 64))
_INV_C2 = np.uint64(pow(0x94D049BB133111EB, -1, 1 << 64))


def _unshift_right(x: np.ndarray, s: int) -> np.ndarray:
    """Invert ``y = x ^ (x >> s)`` (iterate to the fixpoint: each round
    recovers ``s`` more high-order-correct bits)."""
    y = x.copy()
    for _ in range(-(-64 // s) - 1):
        y = x ^ (y >> np.uint64(s))
    return y


def splitmix64_inv(x: np.ndarray) -> np.ndarray:
    """Exact inverse of :func:`splitmix64` (vectorized)."""
    x = np.asarray(x, dtype=np.uint64)
    with np.errstate(over="ignore"):
        x = _unshift_right(x, 31)
        x = x * _INV_C2
        x = _unshift_right(x, 27)
        x = x * _INV_C1
        x = _unshift_right(x, 30)
        x = x - np.uint64(0x9E3779B97F4A7C15)
    return x
