"""Device-side override-table lookup — the policy engine's hot-path half.

The policy engine (ratelimiter_tpu/policy/) keeps per-key limit/window
overrides in a fixed-capacity, device-resident table: a SORTED int64 key
array plus parallel value columns. Every decision step consults it with
the branchless binary search below, so a batch mixing default and
overridden keys is still decided in ONE fused dispatch — no per-key host
lookup, no dynamic shapes, no recompiles when entries change (only the
array *contents* change; capacity is the compiled shape).

Key domain: each backend reduces a key to an int64 "search key" host-side
at override-set time (policy/table.py):

* dense backend: the native bulk hash of the formatted key
  (ops/hashing.hash_strings_u64), bit-cast to int64;
* sketch backends: the (h1, h2) uint32 halves the CMS columns are
  derived from, packed as ``(h1 << 32) | h2`` and bit-cast — so the
  query can be packed on device from the operands the step already has,
  and no extra per-request operand crosses the host/device boundary.

Both sides (sort at build time, search at query time) use the SAME int64
total order, so the uint64->int64 bit-cast reordering is harmless.

Padding rows hold PAD_KEY (int64 max) with default values; a search miss
therefore also lands on default values, making ``found`` advisory for
observability rather than load-bearing for correctness.
"""

from __future__ import annotations

from typing import Dict, Tuple

import numpy as np

#: Padding sentinel for unused table rows. A real key hashing to exactly
#: int64-max would match a padding row and read the DEFAULT values — the
#: same decision it would get from a miss (2^-64 per key, and harmless).
PAD_KEY = (1 << 63) - 1


def lookup_i64(table_keys, queries):
    """Branchless binary search: for each query, the index of its match in
    the sorted ``table_keys`` (int64[P], P a power of two, padded with
    PAD_KEY) and whether it matched.

    Returns ``(idx int32[B], found bool[B])`` where idx is safe to gather
    with even on misses (clamped to [0, P-1]).
    """
    import jax.numpy as jnp

    P = table_keys.shape[0]
    assert P & (P - 1) == 0, f"table capacity must be a power of two, got {P}"
    # Classic offset descent: after the loop, idx is the largest i with
    # table_keys[i] <= q (or -1 when every entry is greater). The step
    # sequence starts at P (not P/2) with an explicit bounds mask so the
    # LAST row is reachable — steps summing to P-1 from idx=-1 would top
    # out at P-2 and a FULL table would silently lose its max-key entry.
    idx = jnp.full(queries.shape, -1, jnp.int32)
    step = P
    while step >= 1:
        cand = idx + step
        in_range = cand <= P - 1
        probe = table_keys[jnp.minimum(cand, P - 1)] <= queries
        idx = jnp.where(in_range & probe, cand, idx)
        step //= 2
    safe = jnp.maximum(idx, 0)
    found = (idx >= 0) & (table_keys[safe] == queries)
    return safe, found


def lookup_host(table_keys: np.ndarray, queries: np.ndarray,
                ) -> Tuple[np.ndarray, np.ndarray]:
    """NumPy twin of lookup_i64 (same contract) for host-side result
    assembly and tests."""
    idx = np.searchsorted(table_keys, queries, side="right").astype(np.int64) - 1
    safe = np.maximum(idx, 0).astype(np.int32)
    found = (idx >= 0) & (table_keys[safe] == queries)
    return safe, found


def pack_halves(h1, h2):
    """Device-side (h1, h2) uint32 -> int64 search key, bit-identical to
    policy/table.py's host packing (uint64 ``(h1 << 32) | h2`` bit-cast)."""
    import jax
    import jax.numpy as jnp

    packed = (h1.astype(jnp.uint64) << jnp.uint64(32)) | h2.astype(jnp.uint64)
    return jax.lax.bitcast_convert_type(packed, jnp.int64)


def pack_halves_host(h1: np.ndarray, h2: np.ndarray) -> np.ndarray:
    """Host twin of pack_halves."""
    packed = (h1.astype(np.uint64) << np.uint64(32)) | h2.astype(np.uint64)
    return packed.view(np.int64)


def empty_arrays(capacity: int, defaults: Dict[str, int]) -> Dict[str, np.ndarray]:
    """An all-padding host table: ``key`` int64[capacity] of PAD_KEY plus
    one int64 column per default value. Every lookup misses (or reads
    defaults), so an empty table is behaviorally a no-op."""
    out = {"key": np.full(capacity, PAD_KEY, dtype=np.int64)}
    for name, val in defaults.items():
        out[name] = np.full(capacity, int(val), dtype=np.int64)
    return out
