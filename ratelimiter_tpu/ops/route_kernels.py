"""Device-side all-to-all frame routing for the sliced mesh (ADR-024).

The host router (ADR-013) partitions every mixed frame on the host — a
stable argsort over the owner vector, per-slice sub-launches, a barrier,
and an index-map scatter of results. This module is the SPMD answer the
ADR deferred: one shard_map'd step over the slice mesh in which each
device

1. receives an even 1/n shard of the frame's (h64, ns) columns,
2. computes ``owner = h64 % n`` on device (premix lanes splitmix64
   first — the same finalize-then-mod rule as
   ``SlicedMeshLimiter.owner_of_id``),
3. bins its rows into fixed-capacity per-destination bins and routes
   them with ONE ``jax.lax.all_to_all``,
4. runs the UNCHANGED fused decision kernel
   (sketch_kernels._sketch_step / bucket_kernels._bucket_step) on the
   rows it owns, against its own slice state (sharded, not replicated —
   each device's shard IS that slice's counters), and
5. all-to-all's the verdicts back to source order and assembles the
   finish_window/finish_bucket result columns in frame order.

The host never argsorts, never builds index maps, never fans out
sub-launches; resolve blocks on one ticket.

Bit-identity with the host-routed oracle holds because the destination
device runs the exact same step body on the exact same rows in the exact
same order: a source shard is a contiguous chunk of the frame, bins fill
in shard order, and the tiled all_to_all concatenates source-major — so
an owner's received rows are in global frame order, which is precisely
the order the host router's stable argsort feeds that slice. Pad rows
(key 0, n = 0) are decision-inert in both paths (no mass, no counter
write), so differing pad counts cannot diverge state.

Bins are fixed capacity C per (source, destination) pair — shapes must
be static under jit. A source with more than C rows for one destination
sets a device-computed overflow flag (pmax'd to every device); the step
then keeps ALL state leaves untouched (``jnp.where(ovf, old, new)``)
and the host re-dispatches the frame through the host router, so
admission is never silently dropped OR double-counted. Capacity is
``ceil(bin_headroom * L / n)`` (MeshSpec.bin_headroom): uniform mixed
traffic expects L/n rows per bin, affine single-owner frames need up to
L and deliberately overflow to the host router's single-owner
passthrough instead of paying n× bin memory (the trade-off recorded in
docs/ADR/024-collective-mesh-router.md).
"""

from __future__ import annotations

from typing import Callable, Dict, Tuple

import jax
import jax.numpy as jnp

from ratelimiter_tpu.core.config import Config
from ratelimiter_tpu.ops import ensure_x64
from ratelimiter_tpu.parallel.mesh import AXIS

#: Empty bin slots travel with this ns sentinel so the destination can
#: tell a routed row from bin padding without shipping an index column.
_EMPTY = -1


def bin_capacity(L: int, n: int, headroom: float) -> int:
    """Static per-(source, destination) bin capacity for an L-row shard
    on an n-device mesh. Clamped to [1, L]: a source can send at most
    its whole shard to one destination, and zero-capacity bins would
    overflow every non-empty frame. Two lower bounds apply on top of
    the headroom multiplier when headroom >= 1 (headroom < 1 skips
    both so tests can force capacity-1 bins to exercise the fallback):

    * a flat floor of 8 rows (the _MIN_PAD instinct) so SMALL mixed
      frames — where binomial noise dwarfs the L/n mean — do not
      overflow constantly, and
    * a binomial tail bound ``mean + 4*sqrt(mean) + 8``: each of the
      n^2 (source, destination) pairs receives Bin(L, 1/n) rows, and a
      plain 2x-mean headroom still overflows ~10-20% of uniform frames
      at mid sizes (L=32, n=8 puts C at 8 against a mean of 4 —
      measured maxbin 9-10). Four sigmas plus slack pushes per-frame
      overflow below ~1e-4 while the bin memory stays O(L) per device.
    """
    c = int(-(-int(headroom * L) // n)) if headroom > 0 else 1
    if headroom >= 1.0:
        mean = L / n
        tail = int(mean + 4.0 * mean ** 0.5 + 8)
        c = max(c, 8, tail)
    return max(1, min(L, c))


def _route(h64, ns, b, n: int, L: int, C: int, premix: bool):
    """Per-device routing prologue: owner mod, per-destination ranks,
    bin scatter, one all_to_all each for the key and count columns.
    Returns (h_own, ns_own, order, binpos, keep, ovf_local) where
    h_own/ns_own are the owned rows compacted to the front in global
    frame order and padded with decision-inert (0, 0) rows."""
    from ratelimiter_tpu.ops.hashing import splitmix64_dev

    me = jax.lax.axis_index(AXIS)
    gidx = me.astype(jnp.int64) * L + jnp.arange(L, dtype=jnp.int64)
    valid_src = gidx < b
    hfin = splitmix64_dev(h64) if premix else h64
    owner = (hfin % jnp.uint64(n)).astype(jnp.int32)
    # Exclusive per-destination rank among this shard's valid rows: a
    # one-hot cumsum (L x n) — no sort on the routing path.
    oh = ((owner[:, None] == jnp.arange(n, dtype=jnp.int32)[None, :])
          & valid_src[:, None]).astype(jnp.int32)
    rank = jnp.take_along_axis(jnp.cumsum(oh, axis=0) - oh,
                               owner[:, None].astype(jnp.int32),
                               axis=1)[:, 0]
    keep = valid_src & (rank < C)
    ovf_local = jnp.any(valid_src & (rank >= C))
    binpos = owner * C + rank
    # Out-of-range scatter index drops the row (bin padding keeps the
    # _EMPTY sentinel) — no host-side compaction, no dynamic shapes.
    pos = jnp.where(keep, binpos, n * C)
    send_h = jnp.zeros(n * C, jnp.uint64).at[pos].set(h64, mode="drop")
    send_ns = jnp.full(n * C, _EMPTY, jnp.int32).at[pos].set(
        ns, mode="drop")
    recv_h = jax.lax.all_to_all(send_h, AXIS, 0, 0, tiled=True)
    recv_ns = jax.lax.all_to_all(send_ns, AXIS, 0, 0, tiled=True)
    valid_r = recv_ns != _EMPTY
    # Compact owned rows to the front. Source shards are contiguous
    # frame chunks and the tiled all_to_all concatenates source-major,
    # so a STABLE sort on validity preserves global frame order — the
    # order the host router's stable argsort would feed this slice
    # (the bit-identity linchpin: in-batch same-key sequencing).
    order = jnp.argsort(~valid_r, stable=True)
    vr = valid_r[order]
    h_own = jnp.where(vr, recv_h[order], jnp.uint64(0))
    ns_own = jnp.where(vr, recv_ns[order], 0)
    return h_own, ns_own, order, binpos, keep, ovf_local


def _return_route(cols, order, binpos, keep):
    """Inverse-scatter per-row result columns into the bin layout and
    all_to_all them back to their source devices; gather into source row
    order. Rows the source never shipped (overflow) read slot 0 garbage
    — the frame is re-dispatched host-side in that case, so the values
    never reach a client."""
    out = []
    safe = jnp.where(keep, binpos, 0)
    for c in cols:
        back = jnp.zeros(c.shape, c.dtype).at[order].set(c)
        ret = jax.lax.all_to_all(back, AXIS, 0, 0, tiled=True)
        out.append(ret[safe])
    return out


def state_layout(cfg: Config) -> Tuple[str, Tuple[str, ...],
                                       Tuple[str, ...]]:
    """(kind, mutated leaves, read-only leaves) of the per-slice state
    under one routed step. Read-only leaves (the slab ring and its
    period bookkeeping — only the host-driven rollover writes them) ride
    as a second operand group that is never an output, so the step
    neither copies nor donates them."""
    from ratelimiter_tpu.core.types import Algorithm

    if cfg.algorithm is Algorithm.TOKEN_BUCKET:
        mut = ["debt", "acc", "rem", "last"]
        if cfg.hierarchy.tenants:
            mut += ["tn_counts", "tn_period"]
        return "bucket", tuple(mut), ()
    from ratelimiter_tpu.ops import sketch_kernels

    mut = ["cur", "totals"]
    ro = ["slabs", "slab_period", "last_period"]
    if cfg.hierarchy.tenants:
        mut += ["tn_cur", "tn_totals"]
        ro += ["tn_slabs"]
    hh, _ = sketch_kernels._hh_params(cfg)
    if hh:
        mut += ["hh_owner", "hh_owner2", "hh_cur", "hh_totals", "hh_last"]
        ro += ["hh_slabs"]
    return "sketch", tuple(mut), tuple(ro)


#: Per-slice state leaves that are scalars on a slice (assembled as an
#: (n,) global, local (1,) — the body unwraps/rewraps them).
_SCALAR_LEAVES = frozenset(["last_period", "rem", "last", "tn_period"])

_ROUTED_CACHE: Dict[tuple, Callable] = {}


def build_routed_step(cfg: Config, mesh, *, premix: bool, L: int,
                      capacity: int) -> Callable:
    """Jitted collective ``step(mut, ro, h64, ns, b, now_us, policy[,
    hier])`` over the slice mesh.

    ``mut``/``ro`` are the sharded per-slice state groups
    (state_layout), ``h64``/``ns`` the (n*L,)-padded frame columns
    sharded over AXIS, ``b`` the true row count and ``now_us`` the
    decision timestamp (both replicated scalars; traced, so varying b
    never recompiles — only a new L bucket does). Policy (and cascade)
    tables ride replicated, exactly as on the single-slice step.

    Returns ``(new_mut, (allowed, remaining, retry, reset, mass), ovf)``
    — the four finish columns in global frame order, the per-slice
    admitted mass (n,), and the replicated overflow flag. On overflow
    every state leaf is returned UNCHANGED."""
    from ratelimiter_tpu.parallel.mesh_kernels import _HIER_SPEC, shard_map
    from jax.sharding import PartitionSpec as P

    ensure_x64()
    n = mesh.devices.size
    kind, mut_keys, ro_keys = state_layout(cfg)
    seed = cfg.sketch.seed
    tenants = cfg.hierarchy.tenants
    mesh_key = (tuple(mesh.devices.flat), mesh.axis_names)
    if kind == "sketch":
        from ratelimiter_tpu.core.types import Algorithm
        from ratelimiter_tpu.ops import sketch_kernels

        W, sub_us, SW, S, limit = sketch_kernels.sketch_geometry(cfg)
        d, w = cfg.sketch.depth, cfg.sketch.width
        weighted = cfg.algorithm is not Algorithm.FIXED_WINDOW
        cu = cfg.sketch.conservative_update
        hh, hh_thresh = sketch_kernels._hh_params(cfg)
        use_pallas = sketch_kernels._resolve_pallas(cfg)
        statics = (limit, W, SW, d, w, cfg.max_batch_admission_iters,
                   weighted, cu, hh, hh_thresh, tenants, use_pallas)
        step_kw = dict(limit=limit, sub_us=sub_us, SW=SW, S=S, d=d, w=w,
                       iters=cfg.max_batch_admission_iters,
                       weighted=weighted, conservative=cu, hh=hh,
                       hh_thresh=hh_thresh, tenants=tenants,
                       use_pallas=use_pallas)
    else:
        from ratelimiter_tpu.ops import bucket_kernels

        limit, num, den, d, w, iters = bucket_kernels._params(cfg)
        tenants_, wus = bucket_kernels._hier_params(cfg)
        from ratelimiter_tpu.ops.sketch_kernels import _resolve_pallas

        use_pallas = _resolve_pallas(cfg, bucket=True)
        statics = (limit, num, den, d, w, iters, tenants_, wus, use_pallas)
        step_kw = dict(limit=limit, rate_num=num, rate_den=den, d=d, w=w,
                       iters=iters, tenants=tenants_, window_us=wus,
                       use_pallas=use_pallas)
        window_us = wus
    key = (kind, mesh_key, statics, seed, premix, L, capacity)
    cached = _ROUTED_CACHE.get(key)
    if cached is not None:
        return cached

    C = capacity

    def _unwrap(mut, ro):
        state = {}
        for k in mut_keys:
            state[k] = mut[k][0] if k in _SCALAR_LEAVES else mut[k]
        for k in ro_keys:
            state[k] = ro[k][0] if k in _SCALAR_LEAVES else ro[k]
        return state

    def _rewrap_mut(new_state, old_mut, ovf):
        out = {}
        for k in mut_keys:
            v = new_state[k]
            if k in _SCALAR_LEAVES:
                v = v.reshape(1)
            # Overflow leaves the frame to the host router: EVERY state
            # write is suppressed so the re-dispatch admits each row
            # exactly once (no lost, no duplicated admission mass).
            out[k] = jnp.where(ovf, old_mut[k], v)
        return out

    def body(mut, ro, h64, ns, b, now_us, policy, hier=None):
        from ratelimiter_tpu.ops.hashing import split_hash_dev, \
            splitmix64_dev

        h_own, ns_own, order, binpos, keep, ovf_l = _route(
            h64, ns, b, n, L, C, premix)
        ovf = jax.lax.pmax(ovf_l.astype(jnp.int32), AXIS) > 0
        state = _unwrap(mut, ro)
        h = splitmix64_dev(h_own) if premix else h_own
        h1, h2 = split_hash_dev(h, seed)
        if kind == "sketch":
            from ratelimiter_tpu.ops import sketch_kernels

            new_state, (allowed, remaining, _est) = \
                sketch_kernels._sketch_step(
                    state, h1, h2, ns_own, now_us, policy, hier, **step_kw)
            retry_col = None
        else:
            from ratelimiter_tpu.ops import bucket_kernels

            new_state, (allowed, remaining, retry_us) = \
                bucket_kernels._bucket_step(
                    state, h1, h2, ns_own, now_us, policy, hier, **step_kw)
            retry_col = retry_us
        mass = jnp.sum(jnp.where(allowed, ns_own, 0)
                       .astype(jnp.int64)).reshape(1)
        cols = [allowed.astype(jnp.uint8), remaining]
        if retry_col is not None:
            cols.append(retry_col)
        rets = _return_route(cols, order, binpos, keep)
        allowed_s = rets[0].astype(jnp.bool_)
        remaining_s = rets[1]
        if kind == "sketch":
            from ratelimiter_tpu.ops import sketch_kernels

            fin = sketch_kernels.finish_window(
                allowed_s, remaining_s, now_us, jnp.int64(W))
        else:
            from ratelimiter_tpu.ops import bucket_kernels

            fin = bucket_kernels.finish_bucket(
                allowed_s, remaining_s, rets[2], now_us,
                jnp.int64(window_us))
        return (_rewrap_mut(new_state, mut, ovf), fin + (mass,),
                ovf.astype(jnp.int32))

    mut_spec = {k: P(AXIS) for k in mut_keys}
    ro_spec = {k: P(AXIS) for k in ro_keys}
    policy_spec = {"key": P(), "limit": P()}
    in_specs = [mut_spec, ro_spec, P(AXIS), P(AXIS), P(), P(), policy_spec]
    if tenants:
        in_specs.append(_HIER_SPEC)
    out_specs = (mut_spec,
                 (P(AXIS), P(AXIS), P(AXIS), P(AXIS), P(AXIS)),
                 P())
    # check_vma=False for the same reason as mesh_kernels: ovf IS
    # replicated (a pmax result) but the checker cannot prove it, and
    # the sharded state outputs flow through sort/cumsum chains.
    mapped = shard_map(body, mesh=mesh, in_specs=tuple(in_specs),
                       out_specs=out_specs, check_vma=False)
    # No donation: the assembled global state aliases the slices' own
    # pinned buffers (jax.make_array_from_single_device_arrays is
    # zero-copy), and donating would invalidate them mid-writeback. The
    # RO group (the big slab ring) is never an output, so the copy cost
    # is bounded by the small mutated leaves.
    step = jax.jit(mapped)
    _ROUTED_CACHE[key] = step
    return step
