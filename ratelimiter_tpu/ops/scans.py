"""Fast prefix scans for TPU.

XLA's cumulative ops lower to log-depth reduce-window passes whose cost on
TPU depends heavily on dtype: f32 cumsum/cummax are near-free at our sizes,
while int32 cumsum measured ~100 µs at 128K elements (vs ~0 for f32) —
enough to dominate the sketch hot path. These helpers keep integer
exactness while doing the heavy lifting in f32 on the MXU:

``exact_cumsum_i32``: split each int32 into (hi, lo) 16-bit limbs, run
*blocked* inclusive cumsums — within 128-element blocks via one triangular
matmul per limb (block partial sums stay < 2^23, exactly representable in
f32) — then stitch blocks with a short int32 offset scan. Exact for any
int32 input whose true prefix sums fit in int32 (the caller's contract,
same as jnp.cumsum).

``cummax_f32``/``cumsum_f32``: thin wrappers documenting that the f32
builtins are the fast path.
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

_C = 128  # lane width; one MXU tile per block
# NumPy constant (NOT a jnp array): materializing it lazily inside a traced
# context would cache a tracer; as np it embeds as a compile-time constant.
_TRI_NP = np.triu(np.ones((_C, _C), np.float32))


def _tri() -> jnp.ndarray:
    return jnp.asarray(_TRI_NP)


def exact_cumsum_i32(x: jnp.ndarray) -> jnp.ndarray:
    """Exact inclusive cumsum of an int32 vector, MXU-blocked."""
    n = x.shape[0]
    m = -(-n // _C)
    xp = jnp.pad(x, (0, m * _C - n)).reshape(m, _C)
    hi = jnp.right_shift(xp, 16)                      # arithmetic shift
    lo = xp - (hi << 16)                              # in [0, 2^16)
    tri = _tri()
    # Precision.HIGHEST is required: the TPU MXU's default precision rounds
    # f32 inputs to bf16 (8-bit mantissa), which cannot represent 16-bit
    # limb values exactly. HIGHEST keeps full f32 semantics — exact for all
    # integers < 2^24, which the limb split guarantees.
    hp = jax.lax.Precision.HIGHEST
    lo_c = jnp.dot(lo.astype(jnp.float32), tri,
                   preferred_element_type=jnp.float32, precision=hp)
    hi_c = jnp.dot(hi.astype(jnp.float32), tri,
                   preferred_element_type=jnp.float32, precision=hp)
    within = hi_c.astype(jnp.int32) * 65536 + lo_c.astype(jnp.int32)  # (m, C)
    tot = within[:, -1]
    offs = jnp.cumsum(tot) - tot                      # short int32 scan (m,)
    return (within + offs[:, None]).reshape(-1)[:n]


def cumsum_f32(x: jnp.ndarray) -> jnp.ndarray:
    """f32 inclusive cumsum — the XLA builtin is fast for f32 on TPU."""
    return jnp.cumsum(x)


def cummax_f32(x: jnp.ndarray) -> jnp.ndarray:
    """f32 inclusive cummax — the XLA builtin is fast for f32 on TPU."""
    return jax.lax.cummax(x)


def cumsum_fast(x: jnp.ndarray) -> jnp.ndarray:
    """Dtype-dispatching cumsum: exact MXU path for int32, builtin for
    floats and wider ints (int64 stays on the exact-but-slower builtin —
    only the dense backend's micro-unit path uses it)."""
    if x.dtype == jnp.int32:
        return exact_cumsum_i32(x)
    return jnp.cumsum(x)
