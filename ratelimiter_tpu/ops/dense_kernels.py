"""Fused batched decision kernels over dense slot-addressed state.

These are the TPU-native replacements for the reference's three Lua scripts
(SURVEY.md §2.2): where Redis executes one interpreted script per request
under a global lock, each kernel here decides a whole batch in one jitted
XLA call — gather state for the batch's slots, sequence same-slot requests
with ops.segment.admit, scatter the consumed amounts back. State lives in
HBM across calls (donated buffers); time is an explicit int64-microsecond
operand (SURVEY.md §2.4.14).

The integer recurrences are bit-identical to algorithms/exact.py (see its
module docstring for the micro-token / window-scaled representations), with
an int64-overflow gate checked at build time: configs too large for the
exact-integer path (limits or windows beyond the gates below) raise at
construction rather than silently losing precision.

State layout (arrays have capacity+1 rows; the last row is the padding slot
batches are padded into — padding requests carry n=0 and are discarded on
the host):

* fixed window:  count:int64[C+1], win_start:int64[C+1] (us)
* sliding:       curr:int64[C+1], prev:int64[C+1], win_start:int64[C+1]
* token bucket:  tokens:int64[C+1] (micro-tokens), rem:int64[C+1]
                 (refill remainder), last:int64[C+1] (us)

Per-key policy overrides (ratelimiter_tpu/policy/): each step optionally
takes ``(policy, keyq)`` — the device-resident sorted override table and
the batch's int64 search keys. A vectorized binary search
(ops/policy_kernels.lookup_i64) resolves each request's effective
(limit, window, refill rate) INSIDE the fused step, so mixed
default/override batches still cost one dispatch. With ``policy=None``
the compiled graph is identical to the pre-policy kernels. Because
windows become per-request, retry/reset leave the host: each step
returns (new_state, (allowed, remaining, retry_us, reset_us)) with
reset_us the absolute reset/refill timestamp.

Exact integer state math needs real int64 (microsecond timestamps and
micro-token levels exceed int32): every factory calls ops.ensure_x64()
and refuses to build without jax_enable_x64 — the flag is the embedding
process's to set, never flipped at import time (a test pins that).
"""

from __future__ import annotations

import math
from functools import partial
from typing import Callable, Dict, Tuple

import jax
import jax.numpy as jnp

from ratelimiter_tpu.core.clock import MICROS, to_micros
from ratelimiter_tpu.core.config import Config
from ratelimiter_tpu.core.errors import InvalidConfigError
from ratelimiter_tpu.core.types import Algorithm
from ratelimiter_tpu.ops import ensure_x64, policy_kernels
from ratelimiter_tpu.ops.segment import admit

State = Dict[str, jnp.ndarray]
#: allowed, remaining, retry_us, reset_us (per request)
Outputs = Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray, jnp.ndarray]


def _resolve(policy, keyq, names, defaults):
    """Per-request effective parameters: ``defaults`` (python ints, baked
    static) when no policy table rides the dispatch, else the binary-search
    lookup over the device-resident table for each of ``names``."""
    if policy is None:
        return defaults
    idx, found = policy_kernels.lookup_i64(policy["key"], keyq)
    return tuple(
        jnp.where(found, policy[name][idx], jnp.int64(default))
        for name, default in zip(names, defaults))


def _bcast(x, like):
    """Broadcast a (possibly scalar) time quantity to per-request shape."""
    return jnp.broadcast_to(jnp.asarray(x, jnp.int64), like.shape)


def check_gate_values(limit: int, window_us: int) -> tuple[int, int]:
    """Overflow gates for the exact-integer paths, for one (limit,
    window_us) operating point — the base config AND every policy-table
    override entry must pass (policy/table.py re-runs this per entry, so
    an override a kernel cannot decide exactly is refused at set time).
    Returns the reduced refill fraction (rate_num, rate_den)."""
    W = window_us
    g = math.gcd(limit * MICROS, W)
    num, den = limit * MICROS // g, W // g
    # token bucket: elapsed*num + rem with elapsed < W, rem < den
    if W * num >= 2**62:
        raise InvalidConfigError(
            "limit*window too large for exact integer token math "
            f"(window_us*rate_num = {W * num} >= 2^62)")
    # sliding window: counts*(W) terms and the micro-rescale (x % W) * MICROS
    if limit * W >= 2**61 or W * MICROS >= 2**63:
        raise InvalidConfigError(
            "limit*window too large for exact integer sliding-window math "
            f"(limit*window_us = {limit * W} >= 2^61)")
    # admission cumsum: batch_total <= B * limit * MICROS; B <= 2^20 assumed
    if limit * MICROS >= 2**42:
        raise InvalidConfigError(
            f"limit {limit} too large for micro-unit batch accounting (>= 2^42/1e6)")
    return num, den


def _check_gates(cfg: Config) -> tuple[int, int, int]:
    """Config-level gate wrapper. Returns (window_us, rate_num, rate_den)."""
    W = to_micros(cfg.window)
    num, den = check_gate_values(cfg.limit, W)
    return W, num, den


def _scale_to_micro(x_winscale: jnp.ndarray, window_us: int) -> jnp.ndarray:
    """floor(x * MICROS / window_us) without int64 overflow, for
    x <= limit*window_us < 2^61. Exactness of comparisons is preserved:
    n*MICROS <= floor(x*MICROS/W)  <=>  n*W <= x  for integer n."""
    q, r = x_winscale // window_us, x_winscale % window_us
    return q * MICROS + (r * MICROS) // window_us


# --------------------------------------------------------------- fixed window

def _fixed_window_step(state: State, sid, n, now_us, policy=None, keyq=None,
                       *, limit, window_us, iters):
    lim, W = _resolve(policy, keyq, ("limit", "window_us"),
                      (limit, window_us))
    cur_ws = (now_us // W) * W  # per-request grid when windows are per-key
    count = state["count"][sid]
    stale = state["win_start"][sid] != cur_ws
    count_eff = jnp.where(stale, 0, count)

    n_units = n * MICROS
    avail_units = (lim - count_eff) * MICROS
    allowed, seen, consumed = admit(sid, n_units, avail_units, iters)

    ncap = state["count"].shape[0]
    base = state["count"].at[sid].set(count_eff)  # roll stale windows to 0
    delta = jnp.zeros((ncap,), jnp.int64).at[sid].add(consumed)
    new_state = {
        "count": base + delta // MICROS,
        "win_start": state["win_start"].at[sid].set(
            jnp.broadcast_to(cur_ws, count.shape)),
    }
    remaining = (seen - jnp.where(allowed, n_units, 0)) // MICROS
    reset_us = _bcast(cur_ws + W, remaining)
    retry_us = jnp.where(allowed, 0, reset_us - now_us)
    return new_state, (allowed, remaining, retry_us, reset_us)


# ------------------------------------------------------------- sliding window

def _sliding_window_step(state: State, sid, n, now_us, policy=None, keyq=None,
                         *, limit, window_us, iters):
    lim, W = _resolve(policy, keyq, ("limit", "window_us"),
                      (limit, window_us))
    cur_ws = (now_us // W) * W
    ws = state["win_start"][sid]
    curr = state["curr"][sid]
    prev = state["prev"][sid]
    current = ws == cur_ws
    rolled_one = ws == cur_ws - W
    curr_eff = jnp.where(current, curr, 0)
    prev_eff = jnp.where(current, prev, jnp.where(rolled_one, curr, 0))

    elapsed = now_us - cur_ws
    free_scaled = lim * W - prev_eff * (W - elapsed) - curr_eff * W
    avail_units = _scale_to_micro(free_scaled, W)
    n_units = n * MICROS
    allowed, seen, consumed = admit(sid, n_units, avail_units, iters)

    ncap = state["curr"].shape[0]
    curr_base = state["curr"].at[sid].set(curr_eff)
    delta = jnp.zeros((ncap,), jnp.int64).at[sid].add(consumed)
    new_state = {
        "curr": curr_base + delta // MICROS,
        "prev": state["prev"].at[sid].set(prev_eff),
        "win_start": state["win_start"].at[sid].set(
            jnp.broadcast_to(cur_ws, curr.shape)),
    }
    remaining = (seen - jnp.where(allowed, n_units, 0)) // MICROS
    reset_us = _bcast(cur_ws + W, remaining)
    retry_us = jnp.where(allowed, 0, reset_us - now_us)
    return new_state, (allowed, remaining, retry_us, reset_us)


# --------------------------------------------------------------- token bucket

def _token_bucket_step(state: State, sid, n, now_us, policy=None, keyq=None,
                       *, limit, window_us, rate_num, rate_den, iters):
    lim, W, num, den = _resolve(
        policy, keyq, ("limit", "window_us", "rate_num", "rate_den"),
        (limit, window_us, rate_num, rate_den))
    cap = lim * MICROS
    tokens = state["tokens"][sid]
    rem = state["rem"][sid]
    last = state["last"][sid]

    elapsed = jnp.maximum(0, now_us - last)
    full = elapsed >= W  # time-to-full from any level <= window
    acc = jnp.where(full, 0, elapsed) * num + rem
    tokens_r = tokens + acc // den
    rem_r = acc % den
    capped = full | (tokens_r >= cap)
    tokens_eff = jnp.where(capped, cap, tokens_r)
    rem_eff = jnp.where(capped, 0, rem_r)

    n_units = n * MICROS
    allowed, seen, consumed = admit(sid, n_units, tokens_eff, iters)

    ncap = state["tokens"].shape[0]
    tokens_base = state["tokens"].at[sid].set(tokens_eff)
    delta = jnp.zeros((ncap,), jnp.int64).at[sid].add(consumed)
    new_state = {
        "tokens": tokens_base - delta,
        "rem": state["rem"].at[sid].set(rem_eff),
        "last": state["last"].at[sid].set(now_us),
    }
    remaining = (seen - jnp.where(allowed, n_units, 0)) // MICROS
    # Reference ``tokenbucket.go:122-130``: deficit/rate, ceil'd (exact.py).
    deficit = jnp.maximum(0, n_units - seen)
    retry_us = jnp.where(allowed, 0, -((-deficit * den) // num))
    # Reference reset_at approximation: now + time to fill the whole bucket
    # from empty (``tokenbucket.go:161-165``) == now + window.
    reset_us = _bcast(now_us + W, remaining)
    return new_state, (allowed, remaining, retry_us, reset_us)


# ------------------------------------------------------------------- factory

def init_state(algorithm: Algorithm, capacity: int, limit: int) -> State:
    """Fresh state with capacity+1 rows (last = padding slot). Token buckets
    start full with last=0: the first touch sees elapsed >= window and
    saturates at capacity, which is exactly the reference's or-capacity
    default for absent keys (``tokenbucket.go:31-33``) — and with a policy
    override, the step's per-request cap clamp makes the first touch
    saturate at the KEY'S capacity, so fresh overridden keys burst to
    their own limit."""
    ensure_x64()
    n = capacity + 1
    z = lambda: jnp.zeros((n,), jnp.int64)
    if algorithm is Algorithm.FIXED_WINDOW:
        return {"count": z(), "win_start": z()}
    if algorithm in (Algorithm.SLIDING_WINDOW, Algorithm.TPU_SKETCH):
        return {"curr": z(), "prev": z(), "win_start": z()}
    return {"tokens": jnp.full((n,), limit * MICROS, jnp.int64), "rem": z(), "last": z()}


#: Compiled steps memoized by their static parameters: limiter instances with
#: the same (algorithm, limit, window, iters) share one jitted callable, so
#: JAX's trace cache is hit instead of recompiling per instance.
_STEP_CACHE: Dict[tuple, Callable] = {}


def _step_fn(cfg: Config) -> Callable:
    """The (un-jitted) step function for cfg's algorithm, statics bound."""
    W, num, den = _check_gates(cfg)
    common = dict(limit=cfg.limit, window_us=W, iters=cfg.max_batch_admission_iters)
    if cfg.algorithm is Algorithm.FIXED_WINDOW:
        return partial(_fixed_window_step, **common)
    if cfg.algorithm in (Algorithm.SLIDING_WINDOW, Algorithm.TPU_SKETCH):
        return partial(_sliding_window_step, **common)
    if cfg.algorithm is Algorithm.TOKEN_BUCKET:
        return partial(_token_bucket_step, **common, rate_num=num, rate_den=den)
    raise InvalidConfigError(f"unsupported algorithm {cfg.algorithm}")


def build_step(cfg: Config) -> Callable[[State, jnp.ndarray, jnp.ndarray, jnp.ndarray],
                                        Tuple[State, Outputs]]:
    """Returns the jitted batched step for cfg's algorithm. State buffers are
    donated: the caller must treat the passed-in state as consumed. Call as
    ``step(state, sid, n, now_us[, policy, keyq])`` — the optional trailing
    operands carry the device-resident override table and the batch's int64
    search keys (ops/policy_kernels.py)."""
    ensure_x64()
    W, _, _ = _check_gates(cfg)
    cache_key = (cfg.algorithm, cfg.limit, W, cfg.max_batch_admission_iters)
    cached = _STEP_CACHE.get(cache_key)
    if cached is not None:
        return cached
    step = jax.jit(_step_fn(cfg), donate_argnums=(0,))
    _STEP_CACHE[cache_key] = step
    return step


def _dense_scan(state: State, sids, ns, now0_us, dt_us, *, fn):
    """T sequential dense steps on device (lax.scan), one dispatch —
    sketch_kernels._sketch_scan's shape for slot-addressed state. The
    leading axis of sids/ns is time; timestamps advance dt_us per step.
    Slot assignment (the host half of the dense backend) happens before
    this: sids are already resolved slot ids."""
    from ratelimiter_tpu.ops.sketch_kernels import _pack_bits

    def body(st, xs):
        sid, n, i = xs
        st, (allowed, *_rest) = fn(st, sid, n, now0_us + i * dt_us)
        return st, (_pack_bits(allowed), jnp.sum(~allowed).astype(jnp.int32))

    T = sids.shape[0]
    idx = jnp.arange(T, dtype=jnp.int64)
    state, (packed, denies) = jax.lax.scan(body, state, (sids, ns, idx))
    return state, packed, denies


_SCAN_CACHE: Dict[tuple, Callable] = {}


def build_scan(cfg: Config) -> Callable:
    """Jitted multi-step runner: ``scan(state, sids, ns, now0_us, dt_us)
    -> (state, packed_masks, deny_counts)``. One device dispatch for T
    batches — the amortized shape benchmarks use to see device time
    instead of per-dispatch host round-trips. Default policy only (the
    bench path; policy-bearing traffic goes through build_step)."""
    ensure_x64()
    W, _, _ = _check_gates(cfg)
    key = (cfg.algorithm, cfg.limit, W, cfg.max_batch_admission_iters)
    cached = _SCAN_CACHE.get(key)
    if cached is not None:
        return cached
    scan = jax.jit(partial(_dense_scan, fn=_step_fn(cfg)), donate_argnums=(0,))
    _SCAN_CACHE[key] = scan
    return scan
