"""Fused batched decision kernels over dense slot-addressed state.

These are the TPU-native replacements for the reference's three Lua scripts
(SURVEY.md §2.2): where Redis executes one interpreted script per request
under a global lock, each kernel here decides a whole batch in one jitted
XLA call — gather state for the batch's slots, sequence same-slot requests
with ops.segment.admit, scatter the consumed amounts back. State lives in
HBM across calls (donated buffers); time is an explicit int64-microsecond
operand (SURVEY.md §2.4.14).

The integer recurrences are bit-identical to algorithms/exact.py (see its
module docstring for the micro-token / window-scaled representations), with
an int64-overflow gate checked at build time: configs too large for the
exact-integer path (limits or windows beyond the gates below) raise at
construction rather than silently losing precision.

State layout (arrays have capacity+1 rows; the last row is the padding slot
batches are padded into — padding requests carry n=0 and are discarded on
the host):

* fixed window:  count:int64[C+1], win_start:int64[C+1] (us)
* sliding:       curr:int64[C+1], prev:int64[C+1], win_start:int64[C+1]
* token bucket:  tokens:int64[C+1] (micro-tokens), rem:int64[C+1]
                 (refill remainder), last:int64[C+1] (us)

Each step returns (new_state, outputs) where outputs are per-request
(allowed, remaining, retry_us); retry_us is 0 for the window algorithms
(their retry-after is the scalar time-to-window-reset, computed on the host).
"""

from __future__ import annotations

import math
from functools import partial
from typing import Callable, Dict, Tuple

import jax

# Exact integer state math needs real int64 (microsecond timestamps and
# micro-token levels exceed int32). Enabled once, at first import of a device
# backend; hot-path sketch kernels pick explicit narrow dtypes so they do not
# pay for this default.
jax.config.update("jax_enable_x64", True)

import jax.numpy as jnp

from ratelimiter_tpu.core.clock import MICROS, to_micros
from ratelimiter_tpu.core.config import Config
from ratelimiter_tpu.core.errors import InvalidConfigError
from ratelimiter_tpu.core.types import Algorithm
from ratelimiter_tpu.ops.segment import admit

State = Dict[str, jnp.ndarray]
Outputs = Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]  # allowed, remaining, retry_us


def _check_gates(cfg: Config) -> tuple[int, int, int]:
    """Overflow gates for the exact-integer paths. Returns
    (window_us, rate_num, rate_den)."""
    W = to_micros(cfg.window)
    g = math.gcd(cfg.limit * MICROS, W)
    num, den = cfg.limit * MICROS // g, W // g
    # token bucket: elapsed*num + rem with elapsed < W, rem < den
    if W * num >= 2**62:
        raise InvalidConfigError(
            "limit*window too large for exact integer token math "
            f"(window_us*rate_num = {W * num} >= 2^62)")
    # sliding window: counts*(W) terms and the micro-rescale (x % W) * MICROS
    if cfg.limit * W >= 2**61 or W * MICROS >= 2**63:
        raise InvalidConfigError(
            "limit*window too large for exact integer sliding-window math "
            f"(limit*window_us = {cfg.limit * W} >= 2^61)")
    # admission cumsum: batch_total <= B * limit * MICROS; B <= 2^20 assumed
    if cfg.limit * MICROS >= 2**42:
        raise InvalidConfigError(
            f"limit {cfg.limit} too large for micro-unit batch accounting (>= 2^42/1e6)")
    return W, num, den


def _scale_to_micro(x_winscale: jnp.ndarray, window_us: int) -> jnp.ndarray:
    """floor(x * MICROS / window_us) without int64 overflow, for
    x <= limit*window_us < 2^61. Exactness of comparisons is preserved:
    n*MICROS <= floor(x*MICROS/W)  <=>  n*W <= x  for integer n."""
    q, r = x_winscale // window_us, x_winscale % window_us
    return q * MICROS + (r * MICROS) // window_us


# --------------------------------------------------------------- fixed window

def _fixed_window_step(state: State, sid, n, now_us, *, limit, window_us, iters):
    cur_ws = (now_us // window_us) * window_us
    count = state["count"][sid]
    stale = state["win_start"][sid] != cur_ws
    count_eff = jnp.where(stale, 0, count)

    n_units = n * MICROS
    avail_units = (limit - count_eff) * MICROS
    allowed, seen, consumed = admit(sid, n_units, avail_units, iters)

    ncap = state["count"].shape[0]
    base = state["count"].at[sid].set(count_eff)  # roll stale windows to 0
    delta = jnp.zeros((ncap,), jnp.int64).at[sid].add(consumed)
    new_state = {
        "count": base + delta // MICROS,
        "win_start": state["win_start"].at[sid].set(cur_ws),
    }
    remaining = (seen - jnp.where(allowed, n_units, 0)) // MICROS
    retry_us = jnp.zeros_like(remaining)
    return new_state, (allowed, remaining, retry_us)


# ------------------------------------------------------------- sliding window

def _sliding_window_step(state: State, sid, n, now_us, *, limit, window_us, iters):
    W = window_us
    cur_ws = (now_us // W) * W
    ws = state["win_start"][sid]
    curr = state["curr"][sid]
    prev = state["prev"][sid]
    current = ws == cur_ws
    rolled_one = ws == cur_ws - W
    curr_eff = jnp.where(current, curr, 0)
    prev_eff = jnp.where(current, prev, jnp.where(rolled_one, curr, 0))

    elapsed = now_us - cur_ws
    free_scaled = limit * W - prev_eff * (W - elapsed) - curr_eff * W
    avail_units = _scale_to_micro(free_scaled, W)
    n_units = n * MICROS
    allowed, seen, consumed = admit(sid, n_units, avail_units, iters)

    ncap = state["curr"].shape[0]
    curr_base = state["curr"].at[sid].set(curr_eff)
    delta = jnp.zeros((ncap,), jnp.int64).at[sid].add(consumed)
    new_state = {
        "curr": curr_base + delta // MICROS,
        "prev": state["prev"].at[sid].set(prev_eff),
        "win_start": state["win_start"].at[sid].set(cur_ws),
    }
    remaining = (seen - jnp.where(allowed, n_units, 0)) // MICROS
    retry_us = jnp.zeros_like(remaining)
    return new_state, (allowed, remaining, retry_us)


# --------------------------------------------------------------- token bucket

def _token_bucket_step(state: State, sid, n, now_us, *, limit, window_us,
                       rate_num, rate_den, iters):
    cap = limit * MICROS
    tokens = state["tokens"][sid]
    rem = state["rem"][sid]
    last = state["last"][sid]

    elapsed = jnp.maximum(0, now_us - last)
    full = elapsed >= window_us  # time-to-full from any level <= window
    acc = jnp.where(full, 0, elapsed) * rate_num + rem
    tokens_r = tokens + acc // rate_den
    rem_r = acc % rate_den
    capped = full | (tokens_r >= cap)
    tokens_eff = jnp.where(capped, cap, tokens_r)
    rem_eff = jnp.where(capped, 0, rem_r)

    n_units = n * MICROS
    allowed, seen, consumed = admit(sid, n_units, tokens_eff, iters)

    ncap = state["tokens"].shape[0]
    tokens_base = state["tokens"].at[sid].set(tokens_eff)
    delta = jnp.zeros((ncap,), jnp.int64).at[sid].add(consumed)
    new_state = {
        "tokens": tokens_base - delta,
        "rem": state["rem"].at[sid].set(rem_eff),
        "last": state["last"].at[sid].set(now_us),
    }
    remaining = (seen - jnp.where(allowed, n_units, 0)) // MICROS
    # Reference ``tokenbucket.go:122-130``: deficit/rate, ceil'd (exact.py).
    deficit = jnp.maximum(0, n_units - seen)
    retry_us = jnp.where(allowed, 0, -((-deficit * rate_den) // rate_num))
    return new_state, (allowed, remaining, retry_us)


# ------------------------------------------------------------------- factory

def init_state(algorithm: Algorithm, capacity: int, limit: int) -> State:
    """Fresh state with capacity+1 rows (last = padding slot). Token buckets
    start full with last=0: the first touch sees elapsed >= window and
    saturates at capacity, which is exactly the reference's or-capacity
    default for absent keys (``tokenbucket.go:31-33``)."""
    n = capacity + 1
    z = lambda: jnp.zeros((n,), jnp.int64)
    if algorithm is Algorithm.FIXED_WINDOW:
        return {"count": z(), "win_start": z()}
    if algorithm in (Algorithm.SLIDING_WINDOW, Algorithm.TPU_SKETCH):
        return {"curr": z(), "prev": z(), "win_start": z()}
    return {"tokens": jnp.full((n,), limit * MICROS, jnp.int64), "rem": z(), "last": z()}


#: Compiled steps memoized by their static parameters: limiter instances with
#: the same (algorithm, limit, window, iters) share one jitted callable, so
#: JAX's trace cache is hit instead of recompiling per instance.
_STEP_CACHE: Dict[tuple, Callable] = {}


def _step_fn(cfg: Config) -> Callable:
    """The (un-jitted) step function for cfg's algorithm, statics bound."""
    W, num, den = _check_gates(cfg)
    common = dict(limit=cfg.limit, window_us=W, iters=cfg.max_batch_admission_iters)
    if cfg.algorithm is Algorithm.FIXED_WINDOW:
        return partial(_fixed_window_step, **common)
    if cfg.algorithm in (Algorithm.SLIDING_WINDOW, Algorithm.TPU_SKETCH):
        return partial(_sliding_window_step, **common)
    if cfg.algorithm is Algorithm.TOKEN_BUCKET:
        return partial(_token_bucket_step, **common, rate_num=num, rate_den=den)
    raise InvalidConfigError(f"unsupported algorithm {cfg.algorithm}")


def build_step(cfg: Config) -> Callable[[State, jnp.ndarray, jnp.ndarray, jnp.ndarray],
                                        Tuple[State, Outputs]]:
    """Returns the jitted batched step for cfg's algorithm. State buffers are
    donated: the caller must treat the passed-in state as consumed."""
    W, _, _ = _check_gates(cfg)
    cache_key = (cfg.algorithm, cfg.limit, W, cfg.max_batch_admission_iters)
    cached = _STEP_CACHE.get(cache_key)
    if cached is not None:
        return cached
    step = jax.jit(_step_fn(cfg), donate_argnums=(0,))
    _STEP_CACHE[cache_key] = step
    return step


def _dense_scan(state: State, sids, ns, now0_us, dt_us, *, fn):
    """T sequential dense steps on device (lax.scan), one dispatch —
    sketch_kernels._sketch_scan's shape for slot-addressed state. The
    leading axis of sids/ns is time; timestamps advance dt_us per step.
    Slot assignment (the host half of the dense backend) happens before
    this: sids are already resolved slot ids."""
    from ratelimiter_tpu.ops.sketch_kernels import _pack_bits

    def body(st, xs):
        sid, n, i = xs
        st, (allowed, _rem, _retry) = fn(st, sid, n, now0_us + i * dt_us)
        return st, (_pack_bits(allowed), jnp.sum(~allowed).astype(jnp.int32))

    T = sids.shape[0]
    idx = jnp.arange(T, dtype=jnp.int64)
    state, (packed, denies) = jax.lax.scan(body, state, (sids, ns, idx))
    return state, packed, denies


_SCAN_CACHE: Dict[tuple, Callable] = {}


def build_scan(cfg: Config) -> Callable:
    """Jitted multi-step runner: ``scan(state, sids, ns, now0_us, dt_us)
    -> (state, packed_masks, deny_counts)``. One device dispatch for T
    batches — the amortized shape benchmarks use to see device time
    instead of per-dispatch host round-trips."""
    W, _, _ = _check_gates(cfg)
    key = (cfg.algorithm, cfg.limit, W, cfg.max_batch_admission_iters)
    cached = _SCAN_CACHE.get(key)
    if cached is not None:
        return cached
    scan = jax.jit(partial(_dense_scan, fn=_step_fn(cfg)), donate_argnums=(0,))
    _SCAN_CACHE[key] = scan
    return scan
