"""Device-side hierarchical cascade — tenant derivation, tiered
admission, weighted fair sharing (ADR-020).

The cascade extends a backend's jitted decision step (sketch windowed,
sketched token bucket, their mesh twins) to evaluate THREE nested scopes
per request — key → tenant → global — in the same single device
dispatch. Nothing new crosses the wire: tenant ids derive ON DEVICE from
a policy-table-style sorted key→tenant map (the same branchless binary
search as ops/policy_kernels.lookup_i64, over the same packed (h1, h2)
search-key domain), and the per-tenant + global counter slab updates in
the same kernel pass as the key-scope sketch write.

Admission semantics (the contract tests/test_hierarchy.py pins against a
host-side sequential reference):

* **Stage 1 — key scope**: the backend's existing greedy in-batch-order
  admission (ops/segment.admit) against per-key availability, exactly as
  without the hierarchy.
* **Stage 2 — tenant scope**: among stage-1 survivors, greedy in-batch-
  order admission per tenant segment against that tenant's availability
  (limit − in-window count).
* **Stage 3 — global scope + fair share**: per-tenant demand is the
  stage-2 survivor mass. When total demand fits the global availability
  G, every survivor passes. Under contention each ACTIVE tenant's
  admissible mass is clipped to ``G * weight_t // Σ active weights``
  (exact int64 math; floor division means the clipped caps can only
  under-fill G — toward denying, never over-admission), and survivors
  admit greedily in batch order within their tenant up to the cap.

Admission is **all-or-nothing**: a request is allowed iff it passes all
three scopes, and a denied request consumes nothing at ANY scope — the
caller recomputes every scope's consumption under the final mask
(ops/segment.segment_consumption) before writing state. One documented
in-batch artifact follows from staging: a request that passes the key
scope but dies at a later scope still occupied key/tenant budget during
the earlier stages' in-batch sequencing, so a later same-key request in
the SAME batch may be denied where a fully sequential joint evaluation
would have admitted it. The artifact lasts one batch, errs toward
denying, and preserves the module-wide never-over-admit direction.

Quantities are plain int64 request counts at the tenant/global scopes
(both backends), so limits up to the HIER_UNLIMITED sentinel (2^40) stay
exact regardless of the key scope's f32/micro-unit domain.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ratelimiter_tpu.core.config import HIER_UNLIMITED
from ratelimiter_tpu.ops import policy_kernels
from ratelimiter_tpu.ops.segment import admit

#: Device-side mirror of core.config.HIER_UNLIMITED (re-exported so
#: kernels and the host TenantTable agree on one sentinel).
UNLIMITED = HIER_UNLIMITED


def derive_tids(hier, h1, h2, tenants: int):
    """(B,) int32 tenant ids for a batch: binary-search the sorted
    key→tenant map on the packed (h1, h2) search key; misses land on the
    default tenant 0. ``hier`` is the device table dict
    {key, tid, limit, weight} (hierarchy/tenants.py host_arrays)."""
    q = policy_kernels.pack_halves(h1, h2)
    idx, found = policy_kernels.lookup_i64(hier["key"], q)
    tid = jnp.where(found, hier["tid"][idx], jnp.int64(0))
    # Clamp defends against a corrupt table row; tid is a gather index
    # into (tenants+1,) slabs where index ``tenants`` is the global slot.
    return jnp.clip(tid, 0, tenants - 1).astype(jnp.int32)


#: Widest tenant domain the dense (one-hot) admission path materializes
#: as a [B, tenants+1] expansion; beyond it the generic sort-based
#: ops/segment.admit runs instead. 64 int32 columns keep the expansion
#: under 1 MB/4k-batch while covering every realistic tenant count.
_DENSE_MAX_SCOPES = 64


def _admit_dense(tid, n, avail, scopes: int, iters: int):
    """Sort-free twin of ops/segment.admit for a SMALL id domain.

    Same greedy fixpoint + safety intersection, bit-identical masks: the
    segment-exclusive cumsum is computed as an exclusive per-column
    cumsum of the one-hot expansion (requests are already in batch
    order, so no sort/unsort passes — the generic admit's dominant
    cost). int32 accumulation is exact under the same total-batch-
    consumption < 2^31 precondition the f32-exact path documents;
    comparisons run in int64 (tenant/global avail carries the 2^40
    UNLIMITED sentinel).
    """
    onehot = tid[:, None] == jnp.arange(scopes, dtype=tid.dtype)[None, :]
    oh32 = onehot.astype(jnp.int32)
    n32 = n.astype(jnp.int32)
    zero32 = jnp.zeros((), jnp.int32)

    def cons_under(mask):
        x = jnp.where(mask, n32, zero32)[:, None] * oh32
        pref = jnp.cumsum(x, axis=0) - x      # exclusive, per column
        return jnp.sum(jnp.where(onehot, pref, 0),
                       axis=1).astype(jnp.int64)

    allowed = jnp.ones(tid.shape, dtype=bool)
    for _ in range(iters):
        allowed = cons_under(allowed) + n <= avail
    cons = cons_under(allowed)
    return allowed & (cons + n <= avail)


def _admit_scope(tid, n, avail, tenants: int, iters: int):
    """Tenant-domain admission: dense one-hot path for realistic tenant
    counts, generic sort-based admit for very wide configs."""
    if tenants + 1 <= _DENSE_MAX_SCOPES:
        return _admit_dense(tid, n, avail, tenants + 1, iters)
    allowed, _, _ = admit(tid, n, avail, iters)
    return allowed


def cascade_admit(allowed_key, tid, n, avail_scopes, weights,
                  tenants: int, iters: int):
    """Stages 2+3 of the cascade over one batch.

    Args:
        allowed_key: bool[B] — stage-1 (key scope) verdicts.
        tid: int32[B] tenant id per request (0 = default tenant).
        n: int64[B] requested amounts (request counts).
        avail_scopes: int64[tenants+1] free quota per tenant, with the
            GLOBAL scope's availability at index ``tenants``.
        weights: int64[tenants+1] fair-share weights (>= 1; the global
            slot's weight is ignored).
        tenants: static tenant capacity (slab width − 1).
        iters: admission fixpoint iterations (the backend's
            max_batch_admission_iters — same exactness contract as
            ops/segment.admit).

    Returns ``(allowed bool[B], hist int64[tenants+1])`` — the final
    all-or-nothing mask and the admitted-mass histogram (per tenant,
    global total at index ``tenants``) ready to fold into the counter
    slab.
    """
    n = n.astype(jnp.int64)
    n2 = jnp.where(allowed_key, n, jnp.int64(0))
    # Key-survivor demand per tenant (global slot stays 0 — tids clamp
    # to [0, tenants)), and its total: the contention predicate.
    demand2 = jnp.zeros((tenants + 1,), jnp.int64).at[tid].add(n2)
    total2 = jnp.sum(demand2)
    g_avail = avail_scopes[tenants]
    uncontended = (jnp.all(demand2[:tenants] <= avail_scopes[:tenants])
                   & (total2 <= g_avail))

    def _uncontended():
        # Every tenant's whole demand fits its availability and the
        # batch total fits the global scope: greedy admission passes
        # every key-scope survivor at both stages (exactly — greedy
        # only ever denies when some cumulative crosses its bound), so
        # the verdicts ARE the stage-1 mask and the histogram is the
        # demand histogram. This is the steady-state serving case; the
        # staged machinery below only runs under real contention.
        return allowed_key, demand2.at[tenants].set(total2)

    def _contended():
        # Stage 2: tenant-scope greedy among key-scope survivors.
        # Masked requests (n=0) always fit; the intersection removes
        # them.
        a2 = _admit_scope(tid, n2, avail_scopes[tid], tenants, iters)
        surv = allowed_key & a2

        # Stage 3: weighted fair share of the global scope. Demand is
        # the survivor mass per tenant; under contention each active
        # tenant's cap is its weight's proportional share of G (floor —
        # under-fills, never over-admits). Uncontended, cap == demand
        # and the admit below passes every survivor (each tenant's
        # cumulative mass is exactly its demand).
        n3 = jnp.where(surv, n, jnp.int64(0))
        demand = jnp.zeros((tenants + 1,), jnp.int64).at[tid].add(n3)
        total = jnp.sum(demand)
        active_w = jnp.where(demand > 0, weights, jnp.int64(0))
        w_sum = jnp.maximum(jnp.sum(active_w), 1)
        # g_avail <= 2^40 (HIER_UNLIMITED) and weights <= 2^20: the
        # product stays < 2^62, exact in int64.
        share = (g_avail * weights) // w_sum
        cap = jnp.where(total > g_avail, jnp.minimum(demand, share),
                        demand)
        a3 = _admit_scope(tid, n3, cap[tid], tenants, iters)
        allowed = surv & a3

        adm = jnp.where(allowed, n, jnp.int64(0))
        hist = jnp.zeros((tenants + 1,), jnp.int64).at[tid].add(adm)
        return allowed, hist.at[tenants].add(jnp.sum(adm))

    return jax.lax.cond(uncontended, _uncontended, _contended)


def scope_avail(limits, counts):
    """int64[T+1] per-scope availability: max(limit − in-window count, 0).
    ``limits`` carries the UNLIMITED sentinel for uncapped scopes."""
    return jnp.maximum(limits - counts.astype(jnp.int64), 0)
