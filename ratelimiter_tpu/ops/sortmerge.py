"""Sort-merge table access: gather/scatter-free reads and histogram writes.

TPU has no hardware gather/scatter; XLA lowers both to ~7 ns/element
sequential loops, which made the naive CMS hot path scatter-bound
(measured on-chip: scatter/gather ~7 ns/elem vs lax.sort ~0.3-1 ns/elem
and cumsum ~0.2 ns/elem). These helpers express "read table[col] for a
batch of cols" and "table[col] += add" as *sorts plus cumsums* instead:

* mix-sort: concatenate the w table cells (key ``2*c``) with the B batch
  elements (key ``2*col + 1``) and stable-sort; every batch element lands
  immediately after its cell.
* read (``row_gather``): delta-encode the table row (``diff`` with
  prepend 0), carry deltas as sort payload, cumsum over the merged order —
  the running sum at a batch element's position is exactly ``row[col]``.
* write (``row_histogram``): carry per-request adds as payload, cumsum;
  the running sum at cell ``c`` is the total of adds with ``col < c``;
  a second "unmix" sort brings cells back into dense col order and a diff
  yields the per-cell histogram to add densely.
* unmix-sort: key ``is_batch ? (w + src_index) : col`` restores original
  batch order (reads) or dense cell order (writes) in one stable sort.

Cost per call: 2 sorts of (w + B) + O(w + B) vector work — independent of
key duplication, no sequential memory loop anywhere. This is the moral
equivalent of Redis pipelining all commands of a batch through one pass
over the keyspace, and it is what makes the ``allow_batch`` hot path
(SURVEY.md §7.4 hard part #4) MXU/VPU-friendly.

All functions are shape-polymorphic in B and w but jit-static per shape.
"""

from __future__ import annotations

from typing import Sequence, Tuple

import jax
import jax.numpy as jnp

from ratelimiter_tpu.ops.scans import cumsum_fast


def _use_sortmerge(B: int, w: int) -> bool:
    """Static strategy choice (trace-time). Sort-merge pays two sorts of
    (w + B) — every sort carries the whole table — while direct indexing
    pays ~7-10 ns per batch element, sequential-on-TPU. Measured on v5e
    (d=3, w=2^20, full step): direct wins 2.2x at B=64K, ties near B=256K,
    sort-merge wins 1.7x at B=1M. Crossover is where B's serialized gather
    cost overtakes the table-dominated sort cost, i.e. B ~ w/2. CPU/GPU
    backends have native gather/scatter — always direct there."""
    import jax

    if jax.default_backend() not in ("tpu", "axon"):
        return False
    return B >= max(64, w // 2)


def _mix_keys(col: jnp.ndarray, w: int) -> jnp.ndarray:
    """int32[(w+B,)] merge keys: cell c -> 2c, batch element -> 2*col+1."""
    cells = (jax.lax.iota(jnp.int32, w) * 2)
    batch = col.astype(jnp.int32) * 2 + 1
    return jnp.concatenate([cells, batch])


def row_gather(rows: Sequence[jnp.ndarray], col: jnp.ndarray) -> Tuple[jnp.ndarray, ...]:
    """Read ``row[col]`` for each row in ``rows`` at a common (B,) col vector.

    Returns a tuple of (B,) arrays in the original batch order. All rows
    must share shape (w,); integer dtypes are propagated exactly (delta
    encoding telescopes back losslessly in int32).
    """
    w = rows[0].shape[0]
    B = col.shape[0]
    if not _use_sortmerge(B, w):
        return tuple(r[col] for r in rows)
    key = _mix_keys(col, w)
    zeros_b = jnp.zeros((B,), rows[0].dtype)
    deltas = [jnp.concatenate([jnp.diff(r, prepend=r.dtype.type(0)), zeros_b])
              for r in rows]
    # src: batch elements carry their original index, cells carry -1.
    src = jnp.concatenate([jnp.full((w,), -1, jnp.int32),
                           jax.lax.iota(jnp.int32, B)])
    sorted_ops = jax.lax.sort((key, src, *deltas), num_keys=1, is_stable=True)
    s_src = sorted_ops[1]
    props = [cumsum_fast(d) for d in sorted_ops[2:]]
    # Unmix: batch entries first, ordered by original index.
    ukey = jnp.where(s_src >= 0, s_src, B + (sorted_ops[0] >> 1))
    unmixed = jax.lax.sort((ukey, *props), num_keys=1, is_stable=True)
    return tuple(u[:B] for u in unmixed[1:])


def row_histogram(col: jnp.ndarray, add: jnp.ndarray, w: int) -> jnp.ndarray:
    """Dense (w,) histogram H with ``H[c] = sum(add[col == c])``.

    The caller applies it with a vectorized ``row + H`` — no scatter.
    """
    B = col.shape[0]
    if not _use_sortmerge(B, w):
        return jnp.zeros((w,), add.dtype).at[col].add(add)
    key = _mix_keys(col, w)
    payload = jnp.concatenate([jnp.zeros((w,), add.dtype), add])
    s_key, s_pay = jax.lax.sort((key, payload), num_keys=1, is_stable=True)
    run = cumsum_fast(s_pay)
    is_cell = (s_key & 1) == 0
    # Cells first in dense col order; batch entries pushed to the tail.
    ukey = jnp.where(is_cell, s_key >> 1, w + jax.lax.iota(jnp.int32, w + B))
    _, u_run = jax.lax.sort((ukey, run), num_keys=1, is_stable=True)
    a_less = u_run[:w]          # adds with col < c, for each cell c
    total = run[-1]
    return jnp.diff(a_less, append=total[None])


def row_histogram_max(col: jnp.ndarray, val: jnp.ndarray, w: int) -> jnp.ndarray:
    """Dense (w,) per-column maxima: ``M[c] = max(val[col == c])``, 0 where
    a column has no entries. ``val`` must be non-negative f32.

    This is the conservative-update write primitive: the caller raises row
    cells with ``row += relu(M - window_read_dense)`` so a cell only grows
    to the largest single-key target that maps to it, not the sum
    (SURVEY.md §7.4 hard part #3).

    Mechanics: two-key sort puts each column's entries immediately after
    their cell, largest value first; the element *after* a cell is therefore
    its column max (or the next cell, when the column is empty); an unmix
    sort lands those per-cell picks back in dense column order.
    """
    B = col.shape[0]
    if not _use_sortmerge(B, w):
        return jnp.zeros((w,), val.dtype).at[col].max(val)
    key = _mix_keys(col, w)
    negv = jnp.concatenate([jnp.zeros((w,), val.dtype), -val])
    s_key, s_negv = jax.lax.sort((key, negv), num_keys=2, is_stable=False)
    is_batch = (s_key & 1) == 1
    first = is_batch & jnp.concatenate(
        [jnp.ones((1,), bool), ~is_batch[:-1]])   # first batch entry of a run
    contrib = jnp.where(first, -s_negv, 0.0)
    after = jnp.concatenate([contrib[1:], jnp.zeros((1,), val.dtype)])
    is_cell = ~is_batch
    ukey = jnp.where(is_cell, s_key >> 1, w + jax.lax.iota(jnp.int32, w + B))
    _, u_after = jax.lax.sort((ukey, after), num_keys=1, is_stable=False)
    return u_after[:w]
