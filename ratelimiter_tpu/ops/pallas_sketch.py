"""Fused Pallas TPU kernels for the sketch hot loop (ADR-011).

The jnp/XLA reference path (ops/sketch_kernels.py, ops/bucket_kernels.py)
expresses one decision step as ~10 separate HLO ops: materialize the
(B, d) column matrix, densify the boundary-weighted combine into a full
(d, w) f32 table, gather per row, min-fold, then scatter the write
histograms through another (d, w) round trip. On TPU each of those ops is
a kernel launch and an HBM materialization. The kernels here fuse each
half of the table access into ONE Pallas kernel gridded over the sketch
rows:

* ``window_estimate``  — column derivation (Kirsch-Mitzenmacher, in
  kernel), boundary sub-window weighting (the rollover-boundary combine),
  gather, and the min-over-rows fold, with nothing but the (B,) estimate
  leaving the kernel;
* ``cu_update`` / ``add_update`` — column derivation, per-column
  conservative-update segment max (or vanilla histogram), dense window
  read, delta clamp, and the in-place totals/cur adds, with the state
  slabs aliased in place (``input_output_aliases``);
* ``bucket_estimate`` / ``bucket_update`` — the token-bucket (GCRA debt
  meter) variants: scalar decay applied on the fly, no decayed slab ever
  materialized.

Contract (tier-1 enforced, tests/test_pallas_parity.py): decisions,
remaining, retry and reset from these kernels are BIT-IDENTICAL to the
jnp reference. That holds by construction — every float op runs in the
same order on the same values as the reference (the scatter max/add
reorderings are exact: f32 max over non-negative finite values and
integer adds are order-insensitive) — and it is what lets ``kernels=`` be
a pure execution knob (excluded from the checkpoint fingerprint).

The batch-sequencing core (ops/segment.admit) is deliberately NOT inside
the kernels: it is sort-based (multi-operand ``lax.sort`` has no Mosaic
lowering), already TPU-shaped, and SHARED with the reference path — which
is also how bit-identity of the decision logic is maintained. The fused
kernels bracket it: fused read -> admit -> fused write.

Backend handling: on non-TPU backends every kernel runs in Pallas
interpret mode (bit-identical, slow — the CI parity lane and the
``kernels="pallas"`` fallback everywhere). The bucket kernels operate on
the int64 debt slab; Mosaic has no 64-bit vector path today, so the auto
selector never picks them on real TPUs (ops resolve_kernels) — forcing
``kernels="pallas"`` for a bucket limiter on a TPU is a parity tool, not
a serving configuration.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:  # pltpu is importable on every backend; guard for exotic builds
    from jax.experimental.pallas import tpu as pltpu
except Exception:  # pragma: no cover
    pltpu = None

from ratelimiter_tpu.core.errors import InvalidConfigError

#: Auto-selector VMEM budget for one (d, w) int32 slab: each fused kernel
#: holds up to three row blocks plus batch vectors resident; geometries
#: past this fall back to the jnp path rather than risk a VMEM OOM at
#: compile time (docs/OPERATIONS.md, `kernels` row).
AUTO_VMEM_SLAB_BYTES = 4 << 20

#: Debt-cell clamp, mirrored from ops/bucket_kernels._DEBT_CAP (importing
#: it would be circular: bucket_kernels imports this module).
_DEBT_CAP = 1 << 61


def _interpret() -> bool:
    """Interpret mode off-TPU: same numerics, no Mosaic requirement."""
    return jax.default_backend() not in ("tpu",)


def resolve_kernels(cfg, *, bucket: bool = False) -> str:
    """Resolve cfg.sketch.kernels to a concrete choice ("pallas"|"jnp").

    auto: pallas on TPU backends when the geometry fits the VMEM budget,
    no heavy-hitter side table is configured, and (for the windowed
    sketch) the slabs are int32; the int64 debt slab keeps auto on jnp
    for bucket limiters on real TPUs (no Mosaic 64-bit vector path).
    Forcing "pallas" with hh_slots raises — the side table's private-cell
    reads are not fused (ADR-011 §limits).
    """
    choice = cfg.sketch.kernels
    if choice == "jnp":
        return "jnp"
    hh = cfg.sketch.hh_slots
    if choice == "pallas":
        if hh:
            raise InvalidConfigError(
                "kernels='pallas' does not support the heavy-hitter side "
                "table (hh_slots > 0); use kernels='jnp' for hh configs")
        return "pallas"
    # auto
    if hh:
        return "jnp"
    if jax.default_backend() != "tpu":
        return "jnp"
    if bucket:
        return "jnp"  # int64 debt slab: no Mosaic 64-bit vector path
    if cfg.sketch.depth * cfg.sketch.width * 4 > AUTO_VMEM_SLAB_BYTES:
        return "jnp"
    return "pallas"


def _cols_for_row(h1, h2, r, w: int):
    """Row r's CMS columns, derived IN KERNEL from the two hash halves —
    the (B, d) column matrix never exists in HBM on the fused path.
    Bit-identical to sketch_kernels._columns row r."""
    cols = (h1 + r.astype(jnp.uint32) * h2) & jnp.uint32(w - 1)
    return cols.astype(jnp.int32)


# ------------------------------------------------------ windowed sketch


def _window_estimate_kernel(frac_ref, h1_ref, h2_ref, totals_ref,
                            boundary_ref, est_ref, *, w: int):
    r = pl.program_id(0)
    cols = _cols_for_row(h1_ref[0, :], h2_ref[0, :], r, w)
    # Dense boundary-weighted combine for THIS row, then gather: the same
    # dense-combine-then-gather order as the reference's direct-indexing
    # regime (numerically identical to its sort-merge regime too — both
    # compute totals[c] + frac * boundary[c] elementwise).
    combined = (totals_ref[0, :].astype(jnp.float32)
                + frac_ref[0, 0] * boundary_ref[0, :].astype(jnp.float32))
    e_r = combined[cols]
    # Sequential grid => the min folds in row order, exactly like the
    # reference's est = min(min(e_0, e_1), ...) chain.

    @pl.when(r == 0)
    def _():
        est_ref[0, :] = e_r

    @pl.when(r != 0)
    def _():
        est_ref[0, :] = jnp.minimum(est_ref[0, :], e_r)


def window_estimate(totals, boundary, frac, h1, h2):
    """Fused min-estimate over the d rows: (B,) f32, NOT yet clamped at 0
    (the caller applies the same jnp.maximum(est, 0.0) as the reference).
    ``boundary`` must be a (d, w) slab (zeros + frac=0 for fixed-window
    semantics — t + 0.0*b == t bitwise for int-cast t)."""
    d, w = totals.shape
    B = h1.shape[0]
    est = pl.pallas_call(
        partial(_window_estimate_kernel, w=w),
        grid=(d,),
        in_specs=[
            pl.BlockSpec((1, 1), lambda r: (0, 0)),
            pl.BlockSpec((1, B), lambda r: (0, 0)),
            pl.BlockSpec((1, B), lambda r: (0, 0)),
            pl.BlockSpec((1, w), lambda r: (r, 0)),
            pl.BlockSpec((1, w), lambda r: (r, 0)),
        ],
        out_specs=pl.BlockSpec((1, B), lambda r: (0, 0)),
        out_shape=jax.ShapeDtypeStruct((1, B), jnp.float32),
        interpret=_interpret(),
    )(jnp.asarray(frac, jnp.float32).reshape(1, 1),
      h1.reshape(1, B), h2.reshape(1, B), totals, boundary)
    return est[0]


def _cu_update_kernel(frac_ref, h1_ref, h2_ref, target_ref, totals_ref,
                      boundary_ref, cur_ref, out_totals_ref, out_cur_ref,
                      *, w: int):
    r = pl.program_id(0)
    cols = _cols_for_row(h1_ref[0, :], h2_ref[0, :], r, w)
    t_row = totals_ref[0, :]
    # Per-column segment max of the post-batch targets (f32 max over
    # non-negative values: order-insensitive, so the scatter equals the
    # reference's row_histogram_max bitwise).
    m = jnp.zeros((w,), jnp.float32).at[cols].max(target_ref[0, :])
    read = (t_row.astype(jnp.float32)
            + frac_ref[0, 0] * boundary_ref[0, :].astype(jnp.float32))
    delta = jnp.ceil(jnp.maximum(m - read, 0.0)).astype(jnp.int32)
    out_totals_ref[0, :] = t_row + delta
    out_cur_ref[0, :] = cur_ref[0, :] + delta


def cu_update(totals, cur, boundary, frac, h1, h2, target):
    """Fused conservative update: returns (new_totals, new_cur), the
    state slabs aliased in place. ``target`` is the (B,) post-batch
    per-key target (0 for denied requests) the reference computes."""
    d, w = totals.shape
    B = h1.shape[0]
    return pl.pallas_call(
        partial(_cu_update_kernel, w=w),
        grid=(d,),
        in_specs=[
            pl.BlockSpec((1, 1), lambda r: (0, 0)),
            pl.BlockSpec((1, B), lambda r: (0, 0)),
            pl.BlockSpec((1, B), lambda r: (0, 0)),
            pl.BlockSpec((1, B), lambda r: (0, 0)),
            pl.BlockSpec((1, w), lambda r: (r, 0)),
            pl.BlockSpec((1, w), lambda r: (r, 0)),
            pl.BlockSpec((1, w), lambda r: (r, 0)),
        ],
        out_specs=(pl.BlockSpec((1, w), lambda r: (r, 0)),
                   pl.BlockSpec((1, w), lambda r: (r, 0))),
        out_shape=(jax.ShapeDtypeStruct((d, w), totals.dtype),
                   jax.ShapeDtypeStruct((d, w), cur.dtype)),
        input_output_aliases={4: 0, 6: 1},
        interpret=_interpret(),
    )(jnp.asarray(frac, jnp.float32).reshape(1, 1),
      h1.reshape(1, B), h2.reshape(1, B), target.reshape(1, B),
      totals, boundary, cur)


def _add_update_kernel(h1_ref, h2_ref, add_ref, totals_ref, cur_ref,
                       out_totals_ref, out_cur_ref, *, w: int):
    r = pl.program_id(0)
    cols = _cols_for_row(h1_ref[0, :], h2_ref[0, :], r, w)
    h = jnp.zeros((w,), add_ref.dtype).at[cols].add(add_ref[0, :])
    out_totals_ref[0, :] = totals_ref[0, :] + h
    out_cur_ref[0, :] = cur_ref[0, :] + h


def add_update(totals, cur, h1, h2, add):
    """Fused vanilla (sum) update: integer scatter-add per row, state
    slabs aliased in place. Exact — integer adds commute."""
    d, w = totals.shape
    B = h1.shape[0]
    return pl.pallas_call(
        partial(_add_update_kernel, w=w),
        grid=(d,),
        in_specs=[
            pl.BlockSpec((1, B), lambda r: (0, 0)),
            pl.BlockSpec((1, B), lambda r: (0, 0)),
            pl.BlockSpec((1, B), lambda r: (0, 0)),
            pl.BlockSpec((1, w), lambda r: (r, 0)),
            pl.BlockSpec((1, w), lambda r: (r, 0)),
        ],
        out_specs=(pl.BlockSpec((1, w), lambda r: (r, 0)),
                   pl.BlockSpec((1, w), lambda r: (r, 0))),
        out_shape=(jax.ShapeDtypeStruct((d, w), totals.dtype),
                   jax.ShapeDtypeStruct((d, w), cur.dtype)),
        input_output_aliases={3: 0, 4: 1},
        interpret=_interpret(),
    )(h1.reshape(1, B), h2.reshape(1, B), add.reshape(1, B), totals, cur)


# -------------------------------------------------------- token bucket


def _bucket_estimate_kernel(decay_ref, h1_ref, h2_ref, debt_ref, est_ref,
                            *, w: int):
    r = pl.program_id(0)
    cols = _cols_for_row(h1_ref[0, :], h2_ref[0, :], r, w)
    # Scalar decay applied on the fly — the decayed (d, w) slab is never
    # materialized (the reference materializes it; clamp-then-gather is
    # exact integer math either way).
    decayed = jnp.maximum(jnp.int64(0), debt_ref[0, :] - decay_ref[0, 0])
    e_r = decayed[cols]

    @pl.when(r == 0)
    def _():
        est_ref[0, :] = e_r

    @pl.when(r != 0)
    def _():
        est_ref[0, :] = jnp.minimum(est_ref[0, :], e_r)


def bucket_estimate(debt, decay, h1, h2):
    """Fused min-over-rows debt estimate, (B,) int64 micro-tokens."""
    d, w = debt.shape
    B = h1.shape[0]
    est = pl.pallas_call(
        partial(_bucket_estimate_kernel, w=w),
        grid=(d,),
        in_specs=[
            pl.BlockSpec((1, 1), lambda r: (0, 0)),
            pl.BlockSpec((1, B), lambda r: (0, 0)),
            pl.BlockSpec((1, B), lambda r: (0, 0)),
            pl.BlockSpec((1, w), lambda r: (r, 0)),
        ],
        out_specs=pl.BlockSpec((1, B), lambda r: (0, 0)),
        out_shape=jax.ShapeDtypeStruct((1, B), jnp.int64),
        interpret=_interpret(),
    )(jnp.asarray(decay, jnp.int64).reshape(1, 1),
      h1.reshape(1, B), h2.reshape(1, B), debt)
    return est[0]


def _bucket_update_kernel(decay_ref, h1_ref, h2_ref, consumed_ref,
                          debt_ref, acc_ref, out_debt_ref, out_acc_ref,
                          *, w: int):
    r = pl.program_id(0)
    cols = _cols_for_row(h1_ref[0, :], h2_ref[0, :], r, w)
    decayed = jnp.maximum(jnp.int64(0), debt_ref[0, :] - decay_ref[0, 0])
    h = jnp.zeros((w,), jnp.int64).at[cols].add(consumed_ref[0, :])
    out_debt_ref[0, :] = jnp.minimum(decayed + h, _DEBT_CAP)
    out_acc_ref[0, :] = jnp.minimum(acc_ref[0, :] + h, _DEBT_CAP)


def bucket_update(debt, acc, decay, h1, h2, consumed):
    """Fused decay + consume: returns (new_debt, new_acc), slabs aliased
    in place. ``consumed`` is admit's (B,) int64 micro-token consumption
    (0 for denied requests — denial consumes nothing)."""
    d, w = debt.shape
    B = h1.shape[0]
    return pl.pallas_call(
        partial(_bucket_update_kernel, w=w),
        grid=(d,),
        in_specs=[
            pl.BlockSpec((1, 1), lambda r: (0, 0)),
            pl.BlockSpec((1, B), lambda r: (0, 0)),
            pl.BlockSpec((1, B), lambda r: (0, 0)),
            pl.BlockSpec((1, B), lambda r: (0, 0)),
            pl.BlockSpec((1, w), lambda r: (r, 0)),
            pl.BlockSpec((1, w), lambda r: (r, 0)),
        ],
        out_specs=(pl.BlockSpec((1, w), lambda r: (r, 0)),
                   pl.BlockSpec((1, w), lambda r: (r, 0))),
        out_shape=(jax.ShapeDtypeStruct((d, w), debt.dtype),
                   jax.ShapeDtypeStruct((d, w), acc.dtype)),
        input_output_aliases={4: 0, 5: 1},
        interpret=_interpret(),
    )(jnp.asarray(decay, jnp.int64).reshape(1, 1),
      h1.reshape(1, B), h2.reshape(1, B), consumed.reshape(1, B),
      debt, acc)
