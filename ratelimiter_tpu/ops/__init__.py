"""Device-side ops: the TPU-native replacement for the reference's Lua kernels.

The reference's atomic compute unit is a Lua script executed inside Redis
(``fixedwindow.go:21-27``, ``slidingwindow.go:22-30``, ``tokenbucket.go:23-52``
— SURVEY.md §2.2). Here the atomic unit is a fused, jitted batched step:
static shapes, no data-dependent Python control flow, int64 micro-units for
drift-free token accounting, and sort+segment-scan sequencing so one batch
behaves like the same requests serialized through Redis.
"""
