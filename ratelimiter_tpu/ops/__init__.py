"""Device-side ops: the TPU-native replacement for the reference's Lua kernels.

The reference's atomic compute unit is a Lua script executed inside Redis
(``fixedwindow.go:21-27``, ``slidingwindow.go:22-30``, ``tokenbucket.go:23-52``
— SURVEY.md §2.2). Here the atomic unit is a fused, jitted batched step:
static shapes, no data-dependent Python control flow, int64 micro-units for
drift-free token accounting, and sort+segment-scan sequencing so one batch
behaves like the same requests serialized through Redis.
"""

from __future__ import annotations


def ensure_x64() -> None:
    """The device kernels do exact integer state math in int64 microseconds
    and micro-tokens; without jax_enable_x64 those arrays silently truncate
    to int32 and every timestamp/level computation is wrong.

    Importing this library does NOT flip the flag for the whole process
    (that global would change the dtype semantics of unrelated user JAX
    code); instead every kernel factory calls this and fails loudly so the
    embedding process opts in explicitly.
    """
    import jax

    if not jax.config.jax_enable_x64:
        raise RuntimeError(
            "ratelimiter_tpu device backends require 64-bit JAX types: call "
            "jax.config.update('jax_enable_x64', True) (or set the "
            "JAX_ENABLE_X64=1 env var) before creating a dense/sketch "
            "limiter. The exact (host) backend works without it.")

