"""Sketched token bucket — unbounded-key TOKEN_BUCKET on the CMS backend.

The reference's flagship algorithm (``tokenbucket.go:23-52``) keeps one
{tokens, last_refill} hash per key in Redis; memory grows with key
cardinality (~170 B/user, ``docs/ALGORITHMS.md:635``). This module gives the
same continuous-refill / burst / denial-consumes-nothing semantics at
O(depth x width) memory, independent of key count, via the classic
token-bucket <-> leaky-meter equivalence (GCRA):

    tokens(t) = limit - debt(t),   where debt decays at the refill rate
    (limit/window tokens per second) and clamps at 0; a consume of n adds
    n to debt; allow iff debt + n <= limit.

The meter form sketches cleanly where the token form does not: per-key
*debt* is a non-negative counter, so a count-min sketch over debts keeps
the CMS error direction — a cell holds the SUM of colliding keys' debts,
so the min-over-rows read can only OVERestimate a key's true debt, which
can only cause false *denies*, never over-admission (the same contract as
ops/sketch_kernels.py, SURVEY.md §7.4 hard part #3).

Decay is exact integer math, no float drift (SURVEY.md §7.4 hard part #5):
every cell decays at the SAME rate, so one scalar per-step decay amount
serves the whole (d, w) slab, with a single global remainder carrying
fractional micro-tokens across steps (the per-key analog is
dense_kernels._token_bucket_step's per-slot ``rem``; here the clamp at 0
happens per cell, which is exactly per-key-correct because linear decay
followed by clamp composes: max(0, max(0, x-a)-b) == max(0, x-(a+b))).

Accuracy model (documented tradeoff, measured by evaluation/accuracy.py):
colliding *active* keys share refill — K hot keys in one cell drain it at
K x their admission rate while it refills at 1 x rate, so persistent
colliders are throttled toward one key's worth of combined throughput.
Errors are always toward denying. Width sizing follows the usual CMS rule
(w >> active hot keys); the conservative-update trick does not apply here
(there is no globally-consistent "window read" target — the decayed debt
is a moving quantity), so writes are vanilla sums.

State (see init_state):
    debt int64[d, w]  micro-token debt cells (1 token = 1e6 micro)
    rem  int64[]      global decay remainder, < rate_den
    last int64[]      timestamp of the last step, microseconds
"""

from __future__ import annotations

from functools import partial
from typing import Callable, Dict, Tuple

import jax
import jax.numpy as jnp

from ratelimiter_tpu.core.clock import MICROS
from ratelimiter_tpu.core.config import Config
from ratelimiter_tpu.ops import ensure_x64, policy_kernels
from ratelimiter_tpu.ops.dense_kernels import _check_gates
from ratelimiter_tpu.ops.segment import admit
from ratelimiter_tpu.ops.sketch_kernels import _columns, _pack_bits
from ratelimiter_tpu.ops.sortmerge import row_gather, row_histogram

State = Dict[str, jnp.ndarray]

#: Cells clamp here on write so debt arithmetic can never overflow int64
#: even under adversarial collision pileups (2^61 micro-tokens = 2.3e12
#: tokens — clamping errs toward denying, preserving the error direction).
_DEBT_CAP = 1 << 61


def init_state(cfg: Config) -> State:
    """All-zero debt == every bucket full (the reference's absent-key
    default, ``tokenbucket.go:31-33``); last=0 makes the first step see a
    huge elapsed whose decay is a no-op on zero debt.

    ``acc`` accumulates LOCAL debt increments since the last DCN export
    (parallel/dcn.py): the step adds its write histogram there too, the
    export snapshots-and-zeroes it, and foreign merges add to ``debt``
    only — so exports can never re-ship foreign traffic (the bucket
    analog of the windowed tier's completed-slab watermark)."""
    d, w = cfg.sketch.depth, cfg.sketch.width
    state = {
        "debt": jnp.zeros((d, w), jnp.int64),
        "acc": jnp.zeros((d, w), jnp.int64),
        "rem": jnp.asarray(0, jnp.int64),
        "last": jnp.asarray(0, jnp.int64),
    }
    T = cfg.hierarchy.tenants
    if T:
        # Hierarchical cascade (ADR-020): tenant/global scopes on the
        # debt-sketch backend are FIXED-WINDOW request counters (index T
        # is the global scope) — a deliberate divergence from the key
        # scope's GCRA meter, keeping tenant math exact int64 at any
        # window length (per-tenant decay rates over a dynamic limit
        # array cannot stay overflow-safe at 365-day windows). tn_period
        # is the window index of the counts; a step in a later window
        # zeroes them lazily.
        state.update({
            "tn_counts": jnp.zeros((T + 1,), jnp.int64),
            "tn_period": jnp.asarray(-(1 << 40), jnp.int64),
        })
    return state


def _decay(state: State, now_us, *, rate_num: int, rate_den: int):
    """Scalar micro-token decay since state['last'], exact and
    overflow-safe. rate = rate_num/rate_den micro-tokens per us, in lowest
    terms (dense_kernels._check_gates guarantees rate_den * rate_num <
    2^62). The quotient arm is clamped so idle-for-years elapsed values
    cannot overflow: past _DEBT_CAP the extra decay is irrelevant because
    every cell has long since clamped at 0."""
    elapsed = jnp.maximum(0, now_us - state["last"])
    e_q = elapsed // rate_den
    acc = (elapsed - e_q * rate_den) * rate_num + state["rem"]
    e_q = jnp.minimum(e_q, _DEBT_CAP // rate_num)
    decay = e_q * rate_num + acc // rate_den
    return decay, acc % rate_den


def _bucket_step(state: State, h1, h2, n, now_us, policy=None, hier=None, *,
                 limit: int, rate_num: int, rate_den: int,
                 d: int, w: int, iters: int, tenants: int = 0,
                 window_us: int = 0,
                 axis_name: str | None = None, use_pallas: bool = False):
    """One batched decision step. Returns (state, (allowed, remaining,
    retry_us)) — the limiter-side retry/reset plumbing is shared with the
    other sketch paths.

    Policy overrides here change a key's burst CAPACITY (cap = limit_k
    micro-tokens); the decay rate stays the global limit/window — debt
    cells are shared by colliding keys, so a per-key decay rate does not
    exist in this representation. Documented divergence from the
    token-form backends (whose overrides scale the refill rate too):
    overridden keys burst to their own limit immediately and refill at
    the default rate. Errors stay toward denying."""
    decay, rem = _decay(state, now_us, rate_num=rate_num, rate_den=rate_den)
    # Fused-kernel path (ADR-011): decay applies on the fly inside the
    # kernels (the decayed slab never materializes) and columns derive
    # in-kernel; collective merges stay on the reference path.
    use_pallas = use_pallas and axis_name is None
    if use_pallas:
        from ratelimiter_tpu.ops import pallas_sketch

        debt = None
        cols = None
        est = pallas_sketch.bucket_estimate(state["debt"], decay, h1, h2)
    else:
        debt = jnp.maximum(jnp.int64(0), state["debt"] - decay)
        cols = _columns(h1, h2, d, w)                   # (B, d)
        est = None
        for r in range(d):
            (e_r,) = row_gather((debt[r],), cols[:, r])
            est = e_r if est is None else jnp.minimum(est, e_r)

    if policy is not None:
        q = policy_kernels.pack_halves(h1, h2)
        pidx, pfound = policy_kernels.lookup_i64(policy["key"], q)
        cap = jnp.where(pfound, policy["limit"][pidx],
                        jnp.int64(limit)) * MICROS
    else:
        cap = limit * MICROS
    avail = jnp.maximum(jnp.int64(0), cap - est)        # micro-tokens
    n_units = n.astype(jnp.int64) * MICROS
    sid = jax.lax.bitcast_convert_type(h1, jnp.int32)
    allowed, seen, consumed = admit(sid, n_units, avail, iters)

    tn_hist = None
    if tenants and hier is not None:
        # Cascade stages 2+3 (ADR-020): fixed-window tenant/global
        # request counters, rolled lazily when the step's timestamp
        # enters a new window. All-or-nothing — the final mask gates the
        # key-scope debt write below, and every scope's consumption view
        # is recomputed under it.
        from ratelimiter_tpu.ops import hier_kernels
        from ratelimiter_tpu.ops.segment import segment_consumption

        tid = hier_kernels.derive_tids(hier, h1, h2, tenants)
        hp = now_us // window_us
        rolled = hp > state["tn_period"]
        counts = jnp.where(rolled, jnp.int64(0), state["tn_counts"])
        avail_sc = hier_kernels.scope_avail(hier["limit"], counts)
        allowed_casc, tn_hist = hier_kernels.cascade_admit(
            allowed, tid, n, avail_sc, hier["weight"], tenants, iters)
        # Final-mask consumption view, cond'd on the cascade having
        # flipped any verdict (same rule as the windowed kernel): no
        # contention → stage-1 seen already reflects the final mask.
        seen = jax.lax.cond(
            jnp.any(allowed_casc != allowed),
            lambda: avail - segment_consumption(
                sid, jnp.where(allowed_casc, n_units, jnp.int64(0))),
            lambda: seen)
        allowed = allowed_casc
        consumed = jnp.where(allowed, n_units, jnp.int64(0))
        if axis_name is not None:
            tn_hist = jax.lax.psum(tn_hist, axis_name)
        tn_out = {"tn_counts": counts + tn_hist,
                  "tn_period": jnp.maximum(state["tn_period"], hp)}
        # Retry for a request the key scope would admit but the cascade
        # denied: the tenant/global window boundary (when those counters
        # reset), not the refill-deficit formula (whose deficit is <= 0
        # for key-fitting requests).
        cascade_retry = (hp + 1) * window_us - now_us
    elif "tn_counts" in state:
        tn_out = {k: state[k] for k in ("tn_counts", "tn_period")}
        cascade_retry = None
    else:
        tn_out = {}
        cascade_retry = None

    if use_pallas:
        from ratelimiter_tpu.ops import pallas_sketch

        debt, acc = pallas_sketch.bucket_update(
            state["debt"], state["acc"], decay, h1, h2, consumed)
    else:
        hists = jnp.stack([row_histogram(cols[:, r], consumed, w)
                           for r in range(d)])
        if axis_name is not None:
            # Multi-chip delta merge: replicated debt, psum of increments
            # over ICI (same invariant as sketch_kernels' delta mode). The
            # psum'd histogram IS the pod's local traffic, so `acc` stays
            # export-correct on meshes too.
            hists = jax.lax.psum(hists, axis_name)
        debt = jnp.minimum(debt + hists, _DEBT_CAP)
        acc = jnp.minimum(state["acc"] + hists, _DEBT_CAP)

    new_state = {"debt": debt,
                 "acc": acc,
                 "rem": rem,
                 "last": jnp.maximum(state["last"], now_us),
                 **tn_out}
    remaining = (seen - jnp.where(allowed, n_units, 0)) // MICROS
    # Reference retry semantics (``tokenbucket.go:122-130``): time to refill
    # the deficit, ceil'd to whole microseconds.
    deficit = jnp.maximum(0, n_units - seen)
    retry_us = jnp.where(allowed, 0, -((-deficit * rate_den) // rate_num))
    if cascade_retry is not None:
        # Cascade-denied rows (deficit 0 at the key scope) retry at the
        # tenant/global window boundary.
        retry_us = jnp.where(~allowed & (deficit <= 0), cascade_retry,
                             retry_us)
    return new_state, (allowed, remaining, retry_us)


def _bucket_reset(state: State, h1, h2, now_us, *,
                  rate_num: int, rate_den: int, d: int, w: int):
    """Per-key reset: zero the key's debt by subtracting its min-estimate
    from all its cells, clamped at 0 (no self-healing sweep exists here, so
    unlike sketch_kernels._sketch_reset transient negatives are not allowed
    to persist). Colliding keys gain allowance — errs toward allowing."""
    decay, rem = _decay(state, now_us, rate_num=rate_num, rate_den=rate_den)
    debt = jnp.maximum(jnp.int64(0), state["debt"] - decay)
    cols = _columns(h1, h2, d, w)
    est = None
    for r in range(d):
        (e_r,) = row_gather((debt[r],), cols[:, r])
        est = e_r if est is None else jnp.minimum(est, e_r)
    hists = jnp.stack([row_histogram(cols[:, r], est, w) for r in range(d)])
    debt = jnp.maximum(jnp.int64(0), debt - hists)
    # Reset is deliberately NOT subtracted from `acc`: the consumed debt
    # it forgives was already exported (or will be) as real local traffic,
    # and a negative export could under-count remotely (over-admission).
    # Cross-pod, a reset key simply recovers locally first.
    out = {"debt": debt, "acc": state["acc"], "rem": rem,
           "last": jnp.maximum(state["last"], now_us)}
    if "tn_counts" in state:
        # Key-scope forgiveness only — tenant/global counters stand
        # (same rule as the windowed sketch's _sketch_reset, ADR-020).
        out.update({k: state[k] for k in ("tn_counts", "tn_period")})
    return out


def _bucket_scan(state: State, h1s, h2s, ns, now0_us, dt_us, *, step_kw):
    """T sequential bucket steps on device (lax.scan), one dispatch —
    sketch_kernels._sketch_scan's shape for the serving/bench loops. No
    sub-window rollover precondition: decay is part of the step itself."""
    def body(st, xs):
        h1, h2, n, i = xs
        st, (allowed, _rem, _retry) = _bucket_step(
            st, h1, h2, n, now0_us + i * dt_us, **step_kw)
        return st, (_pack_bits(allowed), jnp.sum(~allowed).astype(jnp.int32))

    T = h1s.shape[0]
    idx = jnp.arange(T, dtype=jnp.int64)
    state, (packed, denies) = jax.lax.scan(body, state, (h1s, h2s, ns, idx))
    return state, packed, denies


@jax.jit
def finish_bucket(allowed, remaining, retry_us, now_us, window_us):
    """Device-side result assembly for the debt sketch: retry-after =
    deficit / refill rate already computed exactly on device by the step
    (``tokenbucket.go:122-130``); reset_at is the reference's now + window
    approximation (``tokenbucket.go:159-165``). Same one-bulk-fetch
    contract as sketch_kernels.finish_window (ADR-010)."""
    reset = (now_us + window_us).astype(jnp.float64) / 1e6
    return (allowed, remaining.astype(jnp.int64),
            retry_us.astype(jnp.float64) / 1e6,
            jnp.broadcast_to(reset, allowed.shape))


_STEP_CACHE: Dict[tuple, Tuple[Callable, Callable]] = {}
_SCAN_CACHE: Dict[tuple, Callable] = {}


def _params(cfg: Config) -> tuple:
    W, num, den = _check_gates(cfg)
    return (cfg.limit, num, den, cfg.sketch.depth, cfg.sketch.width,
            cfg.max_batch_admission_iters)


def _hier_params(cfg: Config) -> tuple:
    """(tenants, window_us) for the cascade's fixed-window tenant
    counters; (0, window_us) when the hierarchy is disabled."""
    W, _, _ = _check_gates(cfg)
    return cfg.hierarchy.tenants, W


def build_steps(cfg: Config) -> Tuple[Callable, Callable]:
    """Returns (step, reset) jitted callables, memoized per static config.
    ``step`` accepts an optional trailing ``policy`` operand."""
    from ratelimiter_tpu.ops.sketch_kernels import _resolve_pallas

    ensure_x64()
    limit, num, den, d, w, iters = _params(cfg)
    tenants, wus = _hier_params(cfg)
    use_pallas = _resolve_pallas(cfg, bucket=True)
    key = (limit, num, den, d, w, iters, tenants, wus, use_pallas)
    cached = _STEP_CACHE.get(key)
    if cached is not None:
        return cached
    step = jax.jit(
        partial(_bucket_step, limit=limit, rate_num=num, rate_den=den,
                d=d, w=w, iters=iters, tenants=tenants, window_us=wus,
                use_pallas=use_pallas),
        donate_argnums=(0,))
    reset = jax.jit(
        partial(_bucket_reset, rate_num=num, rate_den=den, d=d, w=w),
        donate_argnums=(0,))
    _STEP_CACHE[key] = (step, reset)
    return step, reset


_HASHED_CACHE: Dict[tuple, Callable] = {}


def _bucket_step_h64(state: State, h64, n, now_us, policy=None, hier=None, *,
                     seed: int, premix: bool, **step_kw):
    from ratelimiter_tpu.ops.hashing import split_hash_dev, splitmix64_dev

    h = h64
    if premix:
        h = splitmix64_dev(h)
    h1, h2 = split_hash_dev(h, seed)
    return _bucket_step(state, h1, h2, n, now_us, policy, hier, **step_kw)


def build_hashed_step(cfg: Config, *, premix: bool = False) -> Callable:
    """Jitted ``step(state, h64, n, now_us, policy)`` with the (h1, h2)
    split (and, with premix, the splitmix64 finalizer) ON DEVICE — the
    bucket twin of sketch_kernels.build_hashed_step (ADR-011)."""
    from ratelimiter_tpu.ops.sketch_kernels import _resolve_pallas

    ensure_x64()
    limit, num, den, d, w, iters = _params(cfg)
    tenants, wus = _hier_params(cfg)
    use_pallas = _resolve_pallas(cfg, bucket=True)
    seed = cfg.sketch.seed
    key = (limit, num, den, d, w, iters, tenants, wus, use_pallas, seed,
           premix)
    cached = _HASHED_CACHE.get(key)
    if cached is not None:
        return cached
    step = jax.jit(
        partial(_bucket_step_h64, seed=seed, premix=premix,
                limit=limit, rate_num=num, rate_den=den,
                d=d, w=w, iters=iters, tenants=tenants, window_us=wus,
                use_pallas=use_pallas),
        donate_argnums=(0,))
    _HASHED_CACHE[key] = step
    return step


def build_scan(cfg: Config) -> Callable:
    """Jitted multi-step runner, one dispatch for T batches (bench shape)."""
    from ratelimiter_tpu.ops.sketch_kernels import _resolve_pallas

    ensure_x64()
    limit, num, den, d, w, iters = _params(cfg)
    use_pallas = _resolve_pallas(cfg, bucket=True)
    key = (limit, num, den, d, w, iters, use_pallas)
    cached = _SCAN_CACHE.get(key)
    if cached is not None:
        return cached
    step_kw = dict(limit=limit, rate_num=num, rate_den=den, d=d, w=w,
                   iters=iters, use_pallas=use_pallas)
    scan = jax.jit(partial(_bucket_scan, step_kw=step_kw), donate_argnums=(0,))
    _SCAN_CACHE[key] = scan
    return scan
