"""Host-authoritative per-key override table.

One PolicyTable per limiter instance. The table owns the entry store and
the *host* form of the device arrays; the backend owns placement (single
device, replicated mesh) and decides which value columns its kernels
consume:

* ``limit``      — the entry's absolute limit (tiers pin absolute
  numbers; a later ``update_limit`` moves only the default);
* ``window_us``  — the entry's effective window, microseconds
  (``base_window * window_scale``);
* ``rate_num`` / ``rate_den`` — the entry's token-bucket refill rate as
  a reduced exact fraction (micro-tokens per microsecond), precomputed
  host-side so the device path stays gcd-free.

Thread model: the OWNING LIMITER serializes mutations and dispatches
under its own lock (set/delete happen rarely; dispatches read a
consistent snapshot). The table itself is not internally locked.

Validation happens at set time, not decision time: bounds (positive
limit, legal effective window) plus a backend-supplied ``validator``
re-running that backend's overflow/representability gates per entry —
an override a backend cannot decide exactly is refused loudly, never
silently misdecided (the same posture as ops/dense_kernels._check_gates).

Durability: overrides ride checkpoints as the ``policy_*`` columns
(snapshot_arrays/restore_arrays below) AND are the write-ahead log's
main cargo — with persistence enabled every set/delete is WAL-logged
before acknowledgment and recovers EXACTLY across kill -9, even when
the mutation postdates the newest snapshot (ratelimiter_tpu/persistence/,
docs/ADR/009). Replay re-enters through ``set``'s full validation, so a
log can never smuggle in an entry this backend would refuse.
"""

from __future__ import annotations

import math
from dataclasses import dataclass
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np

from ratelimiter_tpu.core.clock import MICROS, to_micros
from ratelimiter_tpu.core.config import (
    MAX_WINDOW_SECONDS,
    MIN_WINDOW_SECONDS,
    Config,
)
from ratelimiter_tpu.core.errors import InvalidConfigError
from ratelimiter_tpu.ops import policy_kernels as pk


@dataclass(frozen=True)
class Override:
    """One key's tier: an absolute limit and a window multiplier."""

    limit: int
    window_scale: float = 1.0


class PolicyTable:
    """Bounded per-key override store + sorted host arrays.

    Args:
        config: the owning limiter's config (capacity, default limit /
            window, prefix come from here).
        key_fn: maps a key string to its int64 search key — the SAME
            domain the backend's decision step queries in
            (ops/policy_kernels.py module docstring).
        validator: optional hook ``(limit, window_us) -> None`` raising
            InvalidConfigError for entries the backend cannot represent.
        window_scaling: whether this backend supports per-key windows;
            False rejects ``window_scale != 1`` at set time (the sketch
            backends share one ring geometry across all keys).
    """

    def __init__(self, config: Config, *,
                 key_fn: Callable[[str], int],
                 validator: Optional[Callable[[int, int], None]] = None,
                 window_scaling: bool = True):
        self.capacity = config.policy.capacity
        self._key_fn = key_fn
        self._validator = validator
        self._window_scaling = window_scaling
        self._base_limit = config.limit
        self._base_window_us = to_micros(config.window)
        self._base_window_s = float(config.window)
        self._entries: Dict[str, Override] = {}
        self._skey: Dict[str, int] = {}      # key -> int64 search key
        self._by_skey: Dict[int, str] = {}   # reverse map (O(1) clash check)
        #: bumped on every mutation; backends invalidate device caches on it
        self.version = 0
        self._sorted_keys: np.ndarray = np.empty(0, np.int64)
        self._sorted_entries: List[Tuple[str, Override]] = []
        self._host_arrays: Optional[Dict[str, np.ndarray]] = None

    # ------------------------------------------------------------- derive

    def _effective(self, ov: Override) -> Tuple[int, int, int, int]:
        """(limit, window_us, rate_num, rate_den) for one entry."""
        w_us = max(1, int(round(self._base_window_us * ov.window_scale)))
        g = math.gcd(ov.limit * MICROS, w_us)
        return ov.limit, w_us, ov.limit * MICROS // g, w_us // g

    # ------------------------------------------------------------ mutate

    def set(self, key: str, limit: Optional[int] = None,
            window_scale: float = 1.0) -> Override:
        ov = self._insert(key, limit, window_scale)
        self._invalidate()
        return ov

    def _insert(self, key: str, limit: Optional[int],
                window_scale: float) -> Override:
        """Validate + store one entry WITHOUT rebuilding the sorted view
        (set() rebuilds per call; load() rebuilds once for the batch)."""
        if limit is None:
            limit = self._base_limit
        if (not isinstance(limit, int) or isinstance(limit, bool)
                or limit <= 0):
            raise InvalidConfigError(
                f"override limit must be a positive integer, got {limit!r}")
        ws = float(window_scale)
        if not (ws > 0.0) or ws != ws:
            raise InvalidConfigError(
                f"override window_scale must be > 0, got {window_scale!r}")
        if ws != 1.0 and not self._window_scaling:
            raise InvalidConfigError(
                "this backend shares one window geometry across all keys "
                "and cannot scale windows per key (window_scale must be 1); "
                "use the exact or dense backend for per-key windows")
        eff_w_s = self._base_window_s * ws
        if not (MIN_WINDOW_SECONDS <= eff_w_s <= MAX_WINDOW_SECONDS):
            raise InvalidConfigError(
                f"override effective window {eff_w_s:g}s outside "
                f"[{MIN_WINDOW_SECONDS:g}, {MAX_WINDOW_SECONDS:g}]s")
        ov = Override(limit=limit, window_scale=ws)
        if self._validator is not None:
            self._validator(*self._effective(ov)[:2])
        if key not in self._entries and len(self._entries) >= self.capacity:
            raise InvalidConfigError(
                f"policy table full ({self.capacity} overrides); raise "
                "PolicySpec.capacity or delete unused overrides")
        skey = int(self._key_fn(key))
        clash = self._by_skey.get(skey)
        if (clash is not None and clash != key) or skey == pk.PAD_KEY:
            raise InvalidConfigError(
                f"override key {key!r} collides in the hash domain "
                f"(with {clash!r}); rename one of the keys")
        self._entries[key] = ov
        self._skey[key] = skey
        self._by_skey[skey] = key
        return ov

    def delete(self, key: str) -> bool:
        if key not in self._entries:
            return False
        del self._entries[key]
        del self._by_skey[self._skey.pop(key)]
        self._invalidate()
        return True

    def validate_rebase(self, new_limit: int, new_window: float) -> None:
        """Re-run every entry's backend gates against a PROSPECTIVE new
        base (limit, window) — callers check this BEFORE migrating state,
        so a window change that would push an existing override past an
        overflow gate is refused up front, never silently misdecided."""
        if self._validator is None:
            return
        w_us = to_micros(new_window)
        for key, ov in self._entries.items():
            eff_w = max(1, int(round(w_us * ov.window_scale)))
            try:
                self._validator(ov.limit, eff_w)
            except InvalidConfigError as exc:
                raise InvalidConfigError(
                    f"override for {key!r} is not representable under the "
                    f"new window {new_window:g}s: {exc}") from exc

    def rebase(self, new_limit: int, new_window: float) -> None:
        """Re-derive defaults and effective windows after a dynamic
        limit/window update. Entries pin ABSOLUTE limits, so only the
        default columns and the window-derived values move. Callers run
        ``validate_rebase`` first (before any state migration)."""
        self._base_limit = int(new_limit)
        self._base_window_s = float(new_window)
        self._base_window_us = to_micros(new_window)
        self._invalidate()

    def load(self, keys, limits, scales) -> None:
        """Replace all entries (checkpoint restore). Re-runs full set-time
        validation so a snapshot can never smuggle in an entry this
        backend/config combination would refuse; the sorted view rebuilds
        ONCE for the whole batch (restore stays O(n log n))."""
        self._entries.clear()
        self._skey.clear()
        self._by_skey.clear()
        for k, lim, sc in zip(keys, limits, scales):
            self._insert(str(k), int(lim), float(sc))
        self._invalidate()

    def _invalidate(self) -> None:
        self.version += 1
        self._host_arrays = None
        items = sorted(self._entries.items(), key=lambda kv: self._skey[kv[0]])
        self._sorted_entries = items
        self._sorted_keys = np.array([self._skey[k] for k, _ in items],
                                     dtype=np.int64)

    # -------------------------------------------------------------- read

    def get(self, key: str) -> Optional[Override]:
        return self._entries.get(key)

    def effective(self, key: str) -> Optional[Tuple[int, int, int, int]]:
        """(limit, window_us, rate_num, rate_den) or None for default keys
        — the exact backend's host-side consult."""
        ov = self._entries.get(key)
        return None if ov is None else self._effective(ov)

    def items(self) -> List[Tuple[str, Override]]:
        return sorted(self._entries.items())

    def __len__(self) -> int:
        return len(self._entries)

    @property
    def has_window_scaled(self) -> bool:
        return any(ov.window_scale != 1.0 for ov in self._entries.values())

    # -------------------------------------------------------- host arrays

    def host_arrays(self) -> Dict[str, np.ndarray]:
        """Padded, sorted int64 columns {key, limit, window_us, rate_num,
        rate_den} of length ``capacity`` — the host form the backend
        places on device. Rebuilt lazily per version."""
        if self._host_arrays is None:
            g = math.gcd(self._base_limit * MICROS, self._base_window_us)
            arrs = pk.empty_arrays(self.capacity, {
                "limit": self._base_limit,
                "window_us": self._base_window_us,
                "rate_num": self._base_limit * MICROS // g,
                "rate_den": self._base_window_us // g,
            })
            for i, (_key, ov) in enumerate(self._sorted_entries):
                lim, w_us, num, den = self._effective(ov)
                arrs["key"][i] = self._sorted_keys[i]
                arrs["limit"][i] = lim
                arrs["window_us"][i] = w_us
                arrs["rate_num"][i] = num
                arrs["rate_den"][i] = den
            self._host_arrays = arrs
        return self._host_arrays

    def limits_for(self, queries_i64: np.ndarray) -> Optional[np.ndarray]:
        """Per-query effective limits (int64[B]) for host-side result
        assembly (Result.limit / X-RateLimit-Limit), or None when no
        override matches (callers keep the scalar default)."""
        if not self._entries:
            return None
        idx, found = pk.lookup_host(self._sorted_keys,
                                    np.asarray(queries_i64, np.int64))
        if not found.any():
            return None
        lims = np.array([e[1].limit for e in self._sorted_entries],
                        dtype=np.int64)
        return np.where(found, lims[idx], np.int64(self._base_limit))

    # --------------------------------------------------------- checkpoint

    def snapshot_arrays(self) -> Dict[str, np.ndarray]:
        """Checkpoint columns (prefix ``policy_``) appended to a backend's
        state arrays; restore feeds them back through ``load``."""
        items = self.items()
        return {
            "policy_keys": np.array([k for k, _ in items], dtype=str),
            "policy_limits": np.array([ov.limit for _, ov in items],
                                      dtype=np.int64),
            "policy_scales": np.array([ov.window_scale for _, ov in items],
                                      dtype=np.float64),
        }

    def restore_arrays(self, arrays: Dict[str, np.ndarray]) -> None:
        """Consume (pop) the ``policy_*`` columns from a checkpoint's array
        dict; absent columns (older snapshots) restore an empty table."""
        keys = arrays.pop("policy_keys", None)
        limits = arrays.pop("policy_limits", None)
        scales = arrays.pop("policy_scales", None)
        if keys is None:
            self._entries.clear()
            self._skey.clear()
            self._by_skey.clear()
            self._invalidate()
            return
        self.load([str(k) for k in keys],
                  [int(x) for x in np.asarray(limits, np.int64)],
                  [float(x) for x in np.asarray(scales, np.float64)])
