"""Policy engine: tiered per-key limit/window overrides.

The reference documents tiered quotas (free/pro/enterprise keys with
different limits) as a first-class usage pattern (its
``docs/EXAMPLES.md`` tiered-quota section) but implements them as "run
one limiter per tier and route keys yourself". Here tiers are a
first-class *policy table*: a bounded set of per-key overrides resolved
INSIDE the same jitted device step as the admission decision
(ops/policy_kernels.py), so a batch mixing default and overridden keys
still costs exactly one dispatch.

Pieces:

* PolicyTable (policy/table.py) — host-authoritative entry store +
  padded sorted host arrays the backends ship to the device;
* ops/policy_kernels.py — the vectorized binary search the decision
  kernels run per batch;
* RateLimiter.set_override / get_override / delete_override /
  list_overrides (algorithms/base.py) — the management surface, exposed
  over every serving front door (binary protocol, HTTP ``/v1/policy``,
  gRPC Set/Get/DeleteOverride).

Overrides ride checkpoints (each backend snapshots its table and the
config fingerprint covers the table *geometry*), and occupancy is
exported as the ``rate_limiter_policy_overrides`` gauge.
"""

from ratelimiter_tpu.policy.table import Override, PolicyTable

__all__ = ["Override", "PolicyTable"]
