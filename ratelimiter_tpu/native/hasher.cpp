// Bulk 64-bit string hashing — the host-ingest hot path.
//
// The reference ships raw string keys to Redis and lets the store hash them
// (SURVEY.md §2.4.8); here keys are reduced to u64 on the host at ingest
// (SURVEY.md §7.4 hard part #4) and this translation unit is the native
// fast path for doing that in bulk. Two entry points:
//
// * hash_keylist (CPython module function): iterates a Python list of str
//   directly — PyUnicode_AsUTF8AndSize is zero-copy for ASCII and cached
//   per object — so there is NO Python-level packing step at all. This is
//   what ops/hashing.hash_strings_u64 uses.
// * rl_bulk_hash_u64 (plain C ABI, ctypes): hashes a pre-packed
//   buffer+offsets+lengths batch; kept for the NumPy-twin cross-checks and
//   for callers that already hold packed bytes.
//
// The algorithm is a word-at-a-time multiply-rotate construction in the
// xxHash/Murmur family (8-byte little-endian lanes, one round per lane,
// splitmix64 finalizer). It is defined by THIS file plus its bit-identical
// NumPy twin (ratelimiter_tpu/native/fallback.py) and a scalar Python
// reference (tests/test_hashing.py); the three are cross-checked in tests.
// Little-endian hosts only (x86-64 / aarch64 — every TPU host qualifies).
//
// Build: make native  (g++ -O3 -shared -fPIC -I$PYTHON_INCLUDE hasher.cpp)
//        — or automatically on first import (native/__init__.py).

#include <Python.h>

#include <cstdint>
#include <cstring>

namespace {

constexpr uint64_t P1 = 0x9E3779B185EBCA87ULL;  // golden-ratio primes
constexpr uint64_t P2 = 0xC2B2AE3D27D4EB4FULL;
constexpr uint64_t P3 = 0x165667B19E3779F9ULL;

inline uint64_t rotl64(uint64_t x, int r) {
  return (x << r) | (x >> (64 - r));
}

// splitmix64 finalizer — same mix as ops/hashing.splitmix64, so integer-id
// and string-key hashes share avalanche quality.
inline uint64_t fmix64(uint64_t x) {
  x ^= x >> 30; x *= 0xBF58476D1CE4E5B9ULL;
  x ^= x >> 27; x *= 0x94D049BB133111EBULL;
  x ^= x >> 31;
  return x;
}

inline uint64_t round64(uint64_t h, uint64_t lane) {
  return rotl64(h ^ (lane * P1), 27) * P2 + P3;
}

inline uint64_t hash_one(const uint8_t* p, int64_t len, uint64_t seed) {
  uint64_t h = seed ^ (static_cast<uint64_t>(len) * P1);
  const int64_t nw = len >> 3;
  for (int64_t w = 0; w < nw; ++w) {
    uint64_t lane;
    std::memcpy(&lane, p + 8 * w, 8);
    h = round64(h, lane);
  }
  const int64_t rem = len & 7;
  if (rem) {
    uint64_t lane = 0;
    std::memcpy(&lane, p + 8 * nw, static_cast<size_t>(rem));
    h = round64(h, lane);
  }
  return fmix64(h);
}

}  // namespace

extern "C" {

// Hash n byte strings packed back-to-back in buf. offsets[i]/lengths[i]
// locate key i; out receives the 64-bit hashes. Single pass, no allocation.
void rl_bulk_hash_u64(const uint8_t* buf, const int64_t* offsets,
                      const int64_t* lengths, uint64_t seed,
                      uint64_t* out, int64_t n) {
  for (int64_t i = 0; i < n; ++i) {
    out[i] = hash_one(buf + offsets[i], lengths[i], seed);
  }
}

// ABI version so the Python loader can reject a stale .so after the
// algorithm changes.
int64_t rl_hasher_abi_version() { return 2; }

}  // extern "C"

// ------------------------------------------------------------------ module

// hash_keylist(keys: list[str], seed: int, out_addr: int) -> None
// Writes hashes into the uint64 buffer at out_addr (len(keys) elements) —
// the caller (native/__init__.py) owns a numpy array and passes
// arr.ctypes.data, which keeps numpy headers out of the build.
static PyObject* hash_keylist(PyObject*, PyObject* args) {
  PyObject* list;
  unsigned long long seed;
  unsigned long long out_addr;
  if (!PyArg_ParseTuple(args, "O!KK", &PyList_Type, &list, &seed, &out_addr)) {
    return nullptr;
  }
  uint64_t* out = reinterpret_cast<uint64_t*>(out_addr);
  const Py_ssize_t n = PyList_GET_SIZE(list);
  for (Py_ssize_t i = 0; i < n; ++i) {
    PyObject* item = PyList_GET_ITEM(list, i);  // borrowed
    Py_ssize_t len;
    const char* data = PyUnicode_AsUTF8AndSize(item, &len);
    if (data == nullptr) {
      return nullptr;  // not a str (or encode failure) — TypeError raised
    }
    out[i] = hash_one(reinterpret_cast<const uint8_t*>(data),
                      static_cast<int64_t>(len),
                      static_cast<uint64_t>(seed));
  }
  Py_RETURN_NONE;
}

static PyMethodDef kMethods[] = {
    {"hash_keylist", hash_keylist, METH_VARARGS,
     "Hash a list of str into the uint64 buffer at out_addr."},
    {nullptr, nullptr, 0, nullptr},
};

static struct PyModuleDef kModule = {
    PyModuleDef_HEAD_INIT, "_hasher",
    "Native bulk string hasher (see hasher.cpp).", -1, kMethods,
    nullptr, nullptr, nullptr, nullptr,
};

PyMODINIT_FUNC PyInit__hasher(void) { return PyModule_Create(&kModule); }
