// Native front door for the rate-limit service.
//
// The Python asyncio server tops out around 60K decisions/s — the event
// loop, per-frame Python parsing, and response encoding dominate long
// before the device does. This extension moves the ENTIRE serving hot
// path into C++ threads; Python is entered exactly once per batched
// dispatch (the decide callback), which is the same cadence at which the
// device is entered. Protocol and semantics are identical to
// ratelimiter_tpu/serving/protocol.py — the Python clients and the
// serving test suite drive both servers interchangeably.
//
// Threading model:
//   io thread            epoll on listener + conns + eventfd; frame
//                        assembly; C++-side validation (empty key, n==0,
//                        UTF-8, oversized frames) answers ERROR inline;
//                        ALLOW work is hash-routed to a dispatch shard;
//                        HEALTH answered inline from atomics; writes
//                        flushed from per-conn output queues.
//   dispatcher thread(s) one per shard: waits up to max_delay_us for
//                        work, drains up to max_batch keys, builds the
//                        contiguous (blob, offsets, lengths, ns) buffers
//                        WITH the key prefix prepended (so Python hashes
//                        ready-made bytes). Pipelined mode (launch +
//                        resolve callbacks, ADR-010): calls the
//                        non-blocking LAUNCH callback and pushes the
//                        returned ticket onto a bounded in-flight queue
//                        (blocking when full = backpressure), so up to
//                        `inflight` device dispatches overlap. Legacy
//                        mode calls the blocking decide callback.
//   completer thread(s)  one per shard (pipelined mode): drains EVERY
//                        in-flight ticket per wake (completion batching,
//                        ADR-013), calls the Python RESOLVE callback on
//                        each OLDEST-FIRST (blocks on the device with
//                        the GIL released), and hands results to the
//                        responder.
//   responder thread     encodes RESULT / RESULT_BATCH frames and queues
//                        them on connections — batch k's encode+send
//                        overlaps batch k+1's Python decide. Split
//                        batches (keys spanning shards) reassemble via
//                        BatchJoin; the last shard sends the frame.
//                        (SLO mode keeps the inline single-shard decide
//                        path — an SLO needs one well-defined deadline
//                        per dispatch, not a window of them.)
//
// Dispatch shards (num_shards > 1) decide on separate Python-side
// limiter shards concurrently. NOTE: within ONE Python process the GIL
// and the XLA-CPU thread pool serialize most of the decide, so shards
// only pay off when each shard's limiter dispatches to its own device
// (multi-chip hosts) or the decide path is GIL-free; measured on the
// CPU harness, shards=1 is fastest. Keys are routed by FNV-1a, so
// per-key semantics are exact regardless.
//
// Slice-parallel serving (--backend mesh, ADR-012) mounts one
// DEVICE-PINNED limiter slice per shard, making this shard router the
// shard->device router: each shard's dispatcher+completer pair drives
// its own chip's pipelined launch/resolve chain and the decide path is
// collective-free. The Python callbacks release the GIL while their
// device drains (jax blocks_until_ready), so N shards genuinely overlap
// N devices. stats()["shard_decisions"] exposes the per-shard (and so
// per-device) decision counts for balance monitoring.
//
// The Python side (serving/native_server.py) supplies three callbacks:
//   decide(blob, offsets, lengths, ns) -> (flags, remaining, retry,
//       reset_at, limit)            [bytes in, buffer-protocol out]
//   reset(key_bytes) -> None
//   metrics() -> bytes
//
// Build: automatic on first import (native/__init__.py pattern), or
// `make native-server`.

#define PY_SSIZE_T_CLEAN
#include <Python.h>

#include <arpa/inet.h>
#include <errno.h>
#include <fcntl.h>
#include <limits.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <poll.h>
#include <sys/epoll.h>
#include <sys/eventfd.h>
#include <sys/mman.h>
#include <sys/socket.h>
#include <sys/stat.h>
#include <sys/syscall.h>
#include <sys/uio.h>
#include <sys/un.h>
#include <unistd.h>

#include "shm_ring.h"

#include <atomic>
#include <condition_variable>
#include <cstring>
#include <deque>
#include <map>
#include <memory>
#include <mutex>
#include <string>
#include <thread>
#include <vector>

namespace {

// ---- protocol constants (serving/protocol.py) ----
constexpr uint8_t T_ALLOW_N = 1, T_RESET = 2, T_HEALTH = 3, T_METRICS = 4,
                  T_ALLOW_BATCH = 5, T_DCN_PUSH = 6, T_ALLOW_HASHED = 11;
constexpr uint8_t T_RESULT = 129, T_OK = 130, T_HEALTH_R = 131,
                  T_METRICS_R = 132, T_RESULT_BATCH = 133,
                  T_RESULT_HASHED = 136, T_ERROR = 255;
// Shm lane upgrade (ADR-025): 16 aliases FORWARD_FLAG | 0 on the type
// byte, so the hello is matched EXACTLY on the raw byte before any flag
// stripping (base type 0 is invalid, making the exact match unambiguous;
// the hello never composes with the trace/deadline/forward extensions).
constexpr uint8_t T_SHM_HELLO = 16, T_SHM_HELLO_R = 141;

// splitmix64 finalizer — BIT-IDENTICAL to ops/hashing.splitmix64 (and
// its device twin): the hashed wire lane's raw u64 ids are finalized
// HERE, on the io threads, so the Python launch callback receives
// ready-made hashes and stages them with one memcpy (ADR-011).
inline uint64_t splitmix64(uint64_t x) {
  x += 0x9E3779B97F4A7C15ull;
  x = (x ^ (x >> 30)) * 0xBF58476D1CE4E5B9ull;
  x = (x ^ (x >> 27)) * 0x94D049BB133111EBull;
  return x ^ (x >> 31);
}
constexpr uint16_t E_INVALID_N = 1, E_INVALID_KEY = 2,
                   E_STORAGE_UNAVAILABLE = 3, E_INVALID_CONFIG = 5,
                   E_INTERNAL = 7, E_DEADLINE = 8;
constexpr uint32_t MAX_FRAME = 1u << 20;
// T_DCN_PUSH frames carry whole slabs / debt deltas; the larger cap is
// honored ONLY when the server was created with a dcn callback, so plain
// deployments keep the 1 MiB bad-input bound per frame
// (serving/protocol.py MAX_DCN_FRAME).
constexpr uint32_t MAX_DCN_FRAME = 96u << 20;
constexpr uint32_t MAX_KEY_LEN = 4096;
// Trace-context extension (ADR-014, serving/protocol.py TRACE_FLAG):
// request frames with bit 6 set on the type byte prefix their body with
// a u64 trace id. Stripped here at parse; the id rides each Pending to
// the spans callback so the Python flight recorder can attribute every
// pipeline stage of the dispatch that served the frame.
constexpr uint8_t TRACE_FLAG = 0x40;
// Deadline extension (ADR-015, serving/protocol.py DEADLINE_FLAG):
// request frames with bit 5 set prefix their body with an f64 RELATIVE
// deadline budget in seconds (after the trace id when both flags are
// set). Anchored to frame arrival on the local monotonic clock; the
// dispatcher SHEDS work whose deadline expired before its dispatch ran,
// answering per the fail-open policy instead of burning a dispatch
// slot.
constexpr uint8_t DEADLINE_FLAG = 0x20;
// Forward-lane hint (ADR-019, serving/protocol.py FORWARD_FLAG):
// request frames with bit 4 set are fleet forward windows — every row
// is owned by THIS host, and the frame must never share a dispatch
// with client frames whose resolve waits on our own forward legs
// (coupling the two builds the unbounded cross-host dependency chain
// behind the FLEET_r01 mixed p99). Pure hint, no body prefix; the
// dispatcher cuts its drain at forward/non-forward boundaries.
constexpr uint8_t FORWARD_FLAG = 0x10;

// Span clock: CLOCK_MONOTONIC ns — the SAME domain as Python's
// time.monotonic_ns(), so C++ io/dispatch stamps and Python device-side
// spans interleave on one timeline in the dump.
inline uint64_t mono_ns() {
  struct timespec ts;
  clock_gettime(CLOCK_MONOTONIC, &ts);
  return (uint64_t)ts.tv_sec * 1000000000ull + (uint64_t)ts.tv_nsec;
}

// Keys are UTF-8 strings at the protocol level (the asyncio server
// decodes them and rejects invalid byte sequences); validate here so
// both front doors accept exactly the same key space instead of the
// native path silently hashing raw bytes reset() could never name.
bool utf8_valid(const char* s, size_t n) {
  const unsigned char* p = (const unsigned char*)s;
  const unsigned char* end = p + n;
  while (p < end) {
    if (*p < 0x80) { ++p; continue; }
    int len;
    uint32_t cp;
    if ((*p & 0xE0) == 0xC0) { len = 2; cp = *p & 0x1Fu; }
    else if ((*p & 0xF0) == 0xE0) { len = 3; cp = *p & 0x0Fu; }
    else if ((*p & 0xF8) == 0xF0) { len = 4; cp = *p & 0x07u; }
    else return false;
    if (end - p < len) return false;
    for (int i = 1; i < len; ++i) {
      if ((p[i] & 0xC0) != 0x80) return false;
      cp = (cp << 6) | (p[i] & 0x3Fu);
    }
    if (len == 2 && cp < 0x80) return false;                  // overlong
    if (len == 3 && (cp < 0x800 || (cp >= 0xD800 && cp <= 0xDFFF)))
      return false;                                           // overlong/surrogate
    if (len == 4 && (cp < 0x10000 || cp > 0x10FFFF)) return false;
    p += len;
  }
  return true;
}

void put_u32(std::string& b, uint32_t v) { b.append((char*)&v, 4); }
void put_u16(std::string& b, uint16_t v) { b.append((char*)&v, 2); }
void put_u64(std::string& b, uint64_t v) { b.append((char*)&v, 8); }
void put_i64(std::string& b, int64_t v) { b.append((char*)&v, 8); }
void put_f64(std::string& b, double v) { b.append((char*)&v, 8); }

void frame_header(std::string& b, uint8_t type, uint64_t req_id,
                  uint32_t body_len) {
  put_u32(b, 1 + 8 + body_len);
  b.push_back((char)type);
  put_u64(b, req_id);
}

std::string make_error(uint64_t req_id, uint16_t code, const std::string& msg) {
  std::string out;
  frame_header(out, T_ERROR, req_id, 4 + (uint32_t)msg.size());
  put_u16(out, code);
  put_u16(out, (uint16_t)msg.size());
  out += msg;
  return out;
}

// Shm lane state for one upgraded connection (ADR-025; io thread only
// except the ring ctrl words, which the client process shares). The
// socket stays open as the liveness channel: its EOF/HUP reclaims the
// mapping deterministically, so a kill -9'd client can never wedge the
// server. Spin budget before re-arming the doorbell: cheap C++
// iterations, so a deeper spin than the Python mirror's.
constexpr int SHM_SPIN_ITERS = 4096;

struct ShmLane {
  uint8_t* base = nullptr;
  size_t map_len = 0;
  rlshm::LaneView lane;
  int efd_server = -1;   // server reads (request doorbell)
  int efd_client = -1;   // client reads (reply doorbell)
  int ctrl_listen_fd = -1;
  std::string shm_path, ctrl_path;
  bool handshaken = false;   // eventfds delivered; replies ride the ring
  bool unlinked = false;
  ~ShmLane() {
    if (ctrl_listen_fd >= 0) close(ctrl_listen_fd);
    if (efd_server >= 0) close(efd_server);
    if (efd_client >= 0) close(efd_client);
    if (base != nullptr) munmap(base, map_len);
    if (!unlinked) {
      unlink(ctrl_path.c_str());
      unlink(shm_path.c_str());
    }
  }
};

// ---- network engine (ISSUE-20, ADR-026) ----------------------------------
//
// One readiness interface, two backends. Both backends share the SAME
// recv/sendmsg data path (ring_main / flush_writes below), so wire bytes
// are byte-identical per frame no matter which engine armed the fd —
// the engine only answers "which fds are ready".
//
//   epoll  portable default; what CI measures. Gets the full multi-ring
//          + vectored-I/O work.
//   uring  io_uring in poll-readiness mode: oneshot IORING_OP_POLL_ADD
//          SQEs, re-armed in batch and submitted + waited with ONE
//          io_uring_enter per wait round (epoll pays one epoll_wait
//          PLUS one epoll_ctl per interest change; here interest
//          changes ride the same enter). Raw syscalls, no liburing, no
//          kernel uapi headers — the minimal ABI subset is restated
//          below so the backend COMPILES everywhere (CI build gate)
//          and degrades at runtime via the startup probe where the
//          kernel/seccomp refuses io_uring_setup.

struct NetEvent {
  int fd;
  bool rd, wr, err;
};

class NetEngine {
 public:
  virtual ~NetEngine() = default;
  virtual bool add(int fd, bool want_write) = 0;
  virtual bool mod(int fd, bool want_write) = 0;
  virtual void del(int fd) = 0;
  virtual int wait(NetEvent* out, int max, int timeout_ms) = 0;
  virtual const char* name() const = 0;
};

class EpollEngine : public NetEngine {
 public:
  EpollEngine() { epfd_ = epoll_create1(0); }
  ~EpollEngine() override {
    if (epfd_ >= 0) close(epfd_);
  }
  bool ok() const { return epfd_ >= 0; }
  bool add(int fd, bool want_write) override {
    struct epoll_event ev{};
    ev.events = EPOLLIN | (want_write ? EPOLLOUT : 0);
    ev.data.fd = fd;
    return epoll_ctl(epfd_, EPOLL_CTL_ADD, fd, &ev) == 0;
  }
  bool mod(int fd, bool want_write) override {
    struct epoll_event ev{};
    ev.events = EPOLLIN | (want_write ? EPOLLOUT : 0);
    ev.data.fd = fd;
    return epoll_ctl(epfd_, EPOLL_CTL_MOD, fd, &ev) == 0;
  }
  void del(int fd) override { epoll_ctl(epfd_, EPOLL_CTL_DEL, fd, nullptr); }
  int wait(NetEvent* out, int max, int timeout_ms) override {
    if ((int)evs_.size() < max) evs_.resize((size_t)max);
    int n = epoll_wait(epfd_, evs_.data(), max, timeout_ms);
    if (n < 0) return 0;
    for (int i = 0; i < n; ++i) {
      out[i].fd = evs_[i].data.fd;
      out[i].rd = (evs_[i].events & EPOLLIN) != 0;
      out[i].wr = (evs_[i].events & EPOLLOUT) != 0;
      out[i].err = (evs_[i].events & (EPOLLHUP | EPOLLERR)) != 0;
    }
    return n;
  }
  const char* name() const override { return "epoll"; }

 private:
  int epfd_ = -1;
  std::vector<struct epoll_event> evs_;
};

// Minimal io_uring ABI (uapi linux/io_uring.h subset, layout-stable
// since 5.1). Restated locally so the build never depends on kernel
// headers being present or recent.
struct RlUringSqe {
  uint8_t opcode;
  uint8_t flags;
  uint16_t ioprio;
  int32_t fd;
  uint64_t off;
  uint64_t addr;
  uint32_t len;
  uint32_t op_flags;  // poll_events / timeout_flags / ...
  uint64_t user_data;
  uint64_t pad[3];
};
static_assert(sizeof(RlUringSqe) == 64, "io_uring sqe ABI");
struct RlUringCqe {
  uint64_t user_data;
  int32_t res;
  uint32_t flags;
};
struct RlSqOffsets {
  uint32_t head, tail, ring_mask, ring_entries, flags, dropped, array, resv1;
  uint64_t user_addr;
};
struct RlCqOffsets {
  uint32_t head, tail, ring_mask, ring_entries, overflow, cqes, flags, resv1;
  uint64_t user_addr;
};
struct RlUringParams {
  uint32_t sq_entries, cq_entries, flags, sq_thread_cpu, sq_thread_idle;
  uint32_t features, wq_fd, resv[3];
  RlSqOffsets sq_off;
  RlCqOffsets cq_off;
};
constexpr uint8_t RL_IORING_OP_NOP = 0, RL_IORING_OP_POLL_ADD = 6,
                  RL_IORING_OP_POLL_REMOVE = 7, RL_IORING_OP_TIMEOUT = 11;
constexpr uint32_t RL_IORING_ENTER_GETEVENTS = 1u;
constexpr uint64_t RL_IORING_OFF_SQ_RING = 0, RL_IORING_OFF_CQ_RING = 0x8000000,
                   RL_IORING_OFF_SQES = 0x10000000;
constexpr uint32_t RL_IORING_FEAT_SINGLE_MMAP = 1u;
constexpr uint64_t RL_UD_TIMEOUT = ~0ull, RL_UD_IGNORE = ~1ull;
#ifndef __NR_io_uring_setup
#define __NR_io_uring_setup 425
#endif
#ifndef __NR_io_uring_enter
#define __NR_io_uring_enter 426
#endif
struct RlKernelTimespec {
  int64_t tv_sec;
  long long tv_nsec;
};

inline int rl_io_uring_setup(unsigned entries, RlUringParams* p) {
  return (int)syscall(__NR_io_uring_setup, entries, p);
}
inline int rl_io_uring_enter(int fd, unsigned to_submit, unsigned min_complete,
                             unsigned flags) {
  return (int)syscall(__NR_io_uring_enter, fd, to_submit, min_complete, flags,
                      nullptr, 0);
}

class UringEngine : public NetEngine {
 public:
  explicit UringEngine(unsigned entries) {
    RlUringParams p{};
    ring_fd_ = rl_io_uring_setup(entries, &p);
    if (ring_fd_ < 0) {
      err_ = std::string("io_uring_setup: ") + strerror(errno);
      return;
    }
    sq_map_len_ = p.sq_off.array + p.sq_entries * sizeof(uint32_t);
    cq_map_len_ = p.cq_off.cqes + p.cq_entries * sizeof(RlUringCqe);
    bool single = (p.features & RL_IORING_FEAT_SINGLE_MMAP) != 0;
    if (single && cq_map_len_ > sq_map_len_) sq_map_len_ = cq_map_len_;
    sq_ptr_ = (uint8_t*)mmap(nullptr, sq_map_len_, PROT_READ | PROT_WRITE,
                             MAP_SHARED | MAP_POPULATE, ring_fd_,
                             RL_IORING_OFF_SQ_RING);
    if (sq_ptr_ == MAP_FAILED) {
      sq_ptr_ = nullptr;
      err_ = std::string("io_uring sq mmap: ") + strerror(errno);
      return;
    }
    if (single) {
      cq_ptr_ = sq_ptr_;
    } else {
      cq_ptr_ = (uint8_t*)mmap(nullptr, cq_map_len_, PROT_READ | PROT_WRITE,
                               MAP_SHARED | MAP_POPULATE, ring_fd_,
                               RL_IORING_OFF_CQ_RING);
      if (cq_ptr_ == MAP_FAILED) {
        cq_ptr_ = nullptr;
        err_ = std::string("io_uring cq mmap: ") + strerror(errno);
        return;
      }
    }
    sqes_len_ = p.sq_entries * sizeof(RlUringSqe);
    sqes_ = (RlUringSqe*)mmap(nullptr, sqes_len_, PROT_READ | PROT_WRITE,
                              MAP_SHARED | MAP_POPULATE, ring_fd_,
                              RL_IORING_OFF_SQES);
    if (sqes_ == MAP_FAILED) {
      sqes_ = nullptr;
      err_ = std::string("io_uring sqes mmap: ") + strerror(errno);
      return;
    }
    sq_head_ = (std::atomic<uint32_t>*)(sq_ptr_ + p.sq_off.head);
    sq_tail_ = (std::atomic<uint32_t>*)(sq_ptr_ + p.sq_off.tail);
    sq_mask_ = *(uint32_t*)(sq_ptr_ + p.sq_off.ring_mask);
    sq_array_ = (uint32_t*)(sq_ptr_ + p.sq_off.array);
    cq_head_ = (std::atomic<uint32_t>*)(cq_ptr_ + p.cq_off.head);
    cq_tail_ = (std::atomic<uint32_t>*)(cq_ptr_ + p.cq_off.tail);
    cq_mask_ = *(uint32_t*)(cq_ptr_ + p.cq_off.ring_mask);
    cqes_ = (RlUringCqe*)(cq_ptr_ + p.cq_off.cqes);
    ready_ = true;
  }
  ~UringEngine() override {
    if (sqes_ != nullptr) munmap(sqes_, sqes_len_);
    if (cq_ptr_ != nullptr && cq_ptr_ != sq_ptr_) munmap(cq_ptr_, cq_map_len_);
    if (sq_ptr_ != nullptr) munmap(sq_ptr_, sq_map_len_);
    if (ring_fd_ >= 0) close(ring_fd_);
  }
  bool ok() const { return ready_; }
  const std::string& error() const { return err_; }

  bool add(int fd, bool want_write) override {
    FdState& st = fds_[fd];
    st.mask = (uint16_t)(POLLIN | (want_write ? POLLOUT : 0));
    st.gen = ++gen_ctr_;
    st.armed = false;
    return true;
  }
  bool mod(int fd, bool want_write) override {
    auto it = fds_.find(fd);
    if (it == fds_.end()) return false;
    uint16_t mask = (uint16_t)(POLLIN | (want_write ? POLLOUT : 0));
    if (mask == it->second.mask) return true;
    // Retire the armed oneshot for the OLD interest set: bump the
    // generation (its eventual CQE is ignored) and reap it promptly so
    // a stale POLLIN-only arm can't delay the new POLLOUT interest.
    if (it->second.armed)
      push_sqe_remove(((uint64_t)it->second.gen << 32) | (uint32_t)fd);
    it->second.mask = mask;
    it->second.gen = ++gen_ctr_;
    it->second.armed = false;
    return true;
  }
  void del(int fd) override {
    auto it = fds_.find(fd);
    if (it == fds_.end()) return;
    if (it->second.armed)
      push_sqe_remove(((uint64_t)it->second.gen << 32) | (uint32_t)fd);
    fds_.erase(it);
  }
  int wait(NetEvent* out, int max, int timeout_ms) override {
    // Re-arm every unarmed fd (oneshot POLL_ADD), append the timeout
    // SQE, submit + wait in ONE enter.
    for (auto& kv : fds_) {
      if (kv.second.armed) continue;
      RlUringSqe* sqe = get_sqe();
      if (sqe == nullptr) break;
      memset(sqe, 0, sizeof(*sqe));
      sqe->opcode = RL_IORING_OP_POLL_ADD;
      sqe->fd = kv.first;
      sqe->op_flags = kv.second.mask;  // poll_events (low 16 bits)
      sqe->user_data = ((uint64_t)kv.second.gen << 32) | (uint32_t)kv.first;
      kv.second.armed = true;
    }
    ts_.tv_sec = timeout_ms / 1000;
    ts_.tv_nsec = (long long)(timeout_ms % 1000) * 1000000ll;
    RlUringSqe* tsq = get_sqe();
    if (tsq != nullptr) {
      memset(tsq, 0, sizeof(*tsq));
      tsq->opcode = RL_IORING_OP_TIMEOUT;
      tsq->fd = -1;
      tsq->addr = (uint64_t)(uintptr_t)&ts_;
      tsq->len = 1;
      tsq->user_data = RL_UD_TIMEOUT;
    }
    int r = rl_io_uring_enter(ring_fd_, pending_, 1,
                              RL_IORING_ENTER_GETEVENTS);
    if (r >= 0) pending_ = 0;
    int n = 0;
    uint32_t head = cq_head_->load(std::memory_order_acquire);
    uint32_t tail = cq_tail_->load(std::memory_order_acquire);
    while (head != tail && n < max) {
      const RlUringCqe& cqe = cqes_[head & cq_mask_];
      ++head;
      if (cqe.user_data == RL_UD_TIMEOUT || cqe.user_data == RL_UD_IGNORE)
        continue;
      int fd = (int)(uint32_t)cqe.user_data;
      uint32_t gen = (uint32_t)(cqe.user_data >> 32);
      auto it = fds_.find(fd);
      if (it == fds_.end() || it->second.gen != gen) continue;  // stale
      it->second.armed = false;  // oneshot fired: re-arm next round
      if (cqe.res < 0) {
        if (cqe.res == -ECANCELED) continue;
        out[n++] = NetEvent{fd, false, false, true};
        continue;
      }
      uint32_t rev = (uint32_t)cqe.res;
      out[n].fd = fd;
      out[n].rd = (rev & POLLIN) != 0;
      out[n].wr = (rev & POLLOUT) != 0;
      out[n].err = (rev & (POLLERR | POLLHUP)) != 0;
      ++n;
    }
    cq_head_->store(head, std::memory_order_release);
    return n;
  }
  const char* name() const override { return "uring"; }

 private:
  struct FdState {
    uint16_t mask = POLLIN;
    uint32_t gen = 0;
    bool armed = false;
  };
  RlUringSqe* get_sqe() {
    uint32_t head = sq_head_->load(std::memory_order_acquire);
    uint32_t tail = sq_tail_->load(std::memory_order_relaxed);
    if (tail - head >= sq_mask_ + 1) {
      // SQ full: flush what is queued without waiting, then retry once.
      if (rl_io_uring_enter(ring_fd_, pending_, 0, 0) >= 0) pending_ = 0;
      head = sq_head_->load(std::memory_order_acquire);
      if (tail - head >= sq_mask_ + 1) return nullptr;
    }
    uint32_t idx = tail & sq_mask_;
    sq_array_[idx] = idx;
    sq_tail_->store(tail + 1, std::memory_order_release);
    ++pending_;
    return &sqes_[idx];
  }
  void push_sqe_remove(uint64_t target_ud) {
    RlUringSqe* sqe = get_sqe();
    if (sqe == nullptr) return;
    memset(sqe, 0, sizeof(*sqe));
    sqe->opcode = RL_IORING_OP_POLL_REMOVE;
    sqe->fd = -1;
    sqe->addr = target_ud;
    sqe->user_data = RL_UD_IGNORE;
  }

  int ring_fd_ = -1;
  bool ready_ = false;
  std::string err_;
  uint8_t *sq_ptr_ = nullptr, *cq_ptr_ = nullptr;
  size_t sq_map_len_ = 0, cq_map_len_ = 0, sqes_len_ = 0;
  RlUringSqe* sqes_ = nullptr;
  std::atomic<uint32_t>*sq_head_ = nullptr, *sq_tail_ = nullptr;
  std::atomic<uint32_t>*cq_head_ = nullptr, *cq_tail_ = nullptr;
  uint32_t sq_mask_ = 0, cq_mask_ = 0;
  uint32_t* sq_array_ = nullptr;
  RlUringCqe* cqes_ = nullptr;
  std::map<int, FdState> fds_;
  uint32_t gen_ctr_ = 0;
  unsigned pending_ = 0;
  RlKernelTimespec ts_{};
};

// Startup probe (ADR-026): a full setup + NOP round trip, not just a
// syscall-exists check — seccomp policies that allow io_uring_setup but
// kill io_uring_enter, and kernels with the interface compiled out,
// both fail HERE and the server falls back to epoll with the reason
// recorded in stats()/healthz/logs. Never fatal, even under an explicit
// --net-engine uring: tests assert the probe-miss record instead of
// skipping.
bool uring_probe(std::string& err) {
  RlUringParams p{};
  int fd = rl_io_uring_setup(8, &p);
  if (fd < 0) {
    err = std::string("io_uring_setup: ") + strerror(errno);
    return false;
  }
  size_t sq_len = p.sq_off.array + p.sq_entries * sizeof(uint32_t);
  size_t cq_len = p.cq_off.cqes + p.cq_entries * sizeof(RlUringCqe);
  bool single = (p.features & RL_IORING_FEAT_SINGLE_MMAP) != 0;
  if (single && cq_len > sq_len) sq_len = cq_len;
  uint8_t* sqp = (uint8_t*)mmap(nullptr, sq_len, PROT_READ | PROT_WRITE,
                                MAP_SHARED | MAP_POPULATE, fd,
                                RL_IORING_OFF_SQ_RING);
  RlUringSqe* sqes = (RlUringSqe*)mmap(
      nullptr, p.sq_entries * sizeof(RlUringSqe), PROT_READ | PROT_WRITE,
      MAP_SHARED | MAP_POPULATE, fd, RL_IORING_OFF_SQES);
  bool ok = false;
  if (sqp != MAP_FAILED && sqes != MAP_FAILED) {
    uint8_t* cqp = single ? sqp
                          : (uint8_t*)mmap(nullptr, cq_len,
                                           PROT_READ | PROT_WRITE,
                                           MAP_SHARED | MAP_POPULATE, fd,
                                           RL_IORING_OFF_CQ_RING);
    if (cqp != MAP_FAILED) {
      uint32_t tail = *(uint32_t*)(sqp + p.sq_off.tail);
      uint32_t idx = tail & *(uint32_t*)(sqp + p.sq_off.ring_mask);
      memset(&sqes[idx], 0, sizeof(RlUringSqe));
      sqes[idx].opcode = RL_IORING_OP_NOP;
      sqes[idx].user_data = 42;
      ((uint32_t*)(sqp + p.sq_off.array))[idx] = idx;
      std::atomic_thread_fence(std::memory_order_release);
      *(uint32_t*)(sqp + p.sq_off.tail) = tail + 1;
      int r = rl_io_uring_enter(fd, 1, 1, RL_IORING_ENTER_GETEVENTS);
      if (r < 0) {
        err = std::string("io_uring_enter: ") + strerror(errno);
      } else {
        uint32_t chead = *(uint32_t*)(cqp + p.cq_off.head);
        uint32_t ctail = *(volatile uint32_t*)(cqp + p.cq_off.tail);
        RlUringCqe* cqes = (RlUringCqe*)(cqp + p.cq_off.cqes);
        uint32_t cmask = *(uint32_t*)(cqp + p.cq_off.ring_mask);
        ok = chead != ctail && cqes[chead & cmask].user_data == 42;
        if (!ok) err = "io_uring NOP did not complete";
      }
      if (!single) munmap(cqp, cq_len);
    } else {
      err = std::string("io_uring cq mmap: ") + strerror(errno);
    }
  } else {
    err = std::string("io_uring mmap: ") + strerror(errno);
  }
  if (sqes != MAP_FAILED) munmap(sqes, p.sq_entries * sizeof(RlUringSqe));
  if (sqp != MAP_FAILED) munmap(sqp, sq_len);
  close(fd);
  return ok;
}

struct IoRing;

struct Conn {
  int fd = -1;
  std::string rbuf;                 // partial frames (ring thread only)
  std::deque<std::string> wq;       // outgoing frames
  size_t woff = 0;                  // offset into wq.front()
  size_t wq_bytes = 0;              // guarded by wmx (shm slow-reader cut)
  std::mutex wmx;
  std::atomic<bool> closed{false};
  bool want_write = false;          // ring thread only
  // Queued on its ring's dirty list (flush pending): lets N replies to
  // one connection cost ONE eventfd wake + one vectored flush.
  std::atomic<bool> dirty{false};
  // This connection currently holds a DCN-sized receive-buffer grant
  // (ring thread only; counted in Server::dcn_conns).
  bool dcn_big = false;
  // Shm lane after a T_SHM_HELLO upgrade (null = plain socket conn).
  std::unique_ptr<ShmLane> shm;
  // Owning io ring (ISSUE-20): fixed at accept by round-robin pin; all
  // readiness state for this fd (and its shm lane fds) lives there.
  IoRing* ring = nullptr;
};

using ConnPtr = std::shared_ptr<Conn>;

// One sharded io event loop (ISSUE-20): its own engine, eventfd
// doorbell, and fd-ownership maps. Connections are pinned at accept and
// never migrate, so `conns`/`shm_fds` stay single-threaded (ring thread
// only) exactly like the old single io thread's maps — the inbox +
// dirty list (mutex-guarded) are the only cross-thread entry points.
struct IoRing {
  uint32_t idx = 0;
  int event_fd = -1;
  std::unique_ptr<NetEngine> engine;
  std::thread thread;
  std::map<int, ConnPtr> conns;    // ring thread only
  std::map<int, ConnPtr> shm_fds;  // ctrl/efd fd -> conn (ring thread)
  std::mutex imx;                  // guards inbox + dirty
  std::vector<int> inbox;          // accepted fds awaiting adoption
  std::vector<ConnPtr> dirty;      // conns with queued replies to flush
  // True only while the ring thread is parked inside engine->wait().
  // Producers (conn_send, accept handover) ding the eventfd ONLY when
  // this is set: a busy ring re-checks inbox+dirty at the top of every
  // loop iteration, so work queued while it is awake needs no syscall
  // at all. Dekker pairing with the pre-wait emptiness re-check (both
  // seq_cst, producer pushes then loads; ring stores then checks)
  // guarantees no lost wakeup.
  std::atomic<bool> sleeping{false};
  // Engine-maintained syscall ledger (ISSUE-20): the numerator of the
  // syscalls-per-decision metric the conn sweep divides by decisions.
  std::atomic<uint64_t> recv_calls{0};
  std::atomic<uint64_t> writev_calls{0};
  std::atomic<uint64_t> wait_calls{0};
  std::atomic<uint64_t> wake_calls{0};
  std::atomic<uint64_t> writev_frames{0};
};

// Reassembly of one ALLOW_BATCH / ALLOW_HASHED frame split across
// dispatch units: each contributor writes its results at the original
// positions; the LAST one to finish encodes and sends the single
// response frame. `remaining` counts SEGMENTS, not shards (ADR-013):
// besides the io thread's per-shard split of a mixed frame, the
// dispatcher may carve a hashed segment at the max_batch boundary so a
// coalesced run never overshoots the largest prewarmed pad shape — the
// continuation registers itself with a fetch_add BEFORE its first half
// can deposit, so the count can never hit zero early.
struct BatchJoin {
  std::atomic<uint32_t> remaining;
  ConnPtr conn;
  uint64_t req_id;
  uint32_t count;
  std::vector<uint8_t> flags;
  std::vector<int64_t> rem;
  std::vector<double> retry, reset;
  std::atomic<int64_t> limit{0};
  std::atomic<uint16_t> err{0};
  std::mutex emx;  // guards err_msg only
  std::string err_msg;
  bool hashed = false;  // respond with T_RESULT_HASHED (columnar)
  BatchJoin(uint32_t nsh, ConnPtr c, uint64_t rid, uint32_t cnt)
      : remaining(nsh), conn(std::move(c)), req_id(rid), count(cnt),
        flags(cnt), rem(cnt), retry(cnt), reset(cnt) {}
};
using JoinPtr = std::shared_ptr<BatchJoin>;

// One queued decision unit: a scalar ALLOW_N, a whole ALLOW_BATCH frame,
// one shard's slice of a split batch (join != null; pos holds each
// key's index in the original frame), or — hashed lane (ADR-011) — an
// ALLOW_HASHED frame/slice whose keys are finalized u64 hashes in `ids`
// (keys stays empty; responses are columnar T_RESULT_HASHED).
struct Pending {
  ConnPtr conn;
  uint64_t req_id;
  bool is_batch;
  std::vector<std::string> keys;
  std::vector<int64_t> ns;
  JoinPtr join;
  std::vector<uint32_t> pos;
  bool hashed = false;
  std::vector<uint64_t> ids;
  // Flight-recorder stamps (ABI 9, ADR-014): io-thread enqueue time and
  // the frame's wire-propagated trace id (0 = unsampled).
  uint64_t t_io = 0;
  uint64_t trace_id = 0;
  // Wire-propagated absolute deadline, CLOCK_MONOTONIC ns (ABI 10,
  // ADR-015; 0 = none): anchored at frame arrival from the frame's
  // relative budget. Expired items are shed at the dispatch boundary.
  uint64_t deadline_ns = 0;
  // Fleet forward-lane window (FORWARD_FLAG, ADR-019): the dispatcher
  // never mixes forward and non-forward Pendings in one drained run.
  bool fwd = false;
};

inline size_t pending_count(const Pending& p) {
  return p.hashed ? p.ids.size() : p.keys.size();
}

// The dispatch currently being decided, shared between the dispatcher
// and the SLO watcher. Whoever flips `answered` first owns the response.
struct InFlight {
  std::vector<Pending> items;
  std::atomic<bool> answered{false};
  std::chrono::steady_clock::time_point deadline;
  bool active = false;
};

struct Server {
  int listen_fd = -1;
  uint16_t port = 0;
  // Multi-ring network engine (ISSUE-20, ADR-026): N sharded io event
  // loops; connections pinned round-robin by accept order. io_rings==0
  // at create time means auto (min(4, hardware threads)); resolved at
  // start(). net_engine_req: 0 auto, 1 epoll (probe skipped), 2 uring
  // (probe still decides — a refusing kernel downgrades to epoll with
  // the reason recorded, never a hard failure).
  uint32_t io_rings = 0;
  uint32_t net_engine_req = 0;
  bool uring_active = false;
  // Bench-honesty knob (env RL_NET_COALESCE=0, never a flag): restores
  // the pre-ISSUE-20 write-syscall profile — one sendmsg per frame and
  // one eventfd ding per conn_send — so the conn-sweep A/B measures
  // the coalescing win with the same binary on both sides.
  bool net_coalesce = true;
  std::string uring_probe_err;
  std::vector<std::unique_ptr<IoRing>> rings;
  std::atomic<uint64_t> accept_ctr{0};  // round-robin pin (ring 0 only)
  // UDS listener (--listen unix:/path): host strings beginning "unix:".
  bool uds = false;
  std::string uds_path;
  // Shm wire lane (ADR-025). Off by default: T_SHM_HELLO answers
  // E_INVALID_CONFIG and every other wire byte is identical to a server
  // built before the lane existed.
  bool shm_enabled = false;
  std::string shm_dir = "/dev/shm";
  uint32_t shm_ring_bytes = 0;
  std::atomic<uint32_t> lane_ctr{0};      // lane-file names (any ring)
  // Transport observability (scrape-time, mirrors the asyncio door's
  // transport_stats()): cumulative accepts + live/cumulative lane and
  // ring counters.
  std::atomic<uint64_t> conns_tcp{0}, conns_uds{0}, conns_shm{0};
  std::atomic<uint64_t> shm_lanes_active{0};
  std::atomic<uint64_t> shm_doorbell_wakes{0};
  std::atomic<uint64_t> shm_spin_hits{0};
  std::atomic<uint64_t> shm_records_in{0}, shm_records_out{0};
  std::atomic<uint64_t> shm_ring_full_stalls{0};
  std::atomic<uint64_t> shm_req_highwater{0}, shm_rep_highwater{0};
  uint32_t max_batch = 4096;
  uint32_t max_delay_us = 200;
  // Dispatch SLO (0 = disabled): when one batched decide exceeds this,
  // waiters are answered immediately per fail_open policy while the
  // Python call completes in the background (state still converges) —
  // parity with the asyncio batcher's dispatch_timeout (ADR-003).
  uint32_t slo_us = 0;
  bool fail_open = false;
  // Live limit/window for fail-open RESULT frames: refreshed from every
  // successful decide/resolve result AND pushable from Python
  // (set_limits), so responses stamped without a completed dispatch —
  // SLO breaches, draining — carry the CURRENT limit, not the
  // construction-time one (ISSUE-3 bugfix satellite).
  std::atomic<int64_t> limit{0};
  std::atomic<double> window_s{60.0};
  // Bumped by every explicit set_limits push: a dispatch that STARTED
  // before the push must not overwrite the fresher value when it
  // completes (each refresh is gated on the epoch it captured at start).
  // limit_mx serializes the check-then-store against the push itself —
  // a lock-free gate would leave a load/store window where a racing
  // push is still clobbered. Reads stay lock-free (atomics).
  std::atomic<uint64_t> limit_epoch{0};
  std::mutex limit_mx;
  std::atomic<bool> stop{false};

  // Per-dispatch limit refresh, gated on the epoch captured when the
  // dispatch started.
  void refresh_limit(int64_t lim, uint64_t started_epoch) {
    std::lock_guard<std::mutex> g(limit_mx);
    if (limit_epoch.load() == started_epoch) limit.store(lim);
  }
  std::atomic<bool> draining{false};
  std::atomic<uint64_t> decisions{0};
  // Per-shard decision counts (mesh mode: per-DEVICE; bounded by the
  // num_shards <= 64 cap). Routing-balance observability for the
  // slice-parallel serving tier (ADR-012).
  std::atomic<uint64_t> shard_decisions[64]{};
  // Per-shard quarantine state (ABI 10, ADR-015): 0 healthy, 1 out of
  // routing (quarantined/probing/restoring). Pushed from Python by the
  // quarantine manager's on_state_change via set_shard_health;
  // surfaced in stats()["shard_quarantined"] so operators see the
  // degraded topology from the C++ door's own surface.
  std::atomic<uint32_t> shard_quarantined[64]{};
  std::atomic<uint64_t> slo_breaches{0};
  // Decisions shed because their propagated deadline expired before
  // dispatch (ABI 10, ADR-015).
  std::atomic<uint64_t> deadline_shed{0};
  // Cumulative per-stage wall time (ns) across batched dispatches
  // (ABI 9, ADR-014): io (enqueue -> drain), dispatch (drain -> launch
  // or blocking decide returned), device + complete (pipelined resolve
  // split), respond (responder encode+send). stats()["stage_ns"]
  // surfaces them; per-ticket resolution goes through the spans
  // callback instead.
  std::atomic<uint64_t> stage_io_ns{0};
  std::atomic<uint64_t> stage_dispatch_ns{0};
  std::atomic<uint64_t> stage_device_ns{0};
  std::atomic<uint64_t> stage_complete_ns{0};
  std::atomic<uint64_t> stage_respond_ns{0};
  std::atomic<uint64_t> stage_batches{0};
  double started_at = 0.0;

  std::thread slo_thread;
  std::vector<std::thread> dispatch_threads;

  // Dispatch shards (default 1): keys are routed by hash, each shard has
  // its own queue, dispatcher thread, and (Python-side) limiter shard —
  // per-key semantics are exact because a key always lands on the same
  // shard; shards decide concurrently (the in-process analog of the
  // reference's Redis-Cluster keyspace sharding, and the per-chip layout
  // on a multi-chip serving deployment).
  struct ShardQ {
    std::mutex qmx;
    std::condition_variable qcv;
    std::deque<Pending> queue;
    size_t queued_keys = 0;
  };
  uint32_t num_shards = 1;
  std::vector<std::unique_ptr<ShardQ>> shardqs;
  //: Dispatchers still alive — the responder must outlive them (a
  //: dispatcher inside a long Python decide will enqueue its Reply
  //: AFTER stop is set; exiting on stop+empty alone would drop it).
  std::atomic<uint32_t> live_dispatchers{0};

  // Pipelined dispatch (launch/resolve callbacks set, SLO off): one
  // bounded in-flight ticket queue + completer thread per shard. The
  // dispatcher blocks on cv_space when `inflight` tickets are pending —
  // that is the pipeline's backpressure, upstream of the socket reads.
  struct InflightEntry {
    std::vector<Pending> items;
    PyObject* ticket = nullptr;
    size_t total = 0;
    uint64_t limit_epoch = 0;  // epoch observed at launch time
    bool hashed = false;       // respond columnar (T_RESULT_HASHED)
    // Per-ticket stage stamps (ABI 9, ADR-014): earliest io-thread
    // enqueue over the run's items, dispatch window (drain -> launch
    // callback returned), and the run's first sampled trace id.
    uint64_t t_io = 0;
    uint64_t t_d0 = 0;
    uint64_t t_d1 = 0;
    uint64_t trace_id = 0;
  };
  struct PipeQ {
    std::mutex mx;
    std::condition_variable cv_items, cv_space;
    std::deque<InflightEntry> entries;
    // Tickets the completer has swapped out of `entries` but not yet
    // resolved (the batched-drain window). Counts toward the
    // `inflight` bound — a swapped-out ticket is still a
    // launched-but-unresolved device dispatch, so the dispatcher may
    // not reuse its slot until the resolve lands — and graceful
    // shutdown must wait on these too: the queue alone looks empty
    // mid-batch. Guarded by `mx` (NOT atomic — every reader and writer
    // must hold the lock anyway: the increment pairs with the swap,
    // the decrement avoids the cv_space lost-wakeup race, and the
    // readers need entries+resolving as one consistent sum).
    uint64_t resolving = 0;
  };
  uint32_t inflight_window = 8;
  bool pipelined = false;  // resolved at start(): launch+resolve, no SLO
  std::vector<std::unique_ptr<PipeQ>> pipeqs;
  std::vector<std::thread> completer_threads;
  std::atomic<uint32_t> live_completers{0};

  // DCN receive-buffer accounting (pre-screen, ADVICE r5): connections
  // currently granted a slab-sized rbuf, bounded by max_dcn_conns.
  bool dcn_auth_required = false;
  uint32_t max_dcn_conns = 4;
  std::atomic<uint32_t> dcn_conns{0};

  std::mutex ifmx;
  std::condition_variable ifcv;
  InFlight inflight;

  //: Key namespace prepended in C++ while building the decide blob, so
  //: the Python fast path hashes ready-made "prefix:key" bytes instead
  //: of re-packing the blob per dispatch (measured 7 ms/4096 keys in
  //: numpy — the single largest serving cost before this).
  std::string key_prefix;

  // Responder thread (non-SLO path): encoding + send of one batch's
  // responses overlaps the NEXT batch's Python decide.
  struct Reply {
    std::vector<Pending> items;
    std::vector<uint8_t> flags;
    std::vector<int64_t> remaining;
    std::vector<double> retry, reset_at;
    size_t total = 0;
    int64_t limit = 0;
    uint16_t err_code = 0;
    std::string err_msg;
    bool hashed = false;
  };
  std::mutex rmx;
  std::condition_variable rcv;
  std::deque<Reply> rqueue;
  std::thread resp_thread;

  PyObject* cb_decide = nullptr;
  PyObject* cb_reset = nullptr;
  PyObject* cb_metrics = nullptr;
  // Pipelined-mode callbacks (None = legacy blocking decide):
  //   launch(shard, blob, offsets, lengths, ns) -> opaque ticket
  //   resolve(shard, ticket) -> (flags, remaining, retry, reset_at, limit)
  PyObject* cb_launch = nullptr;
  PyObject* cb_resolve = nullptr;
  // Hashed-lane callbacks (None = T_ALLOW_HASHED answered
  // E_INVALID_CONFIG — non-sketch backends have no raw-id path):
  //   decide_hashed(shard, ids, ns) -> result tuple  [blocking]
  //   launch_hashed(shard, ids, ns) -> opaque ticket [pipelined]
  PyObject* cb_decide_hashed = nullptr;
  PyObject* cb_launch_hashed = nullptr;
  bool hashed_enabled = false;
  // DCN merge callback (None = T_DCN_PUSH rejected and the frame cap
  // stays at MAX_FRAME). Called with the raw push payload; the Python
  // side owns auth verification and the merge into every shard limiter.
  PyObject* cb_dcn = nullptr;
  bool dcn_enabled = false;
  // Spans callback (ABI 9, ADR-014; None = per-ticket spans off):
  //   spans(shard, count, trace_id, t_io, t_d0, t_d1, t_v0, t_v1)
  // called from the completer (GIL already held for the resolve) with
  // the ticket's CLOCK_MONOTONIC ns stamps — the Python side records
  // io/dispatch/device/complete spans into the flight recorder.
  // Pipelined mode only; the blocking decide path feeds the aggregate
  // stage_ns counters instead.
  PyObject* cb_spans = nullptr;
  bool spans_enabled = false;
};

// FNV-1a over the raw key bytes: deterministic shard routing (need not
// match the limiter's own key hashing — only stability per key).
uint32_t key_shard(const Server* s, const std::string& k) {
  if (s->num_shards == 1) return 0;
  uint64_t h = 1469598103934665603ull;
  for (unsigned char ch : k) {
    h ^= ch;
    h *= 1099511628211ull;
  }
  return (uint32_t)(h % s->num_shards);
}

// Extract (code, message) from the pending Python exception: message =
// str(exc), code = exc.rl_code when present (the bridge's typed wire
// code), else `fallback_code`. Clears the error. GIL must be held.
uint16_t fetch_py_error(std::string& msg, const char* fallback_msg,
                        uint16_t fallback_code) {
  uint16_t code = fallback_code;
  PyObject *t, *v, *tb;
  PyErr_Fetch(&t, &v, &tb);
  PyObject* str = v ? PyObject_Str(v) : nullptr;
  const char* u =
      (str && PyUnicode_Check(str)) ? PyUnicode_AsUTF8(str) : nullptr;
  msg = u ? u : fallback_msg;
  if (v != nullptr) {
    PyObject* codeattr = PyObject_GetAttrString(v, "rl_code");
    if (codeattr && PyLong_Check(codeattr))
      code = (uint16_t)PyLong_AsLong(codeattr);
    Py_XDECREF(codeattr);
    if (PyErr_Occurred()) PyErr_Clear();
  }
  Py_XDECREF(str);
  Py_XDECREF(t);
  Py_XDECREF(v);
  Py_XDECREF(tb);
  return code;
}

double now_s() {
  struct timespec ts;
  clock_gettime(CLOCK_REALTIME, &ts);
  return ts.tv_sec + ts.tv_nsec * 1e-9;
}

void conn_send(Server* s, const ConnPtr& c, std::string frame) {
  (void)s;
  if (c->closed.load()) return;
  {
    std::lock_guard<std::mutex> g(c->wmx);
    c->wq_bytes += frame.size();
    c->wq.push_back(std::move(frame));
  }
  IoRing* r = c->ring;
  if (r == nullptr) return;
  // Wake the OWNING ring, once per flush round: further replies queued
  // while the conn is already on the dirty list ride the same wake and
  // the same vectored flush (the old path paid one eventfd write per
  // frame and one send per frame).
  bool was_dirty = c->dirty.exchange(true);
  if (!was_dirty) {
    std::lock_guard<std::mutex> g(r->imx);
    r->dirty.push_back(c);
  }
  // Ding only a PARKED ring (see IoRing::sleeping): an awake ring
  // drains the dirty list on its next loop pass without any syscall.
  // exchange(false) elects ONE producer per park — the burst of
  // replies a decide batch fans out pays a single eventfd write, not
  // one per connection (the ring clears the flag itself on wake, so a
  // false winner can't strand a later park). The no-coalesce bench
  // baseline dings unconditionally — that is the pre-ISSUE-20
  // one-eventfd-write-per-reply profile under test.
  if (!s->net_coalesce ||
      (!was_dirty && r->sleeping.exchange(false))) {
    r->wake_calls.fetch_add(1, std::memory_order_relaxed);
    uint64_t one = 1;
    ssize_t w = write(r->event_fd, &one, 8);
    (void)w;
  }
}

// Columnar T_RESULT_HASHED frame: bit-packed allow mask + three column
// memcpys (the response shape the device packs, serving/protocol.py).
void encode_hashed_frame(std::string& out, uint64_t req_id, int64_t limit,
                         const uint8_t* flags, const int64_t* rem,
                         const double* retry, const double* reset,
                         uint32_t count) {
  uint32_t nb = (count + 7) / 8;
  frame_header(out, T_RESULT_HASHED, req_id, 13 + nb + 24 * count);
  // Batch fail_open = OR over the items: a split (multi-shard) frame
  // whose slices disagree — one shard failed open, another decided —
  // must still report that SOME answers are fabricated.
  uint8_t bflags = 0;
  for (uint32_t i = 0; i < count; ++i) bflags |= (uint8_t)(flags[i] & 2);
  out.push_back((char)bflags);
  put_i64(out, limit);
  put_u32(out, count);
  std::string bits(nb, '\0');
  for (uint32_t i = 0; i < count; ++i)
    if (flags[i] & 1) bits[i >> 3] |= (char)(1u << (i & 7));
  out += bits;
  out.append((const char*)rem, (size_t)count * 8);
  out.append((const char*)retry, (size_t)count * 8);
  out.append((const char*)reset, (size_t)count * 8);
}

// ---- SLO watcher ---------------------------------------------------------

void send_policy_answers(Server* s, const std::vector<Pending>& items) {
  // Fail-open: allowed Result with the fail_open flag; fail-closed:
  // typed storage_unavailable error — ADR-003's SLO-breach policy.
  for (const auto& p : items) {
    if (s->fail_open) {
      // Live limit/window (atomics refreshed by every completed
      // dispatch + Python pushes): a breach after update_limit stamps
      // the CURRENT limit.
      int64_t lim = s->limit.load();
      double reset_at = now_s() + s->window_s.load();
      if (p.hashed) {
        uint32_t count = (uint32_t)p.ids.size();
        std::vector<uint8_t> fl(count, 3);  // allowed | fail_open
        std::vector<int64_t> rem(count, 0);
        std::vector<double> retry(count, 0.0), reset(count, reset_at);
        std::string out;
        encode_hashed_frame(out, p.req_id, lim, fl.data(), rem.data(),
                            retry.data(), reset.data(), count);
        conn_send(s, p.conn, std::move(out));
        s->decisions.fetch_add(count);
        s->shard_decisions[0].fetch_add(count);  // SLO => single shard
        continue;
      }
      if (!p.is_batch) {
        std::string out;
        frame_header(out, T_RESULT, p.req_id, 33);
        out.push_back((char)3);  // allowed | fail_open
        put_i64(out, lim);
        put_i64(out, 0);
        put_f64(out, 0.0);
        put_f64(out, reset_at);
        conn_send(s, p.conn, std::move(out));
      } else {
        uint32_t count = (uint32_t)p.keys.size();
        std::string out;
        frame_header(out, T_RESULT_BATCH, p.req_id, 12 + 25 * count);
        put_i64(out, lim);
        put_u32(out, count);
        for (uint32_t i = 0; i < count; ++i) {
          out.push_back((char)3);
          put_i64(out, 0);
          put_f64(out, 0.0);
          put_f64(out, reset_at);
        }
        conn_send(s, p.conn, std::move(out));
      }
      s->decisions.fetch_add(p.keys.size());
      s->shard_decisions[0].fetch_add(p.keys.size());  // SLO => one shard
    } else {
      conn_send(s, p.conn,
                make_error(p.req_id, E_STORAGE_UNAVAILABLE,
                           "dispatch exceeded SLO"));
    }
  }
}

void slo_main(Server* s) {
  std::unique_lock<std::mutex> lk(s->ifmx);
  while (!s->stop.load()) {
    s->ifcv.wait(lk, [&] { return s->stop.load() || s->inflight.active; });
    if (s->stop.load()) return;
    // Wait until the deadline or until the dispatcher deactivates.
    s->ifcv.wait_until(lk, s->inflight.deadline,
                       [&] { return s->stop.load() || !s->inflight.active; });
    if (s->stop.load()) return;
    if (s->inflight.active &&
        std::chrono::steady_clock::now() >= s->inflight.deadline &&
        !s->inflight.answered.exchange(true)) {
      s->slo_breaches.fetch_add(1);
      send_policy_answers(s, s->inflight.items);
      // Leave `active` set: the dispatcher clears it when the (late)
      // decide lands; its responses are discarded via `answered`.
    }
    // Avoid a hot loop while the late dispatch is still running.
    if (s->inflight.active)
      s->ifcv.wait(lk, [&] { return s->stop.load() || !s->inflight.active; });
  }
}

// ---- dispatcher ----------------------------------------------------------

// Build the contiguous (blob, offsets, lengths, ns) decide buffers for a
// drained run; returns the total key count.
size_t build_buffers(Server* s, const std::vector<Pending>& items,
                     std::string& blob, std::vector<int64_t>& offsets,
                     std::vector<int64_t>& lengths,
                     std::vector<int64_t>& ns) {
  size_t total = 0;
  for (auto& p : items) total += p.keys.size();
  const std::string& prefix = s->key_prefix;
  offsets.reserve(total);
  lengths.reserve(total);
  ns.reserve(total);
  for (auto& p : items) {
    for (size_t i = 0; i < p.keys.size(); ++i) {
      offsets.push_back((int64_t)blob.size());
      lengths.push_back((int64_t)(prefix.size() + p.keys[i].size()));
      blob += prefix;
      blob += p.keys[i];
      ns.push_back(p.ns[i]);
    }
  }
  return total;
}

// Parse the (flags, remaining, retry, reset_at, limit) result tuple into
// `r` (buffer protocol); sets r.err_* on malformed results. GIL held.
void parse_result_tuple(PyObject* res, size_t total, Server::Reply& r,
                        const char* what) {
  PyObject *o_fl, *o_rem, *o_ret, *o_rst;
  long long o_lim = 0;
  if (!PyArg_ParseTuple(res, "OOOOL", &o_fl, &o_rem, &o_ret, &o_rst,
                        &o_lim)) {
    r.err_code = E_INTERNAL;
    r.err_msg = std::string(what) + " returned a malformed tuple";
    PyErr_Clear();
    return;
  }
  r.limit = (int64_t)o_lim;
  r.flags.resize(total);
  r.remaining.resize(total);
  r.retry.resize(total);
  r.reset_at.resize(total);
  Py_buffer bufs[4];
  PyObject* objs[4] = {o_fl, o_rem, o_ret, o_rst};
  int acquired = 0;  // bufs[0..acquired) hold views needing release
  while (acquired < 4 &&
         PyObject_GetBuffer(objs[acquired], &bufs[acquired],
                            PyBUF_SIMPLE) == 0)
    ++acquired;
  bool ok = acquired == 4;
  if (!ok || (size_t)bufs[0].len < total ||
      (size_t)bufs[1].len < total * 8 ||
      (size_t)bufs[2].len < total * 8 ||
      (size_t)bufs[3].len < total * 8) {
    r.err_code = E_INTERNAL;
    r.err_msg = std::string(what) + " returned short buffers";
    PyErr_Clear();
  } else {
    memcpy(r.flags.data(), bufs[0].buf, total);
    memcpy(r.remaining.data(), bufs[1].buf, total * 8);
    memcpy(r.retry.data(), bufs[2].buf, total * 8);
    memcpy(r.reset_at.data(), bufs[3].buf, total * 8);
  }
  for (int i = 0; i < acquired; ++i) PyBuffer_Release(&bufs[i]);
}

// Calls the Python decide callback for a drained run of Pending items,
// filling `r` with per-request results (or an error). Returns false if
// the callback raised.
bool decide_core(Server* s, uint32_t shard, std::vector<Pending>& items,
                 Server::Reply& r, uint64_t trace_id) {
  std::string blob;
  std::vector<int64_t> offsets, lengths, ns;
  size_t total = build_buffers(s, items, blob, offsets, lengths, ns);
  if (total == 0) {
    // Only empty ALLOW_BATCH frames: nothing to decide (and empty
    // buffers would reach Python as None through Py_BuildValue y#).
    r.limit = s->limit.load();
    return true;
  }

  {
    PyGILState_STATE g = PyGILState_Ensure();
    PyObject* args = Py_BuildValue(
        "(Iy#y#y#y#K)", (unsigned int)shard,
        blob.data(), (Py_ssize_t)blob.size(),
        (const char*)offsets.data(), (Py_ssize_t)(offsets.size() * 8),
        (const char*)lengths.data(), (Py_ssize_t)(lengths.size() * 8),
        (const char*)ns.data(), (Py_ssize_t)(ns.size() * 8),
        (unsigned long long)trace_id);
    PyObject* res = args ? PyObject_CallObject(s->cb_decide, args) : nullptr;
    Py_XDECREF(args);
    if (res == nullptr) {
      // Python-side mapping: the bridge returns a typed code via the
      // exception's .rl_code when it can; default storage_unavailable.
      r.err_code = fetch_py_error(r.err_msg, "decide callback failed",
                                  E_STORAGE_UNAVAILABLE);
    } else {
      parse_result_tuple(res, total, r, "decide");
      Py_DECREF(res);
    }
    PyGILState_Release(g);
  }

  r.total = total;
  // decisions accounting is the CALLER's job: the SLO path must not
  // double-count a breached batch the watcher already counted.
  return r.err_code == 0;
}

// Launch phase (pipelined mode): stage + enqueue via the non-blocking
// Python launch callback. Returns the ticket (new reference), or null
// with r.err_* set when the callback raised.
PyObject* launch_core(Server* s, uint32_t shard, std::vector<Pending>& items,
                      Server::Reply& r, size_t* total_out,
                      uint64_t trace_id) {
  std::string blob;
  std::vector<int64_t> offsets, lengths, ns;
  size_t total = build_buffers(s, items, blob, offsets, lengths, ns);
  *total_out = total;
  if (total == 0) {
    r.limit = s->limit.load();
    return nullptr;  // err_code == 0: empty frame, answered directly
  }
  PyObject* ticket = nullptr;
  {
    PyGILState_STATE g = PyGILState_Ensure();
    PyObject* args = Py_BuildValue(
        "(Iy#y#y#y#K)", (unsigned int)shard,
        blob.data(), (Py_ssize_t)blob.size(),
        (const char*)offsets.data(), (Py_ssize_t)(offsets.size() * 8),
        (const char*)lengths.data(), (Py_ssize_t)(lengths.size() * 8),
        (const char*)ns.data(), (Py_ssize_t)(ns.size() * 8),
        (unsigned long long)trace_id);
    ticket = args ? PyObject_CallObject(s->cb_launch, args) : nullptr;
    Py_XDECREF(args);
    if (ticket == nullptr)
      r.err_code = fetch_py_error(r.err_msg, "launch callback failed",
                                  E_STORAGE_UNAVAILABLE);
    PyGILState_Release(g);
  }
  return ticket;
}

// Hashed-lane buffers: finalized u64 ids + ns, contiguous per drained
// run — two memcpy-built arrays, no blob, no offsets/lengths.
size_t build_hashed_buffers(const std::vector<Pending>& items,
                            std::vector<uint64_t>& ids,
                            std::vector<int64_t>& ns) {
  size_t total = 0;
  for (auto& p : items) total += p.ids.size();
  ids.reserve(total);
  ns.reserve(total);
  for (auto& p : items) {
    ids.insert(ids.end(), p.ids.begin(), p.ids.end());
    ns.insert(ns.end(), p.ns.begin(), p.ns.end());
  }
  return total;
}

// Blocking decide for a hashed run (legacy / SLO modes).
bool decide_hashed_core(Server* s, uint32_t shard,
                        std::vector<Pending>& items, Server::Reply& r,
                        uint64_t trace_id) {
  std::vector<uint64_t> ids;
  std::vector<int64_t> ns;
  size_t total = build_hashed_buffers(items, ids, ns);
  r.hashed = true;
  if (total == 0) {
    r.limit = s->limit.load();
    return true;
  }
  {
    PyGILState_STATE g = PyGILState_Ensure();
    PyObject* args = Py_BuildValue(
        "(Iy#y#K)", (unsigned int)shard,
        (const char*)ids.data(), (Py_ssize_t)(ids.size() * 8),
        (const char*)ns.data(), (Py_ssize_t)(ns.size() * 8),
        (unsigned long long)trace_id);
    PyObject* res =
        args ? PyObject_CallObject(s->cb_decide_hashed, args) : nullptr;
    Py_XDECREF(args);
    if (res == nullptr) {
      r.err_code = fetch_py_error(r.err_msg, "decide_hashed callback failed",
                                  E_STORAGE_UNAVAILABLE);
    } else {
      parse_result_tuple(res, total, r, "decide_hashed");
      Py_DECREF(res);
    }
    PyGILState_Release(g);
  }
  r.total = total;
  return r.err_code == 0;
}

// Non-blocking launch for a hashed run (pipelined mode).
PyObject* launch_hashed_core(Server* s, uint32_t shard,
                             std::vector<Pending>& items, Server::Reply& r,
                             size_t* total_out, uint64_t trace_id) {
  std::vector<uint64_t> ids;
  std::vector<int64_t> ns;
  size_t total = build_hashed_buffers(items, ids, ns);
  *total_out = total;
  r.hashed = true;
  if (total == 0) {
    r.limit = s->limit.load();
    return nullptr;  // err_code == 0: empty frame, answered directly
  }
  PyObject* ticket = nullptr;
  {
    PyGILState_STATE g = PyGILState_Ensure();
    PyObject* args = Py_BuildValue(
        "(Iy#y#K)", (unsigned int)shard,
        (const char*)ids.data(), (Py_ssize_t)(ids.size() * 8),
        (const char*)ns.data(), (Py_ssize_t)(ns.size() * 8),
        (unsigned long long)trace_id);
    ticket = args ? PyObject_CallObject(s->cb_launch_hashed, args) : nullptr;
    Py_XDECREF(args);
    if (ticket == nullptr)
      r.err_code = fetch_py_error(r.err_msg, "launch_hashed callback failed",
                                  E_STORAGE_UNAVAILABLE);
    PyGILState_Release(g);
  }
  return ticket;
}

// Completer (pipelined mode): resolve in-flight tickets OLDEST FIRST and
// hand results to the responder. Outlives the dispatchers (a dispatcher
// mid-launch at stop time pushes its ticket afterward) and drains the
// queue fully before exiting, so every launched batch is answered and
// every ticket reference released.
void completer_main(Server* s, uint32_t shard) {
  Server::PipeQ& q = *s->pipeqs[shard];
  s->live_completers.fetch_add(1);
  struct Depart {
    Server* s;
    ~Depart() {
      s->live_completers.fetch_sub(1);
      s->rcv.notify_all();  // responder re-checks its exit condition
    }
  } depart{s};
  while (true) {
    // Completion batching (ADR-013): drain EVERY in-flight ticket in one
    // wake — resolve order stays oldest-first (FIFO state threading),
    // the whole batch leaves the queue in one cv_items acquisition, and
    // a multi-segment frame whose slices resolved back-to-back finishes
    // its BatchJoin within one wake instead of straddling several.
    // Window slots free ONE PER RESOLVE below, not at swap time: a
    // swapped-out ticket is still a launched-but-unresolved device
    // dispatch, and releasing the whole window here would let the
    // dispatcher run the outstanding depth to 2x the documented
    // `inflight` bound.
    std::deque<Server::InflightEntry> batch;
    {
      std::unique_lock<std::mutex> lk(q.mx);
      q.cv_items.wait(lk, [&] {
        return !q.entries.empty() ||
               (s->stop.load() && s->live_dispatchers.load() == 0);
      });
      if (q.entries.empty()) return;  // stopped, launchers gone, drained
      batch.swap(q.entries);
      q.resolving += batch.size();
    }
    for (auto& e : batch) {
      Server::Reply r;
      r.hashed = e.hashed;
      uint64_t t_v0 = mono_ns(), t_v1 = t_v0;
      {
        PyGILState_STATE g = PyGILState_Ensure();
        PyObject* res = PyObject_CallFunction(
            s->cb_resolve, "IO", (unsigned int)shard, e.ticket);
        Py_DECREF(e.ticket);
        t_v1 = mono_ns();
        if (res == nullptr) {
          r.err_code = fetch_py_error(r.err_msg, "resolve callback failed",
                                      E_STORAGE_UNAVAILABLE);
        } else {
          parse_result_tuple(res, e.total, r, "resolve");
          Py_DECREF(res);
        }
        if (s->spans_enabled) {
          // Per-ticket stage stamps into the Python flight recorder
          // (ABI 9, ADR-014) — the GIL is already held for the resolve,
          // so the callback costs no extra acquisition. Failures must
          // never break serving: clear and move on.
          PyObject* sres = PyObject_CallFunction(
              s->cb_spans, "IKKKKKKK", (unsigned int)shard,
              (unsigned long long)e.total,
              (unsigned long long)e.trace_id, (unsigned long long)e.t_io,
              (unsigned long long)e.t_d0, (unsigned long long)e.t_d1,
              (unsigned long long)t_v0, (unsigned long long)t_v1);
          if (sres == nullptr) PyErr_Clear();
          else Py_DECREF(sres);
        }
        PyGILState_Release(g);
      }
      r.total = e.total;
      if (r.err_code == 0) {
        s->decisions.fetch_add(r.total);
        s->shard_decisions[shard].fetch_add(r.total);
        // Gated on the launch-time epoch: this dispatch's limit is stale
        // relative to any set_limits push issued since it launched.
        s->refresh_limit(r.limit, e.limit_epoch);
      }
      if (e.t_io && e.t_d0 >= e.t_io) s->stage_io_ns.fetch_add(e.t_d0 - e.t_io);
      s->stage_dispatch_ns.fetch_add(e.t_d1 - e.t_d0);
      s->stage_device_ns.fetch_add(t_v1 - t_v0);
      s->stage_complete_ns.fetch_add(mono_ns() - t_v1);
      s->stage_batches.fetch_add(1);
      r.items = std::move(e.items);
      {
        std::lock_guard<std::mutex> g(s->rmx);
        s->rqueue.push_back(std::move(r));
      }
      s->rcv.notify_one();
      {
        // Decrement under the lock so a dispatcher mid-predicate on
        // cv_space can't miss the wakeup (the lost-notify race of
        // signalling between its check and its block).
        std::lock_guard<std::mutex> lk(q.mx);
        q.resolving -= 1;
      }
      q.cv_space.notify_one();
    }
  }
}

// Finalize one split batch: called by the LAST shard to contribute.
// Failure semantics across shards are NOT transactional (the same
// contract as any keyspace-sharded store, e.g. a multi-key op spanning
// Redis Cluster slots): if one shard's decide fails, the whole frame
// answers ERROR, but keys on shards that succeeded HAVE consumed quota.
// The error direction is toward denying on retry, never over-admission.
void finish_join(Server* s, const JoinPtr& j) {
  uint16_t err = j->err.load();
  if (err != 0) {
    std::string msg;
    {
      std::lock_guard<std::mutex> g(j->emx);
      msg = j->err_msg;
    }
    conn_send(s, j->conn, make_error(j->req_id, err, msg));
    return;
  }
  std::string out;
  if (j->hashed) {
    encode_hashed_frame(out, j->req_id, j->limit.load(), j->flags.data(),
                        j->rem.data(), j->retry.data(), j->reset.data(),
                        j->count);
    conn_send(s, j->conn, std::move(out));
    return;
  }
  frame_header(out, T_RESULT_BATCH, j->req_id, 12 + 25 * j->count);
  put_i64(out, j->limit.load());
  put_u32(out, j->count);
  for (uint32_t i = 0; i < j->count; ++i) {
    out.push_back((char)j->flags[i]);
    put_i64(out, j->rem[i]);
    put_f64(out, j->retry[i]);
    put_f64(out, j->reset[i]);
  }
  conn_send(s, j->conn, std::move(out));
}

// Encode and queue one batch's responses from filled results.
void emit_reply(Server* s, std::vector<Pending>& items,
                const Server::Reply& r) {
  size_t idx = 0;
  for (auto& p : items) {
    if (p.join) {
      // One shard's slice of a split batch: deposit results at the
      // original positions; the last contributor sends the frame.
      JoinPtr j = p.join;
      if (r.err_code != 0) {
        uint16_t zero = 0;
        if (j->err.compare_exchange_strong(zero, r.err_code)) {
          std::lock_guard<std::mutex> g(j->emx);
          j->err_msg = r.err_msg;
        }
      } else {
        for (size_t i = 0; i < p.pos.size(); ++i) {
          uint32_t at = p.pos[i];
          j->flags[at] = r.flags[idx];
          j->rem[at] = r.remaining[idx];
          j->retry[at] = r.retry[idx];
          j->reset[at] = r.reset_at[idx];
          ++idx;
        }
        j->limit.store(r.limit);
      }
      if (r.err_code != 0) idx += pending_count(p);
      if (j->remaining.fetch_sub(1) == 1) finish_join(s, j);
      continue;
    }
    if (r.err_code != 0) {
      conn_send(s, p.conn, make_error(p.req_id, r.err_code, r.err_msg));
      continue;
    }
    std::string out;
    if (p.hashed) {
      // Columnar hashed response: three slice memcpys straight out of
      // the resolve buffers (ADR-011).
      uint32_t count = (uint32_t)p.ids.size();
      encode_hashed_frame(out, p.req_id, r.limit, r.flags.data() + idx,
                          r.remaining.data() + idx, r.retry.data() + idx,
                          r.reset_at.data() + idx, count);
      idx += count;
      conn_send(s, p.conn, std::move(out));
      continue;
    }
    if (!p.is_batch) {
      frame_header(out, T_RESULT, p.req_id, 33);
      out.push_back((char)r.flags[idx]);
      put_i64(out, r.limit);
      put_i64(out, r.remaining[idx]);
      put_f64(out, r.retry[idx]);
      put_f64(out, r.reset_at[idx]);
      ++idx;
    } else {
      uint32_t count = (uint32_t)p.keys.size();
      frame_header(out, T_RESULT_BATCH, p.req_id, 12 + 25 * count);
      put_i64(out, r.limit);
      put_u32(out, count);
      for (uint32_t i = 0; i < count; ++i) {
        out.push_back((char)r.flags[idx]);
        put_i64(out, r.remaining[idx]);
        put_f64(out, r.retry[idx]);
        put_f64(out, r.reset_at[idx]);
        ++idx;
      }
    }
    conn_send(s, p.conn, std::move(out));
  }
}

// SLO-path wrapper (single-shard only): decide, then answer inline
// unless the watcher beat us to it.
bool run_decide(Server* s, std::vector<Pending>& items,
                std::atomic<bool>* gate, bool hashed = false) {
  Server::Reply r;
  uint64_t ep = s->limit_epoch.load();
  uint64_t trace = 0;
  for (const auto& p : items)
    if (p.trace_id) { trace = p.trace_id; break; }
  bool ok = hashed ? decide_hashed_core(s, 0, items, r, trace)
                   : decide_core(s, 0, items, r, trace);
  if (gate != nullptr && gate->exchange(true)) {
    // SLO watcher already answered (and counted) these waiters; the
    // (late) state update above still landed in the limiter — drop the
    // responses.
    return ok;
  }
  if (ok) {
    s->decisions.fetch_add(r.total);
    s->shard_decisions[0].fetch_add(r.total);  // SLO path: single shard
    if (r.total) s->refresh_limit(r.limit, ep);
  }
  emit_reply(s, items, r);
  return ok;
}

// Non-SLO responder: encoding + socket handoff for batch k runs here
// while the dispatcher's batch k+1 is already inside the Python decide.
// Exits only once every dispatcher has exited AND the queue is drained —
// a dispatcher still inside a Python decide at stop time will enqueue
// its Reply afterward, and those waiters must still be answered.
void responder_main(Server* s) {
  while (true) {
    Server::Reply r;
    {
      std::unique_lock<std::mutex> lk(s->rmx);
      s->rcv.wait(lk, [&] {
        return !s->rqueue.empty() ||
               (s->stop.load() && s->live_dispatchers.load() == 0 &&
                s->live_completers.load() == 0);
      });
      if (s->rqueue.empty()) return;  // stopped, producers gone, drained
      r = std::move(s->rqueue.front());
      s->rqueue.pop_front();
    }
    uint64_t t0 = mono_ns();
    emit_reply(s, r.items, r);
    // Respond stage aggregate (ABI 9): encode + socket handoff time —
    // per-ticket span resolution stops at the completer (this thread is
    // deliberately GIL-free), so the responder reports in stats() only.
    s->stage_respond_ns.fetch_add(mono_ns() - t0);
  }
}

// Dispatch one drained group (string or hashed) via the mode-appropriate
// non-SLO path: pipelined launch when the matching launch callback is
// installed, blocking decide handed to the responder otherwise. String
// and hashed runs dispatch separately — their Python entry points (and
// response encodings) differ — but share the shard's in-flight window.
void dispatch_group(Server* s, uint32_t shard, std::vector<Pending>&& group,
                    bool hashed) {
  bool pipelined =
      s->pipelined &&
      (!hashed ||
       (s->cb_launch_hashed != nullptr && s->cb_launch_hashed != Py_None));
  // Per-run stage stamps (ABI 9): earliest io enqueue and the first
  // sampled trace id over the drained items.
  uint64_t run_io = 0, run_trace = 0;
  for (const auto& p : group) {
    if (p.t_io && (run_io == 0 || p.t_io < run_io)) run_io = p.t_io;
    if (run_trace == 0 && p.trace_id) run_trace = p.trace_id;
  }
  uint64_t t_d0 = mono_ns();
  if (pipelined) {
    Server::Reply r;
    size_t total = 0;
    uint64_t ep = s->limit_epoch.load();
    PyObject* ticket =
        hashed ? launch_hashed_core(s, shard, group, r, &total, run_trace)
               : launch_core(s, shard, group, r, &total, run_trace);
    if (ticket == nullptr) {
      // Launch failed (typed error for every waiter) or the run held
      // only empty frames — answer via the responder directly.
      r.total = total;
      r.items = std::move(group);
      {
        std::lock_guard<std::mutex> g(s->rmx);
        s->rqueue.push_back(std::move(r));
      }
      s->rcv.notify_one();
      return;
    }
    Server::PipeQ& pq = *s->pipeqs[shard];
    {
      std::unique_lock<std::mutex> lk(pq.mx);
      // Bounded window: block HERE (backpressure) when `inflight`
      // tickets are unresolved — queued PLUS swapped out for the
      // completer's batched drain, which are still unresolved device
      // dispatches; on stop, push anyway — the completer drains
      // everything before exiting.
      pq.cv_space.wait(lk, [&] {
        return pq.entries.size() + pq.resolving <
                   s->inflight_window ||
               s->stop.load();
      });
      pq.entries.push_back({std::move(group), ticket, total, ep, hashed,
                            run_io, t_d0, mono_ns(), run_trace});
    }
    pq.cv_items.notify_one();
    return;
  }
  // Throughput path: decide here, hand encode+send to the responder so
  // the next batch's decide starts immediately.
  Server::Reply r;
  r.hashed = hashed;
  uint64_t dep = s->limit_epoch.load();
  bool ok = hashed ? decide_hashed_core(s, shard, group, r, run_trace)
                   : decide_core(s, shard, group, r, run_trace);
  if (ok) {
    s->decisions.fetch_add(r.total);
    s->shard_decisions[shard].fetch_add(r.total);
    if (r.total) s->refresh_limit(r.limit, dep);
  }
  // Blocking path: decide covers dispatch+device in one span — feed the
  // aggregates (per-ticket spans are a pipelined-mode surface).
  if (run_io && t_d0 >= run_io) s->stage_io_ns.fetch_add(t_d0 - run_io);
  s->stage_dispatch_ns.fetch_add(mono_ns() - t_d0);
  s->stage_batches.fetch_add(1);
  r.items = std::move(group);
  {
    std::lock_guard<std::mutex> g(s->rmx);
    s->rqueue.push_back(std::move(r));
  }
  s->rcv.notify_one();
}

// Deadline shedding (ABI 10, ADR-015): answer the items of `group`
// whose propagated deadline expired BEFORE their dispatch ran, per the
// fail-open policy — fail-open rows stamped allowed|fail_open with the
// LIVE limit/window, fail-closed a typed E_DEADLINE error — and remove
// them from the group so the dispatch slot is never burned on them.
// Join-split segments deposit through emit_reply's normal paths, so a
// partially-shed multi-shard frame still answers as ONE frame.
void shed_expired(Server* s, uint32_t shard, std::vector<Pending>& group,
                  bool hashed) {
  uint64_t now = mono_ns();
  bool any = false;
  for (const auto& p : group)
    if (p.deadline_ns != 0 && now >= p.deadline_ns) { any = true; break; }
  if (!any) return;
  std::vector<Pending> live, dead;
  live.reserve(group.size());
  for (auto& p : group) {
    if (p.deadline_ns != 0 && now >= p.deadline_ns)
      dead.push_back(std::move(p));
    else
      live.push_back(std::move(p));
  }
  size_t total = 0;
  for (const auto& p : dead) total += pending_count(p);
  s->deadline_shed.fetch_add(total);
  Server::Reply r;
  r.hashed = hashed;
  r.total = total;
  if (s->fail_open) {
    r.limit = s->limit.load();
    double reset_at = now_s() + s->window_s.load();
    r.flags.assign(total, 3);  // allowed | fail_open
    r.remaining.assign(total, 0);
    r.retry.assign(total, 0.0);
    r.reset_at.assign(total, reset_at);
    s->decisions.fetch_add(total);
    s->shard_decisions[shard].fetch_add(total);
  } else {
    r.err_code = E_DEADLINE;
    r.err_msg = "request deadline expired before dispatch";
  }
  emit_reply(s, dead, r);
  group = std::move(live);
}

void handle_reset(Server* s, uint32_t shard, const Pending& p) {
  uint16_t err_code = 0;
  std::string err_msg;
  {
    PyGILState_STATE g = PyGILState_Ensure();
    PyObject* res = PyObject_CallFunction(
        s->cb_reset, "Iy#", (unsigned int)shard, p.keys[0].data(),
        (Py_ssize_t)p.keys[0].size());
    if (res == nullptr) {
      err_code = fetch_py_error(err_msg, "reset failed",
                                E_STORAGE_UNAVAILABLE);
    } else {
      Py_DECREF(res);
    }
    PyGILState_Release(g);
  }
  std::string out;
  if (err_code) {
    out = make_error(p.req_id, err_code, err_msg);
  } else {
    frame_header(out, T_OK, p.req_id, 0);
  }
  conn_send(s, p.conn, std::move(out));
}

void handle_dcn(Server* s, const Pending& p) {
  // One T_DCN_PUSH payload (keys[0] holds the raw body). Rides shard 0's
  // queue so merges serialize with that dispatcher; the Python callback
  // fans the merge out to every shard limiter itself.
  uint16_t err_code = 0;
  std::string err_msg;
  {
    PyGILState_STATE g = PyGILState_Ensure();
    PyObject* res = PyObject_CallFunction(
        s->cb_dcn, "y#", p.keys[0].data(), (Py_ssize_t)p.keys[0].size());
    if (res == nullptr) {
      err_code = fetch_py_error(err_msg, "DCN merge failed", E_INTERNAL);
    } else {
      Py_DECREF(res);
    }
    PyGILState_Release(g);
  }
  std::string out;
  if (err_code) {
    out = make_error(p.req_id, err_code, err_msg);
  } else {
    frame_header(out, T_OK, p.req_id, 0);
  }
  conn_send(s, p.conn, std::move(out));
}

void handle_metrics(Server* s, const Pending& p) {
  std::string text;
  {
    PyGILState_STATE g = PyGILState_Ensure();
    PyObject* res = s->cb_metrics && s->cb_metrics != Py_None
                        ? PyObject_CallNoArgs(s->cb_metrics)
                        : nullptr;
    if (res != nullptr) {
      if (PyBytes_Check(res))
        text.assign(PyBytes_AsString(res), PyBytes_Size(res));
      else if (PyUnicode_Check(res)) {
        Py_ssize_t n = 0;
        const char* u = PyUnicode_AsUTF8AndSize(res, &n);
        if (u != nullptr) text.assign(u, n);
        else PyErr_Clear();
      }
      Py_DECREF(res);
    } else if (PyErr_Occurred()) {
      PyErr_Clear();
    }
    PyGILState_Release(g);
  }
  std::string out;
  frame_header(out, T_METRICS_R, p.req_id, 4 + (uint32_t)text.size());
  put_u32(out, (uint32_t)text.size());
  out += text;
  conn_send(s, p.conn, std::move(out));
}

void dispatcher_main(Server* s, uint32_t shard) {
  Server::ShardQ& q = *s->shardqs[shard];
  s->live_dispatchers.fetch_add(1);
  struct Depart {
    Server* s;
    ~Depart() {
      s->live_dispatchers.fetch_sub(1);
      s->rcv.notify_all();  // let the responder re-check its exit condition
      for (auto& pq : s->pipeqs) pq->cv_items.notify_all();  // completers too
    }
  } depart{s};
  while (true) {
    std::vector<Pending> run;
    size_t run_keys = 0;
    {
      std::unique_lock<std::mutex> lk(q.qmx);
      if (q.queue.empty()) {
        q.qcv.wait(lk, [&] { return s->stop.load() || !q.queue.empty(); });
      } else {
        // First item already waiting: coalesce for up to max_delay.
        q.qcv.wait_for(lk, std::chrono::microseconds(s->max_delay_us),
                       [&] {
                         return s->stop.load() ||
                                q.queued_keys >= s->max_batch;
                       });
      }
      if (s->stop.load() && q.queue.empty()) return;
      while (!q.queue.empty() && run_keys < s->max_batch) {
        // RESET/METRICS ride the same queue (keys empty or kind marker).
        Pending& front = q.queue.front();
        // Forward-lane boundary (ADR-019): never mix forward windows
        // (all rows local) with client frames (whose bridge resolve
        // may wait on OUR forward legs) in one dispatch — the shared
        // barrier would couple the forward reply to a peer's progress.
        if (!run.empty() && front.fwd != run.back().fwd) break;
        size_t nk = pending_count(front);
        size_t room = s->max_batch - run_keys;
        // Cut BEFORE crossing max_batch (never overshoot the largest
        // prewarmed pad shape). Mid-run, string Pendings cut whole
        // (the next run takes them); an oversized Pending — hashed
        // anywhere in a run, string opening one — is carved at the
        // boundary below. Only SLO mode still dispatches an oversized
        // Pending whole: the SLO watcher answers per-Pending with no
        // join awareness, and prewarm covers one pad shape past
        // max_batch, so only an SLO-mode frame past 2*max_batch pays
        // a hot-path compile.
        if (nk > room && run_keys > 0 &&
            (!front.hashed || s->slo_us > 0)) break;
        if (nk > room && s->slo_us == 0) {
          // Never let a dispatch overshoot max_batch: the Python side
          // prewarms every pad shape up to max_batch, so a run of
          // max_batch+1 items pads to the NEXT power of two and pays a
          // full jit compile on the hot path — the multi-second stalls
          // behind the r06 mixed-traffic collapse (ADR-013). Segments
          // are position-indexed (`pos`), so carve off exactly `room`
          // items and leave a continuation that reassembles through
          // the same (extended) BatchJoin — the string lane rides the
          // shard-split deposit path verbatim. (room >= 1 here: the
          // loop condition guarantees run_keys < max_batch; a string
          // Pending only reaches the carve opening a run — the
          // whole-Pending cut above breaks first — so room is the
          // full max_batch there.)
          JoinPtr j = front.join;
          if (j == nullptr) {
            // Whole frame about to be segmented: wrap it in a join so
            // the response still goes out as ONE frame.
            uint32_t cnt = (uint32_t)pending_count(front);
            j = std::make_shared<BatchJoin>(1, front.conn, front.req_id,
                                            cnt);
            j->hashed = front.hashed;
            front.join = j;
            front.pos.resize(cnt);
            for (uint32_t i = 0; i < cnt; ++i) front.pos[i] = i;
          }
          // Register the continuation BEFORE the first half can ever
          // deposit (both still belong to this thread here), so
          // remaining cannot reach zero while a segment is outstanding.
          j->remaining.fetch_add(1);
          Pending head{front.conn, front.req_id, front.is_batch, {}, {}};
          head.hashed = front.hashed;
          head.join = j;
          head.t_io = front.t_io;
          head.trace_id = front.trace_id;
          head.deadline_ns = front.deadline_ns;
          if (front.hashed) {
            head.ids.assign(front.ids.begin(), front.ids.begin() + room);
            front.ids.erase(front.ids.begin(), front.ids.begin() + room);
          } else {
            head.keys.assign(
                std::make_move_iterator(front.keys.begin()),
                std::make_move_iterator(front.keys.begin() + room));
            front.keys.erase(front.keys.begin(),
                             front.keys.begin() + room);
          }
          head.ns.assign(front.ns.begin(), front.ns.begin() + room);
          head.pos.assign(front.pos.begin(), front.pos.begin() + room);
          front.ns.erase(front.ns.begin(), front.ns.begin() + room);
          front.pos.erase(front.pos.begin(), front.pos.begin() + room);
          run_keys += room;
          run.push_back(std::move(head));
          break;  // run is exactly full
        }
        run_keys += nk;
        run.push_back(std::move(front));
        q.queue.pop_front();
      }
      q.queued_keys -= std::min(q.queued_keys, run_keys);
    }
    // Split control items (req_id flag via ns sentinel) from decisions;
    // hashed frames dispatch as their own group (different Python entry
    // point + columnar response encoding, ADR-011).
    std::vector<Pending> decisions, hashed;
    for (auto& p : run) {
      if (!p.hashed && p.ns.size() == 1 && p.ns[0] == -1) {
        handle_reset(s, shard, p);
      } else if (!p.hashed && p.ns.size() == 1 && p.ns[0] == -2) {
        handle_metrics(s, p);
      } else if (!p.hashed && p.ns.size() == 1 && p.ns[0] == -3) {
        handle_dcn(s, p);
      } else if (p.hashed) {
        hashed.push_back(std::move(p));
      } else {
        decisions.push_back(std::move(p));
      }
    }
    // Deadline shedding BEFORE the dispatch fork (ABI 10, ADR-015):
    // both the pipelined/throughput and SLO paths skip expired work.
    if (!decisions.empty()) shed_expired(s, shard, decisions, false);
    if (!hashed.empty()) shed_expired(s, shard, hashed, true);
    if (decisions.empty() && hashed.empty()) continue;
    if (s->slo_us == 0) {
      // Pipelined (ADR-010) or legacy throughput path, per group.
      if (!decisions.empty())
        dispatch_group(s, shard, std::move(decisions), false);
      if (!hashed.empty())
        dispatch_group(s, shard, std::move(hashed), true);
      continue;
    }
    // SLO path (single shard): one group at a time through the
    // single-deadline watcher.
    for (int grp = 0; grp < 2; ++grp) {
      std::vector<Pending>& g = grp == 0 ? decisions : hashed;
      if (g.empty()) continue;
      {
        std::lock_guard<std::mutex> lk(s->ifmx);
        s->inflight.items = std::move(g);
        s->inflight.answered.store(false);
        s->inflight.deadline = std::chrono::steady_clock::now() +
                               std::chrono::microseconds(s->slo_us);
        s->inflight.active = true;
      }
      s->ifcv.notify_all();
      run_decide(s, s->inflight.items, &s->inflight.answered, grp == 1);
      {
        std::lock_guard<std::mutex> lk(s->ifmx);
        s->inflight.active = false;
        s->inflight.items.clear();
      }
      s->ifcv.notify_all();
    }
  }
}

// ---- io thread -----------------------------------------------------------

void close_conn(Server* s, const ConnPtr& c) {
  if (c->closed.exchange(true)) return;
  IoRing* r = c->ring;
  if (c->dcn_big) {
    c->dcn_big = false;
    s->dcn_conns.fetch_sub(1);
  }
  if (c->shm) {
    // Deterministic reclaim (ADR-025): drop the doorbell/control fds
    // from the owning ring's engine, then let the lane destructor unmap
    // + unlink. Records the client pushed but we never drained are
    // abandoned with the mapping — exactly the TCP contract for bytes
    // in a dead socket.
    ShmLane* L = c->shm.get();
    for (int fd : {L->ctrl_listen_fd, L->efd_server}) {
      if (fd >= 0 && r != nullptr) {
        r->engine->del(fd);
        r->shm_fds.erase(fd);
      }
    }
    if (L->handshaken) s->shm_lanes_active.fetch_sub(1);
    c->shm.reset();
  }
  if (r != nullptr) {
    r->engine->del(c->fd);
    r->conns.erase(c->fd);
  }
  close(c->fd);
}

void ding_efd(int fd) {
  uint64_t one = 1;
  ssize_t r = write(fd, &one, 8);
  (void)r;
}

// Reply producer for an upgraded conn: push queued frames into the
// reply ring (every reply funnels through conn_send -> wq, so ALL
// encodings — results, errors, metrics, health — ride unchanged).
// Ring full leaves the residue in wq with producer_waiting raised; the
// client's consumer dings efd_server after freeing space and the drain
// path re-flushes. A peer further behind than the slow-reader cut
// (mirrors the asyncio door's WRITE_BUFFER_LIMIT) is disconnected.
void flush_shm_writes(Server* s, const ConnPtr& c) {
  ShmLane* L = c->shm.get();
  rlshm::Ring& ring = L->lane.outbound;
  bool pushed = false, cut = false;
  {
    std::lock_guard<std::mutex> g(c->wmx);
    while (!c->wq.empty()) {
      const std::string& f = c->wq.front();
      if (8 + rlshm::align8((uint32_t)f.size()) >= ring.capacity) {
        cut = true;  // frame can never fit: fatal for this lane
        break;
      }
      if (!ring.try_push((const uint8_t*)f.data(), (uint32_t)f.size())) {
        ring.set_producer_waiting();
        // Re-check after the SeqCst store: the consumer may have freed
        // space between the failed push and the flag store.
        if (!ring.try_push((const uint8_t*)f.data(), (uint32_t)f.size())) {
          s->shm_ring_full_stalls.fetch_add(1);
          break;
        }
        ring.clear_producer_waiting();
      }
      pushed = true;
      s->shm_records_out.fetch_add(1);
      c->wq_bytes -= f.size();
      c->wq.pop_front();
    }
    if (c->wq_bytes > 8ul * 1024 * 1024) cut = true;
    uint64_t used = ring.used();
    uint64_t hw = s->shm_rep_highwater.load();
    while (used > hw && !s->shm_rep_highwater.compare_exchange_weak(hw, used)) {
    }
  }
  if (pushed && ring.consumer_sleeping()) ding_efd(L->efd_client);
  if (cut) close_conn(s, c);
}

void flush_writes(Server* s, const ConnPtr& c) {
  if (c->shm && c->shm->handshaken) {
    // Upgraded conn: replies ride the reply ring, not the socket (the
    // socket is the liveness channel only past this point).
    flush_shm_writes(s, c);
    return;
  }
  IoRing* r = c->ring;
  std::lock_guard<std::mutex> g(c->wmx);
  // Vectored flush (ISSUE-20): EVERY queued frame rides one sendmsg
  // per iteration (capped well under IOV_MAX), replacing the old
  // write-per-frame loop. writev_frames / writev_calls is the batch
  // factor the rate_limiter_net_writev_frames metric proves.
  constexpr int kMaxIov = 64;
  static_assert(kMaxIov <= IOV_MAX, "iov cap must respect IOV_MAX");
  const int max_iov = s->net_coalesce ? kMaxIov : 1;
  while (!c->wq.empty()) {
    struct iovec iov[kMaxIov];
    int cnt = 0;
    size_t total = 0;
    for (auto it = c->wq.begin(); it != c->wq.end() && cnt < max_iov; ++it) {
      size_t off = (cnt == 0) ? c->woff : 0;
      iov[cnt].iov_base = (void*)(it->data() + off);
      iov[cnt].iov_len = it->size() - off;
      total += iov[cnt].iov_len;
      ++cnt;
    }
    struct msghdr msg{};
    msg.msg_iov = iov;
    msg.msg_iovlen = (size_t)cnt;
    ssize_t w = sendmsg(c->fd, &msg, MSG_NOSIGNAL);
    if (r != nullptr) r->writev_calls.fetch_add(1, std::memory_order_relaxed);
    if (w < 0) {
      if (errno == EAGAIN || errno == EWOULDBLOCK) break;
      close_conn(s, c);
      return;
    }
    size_t left = (size_t)w;
    while (left > 0 && !c->wq.empty()) {
      size_t avail = c->wq.front().size() - c->woff;
      if (left >= avail) {
        left -= avail;
        c->wq_bytes -= c->wq.front().size();
        c->wq.pop_front();
        c->woff = 0;
        if (r != nullptr)
          r->writev_frames.fetch_add(1, std::memory_order_relaxed);
      } else {
        c->woff += left;
        left = 0;
      }
    }
    if ((size_t)w < total) break;  // kernel buffer full: wait for EPOLLOUT
  }
  bool want = !c->wq.empty();
  if (want != c->want_write) {
    c->want_write = want;
    if (r != nullptr) r->engine->mod(c->fd, want);
  }
}

bool process_rbuf(Server* s, const ConnPtr& c);

uint32_t clamp_ring_bytes(uint32_t n) {
  // Mirrors serving/shm.py clamp_ring_bytes: 0 -> default 2 MiB, else a
  // power of two in [MIN_RING, MAX_RING].
  if (n == 0) return 1u << 21;
  if (n < rlshm::MIN_RING) n = rlshm::MIN_RING;
  if (n > rlshm::MAX_RING) n = rlshm::MAX_RING;
  uint32_t p = rlshm::MIN_RING;
  while (p < n) p <<= 1;
  return p;
}

// T_SHM_HELLO on the io thread (ADR-025): create the per-connection
// mapping + eventfds + one-shot control listener, answer T_SHM_HELLO_R
// over the socket. Returns false on a malformed body (protocol error:
// the caller closes the connection, matching parse_shm_hello's raise).
bool handle_shm_hello(Server* s, const ConnPtr& c, uint64_t req_id,
                      const char* body, uint32_t blen) {
  if (blen != 12) return false;
  if (!s->shm_enabled) {
    conn_send(s, c, make_error(req_id, E_INVALID_CONFIG,
                               "shm lane not enabled on this server "
                               "(--shm)"));
    return true;
  }
  if (c->shm) {
    conn_send(s, c, make_error(req_id, E_INVALID_CONFIG,
                               "shm lane already active on this "
                               "connection"));
    return true;
  }
  uint32_t version, req_b, rep_b;
  memcpy(&version, body, 4);
  memcpy(&req_b, body + 4, 4);
  memcpy(&rep_b, body + 8, 4);
  if (version != rlshm::VERSION) {
    conn_send(s, c, make_error(req_id, E_INVALID_CONFIG,
                               "unsupported shm lane version"));
    return true;
  }
  uint32_t req_cap = clamp_ring_bytes(req_b ? req_b : s->shm_ring_bytes);
  uint32_t rep_cap = clamp_ring_bytes(rep_b ? rep_b : s->shm_ring_bytes);
  auto L = std::make_unique<ShmLane>();
  int sfd = -1;
  char path[512];
  for (int attempt = 0; attempt < 64 && sfd < 0; ++attempt) {
    snprintf(path, sizeof(path), "%s/rltpu-shm-%d-n%u-%d",
             s->shm_dir.c_str(), (int)getpid(),
             s->lane_ctr.fetch_add(1) + 1, attempt);
    sfd = open(path, O_CREAT | O_EXCL | O_RDWR, 0600);
  }
  if (sfd < 0) {
    conn_send(s, c, make_error(req_id, E_STORAGE_UNAVAILABLE,
                               "could not allocate shm lane file"));
    return true;
  }
  L->shm_path = path;
  L->ctrl_path = L->shm_path + ".ctrl";
  L->map_len = (size_t)rlshm::total_bytes(req_cap, rep_cap);
  if (ftruncate(sfd, (off_t)L->map_len) != 0 ||
      (L->base = (uint8_t*)mmap(nullptr, L->map_len,
                                PROT_READ | PROT_WRITE, MAP_SHARED, sfd,
                                0)) == MAP_FAILED) {
    L->base = nullptr;
    close(sfd);
    unlink(path);
    L->unlinked = true;
    conn_send(s, c, make_error(req_id, E_STORAGE_UNAVAILABLE,
                               "could not map shm lane file"));
    return true;
  }
  close(sfd);
  rlshm::init_file(L->base, req_cap, rep_cap);
  rlshm::attach(L->base, /*server=*/true, &L->lane);
  // Armed from birth: the client's very first push must ding the
  // doorbell (the drain path re-arms after each empty spin).
  L->lane.inbound.set_sleeping();
  L->efd_server = eventfd(0, EFD_NONBLOCK);
  L->efd_client = eventfd(0, EFD_NONBLOCK);
  L->ctrl_listen_fd =
      socket(AF_UNIX, SOCK_STREAM | SOCK_NONBLOCK, 0);
  struct sockaddr_un sun{};
  sun.sun_family = AF_UNIX;
  if (L->efd_server < 0 || L->efd_client < 0 || L->ctrl_listen_fd < 0 ||
      L->ctrl_path.size() >= sizeof(sun.sun_path)) {
    conn_send(s, c, make_error(req_id, E_STORAGE_UNAVAILABLE,
                               "could not set up shm lane doorbells"));
    return true;  // ~ShmLane cleans up
  }
  memcpy(sun.sun_path, L->ctrl_path.c_str(), L->ctrl_path.size() + 1);
  unlink(L->ctrl_path.c_str());
  if (bind(L->ctrl_listen_fd, (struct sockaddr*)&sun, sizeof(sun)) != 0 ||
      chmod(L->ctrl_path.c_str(), 0600) != 0 ||
      listen(L->ctrl_listen_fd, 1) != 0) {
    conn_send(s, c, make_error(req_id, E_STORAGE_UNAVAILABLE,
                               "could not bind shm control socket"));
    return true;
  }
  // The lane's ctrl socket rides the conn's OWN ring (ISSUE-20), so
  // handshake and doorbell traffic shard with the connection.
  c->ring->engine->add(L->ctrl_listen_fd, false);
  c->ring->shm_fds[L->ctrl_listen_fd] = c;
  std::string sp = L->shm_path, cp = L->ctrl_path;
  c->shm = std::move(L);
  s->conns_shm.fetch_add(1);
  std::string out;
  frame_header(out, T_SHM_HELLO_R, req_id,
               9 + 2 + (uint32_t)sp.size() + 2 + (uint32_t)cp.size());
  out.push_back((char)1);  // ok
  put_u32(out, req_cap);
  put_u32(out, rep_cap);
  put_u16(out, (uint16_t)sp.size());
  out += sp;
  put_u16(out, (uint16_t)cp.size());
  out += cp;
  conn_send(s, c, std::move(out));  // lane not handshaken: rides the socket
  return true;
}

// Control-socket accept: ship the eventfd pair via SCM_RIGHTS, then
// unlink both filesystem artifacts (the peer holds them open) and start
// watching the request doorbell.
void shm_ctrl_accept(Server* s, const ConnPtr& c) {
  ShmLane* L = c->shm.get();
  int cfd = accept4(L->ctrl_listen_fd, nullptr, nullptr, 0);
  if (cfd < 0) return;
  char data = 'x';
  struct iovec iov {
    &data, 1
  };
  char cbuf[CMSG_SPACE(2 * sizeof(int))];
  memset(cbuf, 0, sizeof(cbuf));
  struct msghdr msg{};
  msg.msg_iov = &iov;
  msg.msg_iovlen = 1;
  msg.msg_control = cbuf;
  msg.msg_controllen = sizeof(cbuf);
  struct cmsghdr* cm = CMSG_FIRSTHDR(&msg);
  cm->cmsg_level = SOL_SOCKET;
  cm->cmsg_type = SCM_RIGHTS;
  cm->cmsg_len = CMSG_LEN(2 * sizeof(int));
  int fds[2] = {L->efd_server, L->efd_client};
  memcpy(CMSG_DATA(cm), fds, sizeof(fds));
  msg.msg_controllen = cm->cmsg_len;
  ssize_t w = sendmsg(cfd, &msg, 0);
  close(cfd);
  IoRing* r = c->ring;
  r->engine->del(L->ctrl_listen_fd);
  r->shm_fds.erase(L->ctrl_listen_fd);
  close(L->ctrl_listen_fd);
  L->ctrl_listen_fd = -1;
  unlink(L->ctrl_path.c_str());
  unlink(L->shm_path.c_str());
  L->unlinked = true;
  if (w < 0) {
    close_conn(s, c);
    return;
  }
  L->handshaken = true;
  s->shm_lanes_active.fetch_add(1);
  r->engine->add(L->efd_server, false);
  r->shm_fds[L->efd_server] = c;
  // Replies queued during the handshake window move to the ring now.
  flush_shm_writes(s, c);
}

// Request-doorbell wake: drain every committed record into rbuf (records
// ARE wire frames, so the normal parser consumes them unchanged), with
// the same cleared-while-draining / re-arm / missed-wake-recheck
// protocol as the Python ServerLane. A torn record poisons the lane —
// reclaim through the liveness socket, never spin on corrupt memory.
void shm_drain(Server* s, const ConnPtr& c) {
  ShmLane* L = c->shm.get();
  uint64_t junk;
  ssize_t r = read(L->efd_server, &junk, 8);
  (void)r;
  s->shm_doorbell_wakes.fetch_add(1);
  rlshm::Ring& ring = L->lane.inbound;
  uint64_t used = ring.used();
  uint64_t hw = s->shm_req_highwater.load();
  while (used > hw && !s->shm_req_highwater.compare_exchange_weak(hw, used)) {
  }
  ring.clear_sleeping();
  bool dead = false;
  for (;;) {
    const uint8_t* payload;
    uint32_t len;
    rlshm::Ring::PopResult pr = ring.pop(&payload, &len);
    if (pr == rlshm::Ring::POP_EMPTY) {
      // Dispatch what is buffered BEFORE burning the spin budget — the
      // spin exists to catch back-to-back pushes cheaply, not to delay
      // work already in hand.
      if (!c->rbuf.empty() && !process_rbuf(s, c)) {
        dead = true;
        break;
      }
      for (int i = 0; i < SHM_SPIN_ITERS; ++i) {
        pr = ring.pop(&payload, &len);
        if (pr != rlshm::Ring::POP_EMPTY) {
          s->shm_spin_hits.fetch_add(1);
          break;
        }
      }
      if (pr == rlshm::Ring::POP_EMPTY) {
        ring.set_sleeping();
        pr = ring.pop(&payload, &len);  // missed-wake recheck
        if (pr == rlshm::Ring::POP_EMPTY) break;
        ring.clear_sleeping();
      }
    }
    if (pr == rlshm::Ring::POP_TORN) {
      dead = true;
      break;
    }
    c->rbuf.append((const char*)payload, len);
    ring.advance(len);
    s->shm_records_in.fetch_add(1);
  }
  if (!dead && !c->rbuf.empty() && !process_rbuf(s, c)) dead = true;
  if (dead) {
    close_conn(s, c);
    return;
  }
  if (ring.producer_waiting()) {
    ring.clear_producer_waiting();
    ding_efd(L->efd_client);
  }
  // Space may have been freed on the reply ring by the client too;
  // retry any residue the last flush left queued.
  flush_shm_writes(s, c);
}

// Parse complete frames out of c->rbuf; enqueue work.
bool process_rbuf(Server* s, const ConnPtr& c) {
  size_t off = 0;
  while (c->rbuf.size() - off >= 13) {
    uint32_t length;
    memcpy(&length, c->rbuf.data() + off, 4);
    if (length < 9) return false;  // protocol error
    // The type byte is already in hand (>= 13 bytes buffered), so the
    // per-frame cap can be type-aware: DCN pushes get the slab-sized cap
    // ONLY on a DCN-enabled server (mirrors protocol.parse_header's
    // allow_dcn). The trace-context flag (ADR-014) is stripped first:
    // flagged requests prefix their body with a u64 trace id.
    uint8_t rawtype = (uint8_t)c->rbuf[off + 4];
    if (rawtype == T_SHM_HELLO) {
      // Shm lane upgrade (ADR-025): EXACT match on the raw type byte
      // BEFORE any flag stripping — 16 aliases FORWARD_FLAG | 0, and
      // base type 0 is invalid, so this cannot shadow a real frame.
      if (length > MAX_FRAME) return false;
      if (c->rbuf.size() - off < 4 + length) break;
      uint64_t rid;
      memcpy(&rid, c->rbuf.data() + off + 5, 8);
      const char* hbody = c->rbuf.data() + off + 13;
      uint32_t hlen = length - 9;
      off += 4 + length;
      if (!handle_shm_hello(s, c, rid, hbody, hlen)) return false;
      continue;
    }
    bool traced = (rawtype & TRACE_FLAG) != 0 && rawtype < 0x80;
    uint8_t type = traced ? (uint8_t)(rawtype & ~TRACE_FLAG) : rawtype;
    bool deadlined = (type & DEADLINE_FLAG) != 0 && rawtype < 0x80;
    if (deadlined) type = (uint8_t)(type & ~DEADLINE_FLAG);
    bool fwd_hint = (type & FORWARD_FLAG) != 0 && rawtype < 0x80;
    if (fwd_hint) type = (uint8_t)(type & ~FORWARD_FLAG);
    uint64_t req_id;
    memcpy(&req_id, c->rbuf.data() + off + 5, 8);
    uint32_t cap =
        (s->dcn_enabled && type == T_DCN_PUSH) ? MAX_DCN_FRAME : MAX_FRAME;
    if (length > cap) return false;  // protocol error
    size_t tskip = (traced ? 8 : 0) + (deadlined ? 8 : 0);
    if (s->dcn_enabled && type == T_DCN_PUSH && !c->dcn_big &&
        (size_t)4 + length > c->rbuf.size() - off) {
      // Incomplete DCN frame that will need slab-sized buffering:
      // pre-screen BEFORE granting it (ADVICE r5). When the server
      // requires push auth, the body must open with the RLA envelope
      // magic — an oversized garbage stream labeled T_DCN_PUSH dies
      // here, 4 bytes in, instead of buffering up to MAX_DCN_FRAME.
      // A traced push shifts the envelope past the 8-byte trace id.
      if (c->rbuf.size() - off < 17 + tskip)
        break;  // need the first 4 body bytes
      const char* bm = c->rbuf.data() + off + 13 + tskip;
      if (s->dcn_auth_required &&
          !(bm[0] == 'R' && bm[1] == 'L' && bm[2] == 'A' &&
            (bm[3] == '1' || bm[3] == '2')))
        return false;
      // Bound the number of connections holding DCN-sized buffers.
      if (s->dcn_conns.fetch_add(1) >= s->max_dcn_conns) {
        s->dcn_conns.fetch_sub(1);
        // Best-effort DIRECT send: returning false closes the conn
        // immediately, so the queued-write path would drop the typed
        // refusal before the peer could read it.
        std::string err = make_error(req_id, E_STORAGE_UNAVAILABLE,
                                     "too many concurrent DCN transfers "
                                     "(raise max_dcn_conns)");
        ssize_t w = send(c->fd, err.data(), err.size(), MSG_NOSIGNAL);
        (void)w;
        return false;
      }
      c->dcn_big = true;
    }
    if (c->rbuf.size() - off < 4 + length) break;
    const char* body = c->rbuf.data() + off + 13;
    uint32_t blen = length - 9;
    off += 4 + length;
    uint64_t trace_id = 0;
    if (traced) {
      if (blen < 8) return false;  // short trace-id extension
      memcpy(&trace_id, body, 8);
      body += 8;
      blen -= 8;
    }
    uint64_t deadline_ns = 0;
    if (deadlined) {
      if (blen < 8) return false;  // short deadline extension
      double budget;
      memcpy(&budget, body, 8);
      body += 8;
      blen -= 8;
      // Relative budget anchored at arrival (wall clocks need not
      // agree across machines); non-positive budgets are already
      // expired and shed at the next dispatch boundary.
      if (budget > 0.0 && budget < 86400.0 * 365)
        deadline_ns = mono_ns() + (uint64_t)(budget * 1e9);
      else if (budget <= 0.0)
        deadline_ns = 1;  // any past instant: expired on arrival
    }

    auto enqueue = [&](Pending&& p, size_t nkeys, uint32_t shard) {
      Server::ShardQ& q = *s->shardqs[shard];
      std::lock_guard<std::mutex> g(q.qmx);
      q.queue.push_back(std::move(p));
      q.queued_keys += nkeys;
      q.qcv.notify_one();
    };

    if (type == T_ALLOW_N) {
      if (blen < 6) return false;
      uint32_t n;
      uint16_t klen;
      memcpy(&n, body, 4);
      memcpy(&klen, body + 4, 2);
      if (blen != 6u + klen || klen > MAX_KEY_LEN) return false;
      if (s->draining.load()) {
        conn_send(s, c, make_error(req_id, E_STORAGE_UNAVAILABLE,
                                   "server is shutting down"));
      } else if (klen == 0 || !utf8_valid(body + 6, klen)) {
        // Key before n: the asyncio server decodes the key during frame
        // parsing, so a frame bad in both ways answers E_INVALID_KEY
        // there — the two front doors must agree on the code.
        conn_send(s, c, make_error(req_id, E_INVALID_KEY,
                                   "key must be a non-empty UTF-8 string"));
      } else if (n == 0) {
        conn_send(s, c, make_error(req_id, E_INVALID_N,
                                   "n must be a positive integer, got 0"));
      } else {
        std::string key(body + 6, klen);
        uint32_t shard = key_shard(s, key);
        Pending p{c, req_id, false, {std::move(key)}, {(int64_t)n}};
        p.t_io = mono_ns();
        p.trace_id = trace_id;
        p.deadline_ns = deadline_ns;
        enqueue(std::move(p), 1, shard);
      }
    } else if (type == T_ALLOW_BATCH) {
      if (blen < 4) return false;
      uint32_t count;
      memcpy(&count, body, 4);
      // Untrusted count: every item needs >= 6 body bytes, so anything
      // larger is malformed — reject BEFORE reserving (alloc bound).
      if (count > (blen - 4) / 6) return false;
      Pending p{c, req_id, true, {}, {}};
      p.t_io = mono_ns();
      p.trace_id = trace_id;
      p.deadline_ns = deadline_ns;
      p.fwd = fwd_hint;
      p.keys.reserve(count);
      p.ns.reserve(count);
      size_t pos = 4;
      // Error precedence mirrors the asyncio server exactly: it decodes
      // every key at parse time (any undecodable key anywhere answers
      // E_INVALID_KEY), then validates pairs in order, key before n.
      bool bad_utf8 = false;
      uint16_t first_err = 0;
      for (uint32_t i = 0; i < count; ++i) {
        if (pos + 6 > blen) return false;
        uint32_t n;
        uint16_t klen;
        memcpy(&n, body + pos, 4);
        memcpy(&klen, body + pos + 4, 2);
        pos += 6;
        if (klen > MAX_KEY_LEN || pos + klen > blen) return false;
        if (klen != 0 && !utf8_valid(body + pos, klen)) bad_utf8 = true;
        if (first_err == 0) {
          if (klen == 0) first_err = E_INVALID_KEY;
          else if (n == 0) first_err = E_INVALID_N;
        }
        p.keys.emplace_back(body + pos, klen);
        p.ns.push_back((int64_t)n);
        pos += klen;
      }
      if (pos != blen) return false;
      if (s->draining.load()) {
        conn_send(s, c, make_error(req_id, E_STORAGE_UNAVAILABLE,
                                   "server is shutting down"));
      } else if (bad_utf8 || first_err == E_INVALID_KEY) {
        conn_send(s, c, make_error(req_id, E_INVALID_KEY,
                                   "key must be a non-empty UTF-8 string"));
      } else if (first_err == E_INVALID_N) {
        conn_send(s, c, make_error(req_id, E_INVALID_N,
                                   "n must be a positive integer"));
      } else if (s->num_shards == 1 || p.keys.empty()) {
        // count==0 frames are valid (empty RESULT_BATCH): route whole to
        // shard 0 — the mixed-shard splitter below indexes keys[0].
        size_t nk = p.keys.size();
        enqueue(std::move(p), nk, 0);
      } else {
        // Route each key to its shard. Single-shard frames go whole;
        // mixed frames split into per-shard slices joined for the one
        // response (BatchJoin).
        std::vector<uint32_t> shards_of(p.keys.size());
        uint32_t first_shard = key_shard(s, p.keys[0]);
        bool mixed = false;
        shards_of[0] = first_shard;
        for (size_t i = 1; i < p.keys.size(); ++i) {
          shards_of[i] = key_shard(s, p.keys[i]);
          mixed |= shards_of[i] != first_shard;
        }
        if (!mixed) {
          size_t nk = p.keys.size();
          enqueue(std::move(p), nk, first_shard);
        } else {
          std::vector<std::vector<uint32_t>> per(s->num_shards);
          for (size_t i = 0; i < p.keys.size(); ++i)
            per[shards_of[i]].push_back((uint32_t)i);
          uint32_t involved = 0;
          for (auto& v : per) involved += !v.empty();
          JoinPtr j = std::make_shared<BatchJoin>(
              involved, c, req_id, (uint32_t)p.keys.size());
          for (uint32_t sh = 0; sh < s->num_shards; ++sh) {
            if (per[sh].empty()) continue;
            Pending part{c, req_id, true, {}, {}};
            part.t_io = p.t_io;
            part.trace_id = p.trace_id;
            part.deadline_ns = p.deadline_ns;
            part.fwd = p.fwd;
            part.join = j;
            part.pos = std::move(per[sh]);
            part.keys.reserve(part.pos.size());
            part.ns.reserve(part.pos.size());
            for (uint32_t at : part.pos) {
              part.keys.push_back(std::move(p.keys[at]));
              part.ns.push_back(p.ns[at]);
            }
            size_t nk = part.keys.size();
            enqueue(std::move(part), nk, sh);
          }
        }
      }
    } else if (type == T_ALLOW_HASHED) {
      // Zero-copy bulk lane (ADR-011): columnar u64 ids + u32 ns. The
      // splitmix64 finalizer runs HERE (io thread, GIL-free) so the
      // dispatcher's launch hands Python ready-made hashes.
      if (blen < 4) return false;
      uint32_t count;
      memcpy(&count, body, 4);
      if (count > (blen - 4) / 12 || blen != 4 + 12ull * count)
        return false;
      if (!s->hashed_enabled) {
        conn_send(s, c, make_error(req_id, E_INVALID_CONFIG,
                                   "the hashed bulk lane requires a "
                                   "sketch-family backend"));
      } else if (s->draining.load()) {
        conn_send(s, c, make_error(req_id, E_STORAGE_UNAVAILABLE,
                                   "server is shutting down"));
      } else {
        const char* idp = body + 4;
        const char* npp = body + 4 + 8ull * count;
        bool bad_n = false;
        Pending p{c, req_id, true, {}, {}};
        p.t_io = mono_ns();
        p.trace_id = trace_id;
        p.deadline_ns = deadline_ns;
        p.fwd = fwd_hint;
        p.hashed = true;
        p.ids.reserve(count);
        p.ns.reserve(count);
        for (uint32_t i = 0; i < count; ++i) {
          uint64_t raw;
          uint32_t n;
          memcpy(&raw, idp + 8ull * i, 8);
          memcpy(&n, npp + 4ull * i, 4);
          if (n == 0) bad_n = true;
          p.ids.push_back(splitmix64(raw));
          p.ns.push_back((int64_t)n);
        }
        if (bad_n) {
          conn_send(s, c, make_error(req_id, E_INVALID_N,
                                     "n must be a positive integer"));
        } else if (s->num_shards == 1 || count == 0) {
          enqueue(std::move(p), count, 0);
        } else {
          // Per-id shard routing on the FINALIZED hash (well mixed);
          // Python mirror: NativeRateLimitServer.shard_of_id.
          std::vector<uint32_t> shards_of(count);
          uint32_t first_shard = (uint32_t)(p.ids[0] % s->num_shards);
          bool mixed = false;
          shards_of[0] = first_shard;
          for (uint32_t i = 1; i < count; ++i) {
            shards_of[i] = (uint32_t)(p.ids[i] % s->num_shards);
            mixed |= shards_of[i] != first_shard;
          }
          if (!mixed) {
            enqueue(std::move(p), count, first_shard);
          } else {
            std::vector<std::vector<uint32_t>> per(s->num_shards);
            for (uint32_t i = 0; i < count; ++i)
              per[shards_of[i]].push_back(i);
            uint32_t involved = 0;
            for (auto& v : per) involved += !v.empty();
            JoinPtr j = std::make_shared<BatchJoin>(involved, c, req_id,
                                                    count);
            j->hashed = true;
            for (uint32_t sh = 0; sh < s->num_shards; ++sh) {
              if (per[sh].empty()) continue;
              Pending part{c, req_id, true, {}, {}};
              part.t_io = p.t_io;
              part.trace_id = p.trace_id;
              part.deadline_ns = p.deadline_ns;
              part.fwd = p.fwd;
              part.hashed = true;
              part.join = j;
              part.pos = std::move(per[sh]);
              part.ids.reserve(part.pos.size());
              part.ns.reserve(part.pos.size());
              for (uint32_t at : part.pos) {
                part.ids.push_back(p.ids[at]);
                part.ns.push_back(p.ns[at]);
              }
              size_t nk = part.ids.size();
              enqueue(std::move(part), nk, sh);
            }
          }
        }
      }
    } else if (type == T_RESET) {
      if (blen < 2) return false;
      uint16_t klen;
      memcpy(&klen, body, 2);
      if (blen != 2u + klen || klen > MAX_KEY_LEN) return false;
      if (klen == 0 || !utf8_valid(body + 2, klen)) {
        conn_send(s, c, make_error(req_id, E_INVALID_KEY,
                                   "key must be a non-empty UTF-8 string"));
      } else {
        std::string key(body + 2, klen);
        uint32_t shard = key_shard(s, key);
        Pending p{c, req_id, false, {std::move(key)}, {-1}};
        enqueue(std::move(p), 0, shard);
      }
    } else if (type == T_HEALTH) {
      std::string out;
      frame_header(out, T_HEALTH_R, req_id, 17);
      out.push_back(s->draining.load() ? 0 : 1);
      put_f64(out, now_s() - s->started_at);
      uint64_t d = s->decisions.load();
      out.append((char*)&d, 8);
      conn_send(s, c, std::move(out));
    } else if (type == T_METRICS) {
      Pending p{c, req_id, false, {std::string()}, {-2}};
      enqueue(std::move(p), 0, 0);
    } else if (type == T_DCN_PUSH) {
      if (c->dcn_big) {
        // Whole frame in hand: release the slab-sized buffer grant.
        c->dcn_big = false;
        s->dcn_conns.fetch_sub(1);
      }
      if (!s->dcn_enabled) {
        conn_send(s, c, make_error(req_id, E_INVALID_CONFIG,
                                   "DCN exchange not enabled on this server"));
      } else if (s->draining.load()) {
        conn_send(s, c, make_error(req_id, E_STORAGE_UNAVAILABLE,
                                   "server is shutting down"));
      } else {
        Pending p{c, req_id, false, {std::string(body, blen)}, {-3}};
        enqueue(std::move(p), 0, 0);
      }
    } else {
      conn_send(s, c, make_error(req_id, E_INTERNAL, "unknown request type"));
    }
  }
  if (off) c->rbuf.erase(0, off);
  return true;
}

// Adopt an accepted socket onto this ring (ring thread only).
void ring_adopt(Server* s, IoRing* r, int cfd) {
  (void)s;
  auto c = std::make_shared<Conn>();
  c->fd = cfd;
  c->ring = r;
  r->conns[cfd] = c;
  r->engine->add(cfd, false);
}

// Per-connection fairness budget (ISSUE-20 satellite): the read drain
// still runs until EAGAIN, but one firehose connection may consume at
// most this many bytes per wakeup — the engine's level-triggered wait
// re-reports the fd immediately, AFTER every other ready connection on
// the ring got its turn.
constexpr size_t FAIR_READ_BUDGET = 1ul << 19;  // 512 KiB / conn / wakeup

// Adopt handed-over fds and flush reply-dirty conns. Runs at the top
// of every ring loop pass AND on an eventfd wakeup, so producers only
// pay the eventfd syscall when the ring is parked (IoRing::sleeping).
void ring_drain_pending(Server* s, IoRing* r) {
  std::vector<int> inbox;
  std::vector<ConnPtr> dirty;
  {
    std::lock_guard<std::mutex> g(r->imx);
    inbox.swap(r->inbox);
    dirty.swap(r->dirty);
  }
  for (int cfd : inbox) ring_adopt(s, r, cfd);
  // Flush exactly the conns with queued replies: the dirty flag
  // clears BEFORE the flush so a racing conn_send re-queues.
  for (auto& c : dirty) {
    c->dirty.store(false);
    if (!c->closed.load()) flush_writes(s, c);
  }
}

void ring_main(Server* s, IoRing* r) {
  std::vector<NetEvent> events(128);
  char buf[65536];
  while (!s->stop.load()) {
    ring_drain_pending(s, r);
    // Park only when no work arrived during the drain (Dekker with the
    // producers: sleeping is set BEFORE the emptiness re-check; a
    // producer pushes BEFORE it loads sleeping — one of the two always
    // sees the other).
    r->sleeping.store(true);
    bool pending;
    {
      std::lock_guard<std::mutex> g(r->imx);
      pending = !r->inbox.empty() || !r->dirty.empty();
    }
    if (pending || s->stop.load()) {
      r->sleeping.store(false);
      if (s->stop.load()) break;
      continue;
    }
    int n = r->engine->wait(events.data(), (int)events.size(), 100);
    r->sleeping.store(false);
    r->wait_calls.fetch_add(1, std::memory_order_relaxed);
    for (int i = 0; i < n; ++i) {
      int fd = events[i].fd;
      if (fd == s->listen_fd && r->idx == 0) {
        // Ring 0 owns the listener; connections are pinned to rings
        // round-robin by accept order (ISSUE-20). Foreign fds travel
        // through the target ring's inbox + eventfd ding so each
        // ring's conn map stays single-threaded.
        while (true) {
          int cfd = accept4(s->listen_fd, nullptr, nullptr, SOCK_NONBLOCK);
          if (cfd < 0) break;
          if (s->uds) {
            s->conns_uds.fetch_add(1);
          } else {
            int one = 1;
            setsockopt(cfd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
            s->conns_tcp.fetch_add(1);
          }
          uint32_t k =
              (uint32_t)(s->accept_ctr.fetch_add(1) % s->rings.size());
          if (k == r->idx) {
            ring_adopt(s, r, cfd);
          } else {
            IoRing* t = s->rings[k].get();
            {
              std::lock_guard<std::mutex> g(t->imx);
              t->inbox.push_back(cfd);
            }
            if (t->sleeping.exchange(false)) ding_efd(t->event_fd);
          }
        }
      } else if (fd == r->event_fd) {
        uint64_t drain;
        ssize_t rr = read(r->event_fd, &drain, 8);
        (void)rr;
        ring_drain_pending(s, r);
      } else {
        // Shm lane fds first: the one-shot control listener and, after
        // the handshake, the request doorbell (ADR-025).
        auto sit = r->shm_fds.find(fd);
        if (sit != r->shm_fds.end()) {
          ConnPtr sc = sit->second;
          if (sc->shm && fd == sc->shm->ctrl_listen_fd)
            shm_ctrl_accept(s, sc);
          else if (sc->shm)
            shm_drain(s, sc);
          continue;
        }
        auto it = r->conns.find(fd);
        if (it == r->conns.end()) continue;
        ConnPtr c = it->second;
        if (events[i].err) {
          close_conn(s, c);
          continue;
        }
        if (events[i].rd) {
          // Backpressure bound on unparsed bytes. The slab-sized cap
          // (up to MAX_DCN_FRAME — the same buffering the asyncio door
          // accepts via readexactly) is PER-CONNECTION GRANTED, not
          // blanket: process_rbuf issues the grant only after the
          // pre-screen (DCN frame header + RLA magic when auth is
          // required, bounded concurrent holders) — an oversized
          // garbage stream dies at the 4 MiB bound (ADVICE r5).
          const size_t small_cap = 4ul * MAX_FRAME;
          const size_t big_cap = 4ul + MAX_DCN_FRAME + 4ul * MAX_FRAME;
          bool dead = false;
          size_t budget = FAIR_READ_BUDGET;
          while (true) {
            ssize_t rd = recv(fd, buf, sizeof(buf), 0);
            r->recv_calls.fetch_add(1, std::memory_order_relaxed);
            if (rd > 0) {
              c->rbuf.append(buf, (size_t)rd);
              if (c->rbuf.size() > (c->dcn_big ? big_cap : small_cap)) {
                // May be a legal DCN push outgrowing the small cap:
                // parse what is buffered (grants dcn_big when the
                // pre-screen passes), then re-check.
                if (!process_rbuf(s, c)) { dead = true; break; }
                if (c->rbuf.size() > (c->dcn_big ? big_cap : small_cap)) {
                  dead = true;
                  break;
                }
              }
              budget -= (budget < (size_t)rd) ? budget : (size_t)rd;
              if (budget == 0) break;  // fairness cut: wait re-reports
              // Short read = the kernel handed over everything it had
              // buffered; skip the EAGAIN probe that would otherwise
              // end every drain cycle (halves recv syscalls at high
              // conn counts — bytes landing after this instant re-arm
              // the level-triggered wait). The no-coalesce bench
              // baseline keeps the probe: pre-ISSUE-20 profile.
              if (s->net_coalesce && (size_t)rd < sizeof(buf)) break;
            } else if (rd == 0) {
              dead = true;
              break;
            } else {
              if (errno == EAGAIN || errno == EWOULDBLOCK) break;
              dead = true;
              break;
            }
          }
          if (!dead && !process_rbuf(s, c)) dead = true;
          if (dead) {
            close_conn(s, c);
            continue;
          }
        }
        if (events[i].wr) flush_writes(s, c);
      }
    }
  }
  // Teardown: close everything (pending writes were flushed by drain).
  for (auto& kv : std::map<int, ConnPtr>(r->conns)) close_conn(s, kv.second);
}

// ---- Python object -------------------------------------------------------

struct PyServer {
  PyObject_HEAD
  Server* s;
};

PyObject* server_start(PyObject* self, PyObject* args) {
  PyServer* ps = (PyServer*)self;
  Server* s = ps->s;
  const char* host;
  int port;
  if (!PyArg_ParseTuple(args, "si", &host, &port)) return nullptr;

  if (strncmp(host, "unix:", 5) == 0) {
    // UDS listener (ADR-025 transport ladder): host is "unix:/path".
    const char* upath = host + 5;
    struct sockaddr_un sun{};
    if (strlen(upath) >= sizeof(sun.sun_path)) {
      PyErr_SetString(PyExc_ValueError, "unix socket path too long");
      return nullptr;
    }
    s->uds = true;
    s->uds_path = upath;
    s->listen_fd = socket(AF_UNIX, SOCK_STREAM | SOCK_NONBLOCK, 0);
    sun.sun_family = AF_UNIX;
    memcpy(sun.sun_path, upath, strlen(upath) + 1);
    unlink(upath);  // stale socket from a previous run
    if (bind(s->listen_fd, (struct sockaddr*)&sun, sizeof(sun)) != 0 ||
        listen(s->listen_fd, 512) != 0) {
      PyErr_SetFromErrno(PyExc_OSError);
      return nullptr;
    }
    s->port = 0;
  } else {
    s->listen_fd = socket(AF_INET, SOCK_STREAM | SOCK_NONBLOCK, 0);
    int one = 1;
    setsockopt(s->listen_fd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
    struct sockaddr_in addr{};
    addr.sin_family = AF_INET;
    addr.sin_port = htons((uint16_t)port);
    inet_pton(AF_INET, host, &addr.sin_addr);
    if (bind(s->listen_fd, (struct sockaddr*)&addr, sizeof(addr)) != 0 ||
        listen(s->listen_fd, 512) != 0) {
      PyErr_SetFromErrno(PyExc_OSError);
      return nullptr;
    }
    socklen_t alen = sizeof(addr);
    getsockname(s->listen_fd, (struct sockaddr*)&addr, &alen);
    s->port = ntohs(addr.sin_port);
  }

  // Network engine resolution (ISSUE-20, ADR-026): ring count, then the
  // io_uring startup probe. The probe runs for auto AND for an explicit
  // uring request — a refusing kernel (seccomp, CONFIG_IO_URING off)
  // downgrades to epoll with the reason recorded in stats()/healthz,
  // never a hard failure, so parity tests can always start the server
  // and assert the probe-miss record instead of silently skipping.
  if (s->io_rings == 0) {
    unsigned hc = std::thread::hardware_concurrency();
    s->io_rings = hc == 0 ? 1 : (hc < 4 ? hc : 4);
  }
  if (s->io_rings > 64) s->io_rings = 64;
  {
    const char* nc = getenv("RL_NET_COALESCE");
    s->net_coalesce = !(nc != nullptr && nc[0] == '0');
  }
  s->uring_active = false;
  s->uring_probe_err.clear();
  if (s->net_engine_req != 1) {
    s->uring_active = uring_probe(s->uring_probe_err);
  }
  s->rings.clear();
  for (uint32_t i = 0; i < s->io_rings; ++i) {
    auto ring = std::make_unique<IoRing>();
    ring->idx = i;
    ring->event_fd = eventfd(0, EFD_NONBLOCK);
    if (s->uring_active) {
      auto u = std::make_unique<UringEngine>(1024);
      if (u->ok()) {
        ring->engine = std::move(u);
      } else {
        // Probe passed but this ring's setup failed (fd/memlock
        // limits): record and fall back — every ring must serve.
        s->uring_probe_err = u->error();
        s->uring_active = false;
      }
    }
    if (!ring->engine) ring->engine = std::make_unique<EpollEngine>();
    ring->engine->add(ring->event_fd, false);
    if (i == 0) ring->engine->add(s->listen_fd, false);
    s->rings.push_back(std::move(ring));
  }

  s->started_at = now_s();
  s->shardqs.clear();
  for (uint32_t i = 0; i < s->num_shards; ++i)
    s->shardqs.push_back(std::make_unique<Server::ShardQ>());
  // Pipelined mode needs both callbacks and no SLO (the watcher's
  // single-deadline contract assumes one dispatch in flight).
  s->pipelined = s->cb_launch != nullptr && s->cb_launch != Py_None &&
                 s->cb_resolve != nullptr && s->cb_resolve != Py_None &&
                 s->slo_us == 0 && s->inflight_window > 1;
  s->pipeqs.clear();
  if (s->pipelined)
    for (uint32_t i = 0; i < s->num_shards; ++i)
      s->pipeqs.push_back(std::make_unique<Server::PipeQ>());
  for (auto& ring : s->rings)
    ring->thread = std::thread(ring_main, s, ring.get());
  for (uint32_t i = 0; i < s->num_shards; ++i)
    s->dispatch_threads.emplace_back(dispatcher_main, s, i);
  if (s->pipelined)
    for (uint32_t i = 0; i < s->num_shards; ++i)
      s->completer_threads.emplace_back(completer_main, s, i);
  if (s->slo_us > 0) s->slo_thread = std::thread(slo_main, s);
  else s->resp_thread = std::thread(responder_main, s);
  return PyLong_FromLong(s->port);
}

PyObject* server_shutdown(PyObject* self, PyObject* Py_UNUSED(ignored)) {
  PyServer* ps = (PyServer*)self;
  Server* s = ps->s;
  if (s->listen_fd >= 0) {
    // Graceful: stop new work, let the dispatchers drain their queues.
    s->draining.store(true);
    Py_BEGIN_ALLOW_THREADS;
    for (int i = 0; i < 200; ++i) {  // up to ~2 s of drain
      bool empty = true;
      for (auto& q : s->shardqs) {
        std::lock_guard<std::mutex> g(q->qmx);
        empty = empty && q->queue.empty();
      }
      if (empty) break;
      usleep(10000);
    }
    // Let the completers resolve every in-flight ticket (pipelined
    // mode) — an unresolved launch is an unanswered client. A ticket a
    // completer has swapped out for its batched drain counts too
    // (`resolving`): the queue alone looks empty mid-batch. Read both
    // under the queue's lock — the completer's swap and its
    // resolving increment happen atomically under that lock, so an
    // empty queue observed here implies any swapped batch is already
    // counted (checking the counter before the lock could miss the
    // transition and proceed mid-resolve).
    for (int i = 0; i < 200; ++i) {
      bool empty = true;
      for (auto& pq : s->pipeqs) {
        std::lock_guard<std::mutex> g(pq->mx);
        empty = empty && pq->entries.empty() && pq->resolving == 0;
      }
      if (empty) break;
      usleep(10000);
    }
    // Let the responder drain queued replies before stopping.
    for (int i = 0; i < 200; ++i) {
      {
        std::lock_guard<std::mutex> g(s->rmx);
        if (s->rqueue.empty()) break;
      }
      usleep(10000);
    }
    usleep(20000);  // let final responses flush
    s->stop.store(true);
    for (auto& q : s->shardqs) q->qcv.notify_all();
    for (auto& pq : s->pipeqs) {
      pq->cv_items.notify_all();
      pq->cv_space.notify_all();
    }
    s->ifcv.notify_all();
    s->rcv.notify_all();
    for (auto& ring : s->rings) ding_efd(ring->event_fd);
    for (auto& ring : s->rings)
      if (ring->thread.joinable()) ring->thread.join();
    for (auto& t : s->dispatch_threads)
      if (t.joinable()) t.join();
    s->dispatch_threads.clear();
    for (auto& t : s->completer_threads)
      if (t.joinable()) t.join();
    s->completer_threads.clear();
    if (s->slo_thread.joinable()) s->slo_thread.join();
    if (s->resp_thread.joinable()) s->resp_thread.join();
    Py_END_ALLOW_THREADS;
    close(s->listen_fd);
    for (auto& ring : s->rings) {
      if (ring->event_fd >= 0) close(ring->event_fd);
      ring->event_fd = -1;
      ring->engine.reset();  // closes the epoll/uring fd
    }
    s->listen_fd = -1;
    if (s->uds && !s->uds_path.empty()) unlink(s->uds_path.c_str());
  }
  Py_RETURN_NONE;
}

PyObject* server_stats(PyObject* self, PyObject* Py_UNUSED(ignored)) {
  PyServer* ps = (PyServer*)self;
  size_t depth = 0;
  for (auto& pq : ps->s->pipeqs) {
    std::lock_guard<std::mutex> g(pq->mx);
    // Queued plus swapped out for the completer's batched drain — both
    // are launched-but-unresolved.
    depth += pq->entries.size() + (size_t)pq->resolving;
  }
  PyObject* per_shard = PyList_New(ps->s->num_shards);
  if (per_shard == nullptr) return nullptr;
  for (uint32_t i = 0; i < ps->s->num_shards; ++i) {
    PyObject* v = PyLong_FromUnsignedLongLong(
        (unsigned long long)ps->s->shard_decisions[i].load());
    if (v == nullptr) {
      Py_DECREF(per_shard);
      return nullptr;
    }
    PyList_SET_ITEM(per_shard, i, v);
  }
  // Per-shard quarantine state (ABI 10, ADR-015).
  PyObject* per_quar = PyList_New(ps->s->num_shards);
  if (per_quar == nullptr) {
    Py_DECREF(per_shard);
    return nullptr;
  }
  for (uint32_t i = 0; i < ps->s->num_shards; ++i) {
    PyObject* v =
        PyLong_FromLong((long)ps->s->shard_quarantined[i].load());
    if (v == nullptr) {
      Py_DECREF(per_shard);
      Py_DECREF(per_quar);
      return nullptr;
    }
    PyList_SET_ITEM(per_quar, i, v);
  }
  // Cumulative per-stage wall time (ABI 9, ADR-014): ns each pipeline
  // stage has consumed across batched dispatches, plus the dispatch
  // count — enough to derive mean per-stage cost without any Python
  // callback in the loop.
  PyObject* stage_ns = Py_BuildValue(
      "{s:K,s:K,s:K,s:K,s:K,s:K}",
      "io", (unsigned long long)ps->s->stage_io_ns.load(),
      "dispatch", (unsigned long long)ps->s->stage_dispatch_ns.load(),
      "device", (unsigned long long)ps->s->stage_device_ns.load(),
      "complete", (unsigned long long)ps->s->stage_complete_ns.load(),
      "respond", (unsigned long long)ps->s->stage_respond_ns.load(),
      "batches", (unsigned long long)ps->s->stage_batches.load());
  if (stage_ns == nullptr) {
    Py_DECREF(per_shard);
    Py_DECREF(per_quar);
    return nullptr;
  }
  // Per-transport accepts + shm lane counters (ADR-025): the same
  // shape the asyncio door's transport_stats() reports, so the metrics
  // collect hook and bench tooling read one schema from either door.
  PyObject* transport = Py_BuildValue(
      "{s:K,s:K,s:K}",
      "tcp", (unsigned long long)ps->s->conns_tcp.load(),
      "uds", (unsigned long long)ps->s->conns_uds.load(),
      "shm", (unsigned long long)ps->s->conns_shm.load());
  PyObject* shm_stats = Py_BuildValue(
      "{s:K,s:K,s:K,s:K,s:K,s:K,s:K,s:K}",
      "lanes_active", (unsigned long long)ps->s->shm_lanes_active.load(),
      "doorbell_wakes",
      (unsigned long long)ps->s->shm_doorbell_wakes.load(),
      "spin_hits", (unsigned long long)ps->s->shm_spin_hits.load(),
      "ring_full_stalls",
      (unsigned long long)ps->s->shm_ring_full_stalls.load(),
      "records_in", (unsigned long long)ps->s->shm_records_in.load(),
      "records_out", (unsigned long long)ps->s->shm_records_out.load(),
      "req_ring_highwater_bytes",
      (unsigned long long)ps->s->shm_req_highwater.load(),
      "rep_ring_highwater_bytes",
      (unsigned long long)ps->s->shm_rep_highwater.load());
  // Network-engine ledger (ISSUE-20, ADR-026): which backend the probe
  // selected, the ring count, and the engine-maintained syscall
  // counters — the numerator of syscalls-per-decision. uring_probe is
  // "pass" / "fail" / "off" (off = --net-engine epoll skipped it);
  // uring_probe_err carries the recorded downgrade reason.
  uint64_t net_recv = 0, net_writev = 0, net_wait = 0, net_wake = 0,
           net_wframes = 0;
  for (auto& ring : ps->s->rings) {
    net_recv += ring->recv_calls.load();
    net_writev += ring->writev_calls.load();
    net_wait += ring->wait_calls.load();
    net_wake += ring->wake_calls.load();
    net_wframes += ring->writev_frames.load();
  }
  PyObject* net = Py_BuildValue(
      "{s:s,s:I,s:s,s:s,s:K,s:K,s:K,s:K,s:K}",
      "engine", ps->s->uring_active ? "uring" : "epoll",
      "rings", (unsigned int)ps->s->rings.size(),
      "uring_probe",
      ps->s->net_engine_req == 1 ? "off"
                                 : (ps->s->uring_active ? "pass" : "fail"),
      "uring_probe_err", ps->s->uring_probe_err.c_str(),
      "recv_calls", (unsigned long long)net_recv,
      "writev_calls", (unsigned long long)net_writev,
      "wait_calls", (unsigned long long)net_wait,
      "wake_calls", (unsigned long long)net_wake,
      "writev_frames", (unsigned long long)net_wframes);
  if (transport == nullptr || shm_stats == nullptr || net == nullptr) {
    Py_DECREF(per_shard);
    Py_DECREF(per_quar);
    Py_DECREF(stage_ns);
    Py_XDECREF(transport);
    Py_XDECREF(shm_stats);
    Py_XDECREF(net);
    return nullptr;
  }
  PyObject* out = Py_BuildValue(
      "{s:K,s:K,s:K,s:d,s:K,s:I,s:O,s:I,s:O,s:O,s:O,s:O,s:O,s:O}",
      "decisions_total",
      (unsigned long long)ps->s->decisions.load(), "slo_breaches_total",
      (unsigned long long)ps->s->slo_breaches.load(),
      // Deadline shedding (ABI 10, ADR-015).
      "deadline_shed_total",
      (unsigned long long)ps->s->deadline_shed.load(), "uptime_s",
      now_s() - ps->s->started_at, "inflight_depth",
      (unsigned long long)depth, "inflight_window", ps->s->inflight_window,
      "pipelined", ps->s->pipelined ? Py_True : Py_False,
      // Shard routing observability (mesh mode: one shard == one
      // device, so this is the per-device decision balance, ADR-012).
      "num_shards", ps->s->num_shards, "shard_decisions", per_shard,
      "shard_quarantined", per_quar, "stage_ns", stage_ns,
      "transport", transport, "shm", shm_stats, "net", net);
  Py_DECREF(per_shard);  // Py_BuildValue "O" took its own reference
  Py_DECREF(per_quar);
  Py_DECREF(stage_ns);
  Py_DECREF(transport);
  Py_DECREF(shm_stats);
  Py_DECREF(net);
  return out;
}

PyObject* server_set_shard_health(PyObject* self, PyObject* args) {
  // Quarantine-state push (ABI 10, ADR-015): the Python quarantine
  // manager's on_state_change mirrors each slice's health here so the
  // C++ door's stats() reports the degraded topology (0 = healthy,
  // 1 = out of routing).
  PyServer* ps = (PyServer*)self;
  unsigned int shard;
  int quarantined;
  if (!PyArg_ParseTuple(args, "Ip", &shard, &quarantined)) return nullptr;
  if (shard >= ps->s->num_shards) {
    PyErr_SetString(PyExc_ValueError, "shard out of range");
    return nullptr;
  }
  ps->s->shard_quarantined[shard].store(quarantined ? 1u : 0u);
  Py_RETURN_NONE;
}

PyObject* server_set_limits(PyObject* self, PyObject* args) {
  // Python push for the fail-open stamp fields (update_limit /
  // update_window on the bridge): responses stamped WITHOUT a completed
  // dispatch must carry the live limit.
  PyServer* ps = (PyServer*)self;
  long long limit;
  double window_s;
  if (!PyArg_ParseTuple(args, "Ld", &limit, &window_s)) return nullptr;
  {
    std::lock_guard<std::mutex> g(ps->s->limit_mx);
    ps->s->limit.store((int64_t)limit);
    ps->s->window_s.store(window_s);
    // Invalidate the per-batch refresh of every dispatch already
    // started: their limit predates this push.
    ps->s->limit_epoch.fetch_add(1);
  }
  Py_RETURN_NONE;
}

void server_dealloc(PyObject* self) {
  PyServer* ps = (PyServer*)self;
  if (ps->s != nullptr) {
    if (ps->s->listen_fd >= 0) {
      ps->s->stop.store(true);
      for (auto& q : ps->s->shardqs) q->qcv.notify_all();
      for (auto& pq : ps->s->pipeqs) {
        pq->cv_items.notify_all();
        pq->cv_space.notify_all();
      }
      ps->s->ifcv.notify_all();
      ps->s->rcv.notify_all();
      for (auto& ring : ps->s->rings) ding_efd(ring->event_fd);
      // The dispatcher may be blocked in PyGILState_Ensure for a decide;
      // joining while holding the GIL would deadlock.
      Py_BEGIN_ALLOW_THREADS;
      for (auto& ring : ps->s->rings)
        if (ring->thread.joinable()) ring->thread.join();
      for (auto& t : ps->s->dispatch_threads)
        if (t.joinable()) t.join();
      ps->s->dispatch_threads.clear();
      for (auto& t : ps->s->completer_threads)
        if (t.joinable()) t.join();
      ps->s->completer_threads.clear();
      if (ps->s->slo_thread.joinable()) ps->s->slo_thread.join();
      if (ps->s->resp_thread.joinable()) ps->s->resp_thread.join();
      Py_END_ALLOW_THREADS;
      close(ps->s->listen_fd);
      for (auto& ring : ps->s->rings) {
        if (ring->event_fd >= 0) close(ring->event_fd);
        ring->event_fd = -1;
        ring->engine.reset();
      }
    }
    Py_XDECREF(ps->s->cb_decide);
    Py_XDECREF(ps->s->cb_reset);
    Py_XDECREF(ps->s->cb_metrics);
    Py_XDECREF(ps->s->cb_dcn);
    Py_XDECREF(ps->s->cb_launch);
    Py_XDECREF(ps->s->cb_resolve);
    Py_XDECREF(ps->s->cb_decide_hashed);
    Py_XDECREF(ps->s->cb_launch_hashed);
    Py_XDECREF(ps->s->cb_spans);
    delete ps->s;
  }
  Py_TYPE(self)->tp_free(self);
}

PyMethodDef server_methods[] = {
    {"start", server_start, METH_VARARGS, "start(host, port) -> bound port"},
    {"shutdown", server_shutdown, METH_NOARGS, "graceful drain + stop"},
    {"stats", server_stats, METH_NOARGS,
     "{decisions_total, uptime_s, inflight_depth, ...}"},
    {"set_limits", server_set_limits, METH_VARARGS,
     "set_limits(limit, window_s): refresh the fail-open stamp fields"},
    {"set_shard_health", server_set_shard_health, METH_VARARGS,
     "set_shard_health(shard, quarantined): mirror quarantine state"},
    {nullptr, nullptr, 0, nullptr},
};

PyTypeObject PyServerType = {
    PyVarObject_HEAD_INIT(nullptr, 0)
};

PyObject* create_server(PyObject* Py_UNUSED(mod), PyObject* args,
                        PyObject* kwargs) {
  static const char* kwlist[] = {"decide",    "reset",        "metrics",
                                 "max_batch", "max_delay_us", "slo_us",
                                 "fail_open", "limit",        "window_s",
                                 "key_prefix", "num_shards",  "dcn",
                                 "launch",    "resolve",      "inflight",
                                 "dcn_auth_required", "max_dcn_conns",
                                 "decide_hashed", "launch_hashed",
                                 "spans",
                                 "shm", "shm_dir", "shm_ring_bytes",
                                 "net_engine", "io_rings",
                                 nullptr};
  PyObject *decide, *reset, *metrics = Py_None, *dcn = Py_None;
  PyObject *launch = Py_None, *resolve = Py_None;
  PyObject *decide_hashed = Py_None, *launch_hashed = Py_None;
  PyObject *spans = Py_None;
  unsigned int max_batch = 4096, max_delay_us = 200, slo_us = 0;
  int fail_open = 0;
  long long limit = 0;
  double window_s = 60.0;
  const char* key_prefix = nullptr;
  Py_ssize_t key_prefix_len = 0;
  unsigned int num_shards = 1, inflight = 8, max_dcn_conns = 4;
  int dcn_auth_required = 0;
  int shm = 0;
  const char* shm_dir = nullptr;
  unsigned int shm_ring_bytes = 0;
  const char* net_engine = nullptr;
  unsigned int io_rings = 0;
  if (!PyArg_ParseTupleAndKeywords(args, kwargs, "OO|OIIIpLdy#IOOOIpIOOOpsIsI",
                                   (char**)kwlist,
                                   &decide, &reset, &metrics, &max_batch,
                                   &max_delay_us, &slo_us, &fail_open, &limit,
                                   &window_s, &key_prefix, &key_prefix_len,
                                   &num_shards, &dcn, &launch, &resolve,
                                   &inflight, &dcn_auth_required,
                                   &max_dcn_conns, &decide_hashed,
                                   &launch_hashed, &spans, &shm, &shm_dir,
                                   &shm_ring_bytes, &net_engine, &io_rings))
    return nullptr;
  uint32_t net_engine_req = 0;  // auto
  if (net_engine != nullptr && net_engine[0] != '\0') {
    if (strcmp(net_engine, "auto") == 0) net_engine_req = 0;
    else if (strcmp(net_engine, "epoll") == 0) net_engine_req = 1;
    else if (strcmp(net_engine, "uring") == 0) net_engine_req = 2;
    else {
      PyErr_SetString(PyExc_ValueError,
                      "net_engine must be 'auto', 'epoll' or 'uring'");
      return nullptr;
    }
  }
  if (num_shards < 1 || num_shards > 64) {
    PyErr_SetString(PyExc_ValueError, "num_shards must be in [1, 64]");
    return nullptr;
  }
  if (num_shards > 1 && slo_us > 0) {
    PyErr_SetString(PyExc_ValueError,
                    "dispatch_timeout (SLO) requires num_shards == 1");
    return nullptr;
  }
  PyServer* ps = PyObject_New(PyServer, &PyServerType);
  if (ps == nullptr) return nullptr;
  ps->s = new Server();
  ps->s->max_batch = max_batch;
  ps->s->max_delay_us = max_delay_us;
  ps->s->slo_us = slo_us;
  ps->s->fail_open = fail_open != 0;
  ps->s->limit.store((int64_t)limit);
  ps->s->window_s.store(window_s);
  ps->s->num_shards = num_shards;
  ps->s->inflight_window = inflight < 1 ? 1 : inflight;
  ps->s->dcn_auth_required = dcn_auth_required != 0;
  ps->s->max_dcn_conns = max_dcn_conns;
  ps->s->shm_enabled = shm != 0;
  if (shm_dir != nullptr && shm_dir[0] != '\0') ps->s->shm_dir = shm_dir;
  ps->s->shm_ring_bytes = shm_ring_bytes;
  ps->s->net_engine_req = net_engine_req;
  ps->s->io_rings = io_rings;
  if (key_prefix != nullptr && key_prefix_len > 0)
    ps->s->key_prefix.assign(key_prefix, (size_t)key_prefix_len);
  Py_INCREF(decide);
  Py_INCREF(reset);
  Py_INCREF(metrics);
  Py_INCREF(dcn);
  Py_INCREF(launch);
  Py_INCREF(resolve);
  Py_INCREF(decide_hashed);
  Py_INCREF(launch_hashed);
  Py_INCREF(spans);
  ps->s->cb_decide = decide;
  ps->s->cb_reset = reset;
  ps->s->cb_metrics = metrics;
  ps->s->cb_dcn = dcn;
  ps->s->cb_launch = launch;
  ps->s->cb_resolve = resolve;
  ps->s->cb_decide_hashed = decide_hashed;
  ps->s->cb_launch_hashed = launch_hashed;
  ps->s->cb_spans = spans;
  ps->s->dcn_enabled = dcn != Py_None;
  ps->s->hashed_enabled = decide_hashed != Py_None;
  ps->s->spans_enabled = spans != Py_None;
  return (PyObject*)ps;
}

PyMethodDef module_methods[] = {
    {"create_server", (PyCFunction)create_server,
     METH_VARARGS | METH_KEYWORDS,
     "create_server(decide, reset, metrics=None, max_batch=4096, "
     "max_delay_us=200) -> Server"},
    {nullptr, nullptr, 0, nullptr},
};

struct PyModuleDef server_module = {
    PyModuleDef_HEAD_INIT, "_server",
    "Native multi-ring front door for the rate-limit service", -1,
    module_methods,
};

}  // namespace

extern "C" {

// C ABI probe so the loader can verify the build (native/__init__ pattern).
int64_t rl_server_abi_version() { return 13; }

PyMODINIT_FUNC PyInit__server(void) {
  PyServerType.tp_name = "ratelimiter_tpu.native._server.Server";
  PyServerType.tp_basicsize = sizeof(PyServer);
  PyServerType.tp_dealloc = server_dealloc;
  PyServerType.tp_flags = Py_TPFLAGS_DEFAULT;
  PyServerType.tp_methods = server_methods;
  if (PyType_Ready(&PyServerType) < 0) return nullptr;
  return PyModule_Create(&server_module);
}

}  // extern "C"
