"""NumPy twin of the native hasher — bit-identical to hasher.cpp.

Used when the C extension is not built (no compiler on the host). Fully
vectorized over the batch: the per-key variable-length byte streams are
gathered into a dense (n, W) little-endian uint64 lane matrix and the
multiply-rotate rounds run column-wise, masked by each key's lane count, so
cost is O(n * max_lanes) vector ops with no Python-level per-key loop.

The algorithm contract lives in hasher.cpp; change them together (and bump
rl_hasher_abi_version).
"""

from __future__ import annotations

import numpy as np

_P1 = np.uint64(0x9E3779B185EBCA87)
_P2 = np.uint64(0xC2B2AE3D27D4EB4F)
_P3 = np.uint64(0x165667B19E3779F9)


def _rotl64(x: np.ndarray, r: int) -> np.ndarray:
    return (x << np.uint64(r)) | (x >> np.uint64(64 - r))


def _fmix64(x: np.ndarray) -> np.ndarray:
    x = x.copy()
    x ^= x >> np.uint64(30)
    x *= np.uint64(0xBF58476D1CE4E5B9)
    x ^= x >> np.uint64(27)
    x *= np.uint64(0x94D049BB133111EB)
    x ^= x >> np.uint64(31)
    return x


def hash_packed_numpy(buf: np.ndarray, offsets: np.ndarray,
                      lengths: np.ndarray, seed: int) -> np.ndarray:
    """Hash n packed byte strings; same layout contract as rl_bulk_hash_u64."""
    n = offsets.shape[0]
    if n == 0:
        return np.empty(0, dtype=np.uint64)
    if buf.shape[0] == 0:
        # All-empty keys: zero lanes, just the seeded length mix + finalizer.
        with np.errstate(over="ignore"):
            return _fmix64(np.full(n, np.uint64(seed), dtype=np.uint64))
    with np.errstate(over="ignore"):
        max_len = int(lengths.max(initial=0))
        W = max(1, -(-max_len // 8))  # lanes per key
        # Gather each key's bytes into a zero-padded (n, W*8) matrix. The
        # clip keeps indices in-bounds; the mask zeroes tail bytes.
        idx = offsets[:, None] + np.arange(W * 8, dtype=np.int64)[None, :]
        valid = idx < (offsets + lengths)[:, None]
        dense = np.where(valid, buf[np.minimum(idx, buf.shape[0] - 1)], 0)
        lanes = np.ascontiguousarray(dense, dtype=np.uint8).reshape(n, W, 8)
        lanes = lanes.view('<u8').reshape(n, W)  # little-endian lanes

        h = np.uint64(seed) ^ (lengths.astype(np.uint64) * _P1)
        n_lanes = -(-lengths // 8)  # ceil: the remainder lane is one round
        for w in range(W):
            active = w < n_lanes
            hr = _rotl64(h ^ (lanes[:, w] * _P1), 27) * _P2 + _P3
            h = np.where(active, hr, h)
        return _fmix64(h)
