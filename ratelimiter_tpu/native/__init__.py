"""Native host runtime: C++ bulk string hashing behind a ctypes seam.

The decision hot path is JAX/XLA on device; the *host* hot path is turning
string keys into u64 hashes at ingest (SURVEY.md §7.4 hard part #4). The
reference pays a Redis round-trip per key so its host cost never shows; at
10M+ decisions/s ours does, so hashing is native:

* ``hasher.cpp``   — the C++ kernel, built into ``_hasher.so`` by make
                     (or automatically, once, on first import when a
                     compiler is present — exactly the role a prebuilt
                     wheel would play);
* ``fallback.py``  — bit-identical vectorized NumPy twin for hosts with no
                     compiler;
* this module      — packing (Python strings -> one contiguous byte buffer
                     + offsets/lengths) and dispatch.

pybind11 is deliberately not used (not in the image); the ABI is a C array
call through ctypes — zero copies beyond the unavoidable UTF-8 encode.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import sysconfig
import threading
from typing import Optional, Sequence, Tuple

import numpy as np

from ratelimiter_tpu.native.fallback import hash_packed_numpy

DEFAULT_SEED = 0x52_4C_54_50_55_31  # "RLTPU1"

_DIR = os.path.dirname(os.path.abspath(__file__))
_SO = os.path.join(_DIR, "_hasher.so")
_SRC = os.path.join(_DIR, "hasher.cpp")
_ABI = 2

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_mod = None  # the CPython extension module (hash_keylist lives here)
_tried = False


def _try_build() -> bool:
    """One-shot best-effort build of the extension (g++ in the image)."""
    try:
        inc = sysconfig.get_paths()["include"]
        subprocess.run(
            ["g++", "-O3", "-shared", "-fPIC", "-std=c++17", f"-I{inc}",
             "-o", _SO, _SRC],
            check=True, capture_output=True, timeout=120)
        return True
    except Exception:
        return False


def _check_abi(lib: ctypes.CDLL) -> bool:
    lib.rl_hasher_abi_version.restype = ctypes.c_int64
    return lib.rl_hasher_abi_version() == _ABI


def _load() -> Optional[ctypes.CDLL]:
    global _lib, _mod, _tried
    if _lib is not None or _tried:
        return _lib
    with _lock:
        if _lib is not None or _tried:
            return _lib
        _tried = True
        try:
            if not os.path.exists(_SO) and os.environ.get(
                    "RATELIMITER_TPU_NO_BUILD") != "1":
                _try_build()
            if not os.path.exists(_SO):
                return None
            lib = ctypes.CDLL(_SO)
            mod_path = _SO
            if not _check_abi(lib):
                # Stale binary from an older algorithm; rebuild once. dlopen
                # caches by pathname — asking for _SO again would hand back
                # the still-mapped stale object — so the fresh build is
                # copied to and loaded from a distinct per-process name.
                os.remove(_SO)
                if not _try_build():
                    return None
                import shutil

                mod_path = os.path.join(_DIR, f"_hasher_r{os.getpid()}.so")
                shutil.copy2(_SO, mod_path)
                lib = ctypes.CDLL(mod_path)
                if not _check_abi(lib):
                    return None
            lib.rl_bulk_hash_u64.restype = None
            lib.rl_bulk_hash_u64.argtypes = [
                ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
                ctypes.c_uint64, ctypes.c_void_p, ctypes.c_int64,
            ]
            # The same .so is also a CPython extension module exposing the
            # list fast path; load it from the SAME file the ctypes handle
            # came from (spec_from_file_location derives PyInit__hasher
            # from the final name component, so the temp name is fine).
            import importlib.util

            spec = importlib.util.spec_from_file_location(
                "ratelimiter_tpu.native._hasher", mod_path)
            _hasher = importlib.util.module_from_spec(spec)
            spec.loader.exec_module(_hasher)

            _mod = _hasher
            _lib = lib
        except Exception:
            _lib = None
            _mod = None
        return _lib


def native_available() -> bool:
    """True when the C extension is loaded (built or buildable here)."""
    return _load() is not None


def pack_keys(keys: Sequence[str]) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Pack strings into (buf uint8[], offsets int64[], byte_lengths int64[]).

    Fast path: one ``str.join`` + one encode for the whole batch, with
    per-key byte lengths taken from ``len`` — valid exactly when every key
    is ASCII, which the total-bytes check proves after the fact. Non-ASCII
    batches fall back to per-key encoding (correct, slower).
    """
    n = len(keys)
    if n == 0:
        return (np.empty(0, np.uint8), np.empty(0, np.int64),
                np.empty(0, np.int64))
    lengths = np.fromiter((len(k) for k in keys), dtype=np.int64, count=n)
    blob = "".join(keys).encode("utf-8")
    if len(blob) != int(lengths.sum()):
        # Some key is non-ASCII: char count != byte count. Re-pack exactly.
        encoded = [k.encode("utf-8") for k in keys]
        lengths = np.fromiter((len(e) for e in encoded), dtype=np.int64,
                              count=n)
        blob = b"".join(encoded)
    buf = np.frombuffer(blob, dtype=np.uint8)
    offsets = np.cumsum(lengths) - lengths
    return buf, offsets, lengths


def hash_packed(buf: np.ndarray, offsets: np.ndarray, lengths: np.ndarray,
                seed: int = DEFAULT_SEED) -> np.ndarray:
    """Hash a packed batch; native kernel when available, NumPy twin else."""
    lib = _load()
    if lib is None:
        return hash_packed_numpy(buf, offsets, lengths, seed)
    n = offsets.shape[0]
    out = np.empty(n, dtype=np.uint64)
    if n:
        buf = np.ascontiguousarray(buf)
        offsets = np.ascontiguousarray(offsets, dtype=np.int64)
        lengths = np.ascontiguousarray(lengths, dtype=np.int64)
        lib.rl_bulk_hash_u64(
            buf.ctypes.data, offsets.ctypes.data, lengths.ctypes.data,
            ctypes.c_uint64(seed & 0xFFFFFFFFFFFFFFFF),
            out.ctypes.data, ctypes.c_int64(n))
    return out


def bulk_hash_u64(keys: Sequence[str], seed: int = DEFAULT_SEED) -> np.ndarray:
    """Hash a batch of string keys to uint64.

    Fast path: the CPython extension iterates the list directly (zero-copy
    UTF-8 views, no Python-level packing). Fallback: pack + NumPy twin.
    """
    _load()
    if _mod is not None:
        if not isinstance(keys, list):
            keys = list(keys)
        out = np.empty(len(keys), dtype=np.uint64)
        _mod.hash_keylist(keys, seed & 0xFFFFFFFFFFFFFFFF, out.ctypes.data)
        return out
    return hash_packed(*pack_keys(keys), seed=seed)
