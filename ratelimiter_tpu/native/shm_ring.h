// Shared-memory SPSC wire rings (ADR-025): the zero-syscall same-host
// transport. One mapping per connection holds a request ring (client ->
// server) and a reply ring (server -> client); records carry UNMODIFIED
// wire frames (serving/protocol.py framing, byte-for-byte), so every
// frame parser on either side works unchanged.
//
// This header is the single source of truth for the byte layout. It is
// included by BOTH native/server.cpp (the C++ front door's drain/emit
// side) and clients/cpp/loadgen.cpp (the GIL-free A/B driver); the
// Python mirror is ratelimiter_tpu/serving/shm.py — the layout
// constants there MUST match these (the cross-door bit-identical tests
// pin a Python client against this C++ server, so drift fails loudly).
//
// Layout (little-endian, all offsets in bytes):
//
//   FileHeader @ 0 (256 B):
//     u64 magic "RLTPSHM1" | u32 version | u32 header_bytes
//     u32 req_capacity | u32 rep_capacity
//     u64 req_ctrl_off | u64 rep_ctrl_off | u64 req_data_off
//     u64 rep_data_off | zero pad
//   RingCtrl per ring (128 B = two cache lines):
//     consumer line: u64 head | u32 consumer_sleeping | pad to 64
//     producer line: u64 tail | u32 producer_waiting  | pad to 128
//   data regions follow (capacities are powers of two).
//
// head/tail are MONOTONIC byte positions (never wrapped); occupancy is
// tail - head and the slot index is pos & (capacity - 1).
//
// Record: 8-byte header [u32 size | u32 commit] + payload + pad to 8.
//   commit == size ^ COMMIT_XOR   committed data record
//   commit == COMMIT_WRAP         wrap pad: skip 8 + size bytes (the
//                                 producer emits one when a record
//                                 would straddle the ring end, so
//                                 payloads are always CONTIGUOUS —
//                                 frombuffer/pointer views need no
//                                 reassembly)
//   anything else                 torn/corrupt (a crashed or byzantine
//                                 producer): the consumer must stop
//                                 trusting the ring and reclaim via the
//                                 control socket, never spin on it.
//
// Publication order (torn-write safety): payload, then the commit word
// (release), then tail (release). A producer killed mid-record leaves
// tail unmoved — the consumer simply never observes the torn bytes.
// The commit word is second-line defence: it self-checks against the
// size field, so a record that IS visible but inconsistent (only
// possible through corruption, not through any crash point) reads as
// poison instead of a garbage frame length.
//
// Doorbell: bounded spin, then eventfd. The consumer advertises
// `consumer_sleeping` before blocking on its eventfd and re-checks the
// ring after the store (store-then-load, SeqCst) so a concurrent
// publish cannot be missed; the producer dings the eventfd only when
// the flag is set — the steady-state hot path makes ZERO syscalls.
// `producer_waiting` is the mirror-image flag for ring-full
// backpressure: the consumer dings the opposite eventfd after freeing
// space.

#pragma once

#include <stdint.h>
#include <string.h>

#include <atomic>

namespace rlshm {

constexpr uint64_t MAGIC = 0x314D485350544C52ULL;  // "RLTPSHM1" LE
constexpr uint32_t VERSION = 1;
constexpr uint32_t FILE_HEADER_BYTES = 256;
constexpr uint32_t CTRL_BYTES = 128;
constexpr uint32_t COMMIT_XOR = 0x52494E47;  // "RING"
constexpr uint32_t COMMIT_WRAP = 0xFFFFFFFFu;
constexpr uint32_t MIN_RING = 1u << 16;
constexpr uint32_t MAX_RING = 1u << 26;

struct FileHeader {
  uint64_t magic;
  uint32_t version;
  uint32_t header_bytes;
  uint32_t req_capacity;
  uint32_t rep_capacity;
  uint64_t req_ctrl_off;
  uint64_t rep_ctrl_off;
  uint64_t req_data_off;
  uint64_t rep_data_off;
};

struct RingCtrl {
  // Consumer-owned cache line.
  std::atomic<uint64_t> head;
  std::atomic<uint32_t> consumer_sleeping;
  char _pad0[64 - 12];
  // Producer-owned cache line.
  std::atomic<uint64_t> tail;
  std::atomic<uint32_t> producer_waiting;
  char _pad1[64 - 12];
};
static_assert(sizeof(RingCtrl) == CTRL_BYTES, "ring ctrl layout");

inline uint32_t align8(uint32_t n) { return (n + 7u) & ~7u; }

inline uint64_t total_bytes(uint32_t req_cap, uint32_t rep_cap) {
  return (uint64_t)FILE_HEADER_BYTES + 2 * CTRL_BYTES + req_cap + rep_cap;
}

// Initialize a freshly-truncated (zeroed) mapping. Returns the header.
inline FileHeader* init_file(uint8_t* base, uint32_t req_cap,
                             uint32_t rep_cap) {
  FileHeader* h = reinterpret_cast<FileHeader*>(base);
  h->magic = MAGIC;
  h->version = VERSION;
  h->header_bytes = FILE_HEADER_BYTES;
  h->req_capacity = req_cap;
  h->rep_capacity = rep_cap;
  h->req_ctrl_off = FILE_HEADER_BYTES;
  h->rep_ctrl_off = FILE_HEADER_BYTES + CTRL_BYTES;
  h->req_data_off = FILE_HEADER_BYTES + 2 * CTRL_BYTES;
  h->rep_data_off = h->req_data_off + req_cap;
  return h;
}

// One directional ring view (producer or consumer role is by usage).
struct Ring {
  RingCtrl* ctrl = nullptr;
  uint8_t* data = nullptr;
  uint32_t capacity = 0;

  uint64_t used() const {
    return ctrl->tail.load(std::memory_order_acquire) -
           ctrl->head.load(std::memory_order_acquire);
  }

  // ---- producer side ----

  // Try to append one frame as a committed record; false = no space
  // (caller decides: overflow queue server-side, typed backpressure
  // error client-side). Never blocks, never syscalls (the doorbell is
  // the caller's job via `want_doorbell` so batched publishes can
  // coalesce the ding).
  bool try_push(const uint8_t* frame, uint32_t len) {
    uint32_t need = 8 + align8(len);
    uint64_t tail = ctrl->tail.load(std::memory_order_relaxed);
    uint64_t head = ctrl->head.load(std::memory_order_acquire);
    uint64_t free_b = capacity - (tail - head);
    uint32_t off = (uint32_t)(tail & (capacity - 1));
    uint32_t to_end = capacity - off;
    uint64_t total = need + (need > to_end ? to_end : 0);
    if (total > free_b) return false;
    if (need > to_end) {
      // Wrap pad: record payloads stay contiguous.
      memcpy(data + off, &to_end, 0);  // no-op, keeps layout explicit
      uint32_t pad_size = to_end - 8;
      memcpy(data + off, &pad_size, 4);
      reinterpret_cast<std::atomic<uint32_t>*>(data + off + 4)
          ->store(COMMIT_WRAP, std::memory_order_release);
      tail += to_end;
      off = 0;
    }
    memcpy(data + off + 8, frame, len);
    memcpy(data + off, &len, 4);
    reinterpret_cast<std::atomic<uint32_t>*>(data + off + 4)
        ->store(len ^ COMMIT_XOR, std::memory_order_release);
    ctrl->tail.store(tail + need, std::memory_order_release);
    return true;
  }

  bool consumer_sleeping() const {
    return ctrl->consumer_sleeping.load(std::memory_order_acquire) != 0;
  }

  // ---- consumer side ----

  enum PopResult { POP_EMPTY = 0, POP_RECORD = 1, POP_TORN = 2 };

  // Peek the next committed record. POP_RECORD fills (*payload, *len);
  // the caller must copy/consume the bytes BEFORE calling advance().
  PopResult pop(const uint8_t** payload, uint32_t* len) {
    for (;;) {
      uint64_t head = ctrl->head.load(std::memory_order_relaxed);
      uint64_t tail = ctrl->tail.load(std::memory_order_acquire);
      if (head == tail) return POP_EMPTY;
      uint32_t off = (uint32_t)(head & (capacity - 1));
      uint32_t size;
      memcpy(&size, data + off, 4);
      uint32_t commit =
          reinterpret_cast<std::atomic<uint32_t>*>(data + off + 4)
              ->load(std::memory_order_acquire);
      if (commit == COMMIT_WRAP) {
        if (8ull + size > capacity) return POP_TORN;
        ctrl->head.store(head + 8 + size, std::memory_order_release);
        continue;
      }
      if (commit != (size ^ COMMIT_XOR) || 8ull + align8(size) > capacity)
        return POP_TORN;
      *payload = data + off + 8;
      *len = size;
      return POP_RECORD;
    }
  }

  // Release the record returned by the last pop().
  void advance(uint32_t len) {
    uint64_t head = ctrl->head.load(std::memory_order_relaxed);
    ctrl->head.store(head + 8 + align8(len), std::memory_order_release);
  }

  bool producer_waiting() const {
    return ctrl->producer_waiting.load(std::memory_order_acquire) != 0;
  }
  void clear_producer_waiting() {
    ctrl->producer_waiting.store(0, std::memory_order_release);
  }
  void set_producer_waiting() {
    ctrl->producer_waiting.store(1, std::memory_order_seq_cst);
  }
  void set_sleeping() {
    // SeqCst store-then-load: the re-check of tail after this store is
    // ordered after it, so a producer that published before reading the
    // flag is always seen by the re-check (no lost wakeup).
    ctrl->consumer_sleeping.store(1, std::memory_order_seq_cst);
  }
  void clear_sleeping() {
    ctrl->consumer_sleeping.store(0, std::memory_order_release);
  }
  bool empty() const {
    return ctrl->head.load(std::memory_order_acquire) ==
           ctrl->tail.load(std::memory_order_acquire);
  }
};

// Attach rings to a mapped file. `server` selects which ring is the
// inbound one (server consumes req, produces rep; client the reverse).
struct LaneView {
  Ring inbound;   // this side consumes
  Ring outbound;  // this side produces
};

inline bool attach(uint8_t* base, bool server, LaneView* v) {
  FileHeader* h = reinterpret_cast<FileHeader*>(base);
  if (h->magic != MAGIC || h->version != VERSION) return false;
  Ring req{reinterpret_cast<RingCtrl*>(base + h->req_ctrl_off),
           base + h->req_data_off, h->req_capacity};
  Ring rep{reinterpret_cast<RingCtrl*>(base + h->rep_ctrl_off),
           base + h->rep_data_off, h->rep_capacity};
  v->inbound = server ? req : rep;
  v->outbound = server ? rep : req;
  return true;
}

}  // namespace rlshm
