"""Handoff artifacts: restoring range state across fleet hosts (ADR-018).

Every ownership move in the fleet — kill -9 failover (ADR-017), live
migration, graceful departure, and rejoin give-back — ships state the
same way: the giving side's snapshot directory (a shared/replicated
volume, ``FleetHost.snapshot_dir``) is the handoff artifact, and the
receiving side restores **before** it announces ownership
(restore-before-rejoin, the ADR-015 contract):

* ``build_standby(origin=None)`` — the failover / departure shape:
  recover the host's OWN unit from its newest snapshot + WAL suffix,
  then fold any ``aux-*`` adopted-range units its manifest records
  (ADR-017's declared leftover: without the fold, a second failure
  after adoption lost the adopted counters — the successor's successor
  now restores them from the successor's own snapshot cycle).
* ``build_standby(origin=...)`` — the rejoin shape: a returning host
  restores exactly ITS ranges from the successor's aux snapshot of the
  adopted unit, plus the WAL suffix (overrides exact; counters within
  one snapshot interval, under-count only).

Folding uses the conservative union (parallel/reshard.py): the folded
populations are disjoint key ranges, so per-key estimates never drop —
a restored standby can only deny more than the units it absorbed, never
over-admit.
"""

from __future__ import annotations

import logging
import os
from typing import Callable, Optional

from ratelimiter_tpu.core.errors import CheckpointError
from ratelimiter_tpu.persistence import wal as walmod
from ratelimiter_tpu.persistence.snapshotter import read_manifest

log = logging.getLogger("ratelimiter_tpu.fleet")


def _newest_aux_entry(manifest: Optional[dict], origin: str):
    """(snapshot entry, aux record) for the newest snapshot carrying an
    aux unit for ``origin`` — or (None, None)."""
    if manifest is None:
        return None, None
    for entry in reversed(manifest["snapshots"]):
        for aux in entry.get("aux", []):
            if aux.get("origin") == origin:
                return entry, aux
    return None, None


def _replay_wal(unit, dir_: str, after_seq: int,
                owns: Optional[Callable[[str], bool]]) -> int:
    """Replay the WAL suffix onto one standby unit: policy/config
    records apply unconditionally (write-all, the live semantics),
    resets only where the unit owns the key (subtracting a foreign
    key's estimate would erase colliding keys' mass — toward
    over-admitting, the one direction we never take)."""
    replayed = 0
    for rec in walmod.replay(dir_, after_seq=after_seq):
        p = rec.payload
        try:
            if rec.type == walmod.REC_POLICY_SET:
                unit.set_override(
                    p["key"], int(p["limit"]),
                    window_scale=float(p.get("window_scale", 1.0)))
            elif rec.type == walmod.REC_POLICY_DEL:
                unit.delete_override(p["key"])
            elif rec.type == walmod.REC_RESET:
                if owns is not None and owns(p["key"]):
                    unit.reset(p["key"])
            elif rec.type == walmod.REC_UPDATE_LIMIT:
                unit.update_limit(int(p["limit"]))
            elif rec.type == walmod.REC_UPDATE_WINDOW:
                unit.update_window(float(p["window"]))
            replayed += 1
        except Exception as exc:  # noqa: BLE001 — serve with a warning
            log.warning("handoff WAL replay apply failed (seq %d): %s",
                        rec.seq, exc)
    return replayed


def fold_aux_units(unit, dir_: str) -> int:
    """Conservative-union every aux adopted-range snapshot recorded in
    ``dir_``'s newest manifest entries into ``unit`` (one fold per
    origin, newest file each). Returns the number of origins folded."""
    from ratelimiter_tpu.checkpoint import load_state
    from ratelimiter_tpu.parallel import reshard

    manifest = read_manifest(dir_)
    if manifest is None:
        return 0
    seen = set()
    seen_files = set()
    folded = 0
    for entry in reversed(manifest["snapshots"]):
        for aux in entry.get("aux", []):
            origin = aux.get("origin")
            if origin in seen:
                continue
            seen.add(origin)
            if aux["file"] in seen_files:
                continue  # several origins share one merged-unit file
            seen_files.add(aux["file"])
            path = os.path.join(dir_, aux["file"])
            try:
                arrays, meta = load_state(path, unit._CKPT_KIND,
                                          unit.config)
                reshard.merge_into_limiter(unit, arrays, meta)
                folded += 1
                log.warning("handoff: folded adopted-unit snapshot for "
                            "origin %s (%s) into the standby", origin,
                            aux["file"])
            except Exception as exc:  # noqa: BLE001 — under-count only
                log.warning("handoff: aux snapshot %s unreadable (%s); "
                            "its origin's counters under-count "
                            "(fail-toward-allowing)", path, exc)
    return folded


def _restore_mesh_combined(unit, snapshot_dir: str,
                           owns: Optional[Callable[[str], bool]]) -> bool:
    """Fallback for a SLICED-MESH peer: its combined ``mesh:`` snapshot
    cannot restore a single-unit standby directly, but the elastic
    re-bucketing seam can fold it — a 1-slice re-bucket is the
    conservative union of every slice (parallel/reshard.py), so the
    standby's estimates upper-bound each slice's (deny-ward). Returns
    True when a combined snapshot was restored + WAL-replayed."""
    import numpy as np

    from ratelimiter_tpu.checkpoint import _META_KEY
    from ratelimiter_tpu.parallel import reshard

    manifest = read_manifest(snapshot_dir)
    if manifest is None:
        return False
    for entry in reversed(manifest["snapshots"]):
        if len(entry["files"]) != 1:
            continue
        path = os.path.join(snapshot_dir, entry["files"][0])
        try:
            import json as _json

            with np.load(path, allow_pickle=False) as z:
                meta = _json.loads(bytes(z[_META_KEY]).decode())
                if str(meta.get("kind", "")) != f"mesh:{unit._CKPT_KIND}":
                    return False
                arrays = {k: z[k] for k in z.files if k != _META_KEY}
            states, extras = reshard.split_combined(arrays, meta)
            merged, extra = reshard.merge_states(states, extras,
                                                 unit.config)
            unit._restore_loaded(merged, extra,
                                 label=f"{path}[rebucket->1]")
            _replay_wal(unit, snapshot_dir, int(entry["wal_seq"]), owns)
            log.warning("handoff: re-bucketed mesh snapshot %s onto the "
                        "single-unit standby (conservative union, "
                        "ADR-018)", path)
            return True
        except Exception as exc:  # noqa: BLE001 — older entry / fresh
            log.warning("handoff: combined snapshot %s unusable (%s); "
                        "falling back", path, exc)
    return False


def build_standby(config, snapshot_dir: str, *,
                  origin: Optional[str] = None,
                  owns: Optional[Callable[[str], bool]] = None,
                  clock=None):
    """Build one restored standby unit from a peer's snapshot
    directory. ``origin=None`` restores the peer's own unit (newest
    snapshot + WAL suffix) and folds its aux adopted units — a sliced-
    mesh peer's combined snapshot re-buckets onto the unit by
    conservative union; ``origin=<host id>`` restores that origin's aux
    unit only (the rejoin give-back). Raises on a missing/unusable
    artifact — the caller decides whether fresh state is an acceptable
    fallback."""
    from ratelimiter_tpu import create_limiter
    from ratelimiter_tpu.persistence.recover import recover

    unit = create_limiter(config, backend="sketch", clock=clock)
    try:
        if origin is None:
            try:
                report = recover([unit], snapshot_dir)
                log.info("handoff standby from %s: %s", snapshot_dir,
                         report.summary())
            except CheckpointError:
                # Kind/shape mismatch — a sliced-mesh peer. Re-bucket
                # its combined snapshot instead of starting fresh.
                if not _restore_mesh_combined(unit, snapshot_dir, owns):
                    raise
            fold_aux_units(unit, snapshot_dir)
            return unit
        manifest = read_manifest(snapshot_dir)
        entry, aux = _newest_aux_entry(manifest, origin)
        if aux is None:
            raise CheckpointError(
                f"{snapshot_dir}: no aux snapshot for origin "
                f"{origin!r} in the manifest")
        unit.restore(os.path.join(snapshot_dir, aux["file"]))
        replayed = _replay_wal(unit, snapshot_dir,
                               int(entry["wal_seq"]), owns)
        log.info("handoff standby for origin %s from %s: restored %s, "
                 "replayed %d WAL record(s)", origin, snapshot_dir,
                 aux["file"], replayed)
        return unit
    except BaseException:
        unit.close()
        raise
