"""Fleet control tower: cross-host rollup, trace stitching, event merge
(ADR-021).

PRs 10-13 turned N server processes into ONE limiter, but every
observability surface stayed per-process: an operator could not follow a
forwarded frame across the hop, could not get a fleet-wide false-deny
bound, and could not ask "why did tenant X tighten" without grepping N
hosts. This module is the missing plane, in two layers:

* **Pure merge functions** (`merge_audit`, `merge_consumers`,
  `merge_slo`, `merge_hierarchy`, `merge_traces`, `merge_events`) over
  plain member payload dicts — the same code path serves the live
  server fan-out AND the offline tools (tools/fleet_status.py,
  tools/fleet_trace.py), so "the endpoint agrees with an offline merge
  of the members' tallies" is true by construction and pinned by unit
  tests against hand-computed merges.
* **:class:`ControlTower`** — the server-side fan-out: any member
  answers ``GET /v1/fleet/status`` / ``/debug/trace?fleet=1`` /
  ``/debug/events?fleet=1`` by pulling every OTHER member's /healthz,
  trace dump, or event page over the HTTP addresses the fleet map
  declares (``FleetHost.http``), merging with its own. Bearer tokens
  pass THROUGH: the caller's ``Authorization`` header is forwarded to
  peers (debug surfaces are assumed fleet-uniformly tokened), so the
  tower never stores a credential. An unreachable member degrades to a
  named gap in the rollup, never a failed request.

Merge correctness rules (the reason this module exists rather than a
dashboard `avg()`):

* **Audit** tallies SUM (requests, oracle allows/denies, false
  denies/allows) and the Wilson bounds RECOMPUTE over the merged
  counts — averaging per-member rates (or worse, their bounds) would
  let an idle member dilute a lying one and has no coverage guarantee.
* **Top-K consumers** merge by their (h1,h2) hash tokens: a consumer's
  mass can land on two members (mis-routed rows decided before
  forwarding existed in its timeline, rebalance windows), so the token
  — stable across hosts by construction (one hash rule fleet-wide) —
  is the join key; masses sum, ranks recompute.
* **SLO burn** evaluates on merged raw window deltas (spans, slow
  spans, decisions, bad decisions — observability/slo.py exports them
  per window) — the fleet burns budget as one service.
* **Hierarchy** gauges aggregate per scope: in-window mass sums
  (tenant mass is fleet-wide mass), effective/ceiling limits take the
  MIN across members (the binding constraint; gossip should converge
  them, so a spread is itself a finding and is reported).
* **Traces and events** align on the membership's estimated per-peer
  CLOCK_MONOTONIC offsets (announce mono stamps - announce RTT/2,
  fleet/membership.py) and land in ONE Perfetto timeline with a
  process lane per host; spans a receiver recorded under a forward
  window's wire-level trace id are rewritten to the client frame's id
  when the sender's (fragment -> window) link names exactly one
  parent, which is what makes "one trace id across the hop" true in
  the merged view.
"""

from __future__ import annotations

import json
import logging
import urllib.request
from typing import Dict, List, Optional

from ratelimiter_tpu.evaluation.compare import wilson_interval

log = logging.getLogger("ratelimiter_tpu.fleet.tower")

#: Fan-out fetch timeout: a rollup must answer in interactive time even
#: with a dead member in the map.
FETCH_TIMEOUT_S = 3.0


def fetch_json(url: str, *, bearer: Optional[str] = None,
               timeout: float = FETCH_TIMEOUT_S) -> dict:
    """GET one JSON payload (raises on transport/HTTP/parse failure —
    callers degrade per member)."""
    req = urllib.request.Request(url)
    if bearer:
        req.add_header("Authorization", f"Bearer {bearer}")
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return json.loads(r.read())


# =====================================================================
#                        pure merge functions
# =====================================================================


def merge_audit(blocks: Dict[str, dict]) -> dict:
    """Sum the members' shadow-audit tallies and recompute the rates +
    Wilson bounds over the MERGED counts. ``blocks`` maps host id to
    the member's /healthz ``audit`` block (raw counts included)."""
    if not blocks:
        return {}
    tot = {k: 0 for k in ("samples", "oracle_allows", "false_denies",
                          "false_allows", "fail_open_samples",
                          "dropped_decisions", "oracle_errors")}
    per_host = {}
    for host, b in blocks.items():
        for k in tot:
            tot[k] += int(b.get(k, 0))
        per_host[host] = {k: int(b.get(k, 0)) for k in tot}
        per_host[host]["sample"] = b.get("sample")
    oracle_denies = tot["samples"] - tot["oracle_allows"]
    fd_lo, fd_hi = wilson_interval(tot["false_denies"],
                                   tot["oracle_allows"])
    fa_lo, fa_hi = wilson_interval(tot["false_allows"],
                                   max(0, oracle_denies))
    return {
        **tot,
        "oracle_denies": max(0, oracle_denies),
        "false_deny_rate": round(
            tot["false_denies"] / max(1, tot["oracle_allows"]), 8),
        "false_deny_wilson95": [round(fd_lo, 8), round(fd_hi, 8)],
        "false_allow_rate": round(
            tot["false_allows"] / max(1, oracle_denies), 10),
        "false_allow_wilson95": [round(fa_lo, 10), round(fa_hi, 10)],
        "per_host": per_host,
    }


def merge_consumers(blocks: Dict[str, dict], k: int = 10) -> dict:
    """Merge the members' top-K consumer analytics BY (h1,h2) TOKEN:
    masses sum per token (one consumer's rows can have landed on two
    members), ranks and shares recompute over the merged mass."""
    if not blocks:
        return {}
    by_token: Dict[str, dict] = {}
    slots = occupied = tracked = 0
    for host, b in blocks.items():
        slots += int(b.get("slots", 0))
        occupied += int(b.get("occupied", 0))
        tracked += int(b.get("tracked_mass", 0))
        for row in b.get("top", ()):
            tok = row.get("consumer")
            if not tok:
                continue
            d = by_token.setdefault(tok, {"consumer": tok,
                                          "in_window": 0, "hosts": {}})
            d["in_window"] += int(row.get("in_window", 0))
            d["hosts"][host] = int(row.get("in_window", 0))
    top = sorted(by_token.values(), key=lambda r: -r["in_window"])[:k]
    for r in top:
        r["share"] = round(r["in_window"] / max(1, tracked), 6)
    return {"slots": slots, "occupied": occupied,
            "tracked_mass": tracked, "top": top}


def merge_slo(blocks: Dict[str, dict]) -> dict:
    """Fleet burn rate from the members' raw per-window deltas: sum
    spans/decisions (good and bad) per window name, recompute the axis
    fractions and burn over the merged counts. The fleet is one
    service; its budget burns on pooled traffic, not on an average of
    ratios."""
    if not blocks:
        return {}
    objective = max(float(b.get("objective", 0.999))
                    for b in blocks.values())
    budget = 1.0 - objective
    windows: Dict[str, dict] = {}
    per_host_burn: Dict[str, dict] = {}
    for host, b in blocks.items():
        for wname, row in (b.get("windows") or {}).items():
            w = windows.setdefault(wname, {"spans": 0, "spans_slow": 0,
                                           "decisions": 0,
                                           "decisions_bad": 0,
                                           "span_s": 0.0})
            w["spans"] += int(row.get("spans", 0))
            w["spans_slow"] += int(row.get("spans_slow", 0))
            w["decisions"] += int(row.get("decisions", 0))
            w["decisions_bad"] += int(row.get("decisions_bad", 0))
            w["span_s"] = max(w["span_s"], float(row.get("span_s", 0.0)))
            per_host_burn.setdefault(wname, {})[host] = row.get(
                "burn_rate")
    out = {}
    for wname, w in windows.items():
        slow_frac = (w["spans_slow"] / w["spans"]) if w["spans"] else 0.0
        bad_frac = (w["decisions_bad"] / w["decisions"]
                    if w["decisions"] else 0.0)
        out[wname] = {
            **w,
            "latency_bad_fraction": round(slow_frac, 6),
            "availability_bad_fraction": round(bad_frac, 6),
            "burn_rate": round(max(slow_frac, bad_frac)
                               / max(budget, 1e-9), 3),
            "per_host_burn": per_host_burn.get(wname, {}),
        }
    return {"objective": objective, "error_budget": round(budget, 6),
            "windows": out}


def merge_hierarchy(blocks: Dict[str, dict]) -> dict:
    """Aggregate the cascade gauges per scope: in-window mass SUMS
    (tenant mass is fleet mass), effective/ceiling limits take the MIN
    across members (the binding constraint). A spread between members'
    effective limits means the gossip has not converged — reported
    per host rather than papered over."""
    if not blocks:
        return {}

    def _scope_merge(rows: Dict[str, dict]) -> dict:
        out = {"in_window": 0, "effective": None, "ceiling": None,
               "per_host_in_window": {}, "per_host_effective": {}}
        for host, r in rows.items():
            out["in_window"] += int(r.get("in_window", 0))
            out["per_host_in_window"][host] = int(r.get("in_window", 0))
            for field in ("effective", "ceiling"):
                v = r.get(field)
                if v is not None:
                    out[field] = (int(v) if out[field] is None
                                  else min(out[field], int(v)))
            if r.get("effective") is not None:
                out["per_host_effective"][host] = int(r["effective"])
            if r.get("weight") is not None:
                out["weight"] = int(r["weight"])
        return out

    tenants: Dict[str, Dict[str, dict]] = {}
    glob: Dict[str, dict] = {}
    controllers = {}
    for host, b in blocks.items():
        if b.get("global"):
            glob[host] = b["global"]
        for name, row in (b.get("tenants") or {}).items():
            tenants.setdefault(name, {})[host] = row
        if b.get("controller"):
            controllers[host] = b["controller"]
    out = {"global": _scope_merge(glob),
           "tenants": {name: _scope_merge(rows)
                       for name, rows in tenants.items()}}
    if controllers:
        out["controllers"] = controllers
    return out


def merged_status(members: Dict[str, Optional[dict]]) -> dict:
    """The /v1/fleet/status body from per-member /healthz payloads
    (None = unreachable member — named, not failed). Every series is
    host-labeled; the accuracy/consumer/SLO/hierarchy blocks merge by
    the rules in the module docstring."""
    reach = {h: b for h, b in members.items() if b is not None}
    hosts = {}
    for h, b in members.items():
        if b is None:
            hosts[h] = {"reachable": False}
            continue
        fleet = b.get("fleet") or {}
        hosts[h] = {
            "reachable": True,
            "serving": b.get("serving"),
            "decisions_total": b.get("decisions_total"),
            "epoch": fleet.get("epoch"),
            "owned_ranges": fleet.get("owned_ranges"),
            "adopted_buckets": fleet.get("adopted_buckets"),
            "forwarded_total": fleet.get("forwarded_total"),
            "forward_errors_total": fleet.get("forward_errors_total"),
            "member": b.get("member"),
        }
    out: dict = {
        "members": len(members),
        "reachable": len(reach),
        "hosts": hosts,
        "decisions_total": sum(int(b.get("decisions_total", 0))
                               for b in reach.values()),
    }
    epochs = {h: d.get("epoch") for h, d in hosts.items()
              if d.get("epoch") is not None}
    out["epoch"] = max(epochs.values()) if epochs else None
    out["epoch_converged"] = len(set(epochs.values())) <= 1
    audit = {h: b["audit"] for h, b in reach.items() if b.get("audit")}
    if audit:
        out["audit"] = merge_audit(audit)
    cons = {h: b["consumers"] for h, b in reach.items()
            if b.get("consumers")}
    if cons:
        out["consumers"] = merge_consumers(cons)
    slo = {h: b["slo"] for h, b in reach.items() if b.get("slo")}
    if slo:
        out["slo"] = merge_slo(slo)
    hier = {h: b["hierarchy"] for h, b in reach.items()
            if b.get("hierarchy")}
    if hier:
        out["hierarchy"] = merge_hierarchy(hier)
    plc = {h: b["placement"] for h, b in reach.items()
           if b.get("placement")}
    if plc:
        from ratelimiter_tpu.placement.accounting import merge_placement

        out["placement"] = merge_placement(plc)
        rebal = {h: b["placement"]["rebalance"] for h, b in reach.items()
                 if isinstance(b.get("placement"), dict)
                 and b["placement"].get("rebalance")}
        if rebal:
            out["placement"]["rebalance"] = rebal
    return out


# ------------------------------------------------------------- tracing


def synthetic_parent_id(parents) -> str:
    """Deterministic synthetic trace id for a forward window that
    coalesced SEVERAL sampled client frames: the truncated digest of the
    sorted parent ids, in the same 16-hex shape as real trace ids. A
    pure function of the parent set — the same coalition always maps to
    the same id regardless of which window carried it or which host
    runs the stitch (the server-side/offline parity pin depends on
    that)."""
    import hashlib

    return hashlib.sha256(
        ",".join(sorted(parents)).encode()).hexdigest()[:16]


def merge_traces(payloads: Dict[str, Optional[dict]],
                 offsets: Dict[str, Optional[int]],
                 ref: str) -> dict:
    """One offset-aligned Perfetto timeline from per-member
    ``chrome_trace()`` payloads: a process lane per host (Perfetto
    renders one track group per pid), every peer's timestamps shifted
    into ``ref``'s CLOCK_MONOTONIC domain by ``offsets[host]``
    (t_ref = t_host + offset; ns), and forward-window spans REWRITTEN
    to their client frame's trace id wherever the sender's
    (fragment -> window) links name exactly one parent — the cross-hop
    stitch. Multi-parent windows rewrite to ``synthetic_parent_id`` of
    their parent set (window id + parents preserved in args). Hosts
    with a None payload (unreachable) or a None offset (no announce
    heard yet; merged unshifted) are reported in ``otherData``."""
    events: List[dict] = []
    links: List[dict] = []
    meta: List[dict] = []
    hosts_meta: Dict[str, dict] = {}
    for pid, (host, payload) in enumerate(sorted(payloads.items())):
        off = offsets.get(host)
        hosts_meta[host] = {
            "pid": pid,
            "reachable": payload is not None,
            "mono_offset_ns": (0 if host == ref else off),
            "aligned": host == ref or off is not None,
        }
        if payload is None:
            continue
        off_us = 0.0 if host == ref else (off or 0) / 1e3
        meta.append({"name": "process_name", "ph": "M", "pid": pid,
                     "args": {"name": host}})
        meta.append({"name": "process_sort_index", "ph": "M", "pid": pid,
                     "args": {"sort_index": pid}})
        other = payload.get("otherData") or {}
        for tid, tname in (other.get("threads") or {}).items():
            meta.append({"name": "thread_name", "ph": "M", "pid": pid,
                         "tid": int(tid), "args": {"name": tname}})
        for ln in other.get("links") or ():
            links.append({**ln, "host": host})
        for e in payload.get("traceEvents", ()):
            e = dict(e)
            e["pid"] = pid
            e["ts"] = e.get("ts", 0.0) + off_us
            e.setdefault("args", {})
            e["args"] = {**e["args"], "host": host}
            events.append(e)
    # Stitch: window id -> the set of client frame ids that shipped
    # fragments into it (sender-side links). A single-parent window's
    # spans rename to the client id — ONE trace id across the hop; a
    # multi-parent window (several sampled frames coalesced into one
    # wire window) renames to a SYNTHETIC parent id derived from the
    # full parent set (the PR-14 residual: keeping the window id left
    # the receiver's spans grouped apart from every client frame, so a
    # trace viewer's by-id filter found neither side). The synthetic id
    # is a pure function of the sorted parents, so every window that
    # coalesced the same client frames lands under the same id, the
    # server-side and offline stitches agree byte-for-byte, and the
    # original window id + the parent list stay in args.
    parents: Dict[str, set] = {}
    for ln in links:
        parents.setdefault(ln["child"], set()).add(ln["parent"])
    for e in events:
        tid = e["args"].get("trace_id")
        ps = parents.get(tid)
        if not ps:
            continue
        e["args"]["window_id"] = tid
        if len(ps) == 1:
            e["args"]["trace_id"] = next(iter(ps))
        else:
            e["args"]["trace_id"] = synthetic_parent_id(ps)
            e["args"]["trace_parents"] = sorted(ps)
    events.sort(key=lambda e: e.get("ts", 0.0))
    return {
        "traceEvents": meta + events,
        "displayTimeUnit": "ms",
        "otherData": {
            "clock": f"CLOCK_MONOTONIC of {ref} (peers offset-aligned)",
            "ref": ref,
            "hosts": hosts_meta,
            "links": links,
        },
    }


# -------------------------------------------------------------- events


def merge_events(member_events: Dict[str, Optional[dict]],
                 offsets: Dict[str, Optional[int]],
                 ref: str, *, limit: int = 512) -> dict:
    """One fleet-wide control-plane timeline from per-member
    /debug/events pages: every event is host-tagged, its monotonic
    stamp aligned into ``ref``'s clock domain (``mono_aligned_ns``)
    when an offset estimate exists, and the merged list sorts on wall
    time (NTP-grade — control-plane events are seconds apart; the
    aligned monotonic stamp is there for joining against the stitched
    span timeline)."""
    merged: List[dict] = []
    hosts = {}
    for host, page in member_events.items():
        off = 0 if host == ref else offsets.get(host)
        hosts[host] = {"reachable": page is not None,
                       "aligned": off is not None}
        if page is None:
            continue
        for e in page.get("events", ()):
            e = {**e, "host": host}
            if off is not None and "mono_ns" in e:
                e["mono_aligned_ns"] = int(e["mono_ns"]) + off
            merged.append(e)
    merged.sort(key=lambda e: e.get("ts", 0.0))
    if len(merged) > limit:
        merged = merged[-limit:]
    return {"enabled": True, "fleet": True, "ref": ref, "hosts": hosts,
            "events": merged}


# =====================================================================
#                       server-side fan-out
# =====================================================================


class ControlTower:
    """One member's fan-out engine behind /v1/fleet/status,
    /debug/trace?fleet=1 and /debug/events?fleet=1. Peers are read over
    the fleet map's declared HTTP gateways; this member's own payloads
    come from local callables (never a self-HTTP hop)."""

    def __init__(self, core, membership, *, self_health,
                 timeout: float = FETCH_TIMEOUT_S):
        self.core = core
        self.membership = membership
        self.self_health = self_health
        self.timeout = float(timeout)

    # ------------------------------------------------------------ peers

    def _peers(self):
        """[(host_id, base_url | None)] for every OTHER member."""
        out = []
        for h in self.core.map.hosts:
            if h.id == self.core.self_id:
                continue
            addr = h.http_addr
            out.append((h.id, f"http://{addr}" if addr else None))
        return out

    def _offsets(self) -> Dict[str, Optional[int]]:
        offs: Dict[str, Optional[int]] = {self.core.self_id: 0}
        for h in self.core.map.hosts:
            if h.id == self.core.self_id:
                continue
            offs[h.id] = (self.membership.peer_clock(h.id)["offset_ns"]
                          if self.membership is not None else None)
        return offs

    def _fetch(self, base: Optional[str], path: str,
               bearer: Optional[str]) -> Optional[dict]:
        if base is None:
            return None
        try:
            return fetch_json(base + path, bearer=bearer,
                              timeout=self.timeout)
        except Exception as exc:  # noqa: BLE001 — a dead member is a
            # named gap in the rollup, never a failed rollup.
            log.debug("fleet tower fetch %s%s failed: %s", base, path,
                      exc)
            return None

    def _fetch_all(self, path: str,
                   bearer: Optional[str]) -> Dict[str, Optional[dict]]:
        """Fetch ``path`` from every peer CONCURRENTLY: the surface is
        bounded by ONE fetch timeout, not peers × timeout — with three
        partitioned members an 8-host rollup must still answer in
        interactive time (the §12 triage contract)."""
        import concurrent.futures

        peers = self._peers()
        if not peers:
            return {}
        with concurrent.futures.ThreadPoolExecutor(
                max_workers=min(8, len(peers)),
                thread_name_prefix="rl-fleet-tower") as ex:
            futs = {hid: ex.submit(self._fetch, base, path, bearer)
                    for hid, base in peers}
            return {hid: f.result() for hid, f in futs.items()}

    # ---------------------------------------------------------- surfaces

    def fleet_status(self) -> dict:
        members: Dict[str, Optional[dict]] = {
            self.core.self_id: self.self_health()}
        members.update(self._fetch_all("/healthz", None))
        out = merged_status(members)
        out["generated_by"] = self.core.self_id
        return out

    def fleet_trace(self, bearer: Optional[str] = None) -> dict:
        from ratelimiter_tpu.observability import tracing

        rec = tracing.RECORDER
        payloads: Dict[str, Optional[dict]] = {
            self.core.self_id: (rec.chrome_trace() if rec is not None
                                else {"traceEvents": [],
                                      "otherData": {}})}
        payloads.update(self._fetch_all("/debug/trace", bearer))
        return merge_traces(payloads, self._offsets(),
                            self.core.self_id)

    def fleet_events(self, *, limit: int = 512,
                     category: Optional[str] = None,
                     bearer: Optional[str] = None) -> dict:
        from urllib.parse import quote

        from ratelimiter_tpu.observability import events as ev

        j = ev.JOURNAL
        pages: Dict[str, Optional[dict]] = {
            self.core.self_id: (j.tail(limit, category=category)
                                if j is not None else {"events": []})}
        q = f"?tail={int(limit)}"
        if category:
            # Percent-encode: a caller's odd category must 400 locally
            # or filter cleanly — never make peers read as unreachable.
            q += f"&category={quote(category, safe='')}"
        pages.update(self._fetch_all("/debug/events" + q, bearer))
        return merge_events(pages, self._offsets(), self.core.self_id,
                            limit=limit)
