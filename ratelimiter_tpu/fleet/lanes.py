"""Coalesced columnar peer lanes — the fleet forwarding hot path (ADR-019).

PR 10's forwarder proxied mis-routed rows over ONE blocking connection
per peer, one wire round-trip per inbound frame fragment, drained by a
single FIFO worker. Under mixed fleet traffic that serializes every
frame's forward leg behind every other frame's RTT — FLEET_r01 measured
the result: 2-host mixed throughput at 0.34x affine with frame p99 13x
affine. This module is the cross-host twin of the ADR-013 scatter-gather
scheduler: carve, coalesce per destination, pipeline, and reassemble by
row-range views.

One :class:`PeerLane` per peer, each owning ``conns`` pipelined
connections driven from a single background event loop
(:class:`ForwardRuntime`, one daemon thread per :class:`FleetCore`):

* **Coalescing.** Foreign-row fragments from MANY inbound frames queue
  per connection; whenever an in-flight window slot is free the sender
  merges every queued fragment (up to ``coalesce`` rows) into ONE
  ``T_ALLOW_HASHED`` wire frame. There is deliberately no timer: at low
  load a fragment flushes immediately (no added latency), under load
  the window backpressure IS the coalescing window — the same
  slot-availability batching as the micro-batcher's adaptive delay and
  the continuous-batching literature's.
* **Pipelining.** Each connection keeps up to ``inflight`` wire frames
  outstanding (the PR 3 bounded in-flight window, one level up), so the
  peer's door coalesces our windows with its direct traffic instead of
  ping-ponging one frame per RTT.
* **Per-key connection affinity.** A row rides connection
  ``h64 % conns``: the same key always takes the same connection, and
  each connection's frames are sent — and decided by the receiver's
  FIFO door — in submit order, so same-key send order survives
  multi-connection links (the cross-host half of the in-batch
  sequencing contract; pinned by tests/test_fleet_forward.py).
* **Zero-copy reassembly.** The coalesced reply parses into ONE
  columnar :class:`BatchResult`; each member fragment's future resolves
  to ``reply.rows(off, count)`` — numpy VIEWS over the reply buffers
  (the ADR-013 seam), no per-row Python objects anywhere on the path.

Failure attribution: one failed wire frame fails exactly its member
fragments' futures (other windows, other connections, other peers are
untouched); the caller degrades those rows per fail-open/closed policy
(forwarder.collect_jobs). Backpressure: at most ``queue_cap`` fragments
may be outstanding per peer beyond the one being written — overflow
raises the typed StorageUnavailableError at submit, never buffers
unbounded.
"""

from __future__ import annotations

import asyncio
import collections
import concurrent.futures
import itertools
import socket
import threading
import time
from typing import Deque, Dict, List, Optional

import numpy as np

from ratelimiter_tpu.core.errors import StorageUnavailableError
from ratelimiter_tpu.observability import tracing


class ForwardRuntime:
    """One background event loop driving every peer lane of a FleetCore.
    Lazily started on the first forward; submissions cross threads via
    ``call_soon_threadsafe`` only (all lane state is loop-confined)."""

    def __init__(self, name: str = "rl-fleet-forward"):
        self._loop = asyncio.new_event_loop()
        self._thread = threading.Thread(
            target=self._run, name=name, daemon=True)
        self._thread.start()

    def _run(self) -> None:
        asyncio.set_event_loop(self._loop)
        self._loop.run_forever()

    @property
    def loop(self) -> asyncio.AbstractEventLoop:
        return self._loop

    @property
    def alive(self) -> bool:
        return self._thread.is_alive() and not self._loop.is_closed()

    def call_soon(self, fn, *args) -> None:
        self._loop.call_soon_threadsafe(fn, *args)

    def stop(self) -> None:
        if self._loop.is_closed():
            return

        async def _shutdown() -> None:
            # A few ticks first: lane close already failed the waiting
            # reply futures — let their completion handlers finish
            # naturally before cancelling what remains.
            for _ in range(3):
                await asyncio.sleep(0)
            tasks = [t for t in asyncio.all_tasks()
                     if t is not asyncio.current_task()]
            for t in tasks:
                t.cancel()
            await asyncio.gather(*tasks, return_exceptions=True)
            self._loop.stop()

        try:
            self._loop.call_soon_threadsafe(
                lambda: self._loop.create_task(_shutdown()))
        except RuntimeError:  # loop already closing
            return
        self._thread.join(timeout=10)
        if not self._thread.is_alive():
            self._loop.close()


class _Frag:
    """One forwarded fragment: a contiguous run of one inbound frame's
    rows bound for one peer connection. ``fut`` resolves to the
    BatchResult row-range VIEW of the coalesced reply. ``trace`` is the
    originating frame's trace id (0 = unsampled): the sender links it
    to the coalesced window's WINDOW-level id so the receiving host's
    spans stitch back to the client frame (ADR-021)."""

    __slots__ = ("ids", "ns", "b", "fut", "trace")

    def __init__(self, ids: np.ndarray, ns: np.ndarray,
                 fut: "concurrent.futures.Future", trace: int = 0):
        self.ids = ids
        self.ns = ns
        self.b = int(ids.shape[0])
        self.fut = fut
        self.trace = trace


class _Call:
    """A scalar/control op riding the lane (allow_n, reset, string-batch
    fallback): sent FIFO with the row fragments on its affinity
    connection, so a key's scalar calls and batch rows stay ordered."""

    __slots__ = ("build", "parse", "fut", "rows")

    def __init__(self, build, parse, fut, rows: int = 1):
        self.build = build      # fn(req_id) -> wire frame bytes
        self.parse = parse      # fn(type_, body) -> result
        self.fut = fut
        self.rows = rows


class _PeerConn:
    """One pipelined connection to a peer: a FIFO work queue (fragments
    + calls), a sender task that coalesces fragment runs under the
    in-flight window, and a reader task matching responses by request
    id. Everything here runs on the forward loop — no locks."""

    def __init__(self, lane: "PeerLane", idx: int):
        self.lane = lane
        self.idx = idx
        self._loop = lane.runtime.loop
        self._work: Deque = collections.deque()
        self._wake = asyncio.Event()
        self._sem = asyncio.Semaphore(lane.inflight)
        self._ids = itertools.count(1)
        self._waiting: Dict[int, asyncio.Future] = {}
        self._reader: Optional[asyncio.StreamReader] = None
        self._writer: Optional[asyncio.StreamWriter] = None
        self._reader_task: Optional[asyncio.Task] = None
        self._closed = False
        self._sender = self._loop.create_task(self._run())

    # ------------------------------------------------------------ intake

    def enqueue(self, item) -> None:
        """Loop-side: append work and wake the sender."""
        if self._closed:
            self._fail_item(item, StorageUnavailableError(
                f"fleet forward lane to {self.lane.label} is closed"))
            return
        self._work.append(item)
        self._wake.set()

    # ------------------------------------------------------------ sender

    async def _run(self) -> None:
        while not self._closed:
            if not self._work:
                self._wake.clear()
                await self._wake.wait()
                continue
            await self._sem.acquire()
            if self._closed or not self._work:
                self._sem.release()
                continue
            head = self._work[0]
            if isinstance(head, _Call):
                self._work.popleft()
                await self._send_call(head)
            else:
                # Coalesce: merge every queued fragment (submit order)
                # up to the coalesce cap into ONE wire frame. A lone
                # oversized fragment still sends alone — the receiver's
                # dispatcher carves past max_batch (ADR-013).
                frags = [self._work.popleft()]
                rows = frags[0].b
                while (self._work and isinstance(self._work[0], _Frag)
                       and rows + self._work[0].b <= self.lane.coalesce):
                    f = self._work.popleft()
                    frags.append(f)
                    rows += f.b
                await self._send_window(frags, rows)

    async def _ensure_conn(self) -> None:
        dead = (self._writer is None or self._writer.is_closing()
                or self._reader_task is None or self._reader_task.done())
        if not dead:
            return
        self._drop_conn()
        host, port = self.lane.host, self.lane.port
        self._reader, self._writer = await asyncio.wait_for(
            asyncio.open_connection(host, port),
            timeout=min(self.lane.deadline, 5.0))
        sock = self._writer.get_extra_info("socket")
        if sock is not None:
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self._reader_task = self._loop.create_task(self._read_loop())

    def _drop_conn(self) -> None:
        if self._reader_task is not None:
            self._reader_task.cancel()
            self._reader_task = None
        if self._writer is not None:
            try:
                self._writer.close()
            except Exception:  # noqa: BLE001 — teardown best-effort
                pass
            self._writer = None
        for rf in self._waiting.values():
            if not rf.done():
                rf.set_exception(ConnectionError(
                    f"forward connection to {self.lane.label} dropped"))
        self._waiting.clear()

    async def _read_loop(self) -> None:
        from ratelimiter_tpu.serving import protocol as p

        try:
            while True:
                hdr = await self._reader.readexactly(p.HEADER_SIZE)
                length, type_, rid = p.parse_header(hdr)
                body = await self._reader.readexactly(length - 9)
                rf = self._waiting.pop(rid, None)
                if rf is not None and not rf.done():
                    rf.set_result((type_, body))
        except (asyncio.IncompleteReadError, ConnectionResetError,
                asyncio.CancelledError, OSError) as exc:
            for rf in self._waiting.values():
                if not rf.done():
                    rf.set_exception(ConnectionError(
                        f"forward connection to {self.lane.label} lost: "
                        f"{exc!r}"))
            self._waiting.clear()

    async def _send_window(self, frags: List[_Frag], rows: int) -> None:
        from ratelimiter_tpu.serving import protocol as p

        lane = self.lane
        req_id = 0
        try:
            await self._ensure_conn()
            req_id = next(self._ids)
            if len(frags) == 1:
                ids, ns = frags[0].ids, frags[0].ns
            else:
                ids = np.concatenate([f.ids for f in frags])
                ns = np.concatenate([f.ns for f in frags])
            frame = p.with_deadline(
                p.encode_allow_hashed(req_id, ids, ns), lane.deadline)
            # Cross-host trace stitching (ADR-021): when the flight
            # recorder is on, the coalesced window gets ONE fresh
            # WINDOW-level trace id on the wire (TRACE_FLAG) — the
            # receiver's io/coalesce/launch/device spans record under
            # it — and each member fragment's client trace id links to
            # it host-side, so the stitcher (fleet/tower.py) can join
            # the hop back to the client frame. Recorder off: no flag,
            # wire bytes unchanged (the PR 12 shape).
            rec = tracing.RECORDER
            wid = 0
            if rec is not None:
                wid = tracing.new_trace_id()
                frame = p.with_trace(frame, wid)
                for f in frags:
                    if f.trace:
                        rec.link(f.trace, wid)
            # FORWARD_FLAG (ADR-019): the receiver dispatches this
            # window standalone — its reply must never wait on the
            # receiver's own forward legs (the cross-host dependency
            # chain behind FLEET_r01's p99). Outermost, after the trace
            # extension.
            frame = p.with_forward(frame)
            rfut = self._loop.create_future()
            self._waiting[req_id] = rfut
            self._writer.write(frame)
            await self._writer.drain()
        except BaseException as exc:  # degrade the members — including
            # on CancelledError (sender cancelled by close mid-send):
            # the frags are already popped from the work queue, so
            # nothing else can ever resolve their futures.
            self._waiting.pop(req_id, None)
            self._fail_frags(frags, exc if isinstance(exc, Exception)
                             else StorageUnavailableError(
                                 f"fleet forward lane to "
                                 f"{lane.label} shut down"))
            self._drop_conn()
            self._sem.release()
            if not isinstance(exc, Exception):
                raise
            return
        # Counted only once actually on the wire (a failed connect /
        # write above must not skew occupancy or the wire totals).
        lane.note_window(len(frags), rows)
        t0 = time.perf_counter()
        self._loop.create_task(
            self._complete_window(req_id, rfut, frags, rows, t0, wid,
                                  tracing.now() if wid else 0))

    async def _complete_window(self, req_id: int, rfut, frags: List[_Frag],
                               rows: int, t0: float, wid: int = 0,
                               t_send_ns: int = 0) -> None:
        from ratelimiter_tpu.serving import protocol as p

        lane = self.lane
        try:
            try:
                type_, body = await asyncio.wait_for(
                    rfut, lane.deadline + 1.0)
            except asyncio.TimeoutError:
                # The reply may still arrive later: this connection is
                # desynchronized for every frame behind it — drop it.
                self._drop_conn()
                raise StorageUnavailableError(
                    f"fleet forward to {lane.label} timed out after "
                    f"{lane.deadline:.1f}s") from None
            if type_ == p.T_ERROR:
                code, msg = p.parse_error(body)
                raise p.exception_for(code, msg)
            if type_ != p.T_RESULT_HASHED:
                self._drop_conn()
                raise p.ProtocolError(
                    f"unexpected forward response type {type_}")
            res = p.parse_result_hashed(body)
            if len(res) != rows:
                self._drop_conn()
                raise p.ProtocolError(
                    f"forward reply carries {len(res)} rows for a "
                    f"{rows}-row window")
            lane.note_rtt(time.perf_counter() - t0)
            if wid:
                rec = tracing.RECORDER
                if rec is not None:
                    # The sender-side wire span of this coalesced
                    # window, under its window-level id — the hop's
                    # envelope on the stitched timeline (ADR-021).
                    rec.record("forward", t_send_ns, tracing.now(),
                               trace_id=wid, batch=rows)
            off = 0
            for f in frags:
                if not f.fut.done():
                    f.fut.set_result(res.rows(off, f.b))
                off += f.b
        except BaseException as exc:  # noqa: BLE001 — degrade the members
            self._fail_frags(frags, exc if isinstance(exc, Exception)
                             else StorageUnavailableError(
                                 f"fleet forward lane to "
                                 f"{lane.label} shut down"))
            if not isinstance(exc, Exception):
                raise
        finally:
            self._waiting.pop(req_id, None)
            self._sem.release()

    async def _send_call(self, call: _Call) -> None:
        req_id = 0
        try:
            await self._ensure_conn()
            req_id = next(self._ids)
            frame = call.build(req_id)
            rfut = self._loop.create_future()
            self._waiting[req_id] = rfut
            self._writer.write(frame)
            await self._writer.drain()
        except BaseException as exc:  # future carries it — including on
            # CancelledError mid-send (see _send_window).
            self._waiting.pop(req_id, None)
            self._fail_item(call, exc if isinstance(exc, Exception)
                            else StorageUnavailableError(
                                f"fleet forward lane to "
                                f"{self.lane.label} shut down"))
            self._drop_conn()
            self._sem.release()
            if not isinstance(exc, Exception):
                raise
            return
        t0 = time.perf_counter()
        self._loop.create_task(self._complete_call(req_id, rfut, call, t0))

    async def _complete_call(self, req_id: int, rfut, call: _Call,
                             t0: float) -> None:
        from ratelimiter_tpu.serving import protocol as p

        lane = self.lane
        try:
            try:
                type_, body = await asyncio.wait_for(
                    rfut, lane.deadline + 1.0)
            except asyncio.TimeoutError:
                self._drop_conn()
                raise StorageUnavailableError(
                    f"fleet forward to {lane.label} timed out after "
                    f"{lane.deadline:.1f}s") from None
            if type_ == p.T_ERROR:
                code, msg = p.parse_error(body)
                raise p.exception_for(code, msg)
            lane.note_rtt(time.perf_counter() - t0)
            out = call.parse(type_, body)
            if not call.fut.done():
                call.fut.set_result(out)
        except BaseException as exc:  # noqa: BLE001 — future carries it
            self._fail_item(call, exc if isinstance(exc, Exception)
                            else StorageUnavailableError(
                                f"fleet forward lane to "
                                f"{lane.label} shut down"))
            if not isinstance(exc, Exception):
                raise
        finally:
            self._waiting.pop(req_id, None)
            self._sem.release()

    # ------------------------------------------------------------ teardown

    def _fail_frags(self, frags: List[_Frag], exc: BaseException) -> None:
        for f in frags:
            if not f.fut.done():
                f.fut.set_exception(exc)

    @staticmethod
    def _fail_item(item, exc: BaseException) -> None:
        if not item.fut.done():
            item.fut.set_exception(exc)

    def close(self) -> None:
        """Loop-side: stop the sender, drop the socket, fail all work."""
        self._closed = True
        self._wake.set()
        self._sender.cancel()
        exc = StorageUnavailableError(
            f"fleet forward lane to {self.lane.label} is closed")
        while self._work:
            self._fail_item(self._work.popleft(), exc)
        self._drop_conn()


class PeerLane:
    """All forwarding to ONE peer: ``conns`` pipelined connections with
    per-key affinity, a shared outstanding-fragment bound, and the
    per-peer coalescing/occupancy metrics. Thread-safe submit surface;
    connection state is confined to the forward loop."""

    def __init__(self, runtime: ForwardRuntime, host: str, port: int, *,
                 label: str, deadline: float, inflight: int, conns: int,
                 coalesce: int, queue_cap: int, metrics=None):
        self.runtime = runtime
        self.host, self.port = host, port
        self.label = label
        self.deadline = float(deadline)
        self.inflight = max(1, int(inflight))
        self.conns = max(1, int(conns))
        self.coalesce = max(1, int(coalesce))
        self.queue_cap = int(queue_cap)
        self._metrics = metrics  # LaneMetrics (forwarder.py) or None
        self._lock = threading.Lock()
        self._outstanding = 0
        self._closed = False
        self._conns: List[Optional[_PeerConn]] = [None] * self.conns
        # Lifetime wire-frame/row counters (status surface; the metric
        # registry counters are the operational view).
        self.wire_frames = 0
        self.wire_rows = 0

    # ------------------------------------------------------------ submit

    def _admit(self, fut: "concurrent.futures.Future") -> None:
        with self._lock:
            if self._closed or not self.runtime.alive:
                raise StorageUnavailableError(
                    f"fleet forward lane to {self.label} is closed")
            if self._outstanding > self.queue_cap:
                raise StorageUnavailableError(
                    f"fleet forward queue to {self.host}:{self.port} is "
                    f"full ({self.queue_cap} fragments) — peer slow or "
                    f"dead")
            self._outstanding += 1
        fut.add_done_callback(self._release)

    def _release(self, _fut) -> None:
        with self._lock:
            self._outstanding -= 1

    def _dispatch(self, conn_idx: int, item) -> None:
        self.runtime.call_soon(self._loop_enqueue, conn_idx, item)

    def _loop_enqueue(self, conn_idx: int, item) -> None:
        conn = self._conns[conn_idx]
        if conn is None:
            if self._closed:
                _PeerConn._fail_item(item, StorageUnavailableError(
                    f"fleet forward lane to {self.label} is closed"))
                return
            conn = _PeerConn(self, conn_idx)
            self._conns[conn_idx] = conn
        conn.enqueue(item)

    def conn_of(self, h64: np.ndarray) -> np.ndarray:
        """Per-key connection affinity: same finalized hash, same
        connection — always, across frames and lanes — so same-key send
        order survives the multi-connection link."""
        return (np.asarray(h64, np.uint64)
                % np.uint64(self.conns)).astype(np.int64)

    def submit_rows(self, ids: np.ndarray, ns: np.ndarray,
                    conn_idx: int = 0, *,
                    trace: int = 0) -> "concurrent.futures.Future":
        """Queue one columnar fragment (raw u64 ids + ns) on a
        connection; resolves to the BatchResult row-range view of the
        coalesced reply. ``trace`` is the originating frame's trace id
        — linked to the window-level wire id at send (ADR-021)."""
        fut: concurrent.futures.Future = concurrent.futures.Future()
        self._admit(fut)
        self._dispatch(int(conn_idx), _Frag(
            np.ascontiguousarray(ids, dtype=np.uint64),
            np.ascontiguousarray(ns, dtype=np.uint32), fut, trace))
        return fut

    def submit_call(self, build, parse, conn_idx: int = 0,
                    rows: int = 1) -> "concurrent.futures.Future":
        """Queue a scalar/control op (FIFO with the fragments on its
        connection: the op acts as a window boundary)."""
        fut: concurrent.futures.Future = concurrent.futures.Future()
        self._admit(fut)
        self._dispatch(int(conn_idx), _Call(build, parse, fut, rows))
        return fut

    # ----------------------------------------------------------- metrics

    def note_window(self, frames: int, rows: int) -> None:
        self.wire_frames += 1
        self.wire_rows += rows
        m = self._metrics
        if m is not None:
            m.window(self.label, frames, rows)

    def note_rtt(self, seconds: float) -> None:
        m = self._metrics
        if m is not None:
            m.rtt(seconds)

    # ------------------------------------------------------------- close

    def close(self) -> None:
        with self._lock:
            self._closed = True
        if not self.runtime.alive:
            return

        def _close_all() -> None:
            for conn in self._conns:
                if conn is not None:
                    conn.close()

        self.runtime.call_soon(_close_all)
