"""Fleet membership: announce/heartbeat gossip + per-range failover
(ADR-017).

Every fleet member runs one ``FleetMembership``: a background thread
announces this host's view of the ownership map to every peer each
``heartbeat`` seconds (T_DCN_PUSH kind=DCN_KIND_FLEET over the existing
authenticated DCN channel — RLA2 HMAC + replay guard when a secret is
held, ADR-007), and the same thread watches peer liveness:

* an announce from a peer refreshes its ``last_seen`` and, when it
  carries a HIGHER epoch, installs that map (highest epoch wins — the
  fleet's only convergence rule, sufficient because every ownership
  change bumps the epoch exactly once at the host that made it);
* a peer silent past ``dead_after`` (or accumulating
  ``failure_threshold`` quarantine-classified forward failures, the
  ADR-015 classifier) is declared dead;
* if this host is the configured SUCCESSOR for a dead peer's ranges, it
  fails them over: build a standby unit restored from the dead peer's
  newest snapshot + WAL suffix (``adopt_fn`` — restore-before-rejoin,
  the same contract as slice quarantine), mount it for the adopted
  buckets, install the reassigned map at ``epoch + 1``, and announce it
  immediately so routers and peers converge.

Announce reception is PASSIVE for followers: a member that is not the
successor simply learns the new map from the successor's announce (or
keeps forwarding — mis-routed rows stay correct either way, they just
pay a hop).
"""

from __future__ import annotations

import itertools
import logging
import threading
import time
from typing import Callable, Dict, Optional

from ratelimiter_tpu.fleet.config import FleetHost, FleetMap
from ratelimiter_tpu.fleet.forwarder import FleetCore
from ratelimiter_tpu.observability import metrics as m

log = logging.getLogger("ratelimiter_tpu.fleet")


class FleetMembership:
    """Announce/heartbeat + liveness + failover for one fleet member.

    Args:
        core: the process's FleetCore (map swaps and adopted-unit
            mounting go through it).
        heartbeat: seconds between announce pushes.
        dead_after: declare a previously-seen peer dead after this many
            seconds of silence.
        boot_grace: never-seen peers can only be declared dead after
            this many seconds from OUR start (default
            ``max(3 * dead_after, 15)``): a fleet starts in arbitrary
            order and a member still prewarming its jit shapes is not
            dead — failing it over at boot would fork its ranges the
            moment it finally serves (rejoin is never automatic).
        failure_threshold: quarantine-classified forward failures
            (FleetCore.on_peer_failure) before a peer is treated as
            dead without waiting out ``dead_after``.
        adopt_fn: ``adopt_fn(dead: FleetHost) -> limiter`` — build the
            standby unit for the dead host's ranges, restored from its
            ``snapshot_dir`` when reachable (wired by the server binary
            to the persistence tier). None disables adoption (ranges
            degrade per policy until an operator acts).
        secret: DCN shared secret; announces ride the RLA2 envelope.
    """

    def __init__(self, core: FleetCore, *, heartbeat: float = 0.5,
                 dead_after: float = 2.0, failure_threshold: int = 3,
                 boot_grace: Optional[float] = None,
                 adopt_fn: Optional[Callable[[FleetHost], object]] = None,
                 secret: Optional[str] = None,
                 registry: Optional[m.Registry] = None):
        import secrets as _secrets

        self.core = core
        self.heartbeat = float(heartbeat)
        self.dead_after = float(dead_after)
        self.boot_grace = (float(boot_grace) if boot_grace is not None
                           else max(3.0 * self.dead_after, 15.0))
        self.failure_threshold = int(failure_threshold)
        self.adopt_fn = adopt_fn
        self.secret = secret
        self._sender = _secrets.randbits(64)
        self._last_seq = 0
        self._ids = itertools.count(1)
        self._lock = threading.Lock()
        self._last_seen: Dict[str, float] = {}
        self._peer_epoch: Dict[str, int] = {}
        self._failures: Dict[str, int] = {}
        self._dead: set = set()
        self._started_at = time.monotonic()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._conns: Dict[str, object] = {}
        self.failovers = 0
        reg = registry if registry is not None else m.DEFAULT
        self._g_alive = reg.gauge(
            "rate_limiter_fleet_peer_alive",
            "1 while this fleet peer is considered live (announce heard "
            "within dead_after), 0 once declared dead")
        self._c_failovers = reg.counter(
            "rate_limiter_fleet_failovers_total",
            "Per-range failovers this host performed as successor")
        self._c_announces = reg.counter(
            "rate_limiter_fleet_announces_total",
            "Fleet announce frames sent (ok) / failed, by outcome")
        core.on_peer_failure = self.note_peer_failure

    # ---------------------------------------------------------- announce

    def _next_seq(self) -> int:
        # Wall-clock-tracking monotonic sequence, same contract as
        # DcnPusher._next_seq (the replay guard reads seq as a coarse
        # timestamp for first-contact freshness).
        self._last_seq = max(self._last_seq + 1, int(time.time() * 1e6))
        return self._last_seq

    def announce_payload(self) -> dict:
        return {"kind": "announce", "from": self.core.self_id,
                "map": self.core.map_payload(),
                "sent_at": time.time()}

    def announce_once(self) -> int:
        """Push one announce to every peer; returns deliveries. Never
        raises — a dead peer's connection failure is exactly the signal
        the OTHER side's monitor consumes."""
        from ratelimiter_tpu.serving import protocol as p
        from ratelimiter_tpu.serving.dcn_peer import _PeerConn

        payload = self.announce_payload()
        delivered = 0
        for host in self.core.map.hosts:
            if host.id == self.core.self_id:
                continue
            with self._lock:
                if host.id in self._dead:
                    continue
            req_id = next(self._ids)
            frame = p.encode_dcn_fleet(
                req_id, payload, secret=self.secret, sender=self._sender,
                seq=(self._next_seq() if self.secret is not None
                     else None))
            conn = self._conns.get(host.id)
            if conn is None or (conn.host, conn.port) != (host.host,
                                                          host.port):
                conn = _PeerConn(host.host, host.port, timeout=2.0)
                self._conns[host.id] = conn
            try:
                conn.push(frame, req_id)
                delivered += 1
                self._c_announces.inc(outcome="ok")
            except Exception as exc:  # noqa: BLE001 — liveness signal
                self._c_announces.inc(outcome="error")
                log.debug("fleet announce to %s (%s) failed: %s",
                          host.id, host.addr, exc)
        return delivered

    def handle_announce(self, payload: dict) -> None:
        """Receive path (both doors funnel DCN_KIND_FLEET here via
        dcn_peer.merge_push_payload's on_fleet hook)."""
        peer = str(payload.get("from", ""))
        if not peer or peer == self.core.self_id:
            return
        map_d = payload.get("map") or {}
        epoch = int(map_d.get("epoch", 0))
        with self._lock:
            self._last_seen[peer] = time.monotonic()
            self._peer_epoch[peer] = epoch
            self._failures[peer] = 0
            was_dead = peer in self._dead
            if was_dead:
                # A declared-dead peer announcing again is back AS A
                # MEMBER (liveness), but its ranges stay wherever the
                # epoch says they are — rejoining ownership is an
                # operator/resharding action (ROADMAP item 2), never
                # automatic (two hosts serving one range would split
                # counters).
                self._dead.discard(peer)
        self._g_alive.set(1.0, peer=peer)
        if was_dead:
            self.core.set_dead([self.core.map.ordinal(p_id)
                                for p_id in self._dead
                                if self._in_map(p_id)])
        if epoch > self.core.map.epoch:
            try:
                new_map = FleetMap.from_dict(map_d)
            except Exception as exc:  # noqa: BLE001 — bad gossip
                log.warning("fleet announce from %s carried an invalid "
                            "map (%s); ignoring", peer, exc)
                return
            log.info("fleet: adopting map epoch %d from %s (was %d)",
                     epoch, peer, self.core.map.epoch)
            self.core.swap_map(new_map)

    def _in_map(self, host_id: str) -> bool:
        return any(h.id == host_id for h in self.core.map.hosts)

    # ---------------------------------------------------------- liveness

    def note_peer_failure(self, host_id: str, exc: BaseException) -> None:
        """Forward-path failure sink (FleetCore.on_peer_failure): only
        quarantine-classified backend faults count toward death — a
        caller error must never fail a healthy peer over (ADR-015)."""
        from ratelimiter_tpu.parallel.quarantine import classify_failure

        if not classify_failure(exc):
            return
        with self._lock:
            self._failures[host_id] = self._failures.get(host_id, 0) + 1

    def _check_dead(self) -> None:
        now = time.monotonic()
        grace_until = self._started_at + self.boot_grace
        newly_dead = []
        with self._lock:
            for host in self.core.map.hosts:
                hid = host.id
                if hid == self.core.self_id or hid in self._dead:
                    continue
                seen = self._last_seen.get(hid)
                silent = (now - seen > self.dead_after if seen is not None
                          else now > grace_until)
                failed = self._failures.get(hid, 0) >= self.failure_threshold
                if silent or failed:
                    self._dead.add(hid)
                    newly_dead.append((host, "silence" if silent
                                       else "forward failures"))
        for host, why in newly_dead:
            self._g_alive.set(0.0, peer=host.id)
            log.warning("fleet peer %s (%s) declared dead (%s)",
                        host.id, host.addr, why)
            self.core.set_dead([self.core.map.ordinal(p_id)
                                for p_id in self._dead
                                if self._in_map(p_id)])
            self._maybe_failover(host)

    # ---------------------------------------------------------- failover

    def _maybe_failover(self, dead: FleetHost) -> None:
        cur = self.core.map.host(dead.id)
        if not cur.ranges:
            return  # already failed over (or never owned anything)
        if cur.successor != self.core.self_id:
            return  # somebody else's job; we learn the map via announce
        log.warning("fleet: failing over %s's ranges %s to %s "
                    "(epoch %d -> %d)", dead.id,
                    [list(r) for r in cur.ranges], self.core.self_id,
                    self.core.map.epoch, self.core.map.epoch + 1)
        unit = None
        if self.adopt_fn is not None:
            try:
                unit = self.adopt_fn(cur)
            except Exception:  # noqa: BLE001 — adopt empty instead
                log.exception("fleet: standby restore for %s failed; "
                              "adopting the range with FRESH state "
                              "(under-counts, fail-toward-allowing)",
                              dead.id)
        new_map = self.core.map.reassign(dead.id, self.core.self_id)
        if unit is not None:
            # Mount BEFORE the map swap: the instant the swap makes the
            # buckets local, routing finds the restored unit
            # (restore-before-rejoin; a gap would decide adopted keys
            # on empty state).
            self.core.install_adopted(unit, cur.ranges)
            self.core.swap_map(new_map)
        else:
            self.core.swap_map(new_map)
        self.failovers += 1
        self._c_failovers.inc()
        # Converge fast: don't wait a heartbeat to tell the fleet.
        self.announce_once()

    # --------------------------------------------------------- lifecycle

    def start(self) -> None:
        if self._thread is not None:
            return
        self._stop.clear()

        def loop() -> None:
            while not self._stop.wait(self.heartbeat):
                try:
                    self.announce_once()
                    self._check_dead()
                except Exception:  # noqa: BLE001 — keep the heart beating
                    log.exception("fleet membership cycle failed")

        self._thread = threading.Thread(target=loop, daemon=True,
                                        name="rl-fleet-membership")
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10)
            self._thread = None
        for conn in self._conns.values():
            try:
                conn.close()
            except Exception:  # noqa: BLE001 — teardown best-effort
                pass
        self._conns.clear()

    # ----------------------------------------------------------- surface

    def status(self) -> dict:
        now = time.monotonic()
        with self._lock:
            peers = {}
            for host in self.core.map.hosts:
                if host.id == self.core.self_id:
                    continue
                seen = self._last_seen.get(host.id)
                peers[host.id] = {
                    "addr": host.addr,
                    "alive": host.id not in self._dead,
                    "last_seen_age_s": (round(now - seen, 3)
                                        if seen is not None else None),
                    "epoch": self._peer_epoch.get(host.id),
                    "ranges": [list(r) for r in
                               self.core.map.host(host.id).ranges],
                }
        return {"peers": peers, "failovers": self.failovers,
                "heartbeat_s": self.heartbeat,
                "dead_after_s": self.dead_after}
