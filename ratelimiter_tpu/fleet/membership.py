"""Fleet membership: announce/heartbeat gossip + per-range failover
(ADR-017).

Every fleet member runs one ``FleetMembership``: a background thread
announces this host's view of the ownership map to every peer each
``heartbeat`` seconds (T_DCN_PUSH kind=DCN_KIND_FLEET over the existing
authenticated DCN channel — RLA2 HMAC + replay guard when a secret is
held, ADR-007), and the same thread watches peer liveness:

* an announce from a peer refreshes its ``last_seen`` and, when it
  carries a HIGHER epoch, installs that map (highest epoch wins — the
  fleet's only convergence rule, sufficient because every ownership
  change bumps the epoch exactly once at the host that made it);
* a peer silent past ``dead_after`` (or accumulating
  ``failure_threshold`` quarantine-classified forward failures, the
  ADR-015 classifier) is declared dead;
* if this host is the configured SUCCESSOR for a dead peer's ranges, it
  fails them over: build a standby unit restored from the dead peer's
  newest snapshot + WAL suffix (``adopt_fn`` — restore-before-rejoin,
  the same contract as slice quarantine), mount it for the adopted
  buckets, install the reassigned map at ``epoch + 1``, and announce it
  immediately so routers and peers converge.

Announce reception is PASSIVE for followers: a member that is not the
successor simply learns the new map from the successor's announce (or
keeps forwarding — mis-routed rows stay correct either way, they just
pay a hop).

**Elastic lifecycle (ADR-018).** Beyond failover, the same channel moves
ranges between LIVE hosts with one handoff protocol — live migration
(``migrate_ranges``), graceful departure (``depart``, the rolling-restart
drain), and automatic rejoin give-back (a declared-dead peer announcing
again gets its adopted ranges handed back). Every move follows
capture -> WAL-suffix replay -> flip:

1. the GIVING side snapshots (``snapshot_fn`` — the handoff artifact
   lands in its ``snapshot_dir``, reachable from the receiver) and sends
   an authenticated ``handoff`` frame naming the ranges and carrying the
   PROPOSED map at ``epoch + 1``;
2. the RECEIVING side restores a standby from the artifact + WAL suffix
   (``handoff_restore_fn``), mounts it, installs the proposed map, and
   announces — only the receiver ever publishes ``epoch + 1``, and only
   AFTER its restore: a crash at any point leaves exactly one owner per
   range per epoch (the giver at ``epoch``, or the receiver at
   ``epoch + 1``);
3. the giver learns the flip from the announce; its copy of the moved
   ranges becomes inert (rows forward to the new owner), and adopted
   masks reconcile against the new map (``sync_adopted_with_map``).

Counter loss is bounded by the handoff window (decisions between the
capture and the flip), in the under-counting, fail-toward-allowing
direction; overrides replay exactly from the WAL.
"""

from __future__ import annotations

import itertools
import logging
import threading
import time
from typing import Callable, Dict, Optional

from ratelimiter_tpu.fleet.config import FleetHost, FleetMap
from ratelimiter_tpu.fleet.forwarder import FleetCore
from ratelimiter_tpu.observability import events, tracing
from ratelimiter_tpu.observability import metrics as m

log = logging.getLogger("ratelimiter_tpu.fleet")


class FleetMembership:
    """Announce/heartbeat + liveness + failover for one fleet member.

    Args:
        core: the process's FleetCore (map swaps and adopted-unit
            mounting go through it).
        heartbeat: seconds between announce pushes.
        dead_after: declare a previously-seen peer dead after this many
            seconds of silence.
        boot_grace: never-seen peers can only be declared dead after
            this many seconds from OUR start (default
            ``max(3 * dead_after, 15)``): a fleet starts in arbitrary
            order and a member still prewarming its jit shapes is not
            dead — failing it over at boot would fork its ranges the
            moment it finally serves (rejoin is never automatic).
        failure_threshold: quarantine-classified forward failures
            (FleetCore.on_peer_failure) before a peer is treated as
            dead without waiting out ``dead_after``.
        adopt_fn: ``adopt_fn(dead: FleetHost) -> limiter`` — build the
            standby unit for the dead host's ranges, restored from its
            ``snapshot_dir`` when reachable (wired by the server binary
            to the persistence tier). None disables adoption (ranges
            degrade per policy until an operator acts).
        snapshot_fn: take one snapshot NOW (PersistenceManager
            .snapshot_now) — the capture half of every handoff; None
            means handoffs ship from the newest existing snapshot.
        handoff_restore_fn: ``fn(payload) -> limiter | None`` — build
            the restored standby for an incoming handoff (wired to
            fleet/handoff.build_standby). None adopts handed ranges
            with fresh state (under-counts, fail-toward-allowing).
        on_adopt: ``fn(origin, unit, ranges)`` — a standby unit was
            mounted for ``origin``'s ranges; the binary wires this to
            PersistenceManager.add_aux_unit so adopted state rides this
            host's own snapshot cycle (ADR-018, satellite of ADR-017).
        on_release: ``fn(origin)`` — the origin took its ranges back.
        absorb_fn: ``fn(unit) -> bool`` — fold a handoff unit whose
            ORIGIN IS THIS HOST (a rejoin give-back) into the main
            serving limiter instead of mounting it as an adopted
            standby: the ranges then serve on the full pipelined path
            and ride the normal snapshot files, no aux cycle needed.
            Return False to fall back to the adopted mount. The fold
            is the conservative union (parallel/reshard.py); decisions
            landing between its capture and restore are lost —
            sub-second, under-count only, once per rejoin.
        auto_rejoin: hand a returning (previously declared dead) peer
            its adopted ranges back automatically via the handoff
            protocol. True by default — the zero-operator lifecycle;
            False preserves the ADR-017 manual posture.
        secret: DCN shared secret; announces ride the RLA2 envelope.
    """

    def __init__(self, core: FleetCore, *, heartbeat: float = 0.5,
                 dead_after: float = 2.0, failure_threshold: int = 3,
                 boot_grace: Optional[float] = None,
                 adopt_fn: Optional[Callable[[FleetHost], object]] = None,
                 snapshot_fn: Optional[Callable[[], dict]] = None,
                 handoff_restore_fn: Optional[Callable] = None,
                 on_adopt: Optional[Callable] = None,
                 on_release: Optional[Callable[[str], None]] = None,
                 absorb_fn: Optional[Callable] = None,
                 auto_rejoin: bool = True,
                 secret: Optional[str] = None,
                 hier_payload_fn: Optional[Callable[[], dict]] = None,
                 hier_apply_fn: Optional[Callable[[dict], bool]] = None,
                 registry: Optional[m.Registry] = None):
        import secrets as _secrets

        self.core = core
        self.heartbeat = float(heartbeat)
        self.dead_after = float(dead_after)
        self.boot_grace = (float(boot_grace) if boot_grace is not None
                           else max(3.0 * self.dead_after, 15.0))
        self.failure_threshold = int(failure_threshold)
        self.adopt_fn = adopt_fn
        self.snapshot_fn = snapshot_fn
        self.handoff_restore_fn = handoff_restore_fn
        self.on_adopt = on_adopt
        self.on_release = on_release
        self.absorb_fn = absorb_fn
        self.auto_rejoin = bool(auto_rejoin)
        self.secret = secret
        #: Hierarchy effective-limit gossip (ADR-020): when set, every
        #: announce carries the local cascade's revision-stamped
        #: effective-limit frame and every received announce offers its
        #: peer's frame to the local table (last-writer-wins on
        #: revision) — the AIMD controller's fleet convergence path.
        self.hier_payload_fn = hier_payload_fn
        self.hier_apply_fn = hier_apply_fn
        self._sender = _secrets.randbits(64)
        self._last_seq = 0
        self._ids = itertools.count(1)
        self._lock = threading.Lock()
        self._last_seen: Dict[str, float] = {}
        self._peer_epoch: Dict[str, int] = {}
        #: Per-peer clock alignment (ADR-021 trace stitching): announce
        #: frames carry the sender's CLOCK_MONOTONIC ns; on receipt we
        #: note delta_in = our_mono - sender_mono (true offset + one-way
        #: delay), and our own announce pushes to that peer measure the
        #: round trip (push waits for the T_OK ack). offset ≈ delta_in -
        #: rtt/2 maps the peer's span/event timestamps into OUR
        #: monotonic domain (t_mine = t_peer + offset) — the NTP
        #: estimate, good to ~rtt/2 (sub-ms on a LAN, exactly the
        #: precision a cross-host Perfetto lane needs). BOTH sides
        #: min-filter over a short window: delay (connect handshakes,
        #: GC pauses, a loaded receive path) only ever INFLATES
        #: delta_in exactly as it inflates RTT — a latest-sample
        #: delta against a min RTT would shift a lane by one slow
        #: announce's full delay.
        self._peer_deltas: Dict[str, list] = {}
        self._peer_rtts: Dict[str, list] = {}
        self._failures: Dict[str, int] = {}
        self._dead: set = set()
        self._started_at = time.monotonic()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._conns: Dict[str, object] = {}
        self.failovers = 0
        self.handoffs = 0            # completed incoming handoffs
        self.rejoins = 0             # adopted ranges handed back
        self._rejoin_pending: set = set()
        self._rejoin_inflight: set = set()
        #: origin -> monotonic time of the last give-back attempt; a
        #: flapping origin (the rejoin-storm shape) must not drive one
        #: full snapshot per heartbeat cycle.
        self._rejoin_last: Dict[str, float] = {}
        self.rejoin_backoff = max(2.0 * self.dead_after, 5.0)
        self._handoff_lock = threading.Lock()
        #: Serializes frame pushes: announce_once runs on the
        #: membership thread AND at the end of a handoff (its own
        #: thread); _PeerConn sockets and the seq counter are not
        #: otherwise thread-safe — interleaved sends would corrupt
        #: frames / desync acks / emit out-of-order seqs the replay
        #: guard rejects.
        self._send_lock = threading.Lock()
        reg = registry if registry is not None else m.DEFAULT
        self._g_alive = reg.gauge(
            "rate_limiter_fleet_peer_alive",
            "1 while this fleet peer is considered live (announce heard "
            "within dead_after), 0 once declared dead")
        self._c_failovers = reg.counter(
            "rate_limiter_fleet_failovers_total",
            "Per-range failovers this host performed as successor")
        self._c_announces = reg.counter(
            "rate_limiter_fleet_announces_total",
            "Fleet announce frames sent (ok) / failed, by outcome")
        self._c_handoffs = reg.counter(
            "rate_limiter_fleet_handoffs_total",
            "Range handoffs (live migration / departure / rejoin "
            "give-back), by role (send/receive) and reason")
        self._c_rejoins = reg.counter(
            "rate_limiter_fleet_rejoins_total",
            "Adopted ranges handed back to a returning origin host")
        core.on_peer_failure = self.note_peer_failure

    # ---------------------------------------------------------- announce

    def _next_seq(self) -> int:
        # Wall-clock-tracking monotonic sequence, same contract as
        # DcnPusher._next_seq (the replay guard reads seq as a coarse
        # timestamp for first-contact freshness).
        self._last_seq = max(self._last_seq + 1, int(time.time() * 1e6))
        return self._last_seq

    def announce_payload(self) -> dict:
        out = {"kind": "announce", "from": self.core.self_id,
               "map": self.core.map_payload(),
               "sent_at": time.time(),
               # Sender's span clock (CLOCK_MONOTONIC ns, the ADR-014
               # domain): receivers estimate the cross-host clock
               # offset from it (see _peer_delta_in above).
               "mono_ns": time.monotonic_ns()}
        if self.hier_payload_fn is not None:
            try:
                out["hier"] = self.hier_payload_fn()
            except Exception:  # noqa: BLE001 — gossip rides best-effort
                log.exception("fleet: hierarchy payload hook failed")
        return out

    def _push_frame(self, host: FleetHost, payload: dict) -> None:
        """Encode + push one DCN fleet frame to ``host`` (raises on
        delivery failure). Serialized on ``_send_lock``: the heartbeat
        thread and a handoff thread share the peer connections and the
        monotonic seq."""
        from ratelimiter_tpu.serving import protocol as p
        from ratelimiter_tpu.serving.dcn_peer import _PeerConn

        with self._send_lock:
            req_id = next(self._ids)
            if payload.get("kind") == "announce":
                # Re-stamp the span clock PER PUSH (the shared payload
                # was built before earlier peers' round trips): the
                # offset estimate's one-way-delay term must be this
                # push's, not the announce cycle's.
                payload = {**payload, "mono_ns": time.monotonic_ns()}
            frame = p.encode_dcn_fleet(
                req_id, payload, secret=self.secret, sender=self._sender,
                seq=(self._next_seq() if self.secret is not None
                     else None))
            conn = self._conns.get(host.id)
            if conn is None or (conn.host, conn.port) != (host.host,
                                                          host.port):
                conn = _PeerConn(host.host, host.port, timeout=2.0)
                self._conns[host.id] = conn
            t0 = time.monotonic_ns()
            conn.push(frame, req_id)
            rtt = time.monotonic_ns() - t0
        # Round trip of push -> T_OK ack: the one-way-delay estimate in
        # the peer clock offset. Keep a short window and use its MIN
        # (first-connect handshakes and GC pauses only ever inflate).
        with self._lock:
            rtts = self._peer_rtts.setdefault(host.id, [])
            rtts.append(rtt)
            del rtts[:-8]

    def announce_once(self) -> int:
        """Push one announce to every peer; returns deliveries. Never
        raises — a dead peer's connection failure is exactly the signal
        the OTHER side's monitor consumes."""
        payload = self.announce_payload()
        delivered = 0
        for host in self.core.map.hosts:
            if host.id == self.core.self_id:
                continue
            with self._lock:
                if host.id in self._dead:
                    continue
            try:
                self._push_frame(host, payload)
                delivered += 1
                self._c_announces.inc(outcome="ok")
            except Exception as exc:  # noqa: BLE001 — liveness signal
                self._c_announces.inc(outcome="error")
                log.debug("fleet announce to %s (%s) failed: %s",
                          host.id, host.addr, exc)
        return delivered

    def handle_announce(self, payload: dict) -> None:
        """Receive path (both doors funnel DCN_KIND_FLEET here via
        dcn_peer.merge_push_payload's on_fleet hook). Dispatches on the
        payload ``kind``: ``announce`` (liveness + map gossip) or
        ``handoff`` (an ownership move addressed to this host,
        ADR-018)."""
        if payload.get("kind") == "handoff":
            # Off the receive path: a standby restore can take seconds
            # (snapshot load + jit); the door must keep serving. The
            # per-membership handoff lock serializes concurrent moves.
            threading.Thread(target=self._handle_handoff,
                             args=(payload,), daemon=True,
                             name="rl-fleet-handoff").start()
            return
        peer = str(payload.get("from", ""))
        if not peer or peer == self.core.self_id:
            return
        map_d = payload.get("map") or {}
        epoch = int(map_d.get("epoch", 0))
        mono = payload.get("mono_ns")
        with self._lock:
            self._last_seen[peer] = time.monotonic()
            self._peer_epoch[peer] = epoch
            self._failures[peer] = 0
            if mono is not None:
                # Offset raw material: our mono at receipt minus the
                # peer's mono at send (= true offset + one-way delay;
                # the delay half subtracts out in peer_clock()).
                # Min-filtered like the RTTs — see _peer_deltas above.
                deltas = self._peer_deltas.setdefault(peer, [])
                deltas.append(time.monotonic_ns() - int(mono))
                del deltas[:-8]
            was_dead = peer in self._dead
            if was_dead:
                # A declared-dead peer announcing again is back AS A
                # MEMBER (liveness); its ranges stay wherever the epoch
                # says they are until the HANDOFF protocol moves them —
                # with auto_rejoin, this host (if it adopted the peer's
                # ranges) snapshots the standby and hands them back
                # (restore-before-rejoin on the peer's side); never by
                # the peer simply reappearing (two hosts serving one
                # range would split counters — single owner per epoch,
                # ADR-018).
                self._dead.discard(peer)
        if was_dead:
            events.emit("membership", "peer-returned", actor=peer,
                        payload={"epoch": epoch})
        self._g_alive.set(1.0, peer=peer)
        hier = payload.get("hier")
        if hier and self.hier_apply_fn is not None:
            # Before the steady-state map short-circuit below: effective
            # limits move independently of map epochs (the controller
            # ticks far more often than ownership changes).
            try:
                self.hier_apply_fn(hier)
            except Exception:  # noqa: BLE001 — gossip is best-effort
                log.exception("fleet: hierarchy apply hook failed")
        if was_dead:
            self.core.set_dead([self.core.map.ordinal(p_id)
                                for p_id in self._dead
                                if self._in_map(p_id)])
            if (self.auto_rejoin
                    and self.core.adopted_origin_ranges(peer)):
                # Queue for the membership loop (the receive path must
                # stay cheap; the give-back snapshots + pushes).
                with self._lock:
                    self._rejoin_pending.add(peer)
        cur = self.core.map
        if epoch < cur.epoch:
            return
        if epoch == cur.epoch and map_d == cur.to_dict():
            return  # steady state: same map gossiped back
        try:
            new_map = FleetMap.from_dict(map_d)
        except Exception as exc:  # noqa: BLE001 — bad gossip
            log.warning("fleet announce from %s carried an invalid "
                        "map (%s); ignoring", peer, exc)
            return
        if epoch == cur.epoch:
            # Two uncoordinated movers can mint the SAME epoch
            # concurrently (each proposed cur+1). Without a tiebreak
            # the fleet splits permanently — every member keeps
            # whichever map it heard first. Deterministic rule: the
            # smaller canonical key wins everywhere; the losing
            # mover's flip stays unconfirmed (the ownership check in
            # migrate_ranges) and retries at a higher epoch.
            if new_map.canonical_key() >= cur.canonical_key():
                return
            log.warning("fleet: equal-epoch map conflict at %d; "
                        "adopting the canonical winner from %s",
                        epoch, peer)
        else:
            log.info("fleet: adopting map epoch %d from %s (was %d)",
                     epoch, peer, cur.epoch)
        self.core.swap_map(new_map)
        self._reconcile_adopted()

    def _reconcile_adopted(self) -> None:
        """After any map swap: drop adopted-mask bits the new epoch
        assigns elsewhere and release fully-returned origins (their aux
        snapshots stop; the unit's leftover state is inert)."""
        for origin in self.core.sync_adopted_with_map():
            log.info("fleet: origin %s took its ranges back; released "
                     "the adopted mask for it", origin)
            if self.on_release is not None:
                try:
                    self.on_release(origin)
                except Exception:  # noqa: BLE001 — bookkeeping only
                    log.exception("fleet on_release(%s) failed", origin)

    def _in_map(self, host_id: str) -> bool:
        return any(h.id == host_id for h in self.core.map.hosts)

    # ---------------------------------------------------------- liveness

    def _peer_clock_locked(self, host_id: str) -> dict:
        """``self._lock`` held. The ONE offset estimator (peer_clock
        and status() both render it — server-side and offline stitches
        must agree on alignment): min over the window on BOTH terms,
        since delay only ever inflates delta_in exactly as it inflates
        RTT."""
        deltas = self._peer_deltas.get(host_id, ())
        rtts = self._peer_rtts.get(host_id, ())
        rtt = min(rtts) if rtts else None
        if not deltas:
            return {"offset_ns": None, "rtt_ns": rtt}
        return {"offset_ns": int(min(deltas) - (rtt or 0) // 2),
                "rtt_ns": rtt}

    def peer_clock(self, host_id: str) -> dict:
        """Estimated mapping of ``host_id``'s CLOCK_MONOTONIC domain
        into OURS: ``t_mine ≈ t_peer + offset_ns`` (ADR-021 trace/event
        stitching). ``offset_ns`` is None until the peer's first
        announce lands; ``rtt_ns`` is the min observed announce round
        trip (None until we delivered one)."""
        with self._lock:
            return self._peer_clock_locked(host_id)

    def note_peer_failure(self, host_id: str, exc: BaseException) -> None:
        """Forward-path failure sink (FleetCore.on_peer_failure): only
        quarantine-classified backend faults count toward death — a
        caller error must never fail a healthy peer over (ADR-015)."""
        from ratelimiter_tpu.parallel.quarantine import classify_failure

        if not classify_failure(exc):
            return
        with self._lock:
            self._failures[host_id] = self._failures.get(host_id, 0) + 1

    def _check_dead(self) -> None:
        now = time.monotonic()
        grace_until = self._started_at + self.boot_grace
        newly_dead = []
        with self._lock:
            for host in self.core.map.hosts:
                hid = host.id
                if hid == self.core.self_id or hid in self._dead:
                    continue
                seen = self._last_seen.get(hid)
                silent = (now - seen > self.dead_after if seen is not None
                          else now > grace_until)
                failed = self._failures.get(hid, 0) >= self.failure_threshold
                if silent or failed:
                    self._dead.add(hid)
                    newly_dead.append((host, "silence" if silent
                                       else "forward failures"))
        for host, why in newly_dead:
            self._g_alive.set(0.0, peer=host.id)
            log.warning("fleet peer %s (%s) declared dead (%s)",
                        host.id, host.addr, why)
            events.emit("membership", "peer-dead", actor=host.id,
                        severity="warning",
                        payload={"reason": why, "addr": host.addr})
            self.core.set_dead([self.core.map.ordinal(p_id)
                                for p_id in self._dead
                                if self._in_map(p_id)])
            self._maybe_failover(host)

    # ---------------------------------------------------------- failover

    def _maybe_failover(self, dead: FleetHost) -> None:
        cur = self.core.map.host(dead.id)
        if not cur.ranges:
            return  # already failed over (or never owned anything)
        if cur.successor != self.core.self_id:
            return  # somebody else's job; we learn the map via announce
        log.warning("fleet: failing over %s's ranges %s to %s "
                    "(epoch %d -> %d)", dead.id,
                    [list(r) for r in cur.ranges], self.core.self_id,
                    self.core.map.epoch, self.core.map.epoch + 1)
        unit = None
        if self.adopt_fn is not None:
            try:
                unit = self.adopt_fn(cur)
            except Exception:  # noqa: BLE001 — adopt empty instead
                log.exception("fleet: standby restore for %s failed; "
                              "adopting the range with FRESH state "
                              "(under-counts, fail-toward-allowing)",
                              dead.id)
        new_map = self.core.map.reassign(dead.id, self.core.self_id)
        # Mount + swap atomically w.r.t. mask reconciliation (the mount
        # precedes the swap inside, restore-before-rejoin: the instant
        # the swap makes the buckets local, routing finds the restored
        # unit — a gap would decide adopted keys on empty state).
        self.core.install_and_swap(unit, cur.ranges, new_map,
                                   origin=dead.id)
        if unit is not None:
            self._notify_adopt(dead.id, cur.ranges)
        self.failovers += 1
        self._c_failovers.inc()
        events.emit("failover", "adopt-ranges", actor=dead.id,
                    severity="warning",
                    payload={"successor": self.core.self_id,
                             "ranges": [list(r) for r in cur.ranges],
                             "epoch": new_map.epoch,
                             "restored": unit is not None})
        # Converge fast: don't wait a heartbeat to tell the fleet.
        self.announce_once()

    def _notify_adopt(self, origin: str, ranges) -> None:
        """Fold the (possibly merged) standby unit into this host's own
        snapshot cycle under ``origin``'s name (ADR-018: a second
        failure after adoption must not lose the adopted counters)."""
        if self.on_adopt is None:
            return
        try:
            self.on_adopt(origin, self.core.adopted_unit, ranges)
        except Exception:  # noqa: BLE001 — durability bookkeeping only
            log.exception("fleet on_adopt(%s) failed; adopted state "
                          "will not ride this host's snapshots", origin)

    # ---------------------------------------------------------- handoffs

    def _chaos_phase(self, phase: str) -> None:
        from ratelimiter_tpu import chaos

        if chaos.INJECTOR is not None:
            chaos.INJECTOR.handoff_phase(phase)

    def migrate_ranges(self, ranges, to_id: str, *,
                       reason: str = "migrate",
                       origin: Optional[str] = None,
                       wait: float = 10.0) -> bool:
        """Move owned bucket ``ranges`` to live host ``to_id`` with zero
        downtime: capture (fresh snapshot into our ``snapshot_dir``, the
        handoff artifact) -> send the authenticated handoff frame naming
        the PROPOSED map at epoch+1 -> the receiver restores the ranges'
        state (+ WAL suffix) and is the ONLY side that publishes the
        bump, after its restore. We keep serving the ranges until the
        flip lands (stale routers then get forwarded rows / E_NOT_OWNER
        redirects, the ADR-017 window). Returns True once this host has
        seen the flipped epoch, False on timeout (ownership unchanged —
        the move either fully happened or not at all).

        ``origin`` names whose state travels: None ships this host's
        OWN unit (migration / departure); a host id ships that origin's
        adopted standby (the rejoin give-back) so the returning owner
        restores exactly its ranges from our aux snapshot."""
        cur = self.core.map
        me = cur.host(self.core.self_id)
        ranges = tuple((int(lo), int(hi)) for lo, hi in ranges)
        proposed = cur.move_ranges(ranges, self.core.self_id, to_id)
        if proposed.epoch == cur.epoch:   # nothing to move
            return True
        # One correlation id for the whole move: stamped on the send /
        # receive / confirm journal events ON BOTH SIDES (it rides the
        # handoff frame), so an operator can follow one migration
        # across hosts from /debug/events?fleet=1 alone (ADR-021).
        corr = tracing.new_trace_id()
        self._chaos_phase("capture")
        if self.snapshot_fn is not None:
            try:
                self.snapshot_fn()
            except Exception:  # noqa: BLE001 — ship the previous one
                log.exception(
                    "fleet handoff: capture snapshot failed; handing "
                    "off from the newest existing snapshot (counters "
                    "lose up to one interval, fail-toward-allowing)")
        payload = {"kind": "handoff", "from": self.core.self_id,
                   "to": to_id, "reason": reason,
                   "ranges": [list(r) for r in ranges],
                   "map": proposed.to_dict(),
                   "snapshot_dir": me.snapshot_dir,
                   "sent_at": time.time(),
                   "corr": f"{corr:016x}"}
        if origin is not None:
            payload["origin"] = origin
        try:
            self._push_frame(cur.host(to_id), payload)
            self._c_handoffs.inc(role="send", reason=reason)
            events.emit("handoff", "send", actor=to_id, corr=corr,
                        payload={"reason": reason, "origin": origin,
                                 "ranges": [list(r) for r in ranges],
                                 "proposed_epoch": proposed.epoch})
        except Exception as exc:  # noqa: BLE001 — move simply didn't happen
            log.warning("fleet handoff to %s failed to send: %s", to_id,
                        exc)
            self._c_handoffs.inc(role="send_error", reason=reason)
            events.emit("handoff", "send-error", actor=to_id, corr=corr,
                        severity="warning",
                        payload={"reason": reason, "error": str(exc)})
            return False
        # Flip confirmation is OWNERSHIP-level, never epoch-level: a
        # concurrent unrelated bump (a failover elsewhere) also raises
        # the epoch, and epoch >= proposed would falsely confirm a move
        # whose handoff frame the receiver discarded as stale. Only a
        # map that actually assigns the ranges to the receiver counts;
        # an unconfirmed move returns False and the caller retries
        # (re-proposing from the then-current, higher epoch).
        deadline = time.monotonic() + max(0.0, float(wait))
        while True:
            mp = self.core.map
            if mp.epoch > cur.epoch and mp.assigns(ranges, to_id):
                events.emit("handoff", "flip-confirmed", actor=to_id,
                            corr=corr,
                            payload={"reason": reason,
                                     "epoch": mp.epoch})
                return True
            if time.monotonic() >= deadline:
                events.emit("handoff", "flip-timeout", actor=to_id,
                            corr=corr, severity="warning",
                            payload={"reason": reason,
                                     "waited_s": round(float(wait), 3)})
                return False
            time.sleep(0.02)

    def _handle_handoff(self, payload: dict) -> None:
        """Receiver half of a handoff: restore-before-rejoin, then this
        host alone publishes the epoch bump. Any failure (including an
        injected kill) before the final swap leaves the map — and so
        ownership — untouched: the sender still owns the ranges at the
        old epoch."""
        if payload.get("to") != self.core.self_id:
            return
        frm = str(payload.get("from", ""))
        with self._lock:
            if frm:
                self._last_seen[frm] = time.monotonic()
                self._failures[frm] = 0
        with self._handoff_lock:
            self._handle_handoff_locked(payload, frm)

    def _handle_handoff_locked(self, payload: dict, frm: str) -> None:
        try:
            new_map = FleetMap.from_dict(payload.get("map") or {})
        except Exception as exc:  # noqa: BLE001 — bad frame
            log.warning("fleet handoff from %s carried an invalid map "
                        "(%s); ignoring", frm, exc)
            return
        if new_map.epoch <= self.core.map.epoch:
            log.info("fleet handoff from %s is stale (epoch %d <= %d); "
                     "ignoring", frm, new_map.epoch, self.core.map.epoch)
            return
        ranges = tuple((int(lo), int(hi))
                       for lo, hi in payload.get("ranges", []))
        reason = str(payload.get("reason", "migrate"))
        try:
            corr = int(str(payload.get("corr", "") or "0"), 16)
        except ValueError:
            corr = 0
        try:
            self._chaos_phase("restore")
            unit = None
            if self.handoff_restore_fn is not None:
                try:
                    unit = self.handoff_restore_fn(payload)
                except Exception:  # noqa: BLE001 — abort, giver serves on
                    # UNLIKE dead-owner failover, the giver is ALIVE
                    # and still holds the exact counters: flipping to
                    # fresh state here would hand every moved key a
                    # full quota for nothing. Abort before the bump —
                    # ownership stays with the sender, which retries
                    # or keeps serving (single owner throughout).
                    log.exception(
                        "fleet handoff from %s: standby restore failed; "
                        "ABORTING before the epoch bump (the sender "
                        "still owns ranges %s and keeps serving)", frm,
                        [list(r) for r in ranges])
                    self._c_handoffs.inc(role="receive_aborted",
                                         reason=reason)
                    events.emit(
                        "handoff", "receive-aborted", actor=frm,
                        corr=corr, severity="warning",
                        payload={"reason": reason, "phase": "restore",
                                 "ranges": [list(r) for r in ranges]})
                    return
            self._chaos_phase("flip")
            origin = str(payload.get("origin") or frm)
            absorbed = False
            if (unit is not None and origin == self.core.self_id
                    and self.absorb_fn is not None):
                # Rejoin give-back of OUR OWN ranges: fold the unit
                # into the main serving limiter — the ranges then run
                # the full pipelined path and ride the normal snapshot
                # files (no adopted executor, no aux cycle).
                try:
                    absorbed = bool(self.absorb_fn(unit))
                except Exception:  # noqa: BLE001 — adopted fallback
                    log.exception("fleet rejoin absorb failed; "
                                  "mounting as adopted standby instead")
                if absorbed:
                    unit.close()
                    unit = None
            # Mount + swap atomically (mount first inside —
            # restore-before-rejoin, same ordering as failover).
            self.core.install_and_swap(unit, ranges, new_map,
                                       origin=origin)
            if unit is not None:
                self._notify_adopt(origin, ranges)
            self._reconcile_adopted()
        except Exception as exc:  # noqa: BLE001 — abandoned handoff
            # The injected kill / a mid-handoff crash: nothing was
            # published, the sender remains the one owner at the old
            # epoch and retries or keeps serving.
            log.warning("fleet handoff from %s abandoned before the "
                        "flip (%s); ownership unchanged", frm, exc)
            self._c_handoffs.inc(role="receive_aborted", reason=reason)
            events.emit("handoff", "receive-aborted", actor=frm,
                        corr=corr, severity="warning",
                        payload={"reason": reason, "error": str(exc)})
            return
        self.handoffs += 1
        self._c_handoffs.inc(role="receive", reason=reason)
        events.emit("handoff", "receive", actor=frm, corr=corr,
                    payload={"reason": reason,
                             "ranges": [list(r) for r in ranges],
                             "epoch": new_map.epoch,
                             "absorbed": absorbed})
        log.warning("fleet: received %s handoff of %s from %s; now "
                    "serving at epoch %d", reason,
                    [list(r) for r in ranges], frm, new_map.epoch)
        # Converge fast: the sender (and every router) learns the flip
        # from this announce.
        self.announce_once()

    def _maybe_rejoin(self) -> None:
        """Kick give-backs for returning origins (queued by the
        announce path). Each runs on ITS OWN thread: migrate_ranges
        blocks up to its flip wait, and the heartbeat must keep beating
        throughout — a silent gap >= dead_after would make peers
        declare this live host dead mid-rejoin and fork its ranges.
        Retries back off (``rejoin_backoff``) so a flapping origin
        cannot drive one full capture snapshot per heartbeat cycle."""
        now = time.monotonic()
        with self._lock:
            ready = [o for o in self._rejoin_pending
                     if o not in self._rejoin_inflight
                     and now - self._rejoin_last.get(o, 0.0)
                     >= self.rejoin_backoff - 1e-9]
            for o in ready:
                self._rejoin_pending.discard(o)
                self._rejoin_inflight.add(o)
                self._rejoin_last[o] = now
        for origin in ready:
            threading.Thread(target=self._rejoin_one, args=(origin,),
                             daemon=True,
                             name=f"rl-fleet-rejoin-{origin}").start()

    def _rejoin_one(self, origin: str) -> None:
        try:
            ranges = self.core.adopted_origin_ranges(origin)
            if not ranges or not self._in_map(origin):
                return
            log.warning("fleet: %s returned; handing its adopted ranges "
                        "%s back (rejoin)", origin,
                        [list(r) for r in ranges])
            events.emit("handoff", "rejoin-giveback", actor=origin,
                        payload={"ranges": [list(r) for r in ranges]})
            try:
                if self.migrate_ranges(ranges, origin, reason="rejoin",
                                       origin=origin,
                                       wait=max(2.0, 4 * self.heartbeat)):
                    self.rejoins += 1
                    self._c_rejoins.inc()
                else:
                    # Not flipped yet — requeue after the backoff; the
                    # origin may still be prewarming (its next announce
                    # also re-triggers).
                    with self._lock:
                        self._rejoin_pending.add(origin)
            except Exception:  # noqa: BLE001 — retry after backoff
                log.exception("fleet rejoin give-back to %s failed",
                              origin)
                with self._lock:
                    self._rejoin_pending.add(origin)
        finally:
            with self._lock:
                self._rejoin_inflight.discard(origin)

    def depart(self, *, wait: float = 10.0) -> bool:
        """Graceful departure (the rolling-restart drain, ADR-018): hand
        EVERY range this host serves — its own and any adopted — to its
        configured successor (or the first live peer) BEFORE the doors
        close, so a restarting fleet loses no ownership window at all.
        The receiver restores our final snapshot (taken here) + WAL
        suffix and publishes the flip; in-flight routers ride the
        forward/redirect window. Returns True when the flip was
        observed; False leaves ownership with us (the kill -9 failover
        path then covers the restart, exactly as before)."""
        cur = self.core.map
        me = cur.host(self.core.self_id)
        if not me.ranges:
            return True
        with self._lock:
            dead = set(self._dead)
        target = None
        if me.successor and me.successor not in dead:
            target = me.successor
        else:
            for h in cur.hosts:
                if h.id != self.core.self_id and h.id not in dead:
                    target = h.id
                    break
        if target is None:
            log.warning("fleet depart: no live peer to hand ranges to; "
                        "leaving ownership in place (failover covers "
                        "the restart)")
            return False
        ok = self.migrate_ranges(me.ranges, target, reason="depart",
                                 wait=wait)
        if ok:
            log.warning("fleet: departed; %s now owns %s (epoch %d)",
                        target, [list(r) for r in me.ranges],
                        self.core.map.epoch)
        return ok

    # --------------------------------------------------------- lifecycle

    def start(self) -> None:
        if self._thread is not None:
            return
        self._stop.clear()

        def loop() -> None:
            while not self._stop.wait(self.heartbeat):
                try:
                    self.announce_once()
                    self._maybe_rejoin()
                    self._check_dead()
                except Exception:  # noqa: BLE001 — keep the heart beating
                    log.exception("fleet membership cycle failed")

        self._thread = threading.Thread(target=loop, daemon=True,
                                        name="rl-fleet-membership")
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10)
            self._thread = None
        for conn in self._conns.values():
            try:
                conn.close()
            except Exception:  # noqa: BLE001 — teardown best-effort
                pass
        self._conns.clear()

    # ----------------------------------------------------------- surface

    def status(self) -> dict:
        now = time.monotonic()
        with self._lock:
            peers = {}
            for host in self.core.map.hosts:
                if host.id == self.core.self_id:
                    continue
                seen = self._last_seen.get(host.id)
                clk = self._peer_clock_locked(host.id)
                peers[host.id] = {
                    "addr": host.addr,
                    "alive": host.id not in self._dead,
                    "last_seen_age_s": (round(now - seen, 3)
                                        if seen is not None else None),
                    "epoch": self._peer_epoch.get(host.id),
                    "ranges": [list(r) for r in
                               self.core.map.host(host.id).ranges],
                    # Clock alignment (ADR-021): t_mine ≈ t_peer +
                    # offset. Exposed here so OFFLINE stitchers
                    # (tools/fleet_trace.py --offline) can align dumps
                    # without the server-side fan-out.
                    "mono_offset_ns": clk["offset_ns"],
                    "announce_rtt_ms": (round(clk["rtt_ns"] / 1e6, 3)
                                        if clk["rtt_ns"] is not None
                                        else None),
                }
        return {"peers": peers, "failovers": self.failovers,
                "handoffs": self.handoffs, "rejoins": self.rejoins,
                "auto_rejoin": self.auto_rejoin,
                "heartbeat_s": self.heartbeat,
                "dead_after_s": self.dead_after}
