"""Fleet ownership map: which host owns which hash buckets (ADR-017).

The fleet tier shards the keyspace ACROSS PROCESSES exactly as the
slice-parallel mesh shards it across devices (ADR-012): a key reduces to
its finalized u64 hash (``hash_prefixed_u64`` for strings,
``splitmix64(id)`` for raw ids — the one key→hash rule), and

    bucket = h64 % buckets          # the fleet routing rule
    owner  = owner_table[bucket]    # host owning that bucket

Each host owns one or more CONTIGUOUS bucket ranges ``[lo, hi)``.
Contiguity is a failover/resharding convenience (a range moves as one
unit), not a correctness requirement. ``buckets`` is fixed for the life
of a deployment (pick hosts × 8..64 so ranges can later split —
ROADMAP item 2's elastic resharding reassigns ranges, never re-buckets).

The map carries an ``epoch``: every ownership change (today: per-range
failover, ``fleet/membership.py``) bumps it, and the highest epoch wins
everywhere — announce frames gossip the whole map, servers answer
``T_FLEET_MAP`` with theirs, and the E_NOT_OWNER redirect names the
answering epoch so stale routers know to refresh.

This is the capability analog of the reference's Redis Cluster hash
slots (16384 slots, ranges per node): same slot→node indirection, same
"move ranges, not keys" operational story.
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field, replace
from typing import List, Optional, Sequence, Tuple

import numpy as np

from ratelimiter_tpu.core.errors import InvalidConfigError


@dataclass(frozen=True)
class FleetHost:
    """One fleet member: identity, address, owned bucket ranges, and the
    configured failover successor for those ranges."""

    id: str
    host: str
    port: int
    ranges: Tuple[Tuple[int, int], ...] = ()
    #: Host id that adopts this host's ranges when it dies (ADR-017
    #: failover). None = no failover for these ranges (they answer
    #: degraded per fail-open/closed until the host returns).
    successor: Optional[str] = None
    #: This host's --snapshot-dir, as REACHABLE FROM ITS SUCCESSOR
    #: (shared filesystem / replicated volume): the successor restores
    #: the adopted ranges from the newest snapshot + WAL suffix here.
    snapshot_dir: Optional[str] = None
    #: Dispatch shards behind this member's door (ADR-019). 1 (the
    #: default, and always true for the asyncio door) lets peers
    #: hash-forward STRING rows on the columnar lane — a single-shard
    #: receiver decides ``splitmix64_inv(h64)`` bit-identically to the
    #: direct string. A MULTI-shard native member routes string frames
    #: by FNV over raw key bytes, so it MUST declare its shard count
    #: here; peers then forward its string rows as strings. The server
    #: binary refuses to start when its own entry disagrees with its
    #: actual shard count.
    shards: int = 1
    #: This member's HTTP gateway port (ADR-021 control tower). The
    #: fleet fan-out surfaces — /v1/fleet/status, /debug/trace?fleet=1,
    #: /debug/events?fleet=1, and the offline tools — pull peers'
    #: /healthz, trace, and event payloads from it. None = this member
    #: is skipped by rollups (reported as unreachable, never a failure).
    http: Optional[int] = None

    @property
    def addr(self) -> str:
        return f"{self.host}:{self.port}"

    @property
    def http_addr(self) -> Optional[str]:
        return f"{self.host}:{self.http}" if self.http else None

    def to_dict(self) -> dict:
        d = {"id": self.id, "host": self.host, "port": self.port,
             "ranges": [list(r) for r in self.ranges]}
        if self.successor is not None:
            d["successor"] = self.successor
        if self.snapshot_dir is not None:
            d["snapshot_dir"] = self.snapshot_dir
        if self.shards != 1:
            d["shards"] = self.shards
        if self.http is not None:
            d["http"] = self.http
        return d


@dataclass(frozen=True)
class FleetMap:
    """The whole fleet's keyspace ownership at one epoch (immutable —
    ownership changes produce a NEW map via :meth:`reassign`, so readers
    racing a failover see either map, never a half-written one)."""

    buckets: int
    hosts: Tuple[FleetHost, ...]
    epoch: int = 1
    #: bucket -> host ordinal (index into ``hosts``); built lazily.
    _table: Optional[np.ndarray] = field(default=None, compare=False,
                                         repr=False)

    # ------------------------------------------------------------ build

    @classmethod
    def from_dict(cls, d: dict) -> "FleetMap":
        hosts = tuple(
            FleetHost(id=str(h["id"]), host=str(h["host"]),
                      port=int(h["port"]),
                      ranges=tuple((int(lo), int(hi))
                                   for lo, hi in h.get("ranges", [])),
                      successor=h.get("successor"),
                      snapshot_dir=h.get("snapshot_dir"),
                      shards=int(h.get("shards", 1)),
                      http=(int(h["http"]) if h.get("http") else None))
            for h in d["hosts"])
        m = cls(buckets=int(d["buckets"]), hosts=hosts,
                epoch=int(d.get("epoch", 1)))
        m.validate()
        return m

    @classmethod
    def load(cls, path: str) -> "FleetMap":
        with open(path, encoding="utf-8") as f:
            return cls.from_dict(json.load(f))

    def to_dict(self) -> dict:
        return {"buckets": self.buckets, "epoch": self.epoch,
                "hosts": [h.to_dict() for h in self.hosts]}

    # --------------------------------------------------------- validate

    def validate(self) -> None:
        if self.buckets < 1:
            raise InvalidConfigError(
                f"fleet map needs buckets >= 1, got {self.buckets}")
        if not self.hosts:
            raise InvalidConfigError("fleet map has no hosts")
        ids = [h.id for h in self.hosts]
        if len(set(ids)) != len(ids):
            raise InvalidConfigError(f"duplicate fleet host ids: {ids}")
        for h in self.hosts:
            if h.shards < 1:
                raise InvalidConfigError(
                    f"fleet host {h.id!r} declares shards={h.shards}; "
                    f"must be >= 1")
        covered = np.zeros(self.buckets, dtype=np.int32)
        for h in self.hosts:
            if h.successor is not None and h.successor not in ids:
                raise InvalidConfigError(
                    f"fleet host {h.id!r} names unknown successor "
                    f"{h.successor!r}")
            if h.successor == h.id:
                raise InvalidConfigError(
                    f"fleet host {h.id!r} is its own successor")
            for lo, hi in h.ranges:
                if not (0 <= lo < hi <= self.buckets):
                    raise InvalidConfigError(
                        f"fleet host {h.id!r} range [{lo}, {hi}) is "
                        f"outside [0, {self.buckets})")
                covered[lo:hi] += 1
        if (covered != 1).any():
            missing = int((covered == 0).sum())
            doubled = int((covered > 1).sum())
            raise InvalidConfigError(
                f"fleet ranges must cover every bucket exactly once: "
                f"{missing} uncovered, {doubled} doubly-owned of "
                f"{self.buckets}")

    # ---------------------------------------------------------- routing

    @property
    def owner_table(self) -> np.ndarray:
        """int32[buckets] -> host ordinal (one vectorized gather routes a
        whole frame)."""
        t = self._table
        if t is None:
            t = np.zeros(self.buckets, dtype=np.int32)
            for i, h in enumerate(self.hosts):
                for lo, hi in h.ranges:
                    t[lo:hi] = i
            object.__setattr__(self, "_table", t)
        return t

    def bucket_of_hash(self, h64: np.ndarray) -> np.ndarray:
        return (np.asarray(h64, np.uint64)
                % np.uint64(self.buckets)).astype(np.int64)

    def owner_of_hash(self, h64: np.ndarray) -> np.ndarray:
        """Host ordinal per FINALIZED u64 hash."""
        return self.owner_table[self.bucket_of_hash(h64)]

    def partition(self, owners: np.ndarray) -> dict:
        """{host ordinal: frame positions} from a per-row owner vector —
        ONE stable argsort, contiguous position slices, frame order
        preserved within every group. The single partition rule shared
        by FleetClient/AsyncFleetClient fan-out and the server-side
        forwarder's split (a divergent copy would silently give one
        key two owners)."""
        owners = np.asarray(owners)
        groups: dict = {}
        order = np.argsort(owners, kind="stable")
        sorted_owners = owners[order]
        bounds = np.searchsorted(sorted_owners,
                                 np.arange(len(self.hosts) + 1))
        for o in range(len(self.hosts)):
            lo, hi = int(bounds[o]), int(bounds[o + 1])
            if lo < hi:
                groups[o] = order[lo:hi]
        return groups

    def assigns(self, ranges: Sequence[Tuple[int, int]],
                host_id: str) -> bool:
        """True when EVERY bucket of ``ranges`` is owned by ``host_id``
        under this map — the ownership-level flip confirmation
        (ADR-018): an epoch comparison alone would be satisfied by any
        concurrent bump (e.g. an unrelated failover), falsely
        confirming a move that never landed."""
        try:
            o = self.ordinal(host_id)
        except InvalidConfigError:
            return False
        t = self.owner_table
        return all((t[int(lo):int(hi)] == o).all() for lo, hi in ranges)

    def canonical_key(self) -> str:
        """Deterministic content key used to tie-break two DIFFERENT
        maps published at the SAME epoch (two uncoordinated movers can
        mint ``epoch + 1`` concurrently): every member prefers the
        smaller key, so the fleet converges on one winner; the losing
        move's sender sees its flip unconfirmed (``assigns``) and
        retries at a higher epoch."""
        import hashlib

        return hashlib.sha256(json.dumps(
            self.to_dict(), sort_keys=True).encode()).hexdigest()

    def ordinal(self, host_id: str) -> int:
        for i, h in enumerate(self.hosts):
            if h.id == host_id:
                return i
        raise InvalidConfigError(
            f"host {host_id!r} is not in the fleet map "
            f"({[h.id for h in self.hosts]})")

    def host(self, host_id: str) -> FleetHost:
        return self.hosts[self.ordinal(host_id)]

    def owned_buckets(self, host_id: str) -> int:
        return sum(hi - lo for lo, hi in self.host(host_id).ranges)

    # --------------------------------------------------------- failover

    def reassign(self, dead_id: str, to_id: str) -> "FleetMap":
        """New map with ``dead_id``'s ranges moved to ``to_id`` and the
        epoch bumped — the per-range failover transition (ADR-017). The
        dead host stays in the map with no ranges (its identity and
        snapshot_dir remain addressable; a later rejoin is an operator /
        resharding action, ROADMAP item 2)."""
        dead = self.host(dead_id)
        if not dead.ranges:
            return self
        hosts: List[FleetHost] = []
        for h in self.hosts:
            if h.id == dead_id:
                hosts.append(replace(h, ranges=()))
            elif h.id == to_id:
                # Keep ranges sorted by lo so the map stays readable.
                merged = tuple(sorted(h.ranges + dead.ranges))
                hosts.append(replace(h, ranges=merged))
            else:
                hosts.append(h)
        m = FleetMap(buckets=self.buckets, hosts=tuple(hosts),
                     epoch=self.epoch + 1)
        m.validate()
        return m

    def move_ranges(self, ranges: Sequence[Tuple[int, int]], from_id: str,
                    to_id: str) -> "FleetMap":
        """New map with the given ``[lo, hi)`` ranges moved from
        ``from_id`` to ``to_id`` and the epoch bumped — the live
        migration / rejoin / departure transition (ADR-018). A moving
        range may be a whole owned range OR a sub-range of one (the
        placement planner carves slices out of affine units, ADR-023);
        the remainder stays with ``from_id`` as split pieces. Each
        moving range must lie entirely inside ONE owned range — a
        handoff ships one standby unit, so a move that straddles units
        is two moves. Everything else (successors, snapshot dirs) is
        unchanged."""
        src = self.host(from_id)
        self.host(to_id)  # validates existence
        moving = {(int(lo), int(hi)) for lo, hi in ranges}
        owned = set(src.ranges)
        if not moving:
            return self
        if not moving <= owned:
            # Sub-range path: split each containing owned range into
            # (left, moved, right) and keep the leftovers. Whole-unit
            # moves above stay byte-identical to the pre-split code
            # (no coalescing of existing tuples).
            new_owned = set(owned)
            for lo, hi in sorted(moving):
                if not (0 <= lo < hi <= self.buckets):
                    raise InvalidConfigError(
                        f"range [{lo}, {hi}) is outside "
                        f"[0, {self.buckets})")
                parent = next((r for r in new_owned
                               if r[0] <= lo and hi <= r[1]), None)
                if parent is None:
                    raise InvalidConfigError(
                        f"fleet host {from_id!r} does not own range "
                        f"[{lo}, {hi}) as a whole unit or sub-range "
                        f"of one owned range (owns "
                        f"{sorted(new_owned)}); a straddling move "
                        f"must be issued per owned range")
                new_owned.discard(parent)
                if parent[0] < lo:
                    new_owned.add((parent[0], lo))
                if hi < parent[1]:
                    new_owned.add((hi, parent[1]))
            owned_after = new_owned
        else:
            owned_after = owned - moving
        hosts: List[FleetHost] = []
        for h in self.hosts:
            if h.id == from_id:
                hosts.append(replace(h, ranges=tuple(
                    sorted(owned_after))))
            elif h.id == to_id:
                hosts.append(replace(h, ranges=tuple(
                    sorted(set(h.ranges) | moving))))
            else:
                hosts.append(h)
        m = FleetMap(buckets=self.buckets, hosts=tuple(hosts),
                     epoch=self.epoch + 1)
        m.validate()
        return m


def affine_map(addrs: Sequence[Tuple[str, int]], *, buckets: int = 0,
               snapshot_dirs: Optional[Sequence[Optional[str]]] = None,
               ring_successors: bool = True) -> FleetMap:
    """Even contiguous split of ``buckets`` over ``addrs`` (host ids
    ``h0..hN-1``), successors on a ring — the bench/test/bootstrap
    shape. Default buckets = 16 × hosts."""
    n = len(addrs)
    if buckets <= 0:
        buckets = 16 * n
    per = buckets // n
    hosts = []
    for i, (host, port) in enumerate(addrs):
        lo = i * per
        hi = buckets if i == n - 1 else (i + 1) * per
        hosts.append(FleetHost(
            id=f"h{i}", host=host, port=port, ranges=((lo, hi),),
            successor=(f"h{(i + 1) % n}" if ring_successors and n > 1
                       else None),
            snapshot_dir=(snapshot_dirs[i] if snapshot_dirs else None)))
    m = FleetMap(buckets=buckets, hosts=tuple(hosts))
    m.validate()
    return m
