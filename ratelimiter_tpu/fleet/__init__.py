"""Fleet tier: multi-host scale-out with consistent-hash routing,
cross-host forwarding, and per-range failover (ADR-017).

One ratelimiter_tpu server owns a contiguous set of keyspace hash
buckets; a fleet of them is ONE limiter:

* :class:`~ratelimiter_tpu.fleet.config.FleetMap` — the ownership map
  (bucket ranges per host, epoch-versioned);
* :class:`~ratelimiter_tpu.fleet.forwarder.FleetCore` /
  :class:`~ratelimiter_tpu.fleet.forwarder.FleetForwarder` — per-process
  routing + the server-side forwarder for mis-routed rows, riding the
  coalesced columnar peer lanes of ``fleet/lanes.py`` (ADR-019:
  pipelined multi-connection links, cross-frame coalescing windows,
  zero-copy row-view reassembly);
* :class:`~ratelimiter_tpu.fleet.membership.FleetMembership` —
  announce/heartbeat gossip over the authenticated DCN channel plus
  per-range failover onto the configured successor (restored from the
  dead host's newest snapshot + WAL suffix), live range migration /
  graceful departure / automatic rejoin give-back via the handoff
  protocol (ADR-018);
* ``fleet/handoff.py`` — the handoff artifact: standby units restored
  from a peer's snapshot dir (own unit + aux folds, or one origin's
  adopted unit) before ownership flips.

Client-side consistent-hash routing lives in
``serving/client.py`` (``FleetClient`` / ``AsyncFleetClient``).
"""

from ratelimiter_tpu.fleet.config import FleetHost, FleetMap, affine_map
from ratelimiter_tpu.fleet.forwarder import FleetCore, FleetForwarder
from ratelimiter_tpu.fleet.handoff import build_standby
from ratelimiter_tpu.fleet.membership import FleetMembership
from ratelimiter_tpu.fleet.tower import ControlTower

__all__ = [
    "FleetHost",
    "FleetMap",
    "affine_map",
    "ControlTower",
    "FleetCore",
    "FleetForwarder",
    "FleetMembership",
    "build_standby",
]
