"""Fleet routing core + the server-side coalesced forwarder (ADR-017,
forward lanes reworked by ADR-019).

``FleetCore`` is one process's view of the fleet: the live ownership map
(swapped atomically on epoch bumps), this host's identity, per-peer
forward lanes, the adopted-range standby unit installed by failover,
and the shared metrics. Both front doors route through one core:

* the asyncio door wraps its serving limiter in :class:`FleetForwarder`
  (a LimiterDecorator — the micro-batcher's launch_batch / launch_ids
  calls partition per frame);
* the native (C++) door calls the core directly from its bridge
  callbacks (serving/native_server.py), where the key blob is still in
  hand.

Forwarding rides ONE columnar lane (ADR-019): every foreign row reduces
to its finalized u64 hash, the lane ships ``splitmix64_inv(h64)`` on
the plain ``T_ALLOW_HASHED`` wire (the receiver re-finalizes to the
bit-identical hash — splitmix64 is a bijection), and fragments from
MANY inbound frames coalesce into one wire frame per peer connection
per window (fleet/lanes.py). String rows hash-forward on the same lane
when the receiver is single-shard — decisions and policy overrides key
on the finalized hash, so the answer is bit-identical to the string
arriving directly; a MULTI-shard native receiver routes string frames
by FNV over the raw key bytes, so string rows bound for one (declared
``shards`` > 1 in the fleet map) still forward as strings, pipelined on
the same connection. Same-key send order survives the multi-connection
links via per-key connection affinity (``h64 % conns``).

Bounded-ness: each peer lane bounds outstanding fragments
(``--fleet-forward-queue``) and in-flight wire frames per connection
(``--fleet-forward-inflight``); every forwarded frame carries the fleet
forward deadline (the ADR-015 wire extension — the peer sheds expired
work). Overflow / peer failure degrades exactly the failed wire
frame's member rows per the configured fail-open/fail-closed policy,
and feeds the membership failure classifier.
"""

from __future__ import annotations

import concurrent.futures
import logging
import threading
import time
from typing import Dict, List, Optional, Sequence

import numpy as np

from ratelimiter_tpu.core.errors import (
    NotOwnerError,
    StorageUnavailableError,
)
from ratelimiter_tpu.core.types import (
    BatchResult,
    DispatchTicket,
    batch_fail_open,
    fail_open_result,
)
from ratelimiter_tpu.fleet.config import FleetMap
from ratelimiter_tpu.fleet.lanes import ForwardRuntime, PeerLane
from ratelimiter_tpu.observability import events, tracing
from ratelimiter_tpu.observability import metrics as m
from ratelimiter_tpu.observability.decorators import LimiterDecorator
from ratelimiter_tpu.ops.hashing import (
    hash_prefixed_u64,
    splitmix64,
    splitmix64_inv,
)

log = logging.getLogger("ratelimiter_tpu.fleet")

#: Forward RTT histogram buckets: a LAN hop under load — finer than the
#: dispatch buckets below 1 ms, out to the multi-second failure tail.
FORWARD_RTT_BUCKETS = (1e-4, 2.5e-4, 5e-4, 1e-3, 2.5e-3, 5e-3, 1e-2,
                       2.5e-2, 5e-2, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0)


class LaneMetrics:
    """Per-peer coalescing/occupancy instruments shared by every lane
    of one core (ADR-019 observability)."""

    def __init__(self, reg: m.Registry):
        self._g_rows = reg.gauge(
            "rate_limiter_fleet_forward_window_rows",
            "Rows in the most recent coalesced forward window per peer "
            "(occupancy: how much each wire frame amortizes)")
        self._g_frames = reg.gauge(
            "rate_limiter_fleet_forward_window_frames",
            "Member fragments merged into the most recent coalesced "
            "forward window per peer (depth: how many inbound frames "
            "share one wire round-trip)")
        self._c_frames = reg.counter(
            "rate_limiter_fleet_forward_wire_frames_total",
            "Coalesced wire frames sent to each peer (rows_total / "
            "frames_total = mean window occupancy)")
        self._c_rows = reg.counter(
            "rate_limiter_fleet_forward_wire_rows_total",
            "Rows shipped inside coalesced wire frames per peer")
        self._h_rtt = reg.histogram(
            "rate_limiter_fleet_forward_rtt_seconds",
            "Wire round-trip of one coalesced forward frame (send to "
            "parsed reply)", FORWARD_RTT_BUCKETS)

    def window(self, peer: str, frames: int, rows: int) -> None:
        self._g_rows.set(float(rows), peer=peer)
        self._g_frames.set(float(frames), peer=peer)
        self._c_frames.inc(1, peer=peer)
        self._c_rows.inc(rows, peer=peer)

    def rtt(self, seconds: float) -> None:
        self._h_rtt.observe(seconds)


class FleetCore:
    """One process's fleet state: live map + identity + peer lanes +
    adopted-range unit + metrics. Thread-safe: the map reference swaps
    atomically; routing reads never lock."""

    def __init__(self, fleet_map: FleetMap, self_id: str, *,
                 prefix: str = "", forward: bool = True,
                 forward_deadline: float = 1.0,
                 forward_queue: int = 128,
                 forward_inflight: int = 2,
                 forward_conns: int = 1,
                 forward_coalesce: int = 16384,
                 registry: Optional[m.Registry] = None):
        fleet_map.validate()
        self.self_id = self_id
        self.prefix = prefix
        self.forward_enabled = bool(forward)
        self.forward_deadline = float(forward_deadline)
        self.forward_queue = int(forward_queue)
        self.forward_inflight = max(1, int(forward_inflight))
        self.forward_conns = max(1, int(forward_conns))
        # Bounded by the wire: the coalesced REPLY costs ~24.1 B/row
        # against the 1 MiB MAX_FRAME (the request is cheaper at
        # 12 B/row), so the window may never exceed ~43K rows.
        self.forward_coalesce = max(1, min(int(forward_coalesce), 32768))
        self._lock = threading.Lock()
        self._lanes: Dict[int, PeerLane] = {}
        self._runtime: Optional[ForwardRuntime] = None
        #: Adopted-range standby unit (failover): decisions for adopted
        #: buckets run on this limiter, restored from the dead peer's
        #: snapshot + WAL suffix before it serves (restore-before-rejoin).
        self._adopted_unit = None
        #: origin host id -> tuple of (lo, hi) ranges this host serves on
        #: the standby unit FOR that origin (ADR-018: a rejoining origin
        #: takes exactly these back; the aux snapshot cycle labels its
        #: files with them).
        self._adopted_origins: Dict[str, tuple] = {}
        self._adopted_lock = threading.Lock()
        #: Serializes whole install_adopted calls: failover (membership
        #: thread) and a handoff (its own thread) can race — unguarded,
        #: both read no-unit-mounted and the second assignment silently
        #: dropped the first restored unit and its mask bits.
        self._install_lock = threading.Lock()
        self._adopted_exec: Optional[
            concurrent.futures.ThreadPoolExecutor] = None
        #: Failure sink (wired to FleetMembership.note_peer_failure):
        #: classified forward failures count toward peer-death detection.
        self.on_peer_failure = None
        #: Placement load slab (ADR-023): attached by the server binary
        #: for fleet members. owners_of_hash notes every routed row's
        #: bucket into it — observation only, decisions untouched.
        self.load_slab = None
        reg = registry if registry is not None else m.DEFAULT
        self._lane_metrics = LaneMetrics(reg)
        self._g_epoch = reg.gauge(
            "rate_limiter_fleet_epoch",
            "Current fleet ownership-map epoch (bumps on failover)")
        self._g_owned = reg.gauge(
            "rate_limiter_fleet_owned_buckets",
            "Hash buckets this host owns under the current map")
        self._g_adopted = reg.gauge(
            "rate_limiter_fleet_adopted_buckets",
            "Owned buckets served by the adopted-range standby unit "
            "(nonzero only after a failover adoption)")
        self._c_forwarded = reg.counter(
            "rate_limiter_fleet_forwarded_decisions_total",
            "Decisions submitted to their owning host because they "
            "arrived mis-routed (ADR-017 server-side forwarding; "
            "counted at submit — a later lane failure degrades the "
            "rows AND counts them in forward_errors/degraded)")
        self._c_forward_errors = reg.counter(
            "rate_limiter_fleet_forward_errors_total",
            "Forward jobs that failed (peer dead/slow/queue-full); "
            "their rows answered per fail-open/closed policy")
        self._c_redirects = reg.counter(
            "rate_limiter_fleet_redirects_total",
            "Frames answered with the E_NOT_OWNER typed redirect "
            "instead of forwarding")
        self._c_degraded = reg.counter(
            "rate_limiter_fleet_degraded_decisions_total",
            "Decisions answered per fail-open/closed policy because "
            "their owner was unreachable")
        # Buckets whose ownership maps to a dead host mid-failover are
        # recorded here by the membership so routing can degrade fast
        # instead of timing out per frame.
        self._dead_ordinals: frozenset = frozenset()
        self._closed = False
        self._install(fleet_map, adopted_buckets=None)

    # ------------------------------------------------------------- state

    def _install(self, fleet_map: FleetMap,
                 adopted_buckets: Optional[np.ndarray]) -> None:
        """Swap in a new map (and adopted-bucket mask) atomically."""
        self_ord = fleet_map.ordinal(self.self_id)
        adopted = (adopted_buckets if adopted_buckets is not None
                   else np.zeros(fleet_map.buckets, dtype=bool))
        with self._lock:
            prev_epoch = getattr(self, "map", None)
            prev_epoch = prev_epoch.epoch if prev_epoch is not None else None
            self.map = fleet_map
            self.self_ordinal = self_ord
            self._adopted_buckets = adopted
        self._g_epoch.set(float(fleet_map.epoch))
        self._g_owned.set(float(fleet_map.owned_buckets(self.self_id)))
        self._g_adopted.set(float(int(adopted.sum())))
        if prev_epoch is not None and fleet_map.epoch != prev_epoch:
            # Control-plane journal (ADR-021): every ownership-map
            # install with a new epoch, whoever minted it.
            events.emit(
                "epoch", "install", actor=self.self_id,
                payload={
                    "epoch": fleet_map.epoch, "from_epoch": prev_epoch,
                    "owned_buckets": int(
                        fleet_map.owned_buckets(self.self_id)),
                    "adopted_buckets": int(adopted.sum()),
                    "assigns": {h.id: [list(r) for r in h.ranges]
                                for h in fleet_map.hosts}})

    def swap_map(self, new_map: FleetMap,
                 adopted_buckets: Optional[np.ndarray] = None) -> None:
        if adopted_buckets is None:
            # Preserve the existing mask where sizes agree (a map update
            # that doesn't change adoption).
            adopted_buckets = self._adopted_buckets
            if adopted_buckets.shape[0] != new_map.buckets:
                adopted_buckets = None
        self._install(new_map, adopted_buckets)

    def install_adopted(self, unit, ranges: Sequence,
                        origin: Optional[str] = None) -> None:
        """Mount the failover standby unit for ``ranges`` (list of
        (lo, hi) bucket ranges). The unit must already be restored
        (restore-before-rejoin); routing flips to it atomically.

        A SECOND adoption while a unit is already mounted (a migration
        or a second failover landing on the same successor) folds the
        new unit's state into the mounted one by conservative union
        (parallel/reshard.py, ADR-018): the two populations are
        disjoint key ranges, so estimates stay >= each origin's own —
        never an over-admit. The fold runs on the adopted executor so
        it serializes with in-flight adopted decides; whole installs
        serialize on ``_install_lock`` (failover and handoff threads
        can race), and the mask update ORs into the CURRENT mask under
        the map lock so concurrent moves never lose each other's
        buckets. Prefer :meth:`install_and_swap` when a map swap
        follows — it holds the install lock across BOTH, so a racing
        reconcile can never strip the just-mounted bits in the gap."""
        with self._install_lock:
            self._install_adopted_locked(unit, ranges, origin)

    def install_and_swap(self, unit, ranges: Sequence,
                         new_map: FleetMap,
                         origin: Optional[str] = None) -> None:
        """Mount the restored unit and install the new map as ONE
        atomic step w.r.t. mask reconciliation: between a bare
        install_adopted and the swap, the buckets still belong to the
        giver under the CURRENT map, so an unrelated higher-epoch
        announce running sync_adopted_with_map would strip the
        pre-mounted bits (and release the origin) before the flip."""
        with self._install_lock:
            if unit is not None:
                self._install_adopted_locked(unit, ranges, origin)
            self.swap_map(new_map)

    def _install_adopted_locked(self, unit, ranges: Sequence,
                                origin: Optional[str]) -> None:
        """Body of install_adopted; ``_install_lock`` must be held."""
        with self._adopted_lock:
            if self._adopted_exec is None:
                # Single worker: adopted-range decides stay FIFO
                # (per-key order), mirroring every other dispatch unit.
                self._adopted_exec = (
                    concurrent.futures.ThreadPoolExecutor(
                        max_workers=1,
                        thread_name_prefix="rl-fleet-adopted"))
            existing = self._adopted_unit
        if existing is None or existing is unit:
            with self._adopted_lock:
                self._adopted_unit = unit
        else:
            from ratelimiter_tpu.parallel import reshard

            def fold() -> None:
                _, arrays, extra = unit.capture_state()
                reshard.merge_into_limiter(existing, arrays, extra)
                unit.close()

            self.adopted_submit(fold).result()
        if origin is not None:
            self._adopted_origins[origin] = tuple(
                (int(lo), int(hi)) for lo, hi in ranges)
        with self._lock:
            mask = self._adopted_buckets
            if mask.shape[0] != self.map.buckets:
                mask = np.zeros(self.map.buckets, dtype=bool)
            else:
                mask = mask.copy()
            for lo, hi in ranges:
                mask[lo:hi] = True
            self._adopted_buckets = mask
        self._g_adopted.set(float(int(mask.sum())))

    def adopted_origin_ranges(self, origin: str) -> tuple:
        """Ranges this host serves on the standby unit for ``origin``
        (empty tuple when none)."""
        return self._adopted_origins.get(origin, ())

    def sync_adopted_with_map(self) -> List[str]:
        """Reconcile the adopted mask with the CURRENT map: buckets the
        map no longer assigns to this host leave the mask (their new
        owner published a higher epoch — e.g. a rejoined origin took its
        ranges back), and origins whose handed ranges all left are
        released. Returns the released origin ids. Called after every
        map swap; the single-owner-per-epoch invariant makes this pure
        bookkeeping — the epoch bump already moved ownership. Takes
        ``_install_lock`` so it can never interleave with a mid-flight
        install_and_swap (whose mounted bits only become map-owned at
        its swap)."""
        with self._install_lock:
            return self._sync_adopted_locked()

    def _sync_adopted_locked(self) -> List[str]:
        with self._lock:
            mask = self._adopted_buckets
            if not mask.any():
                return []
            mine = self.map.owner_table == self.self_ordinal
            new_mask = mask & mine
            self._adopted_buckets = new_mask
        released = []
        for origin, ranges in list(self._adopted_origins.items()):
            if not any(new_mask[lo:hi].any() for lo, hi in ranges):
                del self._adopted_origins[origin]
                released.append(origin)
        self._g_adopted.set(float(int(new_mask.sum())))
        return released

    def set_dead(self, ordinals: Sequence[int]) -> None:
        """Membership marks unreachable hosts so routing degrades their
        rows immediately instead of paying a connect timeout per frame."""
        self._dead_ordinals = frozenset(int(o) for o in ordinals)

    # ----------------------------------------------------------- routing

    def hash_keys(self, keys: Sequence[str]) -> np.ndarray:
        return hash_prefixed_u64(list(keys), self.prefix)

    def owners_of_hash(self, h64: np.ndarray) -> np.ndarray:
        mp = self.map
        slab = self.load_slab
        if slab is None:
            return mp.owner_of_hash(h64)
        # Placement load accounting (ADR-023) rides the routing lookup:
        # the bucket index is computed here ANYWAY — note it into the
        # slab (two bincount adds) and gather owners from the same
        # vector. Decisions are untouched; with the slab detached this
        # path is byte-identical to owner_of_hash.
        b = mp.bucket_of_hash(h64)
        owners = mp.owner_table[b]
        slab.note(b, owners == self.self_ordinal)
        return owners

    def owners_of_ids(self, ids: np.ndarray) -> np.ndarray:
        return self.owners_of_hash(splitmix64(np.asarray(ids, np.uint64)))

    def all_local(self, owners: np.ndarray) -> bool:
        return bool((owners == self.self_ordinal).all()
                    and not self._adopted_buckets.any())

    def split(self, h64: np.ndarray, owners: np.ndarray):
        """Partition one frame: (local_pos, adopted_pos,
        {foreign_ordinal: pos}) — one stable argsort, contiguous
        position slices, frame order preserved within every group."""
        mine = owners == self.self_ordinal
        if self._adopted_buckets.any():
            adopted_rows = mine & self._adopted_buckets[
                self.map.bucket_of_hash(h64)]
            local_rows = mine & ~adopted_rows
        else:
            adopted_rows = np.zeros(0, dtype=bool)
            local_rows = mine
        local_pos = np.nonzero(local_rows)[0]
        adopted_pos = (np.nonzero(adopted_rows)[0]
                       if adopted_rows.shape[0] else
                       np.zeros(0, dtype=np.int64))
        foreign: Dict[int, np.ndarray] = {}
        if local_pos.shape[0] + adopted_pos.shape[0] < owners.shape[0]:
            fpos = np.nonzero(~mine)[0]
            foreign = {o: fpos[sub] for o, sub in
                       self.map.partition(owners[fpos]).items()}
        return local_pos, adopted_pos, foreign

    def lane(self, ordinal: int) -> PeerLane:
        """The forward lane to one peer (built lazily; rebuilt when a
        map swap moved that ordinal's address)."""
        ln = self._lanes.get(ordinal)
        host = self.map.hosts[ordinal]
        if ln is None or (ln.host, ln.port) != (host.host, host.port):
            with self._lock:
                if self._closed:
                    raise StorageUnavailableError(
                        "fleet core is closed; forwarding unavailable")
                ln = self._lanes.get(ordinal)
                if ln is None or (ln.host, ln.port) != (host.host,
                                                        host.port):
                    if ln is not None:
                        ln.close()
                    if self._runtime is None or not self._runtime.alive:
                        self._runtime = ForwardRuntime()
                    ln = PeerLane(
                        self._runtime, host.host, host.port,
                        label=host.id,
                        deadline=self.forward_deadline,
                        inflight=self.forward_inflight,
                        conns=self.forward_conns,
                        coalesce=self.forward_coalesce,
                        queue_cap=self.forward_queue,
                        metrics=self._lane_metrics)
                    self._lanes[ordinal] = ln
        return ln

    def peer_columnar(self, ordinal: int) -> bool:
        """True when STRING rows may hash-forward to this peer on the
        columnar lane: a single-shard receiver decides a forwarded
        ``splitmix64_inv(h64)`` bit-identically to the direct string
        (decisions and overrides key on the finalized hash). A
        multi-shard native receiver routes string frames by FNV over
        the raw key bytes — hash-routing them would split a key's
        quota across shards — so its entry must declare ``shards`` in
        the fleet map and its string rows forward as strings."""
        return self.map.hosts[ordinal].shards <= 1

    # ------------------------------------------------------- redirecting

    def redirect_error(self, h64_row: int, owner_ordinal: int
                       ) -> NotOwnerError:
        from ratelimiter_tpu.serving import protocol as p

        host = self.map.hosts[owner_ordinal]
        bucket = int(np.uint64(h64_row) % np.uint64(self.map.buckets))
        self._c_redirects.inc()
        return NotOwnerError(
            p.format_not_owner(bucket, f"{host.id}@{host.addr}",
                               self.map.epoch, self.map.buckets),
            owner=host.addr, epoch=self.map.epoch)

    def check_frame_owned(self, h64: np.ndarray) -> None:
        """Redirect-only mode's door check: raises the typed redirect
        when any row is foreign (the whole frame errors, the batch
        error contract)."""
        owners = self.owners_of_hash(h64)
        foreign = owners != self.self_ordinal
        if foreign.any():
            i = int(np.argmax(foreign))
            raise self.redirect_error(int(h64[i]), int(owners[i]))

    # ------------------------------------------------------- forwarding

    def forward_jobs(self, ordinal: int, pos: np.ndarray,
                     h64: np.ndarray, ns: np.ndarray, *,
                     keys_fn=None) -> list:
        """Submit one peer's foreign rows onto its lane, split by
        per-key connection affinity. ``pos`` holds the rows' global
        frame positions; ``h64``/``ns`` are the FULL frame columns.
        Returns ``[(positions, future)]`` — one job per touched
        connection, each future resolving to that job's BatchResult
        (a row-range VIEW of the coalesced reply). Never raises: a
        submit failure (lane closed / queue full) yields a pre-failed
        future so sibling connections' rows still decide."""
        host = self.map.hosts[ordinal]
        self._c_forwarded.inc(int(pos.shape[0]), peer=host.id)
        try:
            lane = self.lane(ordinal)
        except StorageUnavailableError as exc:
            fut: concurrent.futures.Future = concurrent.futures.Future()
            fut.set_exception(exc)
            return [(pos, fut)]
        sub_h = h64[pos]
        sub_ns = ns[pos]
        columnar = keys_fn is None or self.peer_columnar(ordinal)
        if lane.conns == 1:
            groups = [(0, pos, sub_h, sub_ns)]
        else:
            ci = lane.conn_of(sub_h)
            groups = []
            for c in range(lane.conns):
                sel = ci == c
                if sel.any():
                    groups.append((c, pos[sel], sub_h[sel], sub_ns[sel]))
        # Originating frame's trace id (thread-local, set by the
        # batcher around the launch; 0 when tracing is off): rides the
        # fragment so the lane can link it to the coalesced window's
        # wire-level id (ADR-021 cross-host stitching).
        trace = tracing.current() if tracing.RECORDER is not None else 0
        jobs = []
        for conn, g_pos, g_h, g_ns in groups:
            try:
                if columnar:
                    fut = lane.submit_rows(splitmix64_inv(g_h), g_ns,
                                           conn, trace=trace)
                else:
                    keys = keys_fn(g_pos)
                    build, parse = self._string_call(
                        keys, [int(x) for x in g_ns])
                    fut = lane.submit_call(build, parse, conn,
                                           rows=len(keys))
            except StorageUnavailableError as exc:
                fut = concurrent.futures.Future()
                fut.set_exception(exc)
            jobs.append((g_pos, fut))
        return jobs

    def _string_call(self, keys: List[str], ns_list: List[int]):
        """Build/parse pair for the multi-shard string fallback: a
        pipelined T_ALLOW_BATCH whose reply parses COLUMNAR
        (protocol.parse_result_batch_columnar) so scatter_merge stays
        on the numpy path."""
        from ratelimiter_tpu.serving import protocol as p

        dl = self.forward_deadline

        def build(req_id: int) -> bytes:
            # FORWARD_FLAG: the multi-shard receiver's dispatcher also
            # keeps forward windows out of client-frame dispatches.
            return p.with_forward(p.with_deadline(
                p.encode_allow_batch(req_id, keys, ns_list), dl))

        def parse(type_: int, body: bytes):
            if type_ != p.T_RESULT_BATCH:
                raise p.ProtocolError(
                    f"unexpected forward response type {type_}")
            return p.parse_result_batch_columnar(body)

        return build, parse

    @staticmethod
    def _combine_jobs(jobs: list, b: int):
        """Legacy single-future surface over per-connection jobs: one
        job covering the whole fragment passes its future through
        (zero-copy); a multi-connection split scatters back to fragment
        order once every job lands."""
        if len(jobs) == 1 and int(jobs[0][0].shape[0]) == b:
            return jobs[0][1]
        out: concurrent.futures.Future = concurrent.futures.Future()
        lock = threading.Lock()
        state = {"left": len(jobs), "parts": [], "exc": None}

        def _done(pos):
            def cb(f):
                with lock:
                    try:
                        state["parts"].append((pos, f.result()))
                    except BaseException as exc:  # noqa: BLE001
                        if state["exc"] is None:
                            state["exc"] = exc
                    state["left"] -= 1
                    fire = state["left"] == 0
                if not fire:
                    return
                if state["exc"] is not None:
                    out.set_exception(state["exc"])
                else:
                    parts = state["parts"]
                    out.set_result(scatter_merge(
                        b, parts[0][1].limit, parts))
            return cb

        for pos, fut in jobs:
            fut.add_done_callback(_done(pos))
        return out

    def forward_ids(self, ordinal: int, raw_ids: np.ndarray,
                    ns) -> "concurrent.futures.Future":
        """Single-future convenience over :meth:`forward_jobs` for one
        raw-id fragment (tests and ad-hoc callers; the doors submit
        jobs directly)."""
        raw_ids = np.ascontiguousarray(raw_ids, dtype=np.uint64)
        jobs = self.forward_jobs(
            ordinal, np.arange(raw_ids.shape[0]), splitmix64(raw_ids),
            np.asarray(ns, dtype=np.int64))
        return self._combine_jobs(jobs, int(raw_ids.shape[0]))

    def forward_allow_n(self, ordinal: int, key: str,
                        n: int) -> "concurrent.futures.Future":
        """Scalar forward on the key's affinity connection (FIFO with
        its batch rows): keeps the full scalar Result fidelity
        (override limits ride the scalar wire path)."""
        from ratelimiter_tpu.serving import protocol as p

        self._c_forwarded.inc(peer=self.map.hosts[ordinal].id)
        lane = self.lane(ordinal)
        h64 = self.hash_keys([key])
        dl = self.forward_deadline

        def build(req_id: int) -> bytes:
            return p.with_deadline(p.encode_allow_n(req_id, key, int(n)),
                                   dl)

        def parse(type_: int, body: bytes):
            if type_ != p.T_RESULT:
                raise p.ProtocolError(
                    f"unexpected forward response type {type_}")
            return p.parse_result(body)

        return lane.submit_call(build, parse,
                                int(h64[0] % np.uint64(lane.conns)))

    def forward_op(self, ordinal: int, kind: str,
                   key: str) -> "concurrent.futures.Future":
        """Control-plane forward (today: reset) on the key's affinity
        connection so it serializes with that key's decision rows."""
        from ratelimiter_tpu.serving import protocol as p

        if kind != "reset":  # pragma: no cover - programming error
            raise ValueError(f"unknown forward op {kind}")
        lane = self.lane(ordinal)
        h64 = self.hash_keys([key])

        def build(req_id: int) -> bytes:
            return p.encode_reset(req_id, key)

        def parse(type_: int, body: bytes):
            if type_ != p.T_OK:
                raise p.ProtocolError(
                    f"unexpected forward response type {type_}")
            return None

        return lane.submit_call(build, parse,
                                int(h64[0] % np.uint64(lane.conns)))

    def note_forward_failure(self, ordinal: int, exc: BaseException,
                             count: int) -> None:
        host = self.map.hosts[ordinal]
        self._c_forward_errors.inc(peer=host.id)
        self._c_degraded.inc(count)
        cb = self.on_peer_failure
        if cb is not None:
            try:
                cb(host.id, exc)
            except Exception:  # noqa: BLE001 — observability only
                log.exception("fleet on_peer_failure callback failed")

    # ---------------------------------------------------- adopted ranges

    @property
    def adopted_unit(self):
        return self._adopted_unit

    def adopted_submit(self, fn) -> "concurrent.futures.Future":
        with self._adopted_lock:
            ex = self._adopted_exec
        assert ex is not None, "no adopted unit installed"
        return ex.submit(fn)

    def decide_adopted_hashed(self, h64: np.ndarray, ns: np.ndarray
                              ) -> "concurrent.futures.Future":
        unit = self._adopted_unit
        return self.adopted_submit(
            lambda: unit.allow_hashed(h64, ns))

    def decide_adopted_keys(self, keys: List[str], ns
                            ) -> "concurrent.futures.Future":
        unit = self._adopted_unit
        return self.adopted_submit(
            lambda: unit.allow_batch(keys, list(ns)))

    # ----------------------------------------------------------- surface

    def status(self) -> dict:
        """/healthz fleet block (membership adds liveness)."""
        mp = self.map
        me = mp.host(self.self_id)
        with self._lock:  # lane() inserts under the same lock
            lanes = list(self._lanes.values())
        wire_frames = sum(ln.wire_frames for ln in lanes)
        wire_rows = sum(ln.wire_rows for ln in lanes)
        return {
            "self": self.self_id,
            "epoch": mp.epoch,
            "buckets": mp.buckets,
            # Member addresses incl. the declared gateway ports, so
            # offline tools (tools/fleet_trace.py --offline,
            # tools/fleet_status.py --offline) can reach every member
            # from one /healthz read (ADR-021).
            "hosts": {h.id: {"addr": h.addr, "http": h.http}
                      for h in mp.hosts},
            "owned_ranges": [list(r) for r in me.ranges],
            "adopted_buckets": int(self._adopted_buckets.sum()),
            "adopted_origins": {o: [list(r) for r in rs] for o, rs in
                                self._adopted_origins.items()},
            "forwarding": self.forward_enabled,
            "forwarded_total": int(self._c_forwarded.total()),
            "forward_errors_total": int(self._c_forward_errors.total()),
            "redirects_total": int(self._c_redirects.total()),
            "forward_wire_frames_total": wire_frames,
            "forward_wire_rows_total": wire_rows,
            "forward_mean_window_rows": (
                round(wire_rows / wire_frames, 1) if wire_frames else None),
            "forward_inflight_per_conn": self.forward_inflight,
            "forward_conns_per_peer": self.forward_conns,
        }

    def map_payload(self) -> dict:
        return self.map.to_dict()

    def close(self) -> None:
        with self._lock:
            self._closed = True
            lanes = list(self._lanes.values())
            self._lanes.clear()
            runtime = self._runtime
            self._runtime = None
        for ln in lanes:
            ln.close()
        if runtime is not None:
            runtime.stop()
        with self._adopted_lock:
            if self._adopted_exec is not None:
                self._adopted_exec.shutdown(wait=False)
                self._adopted_exec = None
            unit = self._adopted_unit
            self._adopted_unit = None
        if unit is not None:
            try:
                unit.close()
            except Exception:  # noqa: BLE001 — teardown best-effort
                pass


def collect_jobs(core: FleetCore, jobs, cfg, now: float):
    """Wait out a fleet ticket's forward/adopted futures: returns
    ``(parts, err)`` where ``parts`` is ``[(positions, result)]`` ready
    for :func:`scatter_merge`. A failed job degrades EXACTLY its rows
    per the fail-open/closed policy — one failed coalesced wire frame
    touches only its member fragments (fail-closed keeps the FIRST
    error to raise after every job is drained — the ADR-013
    non-transactional frame contract: other hosts' quota stands).

    Per-leg completion (ADR-019 residual): legs harvest AS THEY FINISH
    under ONE shared deadline anchored at collect start, not
    sequentially in launch order each with a fresh budget. An
    early-finishing leg surfaces its rows (and releases its lane reply
    buffer reference) immediately even when an earlier-launched leg is
    the slow one, and the whole barrier is bounded by max(leg), never
    sum(timeouts) — the old loop could stall a pipelined completer
    thread for n_legs × deadline behind one wedged peer."""
    parts = []
    err = None
    deadline = time.monotonic() + core.forward_deadline + 2.0
    by_fut = {id(fut): (pos, ordinal) for pos, fut, ordinal in jobs}

    def _harvest(fut) -> None:
        nonlocal err
        pos, ordinal = by_fut[id(fut)]
        k = int(pos.shape[0])
        try:
            out = fut.result(timeout=0)
        except Exception as exc:
            if ordinal is not None:
                core.note_forward_failure(ordinal, exc, k)
            if not cfg.fail_open:
                err = err if err is not None else StorageUnavailableError(
                    f"fleet forward failed ({exc}); rows fail closed "
                    f"per config")
                return
            out = batch_fail_open(k, cfg.limit, now + float(cfg.window))
        parts.append((pos, out))

    pending = {fut for _, fut, _ in jobs}
    while pending:
        done, pending = concurrent.futures.wait(
            pending, timeout=max(0.0, deadline - time.monotonic()),
            return_when=concurrent.futures.FIRST_COMPLETED)
        for fut in done:
            _harvest(fut)
        if pending and not done:
            # Shared budget exhausted with legs still in flight: fail
            # exactly those rows (fut.result(0) raises TimeoutError).
            for fut in pending:
                _harvest(fut)
            break
    return parts, err


#: One-pass record view of a list of scalar Results (the forwarded-
#: string in-process legs); columnar assembly replaces the former
#: six per-row list comprehensions.
_RESULT_REC = np.dtype([("allowed", "?"), ("remaining", "<i8"),
                        ("retry", "<f8"), ("reset", "<f8"),
                        ("fail_open", "?"), ("limit", "<i8")])


def scatter_merge(b: int, limit: int, parts) -> BatchResult:
    """Scatter per-group results back to frame order: ``parts`` is
    ``[(positions | None, BatchResult | list[Result])]`` (None =
    positions are the whole frame). ``fail_open`` ORs over groups (the
    multi-shard contract, ADR-013); per-row ``limits`` materialize when
    any group carried overrides. Forwarded legs arrive as BatchResult
    row-range VIEWS of the coalesced lane reply (ADR-019) and assemble
    with four vectorized scatters; list[Result] legs collapse to one
    structured-array pass."""
    allowed = np.zeros(b, dtype=bool)
    remaining = np.zeros(b, dtype=np.int64)
    retry = np.zeros(b, dtype=np.float64)
    reset_at = np.zeros(b, dtype=np.float64)
    limits = None
    fail_open = False
    for pos, out in parts:
        sel = slice(None) if pos is None else pos
        if isinstance(out, list):  # forwarded string rows: Result objects
            rec = np.array([(r.allowed, r.remaining, r.retry_after,
                             r.reset_at, r.fail_open, r.limit)
                            for r in out], dtype=_RESULT_REC)
            allowed[sel] = rec["allowed"]
            remaining[sel] = rec["remaining"]
            retry[sel] = rec["retry"]
            reset_at[sel] = rec["reset"]
            fail_open = fail_open or bool(rec["fail_open"].any())
            if (rec["limit"] != limit).any():
                # Keep whatever limit fidelity the leg carried. NOTE:
                # the RESULT_BATCH wire stamps every row with the
                # DEFAULT limit (overridden keys' true limits ride the
                # scalar path only — protocol.py), so forwarded batch
                # rows inherit that documented wire bound; this branch
                # matters for in-process legs and future wire upgrades.
                if limits is None:
                    limits = np.full(b, limit, dtype=np.int64)
                limits[sel] = rec["limit"]
        else:
            allowed[sel] = out.allowed
            remaining[sel] = out.remaining
            retry[sel] = out.retry_after
            reset_at[sel] = out.reset_at
            fail_open = fail_open or out.fail_open
            if getattr(out, "limits", None) is not None:
                if limits is None:
                    limits = np.full(b, limit, dtype=np.int64)
                limits[sel] = out.limits
    return BatchResult(allowed=allowed, limit=limit, remaining=remaining,
                       retry_after=retry, reset_at=reset_at,
                       fail_open=fail_open, limits=limits)


class FleetTicket(DispatchTicket):
    """Composite ticket for one frame split across the fleet: the local
    sub-ticket plus in-flight forward / adopted futures, scattered back
    to frame order at resolve (the cross-HOST sibling of
    MeshDispatchTicket's cross-slice form)."""

    __slots__ = ("local", "local_pos", "jobs")

    def __init__(self, result=None):
        super().__init__(result)
        self.local = None        # (positions | None, inner ticket)
        self.local_pos = None
        self.jobs = ()           # [(positions, future, ordinal|None)]


class FleetForwarder(LimiterDecorator):
    """Asyncio-door fleet decorator: partitions every decision frame by
    keyspace owner — local rows dispatch on the inner limiter, adopted
    rows on the failover standby unit, foreign rows submit onto their
    owner's coalesced forward lane — and reassembles per-frame answers
    in frame order. Wraps the TOP of the serving stack (outside
    persistence: forwarded rows must not consume local quota, and
    decisions are never WAL-logged anyway)."""

    def __init__(self, inner, core: FleetCore):
        super().__init__(inner)
        self.core = core

    @property
    def pipelined(self) -> bool:
        return bool(getattr(self.inner, "pipelined", False))

    # ------------------------------------------------------------ helpers

    def _launch_fleet(self, h64: np.ndarray, ns: np.ndarray, now: float,
                      *, owners: Optional[np.ndarray] = None,
                      keys: Optional[List[str]] = None,
                      raw_ids: Optional[np.ndarray] = None,
                      wire: bool = False) -> FleetTicket:
        core = self.core
        if owners is None:
            owners = core.owners_of_hash(h64)
        if core.all_local(owners):
            # Fast path: the whole frame is ours — one owner check, no
            # split, the inner ticket passes through (wire buffers
            # preserved).
            if raw_ids is not None:
                return self.inner.launch_ids(raw_ids, ns, now=now,
                                             wire=wire)
            return self.inner.launch_hashed(h64, ns, now=now)
        local_pos, adopted_pos, foreign = core.split(h64, owners)
        if foreign and not core.forward_enabled:
            o = next(iter(foreign))
            raise core.redirect_error(int(h64[foreign[o][0]]), o)
        t = FleetTicket()
        t.b = int(h64.shape[0])
        t.limit = self.inner.config.limit
        t.t_sec = now
        jobs = []
        if local_pos.shape[0]:
            if local_pos.shape[0] == t.b:
                sub_h, sub_n = h64, ns
                t.local_pos = None
            else:
                sub_h, sub_n = h64[local_pos], ns[local_pos]
                t.local_pos = local_pos
            if raw_ids is not None:
                ids_sub = (raw_ids if t.local_pos is None
                           else raw_ids[local_pos])
                t.local = self.inner.launch_ids(ids_sub, sub_n, now=now)
            else:
                t.local = self.inner.launch_hashed(sub_h, sub_n, now=now)
        if adopted_pos.shape[0]:
            jobs.append((adopted_pos,
                         core.decide_adopted_hashed(h64[adopted_pos],
                                                    ns[adopted_pos]),
                         None))
        keys_fn = (None if keys is None
                   else (lambda p_: [keys[i] for i in p_]))
        for o, pos in foreign.items():
            if o in core._dead_ordinals:
                # Known-dead owner mid-failover: degrade now rather than
                # pay a connect timeout per frame.
                fut: concurrent.futures.Future = concurrent.futures.Future()
                fut.set_exception(StorageUnavailableError(
                    f"fleet owner {core.map.hosts[o].id} is down "
                    f"(failover pending)"))
                jobs.append((pos, fut, o))
                continue
            for sub_pos, fut in core.forward_jobs(o, pos, h64, ns,
                                                  keys_fn=keys_fn):
                jobs.append((sub_pos, fut, o))
        t.jobs = tuple(jobs)
        return t

    # ----------------------------------------------------------- launch

    def launch_batch(self, keys, ns=None, *, now=None):
        from ratelimiter_tpu.algorithms.base import check_key, check_n

        keys = list(keys)
        for k in keys:
            check_key(k)
        if ns is None:
            ns_arr = np.ones(len(keys), dtype=np.int64)
        else:
            for n in ns:
                check_n(int(n))
            ns_arr = np.asarray(ns, dtype=np.int64)
        t = self.clock.now() if now is None else float(now)
        h64 = self.core.hash_keys(keys)
        # Owners computed ONCE and threaded through (_launch_fleet used
        # to recompute the same table gather per frame).
        owners = self.core.owners_of_hash(h64)
        if self.core.all_local(owners):
            return self.inner.launch_batch(keys, ns, now=now)
        return self._launch_fleet(h64, ns_arr, t, owners=owners,
                                  keys=keys)

    def launch_ids(self, ids, ns=None, *, now=None, wire: bool = False):
        ids = np.asarray(ids, dtype=np.uint64)
        ns_arr = (np.ones(ids.shape[0], dtype=np.int64) if ns is None
                  else np.asarray(ns, dtype=np.int64))
        t = self.clock.now() if now is None else float(now)
        return self._launch_fleet(splitmix64(ids), ns_arr, t,
                                  raw_ids=ids, wire=wire)

    def launch_hashed(self, h64, ns=None, *, now=None):
        h64 = np.asarray(h64, dtype=np.uint64)
        ns_arr = (np.ones(h64.shape[0], dtype=np.int64) if ns is None
                  else np.asarray(ns, dtype=np.int64))
        t = self.clock.now() if now is None else float(now)
        return self._launch_fleet(h64, ns_arr, t)

    # ---------------------------------------------------------- resolve

    def resolve(self, ticket):
        if not isinstance(ticket, FleetTicket):
            return self.inner.resolve(ticket)
        if ticket.result is not None:
            return ticket.result
        parts = []
        err = None
        if ticket.local is not None:
            try:
                parts.append((ticket.local_pos,
                              self.inner.resolve(ticket.local)))
            except Exception as exc:  # finish the forwards regardless
                err = exc
        fparts, ferr = collect_jobs(self.core, ticket.jobs,
                                    self.inner.config, ticket.t_sec)
        parts.extend(fparts)
        err = err if err is not None else ferr
        if err is not None:
            raise err
        res = scatter_merge(ticket.b, ticket.limit, parts)
        ticket.result = res
        return res

    # ------------------------------------------------------ sync surface

    def allow_batch(self, keys, ns=None, *, now=None):
        return self.resolve(self.launch_batch(keys, ns, now=now))

    def allow_ids(self, ids, ns=None, *, now=None):
        return self.resolve(self.launch_ids(ids, ns, now=now))

    def allow_hashed(self, h64, ns=None, *, now=None):
        return self.resolve(self.launch_hashed(h64, ns, now=now))

    def allow_n(self, key, n, *, now=None):
        core = self.core
        h64 = core.hash_keys([key])
        owner = int(core.owners_of_hash(h64)[0])
        if owner == core.self_ordinal:
            if core._adopted_buckets.any() and bool(
                    core._adopted_buckets[
                        int(core.map.bucket_of_hash(h64)[0])]):
                return core.adopted_submit(
                    lambda: core.adopted_unit.allow_n(
                        key, n, now=now)).result()
            return self.inner.allow_n(key, n, now=now)
        if not core.forward_enabled:
            raise core.redirect_error(int(h64[0]), owner)
        t = self.clock.now() if now is None else float(now)
        try:
            fut = core.forward_allow_n(owner, key, n)
            return fut.result(timeout=core.forward_deadline + 2.0)
        except Exception as exc:
            core.note_forward_failure(owner, exc, 1)
            cfg = self.inner.config
            if not cfg.fail_open:
                raise StorageUnavailableError(
                    f"fleet forward failed ({exc}); fails closed per "
                    f"config") from exc
            return fail_open_result(cfg.limit, t + float(cfg.window))

    def reset(self, key: str) -> None:
        """Reset applies locally AND at the owner (a mis-routed reset on
        a non-owner would otherwise be a silent no-op — the same rule as
        shard-routed resets, stretched across hosts)."""
        core = self.core
        h64 = core.hash_keys([key])
        owner = int(core.owners_of_hash(h64)[0])
        if owner == core.self_ordinal:
            if core._adopted_buckets.any() and bool(
                    core._adopted_buckets[
                        int(core.map.bucket_of_hash(h64)[0])]):
                core.adopted_submit(
                    lambda: core.adopted_unit.reset(key)).result()
                return
            self.inner.reset(key)
            return
        if not core.forward_enabled:
            raise core.redirect_error(int(h64[0]), owner)
        core.forward_op(owner, "reset", key).result(
            timeout=core.forward_deadline + 2.0)

    # Policy overrides apply on the LOCAL stack only: fleet-wide
    # distribution is the client's job (FleetClient.set_override hits
    # every member, exactly as set_override_all hits every shard) — a
    # server cannot know whether its peers already heard the same call.
    # The adopted unit mirrors local writes so adopted keys honor
    # overrides set after failover.

    def set_override(self, key, limit=None, *, window_scale=1.0):
        ov = self.inner.set_override(key, limit, window_scale=window_scale)
        unit = self.core.adopted_unit
        if unit is not None:
            self.core.adopted_submit(
                lambda: unit.set_override(
                    key, limit, window_scale=window_scale)).result()
        return ov

    def delete_override(self, key) -> bool:
        existed = self.inner.delete_override(key)
        unit = self.core.adopted_unit
        if unit is not None:
            existed = self.core.adopted_submit(
                lambda: unit.delete_override(key)).result() or existed
        return existed

    def get_override(self, key):
        core = self.core
        unit = core.adopted_unit
        if unit is not None:
            h64 = core.hash_keys([key])
            if bool(core._adopted_buckets[
                    int(core.map.bucket_of_hash(h64)[0])]):
                # Overrides restored from the dead host's WAL live only
                # in the standby unit.
                return core.adopted_submit(
                    lambda: unit.get_override(key)).result()
        return self.inner.get_override(key)

    def close(self) -> None:
        super().close()
        self.core.close()
