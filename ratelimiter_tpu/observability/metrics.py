"""Minimal thread-safe metrics registry with Prometheus text exposition.

The reference plans a Prometheus ``MetricsDecorator``
(``docs/ADR/003-decorator-pattern-for-observability.md:44-66``) with metric
names specced in ``docs/ARCHITECTURE.md:550-566``. No Prometheus client
library is vendored in this environment, so this module implements the
small subset the decorators and the serving tier need — counters, gauges,
histograms, with labels — and renders the standard text format an actual
Prometheus scraper would accept. No external deps, O(1) hot-path cost
(a dict lookup + float add under a lock).
"""

from __future__ import annotations

import threading
import time
from bisect import bisect_left
from typing import Dict, Iterable, Optional, Sequence, Tuple

#: Default histogram buckets, seconds — spans 10 µs host overhead to multi-
#: second SLO breaches (device dispatches land in the 100 µs .. 10 ms range).
LATENCY_BUCKETS = (1e-5, 5e-5, 1e-4, 2.5e-4, 5e-4, 1e-3, 2.5e-3, 5e-3,
                   1e-2, 2.5e-2, 5e-2, 0.1, 0.25, 0.5, 1.0, 2.5)

#: Batch-size buckets for the micro-batcher (powers of two up to 64K).
BATCH_BUCKETS = tuple(float(1 << i) for i in range(17))

#: Snapshot-duration buckets, seconds — the durability subsystem's
#: background captures span ~1 ms (tiny host state) to tens of seconds
#: (multi-GiB sketch rings serialized off-lock). Families using them:
#: rate_limiter_snapshot_duration_seconds plus the gauges/counters
#: rate_limiter_last_snapshot_timestamp_seconds,
#: rate_limiter_snapshot_capture_seconds, rate_limiter_snapshots_total,
#: rate_limiter_snapshot_failures_total, rate_limiter_wal_records_total,
#: rate_limiter_wal_bytes_total, rate_limiter_wal_seq
#: (ratelimiter_tpu/persistence/).
SNAPSHOT_DURATION_BUCKETS = (1e-3, 5e-3, 1e-2, 2.5e-2, 5e-2, 0.1, 0.25,
                             0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0)


def _label_key(labels: Dict[str, str]) -> Tuple[Tuple[str, str], ...]:
    return tuple(sorted(labels.items()))


def _escape_label_value(v: str) -> str:
    """Escape a label value per the Prometheus text exposition spec
    (backslash, double-quote, and newline must be escaped INSIDE the
    quotes) — user-derived values (keys, algorithm strings) would
    otherwise corrupt the whole scrape with one embedded quote."""
    return (str(v).replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def _fmt_labels(items: Iterable[Tuple[str, str]]) -> str:
    inner = ",".join(f'{k}="{_escape_label_value(v)}"' for k, v in items)
    return "{" + inner + "}" if inner else ""


class _Metric:
    def __init__(self, name: str, help_: str, kind: str):
        self.name = name
        self.help = help_
        self.kind = kind
        self._lock = threading.Lock()


class Counter(_Metric):
    """Monotonic counter family, keyed by label values."""

    def __init__(self, name: str, help_: str):
        super().__init__(name, help_, "counter")
        self._values: Dict[tuple, float] = {}

    def inc(self, amount: float = 1.0, **labels: str) -> None:
        key = _label_key(labels)
        with self._lock:
            self._values[key] = self._values.get(key, 0.0) + amount

    def value(self, **labels: str) -> float:
        # Under the lock: a bare dict read races inc()'s read-modify-
        # write and (on resize) dict mutation — cheap, and value() is
        # never on the decide path.
        with self._lock:
            return self._values.get(_label_key(labels), 0.0)

    def total(self, **labels_filter: str) -> float:
        """Sum over every label set matching the (partial) filter —
        the SLO burn-rate tracker's family-wide read (observability/
        slo.py): e.g. a shed counter labeled per door sums to one
        bad-event count."""
        with self._lock:
            out = 0.0
            for key, v in self._values.items():
                kd = dict(key)
                if all(kd.get(k) == v2 for k, v2 in labels_filter.items()):
                    out += v
            return out

    def labeled_values(self) -> list[tuple[tuple, float]]:
        """Locked snapshot of (label_key, value) pairs — for consumers
        that must inspect label VALUES (the burn tracker matches
        ``result=error:*`` prefixes)."""
        with self._lock:
            return list(self._values.items())

    def render(self, om: bool = False) -> list[str]:
        # OpenMetrics requires the counter FAMILY name without the
        # `_total` suffix (HELP/TYPE lines) while the sample keeps it —
        # `# TYPE x_total counter` fails Prometheus's strict OM parser,
        # which would reject the whole scrape. Classic text exposition
        # uses the full name in both places.
        family = self.name
        sample = self.name
        if om:
            if family.endswith("_total"):
                family = family[:-len("_total")]
            else:
                sample = family + "_total"
        lines = [f"# HELP {family} {self.help}",
                 f"# TYPE {family} counter"]
        with self._lock:
            for key, v in sorted(self._values.items()):
                lines.append(f"{sample}{_fmt_labels(key)} {v:g}")
        return lines


class Gauge(_Metric):
    """Point-in-time value family."""

    def __init__(self, name: str, help_: str):
        super().__init__(name, help_, "gauge")
        self._values: Dict[tuple, float] = {}

    def set(self, value: float, **labels: str) -> None:
        with self._lock:
            self._values[_label_key(labels)] = float(value)

    def inc(self, amount: float = 1.0, **labels: str) -> None:
        key = _label_key(labels)
        with self._lock:
            self._values[key] = self._values.get(key, 0.0) + amount

    def value(self, **labels: str) -> float:
        with self._lock:
            return self._values.get(_label_key(labels), 0.0)

    def clear(self) -> None:
        """Drop every label set. For identity/info gauges whose label
        VALUES change over time (e.g. the fleet map epoch on
        ``rate_limiter_member_info``): a gauge only overwrites label
        sets it is told about, so a collect hook clears before it sets
        or stale identities would persist forever."""
        with self._lock:
            self._values.clear()

    def render(self) -> list[str]:
        lines = [f"# HELP {self.name} {self.help}",
                 f"# TYPE {self.name} gauge"]
        with self._lock:
            for key, v in sorted(self._values.items()):
                lines.append(f"{self.name}{_fmt_labels(key)} {v:g}")
        return lines


class Histogram(_Metric):
    """Cumulative histogram family (Prometheus bucket semantics)."""

    def __init__(self, name: str, help_: str,
                 buckets: Sequence[float] = LATENCY_BUCKETS):
        super().__init__(name, help_, "histogram")
        self.buckets = tuple(sorted(buckets))
        self._counts: Dict[tuple, list] = {}   # key -> per-bucket counts + inf
        self._sums: Dict[tuple, float] = {}
        #: (key, bucket_index) -> (exemplar trace id, value, unix ts):
        #: the LAST sampled observation that landed in that bucket.
        #: Rendered only by the OpenMetrics exposition (render_om) —
        #: classic Prometheus text has no exemplar syntax.
        self._exemplars: Dict[tuple, tuple] = {}

    def observe(self, value: float, *, exemplar: Optional[str] = None,
                **labels: str) -> None:
        key = _label_key(labels)
        # bisect instead of a linear scan: this runs per decision on
        # 16-bucket latency families (bisect_left on "first ub >= value"
        # is exactly the old `value <= ub` bucket rule).
        i = bisect_left(self.buckets, value)
        with self._lock:
            counts = self._counts.get(key)
            if counts is None:
                counts = [0] * (len(self.buckets) + 1)
                self._counts[key] = counts
                self._sums[key] = 0.0
            counts[i if i < len(self.buckets) else -1] += 1
            self._sums[key] += value
            if exemplar is not None:
                self._exemplars[(key, i)] = (exemplar, value, time.time())

    def count(self, **labels: str) -> int:
        with self._lock:
            return sum(self._counts.get(_label_key(labels), []))

    def sum(self, **labels: str) -> float:
        with self._lock:
            return self._sums.get(_label_key(labels), 0.0)

    def counts_over(self, threshold: float,
                    **labels_filter: str) -> tuple[int, int, float]:
        """(total, over, effective_threshold) across every label set
        matching the (partial) filter: how many observations landed
        STRICTLY above the largest bucket bound <= ``threshold``.
        Cumulative buckets only resolve at bucket bounds, so the
        threshold snaps DOWN to one (returned as effective_threshold;
        pessimistic — borderline observations count as slow). The SLO
        burn-rate tracker derives its latency axis from this
        (observability/slo.py)."""
        from bisect import bisect_right

        idx = bisect_right(self.buckets, threshold)
        eff = self.buckets[idx - 1] if idx > 0 else 0.0
        total = over = 0
        with self._lock:
            for key, counts in self._counts.items():
                kd = dict(key)
                if not all(kd.get(k) == v for k, v in labels_filter.items()):
                    continue
                s = sum(counts)
                total += s
                over += s - sum(counts[:idx])
        return total, over, eff

    def render(self, om: bool = False) -> list[str]:
        lines = [f"# HELP {self.name} {self.help}",
                 f"# TYPE {self.name} histogram"]
        with self._lock:
            for key, counts in sorted(self._counts.items()):
                cum = 0
                for i, ub in enumerate(self.buckets):
                    cum += counts[i]
                    line = (f"{self.name}_bucket"
                            f"{_fmt_labels(key + (('le', f'{ub:g}'),))} {cum}")
                    ex = self._exemplars.get((key, i)) if om else None
                    if ex is not None:
                        # OpenMetrics exemplar: ties this le-bucket to a
                        # trace id recorded by the flight recorder
                        # (ADR-014) — `# {trace_id="..."} value ts`.
                        line += (f' # {{trace_id="{ex[0]}"}} {ex[1]:g}'
                                 f" {ex[2]:.3f}")
                    lines.append(line)
                cum += counts[-1]
                line = (f"{self.name}_bucket"
                        f"{_fmt_labels(key + (('le', '+Inf'),))} {cum}")
                # The overflow bucket keeps its exemplar too — the
                # slowest observations are exactly the ones worth a
                # trace id (observe() stores them at index
                # len(self.buckets)).
                ex = (self._exemplars.get((key, len(self.buckets)))
                      if om else None)
                if ex is not None:
                    line += (f' # {{trace_id="{ex[0]}"}} {ex[1]:g}'
                             f" {ex[2]:.3f}")
                lines.append(line)
                lines.append(f"{self.name}_sum{_fmt_labels(key)} {self._sums[key]:g}")
                lines.append(f"{self.name}_count{_fmt_labels(key)} {cum}")
        return lines


class Registry:
    """A named collection of metric families; renders the Prometheus text
    exposition format. One default registry per process (DEFAULT), but
    tests and multi-limiter deployments can build private ones."""

    def __init__(self):
        self._lock = threading.Lock()
        self._metrics: Dict[str, _Metric] = {}
        self._collect_hooks: list = []

    def _register(self, metric: _Metric) -> _Metric:
        with self._lock:
            existing = self._metrics.get(metric.name)
            if existing is not None:
                if existing.kind != metric.kind:
                    raise ValueError(
                        f"metric {metric.name} already registered as {existing.kind}")
                return existing
            self._metrics[metric.name] = metric
            return metric

    def counter(self, name: str, help_: str = "") -> Counter:
        return self._register(Counter(name, help_))  # type: ignore[return-value]

    def gauge(self, name: str, help_: str = "") -> Gauge:
        return self._register(Gauge(name, help_))  # type: ignore[return-value]

    def histogram(self, name: str, help_: str = "",
                  buckets: Sequence[float] = LATENCY_BUCKETS) -> Histogram:
        return self._register(Histogram(name, help_, buckets))  # type: ignore[return-value]

    def get(self, name: str) -> Optional[_Metric]:
        return self._metrics.get(name)

    def add_collect_hook(self, fn) -> None:
        """Register a zero-arg callable run at the START of every
        ``render()`` — i.e. at scrape time. This is how gauges whose
        value costs real work (a device fetch under the backend lock,
        e.g. the debt-slab occupancy surface) stay current without ever
        touching the decision hot path: they refresh once per scrape,
        not once per decision. Hooks must be idempotent; duplicates are
        collapsed by identity of the bound callable."""
        with self._lock:
            if fn not in self._collect_hooks:
                self._collect_hooks.append(fn)

    def remove_collect_hook(self, fn) -> None:
        """Unregister a collect hook (no-op if absent). Owners of hooked
        resources MUST call this on close — on the process-default
        registry a leftover hook would pin the closed backend (and its
        device arrays) alive forever and run against it on every
        scrape."""
        with self._lock:
            try:
                self._collect_hooks.remove(fn)
            except ValueError:
                pass

    def render(self, *, openmetrics: bool = False) -> str:
        with self._lock:
            hooks = list(self._collect_hooks)
        for hook in hooks:
            try:
                hook()
            except Exception:  # noqa: BLE001 — a scrape must never fail
                # because one collector's backend is mid-restart/closed;
                # the gauge just keeps its last value.
                pass
        lines: list[str] = []
        with self._lock:
            metrics = list(self._metrics.values())
        for m in metrics:
            if openmetrics and isinstance(m, (Histogram, Counter)):
                lines.extend(m.render(om=True))
            else:
                lines.extend(m.render())
        if openmetrics:
            lines.append("# EOF")
        return "\n".join(lines) + "\n"

    def render_openmetrics(self) -> str:
        """OpenMetrics-flavored exposition: same families, plus
        histogram bucket EXEMPLARS (`# {trace_id="..."} v ts`) tying
        `rate_limiter_*_seconds` buckets to the flight-recorder trace
        ids that landed in them (ADR-014), and the `# EOF` terminator.
        The HTTP gateway serves this for
        `Accept: application/openmetrics-text` scrapes."""
        return self.render(openmetrics=True)


#: Process-default registry (the serving tier exposes it over /metrics).
DEFAULT = Registry()
