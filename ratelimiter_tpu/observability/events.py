"""Control-plane event journal: WHY the limiter changed its mind (ADR-021).

The flight recorder (ADR-014) answers "where did this frame's latency
go" and the observatory (ADR-016) answers "how accurate are we being" —
but after PRs 10-13 turned N hosts into ONE limiter, the questions an
operator actually asks during an incident are control-plane ones: *why
did tenant X get tightened at 14:02, who adopted h1's ranges, when did
slice 3 quarantine, which member published epoch 9?* Until now those
answers lived in scattered WARNING log lines on N machines. This module
is the structured, bounded, queryable record of every control-plane
transition, exposed per member via bearer-gated ``GET /debug/events``
(cursor-paginated) and fleet-wide via ``GET /debug/events?fleet=1``
(merged on the membership's estimated clock offsets, fleet/tower.py).

Design rules:

* **Events are rare.** Controller moves, quarantine transitions,
  handoffs, failovers, epoch bumps, policy/tenant mutations — tens per
  minute at the very worst. A plain lock + deque is the right cost
  model; nothing here is ever on the decide path.
* **Same module-global seam** as ``tracing.RECORDER`` / ``audit.AUDITOR``
  / the chaos injector: library code calls :func:`emit`, which is one
  None check when the journal is off. The server binary enables it by
  default (``--no-event-journal`` opts out) because the whole point is
  being able to reconstruct an incident you did not predict.
* **Every event carries both clocks**: wall time (human correlation,
  NTP-grade) and CLOCK_MONOTONIC ns (the span clock, ADR-014) — the
  fleet merge aligns members on the same per-peer monotonic offsets the
  trace stitcher uses, so events interleave correctly with spans on one
  Perfetto timeline.
* **Correlation ids** join an event to its cause: a controller tick
  stamps one id on every move it makes (and into its log line), handoff
  events share the giver's id across send/receive/flip, and a traced
  frame's trace id can ride along. Ids render as 16-hex tokens, the
  trace-id convention.

Cursor pagination contract (``read``): the caller passes ``after`` (the
last ``seq`` it has seen; 0 = from the oldest held) and gets events with
``seq > after`` in order, up to ``limit``, plus ``cursor`` (pass it back
as the next ``after``) and ``truncated`` (True when the bounded ring
dropped events the cursor never saw — the caller's history has a hole).
"""

from __future__ import annotations

import json
import os
import re
import threading
import time
from collections import deque
from typing import Dict, List, Optional

#: Category vocabulary (free-form strings are accepted — a dump must
#: never be lost to a new subsystem — but the known set is documented
#: so dashboards can enumerate it).
CATEGORIES = (
    "controller",   # AIMD tighten/relax with the triggering signals
    "quarantine",   # slice state transitions (ADR-015)
    "handoff",      # live migration / departure / rejoin phases (ADR-018)
    "failover",     # dead-peer range adoption (ADR-017)
    "epoch",        # ownership-map installs/adoptions
    "membership",   # peer liveness transitions
    "policy",       # per-key override + reset mutations
    "tenant",       # tenant registry / assignment / effective-limit moves
    "lease",        # client-embedded quota leases: grant/return/revoke/expire
    "placement",    # load-aware rebalancing: plan/move/abort/veto (ADR-023)
)


class EventJournal:
    """Bounded in-memory ring of structured control-plane events."""

    def __init__(self, capacity: int = 4096, *, host: str = "",
                 registry=None, spill_dir: Optional[str] = None,
                 spill_segment_bytes: int = 1 << 20,
                 spill_segments: int = 8):
        if capacity < 16:
            raise ValueError(f"capacity must be >= 16, got {capacity}")
        self.capacity = int(capacity)
        self.host = host
        self._lock = threading.Lock()
        self._events: deque = deque(maxlen=self.capacity)
        self._seq = 0
        self._counter = None
        if registry is not None:
            self._counter = registry.counter(
                "rate_limiter_events_total",
                "Control-plane events recorded in the event journal "
                "(ADR-021), by category")
        # Optional append-only file spill: a restart replays the tail
        # of the on-disk segments back into the ring, so pre-restart
        # events survive (`--event-journal-dir`). Bounded: segments
        # rotate at spill_segment_bytes and the oldest is deleted past
        # spill_segments. Spill failures NEVER break serving — they are
        # counted and surfaced in status().
        self._spill_dir = spill_dir
        self._spill_segment_bytes = max(4096, int(spill_segment_bytes))
        self._spill_segments = max(1, int(spill_segments))
        self._spill_file = None
        self._spill_path = ""
        self._spill_index = 0
        self._spill_written = 0
        self._spill_errors = 0
        self._replayed = 0
        if spill_dir:
            self._spill_open(spill_dir)

    # ------------------------------------------------------------ spill

    _SEG_RE = re.compile(r"^events-(\d{8})\.jsonl$")

    def _segments(self) -> List[str]:
        try:
            names = sorted(n for n in os.listdir(self._spill_dir)
                           if self._SEG_RE.match(n))
        except OSError:
            return []
        return names

    def _spill_open(self, spill_dir: str) -> None:
        try:
            os.makedirs(spill_dir, exist_ok=True)
            segs = self._segments()
            # Replay the on-disk tail (oldest segment first) into the
            # ring, re-sequencing: seqs are per-process-generation, the
            # ring's contract is only "monotonic within this journal".
            replay: deque = deque(maxlen=self.capacity)
            for name in segs:
                try:
                    with open(os.path.join(self._spill_dir, name),
                              encoding="utf-8") as f:
                        for line in f:
                            line = line.strip()
                            if not line:
                                continue
                            try:
                                e = json.loads(line)
                            except ValueError:
                                continue  # torn tail write (kill -9)
                            if isinstance(e, dict) and "category" in e:
                                replay.append(e)
                except OSError:
                    continue
            for e in replay:
                self._seq += 1
                e["seq"] = self._seq
                e.setdefault("replayed", True)
                self._events.append(e)
            self._replayed = len(replay)
            if segs:
                self._spill_index = int(
                    self._SEG_RE.match(segs[-1]).group(1)) + 1
            self._spill_rotate_locked()
        except OSError:
            self._spill_errors += 1
            self._spill_file = None

    def _spill_rotate_locked(self) -> None:
        if self._spill_file is not None:
            try:
                self._spill_file.close()
            except OSError:
                pass
        self._spill_path = os.path.join(
            self._spill_dir, f"events-{self._spill_index:08d}.jsonl")
        self._spill_file = open(self._spill_path, "a",
                                encoding="utf-8")
        self._spill_index += 1
        self._spill_written = 0
        # Enforce the segment bound (oldest deleted first).
        segs = self._segments()
        while len(segs) > self._spill_segments:
            try:
                os.unlink(os.path.join(self._spill_dir, segs.pop(0)))
            except OSError:
                break

    def _spill_locked(self, event: dict) -> None:
        if self._spill_file is None:
            return
        try:
            line = json.dumps(event, sort_keys=True,
                              default=str) + "\n"
            self._spill_file.write(line)
            self._spill_file.flush()
            self._spill_written += len(line)
            if self._spill_written >= self._spill_segment_bytes:
                self._spill_rotate_locked()
        except (OSError, ValueError):
            self._spill_errors += 1

    def close(self) -> None:
        with self._lock:
            if self._spill_file is not None:
                try:
                    self._spill_file.close()
                except OSError:
                    pass
                self._spill_file = None

    # ----------------------------------------------------------- record

    def record(self, category: str, action: str, *, actor: str = "",
               corr: int = 0, severity: str = "info",
               payload: Optional[dict] = None) -> int:
        """Append one event; returns its seq. ``corr`` is a u64
        correlation id (0 = none), rendered as the 16-hex trace-id
        convention so it joins against flight-recorder spans."""
        now_wall = time.time()
        now_mono = time.monotonic_ns()
        with self._lock:
            self._seq += 1
            seq = self._seq
            event = {
                "seq": seq,
                "ts": round(now_wall, 6),
                "mono_ns": now_mono,
                "category": str(category),
                "action": str(action),
                "actor": str(actor),
                "corr": (f"{corr & 0xFFFFFFFFFFFFFFFF:016x}" if corr
                         else ""),
                "severity": str(severity),
                "payload": dict(payload) if payload else {},
            }
            self._events.append(event)
            self._spill_locked(event)
        c = self._counter
        if c is not None:
            c.inc(category=str(category))
        return seq

    # ------------------------------------------------------------- read

    def read(self, after: int = 0, limit: int = 256,
             category: Optional[str] = None) -> Dict:
        """Events with ``seq > after`` (oldest first), up to ``limit``.
        See the module docstring for the pagination contract."""
        limit = max(1, min(int(limit), self.capacity))
        with self._lock:
            events = list(self._events)
            newest = self._seq
        oldest = events[0]["seq"] if events else newest + 1
        out: List[dict] = []
        for e in events:
            if e["seq"] <= after:
                continue
            if category is not None and e["category"] != category:
                continue
            out.append(e)
            if len(out) >= limit:
                break
        cursor = out[-1]["seq"] if out else max(after, newest)
        return {
            "enabled": True,
            "host": self.host,
            "events": out,
            "cursor": cursor,
            "newest": newest,
            # The ring dropped events this cursor never saw: the reader
            # asked for history older than the oldest held event.
            "truncated": bool(after + 1 < oldest and after < newest),
        }

    def tail(self, limit: int = 256,
             category: Optional[str] = None) -> Dict:
        """The NEWEST ``limit`` events (still oldest-first in the
        returned list) — the fleet-merge fetch shape, where per-host
        cursors don't compose."""
        limit = max(1, min(int(limit), self.capacity))
        with self._lock:
            events = list(self._events)
            newest = self._seq
        if category is not None:
            events = [e for e in events if e["category"] == category]
        out = events[-limit:]
        return {"enabled": True, "host": self.host, "events": out,
                "cursor": out[-1]["seq"] if out else newest,
                "newest": newest, "truncated": False}

    def status(self) -> dict:
        with self._lock:
            out = {"capacity": self.capacity,
                   "held": len(self._events), "seq": self._seq}
            if self._spill_dir:
                out["spill"] = {
                    "dir": self._spill_dir,
                    "segments": len(self._segments()),
                    "replayed": self._replayed,
                    "errors": self._spill_errors,
                }
            return out


#: Process-wide journal; None = journaling off. Library emit sites pay
#: one None check when off — the same seam as tracing.RECORDER,
#: audit.AUDITOR, and chaos.INJECTOR. The server binary enables it by
#: default (events are rare; reconstructing an unpredicted incident is
#: the feature).
JOURNAL: Optional[EventJournal] = None


def enable(capacity: int = 4096, *, host: str = "",
           registry=None, spill_dir: Optional[str] = None,
           spill_segment_bytes: int = 1 << 20,
           spill_segments: int = 8) -> EventJournal:
    """Install (and return) the process-wide journal, replacing any
    previous one. With ``spill_dir`` the journal keeps an append-only
    on-disk mirror (bounded rotating segments) and replays its tail
    into the ring on startup — a restart no longer loses the events
    that explain WHY it restarted."""
    global JOURNAL
    JOURNAL = EventJournal(capacity, host=host, registry=registry,
                           spill_dir=spill_dir,
                           spill_segment_bytes=spill_segment_bytes,
                           spill_segments=spill_segments)
    return JOURNAL


def disable() -> None:
    global JOURNAL
    if JOURNAL is not None:
        JOURNAL.close()
    JOURNAL = None


def get() -> Optional[EventJournal]:
    return JOURNAL


def emit(category: str, action: str, **kw) -> None:
    """Guarded record: one None check when journaling is off."""
    j = JOURNAL
    if j is not None:
        j.record(category, action, **kw)
