"""Control-plane event journal: WHY the limiter changed its mind (ADR-021).

The flight recorder (ADR-014) answers "where did this frame's latency
go" and the observatory (ADR-016) answers "how accurate are we being" —
but after PRs 10-13 turned N hosts into ONE limiter, the questions an
operator actually asks during an incident are control-plane ones: *why
did tenant X get tightened at 14:02, who adopted h1's ranges, when did
slice 3 quarantine, which member published epoch 9?* Until now those
answers lived in scattered WARNING log lines on N machines. This module
is the structured, bounded, queryable record of every control-plane
transition, exposed per member via bearer-gated ``GET /debug/events``
(cursor-paginated) and fleet-wide via ``GET /debug/events?fleet=1``
(merged on the membership's estimated clock offsets, fleet/tower.py).

Design rules:

* **Events are rare.** Controller moves, quarantine transitions,
  handoffs, failovers, epoch bumps, policy/tenant mutations — tens per
  minute at the very worst. A plain lock + deque is the right cost
  model; nothing here is ever on the decide path.
* **Same module-global seam** as ``tracing.RECORDER`` / ``audit.AUDITOR``
  / the chaos injector: library code calls :func:`emit`, which is one
  None check when the journal is off. The server binary enables it by
  default (``--no-event-journal`` opts out) because the whole point is
  being able to reconstruct an incident you did not predict.
* **Every event carries both clocks**: wall time (human correlation,
  NTP-grade) and CLOCK_MONOTONIC ns (the span clock, ADR-014) — the
  fleet merge aligns members on the same per-peer monotonic offsets the
  trace stitcher uses, so events interleave correctly with spans on one
  Perfetto timeline.
* **Correlation ids** join an event to its cause: a controller tick
  stamps one id on every move it makes (and into its log line), handoff
  events share the giver's id across send/receive/flip, and a traced
  frame's trace id can ride along. Ids render as 16-hex tokens, the
  trace-id convention.

Cursor pagination contract (``read``): the caller passes ``after`` (the
last ``seq`` it has seen; 0 = from the oldest held) and gets events with
``seq > after`` in order, up to ``limit``, plus ``cursor`` (pass it back
as the next ``after``) and ``truncated`` (True when the bounded ring
dropped events the cursor never saw — the caller's history has a hole).
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Dict, List, Optional

#: Category vocabulary (free-form strings are accepted — a dump must
#: never be lost to a new subsystem — but the known set is documented
#: so dashboards can enumerate it).
CATEGORIES = (
    "controller",   # AIMD tighten/relax with the triggering signals
    "quarantine",   # slice state transitions (ADR-015)
    "handoff",      # live migration / departure / rejoin phases (ADR-018)
    "failover",     # dead-peer range adoption (ADR-017)
    "epoch",        # ownership-map installs/adoptions
    "membership",   # peer liveness transitions
    "policy",       # per-key override + reset mutations
    "tenant",       # tenant registry / assignment / effective-limit moves
    "lease",        # client-embedded quota leases: grant/return/revoke/expire
)


class EventJournal:
    """Bounded in-memory ring of structured control-plane events."""

    def __init__(self, capacity: int = 4096, *, host: str = "",
                 registry=None):
        if capacity < 16:
            raise ValueError(f"capacity must be >= 16, got {capacity}")
        self.capacity = int(capacity)
        self.host = host
        self._lock = threading.Lock()
        self._events: deque = deque(maxlen=self.capacity)
        self._seq = 0
        self._counter = None
        if registry is not None:
            self._counter = registry.counter(
                "rate_limiter_events_total",
                "Control-plane events recorded in the event journal "
                "(ADR-021), by category")

    # ----------------------------------------------------------- record

    def record(self, category: str, action: str, *, actor: str = "",
               corr: int = 0, severity: str = "info",
               payload: Optional[dict] = None) -> int:
        """Append one event; returns its seq. ``corr`` is a u64
        correlation id (0 = none), rendered as the 16-hex trace-id
        convention so it joins against flight-recorder spans."""
        now_wall = time.time()
        now_mono = time.monotonic_ns()
        with self._lock:
            self._seq += 1
            seq = self._seq
            self._events.append({
                "seq": seq,
                "ts": round(now_wall, 6),
                "mono_ns": now_mono,
                "category": str(category),
                "action": str(action),
                "actor": str(actor),
                "corr": (f"{corr & 0xFFFFFFFFFFFFFFFF:016x}" if corr
                         else ""),
                "severity": str(severity),
                "payload": dict(payload) if payload else {},
            })
        c = self._counter
        if c is not None:
            c.inc(category=str(category))
        return seq

    # ------------------------------------------------------------- read

    def read(self, after: int = 0, limit: int = 256,
             category: Optional[str] = None) -> Dict:
        """Events with ``seq > after`` (oldest first), up to ``limit``.
        See the module docstring for the pagination contract."""
        limit = max(1, min(int(limit), self.capacity))
        with self._lock:
            events = list(self._events)
            newest = self._seq
        oldest = events[0]["seq"] if events else newest + 1
        out: List[dict] = []
        for e in events:
            if e["seq"] <= after:
                continue
            if category is not None and e["category"] != category:
                continue
            out.append(e)
            if len(out) >= limit:
                break
        cursor = out[-1]["seq"] if out else max(after, newest)
        return {
            "enabled": True,
            "host": self.host,
            "events": out,
            "cursor": cursor,
            "newest": newest,
            # The ring dropped events this cursor never saw: the reader
            # asked for history older than the oldest held event.
            "truncated": bool(after + 1 < oldest and after < newest),
        }

    def tail(self, limit: int = 256,
             category: Optional[str] = None) -> Dict:
        """The NEWEST ``limit`` events (still oldest-first in the
        returned list) — the fleet-merge fetch shape, where per-host
        cursors don't compose."""
        limit = max(1, min(int(limit), self.capacity))
        with self._lock:
            events = list(self._events)
            newest = self._seq
        if category is not None:
            events = [e for e in events if e["category"] == category]
        out = events[-limit:]
        return {"enabled": True, "host": self.host, "events": out,
                "cursor": out[-1]["seq"] if out else newest,
                "newest": newest, "truncated": False}

    def status(self) -> dict:
        with self._lock:
            return {"capacity": self.capacity, "held": len(self._events),
                    "seq": self._seq}


#: Process-wide journal; None = journaling off. Library emit sites pay
#: one None check when off — the same seam as tracing.RECORDER,
#: audit.AUDITOR, and chaos.INJECTOR. The server binary enables it by
#: default (events are rare; reconstructing an unpredicted incident is
#: the feature).
JOURNAL: Optional[EventJournal] = None


def enable(capacity: int = 4096, *, host: str = "",
           registry=None) -> EventJournal:
    """Install (and return) the process-wide journal, replacing any
    previous one."""
    global JOURNAL
    JOURNAL = EventJournal(capacity, host=host, registry=registry)
    return JOURNAL


def disable() -> None:
    global JOURNAL
    JOURNAL = None


def get() -> Optional[EventJournal]:
    return JOURNAL


def emit(category: str, action: str, **kw) -> None:
    """Guarded record: one None check when journaling is off."""
    j = JOURNAL
    if j is not None:
        j.record(category, action, **kw)
