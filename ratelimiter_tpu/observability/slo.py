"""Admission SLO burn rate: error-budget consumption from live counters.

The decision-quality half of the observatory (ADR-016 §5): PR 7's
flight-recorder stage histograms say how long admissions take, and the
deadline-shed / SLO-breach / storage-error counters say which admissions
the serving tier failed outright — this module folds both into the SRE
burn-rate form ("how fast is the error budget burning, over a fast and a
slow window") so an operator can alert on decision quality the same way
they alert on latency.

Two axes, deliberately kept in their native units (mixing them would be
a lie — spans count dispatches, sheds count decisions):

* **latency axis** (span units): fraction of ``rate_limiter_stage_seconds
  {stage=<stage>}`` observations above the latency target. Requires the
  flight recorder (``--flight-recorder``) for per-stage attribution;
  without it the tracker falls back to the always-on
  ``rate_limiter_server_dispatch_seconds`` histogram (whole-dispatch
  wall time, coarser but honest).
* **availability axis** (decision units): bad = deadline sheds + SLO
  breaches + error-result requests + fail-open requests, over total
  requests + sheds.

``burn_rate`` per window = bad_fraction / (1 - objective): 1.0 means the
budget burns exactly at the sustainable rate; 14.4 over 1h is the classic
"page now" multiwindow threshold. The reported rate per window is the
MAX of the two axes — the budget burns at the rate of its worst axis.

Sampling happens at scrape/healthz cadence (a collect hook on the
registry — the debt-slab pattern, never the decide path): the tracker
keeps a short ring of (t, counters) snapshots and differences the newest
against the oldest snapshot at least ``window`` old (or the oldest held,
with the actual span reported), so burn rates are windowed even though
the underlying families are cumulative.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Dict, Optional

from ratelimiter_tpu.observability import metrics as m

#: Counter families whose deltas are availability-axis BAD events, and
#: the request family giving the denominator. Both are DECISION units:
#: sheds count decisions, and SLO breaches are consumed via the
#: decision-unit twin of the per-frame breach counter (one breached
#: frame fails-open up to max_batch decisions that never reach
#: rate_limiter_requests_total — counting the frame-unit family here
#: would understate a full latency outage by a factor of the batch
#: size).
_BAD_COUNTERS = (
    "rate_limiter_server_deadline_shed_total",
    "rate_limiter_server_slo_breach_decisions_total",
)
_REQUESTS = "rate_limiter_requests_total"


class SloBurnTracker:
    """Windowed burn-rate computation over a metrics Registry.

    Args:
        registry: the registry the serving tier records into.
        objective: fraction of admissions that must be good (default
            99.9% — error budget is ``1 - objective``).
        latency_target: seconds; an admission slower than this is a
            latency-axis bad event (snapped down to a histogram bucket
            bound; the snapped value is reported).
        stage: flight-recorder stage whose histogram carries the latency
            axis (default "device" — the dispatch wait, ADR-014).
        windows: burn-rate windows in seconds (default 5m and 1h).
    """

    def __init__(self, registry: Optional[m.Registry] = None, *,
                 objective: float = 0.999, latency_target: float = 0.025,
                 stage: str = "device",
                 windows: tuple = (300.0, 3600.0)):
        if not 0.0 < objective < 1.0:
            raise ValueError(f"objective must be in (0, 1), got {objective}")
        self.registry = registry if registry is not None else m.DEFAULT
        self.objective = float(objective)
        self.latency_target = float(latency_target)
        self.stage = stage
        self.windows = tuple(float(w) for w in windows)
        self._lock = threading.Lock()
        #: ring of (t_monotonic, spans_total, spans_slow, dec_total,
        #: dec_bad, effective_target). Sized from the windows: at the
        #: 0.5 s dedup floor the ring must hold 2x the LONGEST window
        #: of samples, or sub-second polling would evict the slow
        #: window's base and the "1 h" burn rate would silently
        #: evaluate a shorter span (~56 B/slot — ~800 KiB for the
        #: default 1 h window).
        self._samples: deque = deque(
            maxlen=int(2 * max(self.windows) / 0.5) + 16)
        self._attached = False

    # ------------------------------------------------------- counting

    def _counts(self) -> tuple:
        """One consistent-enough read of the cumulative families (each
        family is internally locked; cross-family skew is bounded by
        scrape concurrency and washes out in windowed deltas)."""
        spans_total = spans_slow = 0
        eff = self.latency_target
        hist = self.registry.get("rate_limiter_stage_seconds")
        if isinstance(hist, m.Histogram):
            spans_total, spans_slow, eff = hist.counts_over(
                self.latency_target, stage=self.stage)
        if spans_total == 0:
            # Flight recorder off (or no traffic yet): fall back to the
            # always-on dispatch histogram — whole-dispatch wall time.
            hist = self.registry.get("rate_limiter_server_dispatch_seconds")
            if isinstance(hist, m.Histogram):
                spans_total, spans_slow, eff = hist.counts_over(
                    self.latency_target)
        dec_total = dec_bad = 0.0
        req = self.registry.get(_REQUESTS)
        if isinstance(req, m.Counter):
            dec_total += req.total()
            dec_bad += req.total(result="fail_open")
            # error:<kind> results — enumerate label sets once.
            for key, v in req.labeled_values():
                if any(k == "result" and str(val).startswith("error:")
                       for k, val in key):
                    dec_bad += v
        for name in _BAD_COUNTERS:
            c = self.registry.get(name)
            if isinstance(c, m.Counter):
                bad = c.total()
                # Shed/breached decisions never reach the limiter (and
                # so never land in requests_total) — they join the
                # denominator here as well as the numerator.
                dec_total += bad
                dec_bad += bad
        return spans_total, spans_slow, dec_total, dec_bad, eff

    def sample(self) -> None:
        """Append one snapshot (idempotent at sub-second cadence: a
        hammered /healthz cannot flood the ring)."""
        now = time.monotonic()
        with self._lock:
            if self._samples and now - self._samples[-1][0] < 0.5:
                return
            st, ss, dt, db, eff = self._counts()
            self._samples.append((now, st, ss, dt, db, eff))
            horizon = now - 2 * max(self.windows)
            while len(self._samples) > 2 and self._samples[0][0] < horizon:
                self._samples.popleft()

    # --------------------------------------------------------- status

    @staticmethod
    def _frac(bad: float, total: float) -> float:
        return bad / total if total > 0 else 0.0

    def status(self) -> dict:
        """The /healthz ``slo`` block: per-window burn rates + the raw
        axis fractions. Always samples first, so a bare /healthz poll
        (no scraper running) still gets current numbers."""
        self.sample()
        budget = 1.0 - self.objective
        with self._lock:
            samples = list(self._samples)
        if not samples:
            return {"objective": self.objective, "windows": {}}
        newest = samples[-1]
        out: Dict[str, dict] = {}
        for w in self.windows:
            # Oldest sample at least w old; else the oldest held (the
            # actual span is reported so a young process cannot fake a
            # calm hour).
            base = samples[0]
            for s in samples:
                if newest[0] - s[0] >= w:
                    base = s
                else:
                    break
            span = newest[0] - base[0]
            slow_frac = self._frac(newest[2] - base[2],
                                   newest[1] - base[1])
            bad_frac = self._frac(newest[4] - base[4],
                                  newest[3] - base[3])
            out[f"{int(w)}s"] = {
                "span_s": round(span, 1),
                "latency_bad_fraction": round(slow_frac, 6),
                "availability_bad_fraction": round(bad_frac, 6),
                "burn_rate": round(max(slow_frac, bad_frac) / budget, 3),
                # Raw windowed deltas behind the fractions — the
                # MERGEABLE form (ADR-021): a fleet rollup sums these
                # across members and recomputes the fractions/burn over
                # the merged counts, instead of averaging ratios (which
                # would let an idle member dilute a burning one).
                "spans": int(newest[1] - base[1]),
                "spans_slow": int(newest[2] - base[2]),
                "decisions": int(newest[3] - base[3]),
                "decisions_bad": int(newest[4] - base[4]),
            }
        return {
            "objective": self.objective,
            "error_budget": round(budget, 6),
            "latency_target_s": self.latency_target,
            "latency_target_effective_s": newest[5],
            "latency_stage": self.stage,
            "spans_observed": int(newest[1]),
            "decisions_observed": int(newest[3]),
            "windows": out,
        }

    # ----------------------------------------------------- metrics hook

    def attach(self, registry: Optional[m.Registry] = None) -> None:
        """Export burn-rate gauges at scrape time (collect-hook seam)."""
        reg = registry if registry is not None else self.registry
        g_burn = reg.gauge(
            "rate_limiter_slo_burn_rate",
            "Admission SLO error-budget burn rate (max of the latency "
            "and availability axes; 1.0 = sustainable, ADR-016)")
        g_lat = reg.gauge(
            "rate_limiter_slo_latency_bad_fraction",
            "Fraction of admission stage observations above the latency "
            "target, per burn window")
        g_avail = reg.gauge(
            "rate_limiter_slo_availability_bad_fraction",
            "Fraction of decisions shed/errored/failed-open, per burn "
            "window")

        def collect() -> None:
            st = self.status()
            for wname, row in st.get("windows", {}).items():
                g_burn.set(row["burn_rate"], window=wname)
                g_lat.set(row["latency_bad_fraction"], window=wname)
                g_avail.set(row["availability_bad_fraction"], window=wname)

        reg.add_collect_hook(collect)
        self._collect = collect
        self._collect_reg = reg
        self._attached = True

    def detach(self) -> None:
        if self._attached:
            self._collect_reg.remove_collect_hook(self._collect)
            self._attached = False
