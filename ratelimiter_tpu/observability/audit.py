"""Live accuracy observatory: shadow-oracle auditing of serving traffic.

The sketch backend is approximate BY DESIGN, and until now its quality bar
(<= 1% false-positive denies vs the exact sliding-window oracle —
BASELINE.json, ``evaluation/accuracy.py``) was measured only OFFLINE, in
bench phase B. This module closes the loop in production (ADR-016): both
front doors mirror a deterministic hash-sampled fraction of live decisions
into an exact shadow oracle (plus a collision-free CMS twin) running off
the hot path, so an operator can read the LIVE false-deny / false-allow
rate — with sample counts and Wilson confidence bounds — from /metrics,
/healthz, and ``GET /debug/audit``.

Design rules (ADR-016):

* **Hash-coherent sampling.** A key is ALWAYS or NEVER audited:
  ``splitmix64(h64) % sample == 0`` over the key's finalized routing hash.
  Per-request sampling would feed the shadow oracle fragments of each
  key's timeline and misjudge every window boundary; per-key sampling
  keeps sampled keys' windows coherent, and because both shadow legs are
  per-key exact, the sampled estimate is unbiased for the population rate
  (a cluster sample by key — the Wilson bound treats requests as
  independent, a documented approximation). The sampling hash is a
  DIFFERENT mix of the routing hash, so the audited subset stays uniform
  across mesh slices (sampling on ``h64 % sample`` would alias against
  the ``h64 % n_slices`` slice router).
* **Off the hot path.** The doors' tap is one module-global None check
  (same seam as ``tracing.RECORDER`` and the chaos injector — audit off
  is byte-identical, pinned by tests/test_audit.py) plus, when on, a
  bounded-queue append of references the door already holds. The queue
  DROPS AND COUNTS when full — auditing never applies backpressure to
  serving. All hashing, sampling, and shadow dispatches happen on the
  audit worker thread.
* **Degraded ranges are attributed, not averaged away.** Fail-open
  results (quarantined slices, breaker short-circuits, SLO breaches)
  are counted per slice as ``fail_open_samples`` and EXCLUDED from the
  accuracy rates — a fail-open allowance is not a sketch decision, and
  folding it in would let an outage launder the accuracy number.
* **One comparison engine.** The three-way core (sketch vs
  collision-free twin vs exact oracle) is ``evaluation/compare.py`` —
  the same code the offline bench runs, so the live estimate and the
  phase-B ground truth are the same measurement at two vantage points
  (``bench.py --audit`` checks they agree within the live estimate's
  confidence interval).
"""

from __future__ import annotations

import logging
import threading
from collections import deque
from typing import Dict, Optional

import numpy as np

from ratelimiter_tpu.core.config import Config
from ratelimiter_tpu.evaluation.compare import ShadowComparator, ThreeWayTally

log = logging.getLogger("ratelimiter_tpu.audit")


class ShadowAuditor:
    """Shadow-oracle auditor over a bounded tap queue.

    Args:
        config: the serving limiter's Config (limit/window/algorithm/
            sketch geometry feed the shadow legs; ``config.prefix`` is
            applied when hashing string-lane keys, matching the
            limiter's own hashing).
        sample: audit 1/``sample`` of the keyspace (hash-coherent;
            1 = audit everything, for tests and small deployments).
        n_slices: mesh slice count for per-slice attribution
            (``h64 % n_slices`` — the SlicedMeshLimiter router). 1 for
            single-device backends.
        queue_depth: max tap entries (frames, not decisions) queued for
            the worker; beyond it the tap drops and counts.
        include_twin: also run the collision-free twin (separates CMS
            error from semantic error, at ~2x shadow device work).
        twin_width: twin CMS width. The default sizes for the SAMPLED
            population: collisions among audited keys only, so it can
            stay ~64x smaller than the offline twin.
        oracle_capacity: dense oracle slots — bounds concurrently-active
            audited keys (idle slots recycle after 2 windows); overflow
            surfaces as ``oracle_errors``, never as serving failure.
        registry: attach the audit gauges to this metrics registry.
        start: spawn the worker thread (tests pass False to drive
            ``process_pending`` synchronously).
        live_config: optional zero-arg callable returning the audited
            limiter's CURRENT Config. The worker polls it per processed
            entry and re-baselines the shadow legs when limit/window
            moved (``ShadowComparator.update_policy``) — without this a
            runtime ``update_limit`` would poison the rates forever.
            Entries queued across the flip may be scored under the
            other policy (bounded by queue depth; one-window
            convergence, same class as the ADR-016 blind spots).
    """

    def __init__(self, config: Config, *, sample: int = 64,
                 n_slices: int = 1, queue_depth: int = 512,
                 include_twin: bool = True,
                 twin_width: Optional[int] = None,
                 oracle_capacity: int = 1 << 16,
                 registry=None, start: bool = True,
                 live_config=None):
        if sample < 1:
            raise ValueError(f"sample must be >= 1, got {sample}")
        self.config = config
        self.sample = int(sample)
        #: Power-of-two sample rates select on the hash's TOP bits
        #: (h64 >> shift == 0): two vector ops per frame instead of a
        #: full splitmix64 remix, still hash-coherent and independent of
        #: the low-bit slice router (h64 % n_slices). Other rates keep
        #: the remix (ADR-016 §2).
        self._sample_shift = (64 - (self.sample.bit_length() - 1)
                              if self.sample > 1
                              and self.sample & (self.sample - 1) == 0
                              else None)
        self.n_slices = max(1, int(n_slices))
        self.queue_depth = int(queue_depth)
        self._prefix = config.prefix
        if twin_width is None:
            # Collision-free over the audited subset: the sampled key
            # population is ~1/sample of the full keyspace, so the
            # offline twin's 64x-width rule shrinks by the sample rate
            # (floored so tiny geometries still get headroom).
            twin_width = max(1 << 14,
                             (config.sketch.width * 64) // self.sample)
            # Power of two (sketch geometry validation requires it).
            w = 1 << 14
            while w < twin_width:
                w <<= 1
            twin_width = w
        self._comparator = ShadowComparator(
            config, include_twin=include_twin, twin_width=twin_width,
            oracle_capacity=oracle_capacity)
        self.twin_width = twin_width
        self._live_config = live_config
        self._cur_limit = int(config.limit)
        self._cur_window = float(config.window)

        #: Tap queue: entries are (kind, data, ns, now, allowed,
        #: fail_open, fail_open_slices, slice_idx) appended by serving
        #: threads (GIL-atomic deque.append) and drained by the worker.
        self._q: deque = deque()
        self.dropped_frames = 0
        self.dropped_decisions = 0
        self.oracle_errors = 0
        #: Guards the tally + per-slice counters (written by the worker,
        #: read by status()/gauges from scrape threads). The shadow
        #: dispatches themselves run OUTSIDE this lock.
        self._status_lock = threading.Lock()
        self._per_slice: Dict[int, dict] = {}
        self.fail_open_samples = 0
        self.audited_frames = 0

        self._registries: list = []
        self._wake = threading.Event()
        self._stop = threading.Event()
        self._busy = False
        self._thread: Optional[threading.Thread] = None
        if registry is not None:
            self.attach_registry(registry)
        if start:
            self._thread = threading.Thread(target=self._run, daemon=True,
                                            name="rl-audit")
            self._thread.start()

    # ------------------------------------------------------------- tap
    #
    # Called from serving threads with AUDITOR already known non-None.
    # Hot-path cost: a len() check and a deque append of references the
    # door already holds (BatchResult arrays are fresh device fetches,
    # never mutated after resolve). NO hashing, sampling, or copying
    # here — all of that is worker-side.

    def _offer(self, kind: str, data, ns, now: float, result,
               slice_idx: int) -> None:
        if len(self._q) >= self.queue_depth:
            self.dropped_frames += 1
            try:
                self.dropped_decisions += len(result)
            except TypeError:
                self.dropped_decisions += 1
            return
        self._q.append((kind, data, ns, now, result.allowed,
                        bool(result.fail_open),
                        getattr(result, "fail_open_slices", None),
                        slice_idx))
        self._wake.set()

    def offer_hashed(self, h64, ns, now: float, result, *,
                     slice_idx: int = -1) -> None:
        """Finalized u64 hashes (the doors' string fast path and the
        C++-finalized hashed lane)."""
        self._offer("hashed", h64, ns, now, result, slice_idx)

    def offer_ids(self, ids, ns, now: float, result, *,
                  slice_idx: int = -1) -> None:
        """Raw u64 ids (the asyncio ALLOW_HASHED lane — the worker
        applies the same splitmix64 finalizer the device step does)."""
        self._offer("ids", ids, ns, now, result, slice_idx)

    def offer_keys(self, keys, ns, now: float, result, *,
                   slice_idx: int = -1) -> None:
        """String keys (slow paths); hashed worker-side with the
        limiter's prefix rule."""
        self._offer("keys", keys, ns, now, result, slice_idx)

    # ---------------------------------------------------------- worker

    def _run(self) -> None:
        while not self._stop.is_set():
            self._wake.wait(timeout=0.25)
            self._wake.clear()
            self.process_pending()

    def process_pending(self) -> int:
        """Drain and process everything queued; returns entries handled.
        Runs on the worker thread (or synchronously in tests)."""
        n = 0
        while True:
            # _busy goes up BEFORE the pop: flush() checks "queue empty
            # AND not busy", and raising the flag first closes the
            # window where the last entry has been popped (queue empty)
            # but not yet processed.
            self._busy = True
            try:
                try:
                    entry = self._q.popleft()
                except IndexError:
                    return n
                if self._live_config is not None:
                    self._follow_live_config()
                try:
                    self._process(entry)
                except Exception:  # noqa: BLE001 — auditing must never
                    # take serving down; a poisoned entry is dropped
                    # and counted like an oracle failure.
                    self.oracle_errors += 1
                    log.exception("audit entry dropped")
                n += 1
            finally:
                self._busy = False

    def _follow_live_config(self) -> None:
        """Re-baseline the shadow legs after a runtime update_limit/
        update_window on the audited backend (worker thread only)."""
        try:
            cfg = self._live_config()
            limit, window = int(cfg.limit), float(cfg.window)
        except Exception:  # noqa: BLE001 — a mid-close backend must
            # not kill the worker; the next entry retries.
            return
        if limit != self._cur_limit or window != self._cur_window:
            self._cur_limit, self._cur_window = limit, window
            self._comparator.update_policy(limit, window)

    def _finalize(self, kind: str, data) -> np.ndarray:
        from ratelimiter_tpu.ops.hashing import hash_prefixed_u64, splitmix64

        if kind == "hashed":
            return np.asarray(data, dtype=np.uint64)
        if kind == "ids":
            # The raw-id wire lane finalizes in-step (ADR-011); mirror it.
            return splitmix64(np.asarray(data, dtype=np.uint64))
        # The limiter's own prefix+hash rule (shared definition — see
        # hash_prefixed_u64), so sampled keys always match their
        # serving timeline.
        return hash_prefixed_u64(list(data), self._prefix)

    def _process(self, entry) -> None:
        from ratelimiter_tpu.ops.hashing import splitmix64

        kind, data, ns, now, allowed, fail_open, fo_slices, slice_idx = entry
        h64 = self._finalize(kind, data)
        if h64.size == 0:
            return
        if self.sample > 1:
            # Select BEFORE normalizing anything else: at 1/64 most
            # frames contribute a handful of rows (or none), and this
            # early-out is most of the worker's per-frame budget.
            if self._sample_shift is not None:
                sel = np.flatnonzero(
                    (h64 >> np.uint64(self._sample_shift)) == 0)
            else:
                sel = np.flatnonzero(
                    (splitmix64(h64) % np.uint64(self.sample)) == 0)
            if sel.size == 0:
                return
            h64 = h64[sel]
            allowed = np.atleast_1d(np.asarray(allowed, dtype=bool))[sel]
            ns_arr = (np.ones(h64.shape[0], dtype=np.int64) if ns is None
                      else np.atleast_1d(
                          np.asarray(ns, dtype=np.int64))[sel])
        else:
            allowed = np.atleast_1d(np.asarray(allowed, dtype=bool))
            ns_arr = (np.ones(h64.shape[0], dtype=np.int64) if ns is None
                      else np.atleast_1d(np.asarray(ns, dtype=np.int64)))
        slices = (np.full(h64.shape[0], int(slice_idx), dtype=np.int64)
                  if slice_idx >= 0
                  else (h64 % np.uint64(self.n_slices)).astype(np.int64))

        # Degraded-range attribution (ADR-016 §4): fail-open rows are
        # not sketch decisions — count them per slice and keep them OUT
        # of the accuracy comparison. With per-slice attribution
        # (fail_open_slices) only the named ranges are excluded; an
        # unattributed fail-open excludes the whole frame.
        fo_mask = None
        if fail_open:
            if fo_slices:
                fo_mask = np.isin(slices, np.asarray(list(fo_slices),
                                                     dtype=np.int64))
            else:
                fo_mask = np.ones(h64.shape[0], dtype=bool)
        if fo_mask is not None and fo_mask.any():
            with self._status_lock:
                self.fail_open_samples += int(fo_mask.sum())
                for s in np.unique(slices[fo_mask]):
                    d = self._slice_entry(int(s))
                    d["fail_open_samples"] += int(
                        (slices[fo_mask] == s).sum())
            keep = ~fo_mask
            if not keep.any():
                with self._status_lock:
                    self.audited_frames += 1
                return
            h64, ns_arr, allowed, slices = (h64[keep], ns_arr[keep],
                                            allowed[keep], slices[keep])

        try:
            oracle, twin = self._comparator.decide(h64, ns_arr, now)
        except Exception:  # noqa: BLE001 — shadow capacity/dispatch
            # failure: count, drop the batch, keep serving-side numbers
            # honest (the status block reports oracle_errors).
            self.oracle_errors += 1
            log.warning("audit shadow dispatch failed", exc_info=True)
            return
        fd_rows = oracle & ~allowed
        fa_rows = ~oracle & allowed
        with self._status_lock:
            self.audited_frames += 1
            self._comparator.tally.add(allowed, twin, oracle)
            for s in np.unique(slices):
                m = slices == s
                d = self._slice_entry(int(s))
                d["samples"] += int(m.sum())
                d["oracle_allows"] += int(oracle[m].sum())
                d["false_denies"] += int(fd_rows[m].sum())
                d["false_allows"] += int(fa_rows[m].sum())

    def _slice_entry(self, s: int) -> dict:
        d = self._per_slice.get(s)
        if d is None:
            d = {"samples": 0, "oracle_allows": 0, "false_denies": 0,
                 "false_allows": 0, "fail_open_samples": 0}
            self._per_slice[s] = d
        return d

    # ---------------------------------------------------------- status

    def flush(self, timeout: float = 10.0) -> bool:
        """Wait until every offered entry is processed (tests, bench,
        graceful shutdown). True if drained within the timeout."""
        import time

        deadline = time.monotonic() + timeout
        self._wake.set()
        while time.monotonic() < deadline:
            if not self._q and not self._busy:
                return True
            if self._thread is None:
                self.process_pending()
            else:
                self._wake.set()
                time.sleep(0.002)
        return not self._q and not self._busy

    def status(self) -> dict:
        """The /debug/audit JSON core: rates, Wilson bounds, sample
        counts, per-slice attribution, drop counters."""
        with self._status_lock:
            t = self._comparator.tally
            # Consistent snapshot under the lock; rates derive after.
            tally = ThreeWayTally(
                requests=t.requests, oracle_allows=t.oracle_allows,
                oracle_denies=t.oracle_denies, twin_allows=t.twin_allows,
                false_denies_vs_oracle=t.false_denies_vs_oracle,
                false_allows_vs_oracle=t.false_allows_vs_oracle,
                cms_false_denies_vs_twin=t.cms_false_denies_vs_twin,
                semantic_disagreements=t.semantic_disagreements)
            per_slice = {s: dict(d) for s, d in self._per_slice.items()}
            fail_open_samples = self.fail_open_samples
            frames = self.audited_frames
        fd_lo, fd_hi = tally.false_deny_wilson()
        fa_lo, fa_hi = tally.false_allow_wilson()
        return {
            "enabled": True,
            "sample": self.sample,
            "samples": tally.requests,
            "audited_frames": frames,
            "false_deny_rate": round(tally.false_deny_rate, 8),
            "false_deny_wilson95": [round(fd_lo, 8), round(fd_hi, 8)],
            "false_denies": tally.false_denies_vs_oracle,
            "oracle_allows": tally.oracle_allows,
            "false_allow_rate": round(tally.false_allow_rate, 10),
            "false_allow_wilson95": [round(fa_lo, 10), round(fa_hi, 10)],
            "false_allows": tally.false_allows_vs_oracle,
            "cms_false_deny_rate": round(tally.cms_false_deny_rate, 8),
            "semantic_disagreements": tally.semantic_disagreements,
            "twin": self._comparator.include_twin,
            "fail_open_samples": fail_open_samples,
            "dropped_frames": self.dropped_frames,
            # Drops happen at the tap, BEFORE worker-side sampling, so
            # dropped_decisions counts whole frame lengths; the
            # _audited_estimate divides by the sample rate into the
            # same units as ``samples`` (what the audit stream actually
            # lost).
            "dropped_decisions": self.dropped_decisions,
            "dropped_audited_estimate": self.dropped_decisions
            // self.sample,
            "oracle_errors": self.oracle_errors,
            "per_slice": {str(s): per_slice[s]
                          for s in sorted(per_slice)},
        }

    # ---------------------------------------------------- metrics hook

    def attach_registry(self, registry) -> None:
        """Scrape-time gauges (the debt-slab collect-hook pattern,
        ADR-013 — never the decide path)."""
        g_fd = registry.gauge(
            "rate_limiter_audit_false_deny_rate",
            "Live false-deny rate vs the exact shadow oracle over the "
            "hash-sampled audit stream (ADR-016)")
        g_fd_lo = registry.gauge(
            "rate_limiter_audit_false_deny_wilson_low",
            "Lower 95% Wilson bound on the live false-deny rate")
        g_fd_hi = registry.gauge(
            "rate_limiter_audit_false_deny_wilson_high",
            "Upper 95% Wilson bound on the live false-deny rate")
        g_fa = registry.gauge(
            "rate_limiter_audit_false_allow_rate",
            "Live false-allow rate vs the exact shadow oracle")
        g_n = registry.gauge(
            "rate_limiter_audit_samples",
            "Audited decisions compared against the shadow oracle")
        g_drop = registry.gauge(
            "rate_limiter_audit_dropped_decisions",
            "Decisions in frames dropped at the tap because the audit "
            "queue was full (audit never backpressures serving). "
            "PRE-sampling units — divide by the sample rate to compare "
            "against rate_limiter_audit_samples")
        g_fo = registry.gauge(
            "rate_limiter_audit_fail_open_samples",
            "Sampled decisions excluded from the accuracy rates because "
            "they were fail-open (degraded ranges are attributed, not "
            "averaged away)")
        g_sl_fd = registry.gauge(
            "rate_limiter_audit_slice_false_denies",
            "False denies attributed to one mesh slice's key range")
        g_sl_n = registry.gauge(
            "rate_limiter_audit_slice_samples",
            "Audited decisions attributed to one mesh slice's key range")

        def collect() -> None:
            st = self.status()
            g_fd.set(st["false_deny_rate"])
            g_fd_lo.set(st["false_deny_wilson95"][0])
            g_fd_hi.set(st["false_deny_wilson95"][1])
            g_fa.set(st["false_allow_rate"])
            g_n.set(float(st["samples"]))
            g_drop.set(float(st["dropped_decisions"]))
            g_fo.set(float(st["fail_open_samples"]))
            for s, d in st["per_slice"].items():
                g_sl_fd.set(float(d["false_denies"]), slice=s)
                g_sl_n.set(float(d["samples"]), slice=s)

        registry.add_collect_hook(collect)
        self._registries.append((registry, collect))

    # -------------------------------------------------------- lifecycle

    def close(self) -> None:
        self._stop.set()
        self._wake.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None
        for registry, collect in self._registries:
            registry.remove_collect_hook(collect)
        self._registries.clear()
        self._comparator.close()


#: Process-wide auditor; None = auditing off (the default). The serving
#: doors read this module global once per resolved batch and skip
#: everything when it is None — that None check IS the audit-off
#: overhead budget (byte-identical decisions, pinned by
#: tests/test_audit.py; the same seam as tracing.RECORDER and
#: chaos.INJECTOR).
AUDITOR: Optional[ShadowAuditor] = None


def enable(config: Config, **kw) -> ShadowAuditor:
    """Install (and return) the process-wide auditor. Replaces any
    previous one (which is closed)."""
    global AUDITOR
    if AUDITOR is not None:
        AUDITOR.close()
    AUDITOR = ShadowAuditor(config, **kw)
    return AUDITOR


def disable() -> None:
    """Audit off — hot path byte-identical again."""
    global AUDITOR
    if AUDITOR is not None:
        AUDITOR.close()
    AUDITOR = None


def get() -> Optional[ShadowAuditor]:
    return AUDITOR
