"""Flight-recorder tracing: per-stage spans across the whole serving path.

The reference plans an OpenTelemetry ``TracingDecorator``
(``docs/ADR/003-decorator-pattern-for-observability.md:115-124``); the
existing ``TracingDecorator`` realizes the device half of that with
``jax.profiler`` annotations, but nothing could attribute ONE frame's
latency to the pipeline stages it crossed (io → route → coalesce →
launch → device → resolve → encode, spanning C++ threads, asyncio
executors, and mesh slices — the MULTICHIP_r07 p99 investigation was
done by ad-hoc printf). This module is the missing half: a
flight-recorder of binary span records cheap enough to leave stamped on
the serving hot path.

Design (ADR-014):

* **Per-thread fixed-size ring buffers** of fixed-width records
  (trace_id, stage, shard, batch, t_start/t_end monotonic ns, outcome)
  in a numpy structured array — one row assignment per span, never a
  lock, never an allocation, never I/O on the record path. Rings are
  registered once per thread (the only locked operation) and drained
  only at dump/scrape time.
* **Off by default, zero overhead when off**: hot paths read the module
  global ``RECORDER`` once and skip everything — no clock reads, no
  branches beyond the None check, byte-identical decisions either way
  (tests/test_tracing.py pins this).
* **Trace context** is a caller-supplied u64 id (0 = unsampled). The
  binary protocol carries it as a flagged extension on any request frame
  (``protocol.with_trace``), HTTP carries W3C ``traceparent``, gRPC the
  same header as metadata, and DCN pushes ride the same frame flag
  outside the HMAC envelope, so one id survives client → server → DCN.
* **Dumps are Perfetto-loadable**: ``chrome_trace()`` renders the Chrome
  trace-event JSON Perfetto/chrome://tracing open directly; spans of one
  frame share its trace id in ``args`` and nest by containment
  (frame ⊃ slice ⊃ device), which is the span-tree oracle the tests
  walk.
* **Histograms ride the scrape**: ``attach_registry`` installs a
  collect hook deriving ``rate_limiter_stage_seconds{stage=...}`` from
  the rings at scrape time (the same seam as the debt-slab gauges) with
  OpenMetrics exemplars tying buckets to the trace ids that landed in
  them.
"""

from __future__ import annotations

import collections
import os
import threading
import time
from typing import Dict, List, Optional

import numpy as np

#: Stage vocabulary (u8 codes in the record). Both doors + the mesh
#: composite use these names; unknown names are rejected loudly so
#: dumps stay joinable across versions.
STAGES = (
    "io",         # wire frame parse + enqueue (reader loop / C++ io thread)
    "route",      # shard/slice partition of a frame
    "queue",      # waiting in the pending queue for the next dispatch
    "coalesce",   # coalescing-window residency (first pending -> flush)
    "launch",     # stage + enqueue the jitted step (non-blocking)
    "dispatch",   # native door: drain -> launch callback returned
    "device",     # block on the device for the oldest in-flight dispatch
    "barrier",    # mesh frame: the single completion barrier (ADR-013)
    "slice",      # mesh frame: one slice's sub-dispatch resolve
    "resolve",    # host bookkeeping after the device fetch
    "complete",   # native door: completer post-processing
    "encode",     # response framing
    "respond",    # native door: responder encode+send (aggregate only)
    "http",       # HTTP gateway decision (traceparent attribution)
    "grpc",       # gRPC decision (traceparent metadata attribution)
    "dcn",        # one DCN push round-trip to a peer
    "client",     # client-side request span (loadgen sampling)
    "forward",    # fleet forward lane: one coalesced wire window's
                  # round trip to a peer (send -> parsed reply), recorded
                  # under the WINDOW-level trace id (ADR-021)
)
_STAGE_CODE: Dict[str, int] = {s: i for i, s in enumerate(STAGES)}

#: Outcome codes.
OK, ERROR, FAIL_OPEN = 0, 1, 2

#: One span record: 32 bytes, fixed width — the ring is a plain numpy
#: structured array so a record is ONE row assignment.
RECORD_DTYPE = np.dtype([
    ("trace_id", "<u8"),
    ("t_start", "<u8"),
    ("t_end", "<u8"),
    ("batch", "<u4"),
    ("shard", "<i2"),
    ("stage", "u1"),
    ("outcome", "u1"),
])


def now() -> int:
    """Monotonic nanoseconds — the span clock. Same CLOCK_MONOTONIC
    domain as the native door's ``steady_clock`` stamps, so C++ and
    Python spans interleave on one timeline."""
    return time.monotonic_ns()


def new_trace_id() -> int:
    """Fresh nonzero sampling id (64-bit; 0 means 'unsampled')."""
    import secrets

    return secrets.randbits(64) | 1


def parse_traceparent(header: Optional[str]) -> int:
    """W3C ``traceparent`` -> u64 trace id (low 8 bytes of the 16-byte
    trace-id field), 0 for absent/malformed headers. Lenient on
    version/flags — attribution must never reject a request."""
    if not header:
        return 0
    parts = header.strip().split("-")
    if len(parts) < 3 or len(parts[1]) != 32:
        return 0
    try:
        return int(parts[1][16:], 16)
    except ValueError:
        return 0


def format_traceparent(trace_id: int) -> str:
    """u64 trace id -> a valid ``traceparent`` header value."""
    return f"00-{trace_id & ((1 << 64) - 1):032x}-{trace_id & ((1 << 64) - 1) or 1:016x}-01"


class _Ring:
    """One thread's span ring. Only its owning thread writes; readers
    take racy-but-consistent numpy copies (each row is written once and
    ``idx`` is published after the row — a torn read can at worst see a
    half-written CURRENT row, which drains skip via t_end==0)."""

    __slots__ = ("buf", "idx", "tid", "name")

    def __init__(self, capacity: int):
        self.buf = np.zeros(capacity, dtype=RECORD_DTYPE)
        self.idx = 0  # total records ever written (monotone)
        self.tid = threading.get_ident()
        self.name = threading.current_thread().name


class FlightRecorder:
    """Process-wide span recorder over per-thread rings."""

    def __init__(self, capacity: int = 8192):
        if capacity < 16:
            raise ValueError(f"capacity must be >= 16, got {capacity}")
        # Round up to a power of two so the ring index is a mask.
        cap = 1
        while cap < capacity:
            cap <<= 1
        self.capacity = cap
        self._mask = cap - 1
        self._local = threading.local()
        self._rings: List[_Ring] = []
        self._rings_lock = threading.Lock()
        self._registries: list = []
        #: Parent-child trace-id links (ADR-021): a fleet forward lane
        #: re-frames member fragments under one WINDOW-level trace id
        #: and records (client frame id -> window id) here, so the
        #: cross-host stitcher can join the receiving member's
        #: window-id spans back to the client frame. Bounded; links are
        #: per-window (rare next to spans), appended under a lock.
        self._links: collections.deque = collections.deque(
            maxlen=max(1024, cap))
        self._links_lock = threading.Lock()

    # ------------------------------------------------------------ record

    def _ring(self) -> _Ring:
        ring = getattr(self._local, "ring", None)
        if ring is None:
            ring = _Ring(self.capacity)
            self._local.ring = ring
            with self._rings_lock:
                self._rings.append(ring)
        return ring

    def record(self, stage, t_start: int, t_end: int, *, trace_id: int = 0,
               shard: int = -1, batch: int = 1, outcome: int = OK) -> None:
        """Stamp one span. Hot-path cost: a thread-local lookup and one
        structured-row assignment (no locks, no allocation)."""
        ring = self._ring()
        i = ring.idx & self._mask
        ring.buf[i] = (trace_id & 0xFFFFFFFFFFFFFFFF, t_start, t_end,
                       batch & 0xFFFFFFFF, shard,
                       stage if isinstance(stage, int)
                       else _STAGE_CODE[stage], outcome)
        ring.idx += 1

    def link(self, parent_id: int, child_id: int) -> None:
        """Record a parent->child trace-id relation (the fleet forward
        lane's fragment -> wire-window linkage, ADR-021). Not a
        hot-path call: one link per coalesced wire window."""
        if not parent_id or not child_id or parent_id == child_id:
            return
        with self._links_lock:
            self._links.append((parent_id & 0xFFFFFFFFFFFFFFFF,
                                child_id & 0xFFFFFFFFFFFFFFFF, now()))

    def links(self) -> List[dict]:
        """Recorded trace-id links as dicts (ids in the 16-hex trace-id
        rendering)."""
        with self._links_lock:
            snap = list(self._links)
        return [{"parent": f"{p:016x}", "child": f"{c:016x}",
                 "t_ns": t} for p, c, t in snap]

    # ------------------------------------------------------------- drain

    def _snapshot(self):
        """[(ring, entries-copy oldest-first, first_seq)] without
        stopping writers (copies are taken per ring)."""
        with self._rings_lock:
            rings = list(self._rings)
        out = []
        for ring in rings:
            idx = ring.idx
            n = min(idx, self.capacity)
            if n == 0:
                continue
            lo = idx & self._mask
            if idx <= self.capacity:
                ent = ring.buf[:n].copy()
            else:
                ent = np.concatenate([ring.buf[lo:], ring.buf[:lo]])
            out.append((ring, ent, idx - n))
        return out

    def dump(self) -> List[dict]:
        """Recent spans (up to capacity per thread) as dicts, sorted by
        t_start. Drain-time work only — never on the record path."""
        spans: List[dict] = []
        for ring, ent, _ in self._snapshot():
            keep = ent[ent["t_end"] != 0]
            for row in keep:
                spans.append({
                    "trace_id": int(row["trace_id"]),
                    "stage": STAGES[int(row["stage"])],
                    "shard": int(row["shard"]),
                    "batch": int(row["batch"]),
                    "t_start_ns": int(row["t_start"]),
                    "t_end_ns": int(row["t_end"]),
                    "outcome": int(row["outcome"]),
                    "thread": ring.name,
                })
        spans.sort(key=lambda s: s["t_start_ns"])
        return spans

    def chrome_trace(self) -> dict:
        """Chrome trace-event JSON (Perfetto / chrome://tracing load it
        directly): one complete ("X") event per span, microsecond
        timestamps, trace id / shard / batch / outcome in args."""
        pid = os.getpid()
        events = []
        for ring, ent, _ in self._snapshot():
            keep = ent[ent["t_end"] != 0]
            for row in keep:
                t0 = int(row["t_start"])
                events.append({
                    "name": STAGES[int(row["stage"])],
                    "cat": "ratelimiter",
                    "ph": "X",
                    "ts": t0 / 1e3,
                    "dur": max(int(row["t_end"]) - t0, 0) / 1e3,
                    "pid": pid,
                    "tid": ring.tid,
                    "args": {
                        "trace_id": f"{int(row['trace_id']):016x}",
                        "shard": int(row["shard"]),
                        "batch": int(row["batch"]),
                        "outcome": int(row["outcome"]),
                    },
                })
        events.sort(key=lambda e: e["ts"])
        return {
            "traceEvents": events,
            "displayTimeUnit": "ms",
            "otherData": {"clock": "CLOCK_MONOTONIC",
                          "threads": {str(r.tid): r.name
                                      for r in list(self._rings)},
                          # Fragment -> wire-window linkage plus a
                          # (mono, wall) clock stamp, so an offline
                          # stitcher can join and align dumps pulled
                          # from several hosts (fleet/tower.py).
                          "links": self.links(),
                          "mono_ns": now(),
                          "wall_s": time.time()},
        }

    def stage_summary(self) -> Dict[str, dict]:
        """{stage: {count, total_us, mean_us, p99_us}} over the rings —
        the bench's ``--trace`` breakdown block derives from this."""
        per: Dict[str, list] = {}
        for _, ent, _ in self._snapshot():
            keep = ent[ent["t_end"] != 0]
            for code in np.unique(keep["stage"]):
                rows = keep[keep["stage"] == code]
                per.setdefault(STAGES[int(code)], []).append(
                    (rows["t_end"] - rows["t_start"]).astype(np.int64))
        out: Dict[str, dict] = {}
        for stage, chunks in per.items():
            ns = np.concatenate(chunks)
            out[stage] = {
                "count": int(ns.size),
                "total_us": round(float(ns.sum()) / 1e3, 1),
                "mean_us": round(float(ns.mean()) / 1e3, 1),
                "p99_us": round(float(np.percentile(ns, 99)) / 1e3, 1),
            }
        return out

    # --------------------------------------------- scrape-time histograms

    def attach_registry(self, registry) -> None:
        """Derive ``rate_limiter_stage_seconds{stage=...}`` from the
        rings via the registry's scrape-time collect-hook seam (the same
        mechanism as the debt-slab gauges, ADR-013): spans recorded since
        the previous scrape are observed into the histogram — WITH an
        OpenMetrics exemplar carrying the span's trace id — once per
        scrape, never on the decide path."""
        hist = registry.histogram(
            "rate_limiter_stage_seconds",
            "Per-stage serving latency derived from the flight recorder "
            "(ADR-014); buckets carry trace-id exemplars in the "
            "OpenMetrics rendering")
        cursors: Dict[int, int] = {}

        def collect() -> None:
            for ring, ent, first_seq in self._snapshot():
                seen = cursors.get(id(ring), 0)
                start = max(seen, first_seq)
                fresh = ent[start - first_seq:]
                fresh = fresh[fresh["t_end"] != 0]
                for row in fresh:
                    dt = max(int(row["t_end"]) - int(row["t_start"]), 0) / 1e9
                    tid = int(row["trace_id"])
                    hist.observe(
                        dt,
                        exemplar=(f"{tid:016x}" if tid else None),
                        stage=STAGES[int(row["stage"])])
                cursors[id(ring)] = first_seq + len(ent)

        registry.add_collect_hook(collect)
        self._registries.append((registry, collect))

    def detach(self) -> None:
        for registry, collect in self._registries:
            registry.remove_collect_hook(collect)
        self._registries.clear()


#: Process-wide recorder; None = tracing off (the default). Hot paths
#: read this module global once per operation and skip everything when
#: it is None — that None check IS the documented overhead budget.
RECORDER: Optional[FlightRecorder] = None


def enable(capacity: int = 8192, registry=None) -> FlightRecorder:
    """Turn the flight recorder on (idempotent); optionally attach the
    scrape-time stage histograms to ``registry``."""
    global RECORDER
    if RECORDER is None:
        RECORDER = FlightRecorder(capacity)
    if registry is not None:
        RECORDER.attach_registry(registry)
    return RECORDER


def disable() -> None:
    """Turn tracing off and unhook any scrape-time collectors."""
    global RECORDER
    if RECORDER is not None:
        RECORDER.detach()
    RECORDER = None


def get() -> Optional[FlightRecorder]:
    return RECORDER


def record(stage, t_start: int, t_end: int, **kw) -> None:
    """Convenience guarded record (hot paths inline the None check and
    call ``RECORDER.record`` directly instead)."""
    rec = RECORDER
    if rec is not None:
        rec.record(stage, t_start, t_end, **kw)


# ----------------------------------------------- current-trace context
#
# A thread-local "trace id of the work currently being launched": the
# micro-batcher sets it (recorder-on only) around the limiter launch
# call, so layers WITHOUT a trace-id parameter in their signature — the
# fleet forwarder splitting a frame onto peer lanes is the one that
# matters (ADR-021) — can attribute the rows they ship. For a coalesced
# window the id is the window's representative (first sampled frame),
# the same id its coalesce/launch/device spans carry.

_CTX = threading.local()


def set_current(trace_id: int) -> None:
    _CTX.trace_id = trace_id


def current() -> int:
    """Trace id of the frame/window being launched on this thread
    (0 = none/unsampled)."""
    return getattr(_CTX, "trace_id", 0)
