"""Observability: metrics registry + decorator wrappers (reference L4,
``docs/ADR/003-decorator-pattern-for-observability.md``) + the
flight-recorder tracing subsystem (ADR-014, ``tracing.py``) + the live
accuracy observatory (ADR-016, ``audit.py``/``slo.py``) + the
control-plane event journal (ADR-021, ``events.py``)."""

from ratelimiter_tpu.observability import audit, events, slo, tracing
from ratelimiter_tpu.observability.metrics import (
    BATCH_BUCKETS,
    Counter,
    DEFAULT,
    Gauge,
    Histogram,
    LATENCY_BUCKETS,
    Registry,
)
from ratelimiter_tpu.observability.decorators import (
    CircuitBreakerDecorator,
    LimiterDecorator,
    LoggingDecorator,
    MetricsDecorator,
    TracingDecorator,
)
from ratelimiter_tpu.observability.tracing import FlightRecorder

__all__ = [
    "BATCH_BUCKETS",
    "CircuitBreakerDecorator",
    "Counter",
    "DEFAULT",
    "FlightRecorder",
    "Gauge",
    "Histogram",
    "LATENCY_BUCKETS",
    "LimiterDecorator",
    "LoggingDecorator",
    "MetricsDecorator",
    "Registry",
    "TracingDecorator",
    "audit",
    "events",
    "slo",
    "tracing",
]
