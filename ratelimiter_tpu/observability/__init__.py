"""Observability: metrics registry + decorator wrappers (reference L4,
``docs/ADR/003-decorator-pattern-for-observability.md``)."""

from ratelimiter_tpu.observability.metrics import (
    BATCH_BUCKETS,
    Counter,
    DEFAULT,
    Gauge,
    Histogram,
    LATENCY_BUCKETS,
    Registry,
)
from ratelimiter_tpu.observability.decorators import (
    CircuitBreakerDecorator,
    LimiterDecorator,
    LoggingDecorator,
    MetricsDecorator,
    TracingDecorator,
)

__all__ = [
    "BATCH_BUCKETS",
    "CircuitBreakerDecorator",
    "Counter",
    "DEFAULT",
    "Gauge",
    "Histogram",
    "LATENCY_BUCKETS",
    "LimiterDecorator",
    "LoggingDecorator",
    "MetricsDecorator",
    "Registry",
    "TracingDecorator",
]
