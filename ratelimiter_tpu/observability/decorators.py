"""Observability decorators around RateLimiter.

The reference's L4 layer, designed but unbuilt
(``docs/ADR/003-decorator-pattern-for-observability.md:44-125``,
``docs/ARCHITECTURE.md:269-285``): wrappers that implement the same
RateLimiter surface, so they compose with each other and with any backend
— ``MetricsDecorator(LoggingDecorator(create_limiter(cfg, "sketch")))`` —
and pass the full contract suite (tests/test_decorators.py instantiates
it for a decorated limiter).

Metric names follow the reference's spec (``docs/ARCHITECTURE.md:550-566``):

* ``rate_limiter_requests_total{algorithm,result}`` — result is allowed /
  denied / fail_open / error:<kind>; counts *requests* (allow_n(n) is one).
* ``rate_limiter_decisions_allowed_total`` / ``_denied_total`` — device-side
  per-decision counters, one reduction over the batch mask (free on TPU).
* ``rate_limiter_latency_seconds{algorithm,op}`` — wall time of the inner
  call (the batched dispatch for allow_batch).
* ``rate_limiter_batch_size`` — histogram of decisions per inner dispatch.
* ``rate_limiter_storage_errors_total{algorithm}`` — backend failures,
  whether surfaced as fail-open or raised (analog of
  ``rate_limiter_redis_errors_total``).
"""

from __future__ import annotations

import logging
import threading
import time
from contextlib import contextmanager
from typing import Optional, Sequence

import numpy as np

from ratelimiter_tpu.algorithms.base import RateLimiter
from ratelimiter_tpu.core.errors import (
    ClosedError,
    InvalidKeyError,
    InvalidNError,
    StorageUnavailableError,
)
from ratelimiter_tpu.core.types import BatchResult, Result
from ratelimiter_tpu.observability import metrics as m


class LimiterDecorator(RateLimiter):
    """Base decorator: delegates the whole RateLimiter surface to ``inner``.

    Validation, clocking, and locking all live in the inner limiter; the
    decorator only observes. Subclasses override the ``_observe_*`` hooks.
    """

    def __init__(self, inner: RateLimiter):
        # Deliberately NOT calling RateLimiter.__init__: config is already
        # validated by (and owned by) the inner limiter; re-validating here
        # would double any validation side effects.
        self.inner = inner
        self._closed = False

    # Delegated attributes ------------------------------------------------

    @property
    def config(self):  # type: ignore[override]
        return self.inner.config

    @property
    def clock(self):  # type: ignore[override]
        return self.inner.clock

    # Public surface (decorated) ------------------------------------------

    def allow(self, key: str, *, now: Optional[float] = None) -> Result:
        return self.allow_n(key, 1, now=now)

    def allow_n(self, key: str, n: int, *, now: Optional[float] = None) -> Result:
        t0 = time.perf_counter()
        try:
            res = self.inner.allow_n(key, n, now=now)
        except Exception as exc:
            self._observe_error("allow_n", exc, time.perf_counter() - t0)
            raise
        self._observe_result("allow_n", res, n, time.perf_counter() - t0)
        return res

    def allow_batch(self, keys: Sequence[str], ns=None, *,
                    now: Optional[float] = None) -> BatchResult:
        t0 = time.perf_counter()
        try:
            out = self.inner.allow_batch(keys, ns, now=now)
        except Exception as exc:
            self._observe_error("allow_batch", exc, time.perf_counter() - t0)
            raise
        self._observe_batch("allow_batch", out, ns, time.perf_counter() - t0)
        return out

    def reset(self, key: str) -> None:
        t0 = time.perf_counter()
        try:
            self.inner.reset(key)
        except Exception as exc:
            self._observe_error("reset", exc, time.perf_counter() - t0)
            raise
        self._observe_op("reset", time.perf_counter() - t0)

    # Pipelined dispatch (ADR-010): launch passes through unobserved (it
    # only enqueues); the batch is observed ONCE, at resolve, where the
    # decisions actually exist. Explicit delegation is required — the
    # base class defines launch_batch/resolve, so __getattr__ would never
    # fire and the decorator would run the base eager fallback instead of
    # the backend's real pipelined path.

    @property
    def pipelined(self):  # type: ignore[override]
        return getattr(self.inner, "pipelined", False)

    def launch_batch(self, keys: Sequence[str], ns=None, *,
                     now: Optional[float] = None):
        return self.inner.launch_batch(keys, ns, now=now)

    def resolve(self, ticket):
        t0 = time.perf_counter()
        try:
            out = self.inner.resolve(ticket)
        except Exception as exc:
            self._observe_error("resolve", exc, time.perf_counter() - t0)
            raise
        self._observe_batch("resolve", out, None, time.perf_counter() - t0)
        return out

    # Hashed / raw-id lane (ADR-011): explicit delegation for the same
    # reason as launch_batch/resolve — subclasses (the breaker) must be
    # able to interpose, and the synchronous forms must be observed.
    # The serving doors detect lane SUPPORT on the undecorated backend
    # (hasattr on the decorator would now always be true), so these
    # definitions never advertise a lane the inner limiter lacks.

    def allow_hashed(self, h64, ns=None, *, now: Optional[float] = None):
        t0 = time.perf_counter()
        try:
            out = self.inner.allow_hashed(h64, ns, now=now)
        except Exception as exc:
            self._observe_error("allow_hashed", exc,
                                time.perf_counter() - t0)
            raise
        self._observe_batch("allow_hashed", out, ns,
                            time.perf_counter() - t0)
        return out

    def allow_ids(self, ids, ns=None, *, now: Optional[float] = None):
        t0 = time.perf_counter()
        try:
            out = self.inner.allow_ids(ids, ns, now=now)
        except Exception as exc:
            self._observe_error("allow_ids", exc, time.perf_counter() - t0)
            raise
        self._observe_batch("allow_ids", out, ns, time.perf_counter() - t0)
        return out

    def launch_hashed(self, h64, ns=None, *, now: Optional[float] = None):
        return self.inner.launch_hashed(h64, ns, now=now)

    def launch_ids(self, ids, ns=None, *, now: Optional[float] = None,
                   wire: bool = False):
        return self.inner.launch_ids(ids, ns, now=now, wire=wire)

    def close(self) -> None:
        self._closed = True
        self.inner.close()

    def update_limit(self, new_limit: int) -> None:
        # Delegate wholesale (config lives on the inner limiter; the
        # decorator's config property reflects it automatically).
        self.inner.update_limit(new_limit)

    def update_window(self, new_window: float) -> None:
        # Same: the base implementation would run against the decorator
        # and try to assign its read-only config property.
        self.inner.update_window(new_window)

    def capture_state(self):
        # Explicit (base defines it, so __getattr__ never fires): the
        # durability subsystem snapshots the BACKEND's state.
        return self.inner.capture_state()

    def save(self, path: str) -> None:
        self.inner.save(path)

    # Policy overrides: delegate wholesale rather than running the base
    # implementation against a delegated ``_policy_table`` — backends
    # that OVERRIDE the policy surface instead of owning a table (the
    # sliced mesh limiter fans every mutation out to its device slices,
    # ADR-012) must keep their semantics under any decorator stack.

    def set_override(self, key: str, limit: Optional[int] = None, *,
                     window_scale: float = 1.0):
        return self.inner.set_override(key, limit,
                                       window_scale=window_scale)

    def get_override(self, key: str):
        return self.inner.get_override(key)

    def delete_override(self, key: str) -> bool:
        return self.inner.delete_override(key)

    def list_overrides(self):
        return self.inner.list_overrides()

    def override_count(self) -> int:
        return self.inner.override_count()

    def sub_limiters(self):
        # The dispatch units live on the backend (a composite returns
        # its slices); the base impl would wrongly answer [decorator].
        return self.inner.sub_limiters()

    # Hierarchy surface (ADR-020): same explicit-delegation rule as the
    # policy surface — the base class defines these, so __getattr__
    # never fires, and the sliced mesh OVERRIDES them with write-all
    # semantics that must survive any decorator stack.

    def set_tenant(self, name: str, limit: Optional[int] = None, *,
                   weight: int = 1, floor: Optional[int] = None):
        return self.inner.set_tenant(name, limit, weight=weight,
                                     floor=floor)

    def delete_tenant(self, name: str) -> bool:
        return self.inner.delete_tenant(name)

    def assign_tenant(self, key: str, tenant: str) -> None:
        return self.inner.assign_tenant(key, tenant)

    def unassign_tenant(self, key: str) -> bool:
        return self.inner.unassign_tenant(key)

    def tenant_of(self, key: str) -> str:
        return self.inner.tenant_of(key)

    def get_tenant(self, name: str):
        return self.inner.get_tenant(name)

    def list_tenants(self):
        return self.inner.list_tenants()

    def set_global_limit(self, limit) -> None:
        return self.inner.set_global_limit(limit)

    def set_effective(self, scope: str, limit: int) -> int:
        return self.inner.set_effective(scope, limit)

    def effective_limits(self):
        return self.inner.effective_limits()

    def hierarchy_payload(self) -> dict:
        return self.inner.hierarchy_payload()

    def apply_hierarchy_payload(self, payload: dict) -> bool:
        return self.inner.apply_hierarchy_payload(payload)

    def hierarchy_stats(self) -> dict:
        return self.inner.hierarchy_stats()

    # Pass-through for backend extras (allow_hashed, inject_failure, ...) --

    def __getattr__(self, name: str):
        return getattr(self.inner, name)

    # Hooks ----------------------------------------------------------------

    def _observe_result(self, op: str, res: Result, n: int, dt: float) -> None:
        pass

    def _observe_batch(self, op: str, out: BatchResult, ns, dt: float) -> None:
        pass

    def _observe_op(self, op: str, dt: float) -> None:
        pass

    def _observe_error(self, op: str, exc: Exception, dt: float) -> None:
        pass

    # The abstract hooks are never reached (public surface is overridden),
    # but the ABC requires concrete definitions.

    def _allow_n(self, key: str, n: int, now: float) -> Result:  # pragma: no cover
        raise AssertionError("decorator delegates the public surface")

    def _reset(self, key: str) -> None:  # pragma: no cover
        raise AssertionError("decorator delegates the public surface")


def undecorated(limiter: RateLimiter) -> RateLimiter:
    """Peel the decorator stack down to the backend limiter (the object
    owning ``_state``/``_lock``, which checkpoint and DCN code needs)."""
    while isinstance(limiter, LimiterDecorator):
        limiter = limiter.inner
    return limiter


def _error_kind(exc: Exception) -> str:
    if isinstance(exc, StorageUnavailableError):
        return "storage_unavailable"
    if isinstance(exc, InvalidNError):
        return "invalid_n"
    if isinstance(exc, InvalidKeyError):
        return "invalid_key"
    if isinstance(exc, ClosedError):
        return "closed"
    return "internal"


class MetricsDecorator(LimiterDecorator):
    """Records the reference-specced metric families into a Registry
    (``docs/ADR/003:44-66``; names ``docs/ARCHITECTURE.md:550-566``)."""

    def __init__(self, inner: RateLimiter, registry: Optional[m.Registry] = None,
                 shard: str = "0"):
        super().__init__(inner)
        reg = registry if registry is not None else m.DEFAULT
        self.registry = reg
        #: Envelope-gauge label: with dispatch shards each shard's
        #: decorator must write its OWN series — a shared unlabeled gauge
        #: would be overwritten by whichever shard observed last, masking
        #: an overloaded shard behind a healthy one.
        self._shard = str(shard)
        self._algo = str(inner.config.algorithm)
        self._requests = reg.counter(
            "rate_limiter_requests_total",
            "Rate limit checks by algorithm and result")
        self._allowed = reg.counter(
            "rate_limiter_decisions_allowed_total",
            "Individual decisions allowed (device-side mask sum)")
        self._denied = reg.counter(
            "rate_limiter_decisions_denied_total",
            "Individual decisions denied (device-side mask sum)")
        self._latency = reg.histogram(
            "rate_limiter_latency_seconds",
            "Inner limiter call latency", m.LATENCY_BUCKETS)
        self._batch = reg.histogram(
            "rate_limiter_batch_size",
            "Decisions per batched dispatch", m.BATCH_BUCKETS)
        self._errors = reg.counter(
            "rate_limiter_storage_errors_total",
            "Backend failures (fail-open allowances included)")
        # Accuracy-envelope surface (windowed sketch only): exported so a
        # mis-sized geometry shows up on /metrics, not just in a log line
        # (SURVEY.md §7.4 hard part 3; docs/OPERATIONS.md §3).
        base = undecorated(inner)
        self._sketch = base if hasattr(base, "_period_mass") else None
        if self._sketch is not None:
            self._overload_g = reg.gauge(
                "rate_limiter_sketch_overload_periods",
                "Sub-windows whose admitted mass exceeded the geometry's "
                "accuracy budget (growing value = undersized sketch)")
            self._mass_g = reg.gauge(
                "rate_limiter_sketch_in_window_admitted_mass",
                "Admitted requests currently inside the sliding window")
            self._budget_g = reg.gauge(
                "rate_limiter_sketch_mass_budget",
                "Admitted-mass level where collision error reaches ~1% "
                "false denies for this geometry")
            self._budget_g.set(float(base.mass_budget), shard=self._shard)
        # Debt-slab surface (token-bucket sketch only): the continuous-
        # decay mirror of the mass watchdog (ROADMAP item 5 — strict
        # gating doesn't transfer, visibility does). Reading it costs a
        # device fetch under the backend lock, so the gauges refresh via
        # a scrape-time collect hook, never per decision. A sliced mesh
        # expands to its per-device slices, one series each.
        # Top-K consumer surface (heavy-hitter side table, ADR-016 §5):
        # promoted hot keys' exact in-window counts exported as ranked
        # gauges — refreshed by the same scrape-time collect-hook seam
        # as the debt slab (a K-slot device fetch per unit per scrape,
        # never the decide path). Consumer identity goes to /healthz
        # and /debug/audit as hash tokens; the gauge keys by RANK so
        # label cardinality stays bounded.
        self._hh_units = [
            (i, sl) for i, sl in enumerate(base.sub_limiters())
            if getattr(sl, "has_hh", False)]
        if self._hh_units:
            self._hh_top_g = reg.gauge(
                "rate_limiter_top_consumer_mass",
                "In-window admitted mass of the rank-N hottest tracked "
                "consumer (heavy-hitter side table; identities on "
                "/debug/audit)")
            self._hh_occ_g = reg.gauge(
                "rate_limiter_hh_tracked_consumers",
                "Occupied heavy-hitter slots (promoted hot keys "
                "currently tracked exactly)")
            reg.add_collect_hook(self._collect_consumers)
        self._debt_slabs = [
            (i, sl) for i, sl in enumerate(base.sub_limiters())
            if hasattr(sl, "debt_slab_stats")]
        if self._debt_slabs:
            self._debt_occ_g = reg.gauge(
                "rate_limiter_debt_slab_occupancy",
                "Max per-row fraction of debt cells with positive "
                "effective debt (colliding active keys share refill; "
                "hot rows throttle hot keys toward combined throughput)")
            self._debt_coll_g = reg.gauge(
                "rate_limiter_debt_slab_collision_probability",
                "Chance a fresh key reads an overestimated debt (an "
                "occupied cell in every sketch row) — errors are toward "
                "denying")
            reg.add_collect_hook(self._collect_debt_slab)

    def _collect_debt_slab(self) -> None:
        for i, sl in self._debt_slabs:
            st = sl.debt_slab_stats()
            self._debt_occ_g.set(st["occupancy"],
                                 shard=self._shard, slice=str(i))
            self._debt_coll_g.set(st["collision_p"],
                                  shard=self._shard, slice=str(i))

    def _collect_consumers(self) -> None:
        for i, sl in self._hh_units:
            st = sl.consumer_stats(k=5)
            self._hh_occ_g.set(float(st["occupied"]),
                               shard=self._shard, slice=str(i))
            top = st["top"]
            # Every rank 1..5 is written each scrape: when the list
            # SHRINKS (a hot key's window rolled off), the vacated
            # ranks must drop to 0 — a gauge only overwrites label
            # sets it is told to, so skipping them would leave phantom
            # heavy hitters frozen at their last mass forever.
            for rank in range(1, 6):
                mass = (float(top[rank - 1]["in_window"])
                        if rank <= len(top) else 0.0)
                self._hh_top_g.set(mass, shard=self._shard,
                                   slice=str(i), rank=str(rank))

    def close(self) -> None:
        # Unhook BEFORE closing: on the process-default registry a
        # leftover collect hook would pin this decorator (and the closed
        # backend's device arrays) forever and poke it on every scrape.
        if self._debt_slabs:
            self.registry.remove_collect_hook(self._collect_debt_slab)
        if self._hh_units:
            self.registry.remove_collect_hook(self._collect_consumers)
        super().close()

    def _observe_envelope(self) -> None:
        if self._sketch is not None:
            self._overload_g.set(float(self._sketch.overload_periods),
                                 shard=self._shard)
            self._mass_g.set(float(self._sketch.in_window_admitted_mass()),
                             shard=self._shard)
            self._budget_g.set(float(self._sketch.mass_budget),
                               shard=self._shard)

    def _result_label(self, res: Result) -> str:
        if res.fail_open:
            return "fail_open"
        return "allowed" if res.allowed else "denied"

    def _observe_result(self, op: str, res: Result, n: int, dt: float) -> None:
        self._requests.inc(algorithm=self._algo, result=self._result_label(res))
        if res.fail_open:
            self._errors.inc(algorithm=self._algo)
        if res.allowed:
            self._allowed.inc(algorithm=self._algo)
        else:
            self._denied.inc(algorithm=self._algo)
        self._latency.observe(dt, algorithm=self._algo, op=op)
        self._batch.observe(1.0)
        self._observe_envelope()

    def _observe_batch(self, op: str, out: BatchResult, ns, dt: float) -> None:
        b = len(out)
        n_allowed = int(np.sum(out.allowed))
        result = "fail_open" if out.fail_open else "mixed"
        self._requests.inc(b, algorithm=self._algo, result=result)
        if out.fail_open:
            self._errors.inc(algorithm=self._algo)
        self._allowed.inc(n_allowed, algorithm=self._algo)
        self._denied.inc(b - n_allowed, algorithm=self._algo)
        self._latency.observe(dt, algorithm=self._algo, op=op)
        self._batch.observe(float(b))
        self._observe_envelope()

    def _observe_op(self, op: str, dt: float) -> None:
        self._latency.observe(dt, algorithm=self._algo, op=op)

    def _observe_error(self, op: str, exc: Exception, dt: float) -> None:
        kind = _error_kind(exc)
        self._requests.inc(algorithm=self._algo, result=f"error:{kind}")
        if kind == "storage_unavailable":
            self._errors.inc(algorithm=self._algo)
        self._latency.observe(dt, algorithm=self._algo, op=op)


class TracingDecorator(LimiterDecorator):
    """Profiler-trace wrapper (the reference's planned OpenTelemetry
    ``TracingDecorator``, ``docs/ADR/003:115-124``, realized with the
    JAX profiler — the native tracing stack on TPU).

    Every decorated call runs inside a named ``jax.profiler``
    TraceAnnotation, so device dispatches show up attributed by
    op/algorithm in xplane traces. ``capture(path)`` context-manages a
    full profiler capture around a workload for offline analysis
    (tensorboard / xprof)."""

    def __init__(self, inner: RateLimiter):
        super().__init__(inner)
        self._algo = str(inner.config.algorithm)

    def _annotation(self, op: str):
        import jax.profiler

        return jax.profiler.TraceAnnotation(
            f"ratelimiter/{self._algo}/{op}")

    def allow_n(self, key: str, n: int, *, now: Optional[float] = None) -> Result:
        with self._annotation("allow_n"):
            return self.inner.allow_n(key, n, now=now)

    def allow_batch(self, keys: Sequence[str], ns=None, *,
                    now: Optional[float] = None) -> BatchResult:
        with self._annotation("allow_batch"):
            return self.inner.allow_batch(keys, ns, now=now)

    def reset(self, key: str) -> None:
        with self._annotation("reset"):
            self.inner.reset(key)

    def launch_batch(self, keys: Sequence[str], ns=None, *,
                     now: Optional[float] = None):
        # The pipelined hot path's two phases each get their own
        # annotation — without these, the default serving path's device
        # work would show up unattributed in xplane traces.
        with self._annotation("launch"):
            return self.inner.launch_batch(keys, ns, now=now)

    def resolve(self, ticket):
        with self._annotation("resolve"):
            return self.inner.resolve(ticket)

    @contextmanager
    def capture(self, path: str):
        """Profile everything inside the with-block to ``path`` (xplane
        format; view with tensorboard's profile plugin)."""
        import jax.profiler

        jax.profiler.start_trace(path)
        try:
            yield self
        finally:
            jax.profiler.stop_trace()


class CircuitBreakerDecorator(LimiterDecorator):
    """Circuit breaker around a limiter backend — the reference's planned
    resilience layer (``docs/ADR/002:170-197``, ``ROADMAP.md:104-108``:
    closed / open / half-open states), realized as a decorator.

    * closed: calls pass through; ``failure_threshold`` CONSECUTIVE
      backend failures (StorageUnavailableError raised, or a fail-open
      allowance — both mean the backend is down) trip the breaker;
    * open: for ``cooldown`` seconds the backend is not touched at all —
      decisions short-circuit per the limiter's fail-open/fail-closed
      policy (the point: a dead backend stops eating a dispatch timeout
      per request);
    * half-open: after the cooldown, exactly one probe call reaches the
      backend; success closes the breaker, failure re-opens it with a
      fresh cooldown.

    Time comes from the wrapped limiter's clock, so breaker tests use
    virtual time like everything else.
    """

    def __init__(self, inner: RateLimiter, *, failure_threshold: int = 5,
                 cooldown: float = 10.0,
                 registry: Optional[m.Registry] = None):
        super().__init__(inner)
        if failure_threshold < 1:
            raise ValueError("failure_threshold must be >= 1")
        self.failure_threshold = failure_threshold
        self.cooldown = float(cooldown)
        self._state = "closed"
        self._consecutive = 0
        self._open_until = 0.0
        self._probe_inflight = False
        self._cb_lock = threading.Lock()
        #: Per-sub-limiter scoping (ADR-015 satellite): around a
        #: composite backend (the sliced mesh — sub_limiters() > 1), a
        #: failure ATTRIBUTED to one slice (exception ``slice_index`` /
        #: result ``fail_open_slices``) counts against that slice's own
        #: breaker state and NEVER the whole-keyspace one — one bad
        #: device must not short-circuit every other range. Unattributed
        #: failures (the whole backend down) trip the global breaker as
        #: before.
        self._scoped = len(undecorated(inner).sub_limiters()) > 1
        self._sub_consecutive: dict = {}
        self._sub_last_failure: dict = {}
        self._sub_open_until: dict = {}
        reg = registry if registry is not None else m.DEFAULT
        self._transitions = reg.counter(
            "rate_limiter_breaker_transitions_total",
            "Circuit breaker state transitions")
        self._short_circuits = reg.counter(
            "rate_limiter_breaker_short_circuits_total",
            "Decisions answered without touching the backend")

    @property
    def state(self) -> str:
        return self._state

    def sub_state(self, index: int, now: Optional[float] = None) -> str:
        """Scoped breaker state of one sub-limiter: "open" while its
        cooldown runs, else "closed" (slice-scoped failures never have
        a half-open phase here — the quarantine manager owns per-slice
        probing; this state is attribution bookkeeping)."""
        t = self.inner.clock.now() if now is None else float(now)
        with self._cb_lock:
            return ("open"
                    if self._sub_open_until.get(index, 0.0) > t
                    else "closed")

    def sub_states(self) -> dict:
        with self._cb_lock:
            return dict(self._sub_open_until)

    @staticmethod
    def _exc_slices(exc: Exception):
        si = getattr(exc, "slice_index", None)
        return [si] if si is not None else None

    def _trip(self, now: float) -> None:
        self._state = "open"
        self._open_until = now + self.cooldown
        self._transitions.inc(to="open")

    def _clear_probe(self) -> None:
        """Release the half-open probe slot without judging backend health.

        Non-storage exceptions (key/N validation, a closed limiter, bugs)
        say nothing about whether the backend recovered; counting them as
        failures would re-open the breaker on caller mistakes, and not
        clearing the slot would wedge the breaker permanently (every later
        call short-circuits because the probe "never returned").
        Only the call that OWNS the slot may release it.
        """
        with self._cb_lock:
            self._probe_inflight = False

    def _note_result(self, failed: bool, now: float, probe: bool,
                     slices=None) -> None:
        with self._cb_lock:
            if probe:
                self._probe_inflight = False
            if failed:
                if slices and self._scoped:
                    # Slice-attributed failure: count against the named
                    # slices only. The whole-keyspace breaker must keep
                    # admitting traffic for every other range — that is
                    # the regression a single-slice fault storm used to
                    # cause (it tripped the global breaker). "Consecutive"
                    # is cooldown-windowed: a failure more than one
                    # cooldown after the slice's previous one restarts
                    # its count (a healthy frame can't clear it — frames
                    # not touching the slice say nothing about it — so
                    # isolated transients must not accumulate forever).
                    for s in slices:
                        last = self._sub_last_failure.get(s, 0.0)
                        stale = now - last > self.cooldown
                        self._sub_last_failure[s] = now
                        c = (1 if stale
                             else self._sub_consecutive.get(s, 0) + 1)
                        self._sub_consecutive[s] = c
                        if (c >= self.failure_threshold
                                and self._sub_open_until.get(s, 0.0)
                                <= now):
                            self._sub_open_until[s] = now + self.cooldown
                            self._transitions.inc(to="open",
                                                  slice=str(s))
                    return
                self._consecutive += 1
                if (self._state == "half-open"
                        or self._consecutive >= self.failure_threshold):
                    self._trip(now)
            else:
                self._consecutive = 0
                if self._state != "closed":
                    self._state = "closed"
                    self._transitions.inc(to="closed")

    def _admit_call(self, now: float) -> Optional[bool]:
        """None = short-circuit; False = admitted (breaker closed);
        True = admitted as THE half-open probe (this call owns the slot
        and is the only one allowed to release it — a concurrent
        closed-state call that later fails must not free a slot it never
        held, or two probes could run at once)."""
        with self._cb_lock:
            if self._state == "closed":
                return False
            if self._state == "open" and now >= self._open_until:
                self._state = "half-open"
                self._transitions.inc(to="half-open")
            if self._state == "half-open" and not self._probe_inflight:
                self._probe_inflight = True
                return True
            return None

    def _short_circuit(self, b: int, now: float):
        self._short_circuits.inc(b)
        cfg = self.inner.config
        reset_at = now + float(cfg.window)
        if not cfg.fail_open:
            raise StorageUnavailableError(
                f"circuit breaker open (cooldown {self.cooldown:g}s)")
        if b == 1:
            from ratelimiter_tpu.core.types import fail_open_result

            return fail_open_result(cfg.limit, reset_at)
        from ratelimiter_tpu.core.types import batch_fail_open

        return batch_fail_open(b, cfg.limit, reset_at)

    def allow_n(self, key: str, n: int, *, now: Optional[float] = None) -> Result:
        t = self.inner.clock.now() if now is None else float(now)
        probe = self._admit_call(t)
        if probe is None:
            return self._short_circuit(1, t)
        try:
            res = self.inner.allow_n(key, n, now=now)
        except StorageUnavailableError as exc:
            self._note_result(True, t, probe, self._exc_slices(exc))
            raise
        except BaseException:
            if probe:
                self._clear_probe()
            raise
        self._note_result(res.fail_open, t, probe,
                          getattr(res, "fail_open_slices", None))
        return res

    def allow_batch(self, keys: Sequence[str], ns=None, *,
                    now: Optional[float] = None) -> BatchResult:
        t = self.inner.clock.now() if now is None else float(now)
        probe = self._admit_call(t)
        if probe is None:
            return self._short_circuit(len(keys), t)
        try:
            out = self.inner.allow_batch(keys, ns, now=now)
        except StorageUnavailableError as exc:
            self._note_result(True, t, probe, self._exc_slices(exc))
            raise
        except BaseException:
            if probe:
                self._clear_probe()
            raise
        self._note_result(out.fail_open, t, probe,
                          getattr(out, "fail_open_slices", None))
        return out

    # Pipelined path (ADR-010): the breaker admits (or short-circuits) at
    # LAUNCH — an open breaker must not enqueue device work at all — and
    # judges backend health at RESOLVE, where failure actually surfaces.
    # Probe ownership rides the ticket's meta field between the phases.

    def launch_batch(self, keys: Sequence[str], ns=None, *,
                     now: Optional[float] = None):
        t = self.inner.clock.now() if now is None else float(now)
        probe = self._admit_call(t)
        if probe is None:
            from ratelimiter_tpu.core.types import DispatchTicket

            return DispatchTicket(result=self._short_circuit(len(keys), t))
        try:
            ticket = self.inner.launch_batch(keys, ns, now=now)
        except StorageUnavailableError as exc:
            self._note_result(True, t, probe, self._exc_slices(exc))
            raise
        except BaseException:
            if probe:
                self._clear_probe()
            raise
        ticket.meta = ("breaker", t, probe)
        return ticket

    # Hashed / raw-id lane (ADR-011): the breaker guards every dispatch
    # entry point identically — an open breaker must not enqueue device
    # work for hashed frames any more than for string batches.

    def _guarded_sync(self, fn, b: int, now):
        t = self.inner.clock.now() if now is None else float(now)
        probe = self._admit_call(t)
        if probe is None:
            return self._short_circuit(b, t)
        try:
            out = fn()
        except StorageUnavailableError as exc:
            self._note_result(True, t, probe, self._exc_slices(exc))
            raise
        except BaseException:
            if probe:
                self._clear_probe()
            raise
        self._note_result(out.fail_open, t, probe,
                          getattr(out, "fail_open_slices", None))
        return out

    def _guarded_launch(self, fn, b: int, now):
        t = self.inner.clock.now() if now is None else float(now)
        probe = self._admit_call(t)
        if probe is None:
            from ratelimiter_tpu.core.types import DispatchTicket

            return DispatchTicket(result=self._short_circuit(b, t))
        try:
            ticket = fn()
        except StorageUnavailableError as exc:
            self._note_result(True, t, probe, self._exc_slices(exc))
            raise
        except BaseException:
            if probe:
                self._clear_probe()
            raise
        ticket.meta = ("breaker", t, probe)
        return ticket

    def allow_hashed(self, h64, ns=None, *, now=None):
        return self._guarded_sync(
            lambda: self.inner.allow_hashed(h64, ns, now=now),
            len(h64), now)

    def allow_ids(self, ids, ns=None, *, now=None):
        return self._guarded_sync(
            lambda: self.inner.allow_ids(ids, ns, now=now), len(ids), now)

    def launch_hashed(self, h64, ns=None, *, now=None):
        return self._guarded_launch(
            lambda: self.inner.launch_hashed(h64, ns, now=now),
            len(h64), now)

    def launch_ids(self, ids, ns=None, *, now=None, wire: bool = False):
        return self._guarded_launch(
            lambda: self.inner.launch_ids(ids, ns, now=now, wire=wire),
            len(ids), now)

    def resolve(self, ticket):
        tag = None
        if (isinstance(ticket.meta, tuple) and ticket.meta
                and ticket.meta[0] == "breaker"):
            tag = ticket.meta
            ticket.meta = None
        try:
            out = self.inner.resolve(ticket)
        except StorageUnavailableError as exc:
            if tag is not None:
                self._note_result(True, tag[1], tag[2],
                                  self._exc_slices(exc))
            raise
        except BaseException:
            if tag is not None and tag[2]:
                self._clear_probe()
            raise
        if tag is not None:
            self._note_result(out.fail_open, tag[1], tag[2],
                              getattr(out, "fail_open_slices", None))
        return out


class LoggingDecorator(LimiterDecorator):
    """Structured logging wrapper (``docs/ADR/003:68-91``): decisions at
    DEBUG, fail-open allowances at WARNING, errors at ERROR.

    Keys on the scalar path are logged at the caller's discretion:
    by default as given (the caller owns PII policy, as in the
    reference), or — with ``redact_keys=True`` — as the splitmix64 hash
    of the key's finalized u64 hash (``key#<16 hex>``), an irreversible
    but stable token that still correlates log lines per key without
    writing raw identifiers (user ids, API tokens, emails) into log
    storage. The PII trust boundary is documented in
    docs/OPERATIONS.md §6.

    Fail-open WARNINGs carry ``fail_open_slices`` when the result
    attributes the degradation (a quarantined mesh range, ADR-015), so
    a degraded-range line is actionable — it names WHICH slice's key
    range is answering fabricated allowances, not just that some frame
    somewhere failed open.
    """

    def __init__(self, inner: RateLimiter,
                 logger: Optional[logging.Logger] = None, *,
                 redact_keys: bool = False):
        super().__init__(inner)
        self.logger = logger if logger is not None else logging.getLogger(
            "ratelimiter_tpu")
        self._algo = str(inner.config.algorithm)
        self.redact_keys = bool(redact_keys)

    def _fmt_key(self, key: str) -> str:
        if not self.redact_keys:
            return key
        from ratelimiter_tpu.ops.hashing import key_token

        # Shared token rule (ops/hashing.key_token): redacted log lines
        # stay joinable with journal key_hash fields.
        return key_token(key)

    @staticmethod
    def _fo_slices(res) -> str:
        attr = getattr(res, "fail_open_slices", None)
        return f" fail_open_slices={sorted(attr)}" if attr else ""

    # Scalar path: overridden (not just hooked) so the KEY is in scope
    # for the log line — the base hooks deliberately do not carry it.

    def allow_n(self, key: str, n: int, *,
                now: Optional[float] = None) -> Result:
        t0 = time.perf_counter()
        try:
            res = self.inner.allow_n(key, n, now=now)
        except Exception as exc:
            self._observe_error("allow_n", exc, time.perf_counter() - t0)
            raise
        dt = time.perf_counter() - t0
        if res.fail_open:
            self.logger.warning(
                "fail-open allowance algorithm=%s key=%s n=%d "
                "latency=%.6f%s",
                self._algo, self._fmt_key(key), n, dt, self._fo_slices(res))
        elif self.logger.isEnabledFor(logging.DEBUG):
            self.logger.debug(
                "decision algorithm=%s key=%s allowed=%s n=%d remaining=%d "
                "latency=%.6f",
                self._algo, self._fmt_key(key), res.allowed, n,
                res.remaining, dt)
        return res

    def reset(self, key: str) -> None:
        # Quota-erase is audit-worthy: always logged, same redaction.
        t0 = time.perf_counter()
        try:
            self.inner.reset(key)
        except Exception as exc:
            self._observe_error("reset", exc, time.perf_counter() - t0)
            raise
        self.logger.info("reset algorithm=%s key=%s latency=%.6f",
                         self._algo, self._fmt_key(key),
                         time.perf_counter() - t0)

    def _observe_batch(self, op: str, out: BatchResult, ns, dt: float) -> None:
        if out.fail_open:
            self.logger.warning(
                "fail-open batch algorithm=%s size=%d latency=%.6f%s",
                self._algo, len(out), dt, self._fo_slices(out))
        elif self.logger.isEnabledFor(logging.DEBUG):
            self.logger.debug(
                "batch algorithm=%s size=%d allowed=%d latency=%.6f",
                self._algo, len(out), int(np.sum(out.allowed)), dt)

    def _observe_error(self, op: str, exc: Exception, dt: float) -> None:
        self.logger.error("limiter error op=%s algorithm=%s error=%s",
                          op, self._algo, exc)
