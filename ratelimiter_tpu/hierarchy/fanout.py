"""Uniform hierarchy surface over N dispatch units (ADR-020).

The serving tier mounts the cascade's management surface in three
shapes: one limiter (asyncio door), a SlicedMeshLimiter composite (its
write-all overrides already span the slices), or a LIST of per-shard
limiters mounted directly on the native door. ``HierarchyFanout``
normalizes the last case — and degenerates to pure delegation for a
single unit — so the AIMD controller, the /healthz block, and the
/v1/tenants endpoint program against ONE object everywhere.

Semantics mirror SlicedMeshLimiter's hierarchy overrides: mutations
apply on EVERY unit (each enforces its equal share of the scope limits;
keys hash-route so the key→tenant map rows are simply present
everywhere), reads come from unit 0 (write-all keeps the tables
agreeing), and stats sum the per-unit counter slabs into the whole
deployment's in-window view.
"""

from __future__ import annotations

from typing import List, Optional


class HierarchyFanout:
    """Write-all / read-one / sum-stats over ``units`` (each any object
    exposing the RateLimiter hierarchy surface, decorated or not)."""

    def __init__(self, units: List):
        if not units:
            raise ValueError("HierarchyFanout needs at least one unit")
        self.units = list(units)

    def _all(self, fn):
        out = None
        for u in self.units:
            out = fn(u)
        return out

    # ------------------------------------------------------- mutations

    def set_tenant(self, name: str, limit: Optional[int] = None, *,
                   weight: int = 1, floor: Optional[int] = None):
        return self._all(lambda u: u.set_tenant(name, limit, weight=weight,
                                                floor=floor))

    def delete_tenant(self, name: str) -> bool:
        return bool(self._all(lambda u: u.delete_tenant(name)))

    def assign_tenant(self, key: str, tenant: str) -> None:
        self._all(lambda u: u.assign_tenant(key, tenant))

    def unassign_tenant(self, key: str) -> bool:
        return bool(self._all(lambda u: u.unassign_tenant(key)))

    def set_global_limit(self, limit: Optional[int]) -> None:
        self._all(lambda u: u.set_global_limit(limit))

    def set_effective(self, scope: str, limit: int) -> int:
        return int(self._all(lambda u: u.set_effective(scope, limit)))

    def apply_hierarchy_payload(self, payload: dict) -> bool:
        return bool(self._all(
            lambda u: u.apply_hierarchy_payload(payload)))

    # ----------------------------------------------------------- reads

    def tenant_of(self, key: str) -> str:
        return self.units[0].tenant_of(key)

    def get_tenant(self, name: str):
        return self.units[0].get_tenant(name)

    def list_tenants(self):
        return self.units[0].list_tenants()

    def effective_limits(self):
        return self.units[0].effective_limits()

    def hierarchy_payload(self) -> dict:
        return self.units[0].hierarchy_payload()

    def hierarchy_stats(self) -> dict:
        parts = [u.hierarchy_stats() for u in self.units]
        out = parts[0]
        for p in parts[1:]:
            for name, t in p["tenants"].items():
                out["tenants"][name]["in_window"] += t["in_window"]
            out["global"]["in_window"] += p["global"]["in_window"]
        return out
