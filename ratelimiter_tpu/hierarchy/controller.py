"""AIMD adaptive control over the cascade's effective limits (ADR-020).

Closes ROADMAP item 3's control loop: a background thread (never the hot
path) reads the LIVE signals the observatory already produces —

* the SLO burn rate (observability/slo.SloBurnTracker.status): latency /
  availability pressure on the serving door;
* the audit observatory's Wilson-bounded false-deny rate
  (observability/audit.ShadowAuditor.status): how much of the current
  denying is the LIMITER's own error — used as a tighten VETO, since
  tightening amplifies exactly that;
* per-scope in-window mass from the cascade's own counter slab
  (RateLimiter.hierarchy_stats — the same counters the kernel admits
  against, so "pressure" is measured where it is enforced; the hh-backed
  top-K consumer analytics tell the operator WHICH keys carry a hot
  tenant's mass)

— and moves each scope's *effective* limit between its floor and its
configured ceiling:

* **Multiplicative decrease** (``decrease_factor``) when the door is
  burning SLO budget, or when the global scope is saturated AND a tenant
  is hogging it (mass share > ``hot_share`` × its fair weight share —
  the hot-tenant-storm signature). Hot tenants tighten before the global
  scope ever does, so an abusive tenant is squeezed while the others
  keep their headroom. A per-scope cooldown keeps one decision per
  ``cooldown_s`` — AIMD, not free-fall.
* **Additive increase** (``increase_fraction`` of the ceiling per tick)
  back toward the ceiling once pressure clears and the scope's demand
  sits comfortably under its current effective limit
  (``relax_occupancy``).

Publishing rides the existing update machinery: ``set_effective`` on the
limiter (write-all across mesh slices), and an optional ``publish`` hook
the serving tier wires to the fleet announce channel so members converge
on the newest revision (hierarchy/tenants.effective_payload).
"""

from __future__ import annotations

import logging
import threading
from dataclasses import dataclass
from typing import Callable, Dict, Optional

from ratelimiter_tpu.core.config import HIER_UNLIMITED
from ratelimiter_tpu.hierarchy.tenants import GLOBAL
from ratelimiter_tpu.observability import events, tracing

log = logging.getLogger("ratelimiter_tpu.hierarchy")


@dataclass(frozen=True)
class AIMDGains:
    """Controller gains. Defaults are deliberately gentle: one tighten
    halves-ish a scope, recovery takes ~1/increase_fraction ticks."""

    #: Multiplicative decrease applied on tighten.
    decrease_factor: float = 0.7
    #: Additive increase per tick, as a fraction of the scope's ceiling.
    increase_fraction: float = 0.05
    #: SLO burn rate at/above which the door counts as under pressure.
    burn_tighten: float = 2.0
    #: Burn rate at/below which recovery may proceed.
    burn_relax: float = 1.0
    #: Global-scope occupancy (mass / effective limit) that counts as
    #: saturation — the storm trigger when no SLO tracker is wired.
    saturation: float = 0.9
    #: A tenant is "hot" when its share of global mass exceeds
    #: hot_share × its fair weight share.
    hot_share: float = 2.0
    #: Tighten veto: skip tightening while the audited false-deny
    #: Wilson-95 UPPER bound exceeds this (the limiter is already
    #: over-denying; squeezing harder amplifies its own error).
    false_deny_veto: float = 0.05
    #: Scope demand must sit under relax_occupancy × effective before a
    #: relax step (no point raising a limit demand is still slamming).
    relax_occupancy: float = 0.8
    #: Minimum seconds between tightens of one scope.
    cooldown_s: float = 2.0


class AIMDController:
    """Background AIMD loop over one limiter's TenantTable.

    Args:
        limiter: any limiter (or decorator stack) exposing the hierarchy
            surface (hierarchy_stats / set_effective / effective_limits).
        slo_status: optional zero-arg callable returning
            SloBurnTracker.status() (None = no SLO axis; the saturation
            trigger still runs).
        audit_status: optional zero-arg callable returning
            ShadowAuditor.status() (None = no false-deny veto).
        interval: seconds between ticks.
        gains: AIMDGains.
        publish: optional callable(payload dict) invoked after any
            effective-limit change (the fleet propagation seam).
        registry: metrics registry for the controller gauges (None =
            the process default).
    """

    def __init__(self, limiter, *,
                 slo_status: Optional[Callable[[], dict]] = None,
                 audit_status: Optional[Callable[[], dict]] = None,
                 interval: float = 1.0,
                 gains: Optional[AIMDGains] = None,
                 publish: Optional[Callable[[dict], None]] = None,
                 on_tighten: Optional[Callable[[str], None]] = None,
                 registry=None):
        from ratelimiter_tpu.observability import metrics as m

        self.limiter = limiter
        self.slo_status = slo_status
        self.audit_status = audit_status
        self.interval = float(interval)
        self.gains = gains or AIMDGains()
        self.publish = publish
        #: Called with the scope name after each successful tighten —
        #: the lease-revocation seam (ADR-022): leased budget granted
        #: under the old effective limit must not keep spending at the
        #: old rate once the controller squeezes the scope.
        self.on_tighten = on_tighten
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._last_tighten: Dict[str, float] = {}
        self._last_veto_event = -1e9
        self.ticks = 0
        self.tightened = 0
        self.relaxed = 0
        reg = registry if registry is not None else m.DEFAULT
        self._g_eff = reg.gauge(
            "rate_limiter_hier_effective_limit",
            "Live effective limit per cascade scope (AIMD-controlled)")
        self._g_mass = reg.gauge(
            "rate_limiter_hier_in_window",
            "In-window admitted mass per cascade scope")
        self._c_adj = reg.counter(
            "rate_limiter_hier_adjustments_total",
            "AIMD effective-limit moves by direction")

    # ------------------------------------------------------------ signals

    def _burn(self) -> float:
        if self.slo_status is None:
            return 0.0
        try:
            windows = (self.slo_status() or {}).get("windows") or {}
            if not windows:
                return 0.0
            # The shortest window is the most reactive signal.
            key = min(windows, key=lambda k: float(k.rstrip("s")))
            return float(windows[key].get("burn_rate", 0.0))
        except Exception:  # noqa: BLE001 — a signal, not a dependency
            log.exception("controller: slo_status failed; treating as 0")
            return 0.0

    def _false_deny_hi(self) -> float:
        if self.audit_status is None:
            return 0.0
        try:
            st = self.audit_status() or {}
            return float((st.get("false_deny_wilson95") or [0, 0])[1])
        except Exception:  # noqa: BLE001
            log.exception("controller: audit_status failed; treating as 0")
            return 0.0

    # --------------------------------------------------------------- tick

    def tick(self, now: Optional[float] = None) -> Dict[str, int]:
        """One control step; returns {scope: new effective limit} for the
        scopes it moved (exposed for tests and the bench harness)."""
        import time as _time

        g = self.gains
        now = _time.monotonic() if now is None else now
        stats = self.limiter.hierarchy_stats()
        burn = self._burn()
        fd_hi = self._false_deny_hi()
        tenants: Dict[str, dict] = stats["tenants"]
        gstat = stats["global"]
        g_eff = gstat["effective"]
        g_mass = gstat["in_window"]
        self._g_mass.set(float(g_mass), scope=GLOBAL)
        if g_eff < HIER_UNLIMITED:
            self._g_eff.set(float(g_eff), scope=GLOBAL)
        for name, t in tenants.items():
            self._g_mass.set(float(t["in_window"]), scope=name)
            if t["effective"] < HIER_UNLIMITED:
                self._g_eff.set(float(t["effective"]), scope=name)

        pressure = burn >= g.burn_tighten
        saturated = (g_eff < HIER_UNLIMITED
                     and g_mass >= g.saturation * g_eff)
        w_sum = sum(t["weight"] for t in tenants.values()) or 1
        hot = []
        if g_mass > 0:
            for name, t in tenants.items():
                fair = t["weight"] / w_sum
                if t["in_window"] / g_mass > g.hot_share * fair:
                    hot.append(name)

        moved: Dict[str, int] = {}
        # One correlation id per tick + the full triggering-signal
        # snapshot on every journal event (ADR-021): a tighten must be
        # reconstructable from /debug/events ALONE — cause, signals,
        # old/new limits — without grepping N hosts' logs.
        corr = tracing.new_trace_id() if events.JOURNAL is not None else 0
        snapshot = {
            "burn_rate": round(burn, 4),
            "false_deny_wilson_high": round(fd_hi, 6),
            "global_mass": int(g_mass),
            "global_effective": (int(g_eff) if g_eff < HIER_UNLIMITED
                                 else None),
            "saturated": saturated,
            "hot_tenants": list(hot),
        }

        def _tighten(scope: str, eff: int) -> None:
            if eff >= HIER_UNLIMITED:
                # An uncapped scope has no real limit to move: 0.7 x
                # 2^40 would install a meaningless "limit" while the
                # log/counters claim a containment that contains
                # nothing. Cap the scope (give it a ceiling) to make it
                # controllable.
                return
            if now - self._last_tighten.get(scope, -1e9) < g.cooldown_s:
                return
            new = self.limiter.set_effective(
                scope, max(1, int(eff * g.decrease_factor)))
            if new != eff:
                self._last_tighten[scope] = now
                moved[scope] = new
                self.tightened += 1
                self._c_adj.inc(direction="tighten")
                log.warning("controller: tightened %s %d -> %d "
                            "(burn=%.2f saturated=%s hot=%s corr=%016x)",
                            scope, eff, new, burn, saturated, hot, corr)
                events.emit(
                    "controller", "tighten", actor=scope, corr=corr,
                    severity="warning",
                    payload={"old": int(eff), "new": int(new),
                             "cause": ("hot-tenant" if scope in hot
                                       else "slo-pressure"),
                             "in_window": int(
                                 tenants[scope]["in_window"]
                                 if scope in tenants else g_mass),
                             **snapshot})
                if self.on_tighten is not None:
                    try:
                        self.on_tighten(scope)
                    except Exception:  # noqa: BLE001 — best-effort
                        log.exception(
                            "controller: on_tighten hook failed")

        if (pressure or (saturated and hot)) and fd_hi > g.false_deny_veto:
            # Vetoed tighten: the limiter is already over-denying with
            # 95% confidence — journal it (the "why did it NOT act"
            # half of incident reconstruction). Cooldown-bounded like
            # tightens: a veto holding for an hour at a 1 s tick must
            # not flood the bounded ring and evict the incident's own
            # start (handoffs, failovers, the first tighten).
            if now - self._last_veto_event >= g.cooldown_s:
                self._last_veto_event = now
                events.emit("controller", "tighten-vetoed", corr=corr,
                            severity="warning",
                            payload={"veto_threshold": g.false_deny_veto,
                                     **snapshot})
        if (pressure or (saturated and hot)) and fd_hi <= g.false_deny_veto:
            # Hot tenants squeeze first; the global scope only tightens
            # under SLO pressure with no attributable tenant (fair-share
            # clipping already arbitrates honest contention).
            if hot:
                for name in hot:
                    _tighten(name, tenants[name]["effective"])
            elif pressure and g_eff < HIER_UNLIMITED:
                _tighten(GLOBAL, g_eff)
        elif burn <= g.burn_relax:
            # Additive recovery toward each ceiling once demand clears.
            for name, t in tenants.items():
                eff, ceil_ = t["effective"], t["ceiling"]
                if (eff < ceil_
                        and t["in_window"] <= g.relax_occupancy * eff):
                    step = max(1, int(ceil_ * g.increase_fraction))
                    new = self.limiter.set_effective(
                        name, min(ceil_, eff + step))
                    if new != eff:
                        moved[name] = new
                        self.relaxed += 1
                        self._c_adj.inc(direction="relax")
                        events.emit(
                            "controller", "relax", actor=name, corr=corr,
                            payload={"old": int(eff), "new": int(new),
                                     "ceiling": int(ceil_), **snapshot})
            if (g_eff < gstat["ceiling"]
                    and g_mass <= g.relax_occupancy * g_eff):
                step = max(1, int(gstat["ceiling"] * g.increase_fraction))
                new = self.limiter.set_effective(
                    GLOBAL, min(gstat["ceiling"], g_eff + step))
                if new != g_eff:
                    moved[GLOBAL] = new
                    self.relaxed += 1
                    self._c_adj.inc(direction="relax")
                    events.emit(
                        "controller", "relax", actor=GLOBAL, corr=corr,
                        payload={"old": int(g_eff), "new": int(new),
                                 "ceiling": int(gstat["ceiling"]),
                                 **snapshot})

        if moved and self.publish is not None:
            try:
                self.publish(self.limiter.hierarchy_payload())
            except Exception:  # noqa: BLE001 — propagation is best-effort
                log.exception("controller: publish hook failed")
        self.ticks += 1
        return moved

    # ------------------------------------------------------------ thread

    def start(self) -> None:
        if self._thread is not None:
            return
        self._stop.clear()
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="rl-aimd-controller")
        self._thread.start()

    def _run(self) -> None:
        while not self._stop.wait(self.interval):
            try:
                self.tick()
            except Exception:  # noqa: BLE001 — keep controlling
                log.exception("controller tick failed")

    def stop(self) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=5.0)
            self._thread = None

    def status(self) -> dict:
        return {"ticks": self.ticks, "tightened": self.tightened,
                "relaxed": self.relaxed, "interval": self.interval,
                "effective": self.limiter.effective_limits()}
