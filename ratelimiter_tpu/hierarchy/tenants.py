"""Host-authoritative tenant registry + key→tenant map (ADR-020).

One TenantTable per limiter unit, mirroring the policy engine's split:
the table owns the entry store and the *host* form of the device arrays
(sorted key→tenant map, per-scope limit/weight columns); the backend
owns placement and consults the arrays inside its jitted decision step
(ops/hier_kernels.py). Mutations are serialized by the OWNING LIMITER's
lock (RateLimiter._policy_mutate — the same discipline as PolicyTable).

Two kinds of limit per scope:

* **configured** — the operator-set ceiling (``set_tenant`` /
  ``HierarchySpec``); 0 means unlimited.
* **effective** — what the device table actually enforces right now.
  Defaults to the configured ceiling; the AIMD controller (or an
  operator override) moves it between its floor and the ceiling. The
  distinction is the control loop's lever: tightening never rewrites
  configuration, and recovery has a well-defined target to return to.

Sliced-mesh deployments pass ``divisor = n_slices``: each hash-routed
slice enforces an equal share (``max(1, effective // divisor)``) of
every tenant/global limit, the same static-split rule hash-partitioned
fleet members use. Replicated mesh limiters keep divisor 1 (their psum
makes the counters globally exact).

Durability: tenant definitions, assignments, and the CONTROLLER-MOVED
effective limits ride checkpoints as ``hier_*`` columns
(snapshot_arrays/restore_arrays), so a restart resumes adaptive state
instead of snapping every limit back to its ceiling.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np

from ratelimiter_tpu.core.config import Config, HIER_UNLIMITED
from ratelimiter_tpu.core.errors import InvalidConfigError
from ratelimiter_tpu.ops import policy_kernels as pk

#: Scope name addressing the global (whole-limiter) scope in the
#: effective-limit surfaces.
GLOBAL = "global"

#: Default tenant's reserved name (tid 0 — every unassigned key).
DEFAULT_TENANT = "default"

_MAX_WEIGHT = 1 << 20


@dataclass(frozen=True)
class Tenant:
    """One tenant scope: its slab index, configured ceiling, fair-share
    weight, and controller floor (the AIMD tighten bound)."""

    tid: int
    limit: int        # configured ceiling; 0 = unlimited
    weight: int
    floor: int        # lowest effective limit the controller may set


class TenantTable:
    """Bounded tenant registry + key→tenant assignment map.

    Args:
        config: the owning limiter's config (capacities and the default
            tenant/global limits come from ``config.hierarchy``).
        key_fn: maps a key string to its int64 search key — the SAME
            packed (h1, h2) domain the decision step derives tenant ids
            in (ops/hier_kernels.derive_tids).
        divisor: per-unit share divisor (sliced mesh: n_slices).
    """

    def __init__(self, config: Config, *, key_fn: Callable[[str], int],
                 divisor: int = 1):
        spec = config.hierarchy
        if not spec.enabled:
            raise InvalidConfigError(
                "TenantTable needs hierarchy.tenants > 0")
        self.capacity = spec.tenants
        self.map_capacity = spec.map_capacity
        self.divisor = max(1, int(divisor))
        self._key_fn = key_fn
        self._tenants: Dict[str, Tenant] = {}
        self._names: List[Optional[str]] = [None] * self.capacity
        self._glimit = int(spec.global_limit)          # configured; 0=unl
        #: controller-moved effective limits: tid (or GLOBAL) -> limit.
        #: Absent = tracking the configured ceiling.
        self._eff: Dict[object, int] = {}
        self._assign: Dict[str, str] = {}              # key -> tenant name
        self._skey: Dict[str, int] = {}                # key -> search key
        self._by_skey: Dict[int, str] = {}
        #: bumped on every mutation; backends invalidate device caches.
        self.version = 0
        #: bumped on every EFFECTIVE-limit change; fleet propagation uses
        #: it as a last-writer-wins revision (apply_effective_payload).
        self.revision = 0
        self._host_arrays: Optional[Dict[str, np.ndarray]] = None
        self.set_tenant(DEFAULT_TENANT,
                        limit=spec.default_tenant_limit or None)

    # ------------------------------------------------------------ tenants

    def set_tenant(self, name: str, limit: Optional[int] = None,
                   weight: int = 1, floor: Optional[int] = None) -> Tenant:
        """Register a tenant or update an existing one's ceiling/weight/
        floor. ``limit=None`` means unlimited; the effective limit snaps
        back under a LOWERED ceiling but otherwise stands."""
        if not isinstance(name, str) or not name:
            raise InvalidConfigError(f"tenant name must be a non-empty "
                                     f"string, got {name!r}")
        lim = 0 if limit is None else limit
        if (not isinstance(lim, int) or isinstance(lim, bool)
                or lim < 0 or lim >= HIER_UNLIMITED):
            raise InvalidConfigError(
                f"tenant limit must be None or an integer in [1, 2^40), "
                f"got {limit!r}")
        if (not isinstance(weight, int) or isinstance(weight, bool)
                or weight < 1 or weight > _MAX_WEIGHT):
            raise InvalidConfigError(
                f"tenant weight must be an integer in [1, {_MAX_WEIGHT}], "
                f"got {weight!r}")
        ceil_ = lim or HIER_UNLIMITED
        fl = floor if floor is not None else max(1, ceil_ // 10)
        if (not isinstance(fl, int) or isinstance(fl, bool) or fl < 1
                or fl > ceil_):
            raise InvalidConfigError(
                f"tenant floor must be an integer in [1, ceiling], "
                f"got {floor!r}")
        prev = self._tenants.get(name)
        if prev is None:
            try:
                tid = self._names.index(None)
            except ValueError:
                raise InvalidConfigError(
                    f"tenant table full ({self.capacity} tenants); raise "
                    f"HierarchySpec.tenants") from None
            if name == DEFAULT_TENANT and tid != 0:
                raise InvalidConfigError(
                    "the default tenant must be registered first (tid 0)")
        else:
            tid = prev.tid
        t = Tenant(tid=tid, limit=lim, weight=int(weight), floor=int(fl))
        self._tenants[name] = t
        self._names[tid] = name
        eff = self._eff.get(tid)
        if eff is not None and eff > ceil_:
            self._eff[tid] = ceil_
        self._invalidate()
        return t

    def delete_tenant(self, name: str) -> bool:
        """Unregister a tenant; its keys fall back to the default tenant
        (their map rows are removed)."""
        if name == DEFAULT_TENANT:
            raise InvalidConfigError("the default tenant cannot be deleted")
        t = self._tenants.pop(name, None)
        if t is None:
            return False
        self._names[t.tid] = None
        self._eff.pop(t.tid, None)
        for key in [k for k, v in self._assign.items() if v == name]:
            del self._by_skey[self._skey.pop(key)]
            del self._assign[key]
        self._invalidate()
        return True

    def get_tenant(self, name: str) -> Optional[Tenant]:
        return self._tenants.get(name)

    def tenant_names(self) -> List[str]:
        return sorted(self._tenants)

    # ------------------------------------------------------- assignments

    def assign(self, key: str, tenant: str) -> None:
        if tenant not in self._tenants:
            raise InvalidConfigError(f"unknown tenant {tenant!r}")
        if tenant == DEFAULT_TENANT:
            self.unassign(key)
            return
        if key not in self._assign and len(self._assign) >= self.map_capacity:
            raise InvalidConfigError(
                f"tenant map full ({self.map_capacity} assignments); "
                f"raise HierarchySpec.map_capacity")
        skey = int(self._key_fn(key))
        clash = self._by_skey.get(skey)
        if (clash is not None and clash != key) or skey == pk.PAD_KEY:
            raise InvalidConfigError(
                f"key {key!r} collides in the hash domain (with "
                f"{clash!r}); rename one of the keys")
        self._assign[key] = tenant
        self._skey[key] = skey
        self._by_skey[skey] = key
        self._invalidate()

    def unassign(self, key: str) -> bool:
        if key not in self._assign:
            return False
        del self._assign[key]
        del self._by_skey[self._skey.pop(key)]
        self._invalidate()
        return True

    def tenant_of(self, key: str) -> str:
        return self._assign.get(key, DEFAULT_TENANT)

    def assignments(self) -> List[Tuple[str, str]]:
        return sorted(self._assign.items())

    # -------------------------------------------------- effective limits

    def _ceiling(self, scope: object) -> int:
        if scope == GLOBAL:
            return self._glimit or HIER_UNLIMITED
        name = self._names[scope] if isinstance(scope, int) else None
        if name is None:
            raise InvalidConfigError(f"unknown scope {scope!r}")
        return self._tenants[name].limit or HIER_UNLIMITED

    def _floor(self, scope: object) -> int:
        if scope == GLOBAL:
            return max(1, (self._glimit or HIER_UNLIMITED) // 10)
        return self._tenants[self._names[scope]].floor

    @property
    def global_ceiling(self) -> int:
        return self._glimit or HIER_UNLIMITED

    def set_global_limit(self, limit: Optional[int]) -> None:
        """Move the configured global ceiling (0/None = unlimited)."""
        lim = 0 if limit is None else int(limit)
        if lim < 0 or lim >= HIER_UNLIMITED:
            raise InvalidConfigError(
                f"global limit must be in [0, 2^40), got {limit!r}")
        self._glimit = lim
        eff = self._eff.get(GLOBAL)
        if eff is not None and eff > (lim or HIER_UNLIMITED):
            self._eff[GLOBAL] = lim or HIER_UNLIMITED
        self._invalidate()

    def set_effective(self, scope: str, limit: int) -> int:
        """The controller's lever: set a scope's live effective limit
        (``scope`` = tenant name or GLOBAL), clamped to [floor, ceiling].
        Returns the clamped value actually installed."""
        key: object = GLOBAL
        if scope != GLOBAL:
            t = self._tenants.get(scope)
            if t is None:
                raise InvalidConfigError(f"unknown tenant {scope!r}")
            key = t.tid
        lim = int(limit)
        lim = max(self._floor(key), min(lim, self._ceiling(key)))
        if lim == self.effective_of(scope):
            return lim
        if lim == self._ceiling(key):
            self._eff.pop(key, None)
        else:
            self._eff[key] = lim
        self.revision += 1
        self._invalidate()
        return lim

    def effective_of(self, scope: str) -> int:
        """Current effective limit for a tenant name or GLOBAL (the
        HIER_UNLIMITED sentinel when uncapped)."""
        if scope == GLOBAL:
            return self._eff.get(GLOBAL, self._glimit or HIER_UNLIMITED)
        t = self._tenants.get(scope)
        if t is None:
            raise InvalidConfigError(f"unknown tenant {scope!r}")
        return self._eff.get(t.tid, t.limit or HIER_UNLIMITED)

    def effective_limits(self) -> Dict[str, int]:
        out = {name: self.effective_of(name) for name in self._tenants}
        out[GLOBAL] = self.effective_of(GLOBAL)
        return out

    # ------------------------------------------- fleet propagation frame

    def effective_payload(self) -> dict:
        """JSON-able effective-limit frame for DCN/announce propagation
        (fleet members converge on the highest revision)."""
        return {"revision": self.revision,
                "effective": {str(k): v for k, v in
                              self.effective_limits().items()}}

    def apply_effective_payload(self, payload: dict) -> bool:
        """Adopt a peer's effective limits when its revision is newer.
        Unknown tenant names are skipped (registries may briefly skew
        during a rollout); clamping re-applies locally."""
        try:
            rev = int(payload.get("revision", 0))
            eff = dict(payload.get("effective") or {})
        except Exception:
            return False
        if rev <= self.revision:
            return False
        for scope, lim in eff.items():
            if scope != GLOBAL and scope not in self._tenants:
                continue
            try:
                self.set_effective(scope, int(lim))
            except (InvalidConfigError, ValueError, TypeError):
                continue
        # Adoption lands exactly AT the peer's revision — the per-scope
        # set_effective bumps above must not inflate it past rev, or
        # this member would reject the origin's NEXT move (rev+1) and
        # its own re-announce would roll the fleet back to these values.
        self.revision = rev
        return True

    # -------------------------------------------------------- host arrays

    def _invalidate(self) -> None:
        self.version += 1
        self._host_arrays = None

    def host_arrays(self) -> Dict[str, np.ndarray]:
        """Padded device-table columns: sorted key→tenant map
        ({key, tid}) plus per-scope {limit, weight} with the global
        scope at index ``capacity``. Limits are EFFECTIVE, divided by
        this unit's share divisor; uncapped scopes carry the
        HIER_UNLIMITED sentinel. Rebuilt lazily per version."""
        if self._host_arrays is not None:
            return self._host_arrays
        keys = np.full(self.map_capacity, pk.PAD_KEY, dtype=np.int64)
        tids = np.zeros(self.map_capacity, dtype=np.int64)
        items = sorted((self._skey[k], self._tenants[t].tid)
                       for k, t in self._assign.items())
        for i, (sk, tid) in enumerate(items):
            keys[i] = sk
            tids[i] = tid
        T = self.capacity
        limits = np.full(T + 1, HIER_UNLIMITED, dtype=np.int64)
        weights = np.ones(T + 1, dtype=np.int64)
        for name, t in self._tenants.items():
            eff = self.effective_of(name)
            limits[t.tid] = (eff if eff >= HIER_UNLIMITED
                             else max(1, eff // self.divisor))
            weights[t.tid] = t.weight
        geff = self.effective_of(GLOBAL)
        limits[T] = (geff if geff >= HIER_UNLIMITED
                     else max(1, geff // self.divisor))
        self._host_arrays = {"key": keys, "tid": tids,
                             "limit": limits, "weight": weights}
        return self._host_arrays

    # ---------------------------------------------------------- snapshot

    def snapshot_arrays(self) -> Dict[str, np.ndarray]:
        """Checkpoint columns (prefix ``hier_``): tenant definitions,
        controller-moved effective limits (-1 = tracking the ceiling),
        key assignments, and the effective-limit revision."""
        names = sorted(self._tenants)
        recs = [self._tenants[n] for n in names]
        eff = [self._eff.get(t.tid, -1) for t in recs]
        assigns = self.assignments()
        return {
            "hier_tenant_names": np.array(names, dtype=str),
            "hier_tenant_tids": np.array([t.tid for t in recs], np.int64),
            "hier_tenant_limits": np.array([t.limit for t in recs],
                                           np.int64),
            "hier_tenant_weights": np.array([t.weight for t in recs],
                                            np.int64),
            "hier_tenant_floors": np.array([t.floor for t in recs],
                                           np.int64),
            "hier_tenant_eff": np.array(eff, np.int64),
            "hier_assign_keys": np.array([k for k, _ in assigns],
                                         dtype=str),
            "hier_assign_tenants": np.array([t for _, t in assigns],
                                            dtype=str),
            "hier_meta": np.array(
                [self._glimit, self._eff.get(GLOBAL, -1), self.revision],
                np.int64),
        }

    def restore_arrays(self, arrays: Dict[str, np.ndarray]) -> None:
        """Consume (pop) the ``hier_*`` columns from a checkpoint's array
        dict; absent columns (a pre-hierarchy snapshot restored into a
        hierarchy-enabled config cannot happen — the fingerprint differs
        — but slice sub-dicts may share one combined set) leave the
        construction-time registry untouched."""
        names = arrays.pop("hier_tenant_names", None)
        tids = arrays.pop("hier_tenant_tids", None)
        limits = arrays.pop("hier_tenant_limits", None)
        weights = arrays.pop("hier_tenant_weights", None)
        floors = arrays.pop("hier_tenant_floors", None)
        eff = arrays.pop("hier_tenant_eff", None)
        akeys = arrays.pop("hier_assign_keys", None)
        atenants = arrays.pop("hier_assign_tenants", None)
        meta = arrays.pop("hier_meta", None)
        if names is None:
            return
        self._tenants.clear()
        self._names = [None] * self.capacity
        self._eff.clear()
        self._assign.clear()
        self._skey.clear()
        self._by_skey.clear()
        recs = sorted(
            zip([str(x) for x in names],
                np.asarray(tids, np.int64).tolist(),
                np.asarray(limits, np.int64).tolist(),
                np.asarray(weights, np.int64).tolist(),
                np.asarray(floors, np.int64).tolist(),
                np.asarray(eff, np.int64).tolist()),
            key=lambda r: r[1])
        for name, tid, lim, wgt, fl, ef in recs:
            if tid >= self.capacity:
                raise InvalidConfigError(
                    f"snapshot tenant {name!r} has tid {tid} outside this "
                    f"config's capacity {self.capacity}")
            self._tenants[name] = Tenant(tid=tid, limit=lim, weight=wgt,
                                         floor=fl)
            self._names[tid] = name
            if ef >= 0:
                self._eff[tid] = ef
        if meta is not None:
            glimit, geff, rev = np.asarray(meta, np.int64).tolist()[:3]
            self._glimit = int(glimit)
            if geff >= 0:
                self._eff[GLOBAL] = int(geff)
            self.revision = int(rev)
        if akeys is not None:
            for k, t in zip([str(x) for x in akeys],
                            [str(x) for x in atenants]):
                if t in self._tenants:
                    self.assign(k, t)
        self._invalidate()
