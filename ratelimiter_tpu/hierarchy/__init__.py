"""Hierarchical cascades + adaptive control (ADR-020).

``tenants``    — the host-authoritative tenant registry + key→tenant map
                 (the cascade's control plane; device half in
                 ops/hier_kernels.py).
``controller`` — the AIMD loop closing ROADMAP item 3: tightens/relaxes
                 *effective* scope limits off the live observatory
                 signals (ADR-016 audit rates, SLO burn, per-tenant
                 in-window mass) and publishes them through the existing
                 update machinery so mesh slices and fleet members
                 converge.
``fanout``     — write-all/read-one/sum-stats facade over the native
                 door's per-shard limiter list (the serving mount).
"""

from ratelimiter_tpu.hierarchy.controller import AIMDController, AIMDGains
from ratelimiter_tpu.hierarchy.fanout import HierarchyFanout
from ratelimiter_tpu.hierarchy.tenants import GLOBAL, Tenant, TenantTable

__all__ = [
    "AIMDController",
    "AIMDGains",
    "GLOBAL",
    "HierarchyFanout",
    "Tenant",
    "TenantTable",
]
