"""Checkpoint / restore of limiter state.

The reference gets durability for free: state lives server-side in Redis
and outlives the Go process, bounded by TTLs (``fixedwindow.go:151``,
``docs/ADR/001:51-52`` — losing Redis loses all counters). Here state
lives in HBM and dies with the process, so snapshot/restore is explicit
(SURVEY.md §5.4).

Format: one ``.npz`` holding the state arrays plus a JSON header with a
format version, a backend kind tag, and a **config fingerprint** — restore
refuses a snapshot taken under a different algorithm/limit/window/geometry
(the arrays would be reinterpreted silently otherwise).

Staleness semantics (documented contract, tested in
tests/test_checkpoint.py):

* decisions made after the snapshot are lost on restore — the restored
  limiter *under*-counts the crash window, so errors are toward ALLOWING,
  exactly the reference's "losing Redis = losing counters" posture and
  the right direction for availability;
* elapsed wall time between save and restore needs no special handling:
  every backend keys its state off absolute host timestamps, so the first
  post-restore dispatch applies the usual catch-up (sketch: sub-window
  rollover sweep masks out expired slabs; token bucket: decay/refill from
  the restored ``last``; dense/exact windows: lazy window roll). A
  snapshot restored after >= 1 full window therefore behaves like a fresh
  limiter, as it must.
"""

from __future__ import annotations

import hashlib
import itertools
import io
import json
import os
from dataclasses import asdict
from typing import Any, Dict, Tuple

import numpy as np

from ratelimiter_tpu.core.config import Config
from ratelimiter_tpu.core.errors import CheckpointError

FORMAT_VERSION = 1
_META_KEY = "__ratelimiter_tpu_meta__"
_tmp_counter = itertools.count()


def config_fingerprint(config: Config) -> str:
    """Stable hash over every semantic config field (dataclass fields are
    all plain values, so the sorted-JSON of asdict is canonical).

    The ``persistence`` spec is excluded: snapshot cadence / fsync policy
    are operational knobs, and a snapshot taken at one cadence must
    restore under another. ``sketch.kernels`` is excluded for the same
    reason (ADR-011): the Pallas/jnp selection changes WHICH compiled
    kernels decide, not what the state means — the two paths are pinned
    bit-identical, so a snapshot taken under either must restore under
    the other. ``mesh`` (slice-parallel placement, ADR-012) is excluded
    too: the device count is where state lives, not what it means — the
    per-slice-count refusal lives in SlicedMeshLimiter.restore, which
    can NAME the mismatch instead of reporting an opaque fingerprint
    diff. Every OTHER field participates — changing this function's
    output strands every existing snapshot, which is why
    tests/test_checkpoint.py pins a golden value.
    """
    fields = asdict(config)
    fields.pop("persistence", None)
    fields.pop("mesh", None)
    if isinstance(fields.get("sketch"), dict):
        fields["sketch"].pop("kernels", None)
    h = fields.get("hierarchy")
    if isinstance(h, dict) and not h.get("tenants"):
        # Hierarchy disabled is the pre-ADR-020 world: dropping the spec
        # keeps every existing snapshot's fingerprint (golden pinned).
        # When ENABLED, the cascade geometry shapes the tn_* state
        # arrays, so it must participate like any other geometry field.
        fields.pop("hierarchy", None)
    payload = json.dumps(
        {**fields, "algorithm": str(config.algorithm)},
        sort_keys=True, default=str)
    return hashlib.sha256(payload.encode()).hexdigest()[:32]


def fsync_dir(path: str) -> None:
    """fsync a directory so a just-renamed entry survives power loss
    (the rename itself lives in the directory's metadata). Best-effort:
    some filesystems/platforms refuse O_RDONLY fsync on directories."""
    try:
        fd = os.open(path if path else ".", os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


def write_atomic(path: str, data: bytes) -> None:
    """Crash-atomic file write: tmp + fsync(file) + os.replace + fsync(dir).
    A crash at ANY point leaves either the old file or the new one, never
    a torn mix; after return the bytes are on stable storage."""
    # Unique per call, not just per process: concurrent writers to the
    # same path would otherwise share one tmp name and steal each
    # other's file out from under os.replace (last replace wins either
    # way; both must survive).
    tmp = f"{path}.tmp.{os.getpid()}.{next(_tmp_counter)}"
    try:
        with open(tmp, "wb") as f:
            f.write(data)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise
    fsync_dir(os.path.dirname(os.path.abspath(path)))


def save_state(path: str, kind: str, config: Config,
               arrays: Dict[str, np.ndarray], extra: Dict[str, Any]) -> None:
    """Crash-atomic snapshot write (see write_atomic): a crash mid-save
    never corrupts the previous snapshot, and a completed save survives
    power loss (file and directory entry both fsynced)."""
    meta = {
        "format_version": FORMAT_VERSION,
        "kind": kind,
        "config_fingerprint": config_fingerprint(config),
        **extra,
    }
    if _META_KEY in arrays:
        raise CheckpointError(f"array name {_META_KEY!r} is reserved")
    buf = io.BytesIO()
    np.savez(buf, **arrays,
             **{_META_KEY: np.frombuffer(
                 json.dumps(meta).encode(), dtype=np.uint8)})
    write_atomic(path, buf.getvalue())


def load_state(path: str, kind: str, config: Config,
               ) -> Tuple[Dict[str, np.ndarray], Dict[str, Any]]:
    """Load + validate a snapshot for the given limiter kind and config."""
    with np.load(path, allow_pickle=False) as z:
        arrays = {k: z[k] for k in z.files if k != _META_KEY}
        if _META_KEY not in z.files:
            raise CheckpointError(f"{path}: not a ratelimiter_tpu checkpoint")
        meta = json.loads(bytes(z[_META_KEY]).decode())
    if meta.get("format_version") != FORMAT_VERSION:
        raise CheckpointError(
            f"{path}: format version {meta.get('format_version')} != "
            f"{FORMAT_VERSION}")
    if meta.get("kind") != kind:
        raise CheckpointError(
            f"{path}: snapshot kind {meta.get('kind')!r} cannot restore a "
            f"{kind!r} limiter")
    fp = config_fingerprint(config)
    if meta.get("config_fingerprint") != fp:
        raise CheckpointError(
            f"{path}: config fingerprint mismatch — snapshot was taken "
            "under a different algorithm/limit/window/geometry")
    return arrays, meta
