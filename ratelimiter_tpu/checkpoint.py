"""Checkpoint / restore of limiter state.

The reference gets durability for free: state lives server-side in Redis
and outlives the Go process, bounded by TTLs (``fixedwindow.go:151``,
``docs/ADR/001:51-52`` — losing Redis loses all counters). Here state
lives in HBM and dies with the process, so snapshot/restore is explicit
(SURVEY.md §5.4).

Format: one ``.npz`` holding the state arrays plus a JSON header with a
format version, a backend kind tag, and a **config fingerprint** — restore
refuses a snapshot taken under a different algorithm/limit/window/geometry
(the arrays would be reinterpreted silently otherwise).

Staleness semantics (documented contract, tested in
tests/test_checkpoint.py):

* decisions made after the snapshot are lost on restore — the restored
  limiter *under*-counts the crash window, so errors are toward ALLOWING,
  exactly the reference's "losing Redis = losing counters" posture and
  the right direction for availability;
* elapsed wall time between save and restore needs no special handling:
  every backend keys its state off absolute host timestamps, so the first
  post-restore dispatch applies the usual catch-up (sketch: sub-window
  rollover sweep masks out expired slabs; token bucket: decay/refill from
  the restored ``last``; dense/exact windows: lazy window roll). A
  snapshot restored after >= 1 full window therefore behaves like a fresh
  limiter, as it must.
"""

from __future__ import annotations

import hashlib
import itertools
import io
import json
import os
from dataclasses import asdict
from typing import Any, Dict, Tuple

import numpy as np

from ratelimiter_tpu.core.config import Config
from ratelimiter_tpu.core.errors import CheckpointError

FORMAT_VERSION = 1
_META_KEY = "__ratelimiter_tpu_meta__"
_tmp_counter = itertools.count()


def config_fingerprint(config: Config) -> str:
    """Stable hash over every semantic config field (dataclass fields are
    all plain values, so the sorted-JSON of asdict is canonical)."""
    payload = json.dumps(
        {**asdict(config), "algorithm": str(config.algorithm)},
        sort_keys=True, default=str)
    return hashlib.sha256(payload.encode()).hexdigest()[:32]


def save_state(path: str, kind: str, config: Config,
               arrays: Dict[str, np.ndarray], extra: Dict[str, Any]) -> None:
    """Atomic write (tmp + rename): a crash mid-save never corrupts the
    previous snapshot."""
    meta = {
        "format_version": FORMAT_VERSION,
        "kind": kind,
        "config_fingerprint": config_fingerprint(config),
        **extra,
    }
    if _META_KEY in arrays:
        raise CheckpointError(f"array name {_META_KEY!r} is reserved")
    buf = io.BytesIO()
    np.savez(buf, **arrays,
             **{_META_KEY: np.frombuffer(
                 json.dumps(meta).encode(), dtype=np.uint8)})
    # Unique per call, not just per process: concurrent save() calls to
    # the same path would otherwise share one tmp name and steal each
    # other's file out from under os.replace (last replace wins either
    # way; both must survive).
    tmp = f"{path}.tmp.{os.getpid()}.{next(_tmp_counter)}"
    with open(tmp, "wb") as f:
        f.write(buf.getvalue())
    os.replace(tmp, path)


def load_state(path: str, kind: str, config: Config,
               ) -> Tuple[Dict[str, np.ndarray], Dict[str, Any]]:
    """Load + validate a snapshot for the given limiter kind and config."""
    with np.load(path, allow_pickle=False) as z:
        arrays = {k: z[k] for k in z.files if k != _META_KEY}
        if _META_KEY not in z.files:
            raise CheckpointError(f"{path}: not a ratelimiter_tpu checkpoint")
        meta = json.loads(bytes(z[_META_KEY]).decode())
    if meta.get("format_version") != FORMAT_VERSION:
        raise CheckpointError(
            f"{path}: format version {meta.get('format_version')} != "
            f"{FORMAT_VERSION}")
    if meta.get("kind") != kind:
        raise CheckpointError(
            f"{path}: snapshot kind {meta.get('kind')!r} cannot restore a "
            f"{kind!r} limiter")
    fp = config_fingerprint(config)
    if meta.get("config_fingerprint") != fp:
        raise CheckpointError(
            f"{path}: config fingerprint mismatch — snapshot was taken "
            "under a different algorithm/limit/window/geometry")
    return arrays, meta
