"""Deterministic, seeded placement planner (ADR-023).

``plan_moves`` is a PURE function: (ownership map, per-bucket load
vector, liveness, frozen set, knobs, seed) → bounded migration plan.
Same inputs → byte-identical plan (``Plan.to_dict`` round-trips through
``json.dumps(..., sort_keys=True)`` to the same bytes) — the property
the determinism test pins, and the property that lets every member run
the planner independently: identical views plan identical moves, and
each member executes only the moves it donates, so no leader election
is needed.

Algorithm — greedy max/mean imbalance reduction:

1. Per-host load = sum of the bucket load vector over owned buckets,
   alive hosts only. ``imbalance = max(load) / mean(load)``.
2. Hysteresis: plan only when imbalance ≥ ``trigger_ratio``; plan
   *down to* ``target_ratio`` (a strictly lower bar), so a fleet
   hovering at the trigger doesn't flap move/counter-move.
3. Up to ``max_moves`` times: pick the most-loaded alive donor and the
   least-loaded alive receiver (ties break on host id — determinism),
   and carve the donor sub-range whose mass best matches
   ``min(donor − mean, mean − receiver)``. Candidate windows are
   contiguous runs inside the donor's owned ranges that avoid frozen
   (min-residency cooldown) buckets; a move must improve projected
   imbalance by ``min_gain`` or planning stops.
4. Stop early once projected imbalance ≤ ``target_ratio``.

The planner never plans for dead hosts (failover owns that, ADR-017)
and never moves a bucket still inside its residency cooldown — the
executor stamps moved buckets, so a range settles before it is
eligible to move again (flap prevention).
"""

from __future__ import annotations

import hashlib
import json
from dataclasses import asdict, dataclass, field
from typing import Dict, FrozenSet, Iterable, List, Optional, Sequence, Tuple

import numpy as np

from ratelimiter_tpu.fleet.config import FleetMap


@dataclass(frozen=True)
class PlannerKnobs:
    """Flap-prevention levers (see OPERATIONS §14)."""

    max_moves: int = 2            # move budget per planning cycle
    trigger_ratio: float = 1.4    # act only when imbalance >= this
    target_ratio: float = 1.15    # plan down toward this (hysteresis)
    min_gain: float = 0.02        # required imbalance drop per move
    window_overshoot: float = 1.25  # moved mass may exceed want by this
    min_residency_s: float = 60.0   # cooldown stamped by the executor

    def to_dict(self) -> dict:
        return {k: (round(v, 6) if isinstance(v, float) else v)
                for k, v in asdict(self).items()}


@dataclass
class Plan:
    """A bounded migration plan; ``plan_id`` doubles as the journal
    correlation id (one id per plan, every move event carries it)."""

    plan_id: str
    epoch: int
    reason: str
    imbalance_before: float
    imbalance_projected: float
    moves: List[dict] = field(default_factory=list)
    seed: int = 0
    knobs: dict = field(default_factory=dict)
    loads_before: Dict[str, float] = field(default_factory=dict)
    loads_projected: Dict[str, float] = field(default_factory=dict)

    @property
    def corr(self) -> int:
        return int(self.plan_id, 16)

    def to_dict(self) -> dict:
        return {
            "plan_id": self.plan_id,
            "epoch": self.epoch,
            "reason": self.reason,
            "imbalance_before": self.imbalance_before,
            "imbalance_projected": self.imbalance_projected,
            "moves": list(self.moves),
            "seed": self.seed,
            "knobs": dict(self.knobs),
            "loads_before": dict(self.loads_before),
            "loads_projected": dict(self.loads_projected),
        }


def _host_loads(fmap: FleetMap, rate: np.ndarray,
                alive: Iterable[str]) -> Dict[str, float]:
    alive = set(alive)
    loads: Dict[str, float] = {}
    for h in fmap.hosts:
        if h.id not in alive:
            continue
        s = 0.0
        for lo, hi in h.ranges:
            s += float(rate[lo:hi].sum())
        loads[h.id] = s
    return loads


def _imbalance(loads: Dict[str, float]) -> float:
    if not loads:
        return 1.0
    mean = sum(loads.values()) / len(loads)
    if mean <= 0.0:
        return 1.0
    return max(loads.values()) / mean


def _segments(fmap: FleetMap, host_id: str,
              frozen: FrozenSet[int]) -> List[Tuple[int, int]]:
    """Maximal frozen-free contiguous runs inside the host's owned
    ranges — the candidate window space."""
    segs: List[Tuple[int, int]] = []
    for lo, hi in sorted(fmap.host(host_id).ranges):
        start = lo
        for b in range(lo, hi):
            if b in frozen:
                if b > start:
                    segs.append((start, b))
                start = b + 1
        if hi > start:
            segs.append((start, hi))
    return segs


def _best_window(segs: Sequence[Tuple[int, int]], rate: np.ndarray,
                 want: float, overshoot: float
                 ) -> Optional[Tuple[int, int, float]]:
    """The contiguous window whose mass best matches ``want`` without
    exceeding ``want * overshoot``. Deterministic: iterate windows in
    (lo, hi) order, strict improvement replaces — equal scores keep
    the first (lowest lo, then shortest)."""
    cap = want * overshoot
    best: Optional[Tuple[int, int, float]] = None
    best_score = None
    for lo, hi in segs:
        n = hi - lo
        # Prefix sums make every (i, j) window O(1); the donor's bucket
        # count is map-bounded (buckets ≤ a few thousand), so the O(n²)
        # scan is planner-cadence noise, never hot-path work.
        pref = np.concatenate(([0.0],
                               np.cumsum(rate[lo:hi], dtype=np.float64)))
        for i in range(n):
            for j in range(i + 1, n + 1):
                mass = float(pref[j] - pref[i])
                over = mass > cap
                if over and j > i + 1:
                    break
                # A single bucket hotter than the cap is still a
                # candidate (there is no smaller move); the planner's
                # gain check decides whether shipping it helps.
                score = abs(mass - want)
                if best_score is None or score < best_score - 1e-12:
                    best_score = score
                    best = (lo + i, lo + j, mass)
                if over:
                    break
    return best


def plan_moves(fmap: FleetMap, bucket_rate: np.ndarray, *,
               alive: Iterable[str],
               frozen: Iterable[int] = (),
               knobs: Optional[PlannerKnobs] = None,
               seed: int = 0) -> Plan:
    """Produce a bounded, deterministic migration plan. ``bucket_rate``
    is the MERGED fleet decide rate per bucket (events/s, float64);
    ``alive`` the host ids allowed to donate or receive; ``frozen``
    buckets inside their min-residency cooldown."""
    knobs = knobs or PlannerKnobs()
    rate = np.asarray(bucket_rate, dtype=np.float64)
    if rate.shape[0] != fmap.buckets:
        raise ValueError(
            f"bucket_rate has {rate.shape[0]} entries, map has "
            f"{fmap.buckets} buckets")
    frozen_set: FrozenSet[int] = frozenset(int(b) for b in frozen)
    alive_ids = sorted(set(alive) & {h.id for h in fmap.hosts})

    digest = hashlib.sha256(json.dumps({
        "map": fmap.to_dict(),
        "rate": [round(float(v), 6) for v in rate],
        "alive": alive_ids,
        "frozen": sorted(frozen_set),
        "knobs": knobs.to_dict(),
        "seed": int(seed),
    }, sort_keys=True).encode()).hexdigest()
    plan_id = digest[:16]

    loads = _host_loads(fmap, rate, alive_ids)
    imb0 = _imbalance(loads)
    plan = Plan(plan_id=plan_id, epoch=fmap.epoch, reason="planned",
                imbalance_before=round(imb0, 4),
                imbalance_projected=round(imb0, 4),
                seed=int(seed), knobs=knobs.to_dict(),
                loads_before={k: round(v, 3) for k, v in loads.items()})

    if len(loads) < 2:
        plan.reason = "single-host"
        return plan
    if imb0 < knobs.trigger_ratio:
        plan.reason = "within-band"
        return plan

    work = fmap
    cur = dict(loads)
    mean = sum(cur.values()) / len(cur)
    imb = imb0
    for _ in range(max(0, int(knobs.max_moves))):
        donor = min(cur, key=lambda h: (-cur[h], h))
        receiver = min((h for h in cur if h != donor),
                       key=lambda h: (cur[h], h))
        want = min(cur[donor] - mean, mean - cur[receiver])
        if want <= 0.0:
            plan.reason = "converged"
            break
        segs = _segments(work, donor, frozen_set)
        win = _best_window(segs, rate, want, knobs.window_overshoot)
        if win is None:
            plan.reason = "cooldown"
            break
        lo, hi, mass = win
        if mass <= 0.0:
            plan.reason = "no-eligible-mass"
            break
        nxt = dict(cur)
        nxt[donor] -= mass
        nxt[receiver] += mass
        imb_next = _imbalance(nxt)
        if imb - imb_next < knobs.min_gain:
            plan.reason = "no-gain"
            break
        work = work.move_ranges([(lo, hi)], donor, receiver)
        cur = nxt
        imb = imb_next
        plan.moves.append({"from": donor, "to": receiver,
                           "range": [int(lo), int(hi)],
                           "rate": round(mass, 3)})
        if imb <= knobs.target_ratio:
            plan.reason = "planned"
            break
    plan.imbalance_projected = round(imb, 4)
    plan.loads_projected = {k: round(v, 3) for k, v in cur.items()}
    if plan.moves and plan.reason in ("cooldown", "no-gain",
                                      "converged", "no-eligible-mass"):
        # Partial plans still execute; the reason records why planning
        # stopped short of the budget.
        plan.reason = f"planned-{plan.reason}"
    return plan
