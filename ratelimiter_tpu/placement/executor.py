"""RebalanceController: the placement executor (ADR-023).

Every ``--rebalance`` member runs the same loop: gather the fleet's
per-bucket decide rates (own slab + peers' ``/healthz`` placement
blocks over the ADR-021 tower fetch), run the deterministic planner,
and execute ONLY the moves this member donates — each range has exactly
one owner, so identically-planning members never collide, and no
leader election is needed. Moves go through the existing
``migrate_ranges`` handoff one at a time, inheriting ADR-018's
never-over-admission and single-owner-per-epoch invariants (and its
chaos behavior: an aborted handoff leaves ownership unchanged; the next
cycle replans from the real map).

Safety discipline (the ADR-020 veto, applied to *placement*):

* before every move the controller reads the observatory — SLO burn
  above ``burn_abort`` or a false-deny Wilson upper bound above
  ``false_deny_veto`` aborts the rest of the plan (journaled, with the
  signal snapshot);
* pacing is AIMD: a veto or failed move MULTIPLIES the inter-cycle
  pace (backoff), every clean move additively recovers toward 1×;
* moved buckets get a min-residency stamp — the planner refuses to
  move them again until the cooldown expires (no flapping);
* any alive-but-unreachable member means the load view is partial: the
  cycle is SKIPPED, never planned on a guess.

One correlation id per plan (= the plan id), carried by every
plan/move/abort/veto event in the journal — ``/debug/events?fleet=1``
reconstructs a rebalance end to end.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Callable, Dict, Optional

import numpy as np

from ratelimiter_tpu.observability import events
from ratelimiter_tpu.placement.planner import Plan, PlannerKnobs, plan_moves

log = logging.getLogger("ratelimiter_tpu.placement")


class RebalanceController:
    """Plans and paces load-driven range moves for ONE fleet member."""

    def __init__(self, core, membership, slab, *,
                 interval: float = 10.0,
                 knobs: Optional[PlannerKnobs] = None,
                 seed: int = 0,
                 move_wait: float = 15.0,
                 fetch_peer_health: Optional[Callable[[], Dict[str, Optional[dict]]]] = None,
                 slo_status: Optional[Callable[[], dict]] = None,
                 audit_status: Optional[Callable[[], dict]] = None,
                 burn_abort: float = 2.0,
                 false_deny_veto: float = 0.05,
                 max_pace: float = 16.0,
                 registry=None,
                 clock: Callable[[], float] = time.monotonic):
        self.core = core
        self.membership = membership
        self.slab = slab
        self.interval = float(interval)
        self.knobs = knobs or PlannerKnobs()
        self.seed = int(seed)
        self.move_wait = float(move_wait)
        self.fetch_peer_health = fetch_peer_health
        self.slo_status = slo_status
        self.audit_status = audit_status
        self.burn_abort = float(burn_abort)
        self.false_deny_veto = float(false_deny_veto)
        self.max_pace = float(max_pace)
        self._clock = clock
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._abort = threading.Event()
        self._hold = False
        self._residency: Dict[int, float] = {}
        self._state = "idle"
        self._last_plan: Optional[dict] = None
        self._last_skip = ""
        self.pace = 1.0
        self.cycles = 0
        self.plans = 0
        self.moves_ok = 0
        self.moves_failed = 0
        self.vetoes = 0
        self.aborts = 0
        self._g_imb = self._g_pace = None
        self._c_plans = self._c_moves = self._c_vetoes = None
        if registry is not None:
            self._g_imb = registry.gauge(
                "rate_limiter_placement_imbalance",
                "Fleet max/mean per-host decision-load imbalance as "
                "seen by this member's planner (1.0 = balanced)")
            self._g_pace = registry.gauge(
                "rate_limiter_placement_pace",
                "Rebalance pacing multiplier (AIMD: vetoes/failures "
                "multiply, clean moves additively recover toward 1)")
            self._c_plans = registry.counter(
                "rate_limiter_placement_plans_total",
                "Placement plans produced, by outcome reason")
            self._c_moves = registry.counter(
                "rate_limiter_placement_moves_total",
                "Range moves this member donated under a placement "
                "plan, by result")
            self._c_vetoes = registry.counter(
                "rate_limiter_placement_vetoes_total",
                "Placement moves vetoed/aborted by the observatory "
                "(SLO burn, false-deny bound) or the operator")
            self._g_imb.set(1.0)
            self._g_pace.set(1.0)

    # ---------------------------------------------------------- signals

    def _burn(self) -> float:
        if self.slo_status is None:
            return 0.0
        try:
            windows = (self.slo_status() or {}).get("windows") or {}
            if not windows:
                return 0.0
            key = min(windows, key=lambda k: float(k.rstrip("s")))
            return float(windows[key].get("burn_rate", 0.0))
        except Exception:  # noqa: BLE001 — a signal, not a dependency
            log.exception("rebalance: slo_status failed; treating as 0")
            return 0.0

    def _false_deny_hi(self) -> float:
        if self.audit_status is None:
            return 0.0
        try:
            st = self.audit_status() or {}
            return float((st.get("false_deny_wilson95") or [0, 0])[1])
        except Exception:  # noqa: BLE001
            log.exception("rebalance: audit_status failed; treating as 0")
            return 0.0

    def _signals(self) -> dict:
        burn = self._burn()
        fd_hi = self._false_deny_hi()
        return {"burn_rate": round(burn, 4),
                "false_deny_wilson_high": round(fd_hi, 6),
                "vetoed": bool(burn >= self.burn_abort
                               or fd_hi > self.false_deny_veto)}

    # ------------------------------------------------------ load gather

    def frozen_now(self) -> set:
        now = self._clock()
        with self._lock:
            expired = [b for b, t in self._residency.items() if t <= now]
            for b in expired:
                del self._residency[b]
            return set(self._residency)

    def _stamp_residency(self, lo: int, hi: int) -> None:
        until = self._clock() + self.knobs.min_residency_s
        with self._lock:
            for b in range(lo, hi):
                self._residency[b] = until

    def gather(self) -> dict:
        """One merged load view: own slab + every peer's ``/healthz``
        placement block. Returns ``{"rate", "alive", "gaps"}`` —
        ``gaps`` non-empty means an ALIVE member's load is unknown and
        the cycle must not plan."""
        fmap = self.core.map
        rate = self.slab.rates()
        if rate.shape[0] != fmap.buckets:  # pragma: no cover — config
            raise RuntimeError("load slab does not match map buckets")
        alive = {self.core.self_id}
        gaps = []
        peers_alive = {
            hid: st["alive"] for hid, st in
            (self.membership.status()["peers"] if self.membership
             else {}).items()}
        fetched = (self.fetch_peer_health() if self.fetch_peer_health
                   else {})
        for h in fmap.hosts:
            if h.id == self.core.self_id:
                continue
            if not peers_alive.get(h.id, False):
                continue  # dead peers are failover's problem (ADR-017)
            alive.add(h.id)
            blk = (fetched.get(h.id) or {}).get("placement") or {}
            dr = np.asarray(blk.get("decide_rate", ()),
                            dtype=np.float64)
            if dr.shape[0] != fmap.buckets:
                gaps.append(h.id)
                continue
            rate = rate + dr
        return {"rate": rate, "alive": alive, "gaps": gaps}

    # -------------------------------------------------------------- plan

    def dry_run(self) -> dict:
        """Plan from the live view without executing — the operator
        preview (`POST /v1/fleet/rebalance?action=dry-run`)."""
        view = self.gather()
        if view["gaps"]:
            return {"ok": False, "reason": "load-gap",
                    "gaps": view["gaps"]}
        plan = plan_moves(self.core.map, view["rate"],
                          alive=view["alive"],
                          frozen=self.frozen_now(),
                          knobs=self.knobs, seed=self.seed)
        if self._g_imb is not None:
            self._g_imb.set(plan.imbalance_before)
        return {"ok": True, "plan": plan.to_dict(),
                "signals": self._signals()}

    # ----------------------------------------------------------- execute

    def _execute(self, plan: Plan) -> int:
        """Execute this member's donated moves, one handoff at a time,
        veto-checked before each. Returns the number that flipped."""
        mine = [m for m in plan.moves
                if m["from"] == self.core.self_id]
        if not mine:
            return 0
        done = 0
        for mv in mine:
            if self._abort.is_set() or self._stop.is_set():
                self.aborts += 1
                if self._c_vetoes is not None:
                    self._c_vetoes.inc()
                events.emit("placement", "plan-aborted",
                            actor=self.core.self_id, corr=plan.corr,
                            severity="warning",
                            payload={"plan_id": plan.plan_id,
                                     "cause": "operator-abort",
                                     "moves_done": done,
                                     "moves_left": len(mine) - done})
                break
            sig = self._signals()
            if sig["vetoed"]:
                self.vetoes += 1
                if self._c_vetoes is not None:
                    self._c_vetoes.inc()
                self.pace = min(self.max_pace, self.pace * 2.0)
                events.emit("placement", "move-vetoed",
                            actor=self.core.self_id, corr=plan.corr,
                            severity="warning",
                            payload={"plan_id": plan.plan_id,
                                     "move": dict(mv), **sig})
                log.warning(
                    "rebalance: plan %s vetoed before move %s (burn=%s "
                    "fd_hi=%s); pace -> %.2fx", plan.plan_id, mv,
                    sig["burn_rate"], sig["false_deny_wilson_high"],
                    self.pace)
                break
            lo, hi = mv["range"]
            self._state = "moving"
            ok = False
            try:
                ok = self.membership.migrate_ranges(
                    [(int(lo), int(hi))], mv["to"],
                    reason="rebalance", wait=self.move_wait)
            except Exception:  # noqa: BLE001 — a failed move is a
                # journaled fact and a replan, never a dead controller.
                log.exception("rebalance: move %s failed", mv)
            if ok:
                done += 1
                self.moves_ok += 1
                self._stamp_residency(int(lo), int(hi))
                self.pace = max(1.0, self.pace - 0.25)
                if self._c_moves is not None:
                    self._c_moves.inc(result="ok")
                events.emit("placement", "move",
                            actor=self.core.self_id, corr=plan.corr,
                            payload={"plan_id": plan.plan_id,
                                     "move": dict(mv),
                                     "epoch": self.core.map.epoch,
                                     **sig})
            else:
                self.moves_failed += 1
                self.pace = min(self.max_pace, self.pace * 2.0)
                if self._c_moves is not None:
                    self._c_moves.inc(result="failed")
                events.emit("placement", "move-failed",
                            actor=self.core.self_id, corr=plan.corr,
                            severity="warning",
                            payload={"plan_id": plan.plan_id,
                                     "move": dict(mv), **sig})
                # The map may have moved under us (lost a canonical-key
                # race, concurrent failover): replan from reality.
                break
        if self._g_pace is not None:
            self._g_pace.set(self.pace)
        return done

    def run_cycle(self, *, force: bool = False) -> dict:
        """One gather → plan → execute cycle (the background loop body;
        also the operator ``apply``, which sets ``force`` to override a
        hold)."""
        self.cycles += 1
        if self._hold and not force:
            self._state = "held"
            return {"ok": True, "state": "held"}
        self._abort.clear()
        self._state = "planning"
        view = self.gather()
        if view["gaps"]:
            self._state = "idle"
            self._last_skip = f"load-gap:{','.join(view['gaps'])}"
            if self._c_plans is not None:
                self._c_plans.inc(reason="load-gap")
            return {"ok": False, "reason": "load-gap",
                    "gaps": view["gaps"]}
        plan = plan_moves(self.core.map, view["rate"],
                          alive=view["alive"],
                          frozen=self.frozen_now(),
                          knobs=self.knobs, seed=self.seed)
        self._last_skip = ""
        self._last_plan = plan.to_dict()
        if self._g_imb is not None:
            self._g_imb.set(plan.imbalance_before)
        if self._c_plans is not None:
            self._c_plans.inc(reason=plan.reason)
        if not plan.moves:
            self._state = "idle"
            return {"ok": True, "plan": plan.to_dict(), "executed": 0}
        self.plans += 1
        events.emit("placement", "plan", actor=self.core.self_id,
                    corr=plan.corr,
                    payload={"plan_id": plan.plan_id,
                             "reason": plan.reason,
                             "imbalance_before": plan.imbalance_before,
                             "imbalance_projected":
                                 plan.imbalance_projected,
                             "moves": list(plan.moves),
                             **self._signals()})
        log.info("rebalance: plan %s imbalance %.2fx -> %.2fx, "
                 "%d move(s)", plan.plan_id, plan.imbalance_before,
                 plan.imbalance_projected, len(plan.moves))
        executed = self._execute(plan)
        self._state = "idle"
        return {"ok": True, "plan": plan.to_dict(),
                "executed": executed}

    # --------------------------------------------------- operator verbs

    def abort(self) -> dict:
        """Operator abort: stop the in-flight plan between moves AND
        hold automatic planning until the next ``apply``."""
        self._abort.set()
        with self._lock:
            self._hold = True
        self.aborts += 1
        if self._c_vetoes is not None:
            self._c_vetoes.inc()
        events.emit("placement", "abort", actor="operator",
                    severity="warning",
                    payload={"state": self._state})
        return {"ok": True, "held": True}

    def apply(self) -> dict:
        """Operator apply: clear any hold and run one cycle NOW."""
        with self._lock:
            self._hold = False
        return self.run_cycle(force=True)

    # ------------------------------------------------------------ thread

    def start(self) -> None:
        if self._thread is not None:
            return
        self._stop.clear()
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="rl-rebalance")
        self._thread.start()

    def _run(self) -> None:
        while not self._stop.wait(self.interval * self.pace):
            try:
                self.run_cycle()
            except Exception:  # noqa: BLE001 — keep planning
                log.exception("rebalance cycle failed")

    def stop(self) -> None:
        self._stop.set()
        self._abort.set()
        t = self._thread
        if t is not None:
            t.join(timeout=5.0)
            self._thread = None

    def status(self) -> dict:
        with self._lock:
            hold = self._hold
            frozen = len(self._residency)
        return {
            "state": self._state,
            "held": hold,
            "interval_s": self.interval,
            "pace": round(self.pace, 3),
            "cycles": self.cycles,
            "plans": self.plans,
            "moves_ok": self.moves_ok,
            "moves_failed": self.moves_failed,
            "vetoes": self.vetoes,
            "aborts": self.aborts,
            "frozen_buckets": frozen,
            "last_skip": self._last_skip,
            "last_plan": self._last_plan,
            "knobs": self.knobs.to_dict(),
            "seed": self.seed,
        }
