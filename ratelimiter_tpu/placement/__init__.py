"""Load-aware placement (ADR-023): the fleet rebalancing brain.

ADR-018 built the complete live-migration *mechanism* (capture →
WAL-suffix replay → epoch flip, zero client errors); this package is
the *policy* that was the ROADMAP residual — it decides which ranges
move, when, and how fast:

* :mod:`accounting` — per-bucket decision/forward mass on the hot path
  at counter-increment cost (the bucket index is already computed for
  routing), drained at scrape cadence into EWMA rates.
* :mod:`planner` — a deterministic, seeded greedy planner that turns
  the merged fleet load view into a bounded migration plan under
  hysteresis bands and a min-residency cooldown.
* :mod:`executor` — the RebalanceController: plans execute through the
  existing ``migrate_ranges`` handoff one move at a time, with AIMD
  pacing vetoed by the ADR-016 observatory (SLO burn, false-deny
  Wilson bounds), journaled under one correlation id per plan.
"""

from ratelimiter_tpu.placement.accounting import LoadSlab, merge_placement
from ratelimiter_tpu.placement.planner import Plan, PlannerKnobs, plan_moves
from ratelimiter_tpu.placement.executor import RebalanceController

__all__ = [
    "LoadSlab",
    "merge_placement",
    "Plan",
    "PlannerKnobs",
    "plan_moves",
    "RebalanceController",
]
