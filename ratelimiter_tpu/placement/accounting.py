"""Per-bucket load accounting for placement decisions (ADR-023).

The fleet router already computes ``bucket = h64 % buckets`` for every
row it routes (fleet/config.py ``owner_of_hash``); the only *new* hot
path work is two ``np.bincount`` adds into a per-host u64 slab — the
same cost model as a metrics counter increment. Everything else
(EWMA rates, imbalance, the fleet merge) happens off the decide and
forward paths, at scrape cadence.

Semantics — chosen so the FLEET-WIDE merge counts every decision
exactly once:

* **decision mass**: rows whose owner is *this* member (it decided
  them), whether they arrived directly or were forwarded to it. Summed
  across members, each decision lands in exactly one member's slab —
  the merged per-bucket vector is the true fleet decide load.
* **forward mass**: rows this member shipped to a peer (misrouted
  ingress). A row forwarded from A to B counts forward-mass at A and
  decision-mass at B; forward mass is routing pain, not extra load.

The slab is attached to every fleet member regardless of whether the
rebalancer is enabled: any planning peer needs to see everyone's load,
and the ``/healthz`` placement block + ``rate_limiter_placement_*``
families export unconditionally for fleet members.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Dict, List, Optional

import numpy as np


class LoadSlab:
    """Per-bucket u64 decision/forward accumulators with lazy EWMA
    drains.

    ``note`` / ``note_one`` are the only hot-path entry points; they
    do two bounded bincount adds under a lock. ``snapshot`` drains the
    accumulators into per-bucket EWMA rates (events/s) whenever at
    least ``min_drain_s`` has elapsed — the scrape/healthz cadence is
    the drain cadence, no extra thread.
    """

    def __init__(self, buckets: int, *, ewma_halflife_s: float = 10.0,
                 min_drain_s: float = 0.25, registry=None,
                 clock: Callable[[], float] = time.monotonic):
        if buckets <= 0:
            raise ValueError(f"buckets must be positive, got {buckets}")
        self.buckets = int(buckets)
        self.halflife = float(ewma_halflife_s)
        self.min_drain_s = float(min_drain_s)
        self._clock = clock
        self._lock = threading.Lock()
        self._dec = np.zeros(self.buckets, dtype=np.uint64)
        self._fwd = np.zeros(self.buckets, dtype=np.uint64)
        self._dec_last = np.zeros(self.buckets, dtype=np.uint64)
        self._fwd_last = np.zeros(self.buckets, dtype=np.uint64)
        self._dec_rate = np.zeros(self.buckets, dtype=np.float64)
        self._fwd_rate = np.zeros(self.buckets, dtype=np.float64)
        self._drained_at = clock()
        self._started_at = clock()
        self._c_dec = self._c_fwd = None
        if registry is not None:
            self._c_dec = registry.counter(
                "rate_limiter_placement_decide_mass_total",
                "Rows decided by this member (placement load "
                "accounting, ADR-023)")
            self._c_fwd = registry.counter(
                "rate_limiter_placement_forward_mass_total",
                "Rows this member forwarded to a peer owner "
                "(placement load accounting, ADR-023)")

    # --------------------------------------------------------- hot path

    def note(self, buckets: np.ndarray, local: np.ndarray) -> None:
        """Account one routed frame: ``buckets`` is the int64 bucket
        index per row (already computed for routing), ``local`` the
        boolean owned-here mask per row."""
        n = int(buckets.shape[0])
        if n == 0:
            return
        nloc = int(np.count_nonzero(local))
        if nloc == n:
            dec = np.bincount(buckets, minlength=self.buckets)
            fwd = None
        elif nloc == 0:
            dec = None
            fwd = np.bincount(buckets, minlength=self.buckets)
        else:
            dec = np.bincount(buckets[local], minlength=self.buckets)
            fwd = np.bincount(buckets[~local], minlength=self.buckets)
        with self._lock:
            if dec is not None:
                self._dec += dec.astype(np.uint64)
            if fwd is not None:
                self._fwd += fwd.astype(np.uint64)
        if self._c_dec is not None and nloc:
            self._c_dec.inc(nloc)
        if self._c_fwd is not None and n - nloc:
            self._c_fwd.inc(n - nloc)

    def note_one(self, bucket: int, local: bool) -> None:
        """Scalar fast path (single-key RPCs)."""
        with self._lock:
            if local:
                self._dec[bucket] += np.uint64(1)
            else:
                self._fwd[bucket] += np.uint64(1)
        c = self._c_dec if local else self._c_fwd
        if c is not None:
            c.inc()

    # -------------------------------------------------------- cold path

    def _drain_locked(self, now: float) -> None:
        dt = now - self._drained_at
        if dt < self.min_drain_s:
            return
        d_dec = (self._dec - self._dec_last).astype(np.float64) / dt
        d_fwd = (self._fwd - self._fwd_last).astype(np.float64) / dt
        alpha = 1.0 - 0.5 ** (dt / self.halflife)
        self._dec_rate += alpha * (d_dec - self._dec_rate)
        self._fwd_rate += alpha * (d_fwd - self._fwd_rate)
        self._dec_last = self._dec.copy()
        self._fwd_last = self._fwd.copy()
        self._drained_at = now

    def snapshot(self) -> dict:
        """Drain (if due) and return the per-bucket view the planner
        and ``/healthz`` consume. Rates are EWMA events/s; totals are
        cumulative u64 (wrap-free at any realistic rate)."""
        now = self._clock()
        with self._lock:
            self._drain_locked(now)
            dec_total = int(self._dec.sum())
            fwd_total = int(self._fwd.sum())
            return {
                "buckets": self.buckets,
                "decide_total": dec_total,
                "forward_total": fwd_total,
                "decide_rate": [round(float(v), 3)
                                for v in self._dec_rate],
                "forward_rate": [round(float(v), 3)
                                 for v in self._fwd_rate],
                "halflife_s": self.halflife,
                "age_s": round(now - self._started_at, 3),
            }

    def rates(self) -> np.ndarray:
        """Drained per-bucket decide rate as float64[buckets] (a copy)."""
        now = self._clock()
        with self._lock:
            self._drain_locked(now)
            return self._dec_rate.copy()


def merge_placement(members: Dict[str, Optional[dict]]) -> dict:
    """Fleet-wide merge of per-member ``/healthz`` placement blocks
    (the ADR-021 tower calls this from ``merged_status``): sums the
    per-bucket decide/forward rates across members, carries per-member
    totals, and computes the max/mean per-host decision-load imbalance
    — the number the rebalancer drives toward 1.0.

    A member with a missing/None block is reported as a gap, never
    silently treated as idle.
    """
    buckets = 0
    for blk in members.values():
        if blk and blk.get("buckets"):
            buckets = max(buckets, int(blk["buckets"]))
    dec = np.zeros(buckets, dtype=np.float64) if buckets else None
    fwd = np.zeros(buckets, dtype=np.float64) if buckets else None
    hosts: Dict[str, dict] = {}
    gaps: List[str] = []
    for hid in sorted(members):
        blk = members[hid]
        if not blk or int(blk.get("buckets", 0)) != buckets:
            gaps.append(hid)
            continue
        dr = np.asarray(blk.get("decide_rate", ()), dtype=np.float64)
        fr = np.asarray(blk.get("forward_rate", ()), dtype=np.float64)
        if dr.shape[0] == buckets:
            dec += dr
        if fr.shape[0] == buckets:
            fwd += fr
        hosts[hid] = {
            "decide_rate": round(float(dr.sum()), 3),
            "forward_rate": round(float(fr.sum()), 3),
            "decide_total": int(blk.get("decide_total", 0)),
            "forward_total": int(blk.get("forward_total", 0)),
        }
    rates = [h["decide_rate"] for h in hosts.values()]
    mean = (sum(rates) / len(rates)) if rates else 0.0
    imbalance = (max(rates) / mean) if mean > 0 else 1.0
    return {
        "buckets": buckets,
        "hosts": hosts,
        "gaps": gaps,
        "decide_rate": [round(float(v), 3) for v in dec] if dec is not None else [],
        "forward_rate": [round(float(v), 3) for v in fwd] if fwd is not None else [],
        "imbalance": round(float(imbalance), 4),
    }
