"""Three-way decision comparison engine (offline bench + online audit).

The accuracy story of this repo is one measurement made in two places:
``evaluation/accuracy.py`` runs it OFFLINE over a synthetic trace (the
BASELINE.json metric), and ``observability/audit.py`` runs it ONLINE over
a hash-sampled tap of live traffic (ADR-016). Both consume this module so
the comparison semantics — what counts as a false deny, how the CMS error
is separated from the semantic error, how a confidence interval is put on
a sampled rate — can never drift between the bench and the observatory.

Three-way comparison (each leg isolates one error source):

* live   (the system under test)   — sketch decisions, however obtained
  (an offline SketchLimiter run, or decisions mirrored off a serving
  door);
* twin   (CMS, collision-free)     — same sub-window semantics, width so
  large that collisions are negligible: live-vs-twin disagreement is
  pure CMS (collision) error;
* oracle (dense, exact)            — exact per-key semantics:
  twin-vs-oracle disagreement is the pure semantic resolution
  difference (sub-window ring vs the reference's two-window weighting).

Both the twin and the oracle are PER-KEY EXACT in the relevant sense
(the twin has no collisions, the oracle is exact), so feeding them only
a hash-coherent SAMPLE of the keyspace leaves their verdicts for the
sampled keys unchanged — that is the property that makes the online
auditor's sampled estimate unbiased (ADR-016 §2).
"""

from __future__ import annotations

import dataclasses
import math
from typing import Optional, Tuple

import numpy as np

from ratelimiter_tpu.core.config import Config
from ratelimiter_tpu.core.types import Algorithm


def wilson_interval(k: int, n: int, z: float = 1.96) -> Tuple[float, float]:
    """Wilson score interval for a binomial proportion k/n (default 95%).

    Chosen over the normal approximation because audit sample counts are
    often small and rates are near zero — exactly where the Wald interval
    collapses to a meaningless [p, p]. Returns (0, 1) for n == 0 ("no
    evidence"), never NaN."""
    if n <= 0:
        return (0.0, 1.0)
    p = k / n
    z2 = z * z
    denom = 1.0 + z2 / n
    center = (p + z2 / (2.0 * n)) / denom
    half = (z * math.sqrt(max(p * (1.0 - p) / n + z2 / (4.0 * n * n), 0.0))
            / denom)
    return (max(0.0, center - half), min(1.0, center + half))


@dataclasses.dataclass
class ThreeWayTally:
    """Running counts of one three-way comparison stream.

    ``add`` consumes aligned boolean arrays for one batch; rates and
    Wilson bounds are derived properties so every consumer (bench JSON,
    /debug/audit, gauges) reads the same arithmetic."""

    requests: int = 0
    oracle_allows: int = 0
    oracle_denies: int = 0
    twin_allows: int = 0
    false_denies_vs_oracle: int = 0     # live denied, oracle allowed
    false_allows_vs_oracle: int = 0     # live allowed, oracle denied
    cms_false_denies_vs_twin: int = 0   # live denied, twin allowed
    semantic_disagreements: int = 0     # twin != oracle

    def add(self, live: np.ndarray, twin: Optional[np.ndarray],
            oracle: np.ndarray) -> None:
        live = np.asarray(live, dtype=bool)
        oracle = np.asarray(oracle, dtype=bool)
        self.requests += int(live.size)
        self.oracle_allows += int(oracle.sum())
        self.oracle_denies += int((~oracle).sum())
        self.false_denies_vs_oracle += int((oracle & ~live).sum())
        self.false_allows_vs_oracle += int((~oracle & live).sum())
        if twin is not None:
            twin = np.asarray(twin, dtype=bool)
            self.twin_allows += int(twin.sum())
            self.cms_false_denies_vs_twin += int((twin & ~live).sum())
            self.semantic_disagreements += int((twin != oracle).sum())

    # ----------------------------------------------------------- rates

    @property
    def false_deny_rate(self) -> float:
        """False denies over oracle allows — the BASELINE.json metric."""
        return self.false_denies_vs_oracle / max(1, self.oracle_allows)

    @property
    def false_allow_rate(self) -> float:
        return self.false_allows_vs_oracle / max(1, self.oracle_denies)

    @property
    def cms_false_deny_rate(self) -> float:
        return self.cms_false_denies_vs_twin / max(1, self.twin_allows)

    def false_deny_wilson(self, z: float = 1.96) -> Tuple[float, float]:
        return wilson_interval(self.false_denies_vs_oracle,
                               self.oracle_allows, z)

    def false_allow_wilson(self, z: float = 1.96) -> Tuple[float, float]:
        return wilson_interval(self.false_allows_vs_oracle,
                               self.oracle_denies, z)

    def as_dict(self) -> dict:
        d = dataclasses.asdict(self)
        lo, hi = self.false_deny_wilson()
        d.update(false_deny_rate=self.false_deny_rate,
                 false_allow_rate=self.false_allow_rate,
                 cms_false_deny_rate=self.cms_false_deny_rate,
                 false_deny_wilson95=[lo, hi])
        return d


def _oracle_algorithm(base: Algorithm) -> Algorithm:
    """Exact-backend algorithm with the reference semantics for ``base``
    (TPU_SKETCH follows SLIDING_WINDOW — types.Algorithm docstring)."""
    if base is Algorithm.TOKEN_BUCKET:
        return Algorithm.TOKEN_BUCKET
    if base is Algorithm.FIXED_WINDOW:
        return Algorithm.FIXED_WINDOW
    return Algorithm.SLIDING_WINDOW


class ShadowComparator:
    """The twin + oracle pair, fed a stream of (h64, ns, now, live).

    Owns a collision-free sketch twin and an exact dense oracle built
    from ``config``'s limit/window/algorithm, and a :class:`ThreeWayTally`
    over everything observed. Keys are finalized u64 hashes — the oracle
    is keyed on their decimal form, which preserves decisions exactly
    (the hash is injective on the caller's key population, and both
    shadow legs are per-key exact).

    Thread model: ``decide``/``observe`` must be called from ONE thread
    (the audit worker, or the offline loop); the tally may be read from
    other threads only via a caller-owned lock (the online auditor does
    exactly that — it calls ``decide`` unlocked and folds into the tally
    under its status lock).

    Known blind spots, shared by design with the offline bench and
    documented in ADR-016: per-key policy overrides and DCN-merged
    foreign traffic are invisible to the shadow legs, so keys using
    either show up as (rare, bounded) disagreement.
    """

    def __init__(self, config: Config, *, include_twin: bool = True,
                 twin_width: Optional[int] = None,
                 oracle_capacity: int = 1 << 16):
        from ratelimiter_tpu.algorithms.exact import ExactLimiter
        from ratelimiter_tpu.algorithms.sketch import (
            SketchLimiter,
            SketchTokenBucketLimiter,
        )

        self.config = config
        self.tally = ThreeWayTally()
        self.oracle_errors = 0
        base = dict(limit=config.limit, window=config.window, key_prefix="")
        self._twin = None
        if include_twin:
            # Collision-free twin: one row, width large enough that the
            # caller's key population cannot collide. The offline bench
            # uses 64x the sketch width; the online auditor passes a
            # width sized to the SAMPLED population (1/sample of the
            # keyspace), which is what keeps the shadow state small
            # enough to run forever (ADR-016 §3).
            width = int(twin_width if twin_width is not None
                        else max(config.sketch.width * 64, 1 << 22))
            twin_cfg = Config(
                algorithm=config.algorithm,
                sketch=dataclasses.replace(
                    config.sketch, depth=1, width=width, hh_slots=0,
                    overload_policy="warn"),
                max_batch_admission_iters=config.max_batch_admission_iters,
                **base)
            cls = (SketchTokenBucketLimiter
                   if config.algorithm is Algorithm.TOKEN_BUCKET
                   else SketchLimiter)
            self._twin = cls(twin_cfg)
        # Oracle: exact HOST semantics — bit-for-bit with the dense
        # device oracle (tests/test_cross_backend.py pins exact==dense),
        # but pure dict arithmetic: no device dispatch, no XLA compile,
        # no slot capacity, and only microseconds of GIL per audited
        # batch — which is what lets the ONLINE auditor shadow a serving
        # process without stealing its throughput (ADR-016 §3; the
        # measured A/B in the bench's live_accuracy block guards this).
        # Windowed algorithms take a further inlined u64-keyed fast path
        # (_oracle_fast — the ExactLimiter recurrence without string
        # keys, per-call locks, or Result objects; fuzz-pinned identical
        # to ExactLimiter by tests/test_audit.py); token bucket keeps
        # the ExactLimiter (heavier math, rarer audit target).
        # ``oracle_capacity`` sizes the fast path's prune sweep: past
        # ~4x it, fully-stale entries (idle > one window, both windows
        # expired — semantically identical to fresh) are dropped.
        self._oracle_cap = max(1024, int(oracle_capacity))
        oracle_alg = _oracle_algorithm(config.algorithm)
        oracle_cfg = Config(algorithm=oracle_alg, **base)
        self._oracle = ExactLimiter(oracle_cfg)
        self._fast_windowed = oracle_alg in (Algorithm.SLIDING_WINDOW,
                                             Algorithm.FIXED_WINDOW)
        self._fixed = oracle_alg is Algorithm.FIXED_WINDOW
        from ratelimiter_tpu.core.clock import to_micros

        self._W_us = to_micros(config.window)
        self._limit = int(config.limit)
        self._sw_state: dict = {}

    @property
    def include_twin(self) -> bool:
        return self._twin is not None

    def _oracle_fast(self, h64: np.ndarray, ns_list, now: float) -> np.ndarray:
        """Inlined windowed-oracle batch: EXACTLY ExactLimiter's
        ``_sliding_window`` / ``_fixed_window`` integer recurrence
        (algorithms/exact.py — conditional consume, window_us-scaled
        weighting, lazy rolls) keyed on the u64 hash directly. ~1 us per
        decision vs ~5 us through the public path — the difference
        between the live auditor costing <2% and ~8% of a CPU box's
        serving throughput. Any change here must keep the fuzz pin vs
        ExactLimiter green (tests/test_audit.py)."""
        from ratelimiter_tpu.core.clock import to_micros

        now_us = to_micros(now)
        W = self._W_us
        limit = self._limit
        curr_start = (now_us // W) * W
        elapsed = now_us - curr_start
        fixed = self._fixed
        state = self._sw_state
        out = np.empty(h64.shape[0], dtype=bool)
        budget = limit * W
        for i, h in enumerate(h64.tolist()):
            st = state.get(h)
            if st is None:
                curr = prev = 0
            else:
                start, curr, prev = st
                if start != curr_start:
                    if start == curr_start - W and not fixed:
                        prev, curr = curr, 0
                    else:
                        prev, curr = 0, 0
            n = ns_list[i]
            if fixed:
                ok = curr + n <= limit
            else:
                ok = (n * W
                      <= budget - prev * (W - elapsed) - curr * W)
            if ok:
                curr += n
            out[i] = ok
            state[h] = (curr_start, curr, prev)
        if len(state) > 4 * self._oracle_cap:
            # Drop fully-stale entries (both windows expired == fresh);
            # the TTL-horizon analog of ExactLimiter.prune().
            horizon = curr_start - W
            for h in [h for h, st in state.items() if st[0] < horizon]:
                del state[h]
        return out

    def update_policy(self, limit: int, window: float) -> None:
        """Follow a LIVE ``update_limit``/``update_window`` on the
        audited backend (the online auditor calls this when the serving
        config moves — without it every allow between the old and new
        limit would be scored a false allow forever). A limit change
        updates the comparison constant and both shadow legs in place;
        a window change additionally drops the fast oracle's per-key
        grid (the bucket numbering changed — keys re-learn, erring
        toward allowing for at most one window, the same convergence
        class as the documented blind spots)."""
        from ratelimiter_tpu.core.clock import to_micros

        limit = int(limit)
        if limit != self._limit:
            self._limit = limit
            if self._twin is not None:
                self._twin.update_limit(limit)
            self._oracle.update_limit(limit)
        new_w = to_micros(window)
        if new_w != self._W_us:
            self._W_us = new_w
            self._sw_state.clear()
            if self._twin is not None:
                self._twin.update_window(window)
            self._oracle.update_window(window)

    def decide(self, h64: np.ndarray, ns: Optional[np.ndarray],
               now: float) -> Tuple[np.ndarray, Optional[np.ndarray]]:
        """Run one batch through the oracle (and twin) WITHOUT touching
        the tally: returns (oracle_allowed, twin_allowed-or-None). The
        online auditor uses this so the device dispatches run outside
        its status lock."""
        h64 = np.asarray(h64, dtype=np.uint64)
        if ns is None:
            ns_list = [1] * int(h64.shape[0])
            ns_arr = None
        else:
            ns_arr = np.asarray(ns, dtype=np.int64)
            ns_list = [int(n) for n in ns_arr]
        twin_allowed = None
        if self._twin is not None:
            twin_allowed = self._twin.allow_hashed(h64, ns_arr,
                                                   now=now).allowed
        if self._fast_windowed:
            oracle_allowed = self._oracle_fast(h64, ns_list, now)
        else:
            # Token bucket: the ExactLimiter path. Decimal-formatted
            # hashes key its dict; idle keys prune on the reference's
            # TTL horizons.
            keys = [f"k{int(h)}" for h in h64]
            oracle_allowed = self._oracle.allow_batch(keys, ns_list,
                                                      now=now).allowed
        return oracle_allowed, twin_allowed

    def observe(self, h64: np.ndarray, ns: Optional[np.ndarray], now: float,
                live_allowed: np.ndarray) -> Tuple[np.ndarray,
                                                   Optional[np.ndarray]]:
        """decide + fold into the tally (the offline bench's loop body)."""
        oracle_allowed, twin_allowed = self.decide(h64, ns, now)
        self.tally.add(live_allowed, twin_allowed, oracle_allowed)
        return oracle_allowed, twin_allowed

    def close(self) -> None:
        if self._twin is not None:
            self._twin.close()
            self._twin = None
        self._oracle.close()
