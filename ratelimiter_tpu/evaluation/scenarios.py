"""Abuse-scenario load generation + measurement for the hierarchical
cascade (ADR-020).

Three canonical multi-tenant abuse shapes, expressed as deterministic
frame generators plus a driver that runs them against a REAL
hierarchy-enabled limiter (any backend exposing the cascade surface) and
measures behavior instead of claiming it:

* **hot-tenant-storm** — one tenant's traffic surges to ~90% of the
  global scope. The cascade must keep squeezing the storm into the
  attacker's fair share (the victim tenant keeps its headroom), and the
  AIMD controller (when wired) must tighten the HOT tenant's effective
  limit and additively recover it after the storm clears.
* **rotating-key** — an attacker mints fresh keys every frame, the
  classic per-key-limit evasion that also churns straight past the hh
  side table's per-key tracking (a rotating key never accumulates
  in-window mass under one identity). Per-key scopes never fire; the
  DEFAULT-tenant + global scopes are what contain the aggregate.
* **thundering-herd** — every key of every tenant bursts simultaneously
  at a window rollover. The global scope must clip the synchronized
  surge to exactly its limit, split between tenants proportionally to
  their weights (the fair-share contract, measured).

False-deny accounting is cascade-aware: decisions are shadowed by a
SEQUENTIAL key → tenant → global reference (``CascadeOracle``) evaluated
at the limiter's LIVE effective limits, so a controller tighten is
policy, not error — what the Wilson bound measures is the limiter's own
divergence from its declared cascade semantics (sketch collisions plus
the documented in-batch staging artifact, ops/hier_kernels.py).
"""

from __future__ import annotations

from collections import defaultdict
from dataclasses import dataclass, field
from typing import Dict, Iterator, List, Tuple

import numpy as np

from ratelimiter_tpu.evaluation.compare import wilson_interval

SCENARIOS = ("hot-tenant-storm", "rotating-key", "thundering-herd")


class CascadeOracle:
    """Sequential key → tenant → global reference limiter evaluated at
    live effective limits (requests-per-window; fixed time inside a
    window, ``roll()`` at window boundaries)."""

    def __init__(self, key_limit: int, tenant_of: Dict[str, str]):
        self.key_limit = key_limit
        self.tenant_of = dict(tenant_of)
        self.keys: Dict[str, int] = defaultdict(int)
        self.tenants: Dict[str, int] = defaultdict(int)
        self.total = 0

    def roll(self) -> None:
        """A full window elapsed: every scope's in-window mass clears."""
        self.keys.clear()
        self.tenants.clear()
        self.total = 0

    def decide(self, keys: List[str], effective: Dict[str, int]) -> np.ndarray:
        """Sequential verdicts for one frame under ``effective`` (the
        limiter's live per-scope limits; hierarchy.GLOBAL key for the
        global scope)."""
        out = np.zeros(len(keys), dtype=bool)
        g_lim = effective.get("global")
        for i, k in enumerate(keys):
            t = self.tenant_of.get(k, "default")
            t_lim = effective.get(t)
            ok = (self.keys[k] < self.key_limit
                  and (t_lim is None or self.tenants[t] + 1 <= t_lim)
                  and (g_lim is None or self.total + 1 <= g_lim))
            if ok:
                self.keys[k] += 1
                self.tenants[t] += 1
                self.total += 1
            out[i] = ok
        return out


@dataclass
class FalseDenyTally:
    """Wilson-bounded false-deny accounting vs the cascade oracle."""

    denies: int = 0
    false_denies: int = 0
    samples: int = 0

    def add(self, got: np.ndarray, want: np.ndarray) -> None:
        self.samples += int(got.shape[0])
        self.denies += int((~got).sum())
        self.false_denies += int((want & ~got).sum())

    def wilson95(self) -> Tuple[float, float]:
        return wilson_interval(self.false_denies, self.samples)

    def as_dict(self) -> dict:
        lo, hi = self.wilson95()
        return {"false_denies": self.false_denies,
                "samples": self.samples,
                "false_deny_wilson95": [round(lo, 6), round(hi, 6)]}


# ------------------------------------------------------------- generators


def hot_tenant_storm_frames(
        rng: np.random.Generator, *, batch: int, frames_per_phase: int,
        attacker_keys: int = 40, victim_keys: int = 8,
) -> Iterator[Tuple[str, List[str]]]:
    """(phase, keys) frames: baseline (balanced) → storm (attacker ~90%
    of the frame) → recovery (baseline mix again)."""
    atk = [f"atk{i}" for i in range(attacker_keys)]
    vic = [f"vic{i}" for i in range(victim_keys)]
    # The storm multiplies TOTAL demand (an attack adds traffic, it does
    # not displace the victim's): baseline/recovery frames must sit
    # below global saturation for the controller's relax leg to engage.
    for phase, atk_frac, mult in (("baseline", 0.3, 1), ("storm", 0.9, 4),
                                  ("recovery", 0.3, 1)):
        for _ in range(frames_per_phase):
            b = batch * mult
            n_atk = int(b * atk_frac)
            keys = ([atk[int(i)] for i in
                     rng.integers(0, len(atk), size=n_atk)]
                    + [vic[int(i)] for i in
                       rng.integers(0, len(vic), size=b - n_atk)])
            rng.shuffle(keys)
            yield phase, keys


def rotating_key_frames(
        rng: np.random.Generator, *, batch: int, frames: int,
        legit_keys: int = 16, attacker_frac: float = 0.75,
) -> Iterator[Tuple[str, List[str]]]:
    """Attacker keys are FRESH every frame (``rot<frame>_<i>`` — never
    repeated, never assigned to a tenant, never hh-resident); legit
    traffic rides a stable hot set."""
    legit = [f"legit{i}" for i in range(legit_keys)]
    for f in range(frames):
        n_atk = int(batch * attacker_frac)
        keys = ([f"rot{f}_{i}" for i in range(n_atk)]
                + [legit[int(i)] for i in
                   rng.integers(0, len(legit), size=batch - n_atk)])
        rng.shuffle(keys)
        yield "attack", keys


def thundering_herd_frames(
        rng: np.random.Generator, *, tenants: Dict[str, int],
        keys_per_tenant: int, bursts_per_key: int,
) -> Iterator[Tuple[str, List[str]]]:
    """One synchronized burst frame: every key of every tenant fires
    ``bursts_per_key`` requests at the same instant (the window-rollover
    herd). ``tenants`` maps name -> key count weighting is external."""
    keys = []
    for name in tenants:
        for i in range(keys_per_tenant):
            keys.extend([f"{name}_k{i}"] * bursts_per_key)
    rng.shuffle(keys)
    yield "herd", keys


# ----------------------------------------------------------------- driver


@dataclass
class ScenarioResult:
    name: str
    phases: Dict[str, dict] = field(default_factory=dict)
    extra: Dict[str, object] = field(default_factory=dict)

    def as_dict(self) -> dict:
        return {"scenario": self.name, **self.phases, **self.extra}


def _effective_view(lim) -> Dict[str, int]:
    """Live effective limits with the UNLIMITED sentinel mapped to None
    (the oracle treats None as uncapped)."""
    from ratelimiter_tpu.core.config import HIER_UNLIMITED

    return {scope: (None if v >= HIER_UNLIMITED else int(v))
            for scope, v in lim.effective_limits().items()}


def run_hot_tenant_storm(lim, clock, *, controller=None, batch: int = 256,
                         frames_per_phase: int = 6, window: float = 60.0,
                         seed: int = 7) -> ScenarioResult:
    """Drive the storm against ``lim`` (tenants 'attacker'/'victim' and
    their key assignments must already be registered). Phases advance
    the ManualClock past the window between them; the controller (when
    given) ticks once per frame, off the decision path."""
    rng = np.random.default_rng(seed)
    tenant_of = {f"atk{i}": "attacker" for i in range(40)}
    tenant_of.update({f"vic{i}": "victim" for i in range(8)})
    oracle = CascadeOracle(lim.config.limit, tenant_of)
    res = ScenarioResult("hot-tenant-storm")
    tally_before = FalseDenyTally()   # before the first controller move
    tally_after = FalseDenyTally()
    eff_timeline: List[int] = []
    phase_stats: Dict[str, dict] = {}
    cur_phase = None
    tick = 0.0
    for phase, keys in hot_tenant_storm_frames(
            rng, batch=batch, frames_per_phase=frames_per_phase):
        if phase != cur_phase:
            # Window (and its boundary sub-window) rolls between phases;
            # a warmup decision kicks the rollover sweep.
            clock.advance(2.5 * window)
            lim.allow("phase-warmup")
            oracle.roll()
            cur_phase = phase
            phase_stats[phase] = {"allowed": 0, "demand": 0,
                                  "victim_allowed": 0, "victim_demand": 0}
        eff = _effective_view(lim)
        out = lim.allow_batch(keys)
        got = np.asarray(out.allowed, dtype=bool)
        want = oracle.decide(keys, eff)
        # The sequential oracle is driven by ITS OWN verdicts (the
        # documented comparison basis); both sides saw the same live
        # effective limits, so a controller tighten is policy for both,
        # never a false deny.
        (tally_after if (controller is not None and controller.tightened)
         else tally_before).add(got, want)
        if controller is not None:
            controller.tick(tick)   # off the decision path, per frame
        tick += 1.0
        st = phase_stats[phase]
        vic_rows = np.array([k.startswith("vic") for k in keys])
        st["allowed"] += int(got.sum())
        st["demand"] += len(keys)
        st["victim_allowed"] += int(got[vic_rows].sum())
        st["victim_demand"] += int(vic_rows.sum())
        if controller is not None:
            eff_timeline.append(
                _effective_view(lim).get("attacker") or -1)
    for phase, st in phase_stats.items():
        st["allow_rate"] = round(st["allowed"] / max(st["demand"], 1), 4)
        st["victim_allow_rate"] = round(
            st["victim_allowed"] / max(st["victim_demand"], 1), 4)
        res.phases[phase] = st
    res.extra["false_deny_before_tighten"] = tally_before.as_dict()
    res.extra["false_deny_after_tighten"] = tally_after.as_dict()
    if controller is not None:
        ceiling = dict(lim.list_tenants())["attacker"].limit
        res.extra["controller"] = {
            "tightened": controller.tightened,
            "relaxed": controller.relaxed,
            "attacker_ceiling": ceiling,
            "attacker_effective_min": min(eff_timeline),
            "attacker_effective_final": eff_timeline[-1],
            "effective_timeline": eff_timeline,
        }
    return res


def run_rotating_key(lim, clock, *, batch: int = 256, frames: int = 8,
                     window: float = 60.0, seed: int = 11) -> ScenarioResult:
    """Rotating-key attacker vs the hh side table: fresh keys every
    frame ride the DEFAULT tenant; its ceiling + the global scope
    contain the aggregate while the stable legit set keeps serving."""
    rng = np.random.default_rng(seed)
    res = ScenarioResult("rotating-key")
    atk_allowed = atk_demand = legit_allowed = legit_demand = 0
    for _, keys in rotating_key_frames(rng, batch=batch, frames=frames):
        out = lim.allow_batch(keys)
        got = np.asarray(out.allowed, dtype=bool)
        rot = np.array([k.startswith("rot") for k in keys])
        atk_allowed += int(got[rot].sum())
        atk_demand += int(rot.sum())
        legit_allowed += int(got[~rot].sum())
        legit_demand += int((~rot).sum())
    st = lim.hierarchy_stats()
    res.extra.update({
        "attacker_admitted": atk_allowed,
        "attacker_demand": atk_demand,
        "attacker_admit_rate": round(atk_allowed / max(atk_demand, 1), 4),
        "legit_allow_rate": round(legit_allowed / max(legit_demand, 1), 4),
        "default_tenant_in_window": st["tenants"]["default"]["in_window"],
        "default_tenant_effective": st["tenants"]["default"]["effective"],
        # The containment claim, measured: aggregate admitted attacker
        # mass never exceeds the default tenant's effective limit even
        # though no single key ever hit a per-key limit.
        "contained": atk_allowed <= st["tenants"]["default"]["effective"],
    })
    return res


def run_thundering_herd(lim, clock, *, tenants: Dict[str, int],
                        keys_per_tenant: int = 16, bursts_per_key: int = 4,
                        window: float = 60.0, seed: int = 13) -> ScenarioResult:
    """Synchronized burst at a fresh window: total admitted must equal
    the global effective limit, split ~ proportionally to weights."""
    rng = np.random.default_rng(seed)
    clock.advance(2.5 * window)          # a fresh window for the herd
    lim.allow("herd-warmup")
    res = ScenarioResult("thundering-herd")
    per_tenant_allowed: Dict[str, int] = defaultdict(int)
    total_allowed = 0
    total_demand = 0
    for _, keys in thundering_herd_frames(
            rng, tenants=tenants, keys_per_tenant=keys_per_tenant,
            bursts_per_key=bursts_per_key):
        out = lim.allow_batch(keys)
        got = np.asarray(out.allowed, dtype=bool)
        total_allowed += int(got.sum())
        total_demand += len(keys)
        for k, ok in zip(keys, got):
            if ok:
                per_tenant_allowed[k.split("_k")[0]] += 1
    eff = _effective_view(lim)
    res.extra.update({
        "demand": total_demand,
        "admitted": total_allowed,
        "global_effective": eff.get("global"),
        "per_tenant_admitted": dict(sorted(per_tenant_allowed.items())),
        "weights": dict(tenants),
    })
    return res
