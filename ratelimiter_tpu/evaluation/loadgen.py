"""Device-side synthetic load generation + pipelined decision runner.

The dev/bench environment reaches its TPU through a tunnel whose
host->device bandwidth (~44 MB/s measured) is orders of magnitude below a
production host link (let alone a NIC feeding a colocated host). Uploading
8 bytes of hashed key per decision would therefore benchmark the tunnel,
not the limiter. This module keeps the *system under test* identical —
the same sketch step kernel the limiter dispatches — but synthesizes the
request trace on device:

* uniform u64 stream via the splitmix64 finalizer over a counter (same
  mixer as ops/hashing.py, vectorized integer ops);
* bounded-Pareto inverse CDF maps uniforms to Zipf(alpha)-distributed key
  ids over [0, n_keys) (the continuous analog of the discrete Zipf used by
  evaluation.accuracy — same skew shape, closed form, no lookups);
* ids are hashed to (h1, h2) exactly like real ingest, then decided by
  ops.sketch_kernels._sketch_step; verdicts come back as packed bitmasks
  (1 bit/decision) so readback stays off the critical path.

BASELINE config 3 is expressed this way: batch=4096 ingest batches are
coalesced into one mega-batch device dispatch (the micro-batcher's
behavior at saturation), with full in-batch same-key sequencing — a
*stronger* atomicity story than deciding 4096-slices against stale
snapshots.
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp

from ratelimiter_tpu.core.config import Config
from ratelimiter_tpu.ops import sketch_kernels


def _splitmix64_dev(x: jnp.ndarray) -> jnp.ndarray:
    """Vectorized splitmix64 finalizer on device (uint64; TPU emulates
    64-bit integer ops with 32-bit pairs — still ~ns/element, negligible
    next to the decision kernel)."""
    x = x + jnp.uint64(0x9E3779B97F4A7C15)
    x = (x ^ (x >> jnp.uint64(30))) * jnp.uint64(0xBF58476D1CE4E5B9)
    x = (x ^ (x >> jnp.uint64(27))) * jnp.uint64(0x94D049BB133111EB)
    return x ^ (x >> jnp.uint64(31))


def _zipf_ids(counter0: jnp.ndarray, B: int, n_keys: int, alpha: float) -> jnp.ndarray:
    """(B,) uint64 Zipf(alpha)-distributed ids in [0, n_keys): bounded-Pareto
    inverse CDF, x = (1 + u*((N+1)^(1-a) - 1))^(1/(1-a))."""
    ctr = counter0 + jax.lax.iota(jnp.uint64, B)
    u64 = _splitmix64_dev(ctr)
    u = (u64 >> jnp.uint64(40)).astype(jnp.float32) * jnp.float32(2.0 ** -24)
    a1 = 1.0 - alpha                       # < 0
    hi = float((n_keys + 1) ** a1)
    x = jnp.exp(jnp.log1p(u * jnp.float32(hi - 1.0)) * jnp.float32(1.0 / a1))
    ids = jnp.clip(x.astype(jnp.int64) - 1, 0, n_keys - 1)
    return ids.astype(jnp.uint64)


def build_bench_chunk(cfg: Config, B: int, n_keys: int, alpha: float) -> Callable:
    """Jitted ``chunk(state, counter0, now_us) -> (state, packed, denies)``:
    generate B Zipf requests on device, decide them in one sketch step,
    return the packed allow bitmask + deny count. State is donated (stays
    resident in HBM)."""
    from ratelimiter_tpu.core.types import Algorithm

    W, sub_us, SW, S, limit = sketch_kernels.sketch_geometry(cfg)
    d, w = cfg.sketch.depth, cfg.sketch.width
    weighted = cfg.algorithm is not Algorithm.FIXED_WINDOW
    seed = cfg.sketch.seed

    def chunk(state, counter0, now_us):
        ids = _zipf_ids(counter0, B, n_keys, alpha)
        h = _splitmix64_dev(ids ^ jnp.uint64(seed & 0xFFFFFFFFFFFFFFFF))
        h1 = (h & jnp.uint64(0xFFFFFFFF)).astype(jnp.uint32)
        h2 = (h >> jnp.uint64(32)).astype(jnp.uint32) | jnp.uint32(1)
        n = jnp.ones((B,), jnp.int32)
        state, (allowed, _rem, _est) = sketch_kernels._sketch_step(
            state, h1, h2, n, now_us,
            limit=limit, sub_us=sub_us, SW=SW, S=S, d=d, w=w,
            iters=cfg.max_batch_admission_iters, weighted=weighted,
            conservative=cfg.sketch.conservative_update)
        packed = sketch_kernels._pack_bits(allowed)
        denies = jnp.sum(~allowed).astype(jnp.int32)
        return state, packed, denies

    return jax.jit(chunk, donate_argnums=(0,))
