"""False-deny evaluation harness (BASELINE.json metric).

The north-star accuracy number: on a Zipf(1.1) trace over ~1M keys, the
sketch backend must produce <= 1% false-positive *denies* versus the exact
sliding-window oracle (the stand-in for the reference's Redis sliding window,
SURVEY.md §4.3). Error direction: ops/segment.admit never over-admits
against the *estimate*, and with vanilla (non-conservative) updates CMS
estimates only err upward, so over-admission versus the sketch's own
semantics is impossible in that configuration. With
``conservative_update=True`` (the flagship bench config) the guarantee is
weaker: CU writes raise a cell only to the largest single-key target, so a
cell can undercount colliding traffic once boundary slabs holding part of a
CU write expire — a small, *measured* false-allow risk (BENCH_r02:
``false_allow_rate_vs_oracle ~= 2e-8``), traded for a large false-deny
reduction. Allow-where-oracle-denied events therefore combine that CU
effect with the *semantic* difference between sub-window-ring sliding and
the reference's two-window weighting; the three-way comparison below
separates the CMS-error component from the semantic component.

Three-way comparison (each isolates one error source):
* sketch (CMS, d x w)        — the system under test;
* twin   (CMS, huge width)   — same sub-window semantics, no collisions:
                               sketch-vs-twin disagreement == pure CMS error;
* oracle (dense, exact)      — reference two-window sliding semantics:
                               twin-vs-oracle disagreement == pure semantic
                               resolution difference.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import numpy as np

from ratelimiter_tpu.core.clock import ManualClock
from ratelimiter_tpu.core.config import Config, DenseParams, SketchParams
from ratelimiter_tpu.core.types import Algorithm


def zipf_key_ids(n_keys: int, n_requests: int, alpha: float = 1.1,
                 seed: int = 0) -> np.ndarray:
    """Sample request key ids from a bounded Zipf(alpha) over [0, n_keys):
    inverse-CDF over the normalized 1/rank^alpha mass (BASELINE configs 3/5)."""
    rng = np.random.default_rng(seed)
    ranks = np.arange(1, n_keys + 1, dtype=np.float64)
    cdf = np.cumsum(ranks ** -alpha)
    cdf /= cdf[-1]
    u = rng.random(n_requests)
    return np.searchsorted(cdf, u).astype(np.uint64)


@dataclasses.dataclass
class AccuracyReport:
    requests: int
    oracle_allows: int
    false_denies_vs_oracle: int      # sketch denied, oracle allowed
    false_allows_vs_oracle: int      # sketch allowed, oracle denied (semantic)
    false_deny_rate: float           # vs oracle allows — the BASELINE metric
    cms_false_denies_vs_twin: int    # sketch denied, twin allowed (pure CMS)
    cms_false_deny_rate: float
    semantic_disagreements: int      # twin vs oracle (resolution difference)

    def as_dict(self) -> dict:
        return dataclasses.asdict(self)


def evaluate_accuracy(
    n_keys: int = 100_000,
    n_requests: int = 200_000,
    batch: int = 4096,
    alpha: float = 1.1,
    limit: int = 100,
    window: float = 60.0,
    request_rate: float = 50_000.0,
    sketch: Optional[SketchParams] = None,
    seed: int = 0,
    include_twin: bool = True,
) -> AccuracyReport:
    """Run the same batched trace through sketch / twin / exact-dense oracle
    under identical virtual time (requests arrive uniformly at request_rate)."""
    from ratelimiter_tpu.algorithms.dense import DenseLimiter
    from ratelimiter_tpu.algorithms.sketch import SketchLimiter
    from ratelimiter_tpu.ops.hashing import splitmix64

    sketch = sketch or SketchParams()
    ids = zipf_key_ids(n_keys, n_requests, alpha, seed)
    hashes = splitmix64(ids)

    base = dict(limit=limit, window=window, key_prefix="")
    cfg_sketch = Config(algorithm=Algorithm.TPU_SKETCH, sketch=sketch, **base)
    # Twin: identical sub-window semantics, collision-free width.
    twin_width = max(sketch.width * 64, 1 << 22)
    cfg_twin = Config(algorithm=Algorithm.TPU_SKETCH,
                      sketch=dataclasses.replace(sketch, depth=1, width=twin_width),
                      **base)
    # The oracle only needs a slot per *distinct* key that can appear in the
    # trace (slots are assigned on demand), not per key in the keyspace.
    oracle_cap = min(n_keys, n_requests) + 1
    cfg_oracle = Config(algorithm=Algorithm.SLIDING_WINDOW,
                        dense=DenseParams(capacity=oracle_cap), **base)

    t0 = 1_700_000_000.0
    lim_sketch = SketchLimiter(cfg_sketch, ManualClock(t0))
    lim_twin = SketchLimiter(cfg_twin, ManualClock(t0)) if include_twin else None
    lim_oracle = DenseLimiter(cfg_oracle, ManualClock(t0), capacity=oracle_cap)

    allows_sketch = np.empty(n_requests, dtype=bool)
    allows_twin = np.empty(n_requests, dtype=bool)
    allows_oracle = np.empty(n_requests, dtype=bool)

    # The dense oracle's key->slot map is fed integer-formatted keys once.
    for start in range(0, n_requests, batch):
        end = min(start + batch, n_requests)
        now = t0 + start / request_rate
        h = hashes[start:end]
        allows_sketch[start:end] = lim_sketch.allow_hashed(h, now=now).allowed
        if lim_twin is not None:
            allows_twin[start:end] = lim_twin.allow_hashed(h, now=now).allowed
        keys = [f"k{i}" for i in ids[start:end]]
        allows_oracle[start:end] = lim_oracle.allow_batch(keys, now=now).allowed

    lim_sketch.close()
    if lim_twin is not None:
        lim_twin.close()
    lim_oracle.close()

    oracle_allows = int(allows_oracle.sum())
    fd = int((allows_oracle & ~allows_sketch).sum())
    fa = int((~allows_oracle & allows_sketch).sum())
    if include_twin:
        cms_fd = int((allows_twin & ~allows_sketch).sum())
        twin_allows = int(allows_twin.sum())
        sem = int((allows_twin != allows_oracle).sum())
    else:
        cms_fd, twin_allows, sem = 0, 0, 0
    return AccuracyReport(
        requests=n_requests,
        oracle_allows=oracle_allows,
        false_denies_vs_oracle=fd,
        false_allows_vs_oracle=fa,
        false_deny_rate=fd / max(1, oracle_allows),
        cms_false_denies_vs_twin=cms_fd,
        cms_false_deny_rate=cms_fd / max(1, twin_allows),
        semantic_disagreements=sem,
    )
