"""False-deny evaluation harness (BASELINE.json metric).

The north-star accuracy number: on a Zipf(1.1) trace over ~1M keys, the
sketch backend must produce <= 1% false-positive *denies* versus the exact
sliding-window oracle (the stand-in for the reference's Redis sliding window,
SURVEY.md §4.3). Error direction: ops/segment.admit never over-admits
against the *estimate*, and with vanilla (non-conservative) updates CMS
estimates only err upward, so over-admission versus the sketch's own
semantics is impossible in that configuration. With
``conservative_update=True`` (the flagship bench config) the guarantee is
weaker: CU writes raise a cell only to the largest single-key target, so a
cell can undercount colliding traffic once boundary slabs holding part of a
CU write expire — a small, *measured* false-allow risk (BENCH_r02:
``false_allow_rate_vs_oracle ~= 2e-8``), traded for a large false-deny
reduction. Allow-where-oracle-denied events therefore combine that CU
effect with the *semantic* difference between sub-window-ring sliding and
the reference's two-window weighting; the three-way comparison separates
the CMS-error component from the semantic component.

The comparison core itself (sketch vs collision-free twin vs exact
oracle, tally arithmetic, Wilson intervals) lives in
``evaluation/compare.py`` — the SAME engine the live accuracy observatory
(``observability/audit.py``, ADR-016) runs against a hash-sampled tap of
serving traffic, so the offline bench and the online auditor can never
disagree about what a false deny is. This module is the offline driver:
a synthetic Zipf trace under virtual time.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Tuple

import numpy as np

from ratelimiter_tpu.core.clock import ManualClock
from ratelimiter_tpu.core.config import Config, SketchParams
from ratelimiter_tpu.core.types import Algorithm
from ratelimiter_tpu.evaluation.compare import ShadowComparator


def zipf_key_ids(n_keys: int, n_requests: int, alpha: float = 1.1,
                 seed: int = 0) -> np.ndarray:
    """Sample request key ids from a bounded Zipf(alpha) over [0, n_keys):
    inverse-CDF over the normalized 1/rank^alpha mass (BASELINE configs 3/5)."""
    rng = np.random.default_rng(seed)
    ranks = np.arange(1, n_keys + 1, dtype=np.float64)
    cdf = np.cumsum(ranks ** -alpha)
    cdf /= cdf[-1]
    u = rng.random(n_requests)
    return np.searchsorted(cdf, u).astype(np.uint64)


@dataclasses.dataclass
class AccuracyReport:
    requests: int
    oracle_allows: int
    false_denies_vs_oracle: int      # sketch denied, oracle allowed
    false_allows_vs_oracle: int      # sketch allowed, oracle denied (semantic)
    false_deny_rate: float           # vs oracle allows — the BASELINE metric
    cms_false_denies_vs_twin: int    # sketch denied, twin allowed (pure CMS)
    cms_false_deny_rate: float
    semantic_disagreements: int      # twin vs oracle (resolution difference)
    #: 95% Wilson interval on false_deny_rate (compare.wilson_interval) —
    #: the same bound the live auditor reports, so bench JSONs and
    #: /debug/audit quote comparable uncertainty.
    false_deny_wilson95: Tuple[float, float] = (0.0, 1.0)

    def as_dict(self) -> dict:
        d = dataclasses.asdict(self)
        d["false_deny_wilson95"] = list(self.false_deny_wilson95)
        return d


def evaluate_accuracy(
    n_keys: int = 100_000,
    n_requests: int = 200_000,
    batch: int = 4096,
    alpha: float = 1.1,
    limit: int = 100,
    window: float = 60.0,
    request_rate: float = 50_000.0,
    sketch: Optional[SketchParams] = None,
    seed: int = 0,
    include_twin: bool = True,
) -> AccuracyReport:
    """Run the same batched trace through sketch / twin / exact-dense oracle
    under identical virtual time (requests arrive uniformly at request_rate)."""
    from ratelimiter_tpu.algorithms.sketch import SketchLimiter
    from ratelimiter_tpu.ops.hashing import splitmix64

    sketch = sketch or SketchParams()
    ids = zipf_key_ids(n_keys, n_requests, alpha, seed)
    hashes = splitmix64(ids)

    cfg_sketch = Config(algorithm=Algorithm.TPU_SKETCH, sketch=sketch,
                        limit=limit, window=window, key_prefix="")
    # The oracle only needs a slot per *distinct* key that can appear in the
    # trace (slots are assigned on demand), not per key in the keyspace.
    oracle_cap = min(n_keys, n_requests) + 1

    t0 = 1_700_000_000.0
    lim_sketch = SketchLimiter(cfg_sketch, ManualClock(t0))
    # Twin: identical sub-window semantics, collision-free width; oracle:
    # exact two-window sliding semantics (compare.ShadowComparator).
    comparator = ShadowComparator(
        cfg_sketch, include_twin=include_twin,
        twin_width=max(sketch.width * 64, 1 << 22),
        oracle_capacity=oracle_cap)

    for start in range(0, n_requests, batch):
        end = min(start + batch, n_requests)
        now = t0 + start / request_rate
        h = hashes[start:end]
        live = lim_sketch.allow_hashed(h, now=now).allowed
        comparator.observe(h, None, now, live)

    lim_sketch.close()
    comparator.close()

    t = comparator.tally
    return AccuracyReport(
        requests=t.requests,
        oracle_allows=t.oracle_allows,
        false_denies_vs_oracle=t.false_denies_vs_oracle,
        false_allows_vs_oracle=t.false_allows_vs_oracle,
        false_deny_rate=t.false_deny_rate,
        cms_false_denies_vs_twin=t.cms_false_denies_vs_twin,
        cms_false_deny_rate=t.cms_false_deny_rate,
        semantic_disagreements=t.semantic_disagreements,
        false_deny_wilson95=t.false_deny_wilson(),
    )
