"""On-device exact oracle: measure sketch accuracy AT the benched operating
point, inside the benched run (fixes round-1's hardcoded accuracy claim).

The oracle is the sketch kernel itself instantiated collision-free: depth 1,
width >= n_keys (power of two), and *identity* hashing (h1 = key id,
h2 = 0, so ``col = id``). Every key gets a private cell per sub-window —
that IS an exact per-key sliding-window counter with the same time
discretization and the same in-batch greedy admission as the sketch under
test. The sketch-vs-oracle verdict disagreement is therefore *pure
collision/conservative-update error*, the quantity BASELINE.json caps at 1%
(false denies; false allows measured too and expected ~0).

Both limiters decide the same device-generated trace in one fused chunk
(evaluation/loadgen.py explains why generation is on-device), so accuracy
costs one extra kernel, not a host round-trip per decision.
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp

from ratelimiter_tpu.core.config import Config
from ratelimiter_tpu.evaluation.loadgen import _splitmix64_dev, _zipf_ids
from ratelimiter_tpu.ops import sketch_kernels


def _next_pow2(n: int) -> int:
    p = 1
    while p < n:
        p *= 2
    return p


def oracle_geometry(cfg: Config, n_keys: int) -> dict:
    """Step kwargs for the collision-free oracle twin of ``cfg``."""
    from ratelimiter_tpu.core.types import Algorithm

    W, sub_us, SW, S, limit = sketch_kernels.sketch_geometry(cfg)
    return dict(limit=limit, sub_us=sub_us, SW=SW, S=S,
                d=1, w=_next_pow2(n_keys),
                iters=cfg.max_batch_admission_iters,
                weighted=cfg.algorithm is not Algorithm.FIXED_WINDOW,
                conservative=False)


def init_oracle_state(cfg: Config, n_keys: int) -> sketch_kernels.State:
    g = oracle_geometry(cfg, n_keys)
    return {
        "cur": jnp.zeros((1, g["w"]), jnp.int32),
        "slabs": jnp.zeros((g["S"], 1, g["w"]), jnp.int32),
        "totals": jnp.zeros((1, g["w"]), jnp.int32),
        "slab_period": jnp.full((g["S"],), sketch_kernels._NEVER, jnp.int64),
        "last_period": jnp.asarray(sketch_kernels._NEVER, jnp.int64),
    }


def build_eval_chunk(cfg: Config, B: int, n_keys: int, alpha: float) -> Callable:
    """Jitted ``chunk(states, counter0, now_us) -> (states, stats)`` deciding
    one B-sized Zipf batch with BOTH the sketch and the exact oracle.

    ``states`` is ``{"sk": sketch_state, "or": oracle_state}``; ``stats`` is
    (false_deny, false_allow, sketch_deny, oracle_deny) int64 counts.
    false_deny = sketch denied but the oracle allowed (the capped metric);
    false_allow = sketch allowed but the oracle denied.
    """
    from ratelimiter_tpu.core.types import Algorithm

    W, sub_us, SW, S, limit = sketch_kernels.sketch_geometry(cfg)
    d, w = cfg.sketch.depth, cfg.sketch.width
    weighted = cfg.algorithm is not Algorithm.FIXED_WINDOW
    seed = cfg.sketch.seed
    hh, hh_thresh = sketch_kernels._hh_params(cfg)
    sk_kw = dict(limit=limit, sub_us=sub_us, SW=SW, S=S, d=d, w=w,
                 iters=cfg.max_batch_admission_iters, weighted=weighted,
                 conservative=cfg.sketch.conservative_update,
                 hh=hh, hh_thresh=hh_thresh)
    or_kw = oracle_geometry(cfg, n_keys)

    def chunk(states, counter0, now_us):
        ids = _zipf_ids(counter0, B, n_keys, alpha)
        h = _splitmix64_dev(ids ^ jnp.uint64(seed & 0xFFFFFFFFFFFFFFFF))
        h1 = (h & jnp.uint64(0xFFFFFFFF)).astype(jnp.uint32)
        h2 = (h >> jnp.uint64(32)).astype(jnp.uint32) | jnp.uint32(1)
        n = jnp.ones((B,), jnp.int32)
        sk, (sk_allow, _, _) = sketch_kernels._sketch_step(
            states["sk"], h1, h2, n, now_us, **sk_kw)
        # Oracle: identity columns (h1=id, h2=0), collision-free => exact.
        o1 = ids.astype(jnp.uint32)
        o2 = jnp.zeros((B,), jnp.uint32)
        oc, (or_allow, _, _) = sketch_kernels._sketch_step(
            states["or"], o1, o2, n, now_us, **or_kw)
        stats = (
            jnp.sum(~sk_allow & or_allow).astype(jnp.int64),
            jnp.sum(sk_allow & ~or_allow).astype(jnp.int64),
            jnp.sum(~sk_allow).astype(jnp.int64),
            jnp.sum(~or_allow).astype(jnp.int64),
        )
        return {"sk": sk, "or": oc}, stats

    return jax.jit(chunk, donate_argnums=(0,))


def build_oracle_rollover(cfg: Config, n_keys: int) -> Callable:
    g = oracle_geometry(cfg, n_keys)
    from functools import partial

    return jax.jit(partial(sketch_kernels._rollover, SW=g["SW"], S=g["S"]),
                   donate_argnums=(0,))
