"""Accuracy evaluation: sketch vs exact oracle (BASELINE.json metric).

The three-way comparison core (``compare.py``) is shared with the live
accuracy observatory (``observability/audit.py``, ADR-016)."""

from ratelimiter_tpu.evaluation.accuracy import evaluate_accuracy, zipf_key_ids
from ratelimiter_tpu.evaluation.compare import (
    ShadowComparator,
    ThreeWayTally,
    wilson_interval,
)

__all__ = [
    "ShadowComparator",
    "ThreeWayTally",
    "evaluate_accuracy",
    "wilson_interval",
    "zipf_key_ids",
]
