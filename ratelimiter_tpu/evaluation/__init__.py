"""Accuracy evaluation: sketch vs exact oracle (BASELINE.json metric)."""

from ratelimiter_tpu.evaluation.accuracy import evaluate_accuracy, zipf_key_ids

__all__ = ["evaluate_accuracy", "zipf_key_ids"]
