"""Configuration, validation, defaults, and key formatting.

Parity with reference ``internal/ratelimiter/config.go`` and the Config struct
(``interface.go:46-70``): algorithm, limit, window, key prefix, fail-open.
Extended with the TPU deployment axis (sketch geometry, dense capacity,
admission-scan iterations) per SURVEY.md §5.6.

Divergence note (deliberate, SURVEY.md §2.4.8): in the reference an
empty-string prefix means "no prefix" inside ``FormatKey`` (``config.go:71-77``)
but ``WithDefaults`` re-instates the default prefix, so "no prefix" is
unreachable through public constructors. Here ``key_prefix=None`` (the default)
means "use DEFAULT_PREFIX" and ``key_prefix=""`` genuinely means "no prefix" —
the documented behavior becomes reachable. tests/test_config.py pins both.
"""

from __future__ import annotations

from dataclasses import dataclass, field, replace
from typing import Optional

from ratelimiter_tpu.core.errors import InvalidConfigError
from ratelimiter_tpu.core.types import Algorithm

#: Reference ``config.go:11``.
DEFAULT_PREFIX = "ratelimit"

#: Reference bounds, ``config.go:31-47``.
MIN_WINDOW_SECONDS = 0.001
MAX_WINDOW_SECONDS = 365.0 * 24 * 3600


@dataclass(frozen=True)
class SketchParams:
    """Geometry of the count-min sketch backend (BASELINE.json configs 3-5).

    depth × width int32 counters shared by all keys; the window is covered by
    ``sub_windows`` equal sub-buckets (plus one boundary bucket in the ring)
    so expiry is a cheap slab subtraction instead of Redis TTLs
    (SURVEY.md §2.4.9, hard part #2).
    """

    depth: int = 4
    width: int = 65536
    sub_windows: int = 60
    #: Conservative update: only raise the counters that are below the new
    #: estimate; cuts CMS overestimate and therefore false denies
    #: (SURVEY.md hard part #3).
    conservative_update: bool = True
    seed: int = 0x5bd1e995
    #: Heavy-hitter exact side table: keys whose in-window estimate crosses
    #: ``hh_promote_fraction * limit`` are promoted into a direct-mapped
    #: table of ``hh_slots`` private per-key ring cells (exact counts, no
    #: collision error) and stop feeding the shared sketch. 0 disables.
    #: Helps the moderate-skew regime where a few keys carry most of the
    #: admitted mass (ROADMAP v0.2; ops/sketch_kernels.py docstring).
    hh_slots: int = 0
    hh_promote_fraction: float = 0.5
    #: What to do when the admitted in-window mass exceeds this geometry's
    #: calibrated budget (``mass_budget`` — the point where collision
    #: error passes ~1% false denies):
    #:   "warn"   (default) log loudly once per sub-window and keep
    #:            serving (accuracy silently degrades with load);
    #:   "strict" additionally REJECT new admissions while over budget —
    #:            prefer loud, bounded unavailability (extra denies, the
    #:            limiter's safe direction) over unbounded silent
    #:            misaccounting. The overload clears as history expires.
    #: Either way ``overload_periods`` counts offending sub-windows and is
    #: exported via /metrics and healthz (docs/OPERATIONS.md §3).
    overload_policy: str = "warn"
    #: Hot-loop kernel implementation (ADR-011):
    #:   "auto"   (default) fused Pallas kernels on TPU backends (when the
    #:            geometry fits the VMEM budget and no heavy-hitter side
    #:            table is configured), the jnp/XLA reference path
    #:            everywhere else;
    #:   "pallas" force the fused kernels — on non-TPU backends they run
    #:            in Pallas interpret mode (the CI parity lane), which is
    #:            bit-identical but slow: a correctness tool, not a
    #:            serving configuration;
    #:   "jnp"    force the XLA reference path (the pre-ADR-011 kernels,
    #:            kept as the parity oracle).
    #: Decisions are bit-identical across the three (tier-1 enforced by
    #: tests/test_pallas_parity.py). EXCLUDED from the checkpoint config
    #: fingerprint — an execution knob, not state geometry.
    kernels: str = "auto"

    def validate(self) -> None:
        if self.depth < 1 or self.depth > 16:
            raise InvalidConfigError(f"sketch depth must be in [1, 16], got {self.depth}")
        if self.width < 16 or (self.width & (self.width - 1)) != 0:
            raise InvalidConfigError(
                f"sketch width must be a power of two >= 16, got {self.width}")
        if self.sub_windows < 1 or self.sub_windows > 4096:
            raise InvalidConfigError(
                f"sketch sub_windows must be in [1, 4096], got {self.sub_windows}")
        if self.hh_slots != 0 and (
                self.hh_slots < 16 or self.hh_slots > (1 << 22)
                or (self.hh_slots & (self.hh_slots - 1)) != 0):
            raise InvalidConfigError(
                f"hh_slots must be 0 or a power of two in [16, 2^22], "
                f"got {self.hh_slots}")
        if not (0.0 < self.hh_promote_fraction <= 1.0):
            raise InvalidConfigError(
                f"hh_promote_fraction must be in (0, 1], "
                f"got {self.hh_promote_fraction}")
        if self.overload_policy not in ("warn", "strict"):
            raise InvalidConfigError(
                f"overload_policy must be 'warn' or 'strict', "
                f"got {self.overload_policy!r}")
        if self.kernels not in ("auto", "pallas", "jnp"):
            raise InvalidConfigError(
                f"sketch kernels must be 'auto', 'pallas' or 'jnp', "
                f"got {self.kernels!r}")

    # ------------------------------------------------- load-aware sizing
    #
    # CMS collision error scales with the total ADMITTED in-window mass
    # divided by width. The classic Markov bound (err <= e*M/w w.p.
    # 1-e^-d) is orders of magnitude loose for skewed traffic under
    # conservative update, so sizing here uses the calibrated operating
    # curve measured against the on-device exact oracle (bench.py /
    # benchmarks config 3, Zipf(1.1), conservative_update, depth >= 3):
    #
    #   mean cell load M/w = 2.0 * limit   ->  ~0.8%  false denies
    #   mean cell load M/w = 0.27 * limit  ->  ~0.006% false denies
    #
    # i.e. false_deny ~ (M/(w*limit))^2.5 about the 1% anchor; inverting
    # gives the multiplier k below. Uniform (non-skewed) key traffic has
    # less cell-load variance and needs more width for the same target —
    # pass ``safety > 1`` for such loads.

    @classmethod
    def for_load(cls, limit: int, expected_window_mass: float, *,
                 active_keys: Optional[int] = None,
                 target_false_deny: float = 0.01, depth: int = 4,
                 sub_windows: int = 60, safety: float = 1.0,
                 conservative_update: bool = True,
                 max_state_bytes: int = 4 << 30,
                 seed: int = 0x5bd1e995) -> "SketchParams":
        """Size a sketch geometry for an expected operating point.

        Two error regimes bound the width (both measured on-chip against
        the exact oracle, benchmarks config 3 round 4):

        * mass: collision error grows with admitted in-window mass per
          cell (the curve in the class comment above);
        * occupancy: once active keys outnumber cells, conservative-update
          estimates compound across co-resident keys regardless of mass
          (1M keys on a 2^19-cell d=4 sketch measured 1.7% false denies
          at a mass/cell the mass curve alone prices at <1%; the same
          mass at 1 key/cell measured 0.8%).

        Args:
            limit: the per-key limit the geometry will serve.
            expected_window_mass: expected total ADMITTED requests per
                window across all keys (offered load capped by limits:
                roughly ``min(offered_per_window, active_keys * limit)``).
            active_keys: expected in-window distinct keys; when given,
                width is floored at one cell per active key (the
                occupancy regime above).
            target_false_deny: acceptable steady-state false-deny rate
                vs an exact oracle at that mass (default 1%, the
                BASELINE budget).
            depth: CMS rows (>= 3 for the calibration to hold).
            safety: extra width multiplier for low-skew traffic.
            max_state_bytes: refuse geometries whose ring state would
                exceed this (the full ring is (sub_windows+1) slabs of
                depth x width int32 counters).

        Raises InvalidConfigError if no affordable geometry meets the
        target — undersizing silently is exactly the failure mode this
        exists to prevent (reference sizes its backend explicitly,
        ``docs/ADR/001-redis-as-storage-backend.md:183-187``).
        """
        if limit <= 0:
            raise InvalidConfigError(f"limit must be positive, got {limit}")
        if expected_window_mass <= 0:
            raise InvalidConfigError(
                f"expected_window_mass must be positive, got {expected_window_mass}")
        if not (0.0 < target_false_deny <= 0.5):
            raise InvalidConfigError(
                f"target_false_deny must be in (0, 0.5], got {target_false_deny}")
        if depth < 3:
            raise InvalidConfigError(
                f"for_load calibration requires depth >= 3, got {depth}")
        k = 2.0 * (100.0 * target_false_deny) ** 0.4 / max(safety, 1e-9)
        floor = max(expected_window_mass / (limit * k),
                    float(active_keys or 0))
        width = 16
        while width < floor:
            width *= 2
        state_bytes = (sub_windows + 1) * depth * width * 4
        if state_bytes > max_state_bytes:
            raise InvalidConfigError(
                f"no geometry within max_state_bytes={max_state_bytes}: "
                f"mass {expected_window_mass:g} at limit {limit} and "
                f"target {target_false_deny:g} needs width {width} "
                f"({state_bytes / 2 ** 30:.1f} GiB of ring state); raise "
                f"max_state_bytes, relax the target, or shard the keyspace")
        return cls(depth=depth, width=width, sub_windows=sub_windows,
                   conservative_update=conservative_update, seed=seed)

    def mass_budget(self, limit: int) -> int:
        """In-window admitted mass this geometry absorbs before collision
        error reaches ~1% false denies (the calibrated 1% anchor:
        mean cell load of 2x limit). The sketch limiter tracks admitted
        mass at runtime and warns loudly past this."""
        return int(2.0 * limit * self.width)


#: "Effectively unlimited" sentinel for hierarchy scope limits (requests
#: per window). Chosen so int64 scatter/cumsum math in the cascade kernel
#: can never overflow (avail * weight stays < 2^62 with weights <= 2^20)
#: while still being far beyond any real per-window admission volume.
HIER_UNLIMITED = 1 << 40


@dataclass(frozen=True)
class HierarchySpec:
    """Hierarchical cascade geometry (ratelimiter_tpu/hierarchy/, ADR-020).

    When ``tenants > 0`` the sketch-family decision step evaluates a
    CASCADE of scopes per request — key → tenant → global — with
    all-or-nothing admission in the same single device dispatch: tenant
    ids derive on device from a policy-table-style sorted key→tenant
    map, a per-tenant (+ global) counter slab updates in the same kernel
    pass, and contended global mass is clipped between tenants
    proportionally to their weights (weighted fair sharing).

    Like PolicySpec, these are *compiled-shape* parameters: the tenant
    slab is ``tenants + 1`` counters (index ``tenants`` is the global
    scope) and the key→tenant map is a fixed-capacity sorted array
    consulted by the same branchless binary search as the override
    table. The spec participates in the checkpoint config fingerprint
    ONLY when enabled (``tenants > 0``) so every pre-hierarchy snapshot
    stays restorable.

    Scope limits here are the CONFIGURED defaults (ceilings); the live
    *effective* limits move at runtime — operator calls or the AIMD
    controller (hierarchy/controller.py) — and ride checkpoints as
    ``hier_*`` columns. 0 means unlimited for both limit fields.
    """

    #: Tenant capacity, power of two in [2, 2^12] (tenant 0 is the
    #: implicit default tenant for unassigned keys). 0 disables the
    #: hierarchy subsystem entirely — zero hot-path cost.
    tenants: int = 0
    #: Key→tenant assignment map capacity; power of two (same binary-
    #: search geometry rule as PolicySpec.capacity).
    map_capacity: int = 1024
    #: Global-scope limit, requests per window across ALL keys
    #: (0 = unlimited).
    global_limit: int = 0
    #: Default per-tenant limit, requests per window (0 = unlimited);
    #: individual tenants override via set_tenant.
    default_tenant_limit: int = 0

    @property
    def enabled(self) -> bool:
        return self.tenants > 0

    def validate(self) -> None:
        t = self.tenants
        if t != 0 and (t < 2 or t > (1 << 12) or (t & (t - 1)) != 0):
            raise InvalidConfigError(
                f"hierarchy tenants must be 0 or a power of two in "
                f"[2, 2^12], got {t}")
        m = self.map_capacity
        if m < 8 or m > (1 << 20) or (m & (m - 1)) != 0:
            raise InvalidConfigError(
                f"hierarchy map_capacity must be a power of two in "
                f"[8, 2^20], got {m}")
        for name, v in (("global_limit", self.global_limit),
                        ("default_tenant_limit", self.default_tenant_limit)):
            if (not isinstance(v, int) or isinstance(v, bool) or v < 0
                    or v >= HIER_UNLIMITED):
                raise InvalidConfigError(
                    f"hierarchy {name} must be an integer in "
                    f"[0, 2^40), got {v!r}")


@dataclass(frozen=True)
class PolicySpec:
    """Geometry of the per-key override table (the policy engine,
    ratelimiter_tpu/policy/).

    ``capacity`` bounds how many keys may carry a tiered override at once.
    It is a *compiled-shape* parameter: the device-resident override table
    is a fixed-size sorted array consulted by a vectorized binary search
    inside every decision step, so capacity participates in the config
    fingerprint (checkpoints refuse to restore under a different policy
    geometry). Powers of two keep the branchless binary search exact in
    ``log2(capacity)`` steps.
    """

    #: Max simultaneous per-key overrides; power of two. 1024 entries cost
    #: ~40 KB of device memory — negligible next to any state backend.
    capacity: int = 1024

    def validate(self) -> None:
        if (self.capacity < 8 or self.capacity > (1 << 20)
                or (self.capacity & (self.capacity - 1)) != 0):
            raise InvalidConfigError(
                f"policy capacity must be a power of two in [8, 2^20], "
                f"got {self.capacity}")


@dataclass(frozen=True)
class PersistenceSpec:
    """Durability subsystem configuration (ratelimiter_tpu/persistence/).

    When ``dir`` is set, the limiter stack gains a write-ahead log for
    every non-decision mutation (policy overrides, resets, dynamic
    limit/window updates) plus async background snapshots, and recovery
    on startup replays the WAL suffix past the newest snapshot's
    watermark (docs/ADR/009). ``dir=None`` (the default) disables the
    subsystem entirely — zero hot-path cost.

    Deliberately EXCLUDED from the checkpoint config fingerprint
    (checkpoint.config_fingerprint): these are operational knobs, not
    state geometry — a snapshot taken at one cadence must restore under
    another.
    """

    #: Directory holding WAL segments, snapshots, and the manifest.
    #: None disables persistence.
    dir: Optional[str] = None
    #: Seconds between background snapshots (the crash-window bound on
    #: lost decisions).
    snapshot_interval: float = 30.0
    #: Also snapshot after this many WAL mutations (0 = interval only).
    snapshot_after_mutations: int = 0
    #: Snapshots retained on disk (older ones + their WAL prefix are
    #: pruned after each successful snapshot).
    retain: int = 3
    #: WAL fsync policy: "always" (fsync every append — mutations are
    #: rare control-plane ops, so this is the default), "interval"
    #: (fsync at most every ``wal_fsync_interval`` seconds), "never"
    #: (leave it to the OS; a power loss may drop the tail).
    wal_fsync: str = "always"
    wal_fsync_interval: float = 0.05
    #: WAL segment rotation threshold, bytes.
    wal_max_bytes: int = 64 << 20

    @property
    def enabled(self) -> bool:
        return self.dir is not None

    def validate(self) -> None:
        if self.dir is not None and not isinstance(self.dir, str):
            raise InvalidConfigError(
                f"persistence dir must be a path string or None, "
                f"got {self.dir!r}")
        if not (self.snapshot_interval > 0):
            raise InvalidConfigError(
                f"snapshot_interval must be > 0, "
                f"got {self.snapshot_interval!r}")
        if self.snapshot_after_mutations < 0:
            raise InvalidConfigError(
                f"snapshot_after_mutations must be >= 0, "
                f"got {self.snapshot_after_mutations!r}")
        if self.retain < 1:
            raise InvalidConfigError(
                f"retain must be >= 1, got {self.retain!r}")
        if self.wal_fsync not in ("always", "interval", "never"):
            raise InvalidConfigError(
                f"wal_fsync must be 'always', 'interval' or 'never', "
                f"got {self.wal_fsync!r}")
        if not (self.wal_fsync_interval > 0):
            raise InvalidConfigError(
                f"wal_fsync_interval must be > 0, "
                f"got {self.wal_fsync_interval!r}")
        if self.wal_max_bytes < 4096:
            raise InvalidConfigError(
                f"wal_max_bytes must be >= 4096, got {self.wal_max_bytes!r}")


@dataclass(frozen=True)
class MeshSpec:
    """Slice-parallel serving deployment (``--backend mesh``, ADR-012).

    ``devices`` caps how many visible accelerator devices the sliced mesh
    limiter spans (None = all of them). Each device holds an independent,
    device-pinned single-chip limiter slice; the serving tier routes every
    key to its owning slice by hash, so the decide path is collective-free
    and per-key decisions are bit-identical to a single-device limiter.

    Deliberately EXCLUDED from the checkpoint config fingerprint: the
    device count is a *placement* property, not state geometry — but a
    sliced snapshot still refuses to restore onto a different slice count
    (each slice's counters are only meaningful under the routing that
    produced them; SlicedMeshLimiter.restore raises CheckpointError).
    """

    #: Devices to span (None = every visible device; must be >= 1).
    devices: Optional[int] = None
    #: Frame routing mode (ADR-024). "host" = the ADR-013 scatter-gather
    #: scheduler (host argsort partition, per-slice sub-launches, barrier
    #: + index-map scatter). "collective" = one shard_map'd SPMD dispatch
    #: per frame: owners computed on device, rows all-to-all'd to their
    #: slices, verdicts all-to-all'd back — the host never partitions.
    #: Decisions are bit-identical either way (same ``h64 % n`` owner
    #: rule, same kernels); "collective" targets real accelerator meshes
    #: where ICI beats host phases, and falls back to the host router on
    #: bin overflow (so admission is never dropped) and under the strict
    #: overload policy.
    router: str = "host"
    #: Collective-router bin headroom: per-(source, destination) bin
    #: capacity is ``ceil(bin_headroom * shard_len / devices)``. Uniform
    #: mixed traffic fills bins to ~1/headroom; skewed frames that
    #: overflow a bin fall back to the host router (ADR-024 trade-off).
    bin_headroom: float = 2.0
    #: Failure-domain isolation (ADR-015): wrap every slice in a
    #: quarantine guard — per-slice dispatch deadline + failure
    #: classifier, degraded per-range answers per ``fail_open``, and
    #: half-open probe recovery with restore-before-rejoin. OFF by
    #: default: the guard adds one executor hop per slice resolve, and
    #: the no-quarantine hot path must stay byte-identical.
    quarantine: bool = False
    #: Per-slice sub-dispatch deadline, seconds: a slice that has not
    #: resolved within this budget is classified failed and its key
    #: range degrades (only that range — other slices stay exact).
    slice_deadline: float = 0.25
    #: Seconds a quarantined slice waits before each half-open probe.
    probe_interval: float = 1.0
    #: Consecutive classified failures before a slice quarantines
    #: (1 = first fault quarantines; the failure already degraded that
    #: frame's range either way).
    failure_threshold: int = 1

    def validate(self) -> None:
        if self.devices is not None and (
                not isinstance(self.devices, int) or self.devices < 1):
            raise InvalidConfigError(
                f"mesh devices must be a positive integer or None, "
                f"got {self.devices!r}")
        if self.slice_deadline <= 0:
            raise InvalidConfigError(
                f"mesh slice_deadline must be positive, "
                f"got {self.slice_deadline!r}")
        if self.probe_interval <= 0:
            raise InvalidConfigError(
                f"mesh probe_interval must be positive, "
                f"got {self.probe_interval!r}")
        if not isinstance(self.failure_threshold, int) \
                or self.failure_threshold < 1:
            raise InvalidConfigError(
                f"mesh failure_threshold must be an integer >= 1, "
                f"got {self.failure_threshold!r}")
        if self.router not in ("host", "collective"):
            raise InvalidConfigError(
                f"mesh router must be 'host' or 'collective', "
                f"got {self.router!r}")
        if self.router == "collective" and self.quarantine:
            raise InvalidConfigError(
                "router='collective' is incompatible with quarantine: a "
                "collective dispatch is ONE mesh-wide execution, so a "
                "single slice's fault has whole-mesh blast radius and "
                "per-slice failure domains cannot contain it (ADR-024). "
                "Use router='host' for quarantined deployments.")
        if not (self.bin_headroom > 0):
            raise InvalidConfigError(
                f"mesh bin_headroom must be positive, "
                f"got {self.bin_headroom!r}")


@dataclass(frozen=True)
class DenseParams:
    """Geometry of the dense (exact, slot-addressed) device backend."""

    #: Maximum number of distinct live keys; key -> slot assignment happens
    #: host-side at ingest.
    capacity: int = 1 << 16

    def validate(self) -> None:
        if self.capacity < 1:
            raise InvalidConfigError(f"dense capacity must be positive, got {self.capacity}")


@dataclass(frozen=True)
class Config:
    """User-facing limiter configuration (reference ``interface.go:46-70``).

    Attributes:
        algorithm: which algorithm decides (reference field ``Algorithm``).
        limit: max requests per window (reference field ``Limit``); > 0.
        window: window duration in float seconds (reference field ``Window``);
            bounds 1 ms .. 365 d (``config.go:31-47``).
        key_prefix: namespace prepended to every key. None -> DEFAULT_PREFIX;
            "" -> genuinely no prefix (see module docstring).
        fail_open: on backend failure allow (True) or raise (False)
            (reference ``interface.go:65-69``, ADR-002).
        max_batch_admission_iters: fixpoint iterations for same-key mixed-n
            sequencing inside one batch (exact for uniform n; see
            ops/segment.py).
        sketch: CMS geometry (TPU_SKETCH / sketch backend only).
        dense: dense-store geometry (dense backend only).
        policy: per-key override table geometry (the policy engine;
            every backend consults it inside its decision step).
        persistence: durability subsystem knobs (WAL + async snapshots;
            disabled unless ``persistence.dir`` is set). NOT part of the
            checkpoint fingerprint — operational, not state geometry.
        mesh: slice-parallel serving placement (``--backend mesh``,
            ADR-012). NOT part of the checkpoint fingerprint (placement,
            not geometry); slice-count mismatches are refused separately
            on restore.
        hierarchy: hierarchical cascade geometry (tenant scopes + global
            scope + weighted fair sharing, ADR-020). Disabled by default
            (``tenants=0``); participates in the checkpoint fingerprint
            only when enabled, so pre-hierarchy snapshots stay valid.
    """

    algorithm: Algorithm
    limit: int
    window: float
    key_prefix: Optional[str] = None
    fail_open: bool = False
    max_batch_admission_iters: int = 4
    sketch: SketchParams = field(default_factory=SketchParams)
    dense: DenseParams = field(default_factory=DenseParams)
    policy: PolicySpec = field(default_factory=PolicySpec)
    persistence: PersistenceSpec = field(default_factory=PersistenceSpec)
    mesh: MeshSpec = field(default_factory=MeshSpec)
    hierarchy: HierarchySpec = field(default_factory=HierarchySpec)

    def validate(self) -> None:
        """Reference ``Config.Validate`` (``config.go:16-50``), same bounds."""
        if not isinstance(self.algorithm, Algorithm):
            raise InvalidConfigError(f"invalid algorithm: {self.algorithm!r}")
        if not isinstance(self.limit, int) or isinstance(self.limit, bool) or self.limit <= 0:
            raise InvalidConfigError(f"limit must be a positive integer, got {self.limit!r}")
        w = float(self.window)
        if w < MIN_WINDOW_SECONDS:
            raise InvalidConfigError(
                f"window must be at least 1ms, got {self.window!r}")
        if w > MAX_WINDOW_SECONDS:
            raise InvalidConfigError(
                f"window must be at most 365 days, got {self.window!r}")
        if self.max_batch_admission_iters < 1:
            raise InvalidConfigError(
                "max_batch_admission_iters must be >= 1, "
                f"got {self.max_batch_admission_iters}")
        self.sketch.validate()
        self.dense.validate()
        self.policy.validate()
        self.persistence.validate()
        self.mesh.validate()
        self.hierarchy.validate()

    def with_defaults(self) -> "Config":
        """Non-mutating defaulting (reference ``config.go:54-67``): returns a
        copy with ``key_prefix=None`` resolved to DEFAULT_PREFIX."""
        if self.key_prefix is None:
            return replace(self, key_prefix=DEFAULT_PREFIX)
        return self

    @property
    def prefix(self) -> str:
        """Resolved prefix ("" means no prefix)."""
        return DEFAULT_PREFIX if self.key_prefix is None else self.key_prefix

    def format_key(self, key: str, *parts: object) -> str:
        """Reference ``config.go:81-87`` + the per-algorithm window suffixing
        (``fixedwindow.go:139-141``): ``prefix:key[:part...]``; no leading
        colon when prefix is ""."""
        base = f"{self.prefix}:{key}" if self.prefix else key
        for p in parts:
            base = f"{base}:{p}"
        return base

    @property
    def refill_rate(self) -> float:
        """Token-bucket refill rate in tokens/second = limit / window
        (reference ``tokenbucket.go:155-157``)."""
        return self.limit / float(self.window)
