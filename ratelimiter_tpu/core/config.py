"""Configuration, validation, defaults, and key formatting.

Parity with reference ``internal/ratelimiter/config.go`` and the Config struct
(``interface.go:46-70``): algorithm, limit, window, key prefix, fail-open.
Extended with the TPU deployment axis (sketch geometry, dense capacity,
admission-scan iterations) per SURVEY.md §5.6.

Divergence note (deliberate, SURVEY.md §2.4.8): in the reference an
empty-string prefix means "no prefix" inside ``FormatKey`` (``config.go:71-77``)
but ``WithDefaults`` re-instates the default prefix, so "no prefix" is
unreachable through public constructors. Here ``key_prefix=None`` (the default)
means "use DEFAULT_PREFIX" and ``key_prefix=""`` genuinely means "no prefix" —
the documented behavior becomes reachable. tests/test_config.py pins both.
"""

from __future__ import annotations

from dataclasses import dataclass, field, replace
from typing import Optional

from ratelimiter_tpu.core.errors import InvalidConfigError
from ratelimiter_tpu.core.types import Algorithm

#: Reference ``config.go:11``.
DEFAULT_PREFIX = "ratelimit"

#: Reference bounds, ``config.go:31-47``.
MIN_WINDOW_SECONDS = 0.001
MAX_WINDOW_SECONDS = 365.0 * 24 * 3600


@dataclass(frozen=True)
class SketchParams:
    """Geometry of the count-min sketch backend (BASELINE.json configs 3-5).

    depth × width int32 counters shared by all keys; the window is covered by
    ``sub_windows`` equal sub-buckets (plus one boundary bucket in the ring)
    so expiry is a cheap slab subtraction instead of Redis TTLs
    (SURVEY.md §2.4.9, hard part #2).
    """

    depth: int = 4
    width: int = 65536
    sub_windows: int = 60
    #: Conservative update: only raise the counters that are below the new
    #: estimate; cuts CMS overestimate and therefore false denies
    #: (SURVEY.md hard part #3).
    conservative_update: bool = True
    seed: int = 0x5bd1e995

    def validate(self) -> None:
        if self.depth < 1 or self.depth > 16:
            raise InvalidConfigError(f"sketch depth must be in [1, 16], got {self.depth}")
        if self.width < 16 or (self.width & (self.width - 1)) != 0:
            raise InvalidConfigError(
                f"sketch width must be a power of two >= 16, got {self.width}")
        if self.sub_windows < 1 or self.sub_windows > 4096:
            raise InvalidConfigError(
                f"sketch sub_windows must be in [1, 4096], got {self.sub_windows}")


@dataclass(frozen=True)
class DenseParams:
    """Geometry of the dense (exact, slot-addressed) device backend."""

    #: Maximum number of distinct live keys; key -> slot assignment happens
    #: host-side at ingest.
    capacity: int = 1 << 16

    def validate(self) -> None:
        if self.capacity < 1:
            raise InvalidConfigError(f"dense capacity must be positive, got {self.capacity}")


@dataclass(frozen=True)
class Config:
    """User-facing limiter configuration (reference ``interface.go:46-70``).

    Attributes:
        algorithm: which algorithm decides (reference field ``Algorithm``).
        limit: max requests per window (reference field ``Limit``); > 0.
        window: window duration in float seconds (reference field ``Window``);
            bounds 1 ms .. 365 d (``config.go:31-47``).
        key_prefix: namespace prepended to every key. None -> DEFAULT_PREFIX;
            "" -> genuinely no prefix (see module docstring).
        fail_open: on backend failure allow (True) or raise (False)
            (reference ``interface.go:65-69``, ADR-002).
        max_batch_admission_iters: fixpoint iterations for same-key mixed-n
            sequencing inside one batch (exact for uniform n; see
            ops/segment.py).
        sketch: CMS geometry (TPU_SKETCH / sketch backend only).
        dense: dense-store geometry (dense backend only).
    """

    algorithm: Algorithm
    limit: int
    window: float
    key_prefix: Optional[str] = None
    fail_open: bool = False
    max_batch_admission_iters: int = 4
    sketch: SketchParams = field(default_factory=SketchParams)
    dense: DenseParams = field(default_factory=DenseParams)

    def validate(self) -> None:
        """Reference ``Config.Validate`` (``config.go:16-50``), same bounds."""
        if not isinstance(self.algorithm, Algorithm):
            raise InvalidConfigError(f"invalid algorithm: {self.algorithm!r}")
        if not isinstance(self.limit, int) or isinstance(self.limit, bool) or self.limit <= 0:
            raise InvalidConfigError(f"limit must be a positive integer, got {self.limit!r}")
        w = float(self.window)
        if w < MIN_WINDOW_SECONDS:
            raise InvalidConfigError(
                f"window must be at least 1ms, got {self.window!r}")
        if w > MAX_WINDOW_SECONDS:
            raise InvalidConfigError(
                f"window must be at most 365 days, got {self.window!r}")
        if self.max_batch_admission_iters < 1:
            raise InvalidConfigError(
                "max_batch_admission_iters must be >= 1, "
                f"got {self.max_batch_admission_iters}")
        self.sketch.validate()
        self.dense.validate()

    def with_defaults(self) -> "Config":
        """Non-mutating defaulting (reference ``config.go:54-67``): returns a
        copy with ``key_prefix=None`` resolved to DEFAULT_PREFIX."""
        if self.key_prefix is None:
            return replace(self, key_prefix=DEFAULT_PREFIX)
        return self

    @property
    def prefix(self) -> str:
        """Resolved prefix ("" means no prefix)."""
        return DEFAULT_PREFIX if self.key_prefix is None else self.key_prefix

    def format_key(self, key: str, *parts: object) -> str:
        """Reference ``config.go:81-87`` + the per-algorithm window suffixing
        (``fixedwindow.go:139-141``): ``prefix:key[:part...]``; no leading
        colon when prefix is ""."""
        base = f"{self.prefix}:{key}" if self.prefix else key
        for p in parts:
            base = f"{base}:{p}"
        return base

    @property
    def refill_rate(self) -> float:
        """Token-bucket refill rate in tokens/second = limit / window
        (reference ``tokenbucket.go:155-157``)."""
        return self.limit / float(self.window)
