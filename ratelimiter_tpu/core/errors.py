"""Error types.

Mirrors the sentinel errors of reference ``internal/ratelimiter/errors.go:5-20``.
Unlike the reference — where only ``ErrInvalidN`` is ever raised and
``ErrInvalidConfig``/``ErrStorageUnavailable``/``ErrInvalidKey``/``ErrClosed``
are dead (SURVEY.md §2.1 row 4) — every error here has live raising sites and
is covered by the contract suite.
"""

from __future__ import annotations


class RateLimiterError(Exception):
    """Base class for all ratelimiter_tpu errors."""


class InvalidConfigError(RateLimiterError, ValueError):
    """Raised when a Config fails validation.

    Reference: ``ErrInvalidConfig`` (``errors.go:7``) + the per-field
    validation messages of ``config.go:16-50``.
    """


class InvalidKeyError(RateLimiterError, ValueError):
    """Raised when a request key is empty or not a string.

    Reference: ``ErrInvalidKey`` (``errors.go:13``) — defined there but never
    checked; the dormant contract suite expects it
    (``interface_test.go:246-251``). We honor the documented contract.
    """


class InvalidNError(RateLimiterError, ValueError):
    """Raised when allow_n is called with n <= 0.

    Reference: ``ErrInvalidN`` (``errors.go:10``), raised pre-backend in all
    three algorithms (e.g. ``tokenbucket.go:91-93``).
    """


class StorageUnavailableError(RateLimiterError, RuntimeError):
    """Raised (fail-closed) when the state backend cannot serve a decision.

    Reference: ``ErrStorageUnavailable`` (``errors.go:16``); fail-closed
    returns a wrapped error and *no* Result
    (``fixedwindow_integration_test.go:271-273``) — here that is an exception.
    """


class ClosedError(RateLimiterError, RuntimeError):
    """Raised when a limiter is used after close().

    Reference: ``ErrClosed`` (``errors.go:19``) — defined, never used. Here
    every public method checks it.
    """


class DeadlineExceededError(RateLimiterError, RuntimeError):
    """Raised (fail-closed) when a request's propagated deadline expired
    before its dispatch ran — the server sheds the work instead of
    burning a dispatch slot on an answer nobody is waiting for
    (ADR-015). Fail-open configs answer a fail-open allowance instead.

    No reference analog: the reference's per-decision Redis round-trip
    has no queueing stage where a deadline could be checked.
    """


class RequestTimeoutError(RateLimiterError, TimeoutError):
    """Raised by the blocking Client when one call's read deadline
    expires mid-stream. Names the pending request (``request_id`` /
    ``request_type``) and marks the connection desynchronized: the next
    call reconnects (or resyncs by draining the stale frame) — it can
    NEVER return the timed-out frame's result as its own (ADR-015;
    the pre-PR-8 behavior left the wire misaligned).
    """

    def __init__(self, msg: str, *, request_id: int = 0,
                 request_type: int = 0):
        super().__init__(msg)
        self.request_id = int(request_id)
        self.request_type = int(request_type)


class CheckpointError(RateLimiterError, RuntimeError):
    """Raised when a state snapshot cannot be written or restored (missing
    file, wrong format, or a config fingerprint mismatch).

    No reference analog: the reference delegates durability to Redis
    (``docs/ADR/001:51-52``); HBM-resident state makes snapshotting an
    explicit subsystem here (SURVEY.md §5.4, ratelimiter_tpu/checkpoint.py).
    """


class NotOwnerError(RateLimiterError, RuntimeError):
    """Typed fleet redirect (ADR-017): the server answering this frame
    does not own the keys' hash buckets under its (newer) ownership
    epoch, and forwarding is off (``--fleet-no-forward``) or impossible.
    The message is machine-parseable (``protocol.parse_not_owner``) and
    names the owner's address plus the answering server's epoch, so a
    stale router refreshes its map and re-routes instead of retrying the
    wrong host forever. ``owner``/``epoch`` are populated when the error
    was parsed off the wire or raised by a fleet router."""

    def __init__(self, msg: str, *, owner: str = "", epoch: int = 0):
        super().__init__(msg)
        self.owner = owner
        self.epoch = int(epoch)
