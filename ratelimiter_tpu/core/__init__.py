"""Core types, configuration, errors and clock for ratelimiter_tpu.

Capability parity with reference ``internal/ratelimiter/{interface,config,
result,errors}.go`` (L3 in SURVEY.md §1), with the reference's dead code
made live: result constructors are used by every backend, every error
sentinel has a raising site, and empty keys are rejected (the reference
defines ``ErrInvalidKey`` but never checks it — ``errors.go:13``,
``interface_test.go:246-251``).
"""
