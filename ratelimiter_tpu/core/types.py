"""Algorithm enum, Result, and BatchResult.

Parity with reference ``internal/ratelimiter/interface.go:9-43`` and
``result.go:5-49``. The reference's result constructors are dead code
(defined + tested, never called — SURVEY.md §2.1 row 3); here they are the
only way backends build results, so the semantics in one place:

* allowed  -> remaining = post-decision remaining quota, retry_after = 0
* denied   -> remaining clamped >= 0, retry_after > 0 (algorithm-specific)
* fail-open  (backend down, Config.fail_open=True)  -> allowed, remaining 0
  (reference ``tokenbucket.go:103-110``)
* fail-closed (backend down, fail_open=False) -> raises
  StorageUnavailableError; there is deliberately no Result for it
  (reference returns nil result + error, ``fixedwindow_integration_test.go:271-273``).
"""

from __future__ import annotations

import enum
from dataclasses import dataclass

import numpy as np


class Algorithm(enum.Enum):
    """Rate-limiting algorithm (reference ``interface.go:9-23``) plus this
    framework's own ``TPU_SKETCH`` (BASELINE.json north star)."""

    TOKEN_BUCKET = "token_bucket"
    SLIDING_WINDOW = "sliding_window"
    FIXED_WINDOW = "fixed_window"
    #: Count-min-sketch + sub-window decay; approximate, unbounded key space,
    #: the TPU-native flagship. Semantics follow SLIDING_WINDOW.
    TPU_SKETCH = "tpu_sketch"

    def __str__(self) -> str:  # str(Algorithm.TOKEN_BUCKET) == "token_bucket"
        return self.value


@dataclass(frozen=True)
class Result:
    """Outcome of one allow / allow_n decision (reference ``interface.go:26-43``).

    Attributes:
        allowed: whether the request may proceed.
        limit: the configured limit (for X-RateLimit-Limit headers).
        remaining: quota remaining after this decision, clamped >= 0.
        retry_after: seconds until a retry may succeed; 0 when allowed.
        reset_at: unix seconds when the limit fully resets.
        fail_open: True iff this is a backend-failure fail-open allowance.
    """

    allowed: bool
    limit: int
    remaining: int
    retry_after: float
    reset_at: float
    fail_open: bool = False


def allowed_result(limit: int, remaining: int, reset_at: float) -> Result:
    """Reference ``result.go:6-14`` (NewAllowedResult)."""
    return Result(allowed=True, limit=limit, remaining=max(0, int(remaining)),
                  retry_after=0.0, reset_at=reset_at)


def denied_result(limit: int, remaining: int, retry_after: float,
                  reset_at: float) -> Result:
    """Reference ``result.go:17-26`` (NewDeniedResult); retry_after clamped
    >= 0 the way every algorithm clamps it (``fixedwindow.go:110-112``)."""
    return Result(allowed=False, limit=limit, remaining=max(0, int(remaining)),
                  retry_after=max(0.0, float(retry_after)), reset_at=reset_at)


def fail_open_result(limit: int, reset_at: float) -> Result:
    """Reference ``result.go:29-38``: backend down + fail_open -> allow with
    remaining=0 (``tokenbucket.go:103-110``)."""
    return Result(allowed=True, limit=limit, remaining=0, retry_after=0.0,
                  reset_at=reset_at, fail_open=True)


@dataclass
class BatchResult:
    """Vectorized outcome of allow_batch — the TPU-native first-class shape.

    All arrays are NumPy, length = number of requests, in request order.
    ``result(i)`` materializes a scalar Result for interop with the scalar
    API (e.g. the serving fan-out).
    """

    allowed: np.ndarray      # bool[B]
    limit: int
    remaining: np.ndarray    # int64[B], post-decision, clamped >= 0
    retry_after: np.ndarray  # float64[B] seconds, 0 where allowed
    reset_at: np.ndarray     # float64[B] unix seconds
    fail_open: bool = False
    #: Per-request effective limits when policy overrides touched this
    #: batch (int64[B]); None means every request saw the uniform `limit`.
    limits: "np.ndarray | None" = None
    #: Device-packed wire buffers ``(bits u8[padded/8], words
    #: i64[3*padded], padded)`` when the dispatch was launched
    #: ``wire=True`` (sketch_kernels.pack_wire, ADR-011):
    #: protocol.encode_result_hashed frames straight from these with
    #: slice memcpys instead of re-bit-packing the allow mask. A
    #: 4-tuple ``(bits, words, padded, row_off)`` is the row-window form
    #: produced by ``rows()`` (ADR-013): the same buffers, framing the
    #: ``row_off``-based sub-range.
    wire_packed: "tuple | None" = None

    def __len__(self) -> int:
        return int(self.allowed.shape[0])

    def result(self, i: int) -> Result:
        return Result(
            allowed=bool(self.allowed[i]),
            limit=(int(self.limits[i]) if self.limits is not None
                   else self.limit),
            remaining=int(self.remaining[i]),
            retry_after=float(self.retry_after[i]),
            reset_at=float(self.reset_at[i]),
            fail_open=self.fail_open,
        )

    def results(self) -> list[Result]:
        return [self.result(i) for i in range(len(self))]

    def rows(self, off: int, count: int) -> "BatchResult":
        """A contiguous row-range VIEW of this result (the scatter-gather
        scheduler's per-frame slice of a coalesced window, ADR-013): all
        arrays are numpy views, and device-packed wire buffers ride
        along as a row-offset form ``(bits, words, padded, off)`` so the
        wire encoder still frames the sub-range zero-copy
        (protocol.encode_result_hashed_views). ``fail_open`` is the
        window's OR — a frame coalesced with a failed-open neighbor
        reports conservatively that some answers may be fabricated."""
        wp = self.wire_packed
        if wp is not None:
            bits, words, padded = wp[0], wp[1], wp[2]
            base = wp[3] if len(wp) > 3 else 0
            wp = (bits, words, padded, base + off)
        return BatchResult(
            allowed=self.allowed[off:off + count],
            limit=self.limit,
            remaining=self.remaining[off:off + count],
            retry_after=self.retry_after[off:off + count],
            reset_at=self.reset_at[off:off + count],
            fail_open=self.fail_open,
            limits=(self.limits[off:off + count]
                    if self.limits is not None else None),
            wire_packed=wp,
        )

    @property
    def allow_count(self) -> int:
        return int(np.sum(self.allowed))


def batch_fail_open(n: int, limit: int, reset_at: float) -> BatchResult:
    """Whole-batch fail-open (dispatch failure with Config.fail_open=True)."""
    return BatchResult(
        allowed=np.ones(n, dtype=bool),
        limit=limit,
        remaining=np.zeros(n, dtype=np.int64),
        retry_after=np.zeros(n, dtype=np.float64),
        reset_at=np.full(n, reset_at, dtype=np.float64),
        fail_open=True,
    )


class DispatchTicket:
    """Handle to one *launched* batched dispatch (the pipelined serving hot
    path, ADR-010).

    ``limiter.launch_batch`` / ``launch_hashed`` stage the batch, enqueue
    the jitted step, and return one of these WITHOUT blocking on the
    device; ``limiter.resolve(ticket)`` blocks until that dispatch's
    results are readable and assembles the BatchResult. Sequential
    semantics across in-flight tickets are preserved by state threading
    (each launch consumes the previous launch's donated state buffers),
    not by host blocking — resolve order does not affect counters.

    Backends without an async device path (exact) pre-resolve at launch:
    ``result`` is already set and resolve just returns it.
    """

    __slots__ = ("outs", "b", "limit", "limits", "ns", "now_us", "t_sec",
                 "slot", "padded", "result", "meta", "wire", "trace_id",
                 "audit")

    def __init__(self, result: "BatchResult | None" = None):
        self.outs = None        # device-side (allowed, remaining, retry, reset)
        self.b = len(result) if result is not None else 0
        self.limit = result.limit if result is not None else 0
        self.limits = None      # host per-request override limits (or None)
        self.ns = None          # host ns[:b] (admitted-mass accounting)
        self.now_us = 0
        self.t_sec = 0.0
        self.slot = None        # staging buffers to recycle at resolve
        self.padded = 0
        self.result = result    # set once resolved (or pre-resolved)
        self.meta = None        # decorator/door bookkeeping rides along
        self.wire = False       # outs are device-packed (bits, words)
        #                         wire buffers (sketch_kernels.pack_wire)
        self.trace_id = 0       # flight-recorder trace context (ADR-014);
        #                         0 = unsampled. Set by the serving doors
        #                         at launch so resolve-side spans (incl.
        #                         mesh per-slice spans) link to the frame.
        self.audit = None       # (h64, ns) pinned by the native door's
        #                         launch callbacks ONLY while the live
        #                         auditor is on (ADR-016), so resolve can
        #                         mirror the frame into the shadow-oracle
        #                         tap; None when auditing is off.

    @property
    def resolved(self) -> bool:
        return self.result is not None
