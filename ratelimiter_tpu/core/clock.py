"""Injectable clocks.

The reference takes timestamps from the Go process clock (``time.Now()``,
e.g. ``tokenbucket.go:97``) and tests fake time at the *storage* level with
miniredis ``FastForward`` (SURVEY.md §4.2.2). Here time is an explicit operand
of every decision — host-captured at batch assembly and passed into the device
call as a scalar — so virtual time is first-class and deterministic.

Internally, device kernels take time as int64 **microseconds** (float32 cannot
represent unix-epoch seconds to better than ~256 s; float64 is off by default
on TPU). The public API speaks float seconds.
"""

from __future__ import annotations

import time
from typing import Protocol, runtime_checkable

MICROS = 1_000_000


def to_micros(seconds: float) -> int:
    """Convert float seconds to int64 microseconds (round-to-nearest)."""
    return int(round(seconds * MICROS))


def from_micros(micros: int) -> float:
    return micros / MICROS


@runtime_checkable
class Clock(Protocol):
    def now(self) -> float:
        """Current time as float unix seconds."""
        ...


class SystemClock:
    """Wall clock."""

    def now(self) -> float:
        return time.time()


class ManualClock:
    """Deterministic clock for tests; the analog of miniredis FastForward
    (reference ``fixedwindow_integration_test.go:174``) but exact, and it
    supports negative advances the same way the reference's tests back-date
    state (``slidingwindow_integration_test.go:389``)."""

    def __init__(self, start: float = 1_700_000_000.0):
        self._now = float(start)

    def now(self) -> float:
        return self._now

    def advance(self, seconds: float) -> None:
        self._now += seconds

    def set(self, seconds: float) -> None:
        self._now = float(seconds)
