"""Collective mesh router — one SPMD dispatch per frame (ADR-024).

``CollectiveMeshLimiter`` is the ``MeshSpec.router="collective"`` twin
of the host-routed ``SlicedMeshLimiter`` (ADR-013). State placement is
IDENTICAL — one independent, device-pinned single-chip limiter per
device, every key owned by ``h64 % n`` — but a frame is dispatched as
ONE jitted shard_map step over the slice mesh
(ops/route_kernels.build_routed_step): each device takes an even 1/n
shard of the frame columns, computes owners on device, all-to-all's
rows to their owning slices, runs the unchanged fused decision kernel
against its own slice state, and all-to-all's the verdicts back to
frame order. The host stages two columns and fetches four; it never
argsorts, never builds index maps, never fans out sub-launches, and
resolve blocks on ONE ticket.

Because the per-slice states stay exactly where the host router keeps
them (``self.slices[i]._state``, assembled zero-copy into a global
sharded array per launch and written back shard-by-shard), everything
else — control plane, policy overrides, hierarchy cascade,
capture/restore (including cross-slice-count re-bucketing), chaos
injection, stats — is inherited from SlicedMeshLimiter unchanged, and
decisions are bit-identical to the host-routed oracle
(tests/test_collective_router.py pins it).

Escape hatches back to the host router (never silent):

* bin overflow — a frame whose per-(source, destination) row count
  exceeds the static bin capacity sets a device-computed flag; the step
  leaves state untouched and resolve re-dispatches the ORIGINAL frame
  through the inherited host router (each row admitted exactly once);
* strict overload policy — the windowed sketch's strict gate is a
  per-slice host-side admission decision that must see each slice's
  offered mass BEFORE dispatch; collective frames route host-side when
  it is enabled;
* quarantine is REFUSED at config validation: a collective dispatch has
  whole-mesh blast radius, so per-slice failure domains cannot hold
  (docs/ADR/024, "blast radius").
"""

from __future__ import annotations

import threading
from typing import Optional, Sequence

import numpy as np

from ratelimiter_tpu.algorithms.sketch import _pad_size
from ratelimiter_tpu.core.clock import Clock, to_micros
from ratelimiter_tpu.core.config import Config
from ratelimiter_tpu.core.errors import StorageUnavailableError
from ratelimiter_tpu.core.types import (
    BatchResult,
    DispatchTicket,
    batch_fail_open,
)
from ratelimiter_tpu.observability import tracing
from ratelimiter_tpu.parallel.limiter import SlicedMeshLimiter


class CollectiveDispatchTicket(DispatchTicket):
    """Ticket for one collective frame dispatch.

    ``outs`` holds the device-side result tuple (allowed, remaining,
    retry, reset, per-slice admitted mass, overflow flag). The original
    frame columns ride along so the overflow fallback can re-dispatch
    through the host router with the ORIGINAL decision timestamp."""

    __slots__ = ("arrays", "premix", "wire_lane")

    def __init__(self, result=None):
        super().__init__(result)
        self.arrays = None
        self.premix = False
        self.wire_lane = False


class CollectiveMeshLimiter(SlicedMeshLimiter):
    """Sliced mesh limiter whose decide path is one collective dispatch
    (``MeshSpec.router="collective"``, ADR-024)."""

    def __init__(self, config: Config, clock: Optional[Clock] = None, *,
                 n_devices: Optional[int] = None,
                 devices: Optional[Sequence] = None):
        super().__init__(config, clock, n_devices=n_devices,
                         devices=devices)
        if self.quarantine is not None:  # pragma: no cover - config gate
            from ratelimiter_tpu.core.errors import InvalidConfigError

            raise InvalidConfigError(
                "router='collective' cannot wrap slices in quarantine "
                "guards (whole-mesh blast radius; MeshSpec.validate "
                "refuses this combination)")
        from jax.sharding import Mesh

        self.mesh = Mesh(np.asarray([s._device for s in self.slices]),
                         ("chips",))
        from ratelimiter_tpu.ops import route_kernels

        _, self._mut_keys, self._ro_keys = route_kernels.state_layout(
            self.config)
        #: Serializes collective dispatches: the step is one mesh-wide
        #: execution, and the per-slice state assembly/writeback must be
        #: atomic against control-plane and capture paths (which take
        #: the per-slice locks this launch also holds, in slice order).
        self._mesh_lock = threading.Lock()
        self._ro_cache: dict = {}
        self._pol_dev = None
        self._pol_ver = -1
        self._hier_dev_mesh = None
        self._hier_ver = -1
        #: Host-router fallbacks taken (overflow or strict gate) —
        #: surfaced in consumer stats for the bench's route-phase story.
        self.fallbacks = 0
        self._strict_gate = bool(getattr(self.slices[0], "_strict", False))
        self._cpu = self.mesh.devices.flat[0].platform == "cpu"

    # ----------------------------------------------------- table operands

    def _policy_mesh(self):
        """Mesh-replicated device copy of the override table (slices are
        write-all identical — slice 0 is canonical). Slice locks held."""
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        t = self.slices[0]._policy_table
        if self._pol_dev is None or self._pol_ver != t.version:
            host = t.host_arrays()
            sh = NamedSharding(self.mesh, P())
            self._pol_dev = {"key": jax.device_put(host["key"], sh),
                             "limit": jax.device_put(host["limit"], sh)}
            self._pol_ver = t.version
        return self._pol_dev

    def _hier_mesh(self):
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        t = self.slices[0]._hier_table
        if t is None:
            return None
        if self._hier_dev_mesh is None or self._hier_ver != t.version:
            host = t.host_arrays()
            sh = NamedSharding(self.mesh, P())
            self._hier_dev_mesh = {k: jax.device_put(v, sh)
                                   for k, v in host.items()}
            self._hier_ver = t.version
        return self._hier_dev_mesh

    # ----------------------------------------------------- state assembly

    def _assemble_leaf(self, k: str, *, cache: bool):
        """Zero-copy global view over the slices' pinned state buffers
        (scalar leaves stack to (n,)). RO leaves cache on buffer
        identity — invalidated exactly when a rollover/restore/reset
        installs new arrays."""
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        parts = [s._state[k] for s in self.slices]
        ids = tuple(id(p) for p in parts)
        if cache:
            hit = self._ro_cache.get(k)
            if hit is not None and hit[0] == ids:
                return hit[1]
        if parts[0].ndim == 0:
            parts = [p.reshape(1) for p in parts]
        lead = parts[0].shape[0]
        gshape = (self.n_slices * lead,) + tuple(parts[0].shape[1:])
        arr = jax.make_array_from_single_device_arrays(
            gshape, NamedSharding(self.mesh, P("chips")), parts)
        if cache:
            self._ro_cache[k] = (ids, arr)
        return arr

    def _assemble_state(self):
        mut = {k: self._assemble_leaf(k, cache=False)
               for k in self._mut_keys}
        ro = {k: self._assemble_leaf(k, cache=True) for k in self._ro_keys}
        return mut, ro

    def _writeback(self, new_mut) -> None:
        """Install each device's output shard as its slice's state leaf
        (matched by device, never by list order)."""
        for k in self._mut_keys:
            shards = {sh.device: sh.data
                      for sh in new_mut[k].addressable_shards}
            for s in self.slices:
                v = shards[s._device]
                if s._state[k].ndim == 0:
                    v = v.reshape(())
                s._state[k] = v

    # --------------------------------------------------- routed dispatch

    def _use_host_router(self, b: int) -> bool:
        # Strict overload gating is a host-side per-slice admission
        # decision made BEFORE dispatch against each slice's offered
        # mass — it cannot ride a whole-mesh step. Empty frames take
        # the host router's passthrough (nothing to route).
        return b == 0 or self._strict_gate

    def _launch_routed(self, arrays: np.ndarray, ns: np.ndarray,
                       now: float, *, premix: bool,
                       wire: bool) -> CollectiveDispatchTicket:
        import jax
        import jax.numpy as jnp

        from ratelimiter_tpu.ops import route_kernels
        from ratelimiter_tpu.parallel import mesh_kernels

        b = int(arrays.shape[0])
        n = self.n_slices
        now_us = to_micros(now)
        L = _pad_size(max(1, -(-b // n)))
        C = route_kernels.bin_capacity(
            L, n, self.config.mesh.bin_headroom)
        step = route_kernels.build_routed_step(
            self.config, self.mesh, premix=premix, L=L, capacity=C)
        padded = L * n
        h64p = np.zeros(padded, dtype=np.uint64)
        h64p[:b] = arrays
        nsp = np.zeros(padded, dtype=np.int32)
        nsp[:b] = ns
        rec = tracing.RECORDER
        t_r0 = tracing.now() if rec is not None else 0
        with self._mesh_lock:
            for s in self.slices:
                s._lock.acquire()
            try:
                for s in self.slices:
                    if s._injected_failure is not None:
                        raise s._injected_failure
                    s._sync_period(now_us)
                mut, ro = self._assemble_state()
                args = (mut, ro,
                        mesh_kernels.shard_batch(h64p, self.mesh),
                        mesh_kernels.shard_batch(nsp, self.mesh),
                        jnp.int64(b), jnp.int64(now_us),
                        self._policy_mesh())
                hier = self._hier_mesh()
                if hier is not None:
                    args = args + (hier,)
                new_mut, fin, ovf = step(*args)
                self._writeback(new_mut)
                if self._cpu:
                    # Same rationale as _MeshPlacement._fence_dispatch:
                    # xla:cpu collective rendezvous starve the shared
                    # device pool under concurrent executions — cap the
                    # stream at one while the dispatch locks are held.
                    jax.block_until_ready((fin, ovf))
                if premix:
                    from ratelimiter_tpu.ops.hashing import splitmix64

                    limits = (self.slices[0]._policy_limits(
                        splitmix64(arrays))
                        if len(self.slices[0]._policy_table) else None)
                else:
                    limits = self.slices[0]._policy_limits(arrays)
            finally:
                for s in reversed(self.slices):
                    s._lock.release()
        if rec is not None:
            # The whole launch is one "route" span — the bench's
            # host-phase story: no argsort, no index maps, no fan-out.
            rec.record("route", t_r0, tracing.now(), batch=b)
        t = CollectiveDispatchTicket()
        t.outs = fin + (ovf,)
        t.b = b
        t.limit = self.config.limit
        t.limits = limits
        t.ns = np.asarray(ns)
        t.now_us = now_us
        t.t_sec = now
        t.arrays = arrays
        t.premix = premix
        t.wire_lane = bool(wire and premix)
        t.wire = t.wire_lane
        return t

    def _launch_routed_guarded(self, arrays: np.ndarray, ns: np.ndarray,
                               now: float, *, premix: bool,
                               wire: bool) -> DispatchTicket:
        """Same fail-open/fail-closed launch contract as the slices'
        _launch_guarded — but a collective launch failure spans the
        whole mesh, so fail-open covers the entire frame (the blast-
        radius trade documented in ADR-024)."""
        try:
            return self._launch_routed(arrays, ns, now, premix=premix,
                                       wire=wire)
        except Exception as exc:
            if self.config.fail_open:
                return DispatchTicket(result=batch_fail_open(
                    int(arrays.shape[0]), self.config.limit,
                    now + float(self.config.window)))
            raise StorageUnavailableError(
                f"collective launch failed: {exc}") from exc

    def resolve(self, ticket: DispatchTicket) -> BatchResult:
        if not isinstance(ticket, CollectiveDispatchTicket):
            return super().resolve(ticket)
        if ticket.result is not None:
            return ticket.result
        import jax

        rec = tracing.RECORDER
        t_b0 = tracing.now() if rec is not None else 0
        try:
            jax.block_until_ready(ticket.outs)
            allowed, remaining, retry, reset_at, mass, ovf = \
                jax.device_get(ticket.outs)
        except Exception as exc:
            ticket.outs = None
            if self.config.fail_open:
                res = batch_fail_open(ticket.b, self.config.limit,
                                      ticket.t_sec
                                      + float(self.config.window))
                ticket.result = res
                return res
            raise StorageUnavailableError(
                f"collective resolve failed: {exc}") from exc
        if rec is not None:
            rec.record("barrier", t_b0, tracing.now(),
                       trace_id=getattr(ticket, "trace_id", 0),
                       batch=ticket.b)
        ticket.outs = None
        if int(ovf):
            # Bin overflow: the step left every state leaf untouched,
            # so re-dispatching the ORIGINAL frame (same rows, same
            # decision timestamp) through the host router admits each
            # row exactly once — no lost, no duplicated mass.
            self.fallbacks += 1
            arrays = ticket.arrays
            owners = (self.owner_of_id(arrays) if ticket.premix
                      else self.owner_of_hash(arrays))
            sub = self._launch_split(arrays, ticket.ns, owners,
                                     ticket.t_sec, premix=ticket.premix,
                                     wire=ticket.wire_lane)
            sub.trace_id = getattr(ticket, "trace_id", 0)
            res = super().resolve(sub)
            ticket.result = res
            return res
        b = ticket.b
        for i, s in enumerate(self.slices):
            admitted = int(mass[i])
            if admitted:
                with s._lock:
                    s._note_mass_locked(admitted, ticket.now_us)
        wire_packed = None
        if ticket.wire_lane:
            # Host packbits from the frame-order columns — the same
            # convention as the host router's cross-slice scatter-back
            # (the device-side pack only exists on single-slice
            # passthrough tickets).
            words = np.empty(3 * b, dtype=np.int64)
            words[0:b] = remaining[:b]
            words[b:2 * b] = retry[:b].view(np.int64)
            words[2 * b:3 * b] = reset_at[:b].view(np.int64)
            wire_packed = (np.packbits(allowed[:b], bitorder="little"),
                           words, b)
        res = BatchResult(allowed=allowed[:b], limit=ticket.limit,
                          remaining=remaining[:b], retry_after=retry[:b],
                          reset_at=reset_at[:b], limits=ticket.limits,
                          wire_packed=wire_packed)
        ticket.result = res
        return res

    # ------------------------------------------------ pipelined public API

    def launch_hashed(self, h64: np.ndarray,
                      ns: Optional[np.ndarray] = None, *,
                      now: Optional[float] = None) -> DispatchTicket:
        self._check_open()
        h64 = np.asarray(h64, dtype=np.uint64)
        ns_arr = (np.ones(h64.shape[0], dtype=np.int64) if ns is None
                  else np.asarray(ns, dtype=np.int64))
        t = self.clock.now() if now is None else float(now)
        if self._use_host_router(h64.shape[0]):
            return self._launch_split(h64, ns_arr,
                                      self.owner_of_hash(h64), t,
                                      premix=False, wire=False)
        return self._launch_routed_guarded(h64, ns_arr, t,
                                           premix=False, wire=False)

    def launch_ids(self, ids: np.ndarray,
                   ns: Optional[np.ndarray] = None, *,
                   now: Optional[float] = None,
                   wire: bool = False) -> DispatchTicket:
        self._check_open()
        ids = np.asarray(ids, dtype=np.uint64)
        ns_arr = (np.ones(ids.shape[0], dtype=np.int64) if ns is None
                  else np.asarray(ns, dtype=np.int64))
        t = self.clock.now() if now is None else float(now)
        if self._use_host_router(ids.shape[0]):
            return self._launch_split(ids, ns_arr, self.owner_of_id(ids),
                                      t, premix=True, wire=wire)
        return self._launch_routed_guarded(ids, ns_arr, t,
                                           premix=True, wire=wire)

    def launch_batch(self, keys: Sequence[str],
                     ns: Optional[Sequence[int]] = None, *,
                     now: Optional[float] = None) -> DispatchTicket:
        self._check_open()
        from ratelimiter_tpu.algorithms.base import check_key, check_n

        keys = list(keys)
        for k in keys:
            check_key(k)
        if ns is None:
            ns_arr = np.ones(len(keys), dtype=np.int64)
        else:
            from ratelimiter_tpu.core.errors import InvalidNError

            if len(ns) != len(keys):
                raise InvalidNError(
                    f"ns length {len(ns)} != keys length {len(keys)}")
            for n in ns:
                check_n(int(n))
            ns_arr = np.asarray(ns, dtype=np.int64)
        t = self.clock.now() if now is None else float(now)
        h64 = self._hash(keys)
        if self._use_host_router(h64.shape[0]):
            return self._launch_split(h64, ns_arr,
                                      self.owner_of_hash(h64), t,
                                      premix=False, wire=False)
        return self._launch_routed_guarded(h64, ns_arr, t,
                                           premix=False, wire=False)

    def _allow_batch(self, keys: list, ns: np.ndarray,
                     now: float) -> BatchResult:
        h64 = self._hash(keys)
        if self._use_host_router(h64.shape[0]):
            return super()._allow_batch(keys, ns, now)
        return self.resolve(self._launch_routed_guarded(
            h64, np.asarray(ns, dtype=np.int64), now,
            premix=False, wire=False))

    # ------------------------------------------------------------ prewarm

    def prewarm_routed(self, max_batch: int) -> None:
        """Compile the collective step for every pad bucket the doors
        can produce (the serving _prewarm's loop only reaches the
        slices; the collective step is a distinct program per L)."""
        top = 2 * max_batch
        size = 8
        while True:
            size = min(size, top)
            h = np.arange(size, dtype=np.uint64) + (1 << 62)
            self.allow_hashed(h, now=0.0)
            self.allow_ids(h, now=0.0)
            if size >= top:
                break
            size *= 2

    # -------------------------------------------------------------- stats

    def router_stats(self) -> dict:
        """Collective-path bookkeeping for /v1/health and the bench."""
        return {"mode": "collective", "fallbacks": self.fallbacks}
