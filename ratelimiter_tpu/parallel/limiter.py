"""MeshSketchLimiter — the multi-chip flagship limiter.

Same RateLimiter contract and Config as the single-chip SketchLimiter
(algorithms/sketch.py); the difference is deployment: the request batch is
sharded over a ``jax.sharding.Mesh`` and the sketch state is replicated on
every chip, kept coherent by the collectives in parallel/mesh_kernels.py.

This is the capability analog of the reference's Redis Cluster scale-out
(``docs/ARCHITECTURE.md:199-219``) with the opposite data placement: the
reference shards *state* and moves every request to the owning node; here
state is replicated and only compact write-deltas (or the compact request
shards, in gather mode) cross ICI. A decision never pays a network RTT.
"""

from __future__ import annotations

import logging
from typing import Optional

import numpy as np

from ratelimiter_tpu.algorithms.sketch import (
    SketchLimiter,
    SketchTokenBucketLimiter,
    _pad_size,
)
from ratelimiter_tpu.core.clock import Clock
from ratelimiter_tpu.core.config import Config
from ratelimiter_tpu.parallel import mesh_kernels
from ratelimiter_tpu.parallel.mesh import make_mesh


def _warn_delta() -> None:
    # The only configuration in the codebase that relaxes the strict
    # never-over-admit invariant — say so once, loudly.
    logging.getLogger(__name__).warning(
        "merge='delta': cross-chip admission is eventually consistent; a "
        "key can be over-admitted up to n_chips*limit within one step "
        "(bounded staleness, see docs/ADR/002-mesh-merge-modes.md). Use "
        "merge='gather' for strict exactness.")


class _MeshPlacement:
    """Placement hooks shared by every mesh limiter: batch sharded over the
    mesh axis, state and scalar operands replicated."""

    def _padded_size(self, b: int) -> int:
        per_chip = _pad_size(max(1, -(-b // self.n_chips)))
        return per_chip * self.n_chips

    def _place(self, arr: np.ndarray):
        return mesh_kernels.shard_batch(arr, self.mesh)

    def _place_replicated(self, arr: np.ndarray):
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        return jax.device_put(arr, NamedSharding(self.mesh, P()))

    def memory_bytes(self) -> int:
        """Total HBM across the mesh: state is fully replicated, so each of
        the n_chips devices holds a complete copy."""
        return super().memory_bytes() * self.n_chips


class MeshSketchLimiter(_MeshPlacement, SketchLimiter):
    """Sketch limiter whose dispatch spans every chip of a mesh.

    Args:
        config: limiter configuration (validated as usual).
        mesh: a 1-D ``jax.sharding.Mesh``; default = all visible devices.
        merge: "gather" (bit-exact global sequencing via all_gather — the
            default, and the only mode that preserves the reference's
            strict never-over-admit contract) or "delta" (one psum per
            step, <=1 step staleness: a key hammered from every chip in the
            SAME step can be over-admitted up to n_chips * limit in that
            step; converged and denying from the next step on). See
            parallel/__init__ and docs/ADR/002 for the tradeoff.
        clock: time source (tests inject ManualClock).
    """

    def __init__(self, config: Config, clock: Optional[Clock] = None, *,
                 mesh=None, merge: str = "gather"):
        super().__init__(config, clock)
        if merge == "delta":
            _warn_delta()
        self.mesh = mesh if mesh is not None else make_mesh()
        self.merge = merge
        self.n_chips = int(np.prod(self.mesh.devices.shape))
        # Replace the single-chip step with the mesh step (hashed-operand
        # form: the (h1, h2) split runs inside the shard_map'd body,
        # ADR-011); reset/rollover stay the plain replicated kernels.
        _, self._reset_step, self._rollover = (
            mesh_kernels.build_mesh_steps(self.config, self.mesh, merge))
        self._step = mesh_kernels.build_mesh_hashed_step(
            self.config, self.mesh, merge)
        self._ids_step = None
        self._state = mesh_kernels.replicate_state(self._state, self.mesh)

    def _build_ids_step(self):
        return mesh_kernels.build_mesh_hashed_step(
            self.config, self.mesh, self.merge, premix=True)

    def _apply_config(self, new_cfg):
        steps = mesh_kernels.build_mesh_steps(new_cfg, self.mesh, self.merge)
        step = mesh_kernels.build_mesh_hashed_step(new_cfg, self.mesh,
                                                   self.merge)
        with self._lock:
            self._step = step
            _, self._reset_step, self._rollover = steps
            self._ids_step = None

    def _apply_window(self, new_cfg):
        """Dynamic window on a mesh: migrate the (replicated) ring with
        the plain kernel, then re-install the mesh-compiled steps and
        re-replicate — the base hook alone would silently swap in
        single-chip kernels and drop the merge contract."""
        super()._apply_window(new_cfg)
        steps = mesh_kernels.build_mesh_steps(new_cfg, self.mesh, self.merge)
        step = mesh_kernels.build_mesh_hashed_step(new_cfg, self.mesh,
                                                   self.merge)
        with self._lock:
            self._step = step
            _, self._reset_step, self._rollover = steps
            self._ids_step = None
            self._state = mesh_kernels.replicate_state(self._state, self.mesh)


class MeshTokenBucketLimiter(_MeshPlacement, SketchTokenBucketLimiter):
    """Sketched token bucket spanning a mesh: replicated debt slab, batch
    sharded over chips, same merge modes and staleness contract as
    MeshSketchLimiter (the scalar decay is deterministic on replicated
    state, so only the debt increments need a collective)."""

    def __init__(self, config: Config, clock: Optional[Clock] = None, *,
                 mesh=None, merge: str = "gather"):
        super().__init__(config, clock)
        if merge == "delta":
            _warn_delta()
        self.mesh = mesh if mesh is not None else make_mesh()
        self.merge = merge
        self.n_chips = int(np.prod(self.mesh.devices.shape))
        _, self._reset_step = mesh_kernels.build_mesh_bucket_steps(
            self.config, self.mesh, merge)
        self._step = mesh_kernels.build_mesh_hashed_bucket_step(
            self.config, self.mesh, merge)
        self._ids_step = None
        self._state = mesh_kernels.replicate_state(self._state, self.mesh)

    def _build_ids_step(self):
        return mesh_kernels.build_mesh_hashed_bucket_step(
            self.config, self.mesh, self.merge, premix=True)

    def _apply_config(self, new_cfg):
        import jax.numpy as jnp

        from ratelimiter_tpu.core.clock import MICROS as _MICROS

        steps = mesh_kernels.build_mesh_bucket_steps(new_cfg, self.mesh,
                                                     self.merge)
        step = mesh_kernels.build_mesh_hashed_bucket_step(
            new_cfg, self.mesh, self.merge)
        cap = new_cfg.limit * _MICROS
        with self._lock:
            self._step = step
            _, self._reset_step = steps
            self._ids_step = None
            self._state = dict(
                self._state,
                debt=jnp.minimum(self._state["debt"], cap),
                rem=self._place_replicated(jnp.asarray(0, jnp.int64)))

    def _apply_window(self, new_cfg):
        """Dynamic window on a mesh bucket: the window only sets the
        refill rate, so rebuild the MESH steps (not the single-chip ones
        the base hook installs) and reset the remainder replicated."""
        import jax.numpy as jnp

        from ratelimiter_tpu.core.clock import to_micros as _to_micros

        steps = mesh_kernels.build_mesh_bucket_steps(new_cfg, self.mesh,
                                                     self.merge)
        step = mesh_kernels.build_mesh_hashed_bucket_step(
            new_cfg, self.mesh, self.merge)
        with self._lock:
            self._step = step
            _, self._reset_step = steps
            self._ids_step = None
            self._window_us = _to_micros(new_cfg.window)
            self._state = dict(
                self._state,
                rem=self._place_replicated(jnp.asarray(0, jnp.int64)))
