"""Multi-chip limiters: the collective mesh tier and the sliced serving tier.

Two complementary multi-device deployments share this module:

* ``MeshSketchLimiter`` / ``MeshTokenBucketLimiter`` — the collective
  tier: state replicated on every chip of a ``jax.sharding.Mesh``, the
  request batch sharded positionally, coherence via the all_gather/psum
  merge modes in parallel/mesh_kernels.py. Any chip may see any key; a
  decision pays a collective, never a network RTT.

* ``SlicedMeshLimiter`` — the slice-parallel SERVING tier (ADR-012,
  ``--backend mesh``): one independent, device-pinned single-chip limiter
  per device, and every key routed to its owning slice by hash. The
  decide path is COLLECTIVE-FREE — no cross-chip traffic at all — so
  serving throughput scales with the slice, and each key's decisions are
  bit-identical to a single-device limiter (the oracle property the
  serving tier tests pin). The gather/delta merge modes above remain the
  background-reconciliation story for workloads that cannot route.

This is the capability analog of the reference's Redis Cluster scale-out
(``docs/ARCHITECTURE.md:199-219``): the sliced tier shards *state* by key
ownership exactly as Redis Cluster shards its keyspace — but the routing
hop happens in the serving front door (C++ shard router / host hash), not
as a per-decision network RTT.
"""

from __future__ import annotations

import logging
from typing import List, Optional, Sequence

import numpy as np

from ratelimiter_tpu.algorithms.base import RateLimiter
from ratelimiter_tpu.algorithms.sketch import (
    SketchLimiter,
    SketchTokenBucketLimiter,
    _pad_size,
)
from ratelimiter_tpu.core.clock import Clock
from ratelimiter_tpu.core.config import Config
from ratelimiter_tpu.core.errors import CheckpointError
from ratelimiter_tpu.core.types import Algorithm, BatchResult, DispatchTicket
from ratelimiter_tpu.observability import tracing
from ratelimiter_tpu.parallel import mesh_kernels
from ratelimiter_tpu.parallel.mesh import make_mesh


def _warn_delta() -> None:
    # The only configuration in the codebase that relaxes the strict
    # never-over-admit invariant — say so once, loudly.
    logging.getLogger(__name__).warning(
        "merge='delta': cross-chip admission is eventually consistent; a "
        "key can be over-admitted up to n_chips*limit within one step "
        "(bounded staleness, see docs/ADR/002-mesh-merge-modes.md). Use "
        "merge='gather' for strict exactness.")


class _MeshPlacement:
    """Placement hooks shared by every mesh limiter: batch sharded over the
    mesh axis, state and scalar operands replicated."""

    def _padded_size(self, b: int) -> int:
        per_chip = _pad_size(max(1, -(-b // self.n_chips)))
        return per_chip * self.n_chips

    def _place(self, arr: np.ndarray):
        return mesh_kernels.shard_batch(arr, self.mesh)

    def _place_replicated(self, arr: np.ndarray):
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        return jax.device_put(arr, NamedSharding(self.mesh, P()))

    def memory_bytes(self) -> int:
        """Total HBM across the mesh: state is fully replicated, so each of
        the n_chips devices holds a complete copy."""
        return super().memory_bytes() * self.n_chips

    def _fence_dispatch(self, outs) -> None:
        # At most ONE in-flight collective execution on the host platform:
        # xla:cpu's all_gather/psum rendezvous parks device-pool threads
        # until every participant arrives, so two concurrent n_chips-wide
        # executions can each hold a subset of the pool and starve the
        # other forever (observed under the 150-thread contract storm:
        # a dozen in-flight steps, every rank logging "waiting for all
        # participants to arrive", zero progress). Completing the step
        # while the dispatch lock is still held caps the stream at one
        # rendezvous, which a starved pool always drains. Real devices
        # serialize executions in the hardware queue — the fence there
        # would only re-order the wait, so it stays CPU-only.
        if self.mesh.devices.flat[0].platform == "cpu":
            import jax

            jax.block_until_ready((self._state, outs))


class MeshSketchLimiter(_MeshPlacement, SketchLimiter):
    """Sketch limiter whose dispatch spans every chip of a mesh.

    Args:
        config: limiter configuration (validated as usual).
        mesh: a 1-D ``jax.sharding.Mesh``; default = all visible devices.
        merge: "gather" (bit-exact global sequencing via all_gather — the
            default, and the only mode that preserves the reference's
            strict never-over-admit contract) or "delta" (one psum per
            step, <=1 step staleness: a key hammered from every chip in the
            SAME step can be over-admitted up to n_chips * limit in that
            step; converged and denying from the next step on). See
            parallel/__init__ and docs/ADR/002 for the tradeoff.
        clock: time source (tests inject ManualClock).
    """

    def __init__(self, config: Config, clock: Optional[Clock] = None, *,
                 mesh=None, merge: str = "gather"):
        super().__init__(config, clock)
        if merge == "delta":
            _warn_delta()
        self.mesh = mesh if mesh is not None else make_mesh()
        self.merge = merge
        self.n_chips = int(np.prod(self.mesh.devices.shape))
        # Replace the single-chip step with the mesh step (hashed-operand
        # form: the (h1, h2) split runs inside the shard_map'd body,
        # ADR-011); reset/rollover stay the plain replicated kernels.
        _, self._reset_step, self._rollover = (
            mesh_kernels.build_mesh_steps(self.config, self.mesh, merge))
        self._step = mesh_kernels.build_mesh_hashed_step(
            self.config, self.mesh, merge)
        self._ids_step = None
        self._state = mesh_kernels.replicate_state(self._state, self.mesh)

    def _build_ids_step(self):
        return mesh_kernels.build_mesh_hashed_step(
            self.config, self.mesh, self.merge, premix=True)

    def _apply_config(self, new_cfg):
        steps = mesh_kernels.build_mesh_steps(new_cfg, self.mesh, self.merge)
        step = mesh_kernels.build_mesh_hashed_step(new_cfg, self.mesh,
                                                   self.merge)
        with self._lock:
            self._step = step
            _, self._reset_step, self._rollover = steps
            self._ids_step = None

    def _apply_window(self, new_cfg):
        """Dynamic window on a mesh: migrate the (replicated) ring with
        the plain kernel, then re-install the mesh-compiled steps and
        re-replicate — the base hook alone would silently swap in
        single-chip kernels and drop the merge contract."""
        super()._apply_window(new_cfg)
        steps = mesh_kernels.build_mesh_steps(new_cfg, self.mesh, self.merge)
        step = mesh_kernels.build_mesh_hashed_step(new_cfg, self.mesh,
                                                   self.merge)
        with self._lock:
            self._step = step
            _, self._reset_step, self._rollover = steps
            self._ids_step = None
            self._state = mesh_kernels.replicate_state(self._state, self.mesh)


class MeshTokenBucketLimiter(_MeshPlacement, SketchTokenBucketLimiter):
    """Sketched token bucket spanning a mesh: replicated debt slab, batch
    sharded over chips, same merge modes and staleness contract as
    MeshSketchLimiter (the scalar decay is deterministic on replicated
    state, so only the debt increments need a collective)."""

    def __init__(self, config: Config, clock: Optional[Clock] = None, *,
                 mesh=None, merge: str = "gather"):
        super().__init__(config, clock)
        if merge == "delta":
            _warn_delta()
        self.mesh = mesh if mesh is not None else make_mesh()
        self.merge = merge
        self.n_chips = int(np.prod(self.mesh.devices.shape))
        _, self._reset_step = mesh_kernels.build_mesh_bucket_steps(
            self.config, self.mesh, merge)
        self._step = mesh_kernels.build_mesh_hashed_bucket_step(
            self.config, self.mesh, merge)
        self._ids_step = None
        self._state = mesh_kernels.replicate_state(self._state, self.mesh)

    def _build_ids_step(self):
        return mesh_kernels.build_mesh_hashed_bucket_step(
            self.config, self.mesh, self.merge, premix=True)

    def _apply_config(self, new_cfg):
        import jax.numpy as jnp

        from ratelimiter_tpu.core.clock import MICROS as _MICROS

        steps = mesh_kernels.build_mesh_bucket_steps(new_cfg, self.mesh,
                                                     self.merge)
        step = mesh_kernels.build_mesh_hashed_bucket_step(
            new_cfg, self.mesh, self.merge)
        cap = new_cfg.limit * _MICROS
        with self._lock:
            self._step = step
            _, self._reset_step = steps
            self._ids_step = None
            self._state = dict(
                self._state,
                debt=jnp.minimum(self._state["debt"], cap),
                rem=self._place_replicated(jnp.asarray(0, jnp.int64)))

    def _apply_window(self, new_cfg):
        """Dynamic window on a mesh bucket: the window only sets the
        refill rate, so rebuild the MESH steps (not the single-chip ones
        the base hook installs) and reset the remainder replicated."""
        import jax.numpy as jnp

        from ratelimiter_tpu.core.clock import to_micros as _to_micros

        steps = mesh_kernels.build_mesh_bucket_steps(new_cfg, self.mesh,
                                                     self.merge)
        step = mesh_kernels.build_mesh_hashed_bucket_step(
            new_cfg, self.mesh, self.merge)
        with self._lock:
            self._step = step
            _, self._reset_step = steps
            self._ids_step = None
            self._window_us = _to_micros(new_cfg.window)
            self._state = dict(
                self._state,
                rem=self._place_replicated(jnp.asarray(0, jnp.int64)))


# ===================================================================
#                      slice-parallel serving tier
# ===================================================================

def build_slices(config: Config, clock: Optional[Clock] = None, *,
                 n_devices: Optional[int] = None,
                 devices: Optional[Sequence] = None) -> List[SketchLimiter]:
    """One device-pinned single-chip limiter per device (the slices of
    ``SlicedMeshLimiter``; the native front door mounts them directly as
    its dispatch shards, ADR-012). Token-bucket configs get the sketched
    token bucket, everything else the windowed sketch — the same
    algorithm selection as ``create_limiter(backend="sketch")``."""
    import jax

    if devices is None:
        devices = jax.devices()
    n = n_devices if n_devices is not None else config.mesh.devices
    if n is not None:
        if n < 1:
            from ratelimiter_tpu.core.errors import InvalidConfigError

            raise InvalidConfigError(
                f"mesh needs at least 1 device, got {n}")
        if n > len(devices):
            from ratelimiter_tpu.core.errors import InvalidConfigError

            raise InvalidConfigError(
                f"mesh wants {n} devices but only {len(devices)} are "
                f"visible (XLA_FLAGS=--xla_force_host_platform_device_"
                f"count=N on CPU)")
        devices = list(devices)[:n]
    cls = (SketchTokenBucketLimiter
           if config.algorithm is Algorithm.TOKEN_BUCKET else SketchLimiter)
    # Hierarchy scopes on a hash-partitioned mesh: each slice enforces an
    # equal share of every tenant/global limit (effective // n_slices —
    # the static-split rule; ADR-020), since slices share no counters.
    return [cls(config, clock, device=d, hier_divisor=len(list(devices)))
            for d in devices]


class MeshDispatchTicket(DispatchTicket):
    """Composite ticket for one frame split across slices.

    ``subs`` holds (slice_index, positions, slice_ticket) triples;
    resolve() scatters each slice's results back to the frame's original
    positions. A frame fully owned by one slice skips the split (its
    slice ticket passes through, preserving the device-packed wire
    buffers). ``DispatchTicket.meta`` stays free for the decorator stack
    (the circuit breaker parks judgment state there)."""

    __slots__ = ("subs",)

    def __init__(self, result=None):
        super().__init__(result)
        self.subs = None


class SlicedMeshLimiter(RateLimiter):
    """Slice-parallel serving limiter (``--backend mesh``, ADR-012).

    One independent single-chip limiter (windowed sketch or sketched
    token bucket, per ``config.algorithm``) is pinned to each of the
    mesh's devices; every key is routed to its OWNING slice by hash:

    * pre-hashed keys (``allow_hashed``/``launch_hashed``): owner =
      ``h64 % n_slices``;
    * raw u64 ids (``allow_ids``/``launch_ids``): owner =
      ``splitmix64(id) % n_slices`` — the same router the native door's
      T_ALLOW_HASHED parse applies, so both surfaces agree;
    * string keys: hashed exactly as the single-chip limiter hashes them
      (prefix + hash_strings_u64), then the ``h64`` rule.

    The decide path is collective-free: a frame is partitioned host-side
    (one ``argsort`` over the owner vector), each touched slice gets one
    independent pipelined dispatch on its own device, and results scatter
    back to frame order at resolve. Per-key decisions are therefore
    BIT-IDENTICAL to a single-device limiter fed that key's traffic —
    the oracle property tests/test_mesh_serving.py pins. Cross-slice
    consistency needs none: slices share no keys by construction.

    The collective MeshSketchLimiter (replicated state, gather/delta
    merges) remains the right tool when requests CANNOT be routed (any
    chip may see any key); see the module docstring and ADR-012 §4.
    """

    pipelined = True

    def __init__(self, config: Config, clock: Optional[Clock] = None, *,
                 n_devices: Optional[int] = None,
                 devices: Optional[Sequence] = None):
        super().__init__(config, clock)
        self.slices = build_slices(self.config, self.clock,
                                   n_devices=n_devices, devices=devices)
        self.n_slices = len(self.slices)
        self._CKPT_KIND = f"mesh:{self.slices[0]._CKPT_KIND}"
        self._seed = self.config.sketch.seed
        #: Failure-domain isolation (ADR-015, opt-in via
        #: ``MeshSpec.quarantine``): every slice is wrapped in a
        #: SliceGuard enforcing a per-slice dispatch deadline and
        #: degraded answers for quarantined ranges; ``self.quarantine``
        #: is the shared state machine (None = subsystem off and the
        #: hot path byte-identical to the unguarded build).
        self.quarantine = None
        if self.config.mesh.quarantine:
            from ratelimiter_tpu.parallel.quarantine import (
                QuarantineManager,
                SliceGuard,
            )

            spec = self.config.mesh
            self.quarantine = QuarantineManager(
                self.n_slices, clock=self.clock,
                probe_interval=spec.probe_interval,
                failure_threshold=spec.failure_threshold)
            self.slices = [
                SliceGuard(s, i, self.quarantine,
                           deadline=spec.slice_deadline)
                for i, s in enumerate(self.slices)]

    # ------------------------------------------------------------ routing

    def _hash(self, keys: List[str]) -> np.ndarray:
        """Prefix + hash exactly as the slices do (slice 0 is the
        canonical implementation; all slices share one config)."""
        return self.slices[0]._hash(list(keys))

    def owner_of_hash(self, h64: np.ndarray) -> np.ndarray:
        """Owning slice index per finalized u64 hash."""
        return (np.asarray(h64, np.uint64)
                % np.uint64(self.n_slices)).astype(np.int64)

    def owner_of_id(self, ids: np.ndarray) -> np.ndarray:
        """Owning slice index per RAW u64 id (the hashed wire lane):
        finalize with splitmix64 first, exactly like the native door's
        per-id shard router (server.cpp T_ALLOW_HASHED parse)."""
        from ratelimiter_tpu.ops.hashing import splitmix64

        return self.owner_of_hash(splitmix64(np.asarray(ids, np.uint64)))

    def owner_of_key(self, key: str) -> int:
        return int(self.owner_of_hash(self._hash([key]))[0])

    # ----------------------------------------------------- split dispatch

    def _launch_split(self, arrays: np.ndarray, ns: np.ndarray,
                      owners: np.ndarray, now: float, *,
                      premix: bool, wire: bool) -> MeshDispatchTicket:
        """Partition one frame by owning slice and launch one pipelined
        dispatch per touched slice. ``arrays`` holds finalized hashes
        (premix=False) or raw ids (premix=True — the slice finalizes
        in-step). Single-owner frames pass through unsplit, preserving
        the slice ticket's device-packed wire buffers."""
        b = int(arrays.shape[0])

        def sub_launch(lim, a, n_arr):
            if premix:
                return lim.launch_ids(a, n_arr, now=now, wire=wire)
            return lim.launch_hashed(a, n_arr, now=now)

        first = int(owners[0]) if b else 0
        if b == 0 or self.n_slices == 1 or not np.any(owners != first):
            t = MeshDispatchTicket()
            t.subs = [(first, None, sub_launch(self.slices[first],
                                               arrays, ns))]
            t.b = b
            t.limit = self.config.limit
            # Launch-time decision timestamp (the audit tap mirrors the
            # frame with the now it was DECIDED at, not resolve time —
            # ADR-016).
            t.t_sec = now
            return t
        # One argsort partitions the whole frame; per-slice position
        # arrays come out contiguous (stable sort keeps frame order
        # within a slice, so same-key sequencing inside the frame is
        # preserved — a key's requests all land on its slice in order).
        rec = tracing.RECORDER
        t_r0 = tracing.now() if rec is not None else 0
        order = np.argsort(owners, kind="stable")
        sorted_owners = owners[order]
        bounds = np.searchsorted(sorted_owners, np.arange(self.n_slices + 1))
        t = MeshDispatchTicket()
        t.subs = []
        for s in range(self.n_slices):
            lo, hi = int(bounds[s]), int(bounds[s + 1])
            if lo == hi:
                continue
            pos = order[lo:hi]
            t.subs.append((s, pos, sub_launch(self.slices[s],
                                              arrays[pos], ns[pos])))
        t.b = b
        t.limit = self.config.limit
        t.t_sec = now
        # Wire frames reassemble device-packed buffers at resolve (the
        # scatter-back path) — only meaningful on the raw-id lane, the
        # one surface whose sub-launches pack on device.
        t.wire = bool(wire and premix)
        if rec is not None:
            # "route": the owner partition + per-slice sub-launches.
            # The frame's trace id is stamped on the ticket AFTER launch
            # returns (the door owns it), so this span carries 0 — it
            # still appears on the frame's thread between "launch" start
            # and the device spans.
            rec.record("route", t_r0, tracing.now(), batch=b)
        return t

    def resolve(self, ticket: DispatchTicket) -> BatchResult:
        """Resolve every slice dispatch and scatter results back to the
        frame's original positions — completion is ONE barrier per frame
        (a single ``block_until_ready`` over every sub-dispatch, ADR-013),
        not a per-slice wait chain, so the frame finishes when the
        SLOWEST slice does regardless of resolution order. Failure
        semantics across slices are non-transactional, the same contract
        as the native door's multi-shard frames: a fail-closed error on
        one slice fails the frame, but other slices' quota stands;
        fail-open slices answer fail-open and the frame's flag ORs over
        slices."""
        if ticket.result is not None:
            return ticket.result
        subs = getattr(ticket, "subs", None)
        if subs is None:
            from ratelimiter_tpu.core.errors import RateLimiterError

            raise RateLimiterError(  # pragma: no cover - misuse guard
                "foreign ticket reached SlicedMeshLimiter.resolve")
        if len(subs) == 1 and subs[0][1] is None:
            s, _, sub = subs[0]
            try:
                res = self.slices[s].resolve(sub)
            except Exception as exc:
                if getattr(exc, "slice_index", None) is None:
                    try:
                        exc.slice_index = s
                    except Exception:  # noqa: BLE001 — best-effort
                        pass
                raise
            ticket.result = res
            return res
        # Single completion barrier: wait for EVERY slice's device work
        # in one call, then the per-slice resolves below are pure
        # (already-hot) fetches + bookkeeping. Errors surface in the
        # per-slice resolve, which owns the fail-open/closed contract.
        rec = tracing.RECORDER
        trace = getattr(ticket, "trace_id", 0)
        outs = [sub.outs for _, _, sub in subs
                if getattr(sub, "outs", None) is not None]
        if self.quarantine is not None:
            # Quarantine mode (ADR-015): NO global barrier — a wedged
            # device would hang it indefinitely. Each slice's guard
            # bounds its own resolve with the per-slice deadline
            # instead; the frame finishes within one deadline budget of
            # its slowest (possibly dead) slice.
            outs = []
        if outs:
            t_b0 = tracing.now() if rec is not None else 0
            try:
                import jax

                jax.block_until_ready(outs)
            except Exception:
                pass  # the owning slice's resolve reports it properly
            if rec is not None:
                # The frame's ONE completion barrier (ADR-013): every
                # per-slice span below links to it through the shared
                # trace id — the parent→slice→device tree the span
                # oracle walks (ADR-014).
                rec.record("barrier", t_b0, tracing.now(), trace_id=trace,
                           batch=ticket.b)
        b = ticket.b
        allowed = np.zeros(b, dtype=bool)
        remaining = np.zeros(b, dtype=np.int64)
        retry = np.zeros(b, dtype=np.float64)
        reset_at = np.zeros(b, dtype=np.float64)
        limits = None
        fail_open = False
        #: Per-slice fail-open attribution (ADR-015 / satellite 1): when
        #: EVERY fail-open contribution names its slice, the frame's
        #: result carries the union so the breaker decorator can scope
        #: the failure instead of tripping the whole keyspace.
        fo_slices: list = []
        fo_unattributed = False
        err = None
        wire = bool(getattr(ticket, "wire", False))
        for s, pos, sub in subs:
            t_s0 = tracing.now() if rec is not None else 0
            try:
                res = self.slices[s].resolve(sub)
            except Exception as exc:  # fail-closed slice: finish the rest
                if getattr(exc, "slice_index", None) is None:
                    try:
                        exc.slice_index = s
                    except Exception:  # noqa: BLE001 — best-effort
                        pass
                if rec is not None:
                    rec.record("slice", t_s0, tracing.now(),
                               trace_id=trace, shard=s,
                               outcome=tracing.ERROR)
                err = err if err is not None else exc
                continue
            if rec is not None:
                rec.record("slice", t_s0, tracing.now(), trace_id=trace,
                           shard=s, batch=len(res),
                           outcome=tracing.FAIL_OPEN if res.fail_open
                           else tracing.OK)
            allowed[pos] = res.allowed
            remaining[pos] = res.remaining
            retry[pos] = res.retry_after
            reset_at[pos] = res.reset_at
            if res.fail_open:
                attr = getattr(res, "fail_open_slices", None)
                if attr:
                    fo_slices.extend(attr)
                else:
                    fo_unattributed = True
            fail_open = fail_open or res.fail_open
            wire = wire and res.wire_packed is not None
            if res.limits is not None:
                if limits is None:
                    limits = np.full(b, self.config.limit, dtype=np.int64)
                limits[pos] = res.limits
        if err is not None:
            raise err
        wire_packed = None
        if wire:
            # Scatter-back of the device-packed wire buffers through the
            # index maps (ADR-013): rebuild the frame-order packed form
            # with three vectorized gathers + one packbits, so the wire
            # encoder still frames from packed buffers (memoryview
            # column slices, no per-row host math). The gather is the
            # price of cross-slice reassembly; single-owner frames pass
            # the slice's buffers through untouched above.
            words = np.empty(3 * b, dtype=np.int64)
            words[0:b] = remaining
            words[b:2 * b] = retry.view(np.int64)
            words[2 * b:3 * b] = reset_at.view(np.int64)
            wire_packed = (np.packbits(allowed, bitorder="little"),
                           words, b)
        res = BatchResult(allowed=allowed, limit=self.config.limit,
                          remaining=remaining, retry_after=retry,
                          reset_at=reset_at, fail_open=fail_open,
                          limits=limits, wire_packed=wire_packed)
        if fail_open and fo_slices and not fo_unattributed:
            res.fail_open_slices = sorted(set(fo_slices))
        ticket.result = res
        return res

    # ------------------------------------------------- pipelined public API

    def launch_hashed(self, h64: np.ndarray,
                      ns: Optional[np.ndarray] = None, *,
                      now: Optional[float] = None) -> MeshDispatchTicket:
        self._check_open()
        h64 = np.asarray(h64, dtype=np.uint64)
        ns_arr = (np.ones(h64.shape[0], dtype=np.int64) if ns is None
                  else np.asarray(ns, dtype=np.int64))
        t = self.clock.now() if now is None else float(now)
        return self._launch_split(h64, ns_arr, self.owner_of_hash(h64), t,
                                  premix=False, wire=False)

    def launch_ids(self, ids: np.ndarray,
                   ns: Optional[np.ndarray] = None, *,
                   now: Optional[float] = None,
                   wire: bool = False) -> MeshDispatchTicket:
        self._check_open()
        ids = np.asarray(ids, dtype=np.uint64)
        ns_arr = (np.ones(ids.shape[0], dtype=np.int64) if ns is None
                  else np.asarray(ns, dtype=np.int64))
        t = self.clock.now() if now is None else float(now)
        return self._launch_split(ids, ns_arr, self.owner_of_id(ids), t,
                                  premix=True, wire=wire)

    def launch_batch(self, keys: Sequence[str],
                     ns: Optional[Sequence[int]] = None, *,
                     now: Optional[float] = None) -> MeshDispatchTicket:
        self._check_open()
        from ratelimiter_tpu.algorithms.base import check_key, check_n

        keys = list(keys)
        for k in keys:
            check_key(k)
        if ns is None:
            ns_arr = np.ones(len(keys), dtype=np.int64)
        else:
            from ratelimiter_tpu.core.errors import InvalidNError

            if len(ns) != len(keys):
                raise InvalidNError(
                    f"ns length {len(ns)} != keys length {len(keys)}")
            for n in ns:
                check_n(int(n))
            ns_arr = np.asarray(ns, dtype=np.int64)
        t = self.clock.now() if now is None else float(now)
        h64 = self._hash(keys)
        return self._launch_split(h64, ns_arr, self.owner_of_hash(h64), t,
                                  premix=False, wire=False)

    def allow_hashed(self, h64: np.ndarray,
                     ns: Optional[np.ndarray] = None, *,
                     now: Optional[float] = None) -> BatchResult:
        return self.resolve(self.launch_hashed(h64, ns, now=now))

    def allow_ids(self, ids: np.ndarray,
                  ns: Optional[np.ndarray] = None, *,
                  now: Optional[float] = None) -> BatchResult:
        return self.resolve(self.launch_ids(ids, ns, now=now))

    def _allow_batch(self, keys: list, ns: np.ndarray,
                     now: float) -> BatchResult:
        h64 = self._hash(keys)
        return self.resolve(self._launch_split(
            h64, ns, self.owner_of_hash(h64), now,
            premix=False, wire=False))

    def _allow_n(self, key: str, n: int, now: float):
        return self.slices[self.owner_of_key(key)].allow_n(key, n, now=now)

    # --------------------------------------------------- control plane

    def _reset(self, key: str) -> None:
        self.slices[self.owner_of_key(key)].reset(key)

    def update_limit(self, new_limit: int) -> None:
        self._check_open()
        for s in self.slices:
            s.update_limit(new_limit)
        from dataclasses import replace

        self.config = replace(self.config, limit=new_limit)

    def update_window(self, new_window: float) -> None:
        self._check_open()
        for s in self.slices:
            s.update_window(new_window)
        from dataclasses import replace

        self.config = replace(self.config, window=float(new_window))

    # Policy overrides apply on EVERY slice (idempotent for non-owners —
    # their copy is simply never queried for the key), the same rule as
    # the native door's shard router; reads route to the owner.

    def set_override(self, key: str, limit: Optional[int] = None, *,
                     window_scale: float = 1.0):
        self._check_open()
        ov = None
        for s in self.slices:
            ov = s.set_override(key, limit, window_scale=window_scale)
        return ov

    def get_override(self, key: str):
        self._check_open()
        return self.slices[self.owner_of_key(key)].get_override(key)

    def delete_override(self, key: str) -> bool:
        self._check_open()
        existed = False
        for s in self.slices:
            existed = s.delete_override(key) or existed
        return existed

    def list_overrides(self):
        self._check_open()
        return self.slices[0].list_overrides()

    def override_count(self) -> int:
        return self.slices[0].override_count()

    # Hierarchy surface: HierarchyFanout's write-all / read-one /
    # sum-stats semantics over the slices (each enforces its equal
    # share of the scope limits — ADR-020). Built per call: restore()
    # may rebuild self.slices.

    def _hier(self):
        from ratelimiter_tpu.hierarchy.fanout import HierarchyFanout

        self._check_open()
        return HierarchyFanout(self.slices)

    def set_tenant(self, name, limit=None, *, weight=1, floor=None):
        return self._hier().set_tenant(name, limit, weight=weight,
                                       floor=floor)

    def delete_tenant(self, name: str) -> bool:
        return self._hier().delete_tenant(name)

    def assign_tenant(self, key: str, tenant: str) -> None:
        self._hier().assign_tenant(key, tenant)

    def unassign_tenant(self, key: str) -> bool:
        return self._hier().unassign_tenant(key)

    def tenant_of(self, key: str) -> str:
        return self._hier().tenant_of(key)

    def get_tenant(self, name: str):
        return self._hier().get_tenant(name)

    def list_tenants(self):
        return self._hier().list_tenants()

    def set_global_limit(self, limit) -> None:
        self._hier().set_global_limit(limit)

    def set_effective(self, scope: str, limit: int) -> int:
        return self._hier().set_effective(scope, limit)

    def effective_limits(self):
        return self._hier().effective_limits()

    def hierarchy_payload(self) -> dict:
        return self._hier().hierarchy_payload()

    def apply_hierarchy_payload(self, payload: dict) -> bool:
        return self._hier().apply_hierarchy_payload(payload)

    def hierarchy_stats(self) -> dict:
        """Per-scope stats summed across slices (each slice's counters
        cover its hash-owned keys; the sum is the whole deployment's
        in-window mass). Effective/ceiling values come from slice 0's
        table — mutations are write-all, so the tables agree."""
        return self._hier().hierarchy_stats()

    # ------------------------------------------------- checkpoint seam

    def capture_state(self):
        """One combined snapshot over every slice: each slice captures
        under its own lock (device→host only — the persistence tier
        serializes and writes off-lock, ADR-009). Slices share no keys,
        so per-key consistency holds; cross-key skew between slice
        captures sits inside the documented one-interval staleness
        envelope. The slice count rides in the extras and restore
        REFUSES a different count — slice counters are only meaningful
        under the routing that produced them."""
        self._check_open()
        arrays = {}
        extras = []
        for i, s in enumerate(self.slices):
            _, a, e = s.capture_state()
            arrays.update({f"slice{i}:{k}": v for k, v in a.items()})
            extras.append(e)
        return self._CKPT_KIND, arrays, {
            "n_slices": self.n_slices,
            "slice_extras": extras,
            "saved_at": self.clock.now(),
        }

    def restore(self, path: str) -> None:
        """Restore a combined snapshot. A snapshot taken under a
        DIFFERENT slice count is re-bucketed onto this mesh's geometry
        (parallel/reshard.py, ADR-018): clean splits copy state
        verbatim per new slice, merges take the conservative union
        (elementwise max after period alignment) — per-key override
        tables re-route exactly, estimates only rise, so the resharded
        mesh never over-admits relative to the source. The same math is
        available offline as ``tools/rebucket.py`` for cold resizes."""
        from ratelimiter_tpu.checkpoint import load_state

        self._check_open()
        arrays, meta = load_state(path, self._CKPT_KIND, self.config)
        saved = int(meta.get("n_slices", -1))
        if saved != self.n_slices:
            from ratelimiter_tpu.parallel import reshard

            logging.getLogger(__name__).warning(
                "%s: snapshot holds %d slice(s) but this mesh runs %d "
                "device(s) — re-bucketing key-routed state onto the new "
                "geometry (conservative union: overrides exact, "
                "estimates only rise; ADR-018)", path, saved,
                self.n_slices)
            arrays, meta = reshard.rebucket_combined(
                arrays, meta, self.n_slices, self.config)
        extras = meta.get("slice_extras") or [{}] * self.n_slices
        for i, s in enumerate(self.slices):
            prefix = f"slice{i}:"
            sub = {k[len(prefix):]: v for k, v in arrays.items()
                   if k.startswith(prefix)}
            s._restore_loaded(sub, extras[i], label=f"{path}[slice{i}]")

    def restore_slice(self, path: str, index: int) -> None:
        """Slice-scoped restore (ADR-015): replace ONE slice's state
        with its sub-dictionary of the combined snapshot at ``path``,
        leaving every other slice untouched. This is the recovery half
        of quarantine — a slice rejoining routing restores from the
        newest snapshot (plus the WAL suffix the persistence tier
        replays, recover.recover_unit) before it serves again. Same
        slice-count refusal as a full restore."""
        from ratelimiter_tpu.checkpoint import load_state

        self._check_open()
        if not 0 <= index < self.n_slices:
            raise CheckpointError(
                f"restore_slice: slice {index} out of range "
                f"[0, {self.n_slices})")
        arrays, meta = load_state(path, self._CKPT_KIND, self.config)
        saved = int(meta.get("n_slices", -1))
        if saved != self.n_slices:
            raise CheckpointError(
                f"{path}: snapshot holds {saved} slice(s) but this mesh "
                f"runs {self.n_slices} — a SINGLE slice cannot be "
                f"re-bucketed in place (its peers' state would stay on "
                f"the old routing); use a full restore(), which "
                f"re-buckets the whole snapshot onto the new geometry "
                f"(parallel/reshard.py, ADR-018), or resize the snapshot "
                f"offline with tools/rebucket.py")
        extras = meta.get("slice_extras") or [{}] * self.n_slices
        prefix = f"slice{index}:"
        sub = {k[len(prefix):]: v for k, v in arrays.items()
               if k.startswith(prefix)}
        self.slices[index]._restore_loaded(
            sub, extras[index], label=f"{path}[slice{index}]")

    # ------------------------------------------------- fault injection

    def inject_failure(self, exc: Optional[Exception] = None) -> None:
        for s in self.slices:
            s.inject_failure(exc)

    def heal(self) -> None:
        for s in self.slices:
            s.heal()

    # ----------------------------------------------------- introspection

    def sub_limiters(self):
        """The per-device slices (the serving tier's per-unit seam:
        DCN pushers/merges, prewarm, and the health envelope iterate
        these)."""
        return list(self.slices)

    def memory_bytes(self) -> int:
        return sum(s.memory_bytes() for s in self.slices)

    def in_window_admitted_mass(self) -> int:
        return sum(s.in_window_admitted_mass() for s in self.slices)

    @property
    def mass_budget(self) -> int:
        return sum(s.mass_budget for s in self.slices)

    @property
    def overload_periods(self) -> int:
        return sum(s.overload_periods for s in self.slices)

    def _close(self) -> None:
        for s in self.slices:
            s.close()
