"""Per-slice failure domains for the sliced mesh tier (ADR-015).

The slice-parallel serving tier (ADR-012) gives every device its own
independent limiter slice — which means a fault on one device is
*naturally* scoped to one key range. This module turns that topology
into a contract:

* :func:`classify_failure` — is an exception a BACKEND fault (device
  error, storage outage, injected chaos, deadline) or a CALLER error
  (validation, closed limiter, config drift)? Only backend faults
  quarantine; caller errors propagate untouched.
* :class:`QuarantineManager` — one per deployment: per-slice breaker
  state (healthy → quarantined → probing → restoring → healthy),
  half-open probe scheduling, and the restore-before-rejoin hook
  (ADR-009 snapshot + WAL suffix) that guarantees a recovering slice
  rejoins routing with durable state, never the garbage it wedged on.
* :class:`SliceGuard` — a decorator around ONE slice enforcing the
  per-slice dispatch deadline (a wedged device cannot stall the frame
  past its budget) and answering a quarantined slice's range per the
  configured fail-open/fail-closed policy, stamped with the LIVE
  limit/window (the ADR-013 multi-shard OR contract: the frame's
  ``fail_open`` flag ORs over slices).

The whole subsystem is opt-in (``MeshSpec.quarantine``); with it off,
no guard exists and the mesh hot path is byte-identical to PR 7.
"""

from __future__ import annotations

import concurrent.futures
import logging
import queue as queue_mod
import threading
from typing import Callable, Optional

import numpy as np

from ratelimiter_tpu.core.errors import (
    CheckpointError,
    ClosedError,
    DeadlineExceededError,
    InvalidConfigError,
    InvalidKeyError,
    InvalidNError,
    StorageUnavailableError,
)
from ratelimiter_tpu.core.types import (
    DispatchTicket,
    batch_fail_open,
    fail_open_result,
)
from ratelimiter_tpu.observability import metrics as m
from ratelimiter_tpu.observability.decorators import LimiterDecorator

log = logging.getLogger("ratelimiter_tpu.quarantine")


class _DaemonExecutor:
    """Single-worker executor on a DAEMON thread (the minimal slice of
    the concurrent.futures API the guard needs). A stock
    ThreadPoolExecutor's workers are non-daemon and are JOINED by the
    interpreter's atexit hook — a dispatch wedged forever (the exact
    failure quarantine contains) would then hang process shutdown on
    the very thread that is stuck inside it."""

    def __init__(self, name: str):
        self._q: queue_mod.Queue = queue_mod.Queue()
        self._thread = threading.Thread(target=self._run, name=name,
                                        daemon=True)
        self._thread.start()

    def _run(self) -> None:
        while True:
            item = self._q.get()
            if item is None:
                return
            fut, fn, args = item
            if not fut.set_running_or_notify_cancel():
                continue
            try:
                fut.set_result(fn(*args))
            except BaseException as exc:  # noqa: BLE001 — future carries it
                fut.set_exception(exc)

    def submit(self, fn, *args) -> "concurrent.futures.Future":
        fut: concurrent.futures.Future = concurrent.futures.Future()
        self._q.put((fut, fn, args))
        return fut

    def shutdown(self) -> None:
        self._q.put(None)

#: Reserved probe key hash (golden-ratio constant, top bit set): the
#: half-open probe dispatches ONE unit request against this hash. The
#: admitted mass lands on one CMS cell per row (noise toward denying,
#: bounded by probe cadence) and is overwritten by the snapshot restore
#: that follows a successful probe anyway.
PROBE_HASH = np.uint64(0x9E3779B97F4A7C15)

#: Exception classes that are NEVER backend faults — caller mistakes and
#: config drift must not quarantine a healthy device.
_CALLER_ERRORS = (InvalidKeyError, InvalidNError, InvalidConfigError,
                  ClosedError, CheckpointError, NotImplementedError,
                  TypeError)


def classify_failure(exc: BaseException) -> bool:
    """True iff ``exc`` indicates the SLICE (device/backend) failed —
    the quarantine-worthy class. Conservative direction: an unknown
    RuntimeError from inside a dispatch is treated as a backend fault
    (XLA device errors are RuntimeError subclasses); typed caller
    errors never are."""
    if isinstance(exc, _CALLER_ERRORS):
        return False
    if isinstance(exc, (StorageUnavailableError, DeadlineExceededError,
                        TimeoutError, OSError, MemoryError)):
        return True
    from ratelimiter_tpu.chaos.injector import SliceFault

    if isinstance(exc, SliceFault):
        return True
    # jaxlib.xla_extension.XlaRuntimeError subclasses RuntimeError.
    return isinstance(exc, RuntimeError)


class QuarantineManager:
    """Per-slice breaker state + probe/restore orchestration.

    States per slice:

    * ``healthy``     — traffic routes normally;
    * ``quarantined`` — the slice's range answers degraded; a half-open
      probe fires every ``probe_interval`` seconds (kicked lazily from
      traffic, or explicitly via :meth:`probe_now`);
    * ``probing``     — one probe dispatch in flight (bounded by the
      slice deadline);
    * ``restoring``   — probe succeeded; the restore hook is replaying
      the newest snapshot + WAL suffix into the slice. Traffic stays
      degraded until restore completes — restore-before-rejoin is the
      invariant that makes recovery correct, not merely live (ADR-015).

    ``restore_fn(slice_idx)`` is wired by the deployment (the
    persistence manager's :meth:`~ratelimiter_tpu.persistence.manager.
    PersistenceManager.slice_restorer`); without durability enabled the
    slice rejoins with its live in-memory state (exact for overrides —
    they are re-applied write-all — and conservative for sketch
    counters).
    """

    def __init__(self, n_slices: int, *, clock=None,
                 probe_interval: float = 1.0,
                 failure_threshold: int = 1,
                 restore_fn: Optional[Callable[[int], None]] = None,
                 on_state_change: Optional[Callable[[int, str], None]] = None,
                 registry: Optional[m.Registry] = None):
        from ratelimiter_tpu.core.clock import SystemClock

        self.n_slices = int(n_slices)
        self.clock = clock if clock is not None else SystemClock()
        self.probe_interval = float(probe_interval)
        self.failure_threshold = int(failure_threshold)
        self.restore_fn = restore_fn
        self.on_state_change = on_state_change
        self._lock = threading.Lock()
        self._state = ["healthy"] * self.n_slices
        self._consecutive = [0] * self.n_slices
        self._next_probe_at = [0.0] * self.n_slices
        self._guards: dict = {}
        self.transitions = 0
        self.degraded_decisions = 0
        reg = registry if registry is not None else m.DEFAULT
        self._g_quarantined = reg.gauge(
            "rate_limiter_slice_quarantined",
            "1 while this mesh slice is out of routing (quarantined/"
            "probing/restoring), 0 while healthy (ADR-015)")
        self._c_transitions = reg.counter(
            "rate_limiter_slice_quarantine_transitions_total",
            "Per-slice quarantine state transitions")
        self._c_degraded = reg.counter(
            "rate_limiter_slice_degraded_decisions_total",
            "Decisions answered per fail-open/closed policy because the "
            "owning slice was quarantined or failed")
        for i in range(self.n_slices):
            self._g_quarantined.set(0.0, slice=str(i))

    # ------------------------------------------------------------ wiring

    def register(self, idx: int, guard: "SliceGuard") -> None:
        self._guards[int(idx)] = guard

    # ------------------------------------------------------- transitions

    def _set_state(self, idx: int, state: str) -> None:
        """Lock held by caller."""
        if self._state[idx] == state:
            return
        prev = self._state[idx]
        self._state[idx] = state
        self.transitions += 1
        self._c_transitions.inc(slice=str(idx), to=state)
        self._g_quarantined.set(0.0 if state == "healthy" else 1.0,
                                slice=str(idx))
        # Control-plane journal (ADR-021): quarantine transitions are
        # exactly the "why did range X degrade at 14:02" record.
        from ratelimiter_tpu.observability import events

        events.emit("quarantine", state, actor=f"slice{idx}",
                    severity=("info" if state == "healthy"
                              else "warning"),
                    payload={"slice": idx, "from": prev,
                             "consecutive_failures":
                                 self._consecutive[idx]})
        cb = self.on_state_change
        if cb is not None:
            try:
                cb(idx, state)
            except Exception:  # noqa: BLE001 — observability only
                log.exception("quarantine on_state_change callback failed")

    def state(self, idx: int) -> str:
        with self._lock:
            return self._state[idx]

    def quarantined(self) -> list:
        with self._lock:
            return [i for i, s in enumerate(self._state) if s != "healthy"]

    def status(self) -> dict:
        """/healthz block (degraded-mode runbook, OPERATIONS §8)."""
        with self._lock:
            states = list(self._state)
        out = {
            "slices": len(states),
            "states": states,
            "quarantined": [i for i, s in enumerate(states)
                            if s != "healthy"],
            "transitions": self.transitions,
            "degraded_decisions": self.degraded_decisions,
            "probe_interval": self.probe_interval,
        }
        out["degraded"] = bool(out["quarantined"])
        return out

    # ----------------------------------------------------------- traffic

    def admit(self, idx: int, now: float) -> bool:
        """True = route traffic to the slice; False = answer degraded.
        A quarantined slice whose probe cadence elapsed kicks a
        BACKGROUND half-open probe — client traffic never rides the
        probe, because the slice must restore before it rejoins."""
        with self._lock:
            if self._state[idx] == "healthy":
                return True
            due = (self._state[idx] == "quarantined"
                   and now >= self._next_probe_at[idx])
            if due:
                self._set_state(idx, "probing")
        if due:
            t = threading.Thread(target=self._probe, args=(idx,),
                                 name=f"rl-probe-{idx}", daemon=True)
            t.start()
        return False

    def note_degraded(self, idx: int, count: int) -> None:
        with self._lock:
            self.degraded_decisions += int(count)
        self._c_degraded.inc(int(count), slice=str(idx))

    def note_success(self, idx: int) -> None:
        with self._lock:
            self._consecutive[idx] = 0

    def note_failure(self, idx: int, exc: BaseException, now: float) -> bool:
        """Record one classified backend failure; returns True iff the
        slice is (now) quarantined."""
        with self._lock:
            self._consecutive[idx] += 1
            already = self._state[idx] != "healthy"
            if already or self._consecutive[idx] >= self.failure_threshold:
                if self._state[idx] in ("healthy", "probing"):
                    log.warning(
                        "slice %d quarantined after %d failure(s): %s",
                        idx, self._consecutive[idx], exc)
                self._set_state(idx, "quarantined")
                self._next_probe_at[idx] = now + self.probe_interval
                return True
            return False

    # ------------------------------------------------------------ levers

    def force(self, idx: int) -> None:
        """Runbook lever: quarantine a slice NOW (e.g. ahead of planned
        device maintenance)."""
        with self._lock:
            self._set_state(idx, "quarantined")
            self._next_probe_at[idx] = (self.clock.now()
                                        + self.probe_interval)

    def clear(self, idx: int) -> None:
        """Runbook lever: return a slice to routing WITHOUT probe or
        restore (operator asserts the device and its state are good)."""
        with self._lock:
            self._consecutive[idx] = 0
            self._set_state(idx, "healthy")

    def probe_now(self, idx: int) -> bool:
        """Synchronous probe + restore + rejoin attempt (tests and the
        runbook's forced-recovery lever). True iff the slice is healthy
        afterwards."""
        with self._lock:
            if self._state[idx] == "healthy":
                return True
            self._set_state(idx, "probing")
        self._probe(idx)
        return self.state(idx) == "healthy"

    # ------------------------------------------------------------- probe

    def _probe(self, idx: int) -> None:
        guard = self._guards.get(idx)
        now = self.clock.now()
        try:
            if guard is not None:
                guard.probe()
        except Exception as exc:  # noqa: BLE001 — every fault re-opens
            with self._lock:
                self._set_state(idx, "quarantined")
                self._next_probe_at[idx] = now + self.probe_interval
            log.info("slice %d probe failed (%s); next probe in %.3gs",
                     idx, exc, self.probe_interval)
            return
        # Probe succeeded: restore BEFORE rejoining routing. A slice
        # that wedged mid-dispatch may hold arbitrary staging garbage;
        # the newest snapshot + WAL suffix is the only state we can
        # vouch for (ADR-015 records why restore-then-rejoin beats
        # rejoin-then-converge).
        with self._lock:
            self._set_state(idx, "restoring")
        if self.restore_fn is not None:
            try:
                self.restore_fn(idx)
            except Exception as exc:  # noqa: BLE001 — stay quarantined
                with self._lock:
                    self._set_state(idx, "quarantined")
                    self._next_probe_at[idx] = (self.clock.now()
                                                + self.probe_interval)
                log.warning("slice %d restore failed (%s); staying "
                            "quarantined", idx, exc)
                return
        with self._lock:
            self._consecutive[idx] = 0
            self._set_state(idx, "healthy")
        log.info("slice %d recovered (probe + restore) and rejoined "
                 "routing", idx)


class SliceGuard(LimiterDecorator):
    """Failure-domain guard around ONE mesh slice (ADR-015).

    Every dispatch entry (launch/decide, string/hashed/raw-id) checks
    quarantine state first: a quarantined slice's work is answered per
    the configured fail-open/fail-closed policy WITHOUT touching the
    device. Live dispatches resolve on the guard's own single worker
    thread bounded by the per-slice deadline, so a wedged device
    surfaces as a classified failure within one budget instead of
    hanging the frame. Chaos hooks (ratelimiter_tpu/chaos/) fire inside
    this guard — the same surfaces real faults use.

    Fail-open degraded answers are stamped with the LIVE limit/window
    (the config property delegates to the inner slice, which
    update_limit/update_window mutate) and carry ``fail_open_slices``
    so the breaker decorator can scope the failure to this slice.
    """

    def __init__(self, inner, index: int, manager: QuarantineManager, *,
                 deadline: float = 0.25):
        super().__init__(inner)
        self.slice_index = int(index)
        self._mgr = manager
        self._deadline = float(deadline)
        #: Warm gate: until the slice's FIRST successful dispatch, the
        #: deadline stretches to cover XLA compiles (a cold compile is
        #: not a device fault; prewarm normally absorbs it, but a
        #: no-prewarm start must not quarantine every slice at boot).
        self._warm = False
        self._cold_deadline = max(self._deadline, 30.0)
        self._pool: Optional[_DaemonExecutor] = None
        self._pool_lock = threading.Lock()
        manager.register(self.slice_index, self)

    # ------------------------------------------------------------ plumbing

    def _executor(self) -> _DaemonExecutor:
        # One worker: resolves stay FIFO per slice (launch order ==
        # resolve order, the pipelined state-threading contract), and an
        # orphaned (timed-out) resolve naturally blocks later work on
        # this slice — which is exactly the degraded answer path. The
        # worker is a DAEMON: a dispatch wedged forever must not hang
        # interpreter shutdown.
        with self._pool_lock:
            if self._pool is None:
                self._pool = _DaemonExecutor(
                    f"rl-slice{self.slice_index}")
            return self._pool

    def _degraded(self, b: int, now: float, cause: str, *,
                  scalar: bool = False):
        """Answer ``b`` decisions per policy: fail-open -> allowed rows
        stamped fail_open with the live limit/window; fail-closed ->
        typed StorageUnavailableError carrying ``slice_index`` (the
        breaker-scoping attribution, satellite 1)."""
        self._mgr.note_degraded(self.slice_index, b)
        cfg = self.inner.config
        if not cfg.fail_open:
            exc = StorageUnavailableError(
                f"slice {self.slice_index} unavailable ({cause}); its key "
                f"range fails closed per config")
            exc.slice_index = self.slice_index
            raise exc
        reset_at = now + float(cfg.window)
        if scalar:
            res = fail_open_result(cfg.limit, reset_at)
            # Result is frozen; the attribution riding along is what
            # keeps the scalar lane from tripping the whole-keyspace
            # breaker (same contract as the batch lanes).
            object.__setattr__(res, "fail_open_slices",
                               [self.slice_index])
            return res
        out = batch_fail_open(b, cfg.limit, reset_at)
        out.fail_open_slices = [self.slice_index]
        return out

    def _note_exc(self, exc: BaseException, now: float) -> bool:
        """Classify + record; True iff this was a backend fault (the
        caller then answers degraded)."""
        if not classify_failure(exc):
            return False
        self._mgr.note_failure(self.slice_index, exc, now)
        if getattr(exc, "slice_index", None) is None:
            try:
                exc.slice_index = self.slice_index
            except Exception:  # noqa: BLE001 — attribution best-effort
                pass
        return True

    def _chaos_launch(self) -> None:
        from ratelimiter_tpu import chaos

        if chaos.INJECTOR is not None:
            chaos.INJECTOR.slice_launch(self.slice_index)

    def _chaos_resolve(self) -> None:
        from ratelimiter_tpu import chaos

        if chaos.INJECTOR is not None:
            chaos.INJECTOR.slice_resolve(self.slice_index)

    # ----------------------------------------------------- guarded launch

    def _guard_launch(self, fn, b: int):
        now = self.inner.clock.now()
        if not self._mgr.admit(self.slice_index, now):
            return DispatchTicket(
                result=self._degraded(b, now, "quarantined"))
        try:
            self._chaos_launch()
            return fn()
        except Exception as exc:
            if self._note_exc(exc, now):
                return DispatchTicket(
                    result=self._degraded(b, now, f"launch failed: {exc}"))
            raise

    def launch_hashed(self, h64, ns=None, *, now=None):
        return self._guard_launch(
            lambda: self.inner.launch_hashed(h64, ns, now=now), len(h64))

    def launch_ids(self, ids, ns=None, *, now=None, wire: bool = False):
        return self._guard_launch(
            lambda: self.inner.launch_ids(ids, ns, now=now, wire=wire),
            len(ids))

    def launch_batch(self, keys, ns=None, *, now=None):
        return self._guard_launch(
            lambda: self.inner.launch_batch(keys, ns, now=now), len(keys))

    # ---------------------------------------------------- guarded resolve

    def _eff_deadline(self) -> float:
        return self._deadline if self._warm else self._cold_deadline

    def _resolve_inner(self, ticket):
        self._chaos_resolve()
        return self.inner.resolve(ticket)

    def resolve(self, ticket):
        if ticket.result is not None:
            return ticket.result
        b = int(getattr(ticket, "b", 0))
        now = self.inner.clock.now()
        fut = self._executor().submit(self._resolve_inner, ticket)
        try:
            out = fut.result(timeout=self._eff_deadline())
        except concurrent.futures.TimeoutError:
            # The dispatch keeps running (the worker thread is stuck in
            # it); its eventual outcome is swallowed — the range was
            # already answered per policy, and a later success must not
            # double-answer. Quarantine + probe own recovery.
            fut.add_done_callback(lambda f: f.exception())
            exc = DeadlineExceededError(
                f"slice {self.slice_index} resolve exceeded the "
                f"{self._eff_deadline():g}s per-slice deadline")
            self._note_exc(exc, now)
            return self._degraded(b, now, "deadline exceeded")
        except Exception as exc:
            if self._note_exc(exc, now):
                return self._degraded(b, now, f"resolve failed: {exc}")
            raise
        self._warm = True
        self._mgr.note_success(self.slice_index)
        return out

    # ------------------------------------------------- guarded sync decide

    def _sync_inner(self, fn):
        self._chaos_launch()
        self._chaos_resolve()
        return fn()

    def _guard_sync(self, fn, b: int, *, scalar: bool = False):
        now = self.inner.clock.now()
        if not self._mgr.admit(self.slice_index, now):
            return self._degraded(b, now, "quarantined", scalar=scalar)
        fut = self._executor().submit(self._sync_inner, fn)
        try:
            out = fut.result(timeout=self._eff_deadline())
        except concurrent.futures.TimeoutError:
            fut.add_done_callback(lambda f: f.exception())
            exc = DeadlineExceededError(
                f"slice {self.slice_index} decide exceeded the "
                f"{self._eff_deadline():g}s per-slice deadline")
            self._note_exc(exc, now)
            return self._degraded(b, now, "deadline exceeded",
                                  scalar=scalar)
        except Exception as exc:
            if self._note_exc(exc, now):
                return self._degraded(b, now, f"decide failed: {exc}",
                                      scalar=scalar)
            raise
        self._warm = True
        self._mgr.note_success(self.slice_index)
        return out

    def allow_n(self, key, n, *, now=None):
        return self._guard_sync(
            lambda: self.inner.allow_n(key, n, now=now), 1, scalar=True)

    def allow_batch(self, keys, ns=None, *, now=None):
        return self._guard_sync(
            lambda: self.inner.allow_batch(keys, ns, now=now), len(keys))

    def allow_hashed(self, h64, ns=None, *, now=None):
        return self._guard_sync(
            lambda: self.inner.allow_hashed(h64, ns, now=now), len(h64))

    def allow_ids(self, ids, ns=None, *, now=None):
        return self._guard_sync(
            lambda: self.inner.allow_ids(ids, ns, now=now), len(ids))

    # -------------------------------------------------------------- probe

    def probe(self) -> None:
        """Half-open probe: one reserved-hash unit decision against the
        inner slice, bounded by the slice deadline (a still-wedged
        device times out here, never in client traffic). Raises on any
        fault — the manager re-opens."""
        def _p():
            self._chaos_launch()
            self._chaos_resolve()
            return self.inner.allow_hashed(
                np.asarray([PROBE_HASH], dtype=np.uint64),
                now=self.inner.clock.now())

        fut = self._executor().submit(_p)
        try:
            fut.result(timeout=self._eff_deadline())
        except concurrent.futures.TimeoutError:
            fut.add_done_callback(lambda f: f.exception())
            raise DeadlineExceededError(
                f"slice {self.slice_index} probe exceeded the "
                f"{self._eff_deadline():g}s deadline") from None

    # ------------------------------------------------------ config changes

    def update_limit(self, new_limit: int) -> None:
        # A config change rebuilds the slice's jitted steps, so the next
        # dispatch recompiles — re-open the cold-deadline allowance so
        # the recompile is not misclassified as a device fault.
        self.inner.update_limit(new_limit)
        self._warm = False

    def update_window(self, new_window: float) -> None:
        self.inner.update_window(new_window)
        self._warm = False

    # ---------------------------------------------------------- lifecycle

    def close(self) -> None:
        super().close()
        with self._pool_lock:
            pool = self._pool
            self._pool = None
        if pool is not None:
            pool.shutdown()
