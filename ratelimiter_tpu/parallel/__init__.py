"""Multi-chip deployment: mesh construction + the MeshSketchLimiter.

The reference scales horizontally with Redis Cluster hash-slot sharding
(``docs/ARCHITECTURE.md:199-219``, ``docs/ADR/001:29-34``): more nodes, each
owning a key range, every decision still one network round-trip. The
TPU-native story replaces that with state *replicated in HBM on every chip*
and ICI collectives keeping the replicas coherent — no decision ever leaves
the device mesh (SURVEY.md §2.6).

Two merge modes (ratelimiter_tpu/parallel/mesh_kernels.py):

* ``gather`` — all_gather the per-chip request shards, every chip runs the
  identical global decision kernel. Bit-exact global sequencing (a limit-L
  key admits exactly L across all chips in one step) — *stronger* than
  Redis Cluster, which serializes per key but not across keys.
* ``delta`` — each chip admits its local shard against the replicated
  counts, then a single psum merges the write histograms. One collective
  per step, batch-size-independent; staleness is at most one step's worth
  of same-key cross-chip traffic (the analog of the reference's NTP-skew
  caveat, SURVEY.md §2.4.14). Conservative update is gather/single-chip
  only — cross-chip counts must ADD, so delta mode always uses vanilla
  sums (see sketch_kernels._sketch_step for the two undercount hazards).

Multi-host note: both collectives compile identically over DCN-connected
meshes (jax.distributed); cadence over DCN is the accuracy/bandwidth knob.

Serving note (ADR-012): the serving tier's ``--backend mesh`` uses the
third deployment in this package — ``SlicedMeshLimiter``, one independent
device-pinned limiter per chip with hash routing in the front door — so
the decide path is collective-free and throughput scales with the slice.
The collective limiters above remain the tool for un-routable workloads.
"""

from ratelimiter_tpu.parallel.mesh import make_mesh, mesh_axis
from ratelimiter_tpu.parallel.limiter import (
    MeshSketchLimiter,
    MeshTokenBucketLimiter,
    SlicedMeshLimiter,
    build_slices,
)
from ratelimiter_tpu.parallel.collective import CollectiveMeshLimiter
from ratelimiter_tpu.parallel.dcn import (
    DcnMirrorGroup,
    export_completed,
    export_debt,
    merge_debt,
    merge_completed,
)

__all__ = [
    "CollectiveMeshLimiter",
    "DcnMirrorGroup",
    "MeshSketchLimiter",
    "MeshTokenBucketLimiter",
    "SlicedMeshLimiter",
    "build_slices",
    "export_completed",
    "export_debt",
    "make_mesh",
    "merge_completed",
    "merge_debt",
    "mesh_axis",
]
