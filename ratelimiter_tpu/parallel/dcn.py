"""DCN tier: cross-pod merge of completed sub-window slabs.

The mesh limiters (parallel/limiter.py) keep one pod coherent with a
per-step ICI collective. Across pods (or regions) a per-step collective
is unaffordable; the reference's analog is Redis Cluster spanning
deployments, with NTP-skew-bounded inconsistency
(reference ``docs/ALGORITHMS.md:162``). Here the unit of exchange is the
**completed sub-window slab**: once the rollover kernel flushes a
sub-window, that (d, w) slab is immutable local history — pods exchange
those slabs over any transport and fold them into each other's rings.

Consistency contract (tested in tests/test_dcn.py):

* a key's traffic on pod A is invisible to pod B until the sub-window
  containing it completes and a sync runs — cross-pod over-admission is
  bounded by ``n_pods x limit`` per (sub-window + sync cadence), the
  same envelope as the mesh delta mode one level up;
* after a sync, every pod's window estimate includes all pods' completed
  traffic, and expiry needs no coordination (slabs age out of each ring
  by the same period arithmetic everywhere);
* exports carry ONLY local traffic: a slab is captured at flush time
  (before any foreign merge can land in it), so fan-out topologies never
  double-count. The in-process ``DcnMirrorGroup`` enforces the
  export-all-then-merge-all order; a real transport must do the same
  per cycle.

Windowed sketch algorithms only; the token bucket's DCN story (debt
deltas) is ROADMAP.
"""

from __future__ import annotations

from typing import Dict, List, Sequence, Tuple

import numpy as np

from ratelimiter_tpu.algorithms.sketch import SketchLimiter, SketchTokenBucketLimiter
from ratelimiter_tpu.core.errors import InvalidConfigError
from ratelimiter_tpu.ops import sketch_kernels


def _check(lim: SketchLimiter) -> None:
    if isinstance(lim, SketchTokenBucketLimiter):
        raise InvalidConfigError(
            "DCN slab exchange applies to windowed sketch limiters; the "
            "token bucket's debt-delta exchange is not implemented yet")


def export_completed(lim: SketchLimiter, after_period: int,
                     ) -> Tuple[np.ndarray, np.ndarray, int]:
    """(periods int64[k], slabs int32[k, d, w], last_period): every
    completed sub-window with period > after_period still present in the
    ring, plus the pod's current period. The caller's next watermark is
    ``last_period - 1`` — NOT the max exported period — so periods that
    complete (or receive foreign merges) after this snapshot still
    export next cycle. Call before merging foreign data for the cycle
    (module docstring)."""
    _check(lim)
    _, _, SW, S, _ = sketch_kernels.sketch_geometry(lim.config)
    with lim._lock:
        sp = np.asarray(lim._state["slab_period"])
        last = int(np.asarray(lim._state["last_period"]))
        # In-window completed periods only: [last-SW, last-1]. This also
        # excludes the _NEVER sentinel slab the first rollover flushes.
        take = [(int(p), slot) for slot, p in enumerate(sp.tolist())
                if after_period < p < last and p >= last - SW]
        take.sort()
        if not take:
            d, w = lim.config.sketch.depth, lim.config.sketch.width
            return (np.empty(0, np.int64), np.empty((0, d, w), np.int32),
                    last)
        periods = np.array([p for p, _ in take], dtype=np.int64)
        slabs = np.stack([np.asarray(lim._state["slabs"][slot])
                          for _, slot in take])
    return periods, slabs, last


def merge_completed(lim: SketchLimiter, periods: np.ndarray,
                    slabs: np.ndarray) -> Tuple[int, int]:
    """Fold foreign completed slabs into the local ring; returns
    (applied_count, max_applied_period).

    Double-count safety comes from the caller's watermark discipline
    (export watermark = exporter's ``last_period - 1`` at export time,
    export-before-merge each cycle): every period a merge can touch
    (p < receiver's last) is already at-or-below the receiver's own
    export watermark, so foreign data never re-exports. The one race —
    a rollover landing between a pod's export and its merges in the same
    cycle — can transiently DOUBLE-COUNT one sub-window (the receiver
    re-exports a contaminated slab next cycle); the error direction is
    over-counting, i.e. extra denies, never over-admission, and it ages
    out of the ring with the period. Rules per period p (local
    slot = p mod S):

    * p >= local current period: dropped (not completed locally; the
      next cycle re-delivers it — the exporter should lag one period);
    * slot already holds p: slabs add (another pod's view of the same
      sub-window);
    * slot holds something older: the foreign slab replaces it (the old
      content is out-of-window by ring geometry);
    * slot holds something newer: dropped (foreign data already expired).

    ``totals`` is rebuilt as (in-window slabs) + ``cur`` so estimates see
    the merged history immediately.
    """
    import jax.numpy as jnp

    _check(lim)
    if periods.shape[0] == 0:
        return 0, -(1 << 62)
    W, sub_us, SW, S, _limit = sketch_kernels.sketch_geometry(lim.config)
    applied = 0
    max_applied = -(1 << 62)
    with lim._lock:
        sp = np.array(np.asarray(lim._state["slab_period"]))  # writable copy
        last = int(np.asarray(lim._state["last_period"]))
        new_slabs = lim._state["slabs"]
        new_sp = lim._state["slab_period"]
        for p_np, slab in zip(periods.tolist(), slabs):
            p = int(p_np)
            if p >= last:
                continue
            slot = p % S
            cur_p = int(sp[slot])
            if cur_p == p:
                new_slabs = new_slabs.at[slot].add(jnp.asarray(slab))
            elif cur_p < p:
                new_slabs = new_slabs.at[slot].set(jnp.asarray(slab))
                new_sp = new_sp.at[slot].set(p)
                sp[slot] = p
            else:
                continue
            applied += 1
            max_applied = max(max_applied, p)
        if applied:
            in_window = ((new_sp >= last - SW + 1) &
                         (new_sp <= last - 1)).astype(jnp.int32)
            totals = (jnp.tensordot(in_window, new_slabs, axes=1)
                      + lim._state["cur"])
            lim._state = dict(lim._state, slabs=new_slabs,
                              slab_period=new_sp, totals=totals)
    return applied, max_applied


class DcnMirrorGroup:
    """In-process mirror of a multi-pod deployment: N windowed sketch
    limiters (the 'pods'), synced by exchanging completed slabs. This is
    the test/simulation harness — in production the same two calls wrap
    any transport (the export payload is plain numpy arrays)."""

    def __init__(self, pods: Sequence[SketchLimiter]):
        if not pods:
            raise InvalidConfigError("DcnMirrorGroup needs >= 1 pod")
        for p in pods:
            _check(p)
        fp = {sketch_kernels.sketch_geometry(p.config)
              + (p.config.sketch.depth, p.config.sketch.width,
                 p.config.sketch.seed, p.config.prefix)
              for p in pods}
        if len(fp) != 1:
            raise InvalidConfigError(
                "all pods must share algorithm geometry AND hashing "
                "(window, sub-windows, limit, depth, width, seed, "
                "prefix) — mismatched seeds would merge counts into "
                "other keys' cells")
        self.pods: List[SketchLimiter] = list(pods)
        self._exported_up_to: Dict[int, int] = {i: -(1 << 62)
                                                for i in range(len(pods))}

    def sync(self) -> int:
        """One exchange cycle: export every pod's new completed slabs,
        then merge everything into everyone else. Returns the number of
        slab applications across the group."""
        exports = []
        for i, pod in enumerate(self.pods):
            periods, slabs, last = export_completed(
                pod, self._exported_up_to[i])
            # Watermark = everything completed as of this export; merges
            # this cycle only touch periods <= the watermark, so foreign
            # data never re-exports (see merge_completed's docstring).
            self._exported_up_to[i] = max(self._exported_up_to[i], last - 1)
            exports.append((periods, slabs))
        applied = 0
        for i, pod in enumerate(self.pods):
            for j, (periods, slabs) in enumerate(exports):
                if i == j or periods.shape[0] == 0:
                    continue
                n, _max_p = merge_completed(pod, periods, slabs)
                applied += n
        return applied
