"""DCN tier: cross-pod merge of completed sub-window slabs.

The mesh limiters (parallel/limiter.py) keep one pod coherent with a
per-step ICI collective. Across pods (or regions) a per-step collective
is unaffordable; the reference's analog is Redis Cluster spanning
deployments, with NTP-skew-bounded inconsistency
(reference ``docs/ALGORITHMS.md:162``). Here the unit of exchange is the
**completed sub-window slab**: once the rollover kernel flushes a
sub-window, that (d, w) slab is immutable local history — pods exchange
those slabs over any transport and fold them into each other's rings.

Consistency contract (tested in tests/test_dcn.py):

* a key's traffic on pod A is invisible to pod B until the sub-window
  containing it completes and a sync runs — cross-pod over-admission is
  bounded by ``n_pods x limit`` per (sub-window + sync cadence), the
  same envelope as the mesh delta mode one level up;
* after a sync, every pod's window estimate includes all pods' completed
  traffic, and expiry needs no coordination (slabs age out of each ring
  by the same period arithmetic everywhere);
* exports carry ONLY local traffic: a slab is captured at flush time
  (before any foreign merge can land in it), so fan-out topologies never
  double-count. The in-process ``DcnMirrorGroup`` enforces the
  export-all-then-merge-all order; a real transport must do the same
  per cycle.

The token bucket exchanges **debt deltas** instead of slabs: every step
accumulates its local debt increments into a second ``acc`` slab
(ops/bucket_kernels.init_state), export snapshots-and-zeroes it, and
merges add foreign deltas to ``debt`` only — foreign traffic can never
re-export, the same no-double-count discipline as the watermark above.
Staleness envelope (tested in tests/test_dcn.py):

* pre-sync: pod-local admission — cross-pod over-admission bounded by
  ``n_pods x limit`` per sync interval (burst capacity is per-pod until
  the deltas land);
* post-sync: a delta applied after transit time ``dt`` missed ``dt`` of
  refill decay — over-counting, i.e. extra denies, bounded by
  ``rate x dt``; it drains at the refill rate like any debt.
"""

from __future__ import annotations

from typing import Dict, List, Sequence, Tuple

import numpy as np

from ratelimiter_tpu.algorithms.sketch import SketchLimiter, SketchTokenBucketLimiter
from ratelimiter_tpu.core.errors import InvalidConfigError
from ratelimiter_tpu.ops import sketch_kernels


def _check(lim: SketchLimiter) -> None:
    if isinstance(lim, SketchTokenBucketLimiter):
        raise InvalidConfigError(
            "slab exchange applies to windowed sketch limiters; token "
            "buckets exchange debt deltas (export_debt/merge_debt)")


def export_debt(lim: SketchTokenBucketLimiter) -> np.ndarray:
    """Snapshot-and-zero the pod's accumulated local debt increments:
    int64[d, w] micro-token deltas since the previous export. Exports
    carry ONLY local traffic (merges add to ``debt``, never ``acc``), so
    any fan-out topology is double-count-free by construction."""
    if not isinstance(lim, SketchTokenBucketLimiter):
        raise InvalidConfigError(
            "export_debt needs a SketchTokenBucketLimiter; windowed "
            "limiters exchange completed slabs (export_completed)")
    import jax.numpy as jnp

    with lim._lock:
        acc = np.asarray(lim._state["acc"])
        lim._state = dict(lim._state, acc=jnp.zeros_like(lim._state["acc"]))
    return acc


def restore_debt(lim: SketchTokenBucketLimiter, delta: np.ndarray) -> None:
    """Return an exported-but-undelivered delta to the accumulator so the
    next cycle re-ships it (merges add to ``debt`` only, never ``acc``,
    so re-accumulation cannot double-export). Used by the push transport
    when EVERY peer push fails — without it, a network partition drops
    one interval of traffic per cycle, unbounded in total."""
    if not isinstance(lim, SketchTokenBucketLimiter):
        raise InvalidConfigError("restore_debt needs a SketchTokenBucketLimiter")
    import jax.numpy as jnp

    from ratelimiter_tpu.ops.bucket_kernels import _DEBT_CAP

    with lim._lock:
        lim._state = dict(
            lim._state,
            acc=jnp.minimum(lim._state["acc"] + jnp.asarray(delta),
                            _DEBT_CAP))


def merge_debt(lim: SketchTokenBucketLimiter, delta: np.ndarray) -> int:
    """Add a foreign pod's debt delta to the local slab (clamped to the
    overflow cap). The delta missed refill decay in transit — an
    over-count that drains at the refill rate (module docstring error
    envelope). Returns the number of nonzero cells applied."""
    if not isinstance(lim, SketchTokenBucketLimiter):
        raise InvalidConfigError(
            "merge_debt needs a SketchTokenBucketLimiter; windowed "
            "limiters exchange completed slabs (merge_completed)")
    import jax.numpy as jnp

    from ratelimiter_tpu.ops.bucket_kernels import _DEBT_CAP

    if delta.shape != tuple(lim._state["debt"].shape):
        raise InvalidConfigError(
            f"debt delta shape {delta.shape} != sketch geometry "
            f"{tuple(lim._state['debt'].shape)}")
    # Clamp negative cells: exports are non-negative by construction
    # (acc only ever accumulates consumption), so negatives can only be
    # wire corruption or a malicious frame — and a negative merge would
    # erase real debt (fleet-wide limit bypass). Clamping errs safe.
    delta = np.maximum(delta, 0)
    nz = int(np.count_nonzero(delta))
    if nz == 0:
        return 0
    from ratelimiter_tpu.core.clock import to_micros

    now_us = to_micros(lim.clock.now())
    with lim._lock:
        # Advance `last` to the receiver's now: a pod that never (or long
        # ago) dispatched would otherwise decay the merged debt over the
        # whole idle gap on its next step, silently forgiving foreign
        # traffic. Forward `last` means less decay — the deny direction.
        lim._state = dict(
            lim._state,
            debt=jnp.minimum(lim._state["debt"] + jnp.asarray(delta),
                             _DEBT_CAP),
            last=jnp.maximum(lim._state["last"], now_us))
    return nz


def _foreign_record(lim: SketchLimiter, last: int, SW: int) -> Dict[int, np.ndarray]:
    """Per-period record of foreign contributions merged into the local
    ring (host numpy, lazily attached, pruned to the live window). Must
    be accessed with ``lim._lock`` held.

    This is what keeps exports LOCAL-ONLY under an asynchronous push
    transport: a peer's merge lands in a slab BEFORE this pod happens to
    export that period, so the raw slab is contaminated — re-exporting
    it would echo the peer's own traffic back (systematic double count,
    effective limit ~halved under steady exchange). Exports subtract the
    record, restoring the export-all-then-merge-all guarantee the
    in-process mirror group gets from strict ordering."""
    rec = getattr(lim, "_dcn_foreign", None)
    if rec is None:
        rec = {}
        lim._dcn_foreign = rec
    for q in [q for q in rec if q < last - SW]:
        del rec[q]
    return rec


def export_completed(lim: SketchLimiter, after_period: int,
                     ) -> Tuple[np.ndarray, np.ndarray, int]:
    """(periods int64[k], slabs int32[k, d, w], last_period): every
    completed sub-window with period > after_period still present in the
    ring, plus the pod's current period. The caller's next watermark is
    ``last_period - 1`` — NOT the max exported period — so periods that
    complete (or receive foreign merges) after this snapshot still
    export next cycle. Exported slabs carry LOCAL traffic only: foreign
    contributions merged into the ring are subtracted via the per-period
    record (_foreign_record) before shipping.

    Heavy-hitter side table (hh_slots > 0): a promoted key's traffic
    lives in its private per-period cell, not the CMS — so each exported
    slab FOLDS the side table's row for that period back into CMS form,
    scatter-adding each owner's count at its Kirsch-Mitzenmacher columns
    (the owner's (h1, h2) pair is captured at claim time, ``hh_owner2``).
    The wire format stays pure (d, w) slabs: receivers need no hh
    awareness, merges never touch a receiver's own side table, and the
    error direction is unchanged (foreign hh mass can only collide into
    over-estimates, i.e. extra denies). Slots whose owner pre-dates the
    ``hh_owner2`` state array (older checkpoints restore it as zeros)
    are skipped — their traffic stays local-only, the pre-r5 envelope.
    """
    _check(lim)
    _, _, SW, S, _ = sketch_kernels.sketch_geometry(lim.config)
    d, w = lim.config.sketch.depth, lim.config.sketch.width
    with lim._lock:
        sp = np.asarray(lim._state["slab_period"])
        last = int(np.asarray(lim._state["last_period"]))
        rec = _foreign_record(lim, last, SW)
        # In-window completed periods only: [last-SW, last-1]. This also
        # excludes the _NEVER sentinel slab the first rollover flushes.
        take = [(int(p), slot) for slot, p in enumerate(sp.tolist())
                if after_period < p < last and p >= last - SW]
        take.sort()
        if not take:
            return (np.empty(0, np.int64), np.empty((0, d, w), np.int32),
                    last)
        hh = "hh_owner" in lim._state
        if hh:
            owner = np.asarray(lim._state["hh_owner"])
            owner2 = np.asarray(lim._state["hh_owner2"])
            hh_slabs = np.asarray(lim._state["hh_slabs"])     # (S, K)
            exportable = (owner != 0) & (owner2 != 0)
        periods = np.array([p for p, _ in take], dtype=np.int64)
        out = []
        for per, slot in take:
            slab = np.asarray(lim._state["slabs"][slot])
            f = rec.get(per)
            if f is not None:
                slab = np.maximum(slab - f, 0)
            if hh:
                row = hh_slabs[slot]
                m = exportable & (row > 0)
                if m.any():
                    slab = np.array(slab, dtype=np.int32)     # writable copy
                    o1 = owner[m].astype(np.uint64)
                    o2 = owner2[m].astype(np.uint64)
                    cnt = row[m].astype(np.int32)
                    for r in range(d):
                        cols = ((o1 + r * o2) & (w - 1)).astype(np.int64)
                        np.add.at(slab[r], cols, cnt)
            out.append(slab)
        slabs = np.stack(out)
    return periods, slabs, last


def merge_completed(lim: SketchLimiter, periods: np.ndarray,
                    slabs: np.ndarray) -> Tuple[int, int]:
    """Fold foreign completed slabs into the local ring; returns
    (applied_count, max_applied_period).

    Double-count safety comes from the caller's watermark discipline
    (export watermark = exporter's ``last_period - 1`` at export time,
    export-before-merge each cycle): every period a merge can touch
    (p < receiver's last) is already at-or-below the receiver's own
    export watermark, so foreign data never re-exports. The one race —
    a rollover landing between a pod's export and its merges in the same
    cycle — can transiently DOUBLE-COUNT one sub-window (the receiver
    re-exports a contaminated slab next cycle); the error direction is
    over-counting, i.e. extra denies, never over-admission, and it ages
    out of the ring with the period. Rules per period p (local
    slot = p mod S):

    * p >= local current period: dropped (not completed locally; the
      next cycle re-delivers it — the exporter should lag one period);
    * slot already holds p: slabs add (another pod's view of the same
      sub-window);
    * slot holds something older: the foreign slab replaces it (the old
      content is out-of-window by ring geometry);
    * slot holds something newer: dropped (foreign data already expired).

    ``totals`` is rebuilt as (in-window slabs) + ``cur`` so estimates see
    the merged history immediately.
    """
    import jax.numpy as jnp

    from ratelimiter_tpu.core.clock import to_micros

    _check(lim)
    if periods.shape[0] == 0:
        return 0, -(1 << 62)
    W, sub_us, SW, S, _limit = sketch_kernels.sketch_geometry(lim.config)
    applied = 0
    max_applied = -(1 << 62)
    with lim._lock:
        # Self-roll to the local clock FIRST: the exporter only ships
        # periods ITS clock has completed, and its watermark advances on
        # delivery — if this pod's ring lagged (quiet pod, merge racing
        # the rollover), the p >= last drop below would discard the
        # period FOREVER, not "until the next cycle". With synced clocks
        # this removes the race entirely; residual loss needs cross-pod
        # clock skew > sub_us (the reference's own NTP caveat,
        # ``docs/ALGORITHMS.md:162``).
        lim._sync_period(to_micros(lim.clock.now()))
        sp = np.array(np.asarray(lim._state["slab_period"]))  # writable copy
        last = int(np.asarray(lim._state["last_period"]))
        rec = _foreign_record(lim, last, SW)
        new_slabs = lim._state["slabs"]
        new_sp = lim._state["slab_period"]
        for p_np, slab in zip(periods.tolist(), slabs):
            p = int(p_np)
            if p >= last:
                continue
            # Clamp negative cells: a local reset can legitimately leave
            # transient negatives in an exporter's ring (they self-heal
            # there), but accepting them from the wire would let a bad
            # peer subtract history (over-admission). Reset forgiveness
            # is local-only by design; clamping errs toward denying.
            slab = np.maximum(slab, 0)
            slot = p % S
            cur_p = int(sp[slot])
            if cur_p == p:
                new_slabs = new_slabs.at[slot].add(jnp.asarray(slab))
                prev = rec.get(p)
                rec[p] = slab.astype(np.int64) if prev is None else prev + slab
            elif cur_p < p:
                new_slabs = new_slabs.at[slot].set(jnp.asarray(slab))
                new_sp = new_sp.at[slot].set(p)
                sp[slot] = p
                # The whole slot content is foreign now.
                rec[p] = slab.astype(np.int64).copy()
            else:
                continue
            applied += 1
            max_applied = max(max_applied, p)
        if applied:
            in_window = ((new_sp >= last - SW + 1) &
                         (new_sp <= last - 1)).astype(jnp.int32)
            totals = (jnp.tensordot(in_window, new_slabs, axes=1)
                      + lim._state["cur"])
            lim._state = dict(lim._state, slabs=new_slabs,
                              slab_period=new_sp, totals=totals)
    return applied, max_applied


class DcnMirrorGroup:
    """In-process mirror of a multi-pod deployment: N sketch limiters
    (the 'pods'), synced by exchanging completed slabs (windowed) or
    debt deltas (token bucket). This is the test/simulation harness — in
    production the same calls wrap any transport (the export payloads
    are plain numpy arrays); serving/dcn_peer.py runs them over the
    binary protocol between OS processes."""

    def __init__(self, pods: Sequence[SketchLimiter]):
        if not pods:
            raise InvalidConfigError("DcnMirrorGroup needs >= 1 pod")
        kinds = {isinstance(p, SketchTokenBucketLimiter) for p in pods}
        if len(kinds) != 1:
            raise InvalidConfigError(
                "all pods must run the same algorithm family (all "
                "windowed or all token bucket)")
        self._bucket = kinds.pop()
        if self._bucket:
            fp = {(p.config.limit, float(p.config.window),
                   p.config.sketch.depth, p.config.sketch.width,
                   p.config.sketch.seed, p.config.prefix) for p in pods}
        else:
            for p in pods:
                _check(p)
            fp = {sketch_kernels.sketch_geometry(p.config)
                  + (p.config.sketch.depth, p.config.sketch.width,
                     p.config.sketch.seed, p.config.prefix)
                  for p in pods}
        if len(fp) != 1:
            raise InvalidConfigError(
                "all pods must share algorithm geometry AND hashing "
                "(window, sub-windows, limit, depth, width, seed, "
                "prefix) — mismatched seeds would merge counts into "
                "other keys' cells")
        self.pods: List[SketchLimiter] = list(pods)
        self._exported_up_to: Dict[int, int] = {i: -(1 << 62)
                                                for i in range(len(pods))}

    def sync(self) -> int:
        """One exchange cycle: export every pod's new local history, then
        merge everything into everyone else. Returns the number of
        applications (slabs or nonzero delta cells) across the group."""
        if self._bucket:
            deltas = [export_debt(p) for p in self.pods]
            applied = 0
            for i, pod in enumerate(self.pods):
                for j, delta in enumerate(deltas):
                    if i != j:
                        applied += merge_debt(pod, delta)
            return applied
        exports = []
        for i, pod in enumerate(self.pods):
            periods, slabs, last = export_completed(
                pod, self._exported_up_to[i])
            # Watermark = everything completed as of this export; merges
            # this cycle only touch periods <= the watermark, so foreign
            # data never re-exports (see merge_completed's docstring).
            self._exported_up_to[i] = max(self._exported_up_to[i], last - 1)
            exports.append((periods, slabs))
        applied = 0
        for i, pod in enumerate(self.pods):
            for j, (periods, slabs) in enumerate(exports):
                if i == j or periods.shape[0] == 0:
                    continue
                n, _max_p = merge_completed(pod, periods, slabs)
                applied += n
        return applied
