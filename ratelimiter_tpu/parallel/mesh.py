"""Mesh construction helpers.

One flat axis ("chips") is the deployment unit: a v5e-8 pod slice, or N
virtual CPU devices in CI (``XLA_FLAGS=--xla_force_host_platform_device_count``,
the miniredis-analog of SURVEY.md §4.3). Collectives over a flat axis ride
ICI on real hardware; a two-level ("hosts", "chips") mesh is the DCN tier
and uses the same kernels with axis_name over both axes.
"""

from __future__ import annotations

from typing import Optional, Sequence

import numpy as np

AXIS = "chips"


def mesh_axis() -> str:
    return AXIS


def make_mesh(devices: Optional[Sequence] = None, n_devices: Optional[int] = None):
    """Flat 1-D mesh over the given (default: all) devices."""
    import jax
    from jax.sharding import Mesh

    if devices is None:
        devices = jax.devices()
    if n_devices is not None:
        devices = devices[:n_devices]
    return Mesh(np.asarray(devices), (AXIS,))
